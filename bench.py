"""Driver benchmark: full fleet build throughput on the available chip(s).

Measures the north-star headline (`BASELINE.json`): per-tag anomaly-detector
builds per hour per chip — the COMPLETE build path (synthetic time-series
assembly, scaler stats, CV folds, threshold derivation, final fit, artifact
dump) via ``build_project``, i.e. measurement config 4 ("builder fan-out
from machine config").  Also measures the serving anomaly-scoring rate
(config 5) and reports it alongside.

Prints exactly ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``

``vs_baseline`` is measured models/hour/chip divided by the north-star
per-chip rate (10,000 models/h on 64 chips = 156.25 models/h/chip).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

#: north star: 10k models < 1h on v5e-64 → per-chip rate to match.
NORTH_STAR_MODELS_PER_HOUR_PER_CHIP = 10_000 / 64
NORTH_STAR_SAMPLES_PER_SEC_PER_CHIP = 100_000

N_MACHINES = int(os.environ.get("BENCH_MODELS", "512"))
N_TAGS = int(os.environ.get("BENCH_TAGS", "10"))

#: hard wall-clock budget for the whole bench; must stay under the driver's
#: own timeout so a wedge yields a diagnostic JSON line instead of rc=124.
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "1200"))
#: budget for jax backend init alone — the axon tunnel's failure mode is an
#: INDEFINITE BLOCK inside jax.devices() (see .claude/skills/verify/SKILL.md),
#: which no amount of retry-on-exception can escape.
INIT_TIMEOUT_S = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "180"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


_emit_lock = threading.Lock()
_emitted = False


def emit_once(out: dict) -> None:
    """Print the single JSON result line exactly once (main path and the
    watchdog race for it; whoever gets here first wins).

    Serializes a SNAPSHOT (the watchdog may fire while main mutates ``out``)
    and only marks emitted after the print actually succeeded, so a
    serialization hiccup can't permanently swallow the output line.
    """
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        try:
            line = json.dumps(dict(out))
        except Exception as exc:
            line = json.dumps(
                {"metric": "bench", "value": None, "error": f"emit: {exc}"}
            )
        print(line, flush=True)
        _emitted = True


def start_watchdog(out: dict) -> None:
    """If the deadline passes, emit whatever has been measured so far and
    hard-exit 0: a partial diagnostic line beats a dead rc=124."""

    def fire():
        out.setdefault("error", f"bench deadline ({DEADLINE_S:.0f}s) hit")
        log(f"WATCHDOG: deadline {DEADLINE_S:.0f}s hit; emitting partial result")
        emit_once(out)
        sys.stdout.flush()
        os._exit(0)

    t = threading.Timer(DEADLINE_S, fire)
    t.daemon = True
    t.start()


def make_machines(n: int):
    from gordo_tpu.workflow.config import Machine

    # 4 days @ 10-min resolution ≈ 576 rows/machine, N_TAGS sine-mixture tags.
    return [
        Machine.from_config(
            {
                "name": f"bench-machine-{i:04d}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": [f"tag-{i:04d}-{j}" for j in range(N_TAGS)],
                },
            }
        )
        for i in range(n)
    ]


def bench_build(mesh) -> float:
    """Steady-state project-build rate in models/hour (in-process jit cache
    warm: run once to compile, time the second identical-shape run)."""
    from gordo_tpu.builder.fleet_build import build_project

    machines = make_machines(N_MACHINES)
    rates = []
    for run in range(2):
        out_dir = tempfile.mkdtemp(prefix="gordo-bench-")
        t0 = time.perf_counter()
        result = build_project(
            machines, out_dir, mesh=mesh, max_bucket_size=N_MACHINES
        )
        dt = time.perf_counter() - t0
        shutil.rmtree(out_dir, ignore_errors=True)
        n_ok = len(result.artifacts)
        if result.failed:
            log(f"WARNING: {len(result.failed)} builds failed: "
                f"{dict(list(result.failed.items())[:3])}")
        if n_ok == 0:
            raise RuntimeError("All builds failed")
        rates.append(n_ok / dt * 3600.0)
        log(f"build run {run}: {n_ok} machines in {dt:.2f}s "
            f"({rates[-1]:.0f} models/h)")
    return rates[-1]


def bench_serving() -> float:
    """Warm anomaly-scoring rate (sensor-samples/sec): max of the
    single-machine fused scorer and the stacked fleet scorer serving 64
    machines per dispatch (the project-stream scenario)."""
    from gordo_tpu.builder.build_model import build_model
    from gordo_tpu.serve.fleet_scorer import FleetScorer
    from gordo_tpu.serve.scorer import CompiledScorer

    machine = make_machines(1)[0]
    model, _ = build_model(
        machine.name, machine.model, machine.dataset, {}, machine.evaluation
    )
    rng = np.random.default_rng(0)

    scorer = CompiledScorer(model)
    X = rng.standard_normal((8192, N_TAGS)).astype(np.float32)
    scorer.anomaly_arrays(X, None)  # compile
    n_iter, t0 = 20, time.perf_counter()
    for _ in range(n_iter):
        scorer.anomaly_arrays(X, None)
    single = n_iter * X.size / (time.perf_counter() - t0)
    log(f"serving single: {single:,.0f} sensor-samples/s (fused={scorer.fused})")

    n_machines = 64
    fleet = FleetScorer.from_models(
        {f"m-{i:03d}": model for i in range(n_machines)}
    )
    X_by = {
        f"m-{i:03d}": rng.standard_normal((2048, N_TAGS)).astype(np.float32)
        for i in range(n_machines)
    }
    fleet.score_all(X_by)  # compile
    n_iter, t0 = 10, time.perf_counter()
    for _ in range(n_iter):
        fleet.score_all(X_by)
    stacked = n_iter * n_machines * 2048 * N_TAGS / (time.perf_counter() - t0)
    log(f"serving fleet-stacked ({n_machines} machines/dispatch): "
        f"{stacked:,.0f} sensor-samples/s")
    return max(single, stacked)


def init_devices(attempts: int = 5, backoff_s: float = 2.0):
    """Initialize the jax backend with bounded retry.

    The TPU tunnel (axon PJRT plugin) intermittently fails init with
    UNAVAILABLE when another session holds the chip — the exact failure that
    cost round 1 its only perf number (BENCH_r01.json rc=1).  jax caches
    backend-init errors, so each retry clears backend state first.
    """
    import jax

    last_exc: Exception | None = None
    for attempt in range(attempts):
        try:
            devices = jax.devices()
            log(
                f"jax {jax.__version__} devices (attempt {attempt + 1}): "
                f"{[d.platform for d in devices]}"
            )
            return devices
        except Exception as exc:  # backend init failed — clear cache, retry
            last_exc = exc
            log(
                f"backend init attempt {attempt + 1}/{attempts} failed: {exc!r}"
            )
            if attempt == attempts - 1:
                break  # no retry follows; don't burn the deadline sleeping
            try:
                import jax.extend.backend

                jax.extend.backend.clear_backends()
            except Exception as clear_exc:
                log(f"clear_backends failed: {clear_exc!r}")
            time.sleep(backoff_s * (2**attempt))
    raise RuntimeError(
        f"jax backend init failed after {attempts} attempts: {last_exc!r}"
    )


def init_devices_bounded():
    """Backend init under a deadline: runs :func:`init_devices` in a side
    thread so an indefinite block inside ``jax.devices()`` (wedged axon
    relay grant) surfaces as a TimeoutError instead of hanging the bench."""
    box: dict = {}

    def target():
        try:
            box["devices"] = init_devices()
        except Exception as exc:
            box["error"] = exc

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(INIT_TIMEOUT_S)
    if t.is_alive():
        raise TimeoutError(
            f"jax backend init blocked for {INIT_TIMEOUT_S:.0f}s "
            "(axon tunnel wedge — relay grant likely stuck)"
        )
    if "error" in box:
        raise box["error"]
    return box["devices"]


def main() -> None:
    """Run each bench stage independently; ALWAYS print exactly one JSON
    line, even on failure (a diagnostic record instead of a dead rc=1)."""
    out: dict = {
        "metric": "per-tag anomaly-detector builds/hour/chip (full build path)",
        "value": None,
        "unit": "models/hour/chip",
        "vs_baseline": None,
        "n_machines": N_MACHINES,
    }
    start_watchdog(out)
    try:
        devices = init_devices_bounded()
    except Exception as exc:
        out["error"] = f"backend init: {exc}"
        emit_once(out)
        os._exit(0)  # init thread may still be wedged in jax.devices()

    from gordo_tpu.parallel.mesh import fleet_mesh

    n_chips = len(devices)
    out["n_chips"] = n_chips
    out["platform"] = devices[0].platform
    mesh = fleet_mesh(devices) if n_chips > 1 else None

    try:
        models_per_hour = bench_build(mesh)
        per_chip = models_per_hour / n_chips
        out["value"] = round(per_chip, 1)
        out["vs_baseline"] = round(
            per_chip / NORTH_STAR_MODELS_PER_HOUR_PER_CHIP, 3
        )
    except Exception as exc:
        log(f"build bench failed: {exc!r}")
        out["error"] = f"build bench: {exc}"

    try:
        samples_per_sec = bench_serving()
        # Serving runs on a single device (scorers place work on one chip);
        # report the raw rate under an honest name plus the device count so
        # the headline can't silently inflate if serving ever shards.
        out["serving_samples_per_sec"] = round(samples_per_sec)
        out["serving_devices"] = 1
        out["serving_vs_target"] = round(
            samples_per_sec / NORTH_STAR_SAMPLES_PER_SEC_PER_CHIP, 3
        )
    except Exception as exc:  # serving is the secondary metric
        log(f"serving bench failed: {exc!r}")
        out.setdefault("error", f"serving bench: {exc}")

    emit_once(out)


if __name__ == "__main__":
    main()
