"""Driver benchmark: full fleet build throughput on the available chip(s).

Measures (names track BASELINE.json measurement configs):

- config 4 headline: per-tag anomaly-detector builds/hour/chip — the
  COMPLETE build path (synthetic time-series assembly, scaler stats, CV
  folds, threshold derivation, final fit, artifact dump) via
  ``build_project``.
- config 2: the same build rate for ``lstm_hourglass`` machines (50 tags,
  windowed sequences) plus the LSTM serving rate.
- config 5 serving: end-to-end HTTP throughput under a replayed
  multi-machine sensor stream (real aiohttp server + TCP + codec), single
  and bulk routes, JSON and msgpack wire formats — reported separately, no
  ``max()`` hiding.  In-process scorer rates are kept alongside under
  ``*_inprocess`` names.
- FLOP accounting: analytic training FLOPs per build (see
  ``docs/perf.md``) → ``effective_tflops`` + ``mfu_estimate`` against the
  v5e bf16 peak, so the headline can't silently claim a busy chip.

Prints exactly ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``

``vs_baseline`` is measured models/hour/chip divided by the north-star
per-chip rate (10,000 models/h on 64 chips = 156.25 models/h/chip).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

#: north star: 10k models < 1h on v5e-64 → per-chip rate to match.
NORTH_STAR_MODELS_PER_HOUR_PER_CHIP = 10_000 / 64
NORTH_STAR_SAMPLES_PER_SEC_PER_CHIP = 100_000
#: TPU v5e peak (bf16 matmul); the fp32 programs here can at best reach a
#: fraction of it — the point of the MFU field is honesty, not flattery.
V5E_PEAK_FLOPS = 197e12

N_MACHINES = int(os.environ.get("BENCH_MODELS", "512"))
N_TAGS = int(os.environ.get("BENCH_TAGS", "10"))
N_LSTM_MACHINES = int(os.environ.get("BENCH_LSTM_MODELS", "64"))
N_LSTM_TAGS = int(os.environ.get("BENCH_LSTM_TAGS", "50"))
LSTM_LOOKBACK = int(os.environ.get("BENCH_LSTM_LOOKBACK", "12"))

#: hard wall-clock budget for the whole bench; must stay under the driver's
#: own timeout so a wedge yields a diagnostic JSON line instead of rc=124.
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "1200"))
#: budget for jax backend init alone — the axon tunnel's failure mode is an
#: INDEFINITE BLOCK inside jax.devices() (see .claude/skills/verify/SKILL.md),
#: which no amount of retry-on-exception can escape.
INIT_TIMEOUT_S = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "180"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


_emit_lock = threading.Lock()
_emitted = False


def emit_once(out: dict) -> None:
    """Print the single JSON result line exactly once (main path and the
    watchdog race for it; whoever gets here first wins).

    Serializes a SNAPSHOT (the watchdog may fire while main mutates ``out``)
    and only marks emitted after the print actually succeeded, so a
    serialization hiccup can't permanently swallow the output line.
    """
    try:
        line = json.dumps(dict(out))
    except Exception as exc:
        line = json.dumps(
            {"metric": "bench", "value": None, "error": f"emit: {exc}"}
        )
    emit_line(line)


def emit_line(line: str) -> None:
    """Print a pre-serialized result line through the emit-once gate."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        print(line, flush=True)
        _emitted = True
    try:
        persist_round(json.loads(line))
    except Exception as exc:  # non-JSON line: nothing to persist
        log(f"persist_round skipped (unparseable line): {exc!r}")


_REPO_DIR = os.path.dirname(os.path.abspath(__file__))

#: round number for BENCH_rNN.json persistence (``--round N`` /
#: ``BENCH_ROUND``); None = don't write a round artifact
_ROUND: "int | None" = None
_round_write_failed = False


def persist_round(doc: dict) -> None:
    """Write the emitted result doc to ``BENCH_rNN.json`` in the repo dir.

    Round-file convention (docs/perf.md "Bench round artifacts"): NN is
    the PR/round sequence number; the file carries the single JSON line
    bench.py emitted for that round, so later rounds can be diffed
    without re-running anything.  Written atomically (tmp + rename) —
    the r6 lesson: the round file was referenced from CHANGES.md but a
    plain interrupted write meant it never landed.  Failures are LOUD:
    logged, flagged in the doc, and the process exits nonzero
    (:func:`exit_code`) instead of silently dropping the artifact.
    """
    global _round_write_failed
    if _ROUND is None:
        return
    path = os.path.join(_REPO_DIR, f"BENCH_r{_ROUND:02d}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        log(f"round artifact written: {path}")
    except Exception as exc:
        _round_write_failed = True
        log(f"ERROR: round artifact write FAILED for {path}: {exc!r}")
        try:
            os.unlink(tmp)
        except OSError:
            pass


def exit_code() -> int:
    """0 unless a requested round artifact failed to persist."""
    return 1 if _round_write_failed else 0


def persist_partial(out: dict) -> None:
    """Write the current result snapshot to a platform-tagged sidecar
    (``BENCH_partial_{tpu,cpu}.json``) after backend init and after every
    completed stage.

    The r4 failure mode motivating this: the tunnel wedged mid-round, the
    round-end bench fell back to CPU, and every TPU-measured stage from
    earlier runs was lost.  With the sidecar, any stage that ever completed
    on TPU stays on disk; a later CPU-fallback run embeds it (see
    :func:`cpu_fallback_line`) instead of discarding it.
    """
    try:
        # everything inside the try: an abandoned stage's daemon thread can
        # mutate ``out`` mid-snapshot ("dict changed size during iteration"),
        # and the watchdog's fire() must survive that to reach emit_once
        platform = out.get("platform")
        if platform is None:
            return
        path = os.path.join(_REPO_DIR, f"BENCH_partial_{platform}.json")
        snap = dict(out)
        snap["persisted_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(snap, fh, indent=1)
        os.replace(tmp, path)
    except Exception as exc:  # persistence must never kill the bench
        log(f"persist_partial failed: {exc!r}")


def attach_tpu_partial(doc: dict) -> None:
    """Embed the latest TPU-stage sidecar into a CPU-fallback result doc so
    the single emitted line still carries whatever the TPU measured before
    the tunnel wedged (timestamped; the reader judges staleness)."""
    path = os.path.join(_REPO_DIR, "BENCH_partial_tpu.json")
    try:
        if os.path.exists(path):
            with open(path) as fh:
                doc["tpu_partial"] = json.load(fh)
    except Exception as exc:
        log(f"attach_tpu_partial failed: {exc!r}")


def cpu_fallback_line(budget_s: float) -> "str | None":
    """When the TPU backend can't initialize (wedged tunnel — observed to
    last hours with no client-side recovery), rerun the whole bench on CPU
    in a clean subprocess and return its JSON line.

    A clearly-labeled CPU measurement beats a value=null diagnostic: the
    build path is mostly the same host+XLA pipeline, just slower.  A clean
    process is required — the wedged init thread cannot be recovered
    in-process, and CPU-forcing needs PALLAS_AXON_POOL_IPS unset before
    any jax import.
    """
    if os.environ.get("BENCH_CPU_FALLBACK") == "1":
        return None  # already the fallback process — no recursion
    if budget_s < 120:
        log(f"CPU fallback skipped: only {budget_s:.0f}s left")
        return None
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_CPU_FALLBACK"] = "1"
    # the child's own watchdog fires before the parent's: budget_s is the
    # REMAINING wall time (init already burned its share of DEADLINE_S)
    env["BENCH_DEADLINE_S"] = str(budget_s)
    log("TPU backend unavailable; rerunning bench on CPU (labeled fallback)")
    try:
        # stderr inherited so the child's progress streams through; only
        # stdout (the result line) is captured
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, text=True,
            timeout=budget_s + 30,
        )
    except Exception as exc:
        log(f"CPU fallback run failed: {exc!r}")
        return None
    stdout = (res.stdout or "").strip()
    return stdout.splitlines()[-1] if stdout else None


def start_watchdog(out: dict) -> None:
    """If the deadline passes, emit whatever has been measured so far and
    hard-exit 0: a partial diagnostic line beats a dead rc=124."""

    def fire():
        out.setdefault("error", f"bench deadline ({DEADLINE_S:.0f}s) hit")
        log(f"WATCHDOG: deadline {DEADLINE_S:.0f}s hit; emitting partial result")
        persist_partial(out)
        emit_once(out)
        sys.stdout.flush()
        os._exit(exit_code())

    t = threading.Timer(DEADLINE_S, fire)
    t.daemon = True
    t.start()


LSTM_MODEL = {
    "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.pipeline.Pipeline": {
                "steps": [
                    "gordo_tpu.ops.scalers.MinMaxScaler",
                    {
                        "gordo_tpu.models.estimator.LSTMAutoEncoder": {
                            "kind": "lstm_hourglass",
                            "lookback_window": LSTM_LOOKBACK,
                            "epochs": 10,
                            "batch_size": 64,
                        }
                    },
                ]
            }
        }
    }
}


def make_machines(n: int, n_tags: int = N_TAGS, model: dict | None = None,
                  prefix: str = "bench-machine"):
    from gordo_tpu.workflow.config import Machine

    # 4 days @ 10-min resolution ≈ 576 rows/machine, sine-mixture tags.
    return [
        Machine.from_config(
            {
                "name": f"{prefix}-{i:04d}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": [f"tag-{i:04d}-{j}" for j in range(n_tags)],
                },
                **({"model": model} if model else {}),
            }
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# FLOP accounting (see docs/perf.md for the derivation and caveats)
# ---------------------------------------------------------------------------

def _kernel_params(model) -> int:
    """Weight-matrix parameters of a built detector's network (ndim>=2
    leaves: dense/recurrent kernels; biases/scales excluded)."""
    import jax

    est = model.base_estimator
    if hasattr(est, "steps"):  # Pipeline
        est = est.steps[-1][1]
    return sum(
        x.size for x in jax.tree.leaves(est.params_)
        if getattr(x, "ndim", 0) >= 2
    )


def _train_flops_per_model(
    kernel_params: int, n_rows: int, epochs: int = 10, n_splits: int = 3,
    seq_steps: int = 1,
) -> float:
    """6 * kernel_params * trained_samples: the standard fwd(2)+bwd(4)
    dense-matmul estimate.  CV trains expanding folds (n/(k+1) * (1+..+k)
    rows) then the final fit trains all n rows; recurrent nets multiply by
    the steps each window unrolls (``seq_steps``)."""
    cv_rows = n_rows / (n_splits + 1) * (n_splits * (n_splits + 1) / 2)
    trained = (cv_rows + n_rows) * epochs * seq_steps
    return 6.0 * kernel_params * trained


# ---------------------------------------------------------------------------
# build benches
# ---------------------------------------------------------------------------

def _timed_build_runs(machines, mesh, label: str):
    """Two identical project builds (run 0 compiles, run 1 is the
    steady-state measurement); returns (rates, first artifact's model)."""
    from gordo_tpu import serializer
    from gordo_tpu.builder.fleet_build import build_project

    rates = []
    model = None
    for run in range(2):
        out_dir = tempfile.mkdtemp(prefix=f"gordo-bench-{label}-")
        t0 = time.perf_counter()
        result = build_project(
            machines, out_dir, mesh=mesh, max_bucket_size=len(machines)
        )
        dt = time.perf_counter() - t0
        n_ok = len(result.artifacts)
        if run == 1 and n_ok:
            model = serializer.load(
                result.artifacts[sorted(result.artifacts)[0]]
            )
        shutil.rmtree(out_dir, ignore_errors=True)
        if result.failed:
            log(f"WARNING ({label}): {len(result.failed)} builds failed: "
                f"{dict(list(result.failed.items())[:3])}")
        if n_ok == 0:
            raise RuntimeError(f"All {label} builds failed")
        rates.append(n_ok / dt * 3600.0)
        log(f"{label} build run {run}: {n_ok} machines in {dt:.2f}s "
            f"({rates[-1]:.0f} models/h)")
    return rates, model


def _flop_fields(out: dict, prefix: str, model, models_per_hour: float,
                 seq_steps: int = 1) -> None:
    """Per-chip FLOP-rate + MFU fields (rates arrive fleet-wide; MFU is
    against ONE chip's peak, so divide by n_chips first)."""
    kp = _kernel_params(model)
    flops = _train_flops_per_model(kp, n_rows=576, seq_steps=seq_steps)
    per_chip_rate = models_per_hour / 3600.0 / out.get("n_chips", 1)
    out[f"{prefix}_kernel_params_per_model"] = kp
    out[f"{prefix}_tflops_per_model"] = round(flops / 1e12, 9)
    out[f"{prefix}_effective_tflops_per_chip"] = round(
        flops * per_chip_rate / 1e12, 6
    )
    out[f"{prefix}_mfu_estimate"] = round(
        flops * per_chip_rate / V5E_PEAK_FLOPS, 8
    )


def bench_build(mesh, out: dict) -> float:
    """Steady-state project-build rate in models/hour (in-process jit cache
    warm: run once to compile, time the second identical-shape run)."""
    rates, model = _timed_build_runs(make_machines(N_MACHINES), mesh, "ff")
    if model is not None:
        _flop_fields(out, "build", model, rates[-1])
    return rates[-1]


def bench_build_pipeline(mesh, out: dict) -> None:
    """ISSUE 4 acceptance: serial-vs-pipelined project builds.

    Same machine set, same chunking; the kill-switch path
    (``pipeline=False``) is the baseline.  Chunk sizes force multiple
    chunks per project so the pipeline has stages to overlap.  Protocol:
    one warmup run per mode (compiles land), then 4 PAIRED alternating
    rounds (serial, pipelined, serial, ...) with per-mode BEST (min
    time) standing — timing noise on this shared container is one-sided
    contamination (a background burst can add 30% to a single run,
    nothing can make one faster than the true floor), so min() estimates
    the uncontaminated time; best-of pairing is the same discipline the
    coalesced-vs-direct serving points use.  The stage-occupancy
    telemetry emitted during the pipelined runs is attested into the
    result doc.
    """
    from gordo_tpu import telemetry
    from gordo_tpu.builder.fleet_build import build_project

    def timed(machines, bucket, pipe, label) -> float:
        out_dir = tempfile.mkdtemp(prefix=f"gordo-bench-pipe-{label}-")
        t0 = time.perf_counter()
        result = build_project(
            machines, out_dir, mesh=mesh, max_bucket_size=bucket,
            pipeline=pipe,
        )
        dt = time.perf_counter() - t0
        shutil.rmtree(out_dir, ignore_errors=True)
        if result.failed or len(result.artifacts) != len(machines):
            raise RuntimeError(
                f"build_pipeline {label}@{len(machines)}: "
                f"{len(result.failed)} failed"
            )
        return dt

    for n_machines, bucket in ((64, 16), (512, 64)):
        machines = make_machines(n_machines, prefix=f"bench-pipe{n_machines}")
        for pipe in (False, True):  # warmup: land the compiles
            timed(machines, bucket, pipe, "warmup")
        times = {"serial": [], "pipelined": []}
        for rnd in range(4):
            for label, pipe in (("serial", False), ("pipelined", True)):
                dt = timed(machines, bucket, pipe, label)
                times[label].append(dt)
                log(f"build_pipeline {label}@{n_machines} round {rnd}: "
                    f"{dt:.2f}s ({n_machines / dt * 3600.0:.0f} models/h)")
        best = {label: min(ts) for label, ts in times.items()}
        for label, t in best.items():
            out[f"build_pipeline_{label}_models_per_hour_{n_machines}"] = (
                round(n_machines / t * 3600.0, 1)
            )
        out[f"build_pipeline_speedup_{n_machines}"] = round(
            best["serial"] / best["pipelined"], 4
        )
    # the pipelined runs must have emitted stage-occupancy telemetry; a
    # scrape missing these names means the pipeline silently didn't run
    scrape = telemetry.render()
    wanted = (
        "gordo_build_pipeline_stage_seconds",
        "gordo_build_pipeline_stall_seconds",
        "gordo_build_pipeline_writer_queue_depth",
        "gordo_build_pipeline_chunks_total",
    )
    out["build_pipeline_telemetry_present"] = all(
        name in scrape for name in wanted
    )


def bench_build_throughput(mesh, out: dict) -> None:
    """r23 acceptance: the dispatch/collect split of the build plane.

    Same paired-alternating-best-of protocol as ``bench_build_pipeline``
    (one warmup run per mode lands the compiles, then 4 alternating
    serial/async rounds, per-mode BEST standing — min() rejects one-sided
    timeshare contamination).  Two additions:

    - per-stage attribution from the pipeline stage histogram deltas
      around the best async round — dispatch (host-side launch), device
      (dispatch→collect wall), fetch (blocking D2H), assemble
      (per-machine detector unpacking), write, load — plus the new
      ``gordo_build_device_idle_seconds`` occupancy counter, so the
      remaining between-chunk gaps are measurable instead of inferred;
    - an in-bench byte-parity attestation: one serial and one async
      build of the same machines must produce identical artifacts
      (params + metadata modulo wall-clock fields) and identical
      registry keys, the same contract tests/test_dispatch_collect.py
      pins.

    1-core honesty: on this timeshared single-core container the
    dispatch-behind-collect overlap cannot show as wall-clock win (host
    assembly and "device" compute share the one core, so overlapped work
    serializes anyway) — the CPU-measurable win here is the vectorized
    collect side (pickle-clone assembly, partial D2H, ``tolist`` metadata)
    and the speedup number reads as its lower bound; the overlap itself
    is banked for the TPU tunnel where device compute is genuinely
    asynchronous to the host.
    """
    from gordo_tpu import telemetry
    from gordo_tpu.builder.fleet_build import build_project

    def stage_sums() -> dict:
        metric = telemetry.REGISTRY.snapshot()["metrics"].get(
            "gordo_build_pipeline_stage_seconds"
        ) or {}
        sums = {}
        for key, v in metric.get("series", {}).items():
            sums[json.loads(key)[0]] = float(v["sum"])
        return sums

    def timed(machines, bucket, pipe, label, out_dir=None, reg=None):
        keep = out_dir is not None
        out_dir = out_dir or tempfile.mkdtemp(
            prefix=f"gordo-bench-bt-{label}-"
        )
        before = stage_sums()
        t0 = time.perf_counter()
        result = build_project(
            machines, out_dir, mesh=mesh, max_bucket_size=bucket,
            pipeline=pipe, model_register_dir=reg,
        )
        dt = time.perf_counter() - t0
        after = stage_sums()
        if not keep:
            shutil.rmtree(out_dir, ignore_errors=True)
        if result.failed or len(result.artifacts) != len(machines):
            raise RuntimeError(
                f"build_throughput {label}@{len(machines)}: "
                f"{len(result.failed)} failed"
            )
        stages = {
            k: round(after.get(k, 0.0) - before.get(k, 0.0), 4)
            for k in sorted(set(after) | set(before))
        }
        return dt, stages, result.device_idle_seconds

    n_machines, bucket = 512, 64
    machines = make_machines(n_machines, prefix=f"bench-bt{n_machines}")
    for pipe in (False, True):  # warmup: land the compiles
        timed(machines, bucket, pipe, "warmup")
    times = {"serial": [], "async": []}
    stage_attr = {"serial": None, "async": None}
    idle = {"serial": None, "async": None}
    for rnd in range(4):
        for label, pipe in (("serial", False), ("async", True)):
            dt, stages, idle_s = timed(machines, bucket, pipe, label)
            if not times[label] or dt < min(times[label]):
                stage_attr[label] = stages  # attribution of the BEST round
                idle[label] = round(idle_s, 4)
            times[label].append(dt)
            log(f"build_throughput {label}@{n_machines} round {rnd}: "
                f"{dt:.2f}s ({n_machines / dt * 3600.0:.0f} models/h)")
    best = {label: min(ts) for label, ts in times.items()}
    for label, t in best.items():
        out[f"build_throughput_{label}_models_per_hour_{n_machines}"] = (
            round(n_machines / t * 3600.0, 1)
        )
    out[f"build_throughput_speedup_{n_machines}"] = round(
        best["serial"] / best["async"], 4
    )
    for label in ("serial", "async"):
        out[f"build_throughput_stage_seconds_{label}"] = stage_attr[label]
        out[f"build_throughput_device_idle_seconds_{label}"] = idle[label]
    out["build_throughput_note"] = (
        "1-core timeshare: overlap cannot move wall-clock here (host and "
        "'device' share the core); speedup is the vectorized-collect "
        "lower bound, dispatch overlap banked for TPU"
    )

    # -- in-bench byte-parity attestation (async vs serial, v2 packs) ------
    import pickle

    from gordo_tpu import artifacts as artifacts_mod
    from gordo_tpu.utils import disk_registry

    def scrub(obj, seen=None):
        # mirror tests/test_build_pipeline.py::_scrub_timings: zero
        # wall-clock fields through the pickled graph
        if seen is None:
            seen = set()
        if id(obj) in seen:
            return
        seen.add(id(obj))
        if isinstance(obj, dict):
            for key, zero in (("fleet_seconds", 0.0), ("bucket_size", 0)):
                if key in obj:
                    obj[key] = zero
            for v in obj.values():
                scrub(v, seen)
            return
        if isinstance(obj, (list, tuple)):
            for v in obj:
                scrub(v, seen)
            return
        d = getattr(obj, "__dict__", None)
        if d is None:
            return
        if "fit_seconds_" in d:
            d["fit_seconds_"] = 0.0
        for v in d.values():
            scrub(v, seen)

    parity_machines = make_machines(32, prefix="bench-btp")
    dirs = {}
    for label, pipe in (("serial", False), ("async", True)):
        d = tempfile.mkdtemp(prefix=f"gordo-bench-btpar-{label}-")
        r = tempfile.mkdtemp(prefix=f"gordo-bench-btreg-{label}-")
        timed(parity_machines, 8, pipe, f"parity-{label}", out_dir=d, reg=r)
        dirs[label] = (d, r)
    try:
        sa = artifacts_mod.open_store(dirs["serial"][0])
        sb = artifacts_mod.open_store(dirs["async"][0])
        parity_ok = sorted(sa.names()) == sorted(sb.names())
        for m in parity_machines:
            ma, mb = sa.load_model(m.name), sb.load_model(m.name)
            scrub(ma)
            scrub(mb)
            parity_ok = parity_ok and (
                pickle.dumps(ma) == pickle.dumps(mb)
            )
        parity_ok = parity_ok and sorted(
            disk_registry.list_keys(dirs["serial"][1])
        ) == sorted(disk_registry.list_keys(dirs["async"][1]))
    finally:
        for d, r in dirs.values():
            shutil.rmtree(d, ignore_errors=True)
            shutil.rmtree(r, ignore_errors=True)
    out["build_throughput_parity_ok"] = bool(parity_ok)
    log(f"build_throughput parity (async vs serial, v2): {parity_ok}")
    if not parity_ok:
        raise RuntimeError("async-vs-serial artifact parity FAILED")


def bench_build_ingest(mesh, out: dict) -> None:
    """r24 acceptance: the fleet-vectorized ingest plane vs the
    per-machine pandas load path.

    Same paired-alternating-best-of protocol as the other build stages:
    one warmup run per mode lands the compiles and the OS page cache,
    then 4 alternating per-machine/ingest rounds with the per-mode BEST
    standing (min() rejects one-sided timeshare contamination).  The
    GATED number is the load stage — the pipeline stage-seconds
    histogram delta around each best round — because that is the work
    the ingest plane replaces: 512 sequential resample/join/row-filter
    pandas passes become one columnar numpy pass per dataset geometry,
    writing straight into the preallocated stacked buffer.  Acceptance:
    ingest load ≤ 0.5× the per-machine load.

    ``loader_workers`` is recorded for both modes to attest the r23
    regression fix: the async loader pool is now sized adaptively (2
    threads when the chunk-granular ingest path runs, the wide
    per-machine pool otherwise) instead of a fixed 8 that lost 1.9s to
    thread-pool contention on this 1-core container.

    In-bench byte-parity attestation mirrors build_throughput: one
    per-machine and one ingest build of a 32-machine set — 8 of them
    dataset-fingerprint twins so the fetch-dedup path is exercised, not
    just the vectorized assembly — must produce identical artifacts
    (models modulo zeroed wall-clock timings, metadata modulo volatile
    timing fields) and identical registry keys.  The ingest run's dedup
    counters land in ``build_ingest_dedup``.
    """
    import pickle

    from gordo_tpu import telemetry
    from gordo_tpu import artifacts as artifacts_mod
    from gordo_tpu.builder.fleet_build import build_project
    from gordo_tpu.utils import disk_registry

    def stage_sums() -> dict:
        metric = telemetry.REGISTRY.snapshot()["metrics"].get(
            "gordo_build_pipeline_stage_seconds"
        ) or {}
        sums = {}
        for key, v in metric.get("series", {}).items():
            sums[json.loads(key)[0]] = float(v["sum"])
        return sums

    def timed(machines, bucket, ing, label, out_dir=None, reg=None):
        keep = out_dir is not None
        out_dir = out_dir or tempfile.mkdtemp(
            prefix=f"gordo-bench-bi-{label}-"
        )
        before = stage_sums()
        t0 = time.perf_counter()
        result = build_project(
            machines, out_dir, mesh=mesh, max_bucket_size=bucket,
            pipeline=True, ingest=ing, model_register_dir=reg,
        )
        dt = time.perf_counter() - t0
        after = stage_sums()
        if not keep:
            shutil.rmtree(out_dir, ignore_errors=True)
        if result.failed or len(result.artifacts) != len(machines):
            raise RuntimeError(
                f"build_ingest {label}@{len(machines)}: "
                f"{len(result.failed)} failed"
            )
        stages = {
            k: round(after.get(k, 0.0) - before.get(k, 0.0), 4)
            for k in sorted(set(after) | set(before))
        }
        return dt, stages, result

    n_machines, bucket = N_MACHINES, 64
    machines = make_machines(n_machines, prefix=f"bench-bi{n_machines}")
    for ing in (False, True):  # warmup: land the compiles + page cache
        timed(machines, bucket, ing, "warmup")
    times = {"permachine": [], "ingest": []}
    stage_attr = {"permachine": None, "ingest": None}
    workers = {"permachine": None, "ingest": None}
    dedup = None
    for rnd in range(4):
        for label, ing in (("permachine", False), ("ingest", True)):
            dt, stages, result = timed(machines, bucket, ing, label)
            if not times[label] or dt < min(times[label]):
                stage_attr[label] = stages  # attribution of the BEST round
                workers[label] = result.loader_workers
                if ing:
                    dedup = dict(result.ingest or {})
            times[label].append(dt)
            log(f"build_ingest {label}@{n_machines} round {rnd}: "
                f"{dt:.2f}s load={stages.get('load', 0.0):.2f}s")
    best = {label: min(ts) for label, ts in times.items()}
    for label in ("permachine", "ingest"):
        out[f"build_ingest_{label}_seconds_{n_machines}"] = round(
            best[label], 4
        )
        out[f"build_ingest_stage_seconds_{label}"] = stage_attr[label]
        out[f"build_ingest_loader_workers_{label}"] = workers[label]
    load_pm = stage_attr["permachine"].get("load", 0.0)
    load_in = stage_attr["ingest"].get("load", 0.0)
    ratio = (load_in / load_pm) if load_pm else None
    out["build_ingest_load_seconds_permachine"] = load_pm
    out["build_ingest_load_seconds_ingest"] = load_in
    out["build_ingest_load_ratio"] = round(ratio, 4) if ratio else ratio
    out["build_ingest_load_gate_ok"] = bool(ratio is not None
                                            and ratio <= 0.5)
    out["build_ingest_wall_speedup"] = round(
        best["permachine"] / best["ingest"], 4
    )
    log(f"build_ingest load: per-machine {load_pm:.2f}s, "
        f"ingest {load_in:.2f}s, ratio {ratio:.3f} (gate ≤0.5)")

    # -- in-bench byte-parity attestation (ingest vs per-machine) ----------
    # make_machines tag names don't include the prefix, so two calls with
    # different prefixes yield dataset-fingerprint TWINS: 8 of the 32
    # parity machines dedup against the first 8, exercising the shared
    # fetch path in the attested build, not just vectorized assembly.
    volatile_meta = {
        "model_creation_date", "data_query_duration_sec",
        "cross_validation_duration_sec", "model_builder_duration_sec",
        "fit_samples_per_second", "fit_seconds", "fleet_seconds",
        "bucket_size",
    }  # mirrors tests/test_build_pipeline.py::VOLATILE_META

    def strip_meta(v):
        if isinstance(v, dict):
            return {k: strip_meta(x) for k, x in v.items()
                    if k not in volatile_meta}
        if isinstance(v, list):
            return [strip_meta(x) for x in v]
        return v

    def scrub(obj, seen=None):
        # mirror tests/test_build_pipeline.py::_scrub_timings
        if seen is None:
            seen = set()
        if id(obj) in seen:
            return
        seen.add(id(obj))
        if isinstance(obj, dict):
            for key, zero in (("fleet_seconds", 0.0), ("bucket_size", 0)):
                if key in obj:
                    obj[key] = zero
            for v in obj.values():
                scrub(v, seen)
            return
        if isinstance(obj, (list, tuple)):
            for v in obj:
                scrub(v, seen)
            return
        d = getattr(obj, "__dict__", None)
        if d is None:
            return
        if "fit_seconds_" in d:
            d["fit_seconds_"] = 0.0
        for v in d.values():
            scrub(v, seen)

    parity_machines = (
        make_machines(24, prefix="bench-bi-par")
        + make_machines(8, prefix="bench-bi-twin")
    )
    dirs = {}
    for label, ing in (("permachine", False), ("ingest", True)):
        d = tempfile.mkdtemp(prefix=f"gordo-bench-bipar-{label}-")
        r = tempfile.mkdtemp(prefix=f"gordo-bench-bireg-{label}-")
        # one 32-wide chunk: fetch dedup is chunk-granular, so the twins
        # must share a chunk with their originals to register hits
        _, _, result = timed(
            parity_machines, 32, ing, f"parity-{label}", out_dir=d, reg=r
        )
        if ing:
            out["build_ingest_dedup"] = dict(result.ingest or {})
        dirs[label] = (d, r)
    try:
        sa = artifacts_mod.open_store(dirs["permachine"][0])
        sb = artifacts_mod.open_store(dirs["ingest"][0])
        parity_ok = sorted(sa.names()) == sorted(sb.names())
        for m in parity_machines:
            ma, mb = sa.load_model(m.name), sb.load_model(m.name)
            scrub(ma)
            scrub(mb)
            parity_ok = parity_ok and (
                pickle.dumps(ma) == pickle.dumps(mb)
            )
            parity_ok = parity_ok and (
                strip_meta(sa.load_metadata(m.name))
                == strip_meta(sb.load_metadata(m.name))
            )
        parity_ok = parity_ok and sorted(
            disk_registry.list_keys(dirs["permachine"][1])
        ) == sorted(disk_registry.list_keys(dirs["ingest"][1]))
    finally:
        for d, r in dirs.values():
            shutil.rmtree(d, ignore_errors=True)
            shutil.rmtree(r, ignore_errors=True)
    out["build_ingest_parity_ok"] = bool(parity_ok)
    log(f"build_ingest parity (ingest vs per-machine): {parity_ok}")
    if not parity_ok:
        raise RuntimeError("ingest-vs-per-machine artifact parity FAILED")


def bench_lstm_build(mesh, out: dict) -> None:
    """BASELINE config 2: lstm_hourglass on 50-tag windowed sequences —
    the scenario where scan latency and MXU under-utilization bite."""
    from gordo_tpu.serve.scorer import CompiledScorer

    machines = make_machines(
        N_LSTM_MACHINES, n_tags=N_LSTM_TAGS, model=LSTM_MODEL,
        prefix="bench-lstm",
    )
    rates, model = _timed_build_runs(machines, mesh, "lstm")
    n_chips = out.get("n_chips", 1)
    out["lstm_models_per_hour_per_chip"] = round(rates[-1] / n_chips, 1)
    out["lstm_vs_baseline"] = round(
        rates[-1] / n_chips / NORTH_STAR_MODELS_PER_HOUR_PER_CHIP, 3
    )
    if model is not None:
        _flop_fields(out, "lstm", model, rates[-1], seq_steps=LSTM_LOOKBACK)

        # LSTM serving rate (in-process fused scorer)
        scorer = CompiledScorer(model)
        rng = np.random.default_rng(0)
        X = rng.standard_normal((4096, N_LSTM_TAGS)).astype(np.float32)
        scorer.anomaly_arrays(X, None)  # compile
        n_iter, t0 = 10, time.perf_counter()
        for _ in range(n_iter):
            scorer.anomaly_arrays(X, None)
        lstm_serving = n_iter * X.size / (time.perf_counter() - t0)
        out["lstm_serving_samples_per_sec_inprocess"] = round(lstm_serving)
        log(f"lstm serving (in-process): {lstm_serving:,.0f} samples/s")


# ---------------------------------------------------------------------------
# serving benches
# ---------------------------------------------------------------------------

def _build_serving_model():
    """One built bench machine's (model, metadata) — the serving stages'
    shared prototype."""
    from gordo_tpu.builder.build_model import build_model

    machine = make_machines(1)[0]
    return build_model(
        machine.name, machine.model, machine.dataset, {}, machine.evaluation
    )


def _serving_collection(art_dir: str, model, metadata, n_machines: int = 64):
    """A 64-machine ModelCollection over one artifact dir: each entry loads
    its own params copy, exactly like a 64-machine project (the device
    can't tell values are equal; the stacked program shape is identical)."""
    from gordo_tpu.serve.server import ModelCollection, ModelEntry
    from gordo_tpu import serializer

    art = os.path.join(art_dir, "m-000")
    serializer.dump(model, art, metadata=metadata)
    entries = {
        f"m-{i:03d}": ModelEntry(f"m-{i:03d}", art)
        for i in range(n_machines)
    }
    return ModelCollection(entries, project="bench")


def bench_serving(out: dict) -> None:
    """Config 5.  In-process scorer rates AND end-to-end HTTP replay —
    single + bulk, JSON + msgpack — reported as separate fields."""
    from gordo_tpu.serve.fleet_scorer import FleetScorer
    from gordo_tpu.serve.scorer import CompiledScorer
    from gordo_tpu.serve.replay import replay_bench

    model, metadata = _build_serving_model()
    rng = np.random.default_rng(0)

    # -- in-process (codec-free ceiling) ------------------------------------
    scorer = CompiledScorer(model)
    X = rng.standard_normal((8192, N_TAGS)).astype(np.float32)
    scorer.anomaly_arrays(X, None)  # compile
    n_iter, t0 = 20, time.perf_counter()
    for _ in range(n_iter):
        scorer.anomaly_arrays(X, None)
    single = n_iter * X.size / (time.perf_counter() - t0)
    out["serving_samples_per_sec_inprocess"] = round(single)
    log(f"serving in-process single: {single:,.0f} samples/s")

    n_machines = 64
    fleet = FleetScorer.from_models(
        {f"m-{i:03d}": model for i in range(n_machines)}
    )
    X_by = {
        f"m-{i:03d}": rng.standard_normal((2048, N_TAGS)).astype(np.float32)
        for i in range(n_machines)
    }
    fleet.score_all(X_by)  # compile
    n_iter, t0 = 10, time.perf_counter()
    for _ in range(n_iter):
        fleet.score_all(X_by)
    stacked = n_iter * n_machines * 2048 * N_TAGS / (time.perf_counter() - t0)
    out["serving_samples_per_sec_inprocess_stacked"] = round(stacked)
    log(f"serving in-process stacked ({n_machines} machines): "
        f"{stacked:,.0f} samples/s")

    # -- HTTP replayed stream (the number that matters) ---------------------
    art_dir = tempfile.mkdtemp(prefix="gordo-bench-serve-")
    try:
        collection = _serving_collection(
            art_dir, model, metadata, n_machines
        )

        http = {}
        for mode, wire, rounds, coalesce_ms, par in (
            ("bulk", "json", 5, 0.0, 8),
            ("bulk", "msgpack", 5, 0.0, 8),
            # coalesced-vs-not at three concurrencies (r4 verdict item 4):
            # the adaptive policy must make coalescing >= direct everywhere
            # (or stand down to it).  5 rounds per paired point: at 3 the
            # pair's delta was inside run-to-run noise (±3%) and flipped
            # sign between runs.
            ("single", "json", 3, 0.0, 1),
            ("single", "json", 3, 2.0, 1),
            ("single", "json", 5, 0.0, 8),
            ("single", "json", 5, 2.0, 8),
            ("single", "json", 5, 0.0, 64),
            ("single", "json", 5, 2.0, 64),
        ):
            # paired (direct-vs-coalesced) points run best-of-2: single
            # runs on a shared CPU drift ±10% between adjacent runs, which
            # is larger than the effect under test at low concurrency.
            # Applied symmetrically to both sides of every pair.
            n_attempts = 2 if mode == "single" else 1
            res = None
            for _ in range(n_attempts):
                attempt = replay_bench(
                    collection, mode=mode, wire=wire, n_rounds=rounds,
                    rows=2048, parallelism=par,
                    coalesce_window_ms=coalesce_ms,
                )
                if res is None or (
                    attempt["samples_per_sec"] > res["samples_per_sec"]
                ):
                    res = attempt
            key = f"serving_samples_per_sec_http_{mode}_{wire}"
            if coalesce_ms:
                key += "_coalesced"
            if par != 8:  # 8-way keeps the r3/r4-compatible unsuffixed key
                key += f"_p{par}"
            out[key] = round(res["samples_per_sec"])
            out[key.replace("samples_per_sec", "latency_p50_ms")] = round(
                res["latency_p50_ms"], 2
            )
            if res["latency_n"] >= 20:
                # fewer samples (bulk: one request/round) would record a
                # near-max masquerading as a tail percentile
                out[key.replace("samples_per_sec", "latency_p99_ms")] = round(
                    res["latency_p99_ms"], 2
                )
            http[(mode, wire, bool(coalesce_ms), par)] = res["samples_per_sec"]
            co = res.get("coalescer") or {}
            if co:
                # attest how the adaptive policy behaved in the measured
                # window: "knee_no_gain + 0 dispatches" IS the evidence
                # that the combined path routed direct where batching
                # can't pay (acceptance: never worse than direct)
                out[key + "_coalescer"] = {
                    k: co.get(k)
                    for k in (
                        "dispatches", "requests", "bypassed_requests",
                        "mean_batch", "batch_cap", "knee_estimated",
                        "knee_no_gain", "queue_full_bypassed", "standdowns",
                    )
                }
            co_note = (
                f", batch {co['mean_batch']} cap {co['batch_cap']} "
                f"standdowns {co['standdowns']}"
                if co.get("dispatches") else ""
            )
            log(f"serving HTTP {mode}/{wire} x{par}"
                f"{' +coalesce' if coalesce_ms else ''}: "
                f"{res['samples_per_sec']:,.0f} samples/s "
                f"({res['response_mb_per_sec']:.1f} MB/s responses, "
                f"p50 {res['latency_p50_ms']:.0f}ms / "
                f"p99 {res['latency_p99_ms']:.0f}ms{co_note})")
        # headline serving number = HTTP bulk over the production wire
        out["serving_samples_per_sec"] = round(
            http[("bulk", "msgpack", False, 8)]
        )
        out["serving_devices"] = 1
        out["serving_vs_target"] = round(
            http[("bulk", "msgpack", False, 8)]
            / NORTH_STAR_SAMPLES_PER_SEC_PER_CHIP,
            3,
        )
    finally:
        shutil.rmtree(art_dir, ignore_errors=True)


def bench_serving_openloop(out: dict) -> None:
    """Open-loop (fixed-arrival-rate) latency points — the percentiles an
    SLO would actually use, vs the closed-loop saturation artifacts the
    ``serving`` stage reports.  Protocol per route: measure saturation
    closed-loop, then p50/p99 at 0.5× and 0.8× of it
    (``serve.replay.openloop_bench``)."""
    from gordo_tpu.serve.replay import openloop_bench

    model, metadata = _build_serving_model()
    art_dir = tempfile.mkdtemp(prefix="gordo-bench-openloop-")
    try:
        collection = _serving_collection(art_dir, model, metadata, 64)
        for mode, wire, coalesce_ms, par in (
            # the production bulk wire (acceptance: p99_at_* for msgpack
            # bulk), then the coalescer's route direct vs coalesced
            ("bulk", "msgpack", 0.0, 8),
            ("single", "json", 0.0, 32),
            ("single", "json", 2.0, 32),
        ):
            res = openloop_bench(
                collection, mode=mode, wire=wire, rows=2048,
                parallelism=par, sat_rounds=2, duration_s=4.0,
                coalesce_window_ms=coalesce_ms,
            )
            base = f"serving_openloop_{mode}_{wire}"
            if coalesce_ms:
                base += "_coalesced"
            out[base + "_saturation_rps"] = round(
                res["saturation_requests_per_sec"], 2
            )
            for frac, p in res["points"].items():
                out[f"{base}_p50_at_{frac}_ms"] = round(
                    p["latency_p50_ms"], 2
                )
                out[f"{base}_p99_at_{frac}_ms"] = round(
                    p["latency_p99_ms"], 2
                )
                out[f"{base}_latency_n_at_{frac}"] = p["latency_n"]
            log(
                f"openloop {mode}/{wire}"
                f"{' +coalesce' if coalesce_ms else ''}: sat "
                f"{res['saturation_requests_per_sec']:.1f} req/s; "
                + "; ".join(
                    f"{frac}: p50 {p['latency_p50_ms']:.0f}ms / "
                    f"p99 {p['latency_p99_ms']:.0f}ms (n={p['latency_n']})"
                    for frac, p in res["points"].items()
                )
            )
    finally:
        shutil.rmtree(art_dir, ignore_errors=True)


def bench_serving_precision(out: dict) -> None:
    """ISSUE 7 acceptance: the fused single-dispatch request path vs the
    r11 host-side path, and the serving-precision (dtype) sweep.

    Protocol (docs/perf.md "Serving precision"):

    - in-process fp32-vs-bf16 parity re-attestation (max-normalized
      per-series error; bounds match tests/test_serving_precision.py)
      and the single-dispatch attestation: N requests must move the
      dispatch/transfer counters by exactly N;
    - per dtype (fp32, bf16): p50/p99 + throughput over the
      single-machine JSON route at 1/8/64-way closed loop (fresh
      collection per dtype — buckets restack at the storage dtype);
    - fused vs host (GORDO_SERVE_FUSED=off — the r11 request path with
      concatenate/tile padding and the host confidence divide) at
      64-way fp32, interleaved best-of-2 per side.  Gate: fused p50
      strictly below host p50 in the same run.

    CPU XLA emulates bf16, so bf16 *throughput parity* is the expected
    CPU result (the bf16 win is a TPU lever); the CPU win under test
    here is the fused path vs r11's host-side work.
    """
    from gordo_tpu import telemetry
    from gordo_tpu.serve.replay import replay_bench
    from gordo_tpu.serve.scorer import CompiledScorer

    model, metadata = _build_serving_model()
    art_dir = tempfile.mkdtemp(prefix="gordo-bench-prec-")
    knobs = ("GORDO_SERVE_DTYPE", "GORDO_SERVE_FUSED", "GORDO_SERVE_INT8")
    saved = {k: os.environ.get(k) for k in knobs}

    def setenv(key: str, value: "str | None") -> None:
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = value

    def counter(name: str) -> float:
        metric = telemetry.REGISTRY.snapshot()["metrics"].get(name) or {}
        return float(sum(metric.get("series", {}).values()))

    try:
        rng = np.random.default_rng(0)
        X = rng.standard_normal((2048, N_TAGS)).astype(np.float32)

        # -- parity re-attestation (in-process, per-series bounds) ----------
        ref_scorer = CompiledScorer(model, dtype="float32")
        ref = ref_scorer.anomaly_arrays(X)
        bf = CompiledScorer(model, dtype="bfloat16").anomaly_arrays(X)
        bounds = {
            "model-output": 0.03,
            "total-anomaly-score": 0.10,
            "anomaly-confidence": 0.10,
        }
        errs, parity_ok = {}, True
        for key, tol in bounds.items():
            r = np.asarray(ref[key], np.float32)
            q = np.asarray(bf[key], np.float32)
            scale = max(float(np.max(np.abs(r))), 1e-6)
            err = float(np.max(np.abs(r - q))) / scale
            errs[key] = round(err, 6)
            parity_ok = parity_ok and err <= tol
        out["serving_precision_bf16_max_norm_err"] = errs
        out["serving_precision_bf16_parity_ok"] = bool(parity_ok)
        log(f"serving_precision bf16 parity: {errs} -> "
            f"{'OK' if parity_ok else 'FAIL'}")

        # -- single-dispatch attestation ------------------------------------
        n_att = 20
        d0 = counter("gordo_serve_dispatches_total")
        t0 = counter("gordo_serve_input_transfers_total")
        for _ in range(n_att):
            ref_scorer.anomaly_arrays(X)
        dd = counter("gordo_serve_dispatches_total") - d0
        td = counter("gordo_serve_input_transfers_total") - t0
        out["serving_precision_requests_attested"] = n_att
        out["serving_precision_dispatches_measured"] = dd
        out["serving_precision_one_dispatch_per_request"] = (
            dd == n_att and td == n_att
        )
        log(f"serving_precision dispatch attestation: {dd:.0f} dispatches / "
            f"{td:.0f} transfers for {n_att} requests")

        # -- per-dtype HTTP sweep at 1/8/64-way -----------------------------
        for dtype_name, env_value in (("float32", None), ("bfloat16", "bf16")):
            setenv("GORDO_SERVE_DTYPE", env_value)
            collection = _serving_collection(art_dir, model, metadata, 64)
            for par, rounds in ((1, 3), (8, 4), (64, 4)):
                res = replay_bench(
                    collection, mode="single", wire="json",
                    n_rounds=rounds, rows=2048, parallelism=par,
                )
                key = f"serving_precision_{dtype_name}"
                out[f"{key}_samples_per_sec_p{par}"] = round(
                    res["samples_per_sec"]
                )
                out[f"{key}_p50_ms_p{par}"] = round(res["latency_p50_ms"], 2)
                if res["latency_n"] >= 20:
                    out[f"{key}_p99_ms_p{par}"] = round(
                        res["latency_p99_ms"], 2
                    )
                log(f"serving_precision {dtype_name} x{par}: "
                    f"{res['samples_per_sec']:,.0f} samples/s, "
                    f"p50 {res['latency_p50_ms']:.1f}ms / "
                    f"p99 {res['latency_p99_ms']:.1f}ms")
        setenv("GORDO_SERVE_DTYPE", None)

        # -- fused vs r11 host path, 64-way fp32, interleaved best-of-2 -----
        collection = _serving_collection(art_dir, model, metadata, 64)
        best: dict = {"host": None, "fused": None}
        for _ in range(2):
            for label, fused_env in (("host", "off"), ("fused", None)):
                setenv("GORDO_SERVE_FUSED", fused_env)
                res = replay_bench(
                    collection, mode="single", wire="json",
                    n_rounds=4, rows=2048, parallelism=64,
                )
                point = {
                    "p50": res["latency_p50_ms"],
                    "p99": res["latency_p99_ms"],
                    "sps": res["samples_per_sec"],
                }
                if best[label] is None or point["p50"] < best[label]["p50"]:
                    best[label] = point
                log(f"serving_precision {label} x64: "
                    f"p50 {point['p50']:.1f}ms, {point['sps']:,.0f} samples/s")
        setenv("GORDO_SERVE_FUSED", None)
        out["serving_precision_host_p50_ms_64"] = round(
            best["host"]["p50"], 2
        )
        out["serving_precision_fused_p50_ms_64"] = round(
            best["fused"]["p50"], 2
        )
        out["serving_precision_host_p99_ms_64"] = round(
            best["host"]["p99"], 2
        )
        out["serving_precision_fused_p99_ms_64"] = round(
            best["fused"]["p99"], 2
        )
        out["serving_precision_fused_samples_per_sec_64"] = round(
            best["fused"]["sps"]
        )
        out["serving_precision_host_samples_per_sec_64"] = round(
            best["host"]["sps"]
        )
        # the acceptance gate: the fused single-dispatch path beats the
        # r11 host-side path on CPU p50 at 64-way, same run
        out["serving_precision_fused_beats_host_p50_64"] = (
            best["fused"]["p50"] < best["host"]["p50"]
        )
        log(f"serving_precision fused vs host p50 @64: "
            f"{best['fused']['p50']:.1f}ms vs {best['host']['p50']:.1f}ms "
            f"({'PASS' if best['fused']['p50'] < best['host']['p50'] else 'FAIL'})")
    finally:
        for key, value in saved.items():
            setenv(key, value)
        shutil.rmtree(art_dir, ignore_errors=True)


def bench_telemetry_overhead(out: dict) -> None:
    """Acceptance gate for the telemetry plane: the instrumented msgpack
    bulk path (request middleware + histograms + spans live) must cost
    <= 2% throughput vs the ``GORDO_TELEMETRY=off`` kill switch.

    Protocol (r9 fix): BENCH_r08 recorded a −16.83% "overhead" — the
    instrumented side measured FASTER than the kill switch, i.e. pure
    noise — because each side reported a best-of-3 with no warmup and
    the two sides ran as sequential blocks, so minutes of machine drift
    (plus lucky cold-cache draws) decided the sign.  Now: one unrecorded
    WARMUP round per side (aiohttp connection pool, codec and jit caches
    hot), then 3 recorded samples per side taken INTERLEAVED
    (on, off, on, off, ...) so drift lands on both sides equally, and
    the gate compares per-side MEDIANS — best-of rewards outliers, the
    median ignores them.  The per-side sample lists land in the doc so
    the spread is attestable next to the verdict.
    """
    from gordo_tpu import telemetry
    from gordo_tpu.serve.replay import replay_bench

    model, metadata = _build_serving_model()
    art_dir = tempfile.mkdtemp(prefix="gordo-bench-telemetry-")
    try:
        collection = _serving_collection(art_dir, model, metadata, 64)

        def sample(n_rounds: int = 5) -> dict:
            return replay_bench(
                collection, mode="bulk", wire="msgpack", n_rounds=n_rounds,
                rows=2048, parallelism=8,
            )

        results = {True: [], False: []}
        for i in range(3):
            for enabled in (True, False):
                telemetry.set_enabled(enabled)
                try:
                    if i == 0:
                        sample(n_rounds=2)  # per-side warmup, discarded
                    results[enabled].append(sample())
                finally:
                    telemetry.set_enabled(True)

        def median(rs: "list[dict]") -> "tuple[dict, list[float]]":
            rs = sorted(rs, key=lambda r: r["samples_per_sec"])
            return rs[len(rs) // 2], [r["samples_per_sec"] for r in rs]

        on, on_samples = median(results[True])
        off, off_samples = median(results[False])
        overhead_pct = 100.0 * (
            1.0 - on["samples_per_sec"] / off["samples_per_sec"]
        )
        out["telemetry_on_samples_per_sec"] = round(on["samples_per_sec"])
        out["telemetry_off_samples_per_sec"] = round(off["samples_per_sec"])
        out["telemetry_on_samples"] = [round(v) for v in on_samples]
        out["telemetry_off_samples"] = [round(v) for v in off_samples]
        # negative = instrumented median still faster: residual noise
        # floor, now bounded by the median instead of amplified by max()
        out["telemetry_overhead_pct"] = round(overhead_pct, 2)
        out["telemetry_overhead_ok"] = overhead_pct <= 2.0
        # the in-run scrape attests /metrics served valid text under load
        out["telemetry_scrape"] = on.get("metrics_scrape")
        log(
            f"telemetry overhead (msgpack bulk, interleaved median of 3): "
            f"on {on['samples_per_sec']:,.0f} vs off "
            f"{off['samples_per_sec']:,.0f} samples/s -> "
            f"{overhead_pct:+.2f}% (gate: <= 2%)"
        )
    finally:
        shutil.rmtree(art_dir, ignore_errors=True)


def bench_health_overhead(out: dict) -> None:
    """ISSUE 9 acceptance: the fleet-health plane's per-response score
    sketching must stay within the existing <= 2% telemetry budget on
    the 64-way bulk serving path, and a 2-shard fleet's merged health
    doc must be byte-equivalent to the single-process one for the same
    request stream.

    Protocol: one unrecorded warmup round per side, then 5 ADJACENT
    on/off pairs with the gate on the MEDIAN of pairwise overheads —
    a tightening of telemetry_overhead's r9 interleaving: on this
    shared-box class of machine the per-sample spread is 20-30%, so
    per-side medians taken minutes apart still soak up drift; adjacent
    pairs run seconds apart and their ratio cancels it.  The recording
    side also attests the sketches actually accumulated (a no-op path
    passing the gate would prove nothing).

    Merge parity: the same deterministic per-machine request stream is
    scored once through one full-fleet collection and once through two
    machine-affinity shard collections (the serve.shard partition);
    the shards' health docs merge through telemetry.merge_health_docs —
    the SAME function watchman's /fleet-health endpoint applies to the
    per-replica docs it fetches — and the merged doc must equal the
    single-process doc byte-for-byte after stripping timestamps
    (json.dumps(normalize_health_doc(...), sort_keys=True)).
    """
    from gordo_tpu import telemetry
    from gordo_tpu.serve.replay import replay_bench
    from gordo_tpu.serve.server import ModelCollection
    from gordo_tpu.serve.shard import shard_map

    model, metadata = _build_serving_model()
    art_dir = tempfile.mkdtemp(prefix="gordo-bench-health-")
    try:
        collection = _serving_collection(art_dir, model, metadata, 64)
        names = sorted(collection.entries)
        baselines = {n: collection.entries[n].metadata for n in names}

        def sample(n_rounds: int = 5) -> dict:
            return replay_bench(
                collection, mode="bulk", wire="msgpack", n_rounds=n_rounds,
                rows=2048, parallelism=8,
            )

        telemetry.FLEET_HEALTH.clear()
        telemetry.FLEET_HEALTH.load_baselines(baselines)
        on_samples: "list[float]" = []
        off_samples: "list[float]" = []
        pair_pcts: "list[float]" = []
        for i in range(5):
            for enabled in (True, False):
                telemetry.set_enabled(enabled)
                try:
                    if i == 0:
                        sample(n_rounds=2)  # per-side warmup, discarded
                    rate = sample()["samples_per_sec"]
                finally:
                    telemetry.set_enabled(True)
                (on_samples if enabled else off_samples).append(rate)
            pair_pcts.append(
                100.0 * (1.0 - on_samples[-1] / off_samples[-1])
            )
        overhead_pct = sorted(pair_pcts)[len(pair_pcts) // 2]
        doc = telemetry.FLEET_HEALTH.doc(machines=names)
        recorded = sum(
            1 for e in doc["machines"].values() if e["live"]
        )
        out["health_on_samples"] = [round(v) for v in on_samples]
        out["health_off_samples"] = [round(v) for v in off_samples]
        out["health_pair_overhead_pcts"] = [
            round(p, 2) for p in pair_pcts
        ]
        out["health_overhead_pct"] = round(overhead_pct, 2)
        out["health_overhead_ok"] = overhead_pct <= 2.0
        # recording attestation: every served machine's sketch is live
        # and the drift signal computed against the build baseline
        out["health_machines_recorded"] = recorded
        out["health_top_drift_len"] = len(doc["top-drift"])
        log(
            f"fleet-health overhead (msgpack bulk, median of 5 adjacent "
            f"on/off pairs): {overhead_pct:+.2f}% "
            f"(pairs {[round(p, 2) for p in pair_pcts]}, gate: <= 2%); "
            f"{recorded}/64 machines sketched"
        )

        # -- 2-shard merged doc == single-process doc -----------------------
        rng = np.random.default_rng(14)
        streams = {
            n: [
                rng.standard_normal((1024, N_TAGS)).astype(np.float32)
                for _ in range(3)
            ]
            for n in names
        }

        telemetry.FLEET_HEALTH.clear()
        telemetry.FLEET_HEALTH.load_baselines(baselines)
        full_scorer = collection.fleet_scorer
        for rnd in range(3):
            full_scorer.score_all({n: streams[n][rnd] for n in names})
        doc_full = telemetry.normalize_health_doc(
            telemetry.FLEET_HEALTH.doc(machines=names, top=8)
        )

        telemetry.FLEET_HEALTH.clear()
        owners = shard_map(names, 2)
        shard_docs = []
        for shard_idx in range(2):
            owned = [n for n in names if owners[n] == shard_idx]
            shard_col = ModelCollection(
                {n: collection.entries[n] for n in owned}, project="bench"
            )
            for rnd in range(3):
                shard_col.fleet_scorer.score_all(
                    {n: streams[n][rnd] for n in owned}
                )
            shard_docs.append(
                telemetry.FLEET_HEALTH.doc(machines=owned, top=8)
            )
        merged = telemetry.normalize_health_doc(
            telemetry.merge_health_docs(shard_docs, top=8)
        )
        full_bytes = json.dumps(doc_full, sort_keys=True)
        merged_bytes = json.dumps(merged, sort_keys=True)
        out["health_merge_parity_ok"] = full_bytes == merged_bytes
        out["health_merge_doc_bytes"] = len(full_bytes)
        log(
            "fleet-health 2-shard merged doc parity: "
            + ("byte-equivalent" if full_bytes == merged_bytes
               else "MISMATCH")
            + f" ({len(full_bytes)} bytes, modulo timestamps)"
        )
        telemetry.FLEET_HEALTH.clear()
    finally:
        shutil.rmtree(art_dir, ignore_errors=True)


def bench_artifact_io(out: dict) -> None:
    """ISSUE 6 acceptance: artifact format v2 (memory-mapped bucket
    packs) vs v1 (per-machine dirs) — build artifact-write throughput
    and server time-to-ready, measured in the same run.

    Protocol (docs/perf.md "Artifact I/O"): train ONE machine, then
    replicate its trained detector across N names so the measurement
    isolates artifact I/O from training.  Writes: v1 dumps N per-machine
    dirs through the serializer; v2 writes ``ceil(N/512)`` packs through
    ``artifacts.write_pack``.  Time-to-ready: ``ModelCollection.
    from_directory`` + fleet-scorer construction + a block on the
    stacked device params — everything between "process has artifacts"
    and "bulk scoring is resident", without HTTP noise.  At 512 the
    ready points run best-of-2 interleaved (v1, v2, v1, v2 — shared-CPU
    drift lands on both sides); the 10k points run once each, budget
    permitting.  Gate: v2 time-to-ready at 512 strictly below v1's in
    this run.  The v2 load's whole-pack device transfers are attested
    from the telemetry counter (exactly one per pack).
    """
    import jax

    from gordo_tpu import artifacts, serializer
    from gordo_tpu.serve.server import ModelCollection

    model, metadata = _build_serving_model()
    chunk = 512

    def dir_bytes(d: str) -> int:
        total = 0
        for root, _, files in os.walk(d):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
        return total

    def write_v1(d: str, names: "list[str]") -> float:
        t0 = time.perf_counter()
        for name in names:
            md = dict(metadata)
            md["name"] = name
            serializer.dump(model, os.path.join(d, name), metadata=md)
        return time.perf_counter() - t0

    def write_v2(d: str, names: "list[str]") -> float:
        t0 = time.perf_counter()
        for start in range(0, len(names), chunk):
            part = names[start: start + chunk]
            metas = []
            for name in part:
                md = dict(metadata)
                md["name"] = name
                metas.append(md)
            artifacts.write_pack(d, part, [model] * len(part), metas)
        return time.perf_counter() - t0

    def time_to_ready(d: str) -> float:
        t0 = time.perf_counter()
        coll = ModelCollection.from_directory(d, project="bench")
        fleet = coll.fleet_scorer
        for bucket in fleet.buckets:
            jax.block_until_ready(jax.tree.leaves(bucket.params))
        return time.perf_counter() - t0

    n_large = int(os.environ.get("BENCH_ARTIFACT_MACHINES", "10000"))
    for n in (512, n_large):
        names = [f"am-{i:05d}" for i in range(n)]
        d1 = tempfile.mkdtemp(prefix=f"gordo-bench-art-v1-{n}-")
        d2 = tempfile.mkdtemp(prefix=f"gordo-bench-art-v2-{n}-")
        try:
            t_v1 = write_v1(d1, names)
            t_v2 = write_v2(d2, names)
            b1, b2 = dir_bytes(d1), dir_bytes(d2)
            n_packs = -(-n // chunk)
            out[f"artifact_io_write_v1_s_{n}"] = round(t_v1, 3)
            out[f"artifact_io_write_v2_s_{n}"] = round(t_v2, 3)
            out[f"artifact_io_write_v1_artifacts_per_sec_{n}"] = round(
                n / t_v1, 1
            )
            out[f"artifact_io_write_v2_artifacts_per_sec_{n}"] = round(
                n / t_v2, 1
            )
            out[f"artifact_io_write_v2_mb_per_sec_{n}"] = round(
                b2 / t_v2 / 1e6, 1
            )
            out[f"artifact_io_bytes_v1_{n}"] = b1
            out[f"artifact_io_bytes_v2_{n}"] = b2
            out[f"artifact_io_packs_{n}"] = n_packs
            log(f"artifact_io write @{n}: v1 {t_v1:.2f}s ({b1 / 1e6:.1f} MB)"
                f" vs v2 {t_v2:.2f}s ({b2 / 1e6:.1f} MB, {n_packs} packs)")

            attempts = 2 if n == 512 else 1
            ready = {"v1": [], "v2": []}
            for i in range(attempts):
                ready["v1"].append(time_to_ready(d1))
                if n == 512 and i == 0:
                    d0 = artifacts.device_put_count()
                ready["v2"].append(time_to_ready(d2))
                if n == 512 and i == 0:
                    dputs = artifacts.device_put_count() - d0
                    out["artifact_io_device_puts_512"] = dputs
                    out["artifact_io_one_device_put_per_pack"] = (
                        dputs == n_packs
                    )
            r1, r2 = min(ready["v1"]), min(ready["v2"])
            out[f"artifact_io_ready_v1_s_{n}"] = round(r1, 3)
            out[f"artifact_io_ready_v2_s_{n}"] = round(r2, 3)
            out[f"artifact_io_ready_speedup_{n}"] = round(r1 / r2, 3)
            log(f"artifact_io time-to-ready @{n}: v1 {r1:.2f}s vs "
                f"v2 {r2:.2f}s ({r1 / r2:.2f}x)")
            if n == 512:
                # the acceptance gate, same-run comparison
                out["artifact_io_ready_ok"] = r2 < r1
                # context vs BENCH_r10's warmed-restart 2.19s (different
                # workload — 8-machine forked full restart — recorded
                # for trend reading, not a gate)
                out["artifact_io_ready_v2_beats_r10_restart"] = r2 < 2.19
        finally:
            shutil.rmtree(d1, ignore_errors=True)
            shutil.rmtree(d2, ignore_errors=True)


def bench_hot_reload(out: dict) -> None:
    """ISSUE 11 acceptance: versioned artifact generations + delta hot
    reload — the serving process picks up a ``delta_write`` of k changed
    machines out of BENCH_ARTIFACT_MACHINES (default 10k) in
    O(changed-machines), never restarting and never recompiling.

    Protocol (docs/perf.md "Hot reload"): train ONE machine, replicate
    it across N names into v2 packs (512/chunk), stamp generation 1,
    and keep one long-lived ModelCollection serving it.  Each delta
    cycle ``delta_write``s a contiguous builder-chunk-shaped range of k
    machines (k=32 → a 1-pack slice, k=512 → a whole pack), then times
    ``maybe_delta_reload`` + a block on the stacked device params — the
    moment scoring sees the new generation.  Full-restart baseline is
    ``ModelCollection.from_directory`` + fleet-scorer + block over the
    same dir, interleaved best-of-2 with the delta cycles so shared-CPU
    drift lands on both sides.  Gates: delta@32 ≤ 0.1× full restart;
    zero ``gordo_compile_cache_misses_total`` growth across every
    reload (stable bucket shapes compile nothing); scoring p99 measured
    concurrently DURING reload cycles within 1.25× steady state; and
    post-flip scoring byte-identical to a cold load of the final
    generation.  Device transfers per delta are attested from the
    telemetry counter (exactly one per touched pack).
    """
    import pickle
    import threading

    import jax

    from gordo_tpu import artifacts, telemetry
    from gordo_tpu.serve.server import ModelCollection

    model, metadata = _build_serving_model()
    chunk = 512
    n = int(os.environ.get("BENCH_ARTIFACT_MACHINES", "10000"))
    names = [f"hr-{i:05d}" for i in range(n)]
    d = tempfile.mkdtemp(prefix="gordo-bench-hotreload-")

    def counter(name: str) -> float:
        metric = telemetry.REGISTRY.snapshot()["metrics"].get(name) or {}
        return float(sum(metric.get("series", {}).values()))

    try:
        t0 = time.perf_counter()
        for start in range(0, n, chunk):
            part = names[start: start + chunk]
            metas = []
            for nm in part:
                md = dict(metadata)
                md["name"] = nm
                metas.append(md)
            artifacts.write_pack(d, part, [model] * len(part), metas)
        gen = artifacts.stamp_generation(d)
        out["hot_reload_write_s"] = round(time.perf_counter() - t0, 3)
        out["hot_reload_machines"] = n
        log(f"hot_reload: wrote {n} machines as v2 gen {gen} in "
            f"{out['hot_reload_write_s']}s")

        def time_to_ready() -> float:
            t0 = time.perf_counter()
            coll = ModelCollection.from_directory(d, project="bench")
            fleet = coll.fleet_scorer
            for bucket in fleet.buckets:
                jax.block_until_ready(jax.tree.leaves(bucket.params))
            return time.perf_counter() - t0

        # the long-lived serving collection every delta cycle reloads
        serving = ModelCollection.from_directory(d, project="bench")
        for bucket in serving.fleet_scorer.buckets:
            jax.block_until_ready(jax.tree.leaves(bucket.params))

        # scoring subset spanning changed and unchanged machines; warm
        # the program so the compile-miss window below is pure reload
        rng = np.random.default_rng(0)
        X = rng.standard_normal((512, N_TAGS)).astype(np.float32)
        sub_names = sorted({names[i] for i in (
            0, min(33, n - 1), min(chunk * 3, n - 1), n // 2, n - 1,
        )})
        sub = {nm: X for nm in sub_names}
        serving.fleet_scorer.score_all(sub)
        # the p99 probe request: a whole-fleet bulk sweep — this tier's
        # canonical workload — warmed here so the compile-miss window
        # below spans only reloads
        bulk = {nm: X for nm in names}
        serving.fleet_scorer.score_all(bulk)

        variant = pickle.loads(pickle.dumps(model))
        tick = [1000.0]

        def write_delta(k: int, lo: int) -> "list[str]":
            """Builder-side half: delta_write names[lo:lo+k] as a new
            generation.  On a real fleet this runs on the builder, not
            the serving replica — it never counts as reload time."""
            tick[0] += 1.0
            if hasattr(variant, "aggregate_threshold_"):
                variant.aggregate_threshold_ = tick[0]
            changed = names[lo: lo + k]
            artifacts.delta_write(d, {nm: variant for nm in changed})
            return changed

        def reload_timed(changed: "list[str]") -> "tuple[float, float]":
            """Serving-side half: the reload-to-ready window (wall
            start/end) for the generation just published."""
            t0 = time.perf_counter()
            changes = serving.maybe_delta_reload()
            fleet = serving.fleet_scorer
            for bucket in fleet.buckets:
                jax.block_until_ready(jax.tree.leaves(bucket.params))
            t1 = time.perf_counter()
            if sorted(changes["reloaded"]) != sorted(changed):
                raise RuntimeError(
                    f"reload touched {len(changes['reloaded'])} machines, "
                    f"expected {len(changed)}"
                )
            return t0, t1

        def delta_cycle(k: int, lo: int) -> float:
            t0, t1 = reload_timed(write_delta(k, lo))
            return t1 - t0

        misses0 = counter("gordo_compile_cache_misses_total")

        # interleaved best-of-2: restart, delta@32, restart, delta@32 —
        # then delta@512 twice (a whole pack each, different pack per
        # cycle so neither side rides the other's page cache)
        k_small = min(32, n)
        k_big = min(chunk, n)
        lo_a = chunk * 3 if n >= chunk * 4 else 0
        lo_b = chunk * 4 if n >= chunk * 5 else lo_a
        full_1 = time_to_ready()
        dputs0 = artifacts.device_put_count()
        delta32_1 = delta_cycle(k_small, 0)
        dputs_32 = artifacts.device_put_count() - dputs0
        full_2 = time_to_ready()
        delta32_2 = delta_cycle(k_small, 0)
        delta512_1 = delta_cycle(k_big, lo_a)
        delta512_2 = delta_cycle(k_big, lo_b)

        # p99 while reloads are actually in flight.  The probe request
        # is the whole-fleet sweep from a worker thread — the steady
        # baseline uses the SAME thread structure with the main thread
        # idle, and only samples whose wall interval overlaps a
        # reload-to-ready window count as "during reload".  delta_write
        # runs on the builder on a real fleet, so each cycle lets the
        # request that overlapped the write drain before the reload
        # starts — reload windows measure pure serving-side sharing.
        samples: "list[tuple[float, float]]" = []
        stop = threading.Event()

        def score_loop() -> None:
            while not stop.is_set():
                t0 = time.perf_counter()
                serving.fleet_scorer.score_all(bulk)
                samples.append((t0, time.perf_counter()))

        th = threading.Thread(target=score_loop, daemon=True)
        th.start()
        t_end = time.perf_counter() + 45.0
        while len(samples) < 13 and time.perf_counter() < t_end:
            time.sleep(0.05)
        # first sample is the conventional warm-in discard
        lat_steady = (
            [t1 - t0 for t0, t1 in samples[1:]]
            or [t1 - t0 for t0, t1 in samples]
        )

        mark = max(0, len(samples) - 1)
        windows: "list[tuple[float, float]]" = []
        lo_load = 64 if n >= 96 else 0
        t_end = time.perf_counter() + 120.0
        while len(windows) < 12 and time.perf_counter() < t_end:
            changed = write_delta(k_small, lo_load)
            settle = len(samples) + 1
            while len(samples) < settle and time.perf_counter() < t_end:
                time.sleep(0.01)
            windows.append(reload_timed(changed))
        stop.set()
        th.join(timeout=60)
        reload_cycles = len(windows)
        lat_reload = [
            t1 - t0 for t0, t1 in samples[mark:]
            if any(t0 < w1 and w0 < t1 for w0, w1 in windows)
        ] or lat_steady
        serving.fleet_scorer.score_all(sub)  # post-flip dispatch counted
        misses_delta = (
            counter("gordo_compile_cache_misses_total") - misses0
        )

        full = min(full_1, full_2)
        d32 = min(delta32_1, delta32_2)
        d512 = min(delta512_1, delta512_2)
        p99_s = float(np.percentile(lat_steady, 99)) * 1e3
        p99_r = float(np.percentile(lat_reload, 99)) * 1e3

        out["hot_reload_full_restart_s"] = round(full, 3)
        out["hot_reload_delta_s_32"] = round(d32, 3)
        out["hot_reload_delta_s_512"] = round(d512, 3)
        out["hot_reload_ratio_32"] = round(d32 / full, 4)
        out["hot_reload_ratio_512"] = round(d512 / full, 4)
        out["hot_reload_ratio_32_ok"] = d32 / full <= 0.1
        out["hot_reload_device_puts_32"] = dputs_32
        out["hot_reload_one_put_per_touched_pack"] = dputs_32 == 1.0
        out["hot_reload_compile_misses_delta"] = misses_delta
        out["hot_reload_zero_compile_ok"] = misses_delta == 0.0
        out["hot_reload_cycles_under_load"] = reload_cycles
        out["hot_reload_p99_samples_steady"] = len(lat_steady)
        out["hot_reload_p99_samples_reload"] = len(lat_reload)
        out["hot_reload_p50_steady_ms"] = round(
            float(np.percentile(lat_steady, 50)) * 1e3, 2
        )
        out["hot_reload_p50_reload_ms"] = round(
            float(np.percentile(lat_reload, 50)) * 1e3, 2
        )
        out["hot_reload_p99_steady_ms"] = round(p99_s, 2)
        out["hot_reload_p99_reload_ms"] = round(p99_r, 2)
        out["hot_reload_p99_ratio"] = round(p99_r / p99_s, 3)
        out["hot_reload_p99_ok"] = p99_r <= 1.25 * p99_s
        out["hot_reload_generation"] = serving.generation
        log(f"hot_reload: restart {full:.2f}s vs delta@32 {d32:.3f}s "
            f"({d32 / full:.3f}x) / delta@512 {d512:.3f}s; "
            f"compile misses +{misses_delta:.0f}; p99 steady {p99_s:.1f}ms "
            f"vs during-reload {p99_r:.1f}ms")

        # byte-identity: the delta-reloaded scorer must match a cold
        # load of the final generation exactly
        cold = ModelCollection.from_directory(d, project="bench")
        hot_o = serving.fleet_scorer.score_all(sub)
        cold_o = cold.fleet_scorer.score_all(sub)
        identical = all(
            np.asarray(hot_o[nm][k]).tobytes()
            == np.asarray(cold_o[nm][k]).tobytes()
            for nm in hot_o for k in hot_o[nm]
        )
        out["hot_reload_byte_identical_to_cold_load"] = identical
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_serving_sharded(out: dict) -> None:
    """ISSUE 8 acceptance: the horizontal serving tier — N forked scoring
    replicas (REAL server processes, the multihost_dryrun pattern), each
    loading only its shard of a shared v2 pack dir, driven closed-loop at
    64-way concurrency with client-side machine-affinity routing.

    Protocol (docs/perf.md "Sharded serving"):

    - one trained machine replicated across 64 names, packed v2 in
      8-machine chunks so shard boundaries align with pack boundaries at
      N=2 and N=4;
    - baseline: ONE unsharded server process; sharded: N=2 and N=4
      replica processes (``gordo run-server --shard i/N`` equivalents),
      requests routed to owners via ``serve.shard.ShardRouter``;
    - aggregate throughput + p50/p99 per topology after a full warmup
      round (per-request latencies from submission, 64 in flight);
    - byte parity: the 2-replica scatter-gather of one bulk round must
      equal the single process's response arrays EXACTLY;
    - per-replica time-to-ready at 10k machines: a fresh process loading
      shard 0/4 vs a fresh process loading everything (the 1/N gate —
      each replica touches only its own packs' skeletons/transfers).

    Honesty note: this container exposes ONE CPU core, so N replica
    processes timeshare it — aggregate throughput CANNOT show the real
    N-way win here (the processes are compute-serialized), exactly like
    the TPU numbers banked behind the absent tunnel.  The fields gate
    what 1 core can prove (routing correctness, parity, 1/N ready); the
    throughput ratios are recorded with ``cpu_cores`` alongside.
    """
    import asyncio
    import socket
    import urllib.request

    import aiohttp

    from gordo_tpu import artifacts
    from gordo_tpu.serve import codec
    from gordo_tpu.serve.shard import ShardRouter, shard_slices

    n_machines = int(os.environ.get("BENCH_SHARDED_MACHINES", "64"))
    rows = int(os.environ.get("BENCH_SHARDED_ROWS", "512"))
    rounds = int(os.environ.get("BENCH_SHARDED_ROUNDS", "6"))
    concurrency = 64
    out["cpu_cores"] = os.cpu_count()
    if os.cpu_count() == 1:
        out["sharded_single_core_serialized"] = (
            "1 visible core: replica processes timeshare it, so the "
            "aggregate-throughput axis cannot exceed ~1x here; the "
            "multi-core/TPU win is banked, like the tunnel numbers"
        )

    model, metadata = _build_serving_model()
    names = [f"sm-{i:03d}" for i in range(n_machines)]
    art_dir = tempfile.mkdtemp(prefix="gordo-bench-sharded-")
    procs: "list[subprocess.Popen]" = []
    logs: "list[str]" = []

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def spawn(port: int, shard: "str | None") -> subprocess.Popen:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("GORDO_SERVE_SHARD", None)
        env["JAX_PLATFORMS"] = "cpu"
        args = [
            sys.executable, "-m", "gordo_tpu.cli.cli", "run-server",
            "--model-dir", art_dir, "--project", "bench",
            "--host", "127.0.0.1", "--port", str(port),
            "--rescan-interval", "0",
        ]
        if shard:
            args += ["--shard", shard]
        log_path = os.path.join(art_dir, f"server-{port}.log")
        logs.append(log_path)
        proc = subprocess.Popen(
            args, env=env,
            stdout=open(log_path, "w"), stderr=subprocess.STDOUT,
        )
        procs.append(proc)
        return proc

    def wait_ready(port: int, timeout_s: float = 120.0) -> None:
        deadline = time.monotonic() + timeout_s
        url = f"http://127.0.0.1:{port}/healthz"
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    if resp.status == 200:
                        return
            except Exception:
                time.sleep(0.25)
        raise RuntimeError(f"replica on :{port} never became ready")

    def stop(to_stop: "list[subprocess.Popen]") -> None:
        for proc in to_stop:
            proc.terminate()
        for proc in to_stop:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    headers = {
        "Content-Type": codec.MSGPACK_CONTENT_TYPE,
        "Accept": codec.MSGPACK_CONTENT_TYPE,
    }

    async def drive(urls_by_machine: "dict[str, str]") -> dict:
        """Closed-loop single-machine anomaly rounds, 64 in flight across
        the whole tier, each request routed to its owner replica."""
        rng = np.random.default_rng(0)
        bodies = {
            name: codec.packb(
                {"X": rng.standard_normal((rows, N_TAGS)).astype(np.float32)}
            )
            for name in names
        }
        latencies: "list[float]" = []
        timeout = aiohttp.ClientTimeout(total=300)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            sem = asyncio.Semaphore(concurrency)

            async def post(name: str, measured: bool) -> None:
                url = (
                    f"{urls_by_machine[name]}/gordo/v0/bench/{name}"
                    "/anomaly/prediction"
                )
                async with sem:
                    t0 = time.perf_counter()
                    async with session.post(
                        url, data=bodies[name], headers=headers
                    ) as resp:
                        raw = await resp.read()
                    if resp.status != 200:
                        raise RuntimeError(
                            f"{name} -> {resp.status}: {raw[:160]!r}"
                        )
                if measured:
                    latencies.append(time.perf_counter() - t0)

            # warmup round: per-process compiles land outside the timing
            await asyncio.gather(*(post(n, False) for n in names))
            t0 = time.perf_counter()
            await asyncio.gather(*(
                post(n, True) for _ in range(rounds) for n in names
            ))
            dt = time.perf_counter() - t0
        n_req = rounds * len(names)
        p50, p99 = np.percentile(latencies, [50, 99])
        return {
            "samples_per_sec": n_req * rows * N_TAGS / dt,
            "requests_per_sec": n_req / dt,
            "p50_ms": float(p50 * 1e3),
            "p99_ms": float(p99 * 1e3),
        }

    async def bulk_scatter(
        urls: "list[str]", X_by: "dict[str, np.ndarray]"
    ) -> dict:
        """One bulk round, scatter-gathered across ``urls`` with the
        shared shard function, reassembled in machine order."""
        router = ShardRouter(names, urls)
        plan = router.split(X_by)
        timeout = aiohttp.ClientTimeout(total=300)
        async with aiohttp.ClientSession(timeout=timeout) as session:

            async def post(base: str, members: "list[str]") -> dict:
                async with session.post(
                    f"{base}/gordo/v0/bench/_bulk/anomaly/prediction",
                    data=codec.packb({"X": {m: X_by[m] for m in members}}),
                    headers=headers,
                ) as resp:
                    raw = await resp.read()
                if resp.status != 200:
                    raise RuntimeError(f"bulk {base} -> {resp.status}")
                return codec.unpackb(raw)["data"]

            parts = await asyncio.gather(
                *(post(b, ms) for b, ms in plan.items())
            )
        gathered: dict = {}
        for part in parts:
            gathered.update(part)
        return {m: gathered[m] for m in X_by}

    try:
        # ---- shared v2 artifact dir: 8-machine packs (shard-aligned) ----
        chunk = max(1, n_machines // 8)
        for start in range(0, n_machines, chunk):
            part = names[start: start + chunk]
            metas = []
            for name in part:
                md = dict(metadata)
                md["name"] = name
                metas.append(md)
            artifacts.write_pack(art_dir, part, [model] * len(part), metas)
        log(f"sharded: {n_machines} machines in "
            f"{-(-n_machines // chunk)} packs under {art_dir}")

        # ---- baseline: one unsharded process ----
        base_port = free_port()
        base_proc = spawn(base_port, None)
        wait_ready(base_port)
        base_url = f"http://127.0.0.1:{base_port}"
        baseline = asyncio.run(drive({n: base_url for n in names}))
        out["sharded_baseline_samples_per_sec"] = round(
            baseline["samples_per_sec"]
        )
        out["sharded_baseline_p50_ms"] = round(baseline["p50_ms"], 2)
        out["sharded_baseline_p99_ms"] = round(baseline["p99_ms"], 2)
        log(f"sharded baseline (1 proc): "
            f"{baseline['samples_per_sec']:,.0f} samples/s, "
            f"p50 {baseline['p50_ms']:.0f}ms p99 {baseline['p99_ms']:.0f}ms")

        rng = np.random.default_rng(11)
        X_parity = {
            n: rng.standard_normal((rows, N_TAGS)).astype(np.float32)
            for n in names
        }
        single_bulk = asyncio.run(bulk_scatter([base_url], X_parity))

        for n_replicas in (2, 4):
            ports = [free_port() for _ in range(n_replicas)]
            replica_procs = [
                spawn(port, f"{i}/{n_replicas}")
                for i, port in enumerate(ports)
            ]
            for port in ports:
                wait_ready(port)
            urls = [f"http://127.0.0.1:{p}" for p in ports]
            slices = shard_slices(names, n_replicas)
            url_of = {
                name: urls[i]
                for i, shard in enumerate(slices) for name in shard
            }
            res = asyncio.run(drive(url_of))
            key = f"sharded_{n_replicas}rep"
            out[f"{key}_samples_per_sec"] = round(res["samples_per_sec"])
            out[f"{key}_p50_ms"] = round(res["p50_ms"], 2)
            out[f"{key}_p99_ms"] = round(res["p99_ms"], 2)
            speedup = res["samples_per_sec"] / baseline["samples_per_sec"]
            out[f"sharded_speedup_{n_replicas}"] = round(speedup, 3)
            log(f"sharded {n_replicas} replicas: "
                f"{res['samples_per_sec']:,.0f} samples/s "
                f"({speedup:.2f}x baseline), p50 {res['p50_ms']:.0f}ms "
                f"p99 {res['p99_ms']:.0f}ms")

            if n_replicas == 2:
                sharded_bulk = asyncio.run(bulk_scatter(urls, X_parity))
                parity = list(sharded_bulk) == list(single_bulk) and all(
                    (
                        np.array_equal(sharded_bulk[m][k], v)
                        and getattr(sharded_bulk[m][k], "dtype", None)
                        == getattr(v, "dtype", None)
                    )
                    if isinstance(v, np.ndarray)
                    else sharded_bulk[m][k] == v
                    for m in single_bulk
                    for k, v in single_bulk[m].items()
                )
                out["sharded_parity_ok"] = bool(parity)
                out["sharded_parity_machines"] = len(single_bulk)
                log(f"sharded 2-replica scatter-gather byte parity: "
                    f"{'OK' if parity else 'FAILED'} "
                    f"({len(single_bulk)} machines)")
            stop(replica_procs)
        # the 2x gate the multi-core deployment meets; recorded honestly
        # either way (see cpu_cores / sharded_single_core_serialized)
        out["sharded_2x_ge_1p6_ok"] = out["sharded_speedup_2"] >= 1.6
        stop([base_proc])

        # ---- per-replica time-to-ready at 10k machines ----
        n_large = int(os.environ.get("BENCH_SHARDED_READY_MACHINES", "10000"))
        ready_shards = 4
        big_dir = tempfile.mkdtemp(prefix="gordo-bench-sharded-10k-")
        try:
            big_names = [f"bm-{i:05d}" for i in range(n_large)]
            t0 = time.perf_counter()
            for start in range(0, n_large, 512):
                part = big_names[start: start + 512]
                metas = []
                for name in part:
                    md = dict(metadata)
                    md["name"] = name
                    metas.append(md)
                artifacts.write_pack(
                    big_dir, part, [model] * len(part), metas
                )
            log(f"sharded: {n_large}-machine v2 dir written in "
                f"{time.perf_counter() - t0:.1f}s")

            ready_script = (
                "import json, sys, time\n"
                "import jax\n"
                "from gordo_tpu.serve.server import ModelCollection\n"
                "from gordo_tpu.serve.shard import ShardSpec\n"
                "d, spec = sys.argv[1], sys.argv[2]\n"
                "shard = None if spec == '-' else ShardSpec.parse(spec)\n"
                "t0 = time.perf_counter()\n"
                "coll = ModelCollection.from_directory("
                "d, project='bench', shard=shard)\n"
                "fleet = coll.fleet_scorer\n"
                "for b in fleet.buckets:\n"
                "    jax.block_until_ready(jax.tree.leaves(b.params))\n"
                "print(json.dumps({'ready_s': time.perf_counter() - t0,"
                " 'machines': len(coll.entries)}))\n"
            )

            def ready_child(spec: str) -> dict:
                env = dict(os.environ)
                env.pop("PALLAS_AXON_POOL_IPS", None)
                env.pop("GORDO_SERVE_SHARD", None)
                env["JAX_PLATFORMS"] = "cpu"
                res = subprocess.run(
                    [sys.executable, "-c", ready_script, big_dir, spec],
                    env=env, stdout=subprocess.PIPE, text=True,
                    timeout=600,
                )
                if res.returncode != 0:
                    raise RuntimeError(
                        f"ready child {spec} rc={res.returncode}"
                    )
                return json.loads(res.stdout.strip().splitlines()[-1])

            # min-of-2 per point (page-cache / shared-CPU noise lands on
            # both sides); shard 1 runs once to show a mid-fleet shard
            # (TWO pack-boundary slices) costs the same shape
            full_s = min(ready_child("-")["ready_s"] for _ in range(2))
            shard0_s = min(
                ready_child(f"0/{ready_shards}")["ready_s"]
                for _ in range(2)
            )
            shard1_s = ready_child(f"1/{ready_shards}")["ready_s"]
            fraction = shard0_s / full_s
            out[f"sharded_ready_full_{n_large}_s"] = round(full_s, 3)
            out[f"sharded_ready_shard_{n_large}_s"] = round(shard0_s, 3)
            out[f"sharded_ready_shard1_{n_large}_s"] = round(shard1_s, 3)
            out["sharded_ready_shards"] = ready_shards
            out["sharded_ready_fraction"] = round(fraction, 3)
            # strict same-run gate: shard <= full/N.  The fixed cost both
            # loads share (store open + discover over the FULL index) is
            # a few % of full, so this sits within noise of exactly 1/N.
            out["sharded_ready_1_over_n_ok"] = (
                fraction <= 1.0 / ready_shards
            )
            # the ISSUE reference point: 1/N of the single-process v2
            # number from BENCH_r11 (37.9s v1 -> 6.0s v2 at 10k, CPU)
            out["sharded_ready_vs_r11_6s_ok"] = (
                n_large != 10000 or shard0_s <= 6.0 / ready_shards
            )
            log(f"sharded time-to-ready @{n_large}: full {full_s:.2f}s vs "
                f"shard 0/{ready_shards} {shard0_s:.2f}s / shard 1 "
                f"{shard1_s:.2f}s ({fraction:.3f} of full; gate <= "
                f"{1.0 / ready_shards:.2f}; r11 ref 6.0s/N)")
        finally:
            shutil.rmtree(big_dir, ignore_errors=True)
    except Exception:
        for log_path in logs:
            try:
                with open(log_path) as fh:
                    tail = fh.read()[-2000:]
                if tail:
                    log(f"--- {log_path} tail ---\n{tail}")
            except OSError:
                pass
        raise
    finally:
        stop(procs)
        shutil.rmtree(art_dir, ignore_errors=True)


def bench_cold_start(out: dict) -> None:
    """ISSUE 5 acceptance: cold-start elimination, measured end to end.

    Protocol (docs/perf.md "Cold start"): build a small project once
    (artifacts + warmup manifest on disk), then fork FRESH processes —
    the quantity under test only exists in a process with empty compile
    caches — via ``python -m gordo_tpu.compile.coldstart``:

    - ``cold`` × K: no warmup; the first request eats the compile.
    - ``warm`` × K: manifest-driven AOT warmup first; the first request
      pays dispatch only.  p99 over the K per-process first requests
      (each process contributes exactly one first request).
    - cached restart: two ``warm`` runs sharing a persistent compile
      cache (``GORDO_COMPILE_CACHE=force`` + a scratch
      ``GORDO_COMPILE_CACHE_DIR`` — force because this container's CPU
      backend is excluded by default; back-to-back runs on one machine
      are the trusted single-machine case the override exists for).
      Run 1 populates, run 2 must go ready measurably faster, with the
      ``gordo_compile_cache_hits_total{cache="persistent"}`` counters
      from run 2's exposition attested into the result doc.

    Gates: warmed first-request p99 at least 5x below unwarmed, and
    cached-restart time-to-ready below the uncached one.
    """
    from gordo_tpu.builder.fleet_build import build_project

    trials = int(os.environ.get("BENCH_COLD_TRIALS", "5"))
    rows = 256
    art_dir = tempfile.mkdtemp(prefix="gordo-bench-cold-art-")
    cache_dir = tempfile.mkdtemp(prefix="gordo-bench-cold-cache-")

    def child(mode: str, env_extra: dict) -> dict:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("GORDO_COMPILE_CACHE_DIR", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.update(env_extra)
        res = subprocess.run(
            [sys.executable, "-m", "gordo_tpu.compile.coldstart",
             "--artifacts", art_dir, "--mode", mode, "--rows", str(rows)],
            env=env, stdout=subprocess.PIPE, text=True, timeout=300,
        )
        line = (res.stdout or "").strip().splitlines()
        doc = json.loads(line[-1]) if line else {}
        if res.returncode != 0 or "error" in doc:
            raise RuntimeError(
                f"cold-start child {mode} rc={res.returncode}: "
                f"{doc.get('error', 'no output')}"
            )
        return doc

    try:
        machines = make_machines(8, n_tags=4, prefix="bench-cold")
        result = build_project(machines, art_dir)
        if result.failed:
            raise RuntimeError(f"cold-start build failed: {result.failed}")

        no_disk = {"GORDO_COMPILE_CACHE": "0"}
        cold_runs = [child("cold", no_disk) for _ in range(trials)]
        warm_runs = [child("warm", no_disk) for _ in range(trials)]
        cold_p99 = float(np.percentile(
            [r["first_request_s"] for r in cold_runs], 99
        ))
        warm_p99 = float(np.percentile(
            [r["first_request_s"] for r in warm_runs], 99
        ))
        out["cold_start_trials"] = trials
        out["cold_start_unwarmed_first_request_p99_ms"] = round(
            cold_p99 * 1e3, 2
        )
        out["cold_start_warmed_first_request_p99_ms"] = round(
            warm_p99 * 1e3, 2
        )
        out["cold_start_first_request_speedup"] = round(
            cold_p99 / max(warm_p99, 1e-9), 2
        )
        out["cold_start_warmed_5x_ok"] = cold_p99 >= 5.0 * warm_p99
        log(f"cold_start first request: unwarmed p99 {cold_p99 * 1e3:.0f}ms "
            f"vs warmed p99 {warm_p99 * 1e3:.0f}ms "
            f"({cold_p99 / max(warm_p99, 1e-9):.1f}x)")

        # cached restart: populate the persistent cache, then restart.
        # min-compile-time 0: the bench's deliberately small programs
        # must exercise the disk round-trip the fleet's multi-second
        # programs get by default.
        disk = {"GORDO_COMPILE_CACHE": "force",
                "GORDO_COMPILE_CACHE_DIR": cache_dir,
                "GORDO_COMPILE_CACHE_MIN_SECONDS": "0"}
        populate = child("warm", disk)
        restart = child("warm", disk)
        out["cold_start_time_to_ready_uncached_s"] = populate[
            "time_to_ready_s"
        ]
        out["cold_start_time_to_ready_cached_s"] = restart["time_to_ready_s"]
        out["cold_start_cached_restart_ok"] = (
            restart["time_to_ready_s"] < populate["time_to_ready_s"]
        )
        hits = [
            line for line in restart.get("compile_metrics", ())
            if 'cache="persistent"' in line and "hits" in line
        ]
        out["cold_start_cache_hit_metrics"] = hits
        out["cold_start_metrics_scrape"] = restart.get("compile_metrics")
        log(f"cold_start time-to-ready: uncached "
            f"{populate['time_to_ready_s']:.2f}s vs cached restart "
            f"{restart['time_to_ready_s']:.2f}s; persistent hits: {hits}")
    finally:
        shutil.rmtree(art_dir, ignore_errors=True)
        shutil.rmtree(cache_dir, ignore_errors=True)


def _sha256_tree(*parts) -> str:
    """Stable fp-byte digest over arrays / pytrees of arrays — the
    byte-parity witness the multi_device children compare against the
    single-device pinned run."""
    import hashlib

    import jax

    h = hashlib.sha256()
    for part in parts:
        for leaf in jax.tree_util.tree_leaves(part):
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def _attest_placement(tree) -> dict:
    """Per-device placement attestation: where the first device array in
    ``tree`` actually lives (``addressable_shards``), not where the mesh
    said it should."""
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and leaf.ndim >= 1:
            shards = leaf.addressable_shards
            return {
                "n_shards": len(shards),
                "device_ids": sorted(s.device.id for s in shards),
                "shard_shape": list(shards[0].data.shape),
            }
    return {"n_shards": 0, "device_ids": [], "shard_shape": []}


def scaleout_child_main(argv: "list[str]") -> None:
    """Forked measurement half of :func:`bench_multi_device`: this
    process was spawned with ``XLA_FLAGS=--xla_force_host_platform_
    device_count=N`` already in its environment (device topology is
    fixed at backend init, so the quantity under test only exists in a
    fresh process — the cold_start pattern).

    r22: the real placement plane end to end, in process.  Resolves a
    :class:`~gordo_tpu.mesh.FleetMesh` over every forced device, runs a
    sharded fleet FIT and a sharded fleet SCORING round, and prints one
    JSON line carrying (a) steady-state throughput for both, (b) sha256
    fp32 digests of the fit result and the score outputs — the parent
    compares them across device counts for byte parity against the
    single-device run, (c) ``addressable_shards`` attestation that
    params and stacked scoring buffers really landed one block per
    device, and (d) the compile-registry executable count per phase —
    exactly ONE sharded executable per bucket, stable across rounds."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, required=True)
    p.add_argument("--machines", type=int, default=32)
    p.add_argument("--rows", type=int, default=1024)
    p.add_argument("--rounds", type=int, default=8)
    a = p.parse_args(argv)
    try:
        import jax

        from gordo_tpu.compile import REGISTRY
        from gordo_tpu.mesh import FleetMesh
        from gordo_tpu.parallel.fleet import fleet_fit
        from gordo_tpu.serve.fleet_scorer import FleetScorer
        from gordo_tpu.train.fit import TrainConfig

        devices = jax.devices()
        if len(devices) != a.devices:
            raise RuntimeError(
                f"forced {a.devices} host devices, backend exposes "
                f"{len(devices)}"
            )
        fm = FleetMesh.resolve()  # all forced devices on the fleet axis
        doc: dict = {
            "devices": fm.n_devices,
            "model_shards": fm.n_model_shards,
            "machines": a.machines,
            "rows": a.rows,
            "rounds": a.rounds,
        }

        # -- sharded fleet fit -------------------------------------------
        from gordo_tpu.registry import lookup_factory

        n_feat = 4
        module = lookup_factory("AutoEncoder", "feedforward_hourglass")(
            n_features=n_feat, n_features_out=n_feat
        )
        rng = np.random.default_rng(7)
        Xf = rng.standard_normal(
            (a.machines, 256, n_feat)
        ).astype(np.float32)
        wf = np.ones((a.machines, 256), np.float32)
        cfg = TrainConfig(epochs=2, batch_size=128)
        seeds = np.arange(a.machines, dtype=np.uint32)
        exe0 = REGISTRY.n_executables()
        t0 = time.perf_counter()
        fit_res = fleet_fit(
            module, Xf, Xf, wf, cfg, seeds=seeds, mesh=fm.mesh
        )
        fit_res.collect()
        doc["fit_cold_seconds"] = round(time.perf_counter() - t0, 4)
        doc["fit_executables"] = REGISTRY.n_executables() - exe0
        t0 = time.perf_counter()
        warm = fleet_fit(
            module, Xf, Xf, wf, cfg, seeds=seeds, mesh=fm.mesh
        )
        warm.collect()
        doc["fit_seconds"] = round(time.perf_counter() - t0, 4)
        doc["fit_digest"] = _sha256_tree(
            fit_res.history, fit_res.unstack_params()
        )
        doc["fit_placement"] = _attest_placement(fit_res.params)

        # -- sharded fleet scoring ---------------------------------------
        model, _metadata = _build_serving_model()
        names = [f"md-{i:03d}" for i in range(a.machines)]
        scorer = FleetScorer.from_models(
            {n: model for n in names}, mesh=fm.mesh
        )
        rng = np.random.default_rng(11)
        X_by = {
            n: rng.standard_normal((a.rows, N_TAGS)).astype(np.float32)
            for n in names
        }
        exe0 = REGISTRY.n_executables()
        first = scorer.score_all(X_by)  # compile + first transfers
        exe_after_compile = REGISTRY.n_executables() - exe0
        scorer.score_all(X_by)  # steady state
        t0 = time.perf_counter()
        for _ in range(a.rounds):
            out_scores = scorer.score_all(X_by)
        dt = time.perf_counter() - t0
        samples = a.rounds * a.machines * a.rows * N_TAGS
        doc["score_digest"] = _sha256_tree(
            [out_scores[n] for n in names]
        )
        doc["n_buckets"] = len(scorer.buckets)
        doc["score_executables"] = exe_after_compile
        # one sharded executable per bucket, and NO recompiles once warm
        doc["one_executable_per_bucket_ok"] = (
            exe_after_compile == len(scorer.buckets)
            and REGISTRY.n_executables() - exe0 == exe_after_compile
        )
        doc["score_placement"] = _attest_placement(
            vars(scorer.buckets[0])
        )
        del first
        doc.update({
            "n_stacked": scorer.n_stacked,
            "seconds": round(dt, 4),
            "samples_per_sec": round(samples / dt) if dt > 0 else None,
        })
        print(json.dumps(doc), flush=True)
    except Exception as exc:  # one diagnostic line, never a dead rc
        print(
            json.dumps({"error": f"{type(exc).__name__}: {exc}"}),
            flush=True,
        )
        raise SystemExit(1)
    raise SystemExit(0)


def bench_multi_device(out: dict) -> None:
    """ISSUE 18 tentpole: the placement plane end to end over REAL XLA
    device counts — forked children swept over
    ``--xla_force_host_platform_device_count`` in {1,2,4,8}
    (:func:`scaleout_child_main`), each running an in-process SHARDED
    fleet fit + fleet scoring through :class:`gordo_tpu.mesh.FleetMesh`.

    Beyond the throughput curve (and the r13 replica-scaling gate,
    >=1.6x aggregate at 2), the parent now verifies the correctness
    claims: every sharded child's fit and score sha256 digests must be
    BYTE-IDENTICAL to the 1-device child's (fp32; per-device blocks >= 2
    models — see tests/test_mesh.py for the block-1 ULP caveat), each
    child attests per-device placement via ``addressable_shards``, and
    each confirms exactly one sharded executable per bucket with no
    steady-state recompiles.

    Honesty note stands when the host exposes fewer cores than devices:
    forced host-platform devices timeshare the physical cores, so a flat
    curve there bounds sharding/scheduling overhead rather than
    disproving the multi-chip win.
    """
    counts = [
        int(x) for x in
        os.environ.get("BENCH_MULTI_DEVICE_COUNTS", "1,2,4,8").split(",")
    ]
    machines = int(os.environ.get("BENCH_MULTI_DEVICE_MACHINES", "32"))
    rows = int(os.environ.get("BENCH_MULTI_DEVICE_ROWS", "1024"))
    rounds = int(os.environ.get("BENCH_MULTI_DEVICE_ROUNDS", "8"))
    cores = os.cpu_count()
    out["cpu_cores"] = cores

    def child(n_dev: int) -> dict:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_dev}")
        env["XLA_FLAGS"] = " ".join(flags)
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--scaleout-child", "--devices", str(n_dev),
             "--machines", str(machines), "--rows", str(rows),
             "--rounds", str(rounds)],
            env=env, stdout=subprocess.PIPE, text=True, timeout=420,
        )
        lines = (res.stdout or "").strip().splitlines()
        doc = json.loads(lines[-1]) if lines else {}
        if res.returncode != 0 or "error" in doc:
            raise RuntimeError(
                f"scaleout child @{n_dev} rc={res.returncode}: "
                f"{doc.get('error', 'no output')}"
            )
        return doc

    curve: dict = {}
    fit_curve: dict = {}
    docs: dict = {}
    for n_dev in counts:
        doc = child(n_dev)
        docs[str(n_dev)] = doc
        curve[str(n_dev)] = doc["samples_per_sec"]
        fit_curve[str(n_dev)] = doc.get("fit_seconds")
        log(f"multi_device @{n_dev}: {doc['samples_per_sec']:,} samples/s "
            f"({doc['n_stacked']} stacked, {doc['seconds']}s score, "
            f"{doc.get('fit_seconds')}s fit, "
            f"shards={doc.get('model_shards')})")
    out["multi_device_counts"] = counts
    out["multi_device_machines"] = machines
    out["multi_device_samples_per_sec"] = curve
    out["multi_device_fit_seconds"] = fit_curve

    # byte parity: every sharded child's fit/score digests must equal the
    # single-device child's, bit for bit (fp32)
    base_doc = docs.get("1")
    if base_doc:
        parity = {
            k: (d.get("fit_digest") == base_doc.get("fit_digest")
                and d.get("score_digest") == base_doc.get("score_digest"))
            for k, d in docs.items() if k != "1"
        }
        out["multi_device_byte_parity"] = parity
        out["multi_device_byte_parity_ok"] = all(parity.values())
        log(f"multi_device byte parity vs 1 device: {parity} -> "
            f"{'PASS' if all(parity.values()) else 'FAIL'}")
    # placement attestation + one-executable-per-bucket, per child
    out["multi_device_placement"] = {
        k: {
            "fit": d.get("fit_placement"),
            "score": d.get("score_placement"),
            "one_executable_per_bucket_ok": d.get(
                "one_executable_per_bucket_ok"
            ),
        }
        for k, d in docs.items()
    }
    placement_ok = all(
        d.get("fit_placement", {}).get("n_shards") == int(k)
        and d.get("score_placement", {}).get("n_shards") == int(k)
        and d.get("one_executable_per_bucket_ok")
        for k, d in docs.items()
        if int(k) > 1
    )
    out["multi_device_placement_ok"] = placement_ok
    log(f"multi_device placement attestation (addressable_shards == "
        f"device count, 1 executable/bucket): "
        f"{'PASS' if placement_ok else 'FAIL'}")
    base = curve.get("1")
    if base:
        speedups = {k: round(v / base, 3) for k, v in curve.items() if v}
        out["multi_device_speedup_vs_1"] = speedups
        at2 = speedups.get("2")
        if at2 is not None:
            out["multi_device_speedup_at_2"] = at2
            out["multi_device_ge_1_6x_at_2_ok"] = at2 >= 1.6
            log(f"multi_device gate: {at2:.2f}x @2 devices >= 1.6x -> "
                f"{'PASS' if at2 >= 1.6 else 'FAIL'}")
    if cores is not None and cores < max(counts):
        out["multi_device_core_note"] = (
            f"{cores} visible core(s) for up to {max(counts)} forced "
            "host devices: device programs timeshare the cores, so a "
            "flat curve bounds sharding overhead rather than disproving "
            "the multi-chip win"
        )


def _refresh_parity(out: dict, size: int, warm_dir: str, cold_dir: str,
                    subset, Xp, series: str, median_tol: float,
                    max_tol: float) -> bool:
    """Per-machine warm-vs-cold score parity for one refresh subset:
    max-normalized ``series`` error on the bf16 suite's standard-normal
    input, sampled across the subset.  Machines whose metadata attests a
    cold fallback are counted, not compared — the builder's parity gate
    already demoted them to full rebuilds."""
    from gordo_tpu import artifacts, telemetry
    from gordo_tpu.serve.server import ModelCollection

    sample = subset[::max(1, size // 16)][:16]
    store = artifacts.open_store(warm_dir)
    cold_coll = ModelCollection.from_directory(
        cold_dir, project="bench-refresh-cold"
    )
    warm_coll = ModelCollection.from_directory(
        warm_dir, project="bench-refresh-warm"
    )
    errs: "list[float]" = []
    attested = 0
    failed: "list[str]" = []
    with telemetry.FLEET_HEALTH.suspended():
        for m in sample:
            meta = store.load_metadata(m.name)
            warm_meta = meta.get("model", {}).get("warm_start", {})
            if warm_meta.get("warm") is False:
                attested += 1
                continue
            r = np.asarray(
                cold_coll.get(m.name).scorer.anomaly_arrays(Xp)[series],
                np.float32,
            )
            q = np.asarray(
                warm_coll.get(m.name).scorer.anomaly_arrays(Xp)[series],
                np.float32,
            )
            err = float(np.max(np.abs(r - q))) / max(
                float(np.max(np.abs(r))), 1e-6
            )
            errs.append(err)
            if err > max_tol:
                failed.append(m.name)
    med = float(np.median(errs)) if errs else 0.0
    worst = float(np.max(errs)) if errs else 0.0
    parity_ok = med <= median_tol and not failed
    out[f"refresh_parity_sampled_{size}"] = len(sample)
    out[f"refresh_parity_attested_fallbacks_{size}"] = attested
    out[f"refresh_parity_median_{size}"] = round(med, 4)
    out[f"refresh_parity_max_{size}"] = round(worst, 4)
    out[f"refresh_parity_failed_{size}"] = failed
    out[f"refresh_parity_ok_{size}"] = parity_ok
    log(f"refresh subset {size} parity: {series} median {med:.4f} "
        f"max {worst:.4f} over {len(errs)} machines "
        f"({attested} attested fallback(s), {len(failed)} out of bounds)")
    return parity_ok


def bench_refresh(out: dict) -> None:
    """ISSUE 13 acceptance: drift-driven incremental refresh — warm-start
    subset rebuilds make retraining O(drifted), not O(fleet).

    Protocol (docs/perf.md "Refresh"): build a BENCH_REFRESH_FLEET-machine
    project cold into one v2 store, then for each subset size in
    BENCH_REFRESH_SUBSETS (default 32 and 512) run interleaved best-of-N
    rebuilds of that subset: COLD into a fresh scratch store (full data
    assembly + full-epoch training, what a non-incremental pipeline pays
    for the same machines) vs WARM into the live store
    (``build_project(subset, warm_start=True)``: previous-generation
    params seed a reduced-epoch fit, published via delta writes).  The
    measured operating point is one warm epoch over a 24-epoch base
    (``GORDO_REFRESH_EPOCH_FRACTION=0.04``) — builds here are fully
    deterministic (cold-vs-cold score diff is exactly 0), so parity
    measures nothing but the warm refit's movement.  Gates per subset:
    warm wall-clock ≤ 0.5× cold, and ≪ the full-fleet build; per-machine
    score parity between the first warm rebuild's artifacts and a cold
    reference within the bf16-suite bounds (total-anomaly-score
    max-normalized on the suite's standard-normal input: median ≤ 3%,
    per-machine max ≤ 10%) — machines whose metadata attests a cold
    fallback are counted, not compared.  Finally one end-to-end
    drift→flip→reloaded cycle against a live serving collection: a real
    drifting score-sketch rollup lands, ``refresh_once`` selects and
    warm-rebuilds exactly that machine, and the latency until
    ``maybe_delta_reload`` has the new generation's params on device is
    reported as ``refresh_drift_to_live_s``.
    """
    import jax

    from gordo_tpu import artifacts, telemetry
    from gordo_tpu.builder.fleet_build import build_project
    from gordo_tpu.refresh.loop import RefreshConfig, refresh_once
    from gordo_tpu.serve.server import ModelCollection
    from gordo_tpu.telemetry import fleet_health as fh

    fleet_n = int(os.environ.get("BENCH_REFRESH_FLEET", "576"))
    subsets = [
        int(s) for s in
        os.environ.get("BENCH_REFRESH_SUBSETS", "32,512").split(",")
        if s.strip()
    ]
    subsets = [s for s in subsets if s <= fleet_n]
    reps = int(os.environ.get("BENCH_REFRESH_REPS", "2"))
    bucket = 64
    model = {
        "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.pipeline.Pipeline": {
                    "steps": [
                        "gordo_tpu.ops.scalers.MinMaxScaler",
                        {"gordo_tpu.models.estimator.AutoEncoder": {
                            # converged base models: warm refits (6
                            # epochs = ceil(24 * 0.25)) start near the
                            # optimum, so the parity comparison below
                            # measures publish fidelity, not leftover
                            # training noise
                            "kind": "feedforward_hourglass",
                            "epochs": 24,
                            "batch_size": 64,
                        }},
                    ],
                },
            },
        },
    }
    machines = make_machines(fleet_n, n_tags=4, model=model,
                             prefix="bench-rf")
    reg = telemetry.FLEET_HEALTH

    def counter(name: str) -> float:
        metric = telemetry.REGISTRY.snapshot()["metrics"].get(name) or {}
        return float(sum(metric.get("series", {}).values()))

    def build(mods, dest, **kw):
        t0 = time.perf_counter()
        result = build_project(
            mods, dest, max_bucket_size=bucket, artifact_format="v2", **kw
        )
        dt = time.perf_counter() - t0
        if result.failed:
            raise RuntimeError(
                f"refresh bench build failed: {dict(list(result.failed.items())[:3])}"
            )
        return result, dt

    d = tempfile.mkdtemp(prefix="gordo-bench-refresh-")
    scratch: "list[str]" = []
    saved_frac = os.environ.get("GORDO_REFRESH_EPOCH_FRACTION")
    # the measured operating point: ceil(24 * 0.04) = 1 warm epoch
    os.environ["GORDO_REFRESH_EPOCH_FRACTION"] = os.environ.get(
        "BENCH_REFRESH_EPOCH_FRACTION", "0.04"
    )
    try:
        _, full_s = build(machines, d)
        out["refresh_fleet_machines"] = fleet_n
        out["refresh_full_fleet_s"] = round(full_s, 2)
        out["refresh_full_fleet_models_per_hour"] = round(
            fleet_n / full_s * 3600.0, 1
        )
        log(f"refresh: full fleet {fleet_n} machines cold in {full_s:.1f}s "
            f"({fleet_n / full_s * 3600.0:.0f} models/h)")

        # parity input mirrors the bf16 suite (bench_serving_precision /
        # tests/test_serving_precision.py): standard-normal rows,
        # max-normalized error on the serving-facing anomaly score.
        # Builds here are deterministic (two cold builds score
        # identically), so the cold reference is exact and every diff is
        # the warm refit's movement.  Bounds: median ≤ 3%, per-machine
        # max ≤ 10%.
        parity_series = "total-anomaly-score"
        parity_median_tol, parity_max_tol = 0.03, 0.10
        Xp = np.random.default_rng(0).standard_normal((1024, 4)).astype(
            np.float32
        )
        all_ok = True
        for size in subsets:
            subset = machines[:size]
            # parity first: one cold reference build (which also
            # jit-warms the cold program), then the FIRST warm rebuild
            # over the pristine store — exactly one warm epoch of
            # movement, the steady-state refresh operating point
            cold_dir = tempfile.mkdtemp(
                prefix=f"gordo-bench-refresh-cold{size}-"
            )
            scratch.append(cold_dir)
            _, _ = build(subset, cold_dir)
            warm_result, _ = build(subset, d, warm_start=True)
            parity_ok = _refresh_parity(
                out, size, d, cold_dir, subset, Xp, parity_series,
                parity_median_tol, parity_max_tol,
            )
            # timing: interleaved best-of-N at steady state (both
            # programs are jit-warm from the parity builds above)
            cold_s: "list[float]" = []
            warm_s: "list[float]" = []
            for rep in range(reps):
                rep_dir = tempfile.mkdtemp(
                    prefix=f"gordo-bench-refresh-cold{size}-"
                )
                scratch.append(rep_dir)
                _, dt = build(subset, rep_dir)
                cold_s.append(dt)
                shutil.rmtree(rep_dir, ignore_errors=True)
                scratch.remove(rep_dir)
                warm_result, dt = build(subset, d, warm_start=True)
                warm_s.append(dt)
            cold_best, warm_best = min(cold_s), min(warm_s)
            ratio = warm_best / max(cold_best, 1e-9)
            out[f"refresh_cold_subset_s_{size}"] = round(cold_best, 2)
            out[f"refresh_warm_subset_s_{size}"] = round(warm_best, 2)
            out[f"refresh_cold_models_per_hour_{size}"] = round(
                size / cold_best * 3600.0, 1
            )
            out[f"refresh_warm_models_per_hour_{size}"] = round(
                size / warm_best * 3600.0, 1
            )
            out[f"refresh_warm_over_cold_{size}"] = round(ratio, 3)
            out[f"refresh_warm_halved_ok_{size}"] = warm_best <= 0.5 * cold_best
            out[f"refresh_warm_vs_full_fleet_{size}"] = round(
                warm_best / max(full_s, 1e-9), 3
            )
            out[f"refresh_warm_fallbacks_{size}"] = len(
                warm_result.warm_fallbacks
            )
            log(f"refresh subset {size}: cold {cold_best:.1f}s vs warm "
                f"{warm_best:.1f}s ({ratio:.2f}x, "
                f"{len(warm_result.warm_fallbacks)} fallback(s))")

            all_ok = all_ok and parity_ok and warm_best <= 0.5 * cold_best
            shutil.rmtree(cold_dir, ignore_errors=True)
            scratch.remove(cold_dir)

        # end-to-end: drifting rollup lands → refresh_once warm-rebuilds
        # exactly that machine → the live collection delta-reloads it.
        target = machines[0].name
        names = [m.name for m in machines]
        reg.clear(names)
        coll = ModelCollection.from_directory(d, project="bench-refresh")
        with reg.suspended():
            fleet = coll.fleet_scorer
            for b in fleet.buckets:
                jax.block_until_ready(jax.tree.leaves(b.params))
        gen_before = artifacts.read_generation(d)
        rngh = np.random.default_rng(7)
        fh.write_rollup(d, {
            "gordo-fleet-health": 1,
            "machines": {target: {
                "baseline": fh.sketch_from_scores(
                    rngh.lognormal(0.0, 1.0, 4000), ts=0.0
                ).to_doc(),
                "live": fh.sketch_from_scores(
                    rngh.lognormal(3.0, 1.0, 2000), ts=0.0
                ).to_doc(),
            }},
        })
        rcfg = RefreshConfig(
            machines=machines, output_dir=d, project="bench-refresh",
            hysteresis=1, cooldown_seconds=0,
            build_kwargs={"max_bucket_size": bucket,
                          "artifact_format": "v2"},
        )
        d0 = artifacts.device_put_count()
        t0 = time.perf_counter()
        with reg.suspended():
            summary = refresh_once(rcfg)
            changes = coll.maybe_delta_reload()
            for b in coll.fleet_scorer.buckets:
                jax.block_until_ready(jax.tree.leaves(b.params))
        e2e = time.perf_counter() - t0
        flip_ok = (
            summary.get("outcome") == "rebuilt"
            and summary.get("rebuilt") == [target]
            and summary.get("generation") == gen_before + 1
            and coll.generation == gen_before + 1
            and changes.get("reloaded") == [target]
        )
        out["refresh_drift_to_live_s"] = round(e2e, 2)
        out["refresh_e2e_outcome"] = summary.get("outcome")
        out["refresh_e2e_rebuilt"] = summary.get("rebuilt")
        out["refresh_e2e_reloaded"] = changes.get("reloaded")
        out["refresh_e2e_device_puts"] = artifacts.device_put_count() - d0
        out["refresh_e2e_flip_ok"] = flip_ok
        out["refresh_cycles_total"] = counter("gordo_refresh_cycles_total")
        out["refresh_machines_total"] = counter("gordo_refresh_machines_total")
        out["refresh_ok"] = all_ok and flip_ok
        log(f"refresh e2e: drift→flip→reloaded in {e2e:.2f}s "
            f"(outcome {summary.get('outcome')}, reloaded "
            f"{changes.get('reloaded')}, flip_ok {flip_ok})")
    finally:
        if saved_frac is None:
            os.environ.pop("GORDO_REFRESH_EPOCH_FRACTION", None)
        else:
            os.environ["GORDO_REFRESH_EPOCH_FRACTION"] = saved_frac
        shutil.rmtree(d, ignore_errors=True)
        for s in scratch:
            shutil.rmtree(s, ignore_errors=True)


# ---------------------------------------------------------------------------
# backfill bench
# ---------------------------------------------------------------------------

def _backfill_fleet_dir(model, metadata, names: "list[str]") -> str:
    """A v2 pack dir replicating one built machine across ``names`` in
    512-machine packs (the artifact-plane layout the 10k time-to-ready
    bench uses)."""
    from gordo_tpu import artifacts

    art_dir = tempfile.mkdtemp(prefix="gordo-bench-backfill-")
    for start in range(0, len(names), 512):
        part = names[start: start + 512]
        metas = []
        for name in part:
            md = dict(metadata)
            md["name"] = name
            metas.append(md)
        artifacts.write_pack(art_dir, part, [model] * len(part), metas)
    return art_dir


def bench_backfill(out: dict) -> None:
    """ISSUE 14 acceptance: the backfill plane's archive path vs the only
    alternative the reference had — replaying history through the HTTP
    serving tier.

    Protocol (docs/perf.md "Backfill"):

    - one trained machine replicated across N names (512 and 10k), v2
      packs, identical tag lists — so the provider cost collapses to one
      fetch on BOTH paths and the comparison is codec/transport, not
      data generation;
    - archive path: a warmup ``run_backfill`` over one preceding chunk
      (stacked-program compiles land in the in-process jit registry),
      then a measured run over the full range.  The reported rate is the
      summary's END-TO-END number — artifact loads, provider fetch,
      chunk slicing, dispatch, assemble, mmap write and fsync all
      inside the clock;
    - HTTP comparators against a REAL ``run-server`` subprocess over
      the same artifact dir, same windows, production bulk msgpack
      wire, bodies sized by the client's own ``bulk_rows_budget`` (the
      payload contract any replay client must respect).  Two numbers,
      reported separately:

      * ``http_wire``: raw bulk posts with responses decoded and
        DISCARDED, a few in flight so the server never starves — the
        transport-only saturation floor no real replay can beat;
      * ``http_replay``: the actual ``Client`` (``use_bulk=True``)
        replaying the range and materializing per-machine score frames
        — the pre-backfill way to score history over HTTP (forwarding/
        persistence left OFF, which favors HTTP: the archive's clock
        includes writing scores to disk).

      Server startup, model loading, and warmup rounds are excluded
      from the HTTP clocks (the archive number includes its own);
    - attestation: device transfers per chunk from the run summary
      (one stacked host->device staging per bucket program per chunk;
      the replicated fleet is structurally ONE bucket, so the gate is
      exactly 1.0);
    - gate: archive-path samples/s >= 3x the ``Client`` HTTP replay at
      512 machines on CPU.  The wire floor is recorded alongside so
      the transport-vs-materialization split stays visible.

    Honesty note: with one visible core the replay's client-side codec
    timeshares with the server (in production the client is another
    host), but the dominant replay costs — server unpackb/packb, the
    budget-bounded body sizes, per-request round trips — are inherent
    to the HTTP plane; ``cpu_cores`` is recorded alongside.
    """
    import asyncio
    import socket
    import urllib.request

    import aiohttp
    import pandas as pd

    from gordo_tpu.batch import BackfillConfig, chunk_windows, run_backfill
    from gordo_tpu.client.io import bulk_rows_budget
    from gordo_tpu.dataset import dataset_from_metadata
    from gordo_tpu.serve import codec

    n_small = int(os.environ.get("BENCH_BACKFILL_MACHINES", "512"))
    small_rows = int(os.environ.get("BENCH_BACKFILL_CHUNK_ROWS", "2048"))
    small_chunks = int(os.environ.get("BENCH_BACKFILL_CHUNKS", "8"))
    n_large = int(os.environ.get("BENCH_BACKFILL_LARGE_MACHINES", "10000"))
    large_rows = int(os.environ.get("BENCH_BACKFILL_LARGE_CHUNK_ROWS", "256"))
    large_chunks = int(os.environ.get("BENCH_BACKFILL_LARGE_CHUNKS", "2"))
    concurrency = int(os.environ.get("BENCH_BACKFILL_HTTP_CONCURRENCY", "3"))
    out["cpu_cores"] = os.cpu_count()

    model, metadata = _build_serving_model()
    resolution = (metadata.get("dataset") or {}).get("resolution", "10min")
    step = pd.tseries.frequencies.to_offset(resolution)

    procs: "list[subprocess.Popen]" = []
    logs: "list[str]" = []

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def spawn(port: int, art_dir: str) -> subprocess.Popen:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("GORDO_SERVE_SHARD", None)
        env["JAX_PLATFORMS"] = "cpu"
        log_path = os.path.join(art_dir, f"server-{port}.log")
        logs.append(log_path)
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "gordo_tpu.cli.cli", "run-server",
                "--model-dir", art_dir, "--project", "bench",
                "--host", "127.0.0.1", "--port", str(port),
                "--rescan-interval", "0",
            ],
            env=env,
            stdout=open(log_path, "w"), stderr=subprocess.STDOUT,
        )
        procs.append(proc)
        return proc

    def wait_ready(port: int, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        url = f"http://127.0.0.1:{port}/healthz"
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    if resp.status == 200:
                        return
            except Exception:
                time.sleep(0.25)
        raise RuntimeError(f"backfill server on :{port} never became ready")

    def stop(to_stop: "list[subprocess.Popen]") -> None:
        for proc in to_stop:
            proc.terminate()
        for proc in to_stop:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    headers = {
        "Content-Type": codec.MSGPACK_CONTENT_TYPE,
        "Accept": codec.MSGPACK_CONTENT_TYPE,
    }

    def archive_run(art_dir: str, start, end, rows: int) -> dict:
        """Warmup run over the chunk preceding ``start`` (same stacked
        geometry -> compiles land), then the measured end-to-end run."""
        warm_dir = tempfile.mkdtemp(prefix="gordo-bench-bf-warm-")
        meas_dir = tempfile.mkdtemp(prefix="gordo-bench-bf-arch-")
        try:
            run_backfill(BackfillConfig(
                model_dir=art_dir, start=str(start - step * rows),
                end=str(start), archive_dir=warm_dir, project="bench",
                chunk_rows=rows,
            ))
            return run_backfill(BackfillConfig(
                model_dir=art_dir, start=str(start), end=str(end),
                archive_dir=meas_dir, project="bench", chunk_rows=rows,
            ))
        finally:
            shutil.rmtree(warm_dir, ignore_errors=True)
            shutil.rmtree(meas_dir, ignore_errors=True)

    def http_wire_floor(
        port: int, names: "list[str]", start, end, rows: int
    ) -> dict:
        """The same windows through a real server's bulk msgpack route,
        bodies sized by the client's samples budget, responses decoded
        and DISCARDED — the transport-only floor no real replay client
        can beat (a replay has to materialize and keep its scores)."""
        dataset = dataset_from_metadata(
            metadata["dataset"], str(start), str(end)
        )
        X, _ = dataset.get_data()
        budget_rows = bulk_rows_budget(len(names) * X.shape[1], rows)
        slabs: "list[np.ndarray]" = []
        for t0, t1 in chunk_windows(start, end, resolution, rows):
            lo, hi = X.index.searchsorted(t0), X.index.searchsorted(t1)
            arr = X.iloc[lo:hi].to_numpy(np.float32)
            for r0 in range(0, len(arr), budget_rows):
                if len(arr[r0: r0 + budget_rows]):
                    slabs.append(arr[r0: r0 + budget_rows])

        url = (
            f"http://127.0.0.1:{port}"
            "/gordo/v0/bench/_bulk/anomaly/prediction"
        )

        async def drive() -> "tuple[int, float]":
            samples = 0
            timeout = aiohttp.ClientTimeout(total=900)
            sem = asyncio.Semaphore(concurrency)
            async with aiohttp.ClientSession(timeout=timeout) as session:

                async def post(slab: np.ndarray, measured: bool) -> None:
                    nonlocal samples
                    # packb under the semaphore: at most ``concurrency``
                    # bodies alive, encode overlapped with server work
                    async with sem:
                        body = codec.packb({"X": {n: slab for n in names}})
                        async with session.post(
                            url, data=body, headers=headers
                        ) as resp:
                            raw = await resp.read()
                    if resp.status != 200:
                        raise RuntimeError(
                            f"bulk replay -> {resp.status}: {raw[:160]!r}"
                        )
                    data = codec.unpackb(raw)["data"]
                    if measured:
                        for res in data.values():
                            samples += int(
                                np.asarray(res["tag-anomaly-scores"]).size
                            )

                # warmup: head + tail slab shapes land the server compiles
                await asyncio.gather(
                    post(slabs[0], False), post(slabs[-1], False)
                )
                t0 = time.perf_counter()
                await asyncio.gather(*(post(s, True) for s in slabs))
                return samples, time.perf_counter() - t0

        samples, dt = asyncio.run(drive())
        return {
            "samples": samples,
            "seconds": dt,
            "samples_per_sec": samples / dt if dt > 0 else 0.0,
            "rows_per_request": budget_rows,
            "n_requests": len(slabs),
        }

    def client_replay(port: int, start, end, rows: int) -> dict:
        """THE pre-backfill alternative: the real ``Client`` replaying the
        range over the bulk msgpack wire and materializing per-machine
        score frames — what scoring history over HTTP actually costs.
        Prediction forwarding/persistence is left OFF (favors HTTP: the
        archive path's clock includes writing its scores to disk)."""
        from gordo_tpu.client import Client

        client = Client(
            "bench", port=port, use_bulk=True, batch_size=rows,
        )
        t0 = time.perf_counter()
        results = client.predict(str(start), str(end))
        dt = time.perf_counter() - t0
        samples = 0
        for res in results:
            if not res.ok:
                raise RuntimeError(
                    f"client replay failed for {res.name}: "
                    f"{res.error_messages}"
                )
            frame = res.predictions
            n_tag_cols = sum(
                1 for c in frame.columns if c[0] == "tag-anomaly-scores"
            )
            samples += len(frame) * n_tag_cols
        return {
            "samples": samples,
            "seconds": dt,
            "samples_per_sec": samples / dt if dt > 0 else 0.0,
            "machines": len(results),
        }

    def scenario(
        n: int, rows: int, chunks: int, ready_timeout_s: float,
        with_client_replay: bool,
    ) -> "float | None":
        names = [f"bf-{i:05d}" for i in range(n)]
        art_dir = _backfill_fleet_dir(model, metadata, names)
        server = None
        try:
            start = pd.Timestamp("2024-01-01T00:00:00Z")
            end = start + step * (rows * chunks)
            summary = archive_run(art_dir, start, end, rows)
            key = f"backfill_{n}"
            archive_sps = summary["samples-per-second"]
            out[f"{key}_samples_per_sec"] = round(archive_sps)
            out[f"{key}_samples"] = summary["samples"]
            out[f"{key}_seconds"] = summary["seconds"]
            out[f"{key}_chunks"] = summary["chunks-ok"]
            out[f"{key}_chunk_rows"] = rows
            per_chunk = (
                summary["device-transfers"] / max(1, summary["chunks-ok"])
            )
            out[f"{key}_device_transfers_per_chunk"] = round(per_chunk, 3)
            out[f"{key}_one_transfer_per_chunk_ok"] = per_chunk == 1.0
            log(f"backfill archive @{n}: {archive_sps:,.0f} samples/s "
                f"({summary['samples']:,} samples / {summary['seconds']}s, "
                f"{per_chunk:.1f} transfers/chunk)")

            port = free_port()
            server = spawn(port, art_dir)
            wait_ready(port, ready_timeout_s)

            wire = http_wire_floor(port, names, start, end, rows)
            out[f"{key}_http_wire_samples_per_sec"] = round(
                wire["samples_per_sec"]
            )
            out[f"{key}_http_rows_per_request"] = wire["rows_per_request"]
            out[f"{key}_http_requests"] = wire["n_requests"]
            out[f"{key}_vs_http_wire_speedup"] = round(
                archive_sps / wire["samples_per_sec"], 3
            )
            log(f"backfill http wire floor @{n}: "
                f"{wire['samples_per_sec']:,.0f} samples/s "
                f"({wire['n_requests']} requests of "
                f"{wire['rows_per_request']} rows) -> archive "
                f"{archive_sps / wire['samples_per_sec']:.2f}x")

            if not with_client_replay:
                return None
            replay = client_replay(port, start, end, rows)
            out[f"{key}_http_replay_samples_per_sec"] = round(
                replay["samples_per_sec"]
            )
            out[f"{key}_http_replay_samples"] = replay["samples"]
            out[f"{key}_http_replay_seconds"] = round(replay["seconds"], 3)
            speedup = archive_sps / replay["samples_per_sec"]
            out[f"{key}_vs_http_replay_speedup"] = round(speedup, 3)
            log(f"backfill client replay @{n}: "
                f"{replay['samples_per_sec']:,.0f} samples/s "
                f"({replay['samples']:,} samples / {replay['seconds']:.1f}s)"
                f" -> archive {speedup:.2f}x")
            return speedup
        finally:
            if server is not None:
                stop([server])
            shutil.rmtree(art_dir, ignore_errors=True)

    try:
        speedup = scenario(
            n_small, small_rows, small_chunks, 180.0,
            with_client_replay=True,
        )
        # the acceptance gate: archive path >= 3x replaying the same
        # range through the HTTP tier at 512 machines on CPU
        out["backfill_ge_3x_http_ok"] = speedup >= 3.0
        log(f"backfill gate @{n_small}: {speedup:.2f}x >= 3x -> "
            f"{'PASS' if speedup >= 3.0 else 'FAIL'}")
        if n_large:
            # client-side frame materialization at 10k machines x tiny
            # budget bodies takes tens of minutes — the wire floor is
            # the recorded comparator at fleet scale
            scenario(
                n_large, large_rows, large_chunks, 420.0,
                with_client_replay=False,
            )
    except Exception:
        for log_path in logs:
            try:
                with open(log_path) as fh:
                    tail = fh.read()[-2000:]
                if tail:
                    log(f"--- {log_path} tail ---\n{tail}")
            except OSError:
                pass
        raise
    finally:
        stop(procs)


def bench_scores_lifecycle(out: dict) -> None:
    """ISSUE 16 acceptance: the score-archive lifecycle at fleet-year
    scale — compaction throughput vs raw mmap scan speed, aggregate
    byte-identity across compaction, and the ``/scores/aggregate``
    pushdown vs client-side fetch-and-aggregate over ``score_history``.

    Protocol (docs/perf.md "Archive lifecycle"):

    - a synthetic 512-machine archive: 8 chunks x 2048 rows at 30min
      resolution (~341 days — a fleet-year of scored history; ~75M
      scored samples, ~370 MB of GSA1 columns) written through the REAL
      ``write_chunk`` path (fsync'd segments + completion records);
    - raw scan: every byte of every data segment summed through the
      same ``np.memmap`` reads the query plane uses (best of 2, warm
      page cache — the comparator compaction has to keep up with);
    - compaction: ``compact_scores`` at a 90d partition (3 periods of
      2-3 chunks each; the trailing single-chunk period stays as a
      chunk file — eligibility needs >= 2 segments).  Throughput =
      bytes moved (input scanned + output fsync'd) / wall clock, gated
      >= 0.5x the scan rate; the write-only rate and the medium's
      measured durable-write ceiling are recorded alongside (the fsync
      before each index flip pins the write side to the disk, so the
      honest comparison needs both numbers);
    - aggregates (count/mean/max/p50/p90/p99/exceed over 7d periods)
      run before and after compaction and must be BYTE-identical;
    - pushdown: a real ``run-server`` subprocess over a 1-model v2 pack
      dir holding the archive; ``client.score_summary`` end-to-end
      (HTTP + server-side mmap scan + GSB1 columnar wire + decode) vs
      the pre-r20 client-side path — ``client.score_history`` (LOCAL
      mmap reads, zero wire cost: a handicap the gate absorbs)
      materializing 512 frames + pandas groupby computing the SAME
      stats.  Gate: pushdown >= 10x faster end-to-end.
    """
    import socket
    import urllib.request

    import pandas as pd

    from gordo_tpu.batch import (
        AGGREGATE_STATS,
        ScoreArchive,
        compact_scores,
        gc_scores,
        plan_compaction,
    )
    from gordo_tpu.client import Client

    n_machines = int(os.environ.get("BENCH_SCORES_MACHINES", "512"))
    chunk_rows = int(os.environ.get("BENCH_SCORES_CHUNK_ROWS", "2048"))
    n_chunks = int(os.environ.get("BENCH_SCORES_CHUNKS", "8"))
    n_tags = int(os.environ.get("BENCH_SCORES_TAGS", "8"))
    agg_period = "7d"
    threshold = 1.0
    out["cpu_cores"] = os.cpu_count()

    model, metadata = _build_serving_model()
    art_dir = _backfill_fleet_dir(model, metadata, ["scores-m-000"])
    # the stage measures SOFTWARE throughput (compactor and scan on the
    # same medium); a device-independent medium keeps the ratio from
    # collapsing into this container's fsync bandwidth, which is probed
    # and recorded separately against the real disk below.
    shm = os.environ.get("BENCH_SCORES_DIR", "/dev/shm")
    if os.path.isdir(shm) and os.access(shm, os.W_OK):
        shm_dir = tempfile.mkdtemp(prefix="gordo-bench-scores-", dir=shm)
        for entry in os.listdir(art_dir):
            shutil.move(os.path.join(art_dir, entry),
                        os.path.join(shm_dir, entry))
        os.rmdir(art_dir)
        art_dir = shm_dir
        out["scores_archive_medium"] = "tmpfs"
    else:
        out["scores_archive_medium"] = "disk"
    procs: "list[subprocess.Popen]" = []
    logs: "list[str]" = []

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def spawn(port: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("GORDO_SERVE_SHARD", None)
        env["JAX_PLATFORMS"] = "cpu"
        log_path = os.path.join(art_dir, f"server-{port}.log")
        logs.append(log_path)
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "gordo_tpu.cli.cli", "run-server",
                "--model-dir", art_dir, "--project", "bench",
                "--host", "127.0.0.1", "--port", str(port),
                "--rescan-interval", "0",
            ],
            env=env,
            stdout=open(log_path, "w"), stderr=subprocess.STDOUT,
        )
        procs.append(proc)
        return proc

    def wait_ready(port: int, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        url = f"http://127.0.0.1:{port}/healthz"
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    if resp.status == 200:
                        return
            except Exception:
                time.sleep(0.25)
        raise RuntimeError(f"scores server on :{port} never became ready")

    def stop(to_stop: "list[subprocess.Popen]") -> None:
        for proc in to_stop:
            proc.terminate()
        for proc in to_stop:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    try:
        # -- build the fleet-year archive through the real write path ---
        step = pd.Timedelta("30min")
        step_ns = int(step.value)
        start = pd.Timestamp("2024-01-01T00:00:00Z")
        names = [f"scm-{i:04d}" for i in range(n_machines)]
        arch = ScoreArchive.create(
            art_dir, project="bench", start=str(start),
            end=str(start + step * (chunk_rows * n_chunks)),
            resolution="30min", chunk_rows=chunk_rows,
            n_chunks=n_chunks, dtype="float32", machines=names,
        )
        tags = [f"t{j}" for j in range(n_tags)]
        rng = np.random.default_rng(3)
        t0_ns = int(start.value)
        span_ns = chunk_rows * step_ns
        t_build = time.perf_counter()
        for c in range(n_chunks):
            idx = (
                t0_ns + c * span_ns
                + step_ns * np.arange(chunk_rows, dtype=np.int64)
            )
            tot = rng.random((n_machines, chunk_rows), dtype=np.float32) * 3
            tag = rng.random(
                (n_machines, chunk_rows, n_tags), dtype=np.float32
            )
            arch.write_chunk(c, {
                name: {
                    "index-ns": idx,
                    "total-anomaly-score": tot[i],
                    "tag-anomaly-scores": tag[i],
                    "tags": tags,
                }
                for i, name in enumerate(names)
            })
        build_s = time.perf_counter() - t_build
        rows_total = n_machines * chunk_rows * n_chunks
        out["scores_machines"] = n_machines
        out["scores_rows"] = rows_total
        out["scores_samples"] = rows_total * (n_tags + 1)
        out["scores_archive_build_s"] = round(build_s, 2)

        # -- raw mmap scan floor (best of 2, warm cache) ----------------
        def mmap_scan() -> "tuple[int, float]":
            t0 = time.perf_counter()
            nbytes = 0
            sink = 0
            for path in arch._data_segments():
                buf = np.memmap(path, dtype=np.uint8, mode="r")
                sink += int(np.add.reduce(buf, dtype=np.int64))
                nbytes += buf.size
            return nbytes, time.perf_counter() - t0

        scan_bytes, scan_1 = mmap_scan()
        _, scan_2 = mmap_scan()
        scan_s = min(scan_1, scan_2)
        scan_bps = scan_bytes / scan_s if scan_s > 0 else 0.0
        out["scores_archive_mb"] = round(scan_bytes / 1e6, 1)
        out["scores_scan_mb_per_s"] = round(scan_bps / 1e6, 1)
        log(f"scores scan: {scan_bytes / 1e6:,.0f} MB in {scan_s:.2f}s "
            f"({scan_bps / 1e6:,.0f} MB/s)")

        # -- aggregate before compaction (also the local-latency point) -
        t0 = time.perf_counter()
        agg_pre = arch.aggregate(
            stats=list(AGGREGATE_STATS), period=agg_period,
            threshold=threshold,
        )
        out["scores_aggregate_local_s"] = round(time.perf_counter() - t0, 3)

        # -- durable-write ceiling of the real disk -------------------
        # a production compactor must fsync every period file before
        # the index flip, so on spinning/virtio media its write side is
        # device-bound.  Probe the container's disk with a dd-style
        # write+fsync so the report carries that ceiling next to the
        # software throughput measured above it.
        probe_path = os.path.join(
            tempfile.gettempdir(), "gordo_bench_disk_probe.tmp"
        )
        probe_mb = 128
        block = np.random.default_rng(0).integers(
            0, 256, probe_mb * 1_000_000, dtype=np.uint8
        ).tobytes()
        t0 = time.perf_counter()
        with open(probe_path, "wb") as fh:
            fh.write(block)
            fh.flush()
            os.fsync(fh.fileno())
        disk_bps = len(block) / (time.perf_counter() - t0)
        os.unlink(probe_path)
        del block
        out["scores_disk_write_mb_per_s"] = round(disk_bps / 1e6, 1)

        # -- compaction vs the scan floor -------------------------------
        # throughput counts the bytes the compactor MOVES per wall
        # second: every input byte scanned off the chunk segments plus
        # every output byte written durably — the two directions of
        # compaction I/O, both recorded separately below.
        eligible = plan_compaction(art_dir, period="90d")["eligible"]
        read_bytes = sum(
            os.path.getsize(os.path.join(arch.directory, fname))
            for info in eligible.values()
            for _c, _s, fname in info["segments"]
        )
        t0 = time.perf_counter()
        summary = compact_scores(art_dir, period="90d")
        compact_s = time.perf_counter() - t0
        write_bps = (
            summary["bytes-written"] / compact_s if compact_s > 0 else 0.0
        )
        io_bps = (
            (read_bytes + summary["bytes-written"]) / compact_s
            if compact_s > 0 else 0.0
        )
        ratio = io_bps / scan_bps if scan_bps > 0 else 0.0
        out["scores_compact_periods"] = summary["periods-compacted"]
        out["scores_compact_segments_merged"] = summary["segments-merged"]
        out["scores_compact_mb_read"] = round(read_bytes / 1e6, 1)
        out["scores_compact_mb_written"] = round(
            summary["bytes-written"] / 1e6, 1
        )
        out["scores_compact_s"] = round(compact_s, 2)
        out["scores_compact_write_mb_per_s"] = round(write_bps / 1e6, 1)
        out["scores_compact_vs_disk_ratio"] = round(
            write_bps / disk_bps, 3
        ) if disk_bps > 0 else None
        out["scores_compact_mb_per_s"] = round(io_bps / 1e6, 1)
        out["scores_compact_vs_scan_ratio"] = round(ratio, 3)
        out["scores_compact_ge_half_scan_ok"] = ratio >= 0.5
        log(f"scores compact: {summary['periods-compacted']} periods "
            f"({len(eligible)} planned), "
            f"{summary['bytes-written'] / 1e6:,.0f} MB written + "
            f"{read_bytes / 1e6:,.0f} MB scanned in {compact_s:.2f}s "
            f"({io_bps / 1e6:,.0f} MB/s moved, {ratio:.2f}x scan; "
            f"write side {write_bps / 1e6:,.0f} MB/s vs disk "
            f"{disk_bps / 1e6:,.0f} MB/s) -> "
            f"{'PASS' if ratio >= 0.5 else 'FAIL'}")

        # -- byte-identity across compaction ----------------------------
        agg_post = arch.aggregate(
            stats=list(AGGREGATE_STATS), period=agg_period,
            threshold=threshold,
        )
        identical = agg_pre["periods"] == agg_post["periods"] and all(
            agg_pre["stats"][k].tobytes() == agg_post["stats"][k].tobytes()
            for k in agg_pre["stats"]
        )
        out["scores_aggregate_bytes_identical_ok"] = identical
        log(f"scores aggregate byte-identity across compaction: "
            f"{'PASS' if identical else 'FAIL'}")

        # -- pushdown vs client-side fetch-and-aggregate ----------------
        port = free_port()
        spawn(port)
        wait_ready(port, 240.0)
        client = Client("bench", port=port)
        client.score_summary(machines=names[:1], period=agg_period)  # warm
        t0 = time.perf_counter()
        doc = client.score_summary(
            stats=list(AGGREGATE_STATS), period=agg_period,
            threshold=threshold,
        )
        push_s = time.perf_counter() - t0
        resp_bytes = sum(
            np.asarray(a).nbytes
            for stats_map in doc["data"].values()
            for a in stats_map.values()
        )
        midx = {n: i for i, n in enumerate(agg_post["machines"])}
        parity = all(
            np.array_equal(
                np.asarray(doc["data"][n][k]), agg_post["stats"][k][midx[n]]
            )
            for n in doc["data"] for k in AGGREGATE_STATS
        )
        out["scores_pushdown_parity_ok"] = parity

        t0 = time.perf_counter()
        frames = client.score_history(archive_dir=art_dir)
        fetched = 0
        for frame in frames.values():
            fetched += int(frame.size)
            s = frame["total-anomaly-score"]
            grouped = s.groupby(pd.Grouper(freq="7D"))
            grouped.agg(["count", "mean", "max"])
            grouped.quantile([0.5, 0.9, 0.99])
            s.gt(threshold).groupby(pd.Grouper(freq="7D")).sum()
        fetch_s = time.perf_counter() - t0
        speedup = fetch_s / push_s if push_s > 0 else 0.0
        out["scores_pushdown_s"] = round(push_s, 3)
        out["scores_pushdown_response_kb"] = round(resp_bytes / 1e3, 1)
        out["scores_pushdown_periods"] = len(doc["periods"])
        out["scores_fetch_aggregate_s"] = round(fetch_s, 2)
        out["scores_fetch_aggregate_cells"] = fetched
        out["scores_pushdown_speedup"] = round(speedup, 2)
        out["scores_pushdown_ge_10x_ok"] = speedup >= 10.0
        log(f"scores pushdown: {push_s:.3f}s "
            f"({resp_bytes / 1e3:,.0f} KB over the wire) vs "
            f"fetch-and-aggregate {fetch_s:.2f}s "
            f"({fetched:,} frame cells) -> {speedup:.1f}x >= 10x "
            f"{'PASS' if speedup >= 10.0 else 'FAIL'}")

        # -- retention (destructive: runs last) -------------------------
        now_s = (start + step * (chunk_rows * n_chunks)).timestamp()
        t0 = time.perf_counter()
        g = gc_scores(art_dir, keep_days=180, now=now_s)
        out["scores_gc_s"] = round(time.perf_counter() - t0, 3)
        out["scores_gc_segments_deleted"] = g["segments-deleted"]
        out["scores_gc_mb_reclaimed"] = round(g["bytes-reclaimed"] / 1e6, 1)
        log(f"scores gc --keep 180: {g['segments-deleted']} segment(s), "
            f"{g['bytes-reclaimed'] / 1e6:,.0f} MB reclaimed")
    except Exception:
        for log_path in logs:
            try:
                with open(log_path) as fh:
                    tail = fh.read()[-2000:]
                if tail:
                    log(f"--- {log_path} tail ---\n{tail}")
            except OSError:
                pass
        raise
    finally:
        stop(procs)
        shutil.rmtree(art_dir, ignore_errors=True)


def bench_streaming(out: dict) -> None:
    """ISSUE 17 acceptance: the streaming plane vs 1-row bulk polling,
    end-to-end through a real server, plus detection-to-push latency
    over a live SSE subscriber.

    Protocol (docs/perf.md "Streaming plane"):

    - arrival schedule: a GSA1 archive window written through the real
      ``write_chunk`` path and replayed in ``index-ns`` order — the
      stream is driven by the same clock a backfilled fleet replays;
    - in-process step rate (diagnostic): ``StreamHub.ingest_rows`` once
      per arrival — the fixed-shape incremental step over the
      device-resident ring, dispatched through the compile plane's
      ``bind`` fast path (per-arrival cost is O(window), independent of
      history length) — against the bulk device path re-scoring the
      trailing lookback padded to its 256-row compile bucket;
    - the GATE is end-to-end: a 1-row poller pays one full HTTP bulk
      request per sample (that is the ONLY way the request path yields
      one new verdict), while the streaming plane ingests arrivals in
      transport batches and delivers per-row verdicts through the
      event ring (drained here via the documented long-poll fallback,
      whose batched frames are also how a thin consumer would read).
      Gate: streaming >= 5x polling samples/s/core, both sides
      single-threaded against the same single-core server;
    - detection-to-push p99: a live SSE subscriber over the wire;
      per-event latency = frame receipt minus the verdict's ``time``
      field (stamped by the hub at detection).
    """
    import asyncio
    import threading as _threading
    import urllib.request

    import pandas as pd
    from aiohttp import web

    from gordo_tpu.batch import ScoreArchive
    from gordo_tpu.client import Client
    from gordo_tpu.serve import ModelCollection, build_app
    from gordo_tpu.serve.scorer import CompiledScorer
    from gordo_tpu.serve.stream import StreamHub

    n_replay = int(os.environ.get("BENCH_STREAM_ROWS", "2048"))
    n_poll = int(os.environ.get("BENCH_STREAM_POLLS", "96"))
    n_e2e = int(os.environ.get("BENCH_STREAM_E2E_ROWS", "1024"))
    n_push = int(os.environ.get("BENCH_STREAM_PUSH_EVENTS", "384"))
    ingest_batch = int(os.environ.get("BENCH_STREAM_BATCH", "32"))
    out["cpu_cores"] = os.cpu_count()

    model, metadata = _build_serving_model()
    scorer = CompiledScorer(model)
    name = "stream-m-000"

    # -- arrival schedule: one GSA1 chunk replayed in index order -----------
    arch_dir = tempfile.mkdtemp(prefix="gordo-bench-stream-")
    try:
        step = pd.Timedelta("30min")
        start = pd.Timestamp("2024-01-01T00:00:00Z")
        arch = ScoreArchive.create(
            arch_dir, project="bench", start=str(start),
            end=str(start + step * n_replay), resolution="30min",
            chunk_rows=n_replay, n_chunks=1, dtype="float32",
            machines=[name],
        )
        rng = np.random.default_rng(17)
        idx = (
            int(start.value)
            + int(step.value) * np.arange(n_replay, dtype=np.int64)
        )
        arch.write_chunk(0, {name: {
            "index-ns": idx,
            "total-anomaly-score":
                rng.random(n_replay, dtype=np.float32),
            "tag-anomaly-scores":
                rng.random((n_replay, N_TAGS), dtype=np.float32),
            "tags": [f"tag-{j}" for j in range(N_TAGS)],
        }})
        hist = arch.read_machine(name)
        order = np.argsort(hist["index-ns"], kind="stable")
        X = rng.standard_normal((n_replay, N_TAGS)).astype(np.float32)
        X = X[order]

        # -- in-process device-path diagnostic ------------------------------
        hub = StreamHub()
        warm = 8
        for i in range(warm):  # includes the stream-step compile
            hub.ingest_rows(name, scorer, X[i])
        t0 = time.perf_counter()
        for i in range(warm, n_replay):
            hub.ingest_rows(name, scorer, X[i])
        step_rate = (n_replay - warm) / (time.perf_counter() - t0)
        h = hub.streams[name].state_rows

        scorer.anomaly_arrays(X[:h], None)  # compile the polled bucket
        t0 = time.perf_counter()
        for i in range(h, h + n_poll):
            scorer.anomaly_arrays(X[i - h: i], None)
        device_poll_rate = n_poll / (time.perf_counter() - t0)
        out["stream_step_samples_per_s"] = round(step_rate, 1)
        out["stream_device_polling_samples_per_s"] = round(
            device_poll_rate, 1
        )
        out["stream_state_rows"] = h
        log(
            f"streaming step (in-process): {step_rate:,.0f}/s vs "
            f"{device_poll_rate:,.0f}/s bulk device path"
        )

        # -- end-to-end: real server, 1-row polling vs ingest+drain ---------
        art_dir = _backfill_fleet_dir(model, metadata, [name])
        try:

            async def runner():
                coll = ModelCollection.from_directory(
                    art_dir, project="bench"
                )
                app_runner = web.AppRunner(build_app(coll))
                await app_runner.setup()
                site = web.TCPSite(app_runner, "127.0.0.1", 0)
                await site.start()
                base = f"http://127.0.0.1:{app_runner.addresses[0][1]}"

                def post(url, doc):
                    req = urllib.request.Request(
                        url, data=json.dumps(doc).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=60) as resp:
                        return json.load(resp)

                def drive():
                    # 1-row bulk polling: one request per sample is the
                    # request path's only route to one new verdict
                    url = (
                        f"{base}/gordo/v0/bench/{name}/anomaly/prediction"
                    )
                    post(url, {"X": X[:1].tolist()})  # warm
                    t0 = time.perf_counter()
                    for i in range(n_poll):
                        post(url, {"X": X[i: i + 1].tolist()})
                    poll_rate = n_poll / (time.perf_counter() - t0)

                    # streaming: transport-batched ingest + the consumer
                    # draining the event ring via long-poll frames
                    feeder = Client("bench", base_url=base)
                    feeder.stream_ingest({name: X[:warm].tolist()})
                    stream_url = f"{base}/gordo/v0/bench/stream"
                    got, cursor = 0, 0

                    def feed():
                        j = warm
                        while j < warm + n_e2e:
                            feeder.stream_ingest({name: X[
                                j % (n_replay - ingest_batch):
                                j % (n_replay - ingest_batch)
                                + ingest_batch
                            ].tolist()})
                            j += ingest_batch

                    th = _threading.Thread(target=feed, daemon=True)
                    t0 = time.perf_counter()
                    th.start()
                    while got < n_e2e:
                        status = urllib.request.urlopen(
                            f"{stream_url}?mode=poll&after={cursor}"
                            "&timeout=10", timeout=60,
                        )
                        doc = json.load(status)
                        got += sum(
                            1 for ev in doc["events"]
                            if ev["type"] == "verdict"
                        )
                        cursor = doc["last-event-id"]
                    stream_rate = got / (time.perf_counter() - t0)
                    th.join(timeout=30)

                    # detection-to-push p99 over a live SSE subscriber
                    lats: "list[float]" = []
                    consumer = Client("bench", base_url=base)
                    stop = _threading.Event()

                    def feed_paced():
                        j = 0
                        while not stop.is_set():
                            feeder.stream_ingest(
                                {name: [X[j % n_replay].tolist()]}
                            )
                            j += 1
                            time.sleep(0.003)

                    th2 = _threading.Thread(target=feed_paced, daemon=True)
                    th2.start()
                    try:
                        for ev in consumer.stream(
                            machines=[name], max_events=n_push
                        ):
                            if ev["type"] != "verdict":
                                continue
                            lats.append(
                                time.time() - ev["data"]["time"]
                            )
                    finally:
                        stop.set()
                        th2.join(timeout=10)
                    return poll_rate, stream_rate, lats

                try:
                    return await asyncio.get_running_loop().run_in_executor(
                        None, drive
                    )
                finally:
                    await app_runner.cleanup()

            poll_rate, stream_rate, lats = asyncio.run(runner())
            ratio = stream_rate / poll_rate
            out["stream_samples_per_s_per_core"] = round(stream_rate, 1)
            out["stream_polling_samples_per_s_per_core"] = round(
                poll_rate, 1
            )
            out["stream_vs_polling"] = round(ratio, 2)
            log(
                f"streaming e2e: {stream_rate:,.0f} samples/s/core vs "
                f"{poll_rate:,.0f} polling ({ratio:.1f}x; gate >= 5x)"
            )
            if ratio < 5.0:
                out["stream_gate_miss"] = (
                    f"streaming {ratio:.2f}x polling, gate >= 5x"
                )

            lats_ms = np.asarray(lats) * 1e3
            out["stream_push_p50_ms"] = round(
                float(np.percentile(lats_ms, 50)), 2
            )
            out["stream_push_p99_ms"] = round(
                float(np.percentile(lats_ms, 99)), 2
            )
            out["stream_push_events"] = len(lats)
            log(
                f"streaming push latency over SSE: p50 "
                f"{out['stream_push_p50_ms']}ms p99 "
                f"{out['stream_push_p99_ms']}ms ({len(lats)} events)"
            )
        finally:
            shutil.rmtree(art_dir, ignore_errors=True)
    finally:
        shutil.rmtree(arch_dir, ignore_errors=True)


def bench_serving_wire(out: dict) -> None:
    """ISSUE 15 acceptance: the GSB1 columnar bulk wire vs the r18
    msgpack bulk wire, end-to-end through the real ``Client`` against a
    REAL ``run-server`` subprocess.

    Protocol (docs/perf.md "Bulk wire"):

    - one trained machine replicated across N names (512), v2 packs,
      identical tag lists — the same fleet the backfill bench uses, so
      the comparison is wire codec + client materialization, not model
      or provider variance;
    - both legs are the actual ``Client`` (``use_bulk=True``) replaying
      a range and COUNTING every per-tag score sample it received:

      * ``columnar``: the r19 default — ``Accept`` negotiates GSB1,
        the client decodes zero-copy ``np.frombuffer`` views and the
        samples are counted off the LAZY column access (no DataFrame
        is ever built — the per-machine frame materialization the r18
        profile showed at 35x the raw wire floor is simply not paid);
      * ``msgpack``: ``use_columnar=False``, the r18 wire — per-machine
        DataFrames materialized via ``res.predictions``, exactly how
        BENCH_r18's ``backfill_512_http_replay_samples_per_sec``
        (264,367/s) was measured.

      The msgpack leg replays FEWER chunks (rates are normalized to
      samples/s) so the slow leg fits the stage budget;
    - legs are interleaved (C M C M ...) and each wire reports its
      best-of-``BENCH_WIRE_REPEATS`` — interleaving keeps slow drift
      (page cache, CPU thermal) from biasing one wire;
    - an un-timed warmup leg per wire lands the server's stacked-
      program compiles and both codec paths before any clock starts;
    - attestation: ``serving_wire_value_identity_ok`` — one slab posted
      twice to the same server, once per ``Accept``; every float array
      in the decoded responses must match BITWISE (fp32), scalars
      exactly.  The columnar wire is a relayout, not a requantization;
    - gate: columnar client e2e samples/s >= 3x the r18 msgpack
      baseline at 512 machines on CPU.
    """
    import urllib.request

    import pandas as pd

    from gordo_tpu.client import Client
    from gordo_tpu.serve import codec

    n_machines = int(os.environ.get("BENCH_WIRE_MACHINES", "512"))
    rows = int(os.environ.get("BENCH_WIRE_ROWS", "2048"))
    col_chunks = int(os.environ.get("BENCH_WIRE_CHUNKS", "8"))
    mp_chunks = int(os.environ.get("BENCH_WIRE_MSGPACK_CHUNKS", "2"))
    repeats = int(os.environ.get("BENCH_WIRE_REPEATS", "2"))
    out["cpu_cores"] = os.cpu_count()

    model, metadata = _build_serving_model()
    resolution = (metadata.get("dataset") or {}).get("resolution", "10min")
    step = pd.tseries.frequencies.to_offset(resolution)
    names = [f"wire-{i:05d}" for i in range(n_machines)]
    art_dir = _backfill_fleet_dir(model, metadata, names)

    procs: "list[subprocess.Popen]" = []
    logs: "list[str]" = []

    def free_port() -> int:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def spawn(port: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("GORDO_SERVE_SHARD", None)
        env["JAX_PLATFORMS"] = "cpu"
        log_path = os.path.join(art_dir, f"server-{port}.log")
        logs.append(log_path)
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "gordo_tpu.cli.cli", "run-server",
                "--model-dir", art_dir, "--project", "bench",
                "--host", "127.0.0.1", "--port", str(port),
                "--rescan-interval", "0",
            ],
            env=env,
            stdout=open(log_path, "w"), stderr=subprocess.STDOUT,
        )
        procs.append(proc)
        return proc

    def wait_ready(port: int, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        url = f"http://127.0.0.1:{port}/healthz"
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    if resp.status == 200:
                        return
            except Exception:
                time.sleep(0.25)
        raise RuntimeError(f"wire server on :{port} never became ready")

    def stop(to_stop: "list[subprocess.Popen]") -> None:
        for proc in to_stop:
            proc.terminate()
        for proc in to_stop:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    start = pd.Timestamp("2024-01-01T00:00:00Z")

    def leg(port: int, columnar: bool, chunks: int, timed: bool) -> dict:
        """One full client replay over ``chunks`` windows; per-tag score
        samples counted off the wire-appropriate access path.  The clock
        covers replay AND consumption: the lazy client defers frame work
        to first access, so stopping at ``predict()`` would undercharge
        the msgpack leg exactly the cost this stage exists to measure."""
        client = Client(
            "bench", port=port, use_bulk=True, batch_size=rows,
            use_columnar=columnar,
        )
        end = start + step * (rows * chunks)
        t0 = time.perf_counter()
        results = client.predict(str(start), str(end))
        samples = 0
        for res in results:
            if not res.ok:
                raise RuntimeError(
                    f"wire replay failed for {res.name}: "
                    f"{res.error_messages}"
                )
            if columnar:
                # lazy column access — no DataFrame on this path, which
                # IS the measured difference
                samples += int(
                    np.asarray(res.raw.column("tag-anomaly-scores")).size
                )
            else:
                frame = res.predictions
                n_tag_cols = sum(
                    1 for c in frame.columns
                    if c[0] == "tag-anomaly-scores"
                )
                samples += len(frame) * n_tag_cols
        dt = time.perf_counter() - t0
        if timed:
            log(f"serving_wire {'columnar' if columnar else 'msgpack'} "
                f"leg: {samples / dt:,.0f} samples/s "
                f"({samples:,} samples / {dt:.1f}s, {chunks} chunks)")
        return {
            "samples": samples,
            "seconds": dt,
            "samples_per_sec": samples / dt if dt > 0 else 0.0,
        }

    def value_identity(port: int) -> bool:
        """One slab, posted twice; the two wires must decode to the same
        fp32 BITS for every array and the same python floats."""
        n_tags = len((metadata.get("dataset") or {}).get("tag_list") or [])
        rng = np.random.default_rng(19)
        slab = rng.standard_normal(
            (min(rows, 512), max(1, n_tags))
        ).astype(np.float32)
        subset = names[: min(8, len(names))]
        body = codec.packb({"X": {n: slab for n in subset}})
        url = (
            f"http://127.0.0.1:{port}"
            "/gordo/v0/bench/_bulk/anomaly/prediction"
        )

        def post(accept: str) -> bytes:
            req = urllib.request.Request(
                url, data=body, method="POST",
                headers={
                    "Content-Type": codec.MSGPACK_CONTENT_TYPE,
                    "Accept": accept,
                },
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"wire identity -> {resp.status}")
                return resp.read()

        mp_data = codec.unpackb(post(codec.MSGPACK_CONTENT_TYPE))["data"]
        col_data = codec.decode_columnar(post(
            f"{codec.COLUMNAR_CONTENT_TYPE}, {codec.MSGPACK_CONTENT_TYPE}"
        ))["data"]
        if sorted(mp_data) != sorted(col_data):
            return False
        for name, ref in mp_data.items():
            got = col_data[name]
            if sorted(got) != sorted(ref):
                return False
            for key, val in ref.items():
                if isinstance(val, np.ndarray):
                    if got[key].dtype != val.dtype:
                        return False
                    if got[key].tobytes() != val.tobytes():
                        return False
                elif got[key] != val:
                    return False
        return True

    server = None
    try:
        port = free_port()
        server = spawn(port)
        wait_ready(port, 240.0)

        # identity first: it doubles as a codec-path warmup on both wires
        out["serving_wire_value_identity_ok"] = value_identity(port)

        # un-timed warmup legs land stacked-program compiles + budget-
        # shaped bodies for both wires
        leg(port, columnar=True, chunks=1, timed=False)
        leg(port, columnar=False, chunks=1, timed=False)

        col_best: "dict | None" = None
        mp_best: "dict | None" = None
        for _ in range(max(1, repeats)):
            c = leg(port, columnar=True, chunks=col_chunks, timed=True)
            m = leg(port, columnar=False, chunks=mp_chunks, timed=True)
            if col_best is None or c["samples_per_sec"] > col_best["samples_per_sec"]:
                col_best = c
            if mp_best is None or m["samples_per_sec"] > mp_best["samples_per_sec"]:
                mp_best = m

        col_sps = col_best["samples_per_sec"]
        mp_sps = mp_best["samples_per_sec"]
        out["serving_wire_machines"] = n_machines
        out["serving_wire_chunk_rows"] = rows
        out["serving_wire_columnar_chunks"] = col_chunks
        out["serving_wire_msgpack_chunks"] = mp_chunks
        out["serving_wire_columnar_samples_per_sec"] = round(col_sps)
        out["serving_wire_columnar_samples"] = col_best["samples"]
        out["serving_wire_columnar_seconds"] = round(col_best["seconds"], 3)
        out["serving_wire_msgpack_samples_per_sec"] = round(mp_sps)
        out["serving_wire_msgpack_samples"] = mp_best["samples"]
        out["serving_wire_msgpack_seconds"] = round(mp_best["seconds"], 3)
        out["serving_wire_speedup_vs_msgpack"] = (
            col_sps / mp_sps if mp_sps > 0 else 0.0
        )
        out["serving_wire_r18_baseline_samples_per_sec"] = (
            R18_BULK_REPLAY_SAMPLES_PER_SEC
        )
        out["serving_wire_vs_r18_baseline"] = round(
            col_sps / R18_BULK_REPLAY_SAMPLES_PER_SEC, 3
        )
        out["serving_wire_ge_3x_r18_ok"] = (
            col_sps >= 3.0 * R18_BULK_REPLAY_SAMPLES_PER_SEC
        )
        log(f"serving_wire gate: columnar {col_sps:,.0f}/s vs r18 "
            f"msgpack baseline {R18_BULK_REPLAY_SAMPLES_PER_SEC:,}/s -> "
            f"{col_sps / R18_BULK_REPLAY_SAMPLES_PER_SEC:.2f}x "
            f"(>= 3x: "
            f"{'PASS' if out['serving_wire_ge_3x_r18_ok'] else 'FAIL'}); "
            f"in-run msgpack {mp_sps:,.0f}/s -> "
            f"{out['serving_wire_speedup_vs_msgpack']:.2f}x")
        out["serving_wire_speedup_vs_msgpack"] = round(
            out["serving_wire_speedup_vs_msgpack"], 3
        )
    except Exception:
        for log_path in logs:
            try:
                with open(log_path) as fh:
                    tail = fh.read()[-2000:]
                if tail:
                    log(f"--- {log_path} tail ---\n{tail}")
            except OSError:
                pass
        raise
    finally:
        if server is not None:
            stop([server])
        shutil.rmtree(art_dir, ignore_errors=True)


#: BENCH_r18.json backfill_512_http_replay_samples_per_sec — the msgpack
#: bulk client-replay rate the r19 columnar wire is gated against
R18_BULK_REPLAY_SAMPLES_PER_SEC = 264367


def init_devices(attempts: int = 5, backoff_s: float = 2.0):
    """Initialize the jax backend with bounded retry.

    The TPU tunnel (axon PJRT plugin) intermittently fails init with
    UNAVAILABLE when another session holds the chip — the exact failure that
    cost round 1 its only perf number (BENCH_r01.json rc=1).  jax caches
    backend-init errors, so each retry clears backend state first.
    """
    import jax

    last_exc: Exception | None = None
    for attempt in range(attempts):
        try:
            devices = jax.devices()
            log(
                f"jax {jax.__version__} devices (attempt {attempt + 1}): "
                f"{[d.platform for d in devices]}"
            )
            return devices
        except Exception as exc:  # backend init failed — clear cache, retry
            last_exc = exc
            log(
                f"backend init attempt {attempt + 1}/{attempts} failed: {exc!r}"
            )
            if attempt == attempts - 1:
                break  # no retry follows; don't burn the deadline sleeping
            try:
                import jax.extend.backend

                jax.extend.backend.clear_backends()
            except Exception as clear_exc:
                log(f"clear_backends failed: {clear_exc!r}")
            time.sleep(backoff_s * (2**attempt))
    raise RuntimeError(
        f"jax backend init failed after {attempts} attempts: {last_exc!r}"
    )


def init_devices_bounded():
    """Backend init under a deadline: runs :func:`init_devices` in a side
    thread so an indefinite block inside ``jax.devices()`` (wedged axon
    relay grant) surfaces as a TimeoutError instead of hanging the bench."""
    box: dict = {}

    def target():
        try:
            box["devices"] = init_devices()
        except Exception as exc:
            box["error"] = exc

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(INIT_TIMEOUT_S)
    if t.is_alive():
        raise TimeoutError(
            f"jax backend init blocked for {INIT_TIMEOUT_S:.0f}s "
            "(axon tunnel wedge — relay grant likely stuck)"
        )
    if "error" in box:
        raise box["error"]
    return box["devices"]


def run_stage_bounded(
    name: str, fn, out: dict, budget_s: float
) -> bool:
    """Run one bench stage in a side thread with its own time budget.

    The TPU tunnel's observed failure mode mid-bench is an INDEFINITE block
    inside a device transfer (r4: the serving stage wedged after the builds
    finished, and the global watchdog threw away 3 whole stages' remaining
    budget waiting on it).  A stage that exceeds its budget is abandoned
    (its daemon thread may stay blocked on the wedged grant) and the next
    stage gets its chance; every stage writes its fields into ``out``
    incrementally, so whatever finished is in the emitted line either way.
    """
    if budget_s <= 0:
        # machine-readable even when an earlier stage already claimed
        # out["error"] (setdefault would no-op there)
        out.setdefault("stages_skipped", []).append(name)
        out.setdefault("error", f"{name} stage skipped: no budget left")
        log(f"stage {name}: skipped (no budget left)")
        return False
    box: dict = {}

    def target():
        try:
            fn()
        except Exception as exc:
            # log immediately: if this stage was already abandoned, nobody
            # reads box afterwards and the real cause (e.g. an OOM behind
            # an apparent "wedge") would vanish
            log(f"stage {name} raised: {exc!r}")
            box["error"] = exc

    t = threading.Thread(
        target=target, name=f"bench-{name}", daemon=True
    )
    t.start()
    t.join(budget_s)
    if t.is_alive():
        out.setdefault(
            "error",
            f"{name} stage exceeded {budget_s:.0f}s (tunnel wedge?)",
        )
        # the thread cannot be cancelled; if it is slow rather than wedged
        # it keeps running and CONTENDS with later stages — record that so
        # numbers measured after an abandonment are read as suspect
        out.setdefault("stages_abandoned", []).append(name)
        log(f"stage {name}: abandoned after {budget_s:.0f}s")
        return False
    if "error" in box:
        log(f"stage {name} failed: {box['error']!r}")
        out.setdefault("error", f"{name}: {box['error']}")
        return False
    return True


#: stage registry order == run order == metric priority (a mid-run wedge
#: costs the least important remaining numbers)
STAGES = ("build", "build_pipeline", "build_throughput", "build_ingest",
          "artifact_io", "hot_reload",
          "serving", "serving_precision", "serving_sharded",
          "serving_wire", "serving_openloop", "telemetry_overhead",
          "health_overhead", "cold_start", "multi_device", "refresh",
          "backfill", "scores_lifecycle", "streaming", "lstm")


def parse_cli(argv: "list[str]") -> "tuple[list[str], int | None]":
    """Parse ``(stages, round)`` from the CLI.

    ``--stage NAME`` (repeatable) selects a subset of STAGES to run, in
    canonical order; no ``--stage`` runs everything.  ``--round NN``
    (or the BENCH_ROUND env var) additionally persists the emitted
    result line to ``BENCH_rNN.json`` (atomic write; see
    :func:`persist_round`).  Side-effect-free so tests can exercise it
    without a jax import."""
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--stage", action="append", choices=STAGES, default=None,
        help="Run only the named stage(s); repeatable. Default: all. "
             "Per-stage results persist to BENCH_partial_<platform>.json "
             "either way, so partial runs still leave attestable numbers.",
    )
    p.add_argument(
        "--round", type=int, default=None,
        help="Round number NN: persist the emitted result line to "
             "BENCH_rNN.json (atomic tmp+rename; the run exits nonzero "
             "if the write fails). Defaults to $BENCH_ROUND when set.",
    )
    args = p.parse_args(argv)
    selected = args.stage or list(STAGES)
    rnd = args.round
    if rnd is None and os.environ.get("BENCH_ROUND"):
        rnd = int(os.environ["BENCH_ROUND"])
    return [s for s in STAGES if s in selected], rnd


def parse_stages(argv: "list[str]") -> "list[str]":
    """Back-compat wrapper: just the stage list from :func:`parse_cli`."""
    return parse_cli(argv)[0]


def main(argv: "list[str] | None" = None) -> None:
    """Run each bench stage independently; ALWAYS print exactly one JSON
    line, even on failure (a diagnostic record instead of a dead rc=1).

    Stage order tracks metric priority: the build headline first, the
    serving headline second, open-loop latency points, LSTM scenario last
    — a mid-run tunnel wedge costs the LEAST important remaining numbers,
    and each stage runs under its own budget so one stuck transfer can't
    starve the rest.
    """
    global _ROUND
    stages, _ROUND = parse_cli(sys.argv[1:] if argv is None else argv)
    t_start = time.monotonic()

    def remaining() -> float:
        return DEADLINE_S - (time.monotonic() - t_start)

    out: dict = {
        "metric": "per-tag anomaly-detector builds/hour/chip (full build path)",
        "value": None,
        "unit": "models/hour/chip",
        "vs_baseline": None,
        "n_machines": N_MACHINES,
    }
    if stages != list(STAGES):
        out["stages_selected"] = stages
    start_watchdog(out)
    try:
        devices = init_devices_bounded()
    except Exception as exc:
        line = cpu_fallback_line(remaining() - 60)
        if line is not None:
            try:
                doc = json.loads(line)
                doc["note"] = (
                    "TPU backend unavailable "
                    f"({type(exc).__name__}); CPU fallback run"
                )
                attach_tpu_partial(doc)
                line = json.dumps(doc)
            except Exception:
                pass  # emit the raw line rather than lose it
            emit_line(line)
            os._exit(exit_code())
        out["error"] = f"backend init: {exc}"
        emit_once(out)
        # init thread may still be wedged in jax.devices()
        os._exit(exit_code())

    from gordo_tpu.parallel.mesh import fleet_mesh

    n_chips = len(devices)
    out["n_chips"] = n_chips
    out["platform"] = devices[0].platform
    persist_partial(out)
    mesh = fleet_mesh(devices) if n_chips > 1 else None

    def build_stage():
        models_per_hour = bench_build(mesh, out)
        per_chip = models_per_hour / n_chips
        out["value"] = round(per_chip, 1)
        out["vs_baseline"] = round(
            per_chip / NORTH_STAR_MODELS_PER_HOUR_PER_CHIP, 3
        )

    # proportional budgets (not fixed offsets): whatever DEADLINE_S is,
    # the headline build stage gets the largest share of what's left at
    # its turn, and a short operator-set deadline shrinks every stage
    # instead of silently skipping the most important one.  Every stage
    # persists its partial results the moment it completes, so an
    # interrupted (or --stage-subsetted) run still leaves attestable
    # numbers in BENCH_partial_<platform>.json.
    stage_fns = {
        "build": (build_stage, lambda: remaining() * 0.6),
        "build_pipeline": (
            lambda: bench_build_pipeline(mesh, out),
            lambda: remaining() * 0.6,
        ),
        "build_throughput": (
            lambda: bench_build_throughput(mesh, out),
            lambda: remaining() * 0.6,
        ),
        "build_ingest": (
            lambda: bench_build_ingest(mesh, out),
            lambda: remaining() * 0.6,
        ),
        "artifact_io": (
            lambda: bench_artifact_io(out),
            lambda: min(remaining() * 0.7, 480),
        ),
        "hot_reload": (
            lambda: bench_hot_reload(out),
            lambda: min(remaining() * 0.7, 480),
        ),
        "serving": (
            lambda: bench_serving(out),
            lambda: min(remaining() * 0.7, 480),
        ),
        "serving_precision": (
            lambda: bench_serving_precision(out),
            lambda: min(remaining() * 0.7, 480),
        ),
        "serving_sharded": (
            lambda: bench_serving_sharded(out),
            lambda: min(remaining() * 0.7, 600),
        ),
        "serving_wire": (
            lambda: bench_serving_wire(out),
            lambda: min(remaining() * 0.8, 900),
        ),
        "serving_openloop": (
            lambda: bench_serving_openloop(out),
            lambda: min(remaining() * 0.7, 420),
        ),
        "telemetry_overhead": (
            lambda: bench_telemetry_overhead(out),
            lambda: min(remaining() * 0.7, 360),
        ),
        "health_overhead": (
            lambda: bench_health_overhead(out),
            lambda: min(remaining() * 0.7, 360),
        ),
        "cold_start": (
            lambda: bench_cold_start(out),
            lambda: min(remaining() * 0.7, 420),
        ),
        "multi_device": (
            lambda: bench_multi_device(out),
            lambda: min(remaining() * 0.8, 900),
        ),
        "refresh": (
            lambda: bench_refresh(out),
            lambda: min(remaining() * 0.8, 900),
        ),
        "backfill": (
            lambda: bench_backfill(out),
            lambda: min(remaining() * 0.8, 900),
        ),
        "scores_lifecycle": (
            lambda: bench_scores_lifecycle(out),
            lambda: min(remaining() * 0.8, 900),
        ),
        "streaming": (
            lambda: bench_streaming(out),
            lambda: min(remaining() * 0.7, 480),
        ),
        "lstm": (
            lambda: bench_lstm_build(mesh, out),
            lambda: remaining() - 30,
        ),
    }
    for name in stages:
        fn, budget = stage_fns[name]
        if run_stage_bounded(name, fn, out, budget()):
            out.setdefault("stages_done", []).append(name)
        persist_partial(out)

    emit_once(out)
    # abandoned stage threads may still be blocked on a wedged device
    # grant; a plain return would hang interpreter shutdown on their jax
    # finalizers
    sys.stdout.flush()
    os._exit(exit_code())


if __name__ == "__main__":
    # forked measurement child for bench_multi_device — dispatched before
    # main() so the parent's argparse (whose choices are STAGES) never
    # sees the child flags
    if "--scaleout-child" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--scaleout-child"]
        scaleout_child_main(argv)
    main()
