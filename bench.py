"""Driver benchmark: full fleet build throughput on the available chip(s).

Measures the north-star headline (`BASELINE.json`): per-tag anomaly-detector
builds per hour per chip — the COMPLETE build path (synthetic time-series
assembly, scaler stats, CV folds, threshold derivation, final fit, artifact
dump) via ``build_project``, i.e. measurement config 4 ("builder fan-out
from machine config").  Also measures the serving anomaly-scoring rate
(config 5) and reports it alongside.

Prints exactly ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``

``vs_baseline`` is measured models/hour/chip divided by the north-star
per-chip rate (10,000 models/h on 64 chips = 156.25 models/h/chip).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

#: north star: 10k models < 1h on v5e-64 → per-chip rate to match.
NORTH_STAR_MODELS_PER_HOUR_PER_CHIP = 10_000 / 64
NORTH_STAR_SAMPLES_PER_SEC_PER_CHIP = 100_000

N_MACHINES = int(os.environ.get("BENCH_MODELS", "512"))
N_TAGS = int(os.environ.get("BENCH_TAGS", "10"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_machines(n: int):
    from gordo_tpu.workflow.config import Machine

    # 4 days @ 10-min resolution ≈ 576 rows/machine, N_TAGS sine-mixture tags.
    return [
        Machine.from_config(
            {
                "name": f"bench-machine-{i:04d}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": [f"tag-{i:04d}-{j}" for j in range(N_TAGS)],
                },
            }
        )
        for i in range(n)
    ]


def bench_build(mesh) -> float:
    """Steady-state project-build rate in models/hour (in-process jit cache
    warm: run once to compile, time the second identical-shape run)."""
    from gordo_tpu.builder.fleet_build import build_project

    machines = make_machines(N_MACHINES)
    rates = []
    for run in range(2):
        out_dir = tempfile.mkdtemp(prefix="gordo-bench-")
        t0 = time.perf_counter()
        result = build_project(
            machines, out_dir, mesh=mesh, max_bucket_size=N_MACHINES
        )
        dt = time.perf_counter() - t0
        shutil.rmtree(out_dir, ignore_errors=True)
        n_ok = len(result.artifacts)
        if result.failed:
            log(f"WARNING: {len(result.failed)} builds failed: "
                f"{dict(list(result.failed.items())[:3])}")
        if n_ok == 0:
            raise RuntimeError("All builds failed")
        rates.append(n_ok / dt * 3600.0)
        log(f"build run {run}: {n_ok} machines in {dt:.2f}s "
            f"({rates[-1]:.0f} models/h)")
    return rates[-1]


def bench_serving() -> float:
    """Warm anomaly-scoring rate (sensor-samples/sec): max of the
    single-machine fused scorer and the stacked fleet scorer serving 64
    machines per dispatch (the project-stream scenario)."""
    from gordo_tpu.builder.build_model import build_model
    from gordo_tpu.serve.fleet_scorer import FleetScorer
    from gordo_tpu.serve.scorer import CompiledScorer

    machine = make_machines(1)[0]
    model, _ = build_model(
        machine.name, machine.model, machine.dataset, {}, machine.evaluation
    )
    rng = np.random.default_rng(0)

    scorer = CompiledScorer(model)
    X = rng.standard_normal((8192, N_TAGS)).astype(np.float32)
    scorer.anomaly_arrays(X, None)  # compile
    n_iter, t0 = 20, time.perf_counter()
    for _ in range(n_iter):
        scorer.anomaly_arrays(X, None)
    single = n_iter * X.size / (time.perf_counter() - t0)
    log(f"serving single: {single:,.0f} sensor-samples/s (fused={scorer.fused})")

    n_machines = 64
    fleet = FleetScorer.from_models(
        {f"m-{i:03d}": model for i in range(n_machines)}
    )
    X_by = {
        f"m-{i:03d}": rng.standard_normal((2048, N_TAGS)).astype(np.float32)
        for i in range(n_machines)
    }
    fleet.score_all(X_by)  # compile
    n_iter, t0 = 10, time.perf_counter()
    for _ in range(n_iter):
        fleet.score_all(X_by)
    stacked = n_iter * n_machines * 2048 * N_TAGS / (time.perf_counter() - t0)
    log(f"serving fleet-stacked ({n_machines} machines/dispatch): "
        f"{stacked:,.0f} sensor-samples/s")
    return max(single, stacked)


def main() -> None:
    import jax

    from gordo_tpu.parallel.mesh import fleet_mesh

    devices = jax.devices()
    n_chips = len(devices)
    log(f"jax {jax.__version__} devices: {[d.platform for d in devices]}")
    mesh = fleet_mesh(devices) if n_chips > 1 else None

    models_per_hour = bench_build(mesh)
    per_chip = models_per_hour / n_chips
    try:
        samples_per_sec = bench_serving()
    except Exception as exc:  # serving is the secondary metric
        log(f"serving bench failed: {exc}")
        samples_per_sec = None

    print(
        json.dumps(
            {
                "metric": "per-tag anomaly-detector builds/hour/chip (full build path)",
                "value": round(per_chip, 1),
                "unit": "models/hour/chip",
                "vs_baseline": round(
                    per_chip / NORTH_STAR_MODELS_PER_HOUR_PER_CHIP, 3
                ),
                "n_chips": n_chips,
                "n_machines": N_MACHINES,
                "serving_samples_per_sec_per_chip": (
                    None if samples_per_sec is None else round(samples_per_sec)
                ),
                "serving_vs_target": (
                    None
                    if samples_per_sec is None
                    else round(
                        samples_per_sec / NORTH_STAR_SAMPLES_PER_SEC_PER_CHIP, 3
                    )
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
