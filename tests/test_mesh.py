"""Placement plane (ISSUE 18): FleetMesh resolution, PlacementSpec
shardings, degenerate single-device behavior, pad-to-mesh policy, the
sharded warmup-manifest round-trip, and sharded-vs-single fleet-fit byte
parity.  Fast lane: conftest forces 8 virtual CPU devices, so sharded
cases run on device subsets without a fresh process."""

import json

import jax
import numpy as np
import pytest

from gordo_tpu.mesh import (
    DATA_AXIS,
    ENV_MESH_DEVICES,
    MODEL_AXIS,
    FleetMesh,
    PlacementSpec,
    fleet_mesh,
    model_sharding,
    pad_to_multiple,
    place,
    replicated_sharding,
)


class TestResolution:
    def test_default_takes_every_visible_device(self):
        fm = FleetMesh.resolve()
        assert fm.n_devices == len(jax.devices())
        assert fm.is_sharded and fm.mesh is not None
        assert fm.mesh.shape[MODEL_AXIS] == fm.n_devices
        assert fm.mesh.shape[DATA_AXIS] == 1

    def test_spec_narrows_to_first_n(self):
        fm = FleetMesh.resolve("2")
        assert fm.devices == tuple(jax.devices()[:2])
        assert fm.n_model_shards == 2

    def test_one_is_the_degenerate_sentinel(self):
        fm = FleetMesh.resolve("1")
        assert fm.mesh is None
        assert not fm.is_sharded
        assert fm.n_model_shards == 1
        assert fm.pad(7) == 7  # no mesh, no pad

    def test_env_var_is_the_default_spec(self, monkeypatch):
        monkeypatch.setenv(ENV_MESH_DEVICES, "2")
        assert FleetMesh.resolve().n_devices == 2
        # an explicit spec wins over the env
        assert FleetMesh.resolve("1").n_devices == 1

    def test_auto_and_all_mean_every_device(self, monkeypatch):
        monkeypatch.delenv(ENV_MESH_DEVICES, raising=False)
        for spec in ("auto", "all", "", None):
            assert FleetMesh.resolve(spec).n_devices == len(jax.devices())

    def test_over_ask_raises_with_visibility_hint(self):
        with pytest.raises(ValueError, match="only .* visible"):
            FleetMesh.resolve(str(len(jax.devices()) + 1))

    def test_garbage_specs_raise(self):
        for bad in ("banana", "0", "-2", "1.5"):
            with pytest.raises(ValueError):
                FleetMesh.resolve(bad)

    def test_data_parallel_must_divide(self):
        with pytest.raises(ValueError, match="does not divide"):
            FleetMesh.from_devices(jax.devices()[:3], data_parallel=2)

    def test_describe_is_json_able(self):
        doc = FleetMesh.resolve("4").describe()
        json.dumps(doc)
        assert doc["model_shards"] == 4 and doc["sharded"]
        assert doc["mesh_shape"] == {MODEL_AXIS: 4, DATA_AXIS: 1}
        assert FleetMesh.resolve("1").describe()["mesh_shape"] is None


class TestPadToMesh:
    def test_pad_to_multiple(self):
        assert pad_to_multiple(5, 4) == 8
        assert pad_to_multiple(8, 4) == 8
        assert pad_to_multiple(1, 4) == 4

    def test_ragged_fleet_pads_up_never_truncates(self):
        fm = FleetMesh.resolve("4")
        for m, want in ((1, 4), (3, 4), (4, 4), (5, 8), (9, 12)):
            assert fm.pad(m) == want

    def test_device_count_exceeding_fleet_still_places(self):
        """8 devices, 3 models: the stack pads to 8 and every device holds
        exactly one (possibly padded) model slot."""
        fm = FleetMesh.resolve()  # all 8 virtual devices
        m_pad = fm.pad(3)
        assert m_pad == 8
        arr = place(
            np.arange(m_pad * 2, dtype=np.float32).reshape(m_pad, 2),
            model_sharding(fm.mesh, 1),
        )
        shards = arr.addressable_shards
        assert len(shards) == 8
        assert sorted(s.device.id for s in shards) == list(range(8))
        for s in shards:
            assert s.data.shape == (1, 2)


class TestPlacement:
    def test_sharded_placement_attests_addressable_shards(self):
        fm = FleetMesh.resolve("4")
        x = np.arange(4 * 3, dtype=np.float32).reshape(4, 3)
        arr = place(x, model_sharding(fm.mesh, 1))
        assert len(arr.addressable_shards) == 4
        assert np.array_equal(np.asarray(arr), x)

    def test_replicated_placement_copies_everywhere(self):
        fm = FleetMesh.resolve("2")
        arr = place(np.float32(3.5), replicated_sharding(fm.mesh))
        assert len(arr.addressable_shards) == 2
        assert all(
            float(s.data) == 3.5 for s in arr.addressable_shards
        )

    def test_placement_spec_degenerates_to_none(self):
        spec = PlacementSpec(FleetMesh.resolve("1"))
        assert not spec.is_sharded
        assert spec.stacked() is None and spec.replicated() is None
        assert spec.tree({"a": np.zeros(3)}) is None
        assert spec.leaf(np.zeros((2, 2))) is None

    def test_placement_spec_tree_shards_leading_axis(self):
        fm = FleetMesh.resolve("2")
        spec = PlacementSpec(fm)
        tree = {"w": np.zeros((4, 3)), "b": np.zeros((4,))}
        sh = spec.tree(tree)
        for leaf in sh.values():
            assert leaf.spec[0] == MODEL_AXIS
        placed = place(tree, sh)
        assert len(placed["w"].addressable_shards) == 2

    def test_placement_counters(self):
        from gordo_tpu.telemetry import metrics as telemetry

        reg_c = telemetry.REGISTRY.get("gordo_fleet_placements_total")
        before_sharded = reg_c.value("sharded")
        before_single = reg_c.value("single")
        fm = FleetMesh.resolve("2")
        place(np.zeros((2, 2), np.float32), model_sharding(fm.mesh, 1))
        place(np.zeros((2, 2), np.float32))
        assert reg_c.value("sharded") == before_sharded + 1
        assert reg_c.value("single") == before_single + 1

    def test_mesh_devices_gauge_tracks_last_mesh(self):
        from gordo_tpu.telemetry import metrics as telemetry

        g = telemetry.REGISTRY.get("gordo_mesh_devices")
        FleetMesh.resolve("4")
        assert g.value() == 4.0
        FleetMesh.resolve("1")
        assert g.value() == 1.0


class TestWarmupManifestRoundTrip:
    def _entry(self, name):
        return [{"signature": f"sig-{name}", "machines": [name],
                 "n_machines": 1, "n_features": 2, "n_outputs": 2,
                 "lookback": 1}]

    def test_sharded_mesh_round_trips(self, tmp_path):
        from gordo_tpu.compile import (
            load_warmup_manifest,
            write_warmup_manifest,
        )

        out = str(tmp_path)
        mesh = fleet_mesh(jax.devices()[:2])
        write_warmup_manifest(out, self._entry("m1"), mesh=mesh)
        manifest = load_warmup_manifest(out)
        assert manifest["mesh"] == {
            "device_count": 2,
            "shape": {MODEL_AXIS: 2, DATA_AXIS: 1},
        }

    def test_pre_r22_manifest_reads_mesh_none(self, tmp_path):
        from gordo_tpu.compile import (
            load_warmup_manifest,
            write_warmup_manifest,
        )

        out = str(tmp_path)
        write_warmup_manifest(out, self._entry("m1"))
        assert load_warmup_manifest(out)["mesh"] is None

    def test_disagreeing_shards_read_mesh_none(self, tmp_path):
        from gordo_tpu.compile import (
            load_warmup_manifest,
            write_warmup_manifest,
        )

        out = str(tmp_path)
        write_warmup_manifest(
            out, self._entry("m1"), shard=(0, 2),
            mesh=fleet_mesh(jax.devices()[:2]),
        )
        write_warmup_manifest(
            out, self._entry("m2"), shard=(1, 2),
            mesh=fleet_mesh(jax.devices()[:4]),
        )
        assert load_warmup_manifest(out)["mesh"] is None


class TestShardedFitParity:
    """The acceptance bar: fp32 fleet fit over a real device mesh is
    byte-identical to the single-device path whenever each device holds
    at least TWO model slots.  A per-device block of exactly 1 model makes
    XLA:CPU collapse the unit leading axis and re-associate the per-model
    matmul FMAs — deterministic, but ~1 ULP off; pinned separately below
    so a silent change in either behavior is caught."""

    M, N, F = 8, 40, 4

    @pytest.fixture(scope="class")
    def module(self):
        from gordo_tpu.registry import lookup_factory

        return lookup_factory("AutoEncoder", "feedforward_hourglass")(
            n_features=self.F, n_features_out=self.F
        )

    @pytest.fixture()
    def data(self):
        rng = np.random.default_rng(7)
        X = rng.standard_normal((self.M, self.N, self.F)).astype(np.float32)
        w = np.ones((self.M, self.N), np.float32)
        return X, w

    def _fit(self, module, data, mesh):
        from gordo_tpu.train.fit import TrainConfig
        from gordo_tpu.parallel.fleet import fleet_fit

        X, w = data
        cfg = TrainConfig(epochs=2, batch_size=32)
        seeds = np.arange(self.M, dtype=np.uint32)
        return fleet_fit(module, X, X, w, cfg, seeds=seeds, mesh=mesh)

    @pytest.mark.parametrize("n_dev", [2, 4])
    def test_fit_bytes_match_single_device(self, module, data, n_dev):
        single = self._fit(module, data, None)
        sharded = self._fit(
            module, data, FleetMesh.resolve(str(n_dev)).mesh
        )
        assert np.array_equal(single.history, sharded.history)
        for a, b in zip(
            single.unstack_params(), sharded.unstack_params()
        ):
            for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                na, nb = np.asarray(la), np.asarray(lb)
                assert na.dtype == nb.dtype == np.float32
                assert na.tobytes() == nb.tobytes()

    def test_block_of_one_is_deterministic_and_ulp_close(
        self, module, data
    ):
        """8 models over 8 devices: one model per device.  Not byte-equal
        to single-device (XLA:CPU unit-dim codegen), but run-to-run
        deterministic and within float32 ULP noise of it."""
        mesh = FleetMesh.resolve("8").mesh
        single = self._fit(module, data, None)
        a = self._fit(module, data, mesh)
        b = self._fit(module, data, mesh)
        assert np.array_equal(a.history, b.history)
        np.testing.assert_allclose(
            single.history, a.history, rtol=1e-5, atol=1e-6
        )


class TestMeshCLIAndIndexDoc:
    def test_mesh_info_cli_reports_devices_and_shape(self):
        from click.testing import CliRunner

        from gordo_tpu.cli.cli import gordo

        res = CliRunner().invoke(
            gordo, ["mesh", "info", "--mesh-devices", "2"]
        )
        assert res.exit_code == 0, res.output
        doc = json.loads(res.output)
        assert doc["n_devices"] == 2
        assert doc["mesh_shape"] == {MODEL_AXIS: 2, DATA_AXIS: 1}

    def test_mesh_info_cli_rejects_over_ask(self):
        from click.testing import CliRunner

        from gordo_tpu.cli.cli import gordo

        res = CliRunner().invoke(
            gordo,
            ["mesh", "info", "--mesh-devices", str(len(jax.devices()) + 1)],
        )
        assert res.exit_code != 0
        assert "visible" in res.output

    def test_project_index_mesh_doc(self):
        from gordo_tpu.serve.server import _mesh_doc

        assert _mesh_doc(None) == {
            "device-count": 1, "shape": None, "sharded": False,
        }
        doc = _mesh_doc(fleet_mesh(jax.devices()[:2]))
        assert doc == {
            "device-count": 2,
            "shape": {MODEL_AXIS: 2, DATA_AXIS: 1},
            "sharded": True,
        }
