"""Fleet-sharded serving tier tests: the one shard function (client-
computed == server-owned, every N), misroute 421s, scatter-gather
reassembly, overload shedding (429 + Retry-After, honored by the
client), warmup subsetting, the generator's sharded Deployments/HPA,
watchman's topology republish, and the serve-path shard lint gate.
The 2-replica sharded-vs-single byte-parity suite is slow-lane."""

import asyncio
import importlib.util
import json
import os
import time

import numpy as np
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from gordo_tpu.builder import build_project
from gordo_tpu.serve import ModelCollection, build_app
from gordo_tpu.serve.shard import (
    ShardRouter,
    ShardSpec,
    owned_names,
    shard_map,
    shard_slices,
)
from gordo_tpu.workflow import NormalizedConfig

MACHINES = [f"sh-{c}" for c in "abcdef"]

PROJECT = {
    "machines": [
        {"name": name, "dataset": {
            "type": "RandomDataset",
            "tags": ["s-1", "s-2"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-26T06:00:00Z",
        }}
        for name in MACHINES
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {"gordo_tpu.models.estimator.AutoEncoder": {
                                "kind": "feedforward_hourglass",
                                "epochs": 1,
                                "batch_size": 64,
                            }},
                        ]
                    }
                }
            }
        }
    },
}

X_ROWS = [[0.2, 0.7]] * 32


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("shard-artifacts")
    cfg = NormalizedConfig(PROJECT, "shardproj")
    result = build_project(cfg.machines, str(out))
    assert not result.failed
    return str(out)


# ---------------------------------------------------------------------------
# the shard function itself
# ---------------------------------------------------------------------------

class TestShardFunction:
    def test_deterministic_disjoint_exhaustive(self):
        import random

        names = [f"m-{i:03d}" for i in range(23)]
        shuffled = names[:]
        random.Random(7).shuffle(shuffled)
        for count in range(1, 6):
            a = shard_slices(names, count)
            assert a == shard_slices(shuffled, count)  # order-independent
            flat = [n for shard in a for n in shard]
            assert sorted(flat) == sorted(names)       # exhaustive
            assert len(flat) == len(set(flat))         # disjoint
            assert len(a) == count

    def test_spec_parse_and_env(self, monkeypatch):
        assert ShardSpec.parse("1/4") == ShardSpec(1, 4)
        for bad in ("4/4", "-1/2", "x/2", "2", ""):
            with pytest.raises(ValueError):
                ShardSpec.parse(bad)
        monkeypatch.setenv("GORDO_SERVE_SHARD", "2/3")
        assert ShardSpec.from_env() == ShardSpec(2, 3)
        monkeypatch.delenv("GORDO_SERVE_SHARD")
        assert ShardSpec.from_env() is None

    def test_router_split_preserves_input_order(self):
        names = [f"m-{i}" for i in range(8)]
        router = ShardRouter(names, ["http://a", "http://b"])
        req = ["m-7", "m-0", "m-5", "m-1"]
        plan = router.split(req)
        reassembled = {n for members in plan.values() for n in members}
        assert reassembled == set(req)
        for url, members in plan.items():
            assert members == [n for n in req if router.url_for(n) == url]


# ---------------------------------------------------------------------------
# server-side shard loading
# ---------------------------------------------------------------------------

class TestServerSharding:
    @pytest.mark.parametrize("count", [2, 3, 4, 5])
    def test_client_computed_equals_server_owned(self, model_dir, count):
        """The acceptance contract: for every machine, the shard the
        CLIENT computes locally is the shard whose SERVER actually loaded
        that machine — across N=2..5."""
        table = shard_map(MACHINES, count)
        seen = {}
        for index in range(count):
            coll = ModelCollection.from_directory(
                model_dir, project="shardproj",
                shard=ShardSpec(index, count),
            )
            assert sorted(coll.entries) == owned_names(
                MACHINES, ShardSpec(index, count)
            )
            assert coll.fleet_machines == sorted(MACHINES)
            for name in coll.entries:
                assert table[name] == index  # client table agrees
                seen[name] = index
        assert sorted(seen) == sorted(MACHINES)  # disjoint + exhaustive

    def test_shard_from_env(self, model_dir, monkeypatch):
        monkeypatch.setenv("GORDO_SERVE_SHARD", "0/2")
        coll = ModelCollection.from_directory(model_dir, project="shardproj")
        assert coll.shard == ShardSpec(0, 2)
        assert sorted(coll.entries) == owned_names(MACHINES, ShardSpec(0, 2))

    def test_misrouted_request_is_421_with_owner(self, model_dir):
        spec = ShardSpec(0, 2)
        foreign = owned_names(MACHINES, ShardSpec(1, 2))[0]

        async def fn():
            coll = ModelCollection.from_directory(
                model_dir, project="shardproj", shard=spec
            )
            client = TestClient(TestServer(build_app(coll)))
            await client.start_server()
            try:
                misrouted = await client.get(
                    f"/gordo/v0/shardproj/{foreign}/healthcheck"
                )
                unknown = await client.get(
                    "/gordo/v0/shardproj/not-a-machine/healthcheck"
                )
                owned = await client.get(
                    f"/gordo/v0/shardproj/{sorted(coll.entries)[0]}"
                    "/healthcheck"
                )
                body = await misrouted.json()
                index = await client.get("/gordo/v0/shardproj/")
                return (
                    misrouted.status, unknown.status, owned.status,
                    body, await index.json(),
                )
            finally:
                await client.close()

        mis, unk, own, body, index = asyncio.run(fn())
        assert (mis, unk, own) == (421, 404, 200)
        assert body["shard"] == 1 and body["shard-count"] == 2
        # the routing-topology surface clients compute the table from
        assert index["serve-shard"] == {"index": 0, "count": 2}
        assert index["fleet-machines"] == sorted(MACHINES)
        assert isinstance(index["fleet-generation"], int)
        assert index["machines"] == owned_names(MACHINES, spec)

    def test_warmup_filters_manifest_to_shard(self, model_dir):
        from gordo_tpu.compile import (
            filter_manifest,
            load_warmup_manifest,
            warmup_collection,
        )

        manifest = load_warmup_manifest(model_dir)
        assert manifest is not None
        sub = filter_manifest(manifest, {"sh-a", "sh-b"})
        for entry in sub["programs"]:
            assert set(entry["machines"]) <= {"sh-a", "sh-b"}
            assert entry["n_machines"] == len(entry["machines"])
        assert sub["row_buckets"] == manifest["row_buckets"]

        coll = ModelCollection.from_directory(
            model_dir, project="shardproj", shard=ShardSpec(0, 3)
        )
        stats = warmup_collection(coll)
        assert stats["shard"] == "0/3"
        assert stats["errors"] == 0


# ---------------------------------------------------------------------------
# overload shedding
# ---------------------------------------------------------------------------

class TestOverloadShedding:
    def _post(self, model_dir, prime):
        """Build a coalescing app, let ``prime(coalescer)`` set policy
        state, POST one anomaly request, return (status, headers, body)."""

        async def fn():
            coll = ModelCollection.from_directory(
                model_dir, project="shardproj"
            )
            app = build_app(coll, coalesce_window_ms=2.0)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                from gordo_tpu.serve.server import COALESCER_KEY

                prime(app[COALESCER_KEY])
                resp = await client.post(
                    "/gordo/v0/shardproj/sh-a/anomaly/prediction",
                    json={"X": X_ROWS},
                )
                return resp.status, dict(resp.headers), await resp.json()
            finally:
                await client.close()

        return asyncio.run(fn())

    def test_escalated_standdown_sheds_429_with_retry_after(self, model_dir):
        def prime(coalescer):
            # second consecutive stand-down = the first cooldown doubling:
            # the escalation threshold where queuing turns into shedding
            coalescer._standdown_streak = 2
            coalescer._standdown_until = time.monotonic() + 4.0
            coalescer.last_wait_p99 = 2.5

        status, headers, body = self._post(model_dir, prime)
        assert status == 429
        retry_after = int(headers["Retry-After"])
        # derived from the observed queue wait / remaining cooldown,
        # never a blind constant below either
        assert retry_after >= 2
        assert body["retry-after-seconds"] >= 2.5
        assert "overloaded" in body["error"]

    def test_first_standdown_does_not_shed(self, model_dir):
        def prime(coalescer):
            coalescer._standdown_streak = 1  # transient: route direct
            coalescer._standdown_until = time.monotonic() + 4.0

        status, _, body = self._post(model_dir, prime)
        assert status == 200
        assert "model-output" in body["data"]

    def test_stats_and_gauges_expose_shedding(self, model_dir):
        from gordo_tpu.serve import coalesce as coalesce_mod

        coalescer = coalesce_mod.CoalescingScorer(lambda: None)
        try:
            assert coalesce_mod.stats(coalescer)["shedding"] is False
            coalescer._standdown_streak = 2
            coalescer._standdown_until = time.monotonic() + 2.0
            coalescer.last_wait_p99 = 0.2
            stats = coalesce_mod.stats(coalescer)
            assert stats["shedding"] is True
            ra = coalesce_mod.shed_retry_after(coalescer)
            assert 1.0 <= ra <= coalesce_mod.SHED_RETRY_MAX_S
        finally:
            coalescer.close()


class TestClientHonorsRetryAfter:
    def _run(self, handler, **kw):
        """Drive ``client.io.request_json`` against an in-process endpoint,
        recording every retry sleep."""
        from gordo_tpu.client import io as client_io

        sleeps = []
        real_sleep = asyncio.sleep

        async def recording_sleep(delay, *a, **k):
            sleeps.append(delay)
            await real_sleep(0)

        async def fn():
            app = web.Application()
            app.router.add_post("/score", handler)
            server = TestServer(app)
            await server.start_server()
            orig = client_io.asyncio.sleep
            client_io.asyncio.sleep = recording_sleep
            try:
                import aiohttp

                async with aiohttp.ClientSession() as session:
                    return await client_io.post_json(
                        session, str(server.make_url("/score")), {"x": 1},
                        **kw,
                    )
            finally:
                client_io.asyncio.sleep = orig
                await server.close()

        return asyncio.run(fn()), sleeps

    def test_retry_after_replaces_backoff_capped(self):
        calls = []

        async def handler(request):
            calls.append(1)
            if len(calls) == 1:
                return web.json_response(
                    {"error": "overloaded"}, status=429,
                    headers={"Retry-After": "7"},
                )
            return web.json_response({"ok": True})

        body, sleeps = self._run(handler, retries=3, backoff=0.01)
        assert body == {"ok": True}
        # 7s honored but capped at the schedule's max sleep (0.01 * 2^2)
        assert sleeps == [pytest.approx(0.04)]

    def test_small_retry_after_wins_over_backoff(self):
        calls = []

        async def handler(request):
            calls.append(1)
            if len(calls) == 1:
                return web.json_response(
                    {"error": "warming"}, status=503,
                    headers={"Retry-After": "0"},
                )
            return web.json_response({"ok": True})

        body, sleeps = self._run(handler, retries=3, backoff=0.5)
        assert body == {"ok": True}
        assert sleeps == [0.0]  # the server said "now"; not 0.5s

    def test_no_header_keeps_jittered_exponential_schedule(self):
        calls = []

        async def handler(request):
            calls.append(1)
            if len(calls) < 3:
                return web.json_response({"error": "boom"}, status=503)
            return web.json_response({"ok": True})

        body, sleeps = self._run(handler, retries=3, backoff=0.01)
        assert body == {"ok": True}
        # full jitter: every delay is uniform over [0, backoff * 2^attempt]
        # (a deterministic schedule synchronizes a replica's whole client
        # population into retry waves; docs/operations.md)
        assert len(sleeps) == 2
        assert 0.0 <= sleeps[0] <= 0.01
        assert 0.0 <= sleeps[1] <= 0.02


# ---------------------------------------------------------------------------
# scatter-gather across real sharded replicas
# ---------------------------------------------------------------------------

async def _start_replicas(model_dir, count):
    """N sharded TestServers + one unsharded, all over the same build."""
    replicas = []
    for index in range(count):
        coll = ModelCollection.from_directory(
            model_dir, project="shardproj", shard=ShardSpec(index, count)
        )
        client = TestClient(TestServer(build_app(coll)))
        await client.start_server()
        replicas.append(client)
    single_coll = ModelCollection.from_directory(
        model_dir, project="shardproj"
    )
    single = TestClient(TestServer(build_app(single_coll)))
    await single.start_server()
    return replicas, single


@pytest.mark.slow
@pytest.mark.parametrize("wire", ["msgpack", "columnar"])
def test_scatter_gather_byte_parity_and_order(model_dir, wire):
    """2-replica bulk scoring must return BYTE-identical arrays to the
    single process, reassembled in the original machine order (the slow-
    lane parity pin of the sharded tier) — on both the msgpack wire and
    the r19 GSB1 columnar wire."""
    from gordo_tpu.serve import codec

    rng = np.random.default_rng(5)
    X_by = {
        name: rng.standard_normal((64, 2)).astype(np.float32)
        for name in sorted(MACHINES, reverse=True)  # non-sorted order
    }

    async def fn():
        replicas, single = await _start_replicas(model_dir, 2)
        try:
            urls = [str(r.server.make_url("")) for r in replicas]
            router = ShardRouter(MACHINES, urls)
            plan = router.split(X_by)
            # scatter concurrently; both wires ship raw array bytes
            if wire == "columnar":
                accept = (
                    f"{codec.COLUMNAR_CONTENT_TYPE}, "
                    f"{codec.MSGPACK_CONTENT_TYPE}"
                )
            else:
                accept = codec.MSGPACK_CONTENT_TYPE
            headers = {
                "Content-Type": codec.MSGPACK_CONTENT_TYPE,
                "Accept": accept,
            }

            async def decode(resp):
                if wire == "columnar":
                    assert (
                        resp.content_type == codec.COLUMNAR_CONTENT_TYPE
                    )
                    return codec.decode_columnar(await resp.read())
                return codec.unpackb(await resp.read())

            async def post(client, members):
                resp = await client.post(
                    "/gordo/v0/shardproj/_bulk/anomaly/prediction",
                    data=codec.packb(
                        {"X": {m: X_by[m] for m in members}}
                    ),
                    headers=headers,
                )
                assert resp.status == 200
                return (await decode(resp))["data"]

            parts = await asyncio.gather(*(
                post(replicas[urls.index(u)], members)
                for u, members in plan.items()
            ))
            gathered = {}
            for part in parts:
                gathered.update(part)
            sharded = {m: gathered[m] for m in X_by}  # machine order

            resp = await single.post(
                "/gordo/v0/shardproj/_bulk/anomaly/prediction",
                data=codec.packb({"X": X_by}),
                headers=headers,
            )
            assert resp.status == 200
            single_out = (await decode(resp))["data"]
            return sharded, single_out
        finally:
            for r in replicas:
                await r.close()
            await single.close()

    sharded, single_out = asyncio.run(fn())
    assert list(sharded) == list(X_by)  # original machine order
    assert sorted(single_out) == sorted(sharded)
    for name in X_by:
        for key, value in single_out[name].items():
            got = sharded[name][key]
            if isinstance(value, np.ndarray):
                assert got.dtype == value.dtype, (name, key)
                assert np.array_equal(got, value), (name, key)
            else:
                assert got == value, (name, key)


@pytest.mark.slow
def test_client_routes_and_unions_across_replicas(model_dir):
    """The bundled Client against a 2-replica tier: machine discovery
    unions the shards, metadata requests route to the owning replica
    (no 421s), and the lazily-built router matches the shared table."""
    from gordo_tpu.client import Client

    async def fn():
        replicas, single = await _start_replicas(model_dir, 2)
        try:
            urls = [str(r.server.make_url("")) for r in replicas]
            client = Client("shardproj", replica_urls=urls)
            import aiohttp

            async with aiohttp.ClientSession() as session:
                await client._ensure_router(session)
                names = await client.machine_names_async(session)
                metas = {
                    n: await client.machine_metadata_async(session, n)
                    for n in names
                }
            table = shard_map(MACHINES, 2)
            for name in MACHINES:
                assert client._router.url_for(name) == urls[table[name]]
            return names, metas
        finally:
            for r in replicas:
                await r.close()
            await single.close()

    names, metas = asyncio.run(fn())
    assert sorted(names) == sorted(MACHINES)
    for name, meta in metas.items():
        assert meta["name"] == name


@pytest.mark.slow
def test_rescan_routes_new_machine_to_its_owner(model_dir, tmp_path):
    """A machine built AFTER startup lands on exactly its owning shard
    at the next rescan; the other replica learns it fleet-wide (421,
    not 404) without loading it."""
    import shutil

    live_dir = str(tmp_path / "live")
    shutil.copytree(model_dir, live_dir)
    colls = [
        ModelCollection.from_directory(
            live_dir, project="shardproj", shard=ShardSpec(i, 2)
        )
        for i in range(2)
    ]
    new_name = "sh-zz-late"
    project = {
        "machines": [dict(PROJECT["machines"][0], name=new_name)],
        "globals": PROJECT["globals"],
    }
    result = build_project(
        NormalizedConfig(project, "shardproj").machines, live_dir
    )
    assert not result.failed
    for coll in colls:
        coll.rescan()
    fleet = sorted(MACHINES + [new_name])
    owner = shard_map(fleet, 2)[new_name]
    for i, coll in enumerate(colls):
        assert coll.fleet_machines == fleet
        assert (new_name in coll.entries) == (i == owner)
        assert coll.shard_owner[new_name] == owner


# ---------------------------------------------------------------------------
# generator + watchman surfaces
# ---------------------------------------------------------------------------

class TestGeneratorShardedTier:
    def _config(self):
        return NormalizedConfig(PROJECT, "shardproj")

    def test_sharded_deployments_services_hpa(self):
        from gordo_tpu.workflow import generate_workflow

        docs = generate_workflow(self._config(), serve_shards=2)
        deploys = {
            d["metadata"]["name"]: d for d in docs
            if d["kind"] == "Deployment"
            and "server" in d["metadata"]["name"]
        }
        assert sorted(deploys) == [
            "gordo-server-shardproj-shard-0",
            "gordo-server-shardproj-shard-1",
        ]
        for i, (_, dep) in enumerate(sorted(deploys.items())):
            env = dep["spec"]["template"]["spec"]["containers"][0]["env"]
            assert {"name": "GORDO_SERVE_SHARD", "value": f"{i}/2"} in env
        hpas = [
            d for d in docs
            if d["kind"] == "HorizontalPodAutoscaler"
        ]
        assert len(hpas) == 2
        for hpa in hpas:
            metric = hpa["spec"]["metrics"][0]["pods"]["metric"]["name"]
            assert metric == "gordo_coalesce_wait_service_ratio"
        services = {
            d["metadata"]["name"] for d in docs if d["kind"] == "Service"
        }
        assert "gordo-ml-server-shard-0-shardproj" in services
        assert "gordo-ml-server-shard-1-shardproj" in services

    def test_mappings_route_to_owning_shard(self):
        from gordo_tpu.workflow import generate_workflow

        docs = generate_workflow(self._config(), serve_shards=2)
        table = shard_map(MACHINES, 2)
        mappings = [
            d for d in docs
            if d["kind"] == "Mapping"
            and "stream" not in d["metadata"]["name"]
        ]
        assert len(mappings) == len(MACHINES)
        for mapping in mappings:
            machine = mapping["spec"]["prefix"].rstrip("/").split("/")[-1]
            expected = (
                f"gordo-ml-server-shard-{table[machine]}-shardproj:5555"
            )
            assert mapping["spec"]["service"] == expected

    def test_stream_routes_per_shard_plus_merged(self):
        """Streams are per-replica state, so each shard gets its own
        SSE-safe Mapping (prefix carries the shard, rewrite drops it);
        the merged read-only view routes to the watchman relay."""
        from gordo_tpu.workflow import generate_workflow

        docs = generate_workflow(self._config(), serve_shards=2)
        streams = {
            d["metadata"]["name"]: d for d in docs
            if d["kind"] == "Mapping"
            and "stream" in d["metadata"]["name"]
        }
        assert sorted(streams) == [
            "gordo-mapping-shardproj-stream-merged",
            "gordo-mapping-shardproj-stream-shard-0",
            "gordo-mapping-shardproj-stream-shard-1",
        ]
        for i in range(2):
            spec = streams[
                f"gordo-mapping-shardproj-stream-shard-{i}"
            ]["spec"]
            assert spec["prefix"] == (
                f"/gordo/v0/shardproj/shard-{i}/stream"
            )
            assert spec["rewrite"] == "/gordo/v0/shardproj/stream"
            assert spec["service"] == (
                f"gordo-ml-server-shard-{i}-shardproj:5555"
            )
            assert spec["timeout_ms"] == 0
            assert spec["idle_timeout_ms"] == 86_400_000
        merged = streams["gordo-mapping-shardproj-stream-merged"]["spec"]
        assert merged["prefix"] == "/gordo/v0/shardproj/stream/merged"
        assert merged["rewrite"] == "/stream"
        assert "watchman" in merged["service"]
        assert merged["timeout_ms"] == 0
        # every shard Service fronts long-lived connections
        for svc in (d for d in docs if d["kind"] == "Service"):
            annotations = svc["metadata"]["annotations"]
            assert (
                "service.beta.kubernetes.io/"
                "aws-load-balancer-connection-idle-timeout"
            ) in annotations

    def test_watchman_targets_every_shard(self):
        from gordo_tpu.workflow import generate_workflow

        docs = generate_workflow(self._config(), serve_shards=3)
        watchman = next(
            d for d in docs
            if d["kind"] == "Deployment"
            and "watchman" in d["metadata"]["name"]
        )
        args = watchman["spec"]["template"]["spec"]["containers"][0]["args"]
        targets = [args[i + 1] for i, a in enumerate(args) if a == "--target"]
        assert targets == [
            f"http://gordo-ml-server-shard-{i}-shardproj:5555"
            for i in range(3)
        ]

    def test_refuses_more_shards_than_machines(self):
        from gordo_tpu.workflow import generate_workflow

        with pytest.raises(ValueError, match="exceeds the project's"):
            generate_workflow(self._config(), serve_shards=7)

    def test_unsharded_output_unchanged(self):
        from gordo_tpu.workflow import generate_workflow

        docs = generate_workflow(self._config())
        assert not any(
            d["kind"] == "HorizontalPodAutoscaler" for d in docs
        )
        deploys = [
            d["metadata"]["name"] for d in docs if d["kind"] == "Deployment"
        ]
        assert "gordo-server-shardproj" in deploys


def test_watchman_republishes_shard_topology(model_dir):
    """Watchman's status document (and /metrics) must carry each target's
    shard index + fleet generation — the one-endpoint routing-topology
    view of the tier."""
    from gordo_tpu.watchman import Watchman, build_watchman_app

    async def fn():
        replicas, single = await _start_replicas(model_dir, 2)
        try:
            urls = [str(r.server.make_url("")) for r in replicas]
            watchman = Watchman(
                "shardproj", machines=[], target_base_urls=urls,
                poll_interval=3600,
            )
            wm_client = TestClient(
                TestServer(build_watchman_app(watchman))
            )
            await wm_client.start_server()
            try:
                await watchman.refresh()
                body = await (await wm_client.get("/")).json()
                metrics = await (await wm_client.get("/metrics")).text()
                return urls, body, metrics
            finally:
                await wm_client.close()
        finally:
            for r in replicas:
                await r.close()
            await single.close()

    urls, body, metrics = asyncio.run(fn())
    topo = body["serve-topology"]
    assert set(topo) == set(urls)
    for i, url in enumerate(urls):
        assert topo[url]["shard-index"] == i
        assert topo[url]["shard-count"] == 2
        assert topo[url]["fleet-generation"] > 0
        assert topo[url]["machines"] == owned_names(
            MACHINES, ShardSpec(i, 2)
        )
    assert "gordo_watchman_target_shard_index" in metrics
    assert "gordo_watchman_target_fleet_generation" in metrics


# ---------------------------------------------------------------------------
# lint gate
# ---------------------------------------------------------------------------

class TestShardLintGate:
    @staticmethod
    def _lint(path):
        spec = importlib.util.spec_from_file_location(
            "gordo_lint", os.path.join(
                os.path.dirname(os.path.dirname(__file__)),
                "scripts", "lint.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.lint_file(path)

    def test_partition_machines_rejected_on_serve_path(self, tmp_path):
        bad = tmp_path / "gordo_tpu" / "client" / "thing.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "from gordo_tpu.distributed.partition import "
            "partition_machines\n"
            "def route(ms):\n    return partition_machines(ms, 2)\n"
        )
        msgs = [f[2] for f in self._lint(str(bad))]
        assert any("gordo_tpu.serve.shard" in m for m in msgs)

    def test_adhoc_shard_modulo_rejected(self, tmp_path):
        bad = tmp_path / "gordo_tpu" / "watchman" / "thing.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "def owner(name, n_shards):\n"
            "    return hash(name) % n_shards\n"
        )
        msgs = [f[2] for f in self._lint(str(bad))]
        assert any("ad-hoc shard arithmetic" in m for m in msgs)

    def test_shard_module_and_serve_path_clean(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in (
            os.path.join("gordo_tpu", "serve", "shard.py"),
            os.path.join("gordo_tpu", "serve", "server.py"),
            os.path.join("gordo_tpu", "client", "client.py"),
            os.path.join("gordo_tpu", "watchman", "server.py"),
            os.path.join("gordo_tpu", "workflow", "generator.py"),
        ):
            assert self._lint(os.path.join(repo, rel)) == [], rel


def test_index_json_stays_parseable(model_dir):
    """Guard: the sharded index additions stay JSON-serializable (ints,
    lists — no numpy leakage through fleet-generation)."""
    coll = ModelCollection.from_directory(
        model_dir, project="shardproj", shard=ShardSpec(0, 2)
    )
    json.dumps({
        "generation": coll.generation,
        "fleet": coll.fleet_machines,
        "owner": coll.shard_owner,
    })
