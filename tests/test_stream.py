"""Streaming scoring plane tests (``gordo_tpu/serve/stream.py``).

Three layers, mirroring the subsystem:

* unit — event ring / subscriber fan-out / SSE framing / env knobs
  (fast lane);
* numerical — the acceptance pin: incremental carried-state verdicts
  byte-identical (fp32) to re-scoring the full lookback at every
  steady-state step, for all three window modes, and ACROSS a
  generation flip mid-stream (slow lane — fits real models);
* integration — ingest/subscribe routes, Last-Event-ID resume,
  threshold events, shard misroute contract, the client iterator, and
  the watchman re-fan relay (fast lane — rides one small real build).
"""

import asyncio
import threading

import numpy as np
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

import gordo_tpu.models.factories  # noqa: F401 — register model kinds
from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector
from gordo_tpu.builder import build_project
from gordo_tpu.models.estimator import (
    AutoEncoder,
    LSTMAutoEncoder,
    LSTMForecast,
)
from gordo_tpu.ops.scalers import MinMaxScaler
from gordo_tpu.pipeline import Pipeline
from gordo_tpu.serve import ModelCollection, build_app
from gordo_tpu.serve import stream as stream_mod
from gordo_tpu.serve.scorer import CompiledScorer
from gordo_tpu.serve.shard import ShardSpec, shard_map
from gordo_tpu.serve.stream import (
    EventRing,
    MachineStream,
    StreamHub,
    StreamUnsupported,
    reference_verdict,
    sse_format,
    warm_stream_program,
)
from gordo_tpu.workflow import NormalizedConfig


# ---------------------------------------------------------------------------
# unit: ring / subscribers / framing
# ---------------------------------------------------------------------------


class TestEventRing:
    def test_monotonic_ids_and_since(self):
        ring = EventRing(maxlen=16)
        for i in range(5):
            ring.append("verdict", {"machine": "m", "n": i})
        assert ring.last_id == 5
        events, gap = ring.since(2)
        assert [e["id"] for e in events] == [3, 4, 5]
        assert not gap

    def test_machine_filter(self):
        ring = EventRing(maxlen=16)
        ring.append("verdict", {"machine": "a"})
        ring.append("verdict", {"machine": "b"})
        events, _ = ring.since(0, machines={"b"})
        assert [e["data"]["machine"] for e in events] == ["b"]

    def test_replay_gap_when_trimmed(self):
        ring = EventRing(maxlen=4)
        for i in range(10):
            ring.append("verdict", {"n": i})
        events, gap = ring.since(2)  # ids 3..6 were trimmed
        assert gap
        assert [e["id"] for e in events] == [7, 8, 9, 10]
        # resuming from the head is never a gap
        _, gap = ring.since(10)
        assert not gap

    def test_fresh_ring_no_gap(self):
        ring = EventRing(maxlen=4)
        _, gap = ring.since(0)
        assert not gap


class TestHubFanout:
    def test_publish_fans_to_matching_subscribers(self):
        hub = StreamHub()
        all_sub = hub.subscribe()
        only_b = hub.subscribe(["b"])
        hub.publish("verdict", {"machine": "a"})
        hub.publish("verdict", {"machine": "b"})
        assert all_sub.queue.qsize() == 2
        assert only_b.queue.qsize() == 1
        assert only_b.queue.get_nowait()["data"]["machine"] == "b"
        hub.unsubscribe(all_sub)
        hub.unsubscribe(only_b)
        assert hub.n_subscribers == 0

    def test_slow_consumer_marked_dead_on_overflow(self):
        hub = StreamHub()
        sub = hub.subscribe(maxsize=2)
        for i in range(4):
            hub.publish("verdict", {"machine": "m", "n": i})
        assert sub.dead
        # the ring kept everything the queue could not
        events, gap = hub.ring.since(0)
        assert len(events) == 4 and not gap

    def test_dead_subscriber_skipped(self):
        hub = StreamHub()
        sub = hub.subscribe()
        sub.dead = True
        hub.publish("verdict", {"machine": "m"})
        assert sub.queue.qsize() == 0


class TestSseFraming:
    def test_frame_layout(self):
        frame = sse_format(
            {"id": 7, "type": "verdict", "data": {"machine": "m"}}
        )
        assert frame == b'id: 7\nevent: verdict\ndata: {"machine":"m"}\n\n'

    def test_poll_events_returns_batch_and_cursor(self):
        async def run():
            hub = StreamHub()
            hub.publish("verdict", {"machine": "m", "n": 0})
            doc = await stream_mod.poll_events(hub, None, 0, timeout=0)
            return doc

        doc = asyncio.run(run())
        assert doc["last-event-id"] == 1
        assert len(doc["events"]) == 1 and not doc["replay-gap"]

    def test_poll_waits_for_next_event(self):
        async def run():
            hub = StreamHub()

            async def later():
                await asyncio.sleep(0.05)
                hub.publish("verdict", {"machine": "m"})

            task = asyncio.ensure_future(later())
            doc = await stream_mod.poll_events(hub, None, 0, timeout=5.0)
            await task
            return doc

        doc = asyncio.run(run())
        assert len(doc["events"]) == 1


def test_env_knobs(monkeypatch):
    monkeypatch.setenv("GORDO_STREAM_REPLAY", "128")
    monkeypatch.setenv("GORDO_STREAM_QUEUE", "9")
    monkeypatch.setenv("GORDO_STREAM_KEEPALIVE", "3.5")
    monkeypatch.setenv("GORDO_STREAM_POLL_TIMEOUT", "1.5")
    assert stream_mod.replay_ring_size() == 128
    assert stream_mod.queue_depth() == 9
    assert stream_mod.keepalive_seconds() == 3.5
    assert stream_mod.poll_timeout_seconds() == 1.5


# ---------------------------------------------------------------------------
# numerical parity (the acceptance pin) — slow lane
# ---------------------------------------------------------------------------


def _fit(X, estimator, window=None):
    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([MinMaxScaler(), estimator]), window=window
    )
    det.cross_validate(X)
    det.fit(X)
    return det


def _assert_byte_equal(verdict, ref):
    for key in ref:
        a = np.asarray(verdict[key], np.float32)
        b = np.asarray(ref[key], np.float32)
        assert a.tobytes() == b.tobytes(), key


def _stream_and_check(scorer, X_stream, check_production=True):
    """Feed rows one at a time; every steady-state verdict must be
    byte-identical (fp32) to the full-window program over the same
    trailing rows."""
    ms = MachineStream("parity", scorer)
    h = ms.state_rows
    n_checked = 0
    for t in range(1, len(X_stream) + 1):
        verdict = ms.ingest(X_stream[t - 1])
        if t <= ms.offset:
            assert verdict is None  # warm-up: nothing aligned yet
            continue
        assert verdict is not None
        if t >= h:
            ref = reference_verdict(scorer, X_stream[t - h : t])
            _assert_byte_equal(verdict, ref)
            n_checked += 1
    assert n_checked >= 10  # the pin actually exercised steady state
    if check_production:
        # production path comparison: anomaly_arrays pads requests to
        # row buckets, and XLA kernel selection varies with batch shape
        # at the last ulp — tolerance, not bytes, is the honest contract
        # there (byte-identity above is against the SAME-shape program)
        out = scorer.anomaly_arrays(np.asarray(X_stream, np.float32))
        np.testing.assert_allclose(
            float(verdict["total-anomaly-score"]),
            float(np.asarray(out["total-anomaly-score"])[-1]),
            rtol=1e-4, atol=1e-6,
        )
    return ms


@pytest.mark.slow
class TestIncrementalParity:
    @pytest.mark.parametrize(
        "estimator,window",
        [
            (lambda: AutoEncoder(kind="feedforward_hourglass", epochs=3), 5),
            (lambda: AutoEncoder(kind="feedforward_hourglass", epochs=3), None),
            (
                lambda: LSTMAutoEncoder(
                    kind="lstm_hourglass", lookback_window=4, epochs=2
                ),
                5,
            ),
            (
                lambda: LSTMForecast(
                    kind="lstm_hourglass", lookback_window=4, epochs=2
                ),
                3,
            ),
        ],
        ids=["ff-smoothed", "ff-unsmoothed", "lstm-ae", "lstm-forecast"],
    )
    def test_byte_parity_vs_full_window(self, sine_tags, estimator, window):
        det = _fit(sine_tags[:400], estimator(), window=window)
        scorer = CompiledScorer(det)
        assert scorer.fused
        _stream_and_check(scorer, sine_tags[400:440])

    def test_parity_across_generation_flip(self, sine_tags):
        """A delta hot-reload swaps the scorer mid-stream: the carried
        ring survives (same window geometry), and the FIRST post-flip
        verdict is already byte-identical to a full re-score under the
        new generation's params."""
        det_a = _fit(
            sine_tags[:300],
            AutoEncoder(kind="feedforward_hourglass", epochs=3),
            window=5,
        )
        det_b = _fit(
            sine_tags[100:400],
            AutoEncoder(kind="feedforward_hourglass", epochs=4),
            window=5,
        )
        scorer_a, scorer_b = CompiledScorer(det_a), CompiledScorer(det_b)
        hub = StreamHub()
        X = sine_tags[400:440]
        ms = None
        for t in range(1, len(X) + 1):
            scorer = scorer_a if t <= 20 else scorer_b
            ms = hub.stream_for("flip", scorer)
            verdict = ms.ingest(X[t - 1])
            if t < ms.state_rows:
                continue
            ref = reference_verdict(scorer, X[t - ms.state_rows : t])
            _assert_byte_equal(verdict, ref)
        assert ms.scorer is scorer_b  # the flip actually happened

    def test_geometry_change_reprimes_from_mirror(self, sine_tags):
        """A flip that CHANGES the window geometry rebuilds the ring
        from the host mirror — verdicts immediately byte-match a full
        re-score once enough history fits the new geometry."""
        det_a = _fit(
            sine_tags[:300],
            AutoEncoder(kind="feedforward_hourglass", epochs=3),
            window=7,
        )
        det_b = _fit(
            sine_tags[:300],
            AutoEncoder(kind="feedforward_hourglass", epochs=3),
            window=3,
        )
        scorer_a, scorer_b = CompiledScorer(det_a), CompiledScorer(det_b)
        X = sine_tags[400:430]
        ms = MachineStream("geom", scorer_a)
        for t in range(1, 16):
            ms.ingest(X[t - 1])
        ms.rebind(scorer_b)  # 7-row ring -> 3-row ring, mirror re-primes
        for t in range(16, len(X) + 1):
            verdict = ms.ingest(X[t - 1])
            ref = reference_verdict(scorer_b, X[t - ms.state_rows : t])
            _assert_byte_equal(verdict, ref)

    def test_warmup_stream_program(self, sine_tags):
        det = _fit(
            sine_tags[:300],
            AutoEncoder(kind="feedforward_hourglass", epochs=2),
            window=5,
        )
        warmed = warm_stream_program(
            CompiledScorer(det), sine_tags.shape[1]
        )
        assert [label for label, _ in warmed] == ["serve.stream_step"]

    def test_unfused_model_raises_stream_unsupported(self):
        class NotAChain:
            chain = None
            dtype = "float32"

        with pytest.raises(StreamUnsupported):
            MachineStream("nope", NotAChain())


# ---------------------------------------------------------------------------
# integration: routes, resume, shard contract, client, watchman relay
# ---------------------------------------------------------------------------

_DATASET = {
    "type": "RandomDataset",
    "train_start_date": "2017-12-25T06:00:00Z",
    "train_end_date": "2017-12-27T06:00:00Z",
}

PROJECT = {
    "machines": [
        {"name": "stream-a", "dataset": dict(_DATASET, tags=["st-1", "st-2", "st-3"])},
        {"name": "stream-b", "dataset": dict(_DATASET, tags=["st-4", "st-5", "st-6"])},
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {
                                "gordo_tpu.models.estimator.AutoEncoder": {
                                    "kind": "feedforward_hourglass",
                                    "epochs": 2,
                                    "batch_size": 64,
                                }
                            },
                        ]
                    }
                }
            }
        }
    },
}

MACHINES = ["stream-a", "stream-b"]


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("stream-artifacts")
    result = build_project(
        NormalizedConfig(PROJECT, "streamproj").machines, str(out)
    )
    assert not result.failed
    return str(out)


def _rows(n, seed=0):
    return np.random.default_rng(seed).uniform(0, 1, size=(n, 3)).tolist()


def _call(model_dir, fn, **app_kw):
    async def runner():
        collection = ModelCollection.from_directory(
            model_dir, project="streamproj"
        )
        client = TestClient(TestServer(build_app(collection, **app_kw)))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()

    return asyncio.run(runner())


class TestStreamRoutes:
    def test_ingest_then_poll(self, model_dir):
        async def fn(client):
            r = await client.post(
                "/gordo/v0/streamproj/stream/ingest",
                json={"X": {"stream-a": _rows(6)}},
            )
            body = await r.json()
            poll = await client.get(
                "/gordo/v0/streamproj/stream",
                params={"mode": "poll", "after": "0", "timeout": "0"},
            )
            return r.status, body, await poll.json()

        status, body, doc = _call(model_dir, fn)
        assert status == 200
        assert body["accepted"] == 6
        assert body["events"] == 6  # offset 0: every row verdicts
        assert body["last-event-id"] == doc["last-event-id"]
        assert [e["id"] for e in doc["events"]] == list(range(1, 7))
        assert all(e["type"] == "verdict" for e in doc["events"])
        ev = doc["events"][0]["data"]
        assert ev["machine"] == "stream-a"
        assert len(ev["tag-anomaly-scores"]) == 3
        assert "anomaly-confidence" in ev

    def test_poll_machine_filter_and_resume(self, model_dir):
        async def fn(client):
            await client.post(
                "/gordo/v0/streamproj/stream/ingest",
                json={"X": {"stream-a": _rows(3), "stream-b": _rows(3)}},
            )
            only_b = await client.get(
                "/gordo/v0/streamproj/stream",
                params={
                    "mode": "poll", "after": "0", "timeout": "0",
                    "machines": "stream-b",
                },
            )
            doc = await only_b.json()
            resumed = await client.get(
                "/gordo/v0/streamproj/stream",
                params={
                    "mode": "poll", "timeout": "0",
                    "after": str(doc["last-event-id"]),
                },
            )
            return doc, await resumed.json()

        doc, resumed = _call(model_dir, fn)
        assert len(doc["events"]) == 3
        assert all(
            e["data"]["machine"] == "stream-b" for e in doc["events"]
        )
        # the cursor resumes cleanly: only events past it come back
        assert all(
            e["id"] > doc["last-event-id"] for e in resumed["events"]
        )

    def test_threshold_crossing_events(self, model_dir):
        """Rows far outside the training range force the total score
        over the aggregate threshold — the hub pushes the transition
        (once), then the return transition when rows normalize."""

        async def fn(client):
            await client.post(
                "/gordo/v0/streamproj/stream/ingest",
                json={"machine": "stream-a", "x": _rows(4)},
            )
            wild = (np.ones((3, 3)) * 1e4).tolist()
            await client.post(
                "/gordo/v0/streamproj/stream/ingest",
                json={"machine": "stream-a", "x": wild},
            )
            await client.post(
                "/gordo/v0/streamproj/stream/ingest",
                json={"machine": "stream-a", "x": _rows(4, seed=1)},
            )
            poll = await client.get(
                "/gordo/v0/streamproj/stream",
                params={"mode": "poll", "after": "0", "timeout": "0"},
            )
            return await poll.json()

        doc = _call(model_dir, fn)
        crossings = [e for e in doc["events"] if e["type"] == "threshold"]
        assert [c["data"]["direction"] for c in crossings] == [
            "above", "below",
        ]
        assert all(
            c["data"]["threshold"] > 0 for c in crossings
        )

    def test_sse_replay_and_live_no_dup(self, model_dir):
        """One SSE connection sees replayed + live events exactly once,
        ids strictly increasing — the no-loss/no-dup wire contract."""

        async def fn(client):
            r = await client.post(
                "/gordo/v0/streamproj/stream/ingest",
                json={"machine": "stream-a", "x": _rows(5)},
            )
            n_before = (await r.json())["last-event-id"]
            sse = await client.get(
                "/gordo/v0/streamproj/stream",
                headers={"Last-Event-ID": "0"},
            )
            assert sse.headers["Content-Type"].startswith(
                "text/event-stream"
            )

            async def pump():
                await asyncio.sleep(0.05)
                await client.post(
                    "/gordo/v0/streamproj/stream/ingest",
                    json={"machine": "stream-a", "x": _rows(5, seed=2)},
                )

            task = asyncio.ensure_future(pump())
            ids = []
            while len(ids) < n_before + 5:
                line = (await asyncio.wait_for(
                    sse.content.readline(), 10
                )).decode()
                if line.startswith("id: "):
                    ids.append(int(line[4:]))
            await task
            sse.close()
            return n_before, ids

        n_before, ids = _call(model_dir, fn)
        assert ids == list(range(1, n_before + 6))  # no loss, no dup

    def test_ingest_errors(self, model_dir):
        async def fn(client):
            unknown = await client.post(
                "/gordo/v0/streamproj/stream/ingest",
                json={"machine": "nope", "x": _rows(1)},
            )
            missing = await client.post(
                "/gordo/v0/streamproj/stream/ingest", json={"z": 1}
            )
            bad_width = await client.post(
                "/gordo/v0/streamproj/stream/ingest",
                json={"machine": "stream-a", "x": [[0.1, 0.2]]},
            )
            bad_cursor = await client.get(
                "/gordo/v0/streamproj/stream",
                params={"mode": "poll", "after": "xyz"},
            )
            return (
                unknown.status, missing.status, bad_width.status,
                bad_cursor.status,
            )

        assert _call(model_dir, fn) == (404, 400, 400, 400)

    def test_misrouted_machine_is_421(self, model_dir):
        """Shard contract: streaming requests naming a foreign machine
        421 with the owner identified, same as the path routes."""
        table = shard_map(MACHINES, 2)
        mine = [m for m in MACHINES if table[m] == 0][0]
        foreign = [m for m in MACHINES if table[m] == 1][0]

        async def fn():
            coll = ModelCollection.from_directory(
                model_dir, project="streamproj", shard=ShardSpec(0, 2)
            )
            client = TestClient(TestServer(build_app(coll)))
            await client.start_server()
            try:
                mis = await client.post(
                    "/gordo/v0/streamproj/stream/ingest",
                    json={"machine": foreign, "x": _rows(1)},
                )
                sub = await client.get(
                    "/gordo/v0/streamproj/stream",
                    params={"mode": "poll", "machines": foreign,
                            "timeout": "0"},
                )
                own = await client.post(
                    "/gordo/v0/streamproj/stream/ingest",
                    json={"machine": mine, "x": _rows(1)},
                )
                return mis.status, await mis.json(), sub.status, own.status

            finally:
                await client.close()

        mis, body, sub, own = asyncio.run(fn())
        assert (mis, sub, own) == (421, 421, 200)
        assert body["shard"] == 1 and body["shard-count"] == 2

    def test_stream_metrics_exported(self, model_dir):
        async def fn(client):
            await client.post(
                "/gordo/v0/streamproj/stream/ingest",
                json={"machine": "stream-a", "x": _rows(2)},
            )
            metrics = await client.get("/metrics")
            return await metrics.text()

        text = _call(model_dir, fn)
        for name in (
            "gordo_stream_subscribers",
            "gordo_stream_events_pushed_total",
            "gordo_stream_ingest_rows_total",
            "gordo_stream_push_seconds",
            "gordo_stream_dropped_total",
        ):
            assert name in text, name


class TestClientStream:
    def _serve(self, model_dir, fn):
        """Real TCP server (the sync client drives its own loop)."""

        async def runner():
            coll = ModelCollection.from_directory(
                model_dir, project="streamproj"
            )
            app_runner = web.AppRunner(build_app(coll))
            await app_runner.setup()
            site = web.TCPSite(app_runner, "127.0.0.1", 0)
            await site.start()
            port = app_runner.addresses[0][1]
            try:
                return await asyncio.get_running_loop().run_in_executor(
                    None, fn, f"http://127.0.0.1:{port}"
                )
            finally:
                await app_runner.cleanup()

        return asyncio.run(runner())

    def test_stream_iterator_with_ingest(self, model_dir):
        from gordo_tpu.client import Client

        def fn(base):
            client = Client("streamproj", base_url=base)
            feeder = threading.Thread(
                target=client.stream_ingest,
                args=({"stream-a": _rows(4), "stream-b": _rows(4)},),
            )
            feeder.start()
            try:
                events = list(
                    client.stream(machines=["stream-a"], after=0,
                                  max_events=4)
                )
            finally:
                feeder.join()
            return events

        events = self._serve(model_dir, fn)
        assert len(events) == 4
        assert all(e["type"] == "verdict" for e in events)
        assert all(e["data"]["machine"] == "stream-a" for e in events)
        ids = [e["id"] for e in events]
        assert ids == sorted(set(ids))  # no dup, in order


class TestWatchmanRelay:
    def test_relay_refans_with_origin(self, model_dir):
        from gordo_tpu.watchman.server import Watchman, build_watchman_app

        async def fn():
            coll = ModelCollection.from_directory(
                model_dir, project="streamproj"
            )
            app_runner = web.AppRunner(build_app(coll))
            await app_runner.setup()
            site = web.TCPSite(app_runner, "127.0.0.1", 0)
            await site.start()
            base = f"http://127.0.0.1:{app_runner.addresses[0][1]}"
            watchman = Watchman(
                "streamproj", MACHINES, [base],
                poll_interval=3600, discover=False,
            )
            wm_client = TestClient(
                TestServer(build_watchman_app(watchman))
            )
            await wm_client.start_server()
            try:
                # start the relay, give the upstream SSE a beat to attach
                first = await wm_client.get(
                    "/stream",
                    params={"mode": "poll", "after": "0", "timeout": "0"},
                )
                assert first.status == 200
                await asyncio.sleep(0.2)
                async with wm_client.session.post(
                    f"{base}/gordo/v0/streamproj/stream/ingest",
                    json={"machine": "stream-a", "x": _rows(3)},
                ) as r:
                    assert r.status == 200
                for _ in range(50):
                    poll = await wm_client.get(
                        "/stream",
                        params={"mode": "poll", "after": "0",
                                "timeout": "0.2"},
                    )
                    doc = await poll.json()
                    if len(doc["events"]) >= 3:
                        return doc
                return doc
            finally:
                await wm_client.close()
                await app_runner.cleanup()

        doc = asyncio.run(fn())
        assert len(doc["events"]) == 3
        for ev in doc["events"]:
            assert ev["type"] == "verdict"
            assert ev["data"]["machine"] == "stream-a"
            assert ev["data"]["origin-id"] >= 1  # upstream id preserved
            assert ev["data"]["target"].startswith("http://127.0.0.1")
