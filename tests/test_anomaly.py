"""Anomaly detector + CV tests (reference strategy: threshold math on
synthetic frames, score monotonicity under injected anomalies)."""

import numpy as np
import pandas as pd
import pytest

from gordo_tpu.anomaly import DiffBasedAnomalyDetector
from gordo_tpu.models.estimator import AutoEncoder
from gordo_tpu.ops.scalers import MinMaxScaler, RobustScaler
from gordo_tpu.pipeline import Pipeline
from gordo_tpu.serializer import from_definition, into_definition
from gordo_tpu.train.cv import KFold, TimeSeriesSplit, build_splitter, cross_validate

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow


# -- splitters ----------------------------------------------------------------
def test_timeseries_split_expanding():
    splits = list(TimeSeriesSplit(3).split(np.zeros((100, 2))))
    assert len(splits) == 3
    for train, test in splits:
        assert train.max() < test.min()  # no leakage from the future
    assert splits[-1][1][-1] == 99  # covers the tail


def test_kfold_covers_all():
    splits = list(KFold(4).split(np.zeros((20, 1))))
    covered = np.concatenate([test for _, test in splits])
    assert sorted(covered) == list(range(20))


def test_build_splitter_from_config():
    sp = build_splitter({"sklearn.model_selection.TimeSeriesSplit": {"n_splits": 5}})
    assert isinstance(sp, TimeSeriesSplit) and sp.n_splits == 5
    with pytest.raises(ValueError):
        build_splitter({"NotASplitter": {}})


def test_cross_validate_scores(sine_tags):
    model = Pipeline([MinMaxScaler(), AutoEncoder(epochs=5, learning_rate=1e-2)])
    results = cross_validate(model, sine_tags, cv=TimeSeriesSplit(3))
    assert len(results["folds"]) == 3
    ev = results["scores"]["explained_variance_score"]
    assert len(ev["folds"]) == 3
    assert np.isfinite(ev["mean"])


# -- detector -----------------------------------------------------------------
@pytest.fixture(scope="module")
def fitted_detector(sine_tags):
    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline(
            [MinMaxScaler(), AutoEncoder(epochs=20, learning_rate=1e-2)]
        ),
        scaler=MinMaxScaler(),
    )
    det.cross_validate(sine_tags)
    det.fit(sine_tags)
    return det


def test_default_construction_matches_reference_default():
    det = DiffBasedAnomalyDetector()
    assert isinstance(det.base_estimator, Pipeline)
    assert isinstance(det.scaler, MinMaxScaler)


def test_thresholds_derived(fitted_detector, sine_tags):
    assert fitted_detector.feature_thresholds_ is not None
    assert len(fitted_detector.feature_thresholds_) == sine_tags.shape[1]
    assert fitted_detector.aggregate_threshold_ > 0
    meta = fitted_detector.get_metadata()
    assert "cross_validation" in meta
    assert len(meta["cross_validation"]["feature_thresholds"]) == sine_tags.shape[1]


def test_anomaly_frame_schema(fitted_detector, sine_tags):
    idx = pd.date_range("2020-01-01", periods=len(sine_tags), freq="10min", tz="UTC")
    df = pd.DataFrame(sine_tags, index=idx, columns=[f"tag-{i}" for i in range(6)])
    frame = fitted_detector.anomaly(df, frequency="10min")
    top = set(frame.columns.get_level_values(0))
    assert {
        "model-input", "model-output", "tag-anomaly-scores",
        "total-anomaly-score", "tag-anomaly-thresholds",
        "total-anomaly-threshold", "anomaly-confidence", "start", "end",
    } <= top
    assert len(frame) == len(sine_tags)
    assert (frame[("total-anomaly-score", "")] >= 0).all()


def test_anomaly_detects_injected_spike(fitted_detector, sine_tags):
    corrupted = sine_tags.copy()
    corrupted[300:310] += 5.0  # large excursion on all tags
    frame = fitted_detector.anomaly(corrupted)
    total = frame[("total-anomaly-score", "")].to_numpy()
    clean_mean = total[:290].mean()
    spike_mean = total[300:310].mean()
    assert spike_mean > 3 * clean_mean
    assert spike_mean > fitted_detector.aggregate_threshold_


def test_anomaly_requires_thresholds():
    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([MinMaxScaler(), AutoEncoder(epochs=1)]),
        require_thresholds=True,
    )
    X = np.random.default_rng(0).standard_normal((50, 3)).astype(np.float32)
    det.fit(X)
    with pytest.raises(AttributeError, match="cross_validate"):
        det.anomaly(X)


def test_anomaly_without_thresholds_allowed():
    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([MinMaxScaler(), AutoEncoder(epochs=1)]),
        require_thresholds=False,
    )
    X = np.random.default_rng(0).standard_normal((50, 3)).astype(np.float32)
    det.fit(X)
    frame = det.anomaly(X)
    assert ("total-anomaly-score", "") in frame.columns


def test_detector_definition_roundtrip(sine_tags):
    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([RobustScaler(), AutoEncoder(epochs=1)]),
        scaler=RobustScaler(),
    )
    defn = into_definition(det)
    det2 = from_definition(defn)
    assert isinstance(det2, DiffBasedAnomalyDetector)
    assert isinstance(det2.scaler, RobustScaler)
    # reference-era dotted path also resolves
    det3 = from_definition(
        {
            "gordo_components.model.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {"gordo_tpu.models.estimator.AutoEncoder": {"epochs": 1}},
                        ]
                    }
                }
            }
        }
    )
    assert isinstance(det3, DiffBasedAnomalyDetector)
