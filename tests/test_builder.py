"""Builder tests (reference strategy: build against RandomDataset; cache-key
tests assert same-config → hit, changed config → rebuild)."""

import os

import numpy as np
import pytest

from gordo_tpu import serializer
from gordo_tpu.builder import build_model, calculate_model_key, provide_saved_model
from gordo_tpu.utils import disk_registry

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow

DATA_CONFIG = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00Z",
    "train_end_date": "2020-01-10T00:00:00Z",
    "tag_list": ["tag-a", "tag-b", "tag-c"],
    "resolution": "1h",
}

MODEL_CONFIG = {
    "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.pipeline.Pipeline": {
                "steps": [
                    "gordo_tpu.ops.scalers.MinMaxScaler",
                    {"gordo_tpu.models.estimator.AutoEncoder": {"epochs": 3}},
                ]
            }
        }
    }
}

SIMPLE_MODEL_CONFIG = {
    "gordo_tpu.pipeline.Pipeline": {
        "steps": [
            "gordo_tpu.ops.scalers.MinMaxScaler",
            {"gordo_tpu.models.estimator.AutoEncoder": {"epochs": 2}},
        ]
    }
}


def test_build_model_full_metadata():
    model, meta = build_model("machine-1", MODEL_CONFIG, DATA_CONFIG,
                              metadata={"owner": "team-a"})
    assert meta["name"] == "machine-1"
    assert meta["user_defined"] == {"owner": "team-a"}
    assert meta["dataset"]["resolution"] == "1h"
    assert meta["model"]["cross_validation"]["aggregate_threshold"] > 0
    assert meta["model"]["model_builder_duration_sec"] > 0
    # model is usable
    X = np.random.default_rng(0).standard_normal((30, 3)).astype(np.float32)
    frame = model.anomaly(X)
    assert ("total-anomaly-score", "") in frame.columns


def test_build_model_without_cv():
    model, meta = build_model("m", SIMPLE_MODEL_CONFIG, DATA_CONFIG)
    assert "cross_validation" not in meta["model"]
    assert hasattr(model, "predict")


def test_model_key_stability_and_sensitivity():
    k1 = calculate_model_key("m", MODEL_CONFIG, DATA_CONFIG)
    k2 = calculate_model_key("m", MODEL_CONFIG, DATA_CONFIG)
    assert k1 == k2
    k3 = calculate_model_key("m2", MODEL_CONFIG, DATA_CONFIG)
    changed = {**DATA_CONFIG, "resolution": "2h"}
    k4 = calculate_model_key("m", MODEL_CONFIG, changed)
    assert len({k1, k3, k4}) == 3


def test_provide_saved_model_cache(tmp_path):
    out = tmp_path / "out"
    reg = tmp_path / "registry"
    path1 = provide_saved_model(
        "machine-x", SIMPLE_MODEL_CONFIG, DATA_CONFIG,
        output_dir=str(out), model_register_dir=str(reg),
    )
    assert os.path.exists(os.path.join(path1, "model.pkl"))
    mtime = os.path.getmtime(os.path.join(path1, "model.pkl"))

    # second call: cache hit, no rebuild
    path2 = provide_saved_model(
        "machine-x", SIMPLE_MODEL_CONFIG, DATA_CONFIG,
        output_dir=str(out), model_register_dir=str(reg),
    )
    assert path2 == path1
    assert os.path.getmtime(os.path.join(path1, "model.pkl")) == mtime

    # changed config → rebuild under same name
    changed = {**DATA_CONFIG, "resolution": "2h"}
    provide_saved_model(
        "machine-x", SIMPLE_MODEL_CONFIG, changed,
        output_dir=str(out), model_register_dir=str(reg),
    )
    assert os.path.getmtime(os.path.join(path1, "model.pkl")) != mtime

    # artifact loads and predicts
    model = serializer.load(path1)
    X = np.random.default_rng(0).standard_normal((10, 3)).astype(np.float32)
    assert model.predict(X).shape == (10, 3)
    meta = serializer.load_metadata(path1)
    assert meta["name"] == "machine-x"


def test_provide_saved_model_stale_registry(tmp_path):
    reg = tmp_path / "registry"
    disk_registry.write_key(str(reg), "somekey", "/nonexistent/path")
    assert disk_registry.get_value(str(reg), "somekey") == "/nonexistent/path"
    # build proceeds despite stale entry
    path = provide_saved_model(
        "machine-y", SIMPLE_MODEL_CONFIG, DATA_CONFIG,
        output_dir=str(tmp_path / "out"), model_register_dir=str(reg),
    )
    assert os.path.exists(path)


def test_disk_registry_validation(tmp_path):
    with pytest.raises(ValueError):
        disk_registry.write_key(str(tmp_path), "../escape", "v")
    disk_registry.write_key(str(tmp_path), "ok-key", "value")
    assert disk_registry.get_value(str(tmp_path), "ok-key") == "value"
    assert disk_registry.delete_value(str(tmp_path), "ok-key")
    assert disk_registry.get_value(str(tmp_path), "ok-key") is None
