"""Fleet health plane tests: sketch merge algebra (associativity /
commutativity / split-vs-single equality), drift-score order invariance
and shift detection, the recording wiring through the serving scorers,
the ``/fleet-health`` HTTP surfaces (server + watchman merge), rollup
files + rotation, the top-K gauge export, and the end-to-end acceptance
pin: shifted machines — and exactly those — rank top-K by drift."""

import asyncio
import json
import os

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from gordo_tpu import telemetry
from gordo_tpu.builder import build_project
from gordo_tpu.serve import ModelCollection, build_app
from gordo_tpu.serve.shard import ShardSpec, shard_map
from gordo_tpu.telemetry import fleet_health as fh
from gordo_tpu.workflow import NormalizedConfig

MACHINES = [f"fh-machine-{i}" for i in range(4)]

PROJECT = {
    "machines": [
        {
            "name": name,
            "dataset": {
                "type": "RandomDataset",
                "tags": ["fh-1", "fh-2", "fh-3"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-26T06:00:00Z",
            },
        }
        for name in MACHINES
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {
                                "gordo_tpu.models.estimator.AutoEncoder": {
                                    "kind": "feedforward_hourglass",
                                    "epochs": 1,
                                    "batch_size": 64,
                                }
                            },
                        ]
                    }
                }
            }
        }
    },
}


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("fh-artifacts")
    result = build_project(
        NormalizedConfig(PROJECT, "fhproj").machines, str(out)
    )
    assert not result.failed
    return str(out)


@pytest.fixture(autouse=True)
def _fresh_fleet_health():
    telemetry.FLEET_HEALTH.clear()
    yield
    telemetry.FLEET_HEALTH.clear()


def _sketch(*arrays, ts=1.0):
    sk = fh.ScoreSketch()
    for a in arrays:
        sk.observe(a, ts=ts)
    return sk


def _rng(seed=0):
    return np.random.default_rng(seed)


# ---------------------------------------------------------------------------
# sketch algebra
# ---------------------------------------------------------------------------

class TestSketchMergeAlgebra:
    def test_doc_roundtrip(self):
        sk = _sketch(_rng().lognormal(0, 1, 500))
        doc = sk.to_doc()
        again = fh.ScoreSketch.from_doc(doc).to_doc()
        assert json.dumps(doc, sort_keys=True) == json.dumps(
            again, sort_keys=True
        )

    def test_merge_commutes(self):
        """shard A + shard B == shard B + shard A, byte-for-byte."""
        xs = [_rng(i).lognormal(0, 1, 200) for i in range(2)]
        ab = _sketch(xs[0])
        ab.merge(_sketch(xs[1]))
        ba = _sketch(xs[1])
        ba.merge(_sketch(xs[0]))
        assert json.dumps(ab.to_doc(), sort_keys=True) == json.dumps(
            ba.to_doc(), sort_keys=True
        )

    def test_merge_associates(self):
        """(A+B)+C == A+(B+C): counts exactly, float fields to within
        IEEE reassociation noise (weights are counts, so the weighted
        EWMA reduces to the same sum either way)."""
        xs = [_rng(i).lognormal(0, 1, 150) for i in range(3)]
        left = _sketch(xs[0])
        left.merge(_sketch(xs[1]))
        left.merge(_sketch(xs[2]))
        bc = _sketch(xs[1])
        bc.merge(_sketch(xs[2]))
        right = _sketch(xs[0])
        right.merge(bc)
        assert left.to_doc()["counts"] == right.to_doc()["counts"]
        assert left.count == right.count
        assert left.sum == pytest.approx(right.sum, rel=1e-12)
        assert left.ewma_mean == pytest.approx(right.ewma_mean, rel=1e-12)

    def test_shard_split_equals_single_process(self):
        """A stream split across shards (in arrival order) merges to the
        EXACT single-process sketch — the bench's byte-parity gate at
        unit scale."""
        batches = [_rng(i).lognormal(0, 1, 128) for i in range(4)]
        single = _sketch(*batches)
        shard_a = _sketch(batches[0], batches[1])
        shard_b = _sketch(batches[2], batches[3])
        shard_a.merge(shard_b)
        a_doc, s_doc = shard_a.to_doc(), single.to_doc()
        assert a_doc["counts"] == s_doc["counts"]
        assert a_doc["count"] == s_doc["count"]
        assert a_doc["sum"] == s_doc["sum"]
        assert a_doc["sum-sq"] == s_doc["sum-sq"]

    def test_edges_version_mismatch_rejected(self):
        doc = _sketch(_rng().lognormal(0, 1, 300)).to_doc()
        alien = dict(doc, **{"edges-version": 99})
        with pytest.raises(ValueError, match="edges-version"):
            fh.ScoreSketch.from_doc(alien)
        with pytest.raises(ValueError, match="edges-version"):
            fh.drift_score(alien, doc)


class TestDriftScore:
    def test_order_invariant(self):
        """Resorting (or re-batching) the live stream cannot change the
        drift score — it reads bucket counts only."""
        rng = _rng(7)
        base = _sketch(rng.lognormal(0, 1, 4000)).to_doc()
        scores = rng.lognormal(0.3, 1, 1000)
        forward = _sketch(scores).to_doc()
        perm = scores[rng.permutation(scores.size)]
        shuffled = _sketch(perm[:100], perm[100:]).to_doc()
        d1, d2 = fh.drift_score(base, forward), fh.drift_score(base, shuffled)
        assert d1 is not None and d1 == d2

    def test_detects_shift_and_stays_low_on_same_distribution(self):
        rng = _rng(3)
        base = _sketch(rng.lognormal(0, 1, 4000)).to_doc()
        same = _sketch(rng.lognormal(0, 1, 2000)).to_doc()
        shifted = _sketch(rng.lognormal(2.0, 1, 2000)).to_doc()
        d_same = fh.drift_score(base, same)
        d_shift = fh.drift_score(base, shifted)
        assert d_same < 0.15
        assert d_shift > 0.5
        assert d_shift <= 1.0

    def test_thin_windows_report_none_not_noise(self):
        """Below MIN_DRIFT_COUNT the sampling bias of a Hellinger
        estimate dominates any signal — the score must be None, not an
        arithmetically-true false alarm."""
        rng = _rng(5)
        base = _sketch(rng.lognormal(0, 1, 4000)).to_doc()
        thin = _sketch(rng.lognormal(0, 1, fh.MIN_DRIFT_COUNT - 1)).to_doc()
        assert fh.drift_score(base, thin) is None
        assert fh.drift_score(base, None) is None
        assert fh.drift_score(None, base) is None


# ---------------------------------------------------------------------------
# registry + statuses + gauges
# ---------------------------------------------------------------------------

class TestFleetHealthRegistry:
    def test_record_and_statuses(self):
        rng = _rng(11)
        reg = telemetry.FLEET_HEALTH
        base = _sketch(rng.lognormal(0, 1, 4000)).to_doc()
        for name in ("st-ok", "st-drift", "st-silent"):
            reg.set_baseline(name, base)
        reg.record("st-ok", rng.lognormal(0, 1, 2000))
        reg.record("st-drift", rng.lognormal(2.5, 1, 2000))
        reg.record("st-orphan", rng.lognormal(0, 1, 2000))
        doc = reg.doc(
            machines=["st-ok", "st-drift", "st-silent", "st-orphan"]
        )
        statuses = {n: e["status"] for n, e in doc["machines"].items()}
        assert statuses == {
            "st-ok": "ok",
            "st-drift": "drifting",
            "st-silent": "silent",
            "st-orphan": "no-baseline",
        }
        assert doc["top-drift"][0]["machine"] == "st-drift"

    def test_kill_switch_and_suspension_stop_recording(self):
        reg = telemetry.FLEET_HEALTH
        telemetry.set_enabled(False)
        try:
            reg.record("kw-machine", np.ones(10))
        finally:
            telemetry.set_enabled(True)
        with reg.suspended():
            reg.record("kw-machine", np.ones(10))
        assert reg.doc(machines=["kw-machine"])["machines"][
            "kw-machine"
        ]["live"] is None
        reg.record("kw-machine", np.ones(10))
        assert reg.doc(machines=["kw-machine"])["machines"][
            "kw-machine"
        ]["live"]["count"] == 10

    def test_gauge_export_is_topk_bounded_and_resets(self):
        rng = _rng(13)
        reg = telemetry.FLEET_HEALTH
        base = _sketch(rng.lognormal(0, 1, 4000)).to_doc()
        for i in range(6):
            name = f"gk-{i}"
            reg.set_baseline(name, base)
            # increasing shift: gk-5 drifts most
            reg.record(name, rng.lognormal(0.6 * i, 1, 1000))
        reg.export_gauges(machines=[f"gk-{i}" for i in range(6)], top=2)
        text = telemetry.render()
        top2 = [
            line for line in text.splitlines()
            if line.startswith("gordo_machine_drift{")
        ]
        assert len(top2) == 2
        assert any('machine="gk-5"' in line for line in top2)
        assert 'gordo_fleet_health_machines{status="drifting"}' in text
        # a machine rotating OUT of the top-K leaves no stale series
        reg.clear(["gk-5"])
        reg.export_gauges(machines=[f"gk-{i}" for i in range(5)], top=2)
        text = telemetry.render()
        assert 'gordo_machine_drift{machine="gk-5"}' not in text

    def test_merge_health_docs_disjoint_equals_union(self):
        rng = _rng(17)
        reg = telemetry.FLEET_HEALTH
        base = _sketch(rng.lognormal(0, 1, 4000)).to_doc()
        for name, shift in (("mh-a", 0.0), ("mh-b", 2.0)):
            reg.set_baseline(name, base)
            reg.record(name, rng.lognormal(shift, 1, 1000))
        doc_a = reg.doc(machines=["mh-a"])
        doc_b = reg.doc(machines=["mh-b"])
        both = reg.doc(machines=["mh-a", "mh-b"])
        merged = telemetry.merge_health_docs([doc_a, doc_b])
        assert json.dumps(
            telemetry.normalize_health_doc(merged), sort_keys=True
        ) == json.dumps(
            telemetry.normalize_health_doc(both), sort_keys=True
        )


# ---------------------------------------------------------------------------
# rollup files
# ---------------------------------------------------------------------------

class TestRollups:
    def test_write_load_merge(self, tmp_path):
        rng = _rng(19)
        reg = telemetry.FLEET_HEALTH
        reg.set_baseline("ru-a", _sketch(rng.lognormal(0, 1, 4000)).to_doc())
        reg.record("ru-a", rng.lognormal(0, 1, 500))
        d = str(tmp_path)
        # two "processes": an unsharded one and shard 1/2
        assert fh.write_rollup(d, reg.doc(machines=["ru-a"])) is not None
        reg.record("ru-b", rng.lognormal(0, 1, 500))
        fh.write_rollup(
            d, reg.doc(machines=["ru-b"]), shard=ShardSpec(1, 2)
        )
        docs = telemetry.load_rollups(d)
        assert len(docs) == 2
        merged = telemetry.merge_health_docs(docs)
        assert set(merged["machines"]) == {"ru-a", "ru-b"}

    def test_rollup_rotation_keeps_last_two(self, tmp_path):
        doc = {"gordo-fleet-health": 1, "machines": {}}
        d = str(tmp_path)
        for _ in range(50):
            fh.write_rollup(d, doc, max_bytes=200)
        rolldir = tmp_path / fh.ROLLUP_DIR
        files = sorted(p.name for p in rolldir.iterdir())
        assert files == [
            "rollup-unsharded.jsonl", "rollup-unsharded.jsonl.1",
        ]
        # live file stays bounded near the cap (one line of slack)
        assert (rolldir / files[0]).stat().st_size < 400
        # the loader still reads the latest doc
        assert telemetry.load_rollups(d)

    def test_torn_tail_line_is_skipped(self, tmp_path):
        d = str(tmp_path)
        fh.write_rollup(d, {"gordo-fleet-health": 1, "machines": {"x": {}}})
        path = fh.rollup_path(d)
        with open(path, "a") as f:
            f.write('{"gordo-fleet-health": 1, "mach')  # SIGKILL mid-append
        docs = telemetry.load_rollups(d)
        assert len(docs) == 1 and "x" in docs[0]["machines"]


# ---------------------------------------------------------------------------
# serve-path wiring + the end-to-end acceptance pin
# ---------------------------------------------------------------------------

def _training_matrix():
    """The machines' actual training data (RandomDataset is
    deterministic per tags/dates): live traffic drawn from it scores
    exactly like the training residuals, so unshifted machines stay
    near drift 0 and only a genuine input shift moves the signal."""
    from gordo_tpu.dataset.base import GordoBaseDataset

    ds = GordoBaseDataset.from_dict(
        dict(PROJECT["machines"][0]["dataset"])
    )
    X, _ = ds.get_data()
    return np.asarray(X, np.float32)


def _serve_traffic(collection, shifted=(), rounds=3):
    """Score every machine through its single-machine scorer: the
    training matrix as-is for healthy machines, scaled far outside the
    training range for ``shifted`` ones."""
    X = _training_matrix()
    for _ in range(rounds):
        for name in sorted(collection.entries):
            scale = 8.0 if name in shifted else 1.0
            collection.get(name).scorer.anomaly_arrays(X * scale)


class TestEndToEndDrift:
    def test_builder_records_baselines(self, model_dir):
        collection = ModelCollection.from_directory(
            model_dir, project="fhproj"
        )
        for name, entry in collection.entries.items():
            doc = (entry.metadata.get("fleet-health") or {}).get("baseline")
            assert doc, f"{name} has no training baseline"
            assert doc["count"] >= fh.MIN_DRIFT_COUNT
            assert doc["last-seen"] == 0.0  # training artifacts carry no ts
        # loading the collection adopted them
        assert telemetry.FLEET_HEALTH.baseline(MACHINES[0])

    def test_shifted_machines_rank_topk_and_flag(self, model_dir):
        """ISSUE 9 acceptance: serve shifted input to a subset; exactly
        those machines rank top-K by drift and flag in /fleet-health,
        and their gauges ride /metrics."""
        collection = ModelCollection.from_directory(
            model_dir, project="fhproj"
        )
        shifted = {MACHINES[1], MACHINES[3]}
        _serve_traffic(collection, shifted=shifted)

        async def fn(client):
            health = await (
                await client.get("/gordo/v0/fhproj/fleet-health?top=2")
            ).json()
            metrics_text = await (await client.get("/metrics")).text()
            return health, metrics_text

        async def runner():
            client = TestClient(TestServer(build_app(collection)))
            await client.start_server()
            try:
                return await fn(client)
            finally:
                await client.close()

        health, metrics_text = asyncio.run(runner())
        top = [t["machine"] for t in health["top-drift"]]
        assert sorted(top) == sorted(shifted)
        flagged = {
            n for n, e in health["machines"].items()
            if e["status"] == "drifting"
        }
        assert flagged == shifted
        for name in shifted:
            assert health["machines"][name]["drift"] > 0.5
            assert f'gordo_machine_drift{{machine="{name}"}}' in metrics_text
        for name in set(MACHINES) - shifted:
            assert health["machines"][name]["status"] == "ok"

    def test_bulk_path_records_without_double_count(self, model_dir):
        """score_all must record each machine exactly once per request —
        stacked machines via assemble, fallback/windows-bound machines
        via their own named scorers, never both."""
        collection = ModelCollection.from_directory(
            model_dir, project="fhproj"
        )
        rng = _rng(29)
        X_by = {
            n: rng.uniform(0, 1, (300, 3)).astype(np.float32)
            for n in MACHINES
        }
        collection.fleet_scorer.score_all(X_by)
        doc = telemetry.FLEET_HEALTH.doc(machines=MACHINES)
        for name in MACHINES:
            live = doc["machines"][name]["live"]
            assert live is not None and live["count"] == 300

    def test_rollup_task_writes_under_artifact_dir(self, model_dir):
        collection = ModelCollection.from_directory(
            model_dir, project="fhproj"
        )
        _serve_traffic(collection, rounds=1)

        async def runner():
            client = TestClient(TestServer(
                build_app(collection, health_rollup_interval=0.05)
            ))
            await client.start_server()
            try:
                await asyncio.sleep(0.3)
            finally:
                await client.close()

        asyncio.run(runner())
        docs = telemetry.load_rollups(model_dir)
        assert docs and set(docs[-1]["machines"]) == set(MACHINES)


class TestWatchmanMerge:
    def test_watchman_merges_shard_docs(self, model_dir):
        """Two shard replicas (machine-affinity partition) + a watchman:
        its /fleet-health doc covers the whole fleet, merged from the
        per-shard docs."""
        from gordo_tpu.watchman import Watchman, build_watchman_app

        shard_cols = [
            ModelCollection.from_directory(
                model_dir, project="fhproj", shard=ShardSpec(i, 2)
            )
            for i in range(2)
        ]
        owners = shard_map(MACHINES, 2)
        for col in shard_cols:
            _serve_traffic(col)

        async def main():
            servers = []
            targets = []
            for col in shard_cols:
                client = TestClient(TestServer(build_app(col)))
                await client.start_server()
                servers.append(client)
                targets.append(
                    f"http://{client.server.host}:{client.server.port}"
                )
            watchman = Watchman(
                "fhproj", [], targets, poll_interval=3600, discover=False
            )
            wm_client = TestClient(TestServer(build_watchman_app(watchman)))
            await wm_client.start_server()
            try:
                return await (await wm_client.get("/fleet-health")).json()
            finally:
                await wm_client.close()
                for s in servers:
                    await s.close()

        merged = asyncio.run(main())
        assert merged["targets-responding"] == 2
        assert set(merged["machines"]) == set(MACHINES)
        for name, entry in merged["machines"].items():
            assert entry["live"]["count"] > 0, (name, owners[name])
            assert entry["baseline"] is not None


@pytest.mark.slow
def test_two_shard_merged_doc_byte_equivalent_to_single_process(model_dir):
    """The cross-shard merge parity pin (slow lane, next to the PR 8
    scatter-gather parity suite): the same deterministic request stream
    scored through (a) one full collection and (b) two machine-affinity
    shard collections; the shards' docs merged through
    telemetry.merge_health_docs must equal the single-process doc
    byte-for-byte modulo timestamps."""
    rng = _rng(31)
    streams = {
        n: [rng.uniform(0, 1, (512, 3)).astype(np.float32) for _ in range(3)]
        for n in MACHINES
    }

    telemetry.FLEET_HEALTH.clear()
    full = ModelCollection.from_directory(model_dir, project="fhproj")
    for rnd in range(3):
        full.fleet_scorer.score_all({n: streams[n][rnd] for n in MACHINES})
    doc_full = telemetry.normalize_health_doc(
        telemetry.FLEET_HEALTH.doc(machines=MACHINES, top=3)
    )

    telemetry.FLEET_HEALTH.clear()
    owners = shard_map(MACHINES, 2)
    shard_docs = []
    for idx in range(2):
        col = ModelCollection.from_directory(
            model_dir, project="fhproj", shard=ShardSpec(idx, 2)
        )
        owned = sorted(col.entries)
        assert owned == sorted(n for n in MACHINES if owners[n] == idx)
        for rnd in range(3):
            col.fleet_scorer.score_all({n: streams[n][rnd] for n in owned})
        shard_docs.append(telemetry.FLEET_HEALTH.doc(machines=owned, top=3))
    merged = telemetry.normalize_health_doc(
        telemetry.merge_health_docs(shard_docs, top=3)
    )
    assert json.dumps(merged, sort_keys=True) == json.dumps(
        doc_full, sort_keys=True
    )


def test_baseline_kill_switch(monkeypatch):
    monkeypatch.setenv("GORDO_FLEET_BASELINE", "off")
    assert fh.training_baseline(object(), np.zeros((10, 2))) is None
    assert fh.training_baselines({"m": object()}, {"m": np.zeros((10, 2))}) \
        == {}


def test_span_log_rotation(tmp_path, monkeypatch):
    """Satellite: GORDO_SPAN_LOG rolls over at the size cap, keeping the
    last 2 files — it previously grew unboundedly on long-lived
    servers."""
    log_path = str(tmp_path / "spans.jsonl")
    monkeypatch.setenv("GORDO_SPAN_LOG", log_path)
    monkeypatch.setenv("GORDO_SPAN_LOG_MAX_BYTES", "300")
    for i in range(60):
        with telemetry.span("rotate.section", i=i):
            pass
    assert sorted(os.listdir(tmp_path)) == [
        "spans.jsonl", "spans.jsonl.1",
    ]
    assert os.path.getsize(log_path) < 600
    with open(log_path) as f:
        last = [json.loads(line) for line in f if line.strip()][-1]
    assert last["span"] == "rotate.section" and last["i"] == 59
