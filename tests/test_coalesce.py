"""Unit tests for the continuous-drain adaptive coalescer
(``serve/coalesce.py``) against fake fleet scorers — the batching POLICY
(drain cadence, knee cap, stand-down, off-thread assembly) isolated from
real device dispatch, which the server integration tests cover."""

import threading
import time
from types import SimpleNamespace

import numpy as np

from gordo_tpu.serve.coalesce import CoalescingScorer, estimate_knee, stats


class FakeFleet:
    """Minimal FleetScorer stand-in: every machine 'stacked', score_all
    returns a result derived from each machine's own input (so a swapped
    result is detectable), with a configurable service-time sleep."""

    def __init__(self, names, service_s=0.0):
        self.machine_bucket = {n: (0, i) for i, n in enumerate(names)}
        self.models = {n: object() for n in names}
        self.service_s = service_s
        self.batch_sizes = []
        self._lock = threading.Lock()

    def score_all(self, X_by):
        with self._lock:
            self.batch_sizes.append(len(X_by))
        if self.service_s:
            time.sleep(self.service_s)
        return {
            n: {"model-output": np.asarray(X) * 2.0}
            for n, X in X_by.items()
        }


class FakeDispatchFleet(FakeFleet):
    """A fleet exposing the dispatch_all/assemble split; assemble records
    which thread ran it (the drain thread must never be it)."""

    def __init__(self, names, service_s=0.0):
        super().__init__(names, service_s)
        self.assemble_threads = []

    def dispatch_all(self, X_by):
        with self._lock:
            self.batch_sizes.append(len(X_by))
        if self.service_s:
            time.sleep(self.service_s)
        fleet = self

        class _Pending:
            def assemble(self):
                with fleet._lock:
                    fleet.assemble_threads.append(
                        threading.current_thread().name
                    )
                return {
                    n: {"model-output": np.asarray(X) * 2.0}
                    for n, X in X_by.items()
                }

        return _Pending()


def _mk(fleet, **kw):
    kw.setdefault("max_wait_s", 0.0)
    return CoalescingScorer(lambda: fleet, **kw)


def test_continuous_drain_ignores_the_window():
    """A queue holding >=2 requests dispatches IMMEDIATELY — with the r5
    windowed drain a huge max_wait_s would stall every batch; now it only
    bounds the single-rider grace (inflight==0 here, so not even that)."""
    names = [f"m-{i:02d}" for i in range(8)]
    fleet = FakeFleet(names, service_s=0.02)
    co = _mk(fleet, max_wait_s=30.0)  # would deadlock the old design
    try:
        t0 = time.monotonic()
        futs = [co.submit(n, np.full((4, 2), i, np.float32))
                for i, n in enumerate(names)]
        for i, fut in enumerate(futs):
            out = fut.result(timeout=5)
            np.testing.assert_allclose(
                out["model-output"], np.full((4, 2), 2.0 * i)
            )
        assert time.monotonic() - t0 < 5.0
        # burst coalesced: strictly fewer dispatches than requests
        assert co.n_dispatches < len(names)
        assert co.n_requests == len(names)
    finally:
        co.close()


def test_knee_cap_bounds_every_dispatch():
    names = [f"k-{i:02d}" for i in range(32)]
    fleet = FakeFleet(names, service_s=0.01)
    co = _mk(fleet, knee_batch=4)
    try:
        futs = [co.submit(n, np.ones((2, 2), np.float32)) for n in names]
        for fut in futs:
            fut.result(timeout=10)
        assert max(fleet.batch_sizes) <= 4
        assert co.batch_cap == 4
        assert stats(co)["batch_cap"] == 4
    finally:
        co.close()


def test_standdown_triggers_and_recovers():
    """When queue wait runs away from service time the coalescer stands
    down (should_coalesce -> False) for the cooldown, then resumes."""
    names = [f"s-{i:02d}" for i in range(4)]
    fleet = FakeFleet(names, service_s=0.005)
    co = _mk(
        fleet,
        min_concurrency=1,
        standdown_ratio=1e-6,  # any measurable wait triggers
        standdown_cooldown_s=0.3,
        standdown_max_s=0.3,  # no escalation: recovery timing stays fixed
        signal_window=16,
    )
    try:
        # several sequential rounds so >=4 service samples accumulate
        for _ in range(6):
            futs = [co.submit(n, np.ones((2, 2), np.float32))
                    for n in names]
            for fut in futs:
                fut.result(timeout=5)
        assert co.n_standdowns >= 1
        assert co.standing_down
        co.inflight = 5
        assert not co.should_coalesce()  # standing down: route direct
        assert stats(co)["standing_down"]

        time.sleep(0.35)  # cooldown expires -> coalescing resumes
        assert not co.standing_down
        assert co.should_coalesce()
    finally:
        co.close()


def test_standdown_cooldown_escalates_then_resets():
    """Consecutive stand-downs double the cooldown (bounded); a healthy
    evaluation resets the escalation — a structurally-losing regime must
    converge to ~all-direct instead of thrashing losing re-probes."""
    co = _mk(FakeFleet(["x"]), standdown_ratio=1e9,
             standdown_cooldown_s=0.1, standdown_max_s=0.4,
             signal_window=16)
    try:
        # prime 4 service samples through HEALTHY evaluations (huge ratio)
        for _ in range(4):
            co._note_dispatch_signal([1e-9] * 4, 0.001)
        assert co.n_standdowns == 0
        # each call below adds exactly the threshold of waits -> exactly
        # one evaluation -> one trigger; cooldown must double, bounded
        co.standdown_ratio = 1e-6
        for i, expect_cd in enumerate((0.1, 0.2, 0.4, 0.4)):
            t0 = time.monotonic()
            co._note_dispatch_signal([0.05] * 4, 0.001)
            assert co.n_standdowns == i + 1
            delta = co._standdown_until - t0
            assert expect_cd - 0.02 <= delta <= expect_cd + 0.05, (i, delta)
        # healthy evaluation resets the escalation
        co.standdown_ratio = 1e9
        co._note_dispatch_signal([1e-9] * 4, 0.001)
        assert co._standdown_streak == 0
    finally:
        co.close()


def test_queue_backpressure_bypasses_when_saturated():
    """Once the queue holds 2 knee-capped dispatches' worth, new arrivals
    must route direct (a rider there would wait >=2 service times for no
    gain) — and coalescing resumes as the queue drains."""
    names = [f"q-{i}" for i in range(8)]
    gate = threading.Event()

    class BlockingFleet(FakeFleet):
        def score_all(self, X_by):
            gate.wait(5)
            return super().score_all(X_by)

    fleet = BlockingFleet(names)
    co = _mk(fleet, knee_batch=1, min_concurrency=1)
    try:
        co.inflight = 4
        futs = [co.submit(n, np.ones((2, 2), np.float32))
                for n in names[:4]]
        # drain thread holds one request inside the blocked dispatch; the
        # other three sit queued >= 2 * batch_cap(=1)
        deadline = time.monotonic() + 2
        while len(co._queue) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(co._queue) >= 2
        assert not co.should_coalesce()
        assert co.n_queue_full >= 1
        assert stats(co)["queue_full_bypassed"] >= 1

        gate.set()
        for fut in futs:
            fut.result(timeout=5)
        deadline = time.monotonic() + 2
        while co._queue and time.monotonic() < deadline:
            time.sleep(0.005)
        assert co.should_coalesce()  # drained queue admits riders again
    finally:
        co.close()


def test_healthy_load_never_stands_down():
    """Waits comparable to service time must NOT trip the stand-down —
    the signal fires on runaway queues, not on normal batching."""
    names = [f"h-{i:02d}" for i in range(4)]
    fleet = FakeFleet(names, service_s=0.02)
    co = _mk(fleet, min_concurrency=1, standdown_ratio=50.0,
             signal_window=4)
    try:
        for _ in range(6):
            futs = [co.submit(n, np.ones((2, 2), np.float32))
                    for n in names]
            for fut in futs:
                fut.result(timeout=5)
        assert co.n_standdowns == 0
        assert not co.standing_down
    finally:
        co.close()


def test_assembly_runs_off_the_drain_thread_with_correct_results():
    """dispatch_all's deferred assembly must run on the finish pool (the
    drain thread is gathering the next batch) and every future must get
    the result derived from ITS OWN input — no cross-request mixups."""
    names = [f"d-{i:02d}" for i in range(16)]
    fleet = FakeDispatchFleet(names, service_s=0.005)
    co = _mk(fleet)
    try:
        futs = {}
        for i, n in enumerate(names):
            futs[n] = (i, co.submit(n, np.full((3, 2), i, np.float32)))
        for n, (i, fut) in futs.items():
            out = fut.result(timeout=5)
            np.testing.assert_allclose(
                out["model-output"], np.full((3, 2), 2.0 * i)
            )
        assert fleet.assemble_threads, "dispatch_all path not exercised"
        for tname in fleet.assemble_threads:
            assert tname.startswith("gordo-coalesce-fin"), tname
            assert tname != "gordo-coalescer"
    finally:
        co.close()


def test_estimate_knee_finds_the_amortization_cliff():
    """Service time flat to batch=8, linear past it -> throughput stops
    improving at 8, so the sweep must cap there."""

    class KneeFleet:
        def __init__(self):
            self.buckets = [SimpleNamespace(
                names=[f"b-{i:02d}" for i in range(32)],
                n_features=3, lookback=0,
            )]

        def score_all(self, X_by):
            b = len(X_by)
            # flat to 8, then a 2x-per-doubling cliff: sleep-timer noise
            # under CPU contention cannot blur the knee
            time.sleep(0.004 if b <= 8 else 0.008 * b / 8)
            return {n: {} for n in X_by}

    est = estimate_knee(KneeFleet(), rows=8, max_batch=32)
    assert est["knee"] == 8
    # flat service to the knee: 8 requests cost ~1 single-dispatch time
    assert est["amortization"] > 4


def test_estimate_knee_no_buckets_is_none():
    assert estimate_knee(SimpleNamespace(buckets=[]), rows=8) is None
    co = _mk(FakeFleet(["x"]))
    try:
        # FakeFleet has no .buckets -> estimation degrades to None and the
        # cap stays at the conservative pre-knee bound
        assert co.ensure_knee() is None
        assert co.batch_cap == min(co.max_batch, co.PRE_KNEE_CAP)
    finally:
        co.close()


def test_ensure_knee_sets_batch_cap():
    class KneeFleet(FakeFleet):
        def __init__(self, names):
            super().__init__(names)
            self.buckets = [SimpleNamespace(
                names=list(names), n_features=2, lookback=0,
            )]

        def score_all(self, X_by):
            b = len(X_by)
            time.sleep(0.003 if b <= 4 else 0.006 * b / 4)
            return super().score_all(X_by)

    fleet = KneeFleet([f"e-{i:02d}" for i in range(16)])
    co = _mk(fleet)
    try:
        assert co.ensure_knee(rows=4) == 4
        assert co.batch_cap == 4
        assert stats(co)["knee_estimated"] == 4
        # idempotent: a second call doesn't re-sweep
        n_calls = len(fleet.batch_sizes)
        assert co.ensure_knee(rows=4) == 4
        assert len(fleet.batch_sizes) == n_calls
    finally:
        co.close()


def test_no_amortization_disables_coalescing():
    """Service time linear in batch size (the CPU compute-bound regime):
    sharing a dispatch saves nothing, so the sweep must DISABLE
    coalescing outright instead of batching at a size that can't pay."""

    class LinearFleet(FakeFleet):
        def __init__(self, names):
            super().__init__(names)
            self.buckets = [SimpleNamespace(
                names=list(names), n_features=2, lookback=0,
            )]

        def score_all(self, X_by):
            time.sleep(0.003 * len(X_by))
            return super().score_all(X_by)

    co = _mk(LinearFleet([f"l-{i}" for i in range(8)]), min_concurrency=1)
    try:
        assert co.ensure_knee(rows=4) is None
        assert co._knee_no_gain
        co.inflight = 64
        assert not co.should_coalesce()  # permanently out of the way
        assert stats(co)["knee_no_gain"]
        # an explicit knee_batch is the operator escape hatch: no sweep,
        # no auto-disable
        co2 = _mk(LinearFleet(["a", "b"]), min_concurrency=1, knee_batch=2)
        try:
            co2.inflight = 2
            assert co2.should_coalesce()
        finally:
            co2.close()
    finally:
        co.close()


def test_bypass_counting_and_stats_shape():
    co = _mk(FakeFleet(["m"]), min_concurrency=2)
    try:
        co.inflight = 1
        assert not co.should_coalesce()
        co.inflight = 2
        assert co.should_coalesce()
        s = stats(co)
        assert s["enabled"] and s["bypassed_requests"] == 1
        assert s["standdowns"] == 0 and s["knee_batch"] is None
    finally:
        co.close()
    assert stats(None) == {"enabled": False}


def test_expired_rider_dropped_before_dispatch():
    """Deadline propagation's coalescer leg: a rider whose propagated
    deadline passed while queued resolves with DeadlineExpired BEFORE
    dispatch (no device work for abandoned requests); a live rider in
    the same queue still scores."""
    import pytest

    from gordo_tpu.serve.coalesce import DeadlineExpired

    fleet = FakeFleet(["m-0"])
    co = _mk(fleet)
    try:
        dead = co.submit(
            "m-0", np.ones((2, 2), np.float32),
            deadline=time.monotonic() - 0.01,
        )
        with pytest.raises(DeadlineExpired):
            dead.result(timeout=5)
        live = co.submit(
            "m-0", np.ones((2, 2), np.float32),
            deadline=time.monotonic() + 30.0,
        )
        out = live.result(timeout=5)
        np.testing.assert_allclose(out["model-output"], 2.0)
    finally:
        co.close()
