"""Build-ingest plane (r24): fingerprint dedup correctness, vectorized
parity with the per-machine path, stacked zero-copy handoff, the config
fast path, and the hot-path lint gate.

The central contract — pinned here in BOTH directions — is that the
fleet-vectorized assembly is an invisible optimization: machines with
IDENTICAL dataset fingerprints share one fetch and get byte-identical
frames, machines with ANY differing dataset field (tags, resolution,
row filter, window, ...) must miss the dedup cache, and every machine's
``(X, y, metadata)`` matches what ``dataset.get_data()`` produces to
the bit.
"""

import importlib.util
import os
import pickle
import types

import numpy as np
import pytest

from gordo_tpu.dataset.base import GordoBaseDataset
from gordo_tpu.ingest.fingerprint import (
    dataset_fingerprint,
    provider_fingerprint,
)
from gordo_tpu.ingest.plane import (
    DEDUP_HITS_TOTAL,
    load_chunk,
    owned_stack_base,
    resolve_enabled,
    stack_live_slots,
)

WINDOW = {
    "train_start_date": "2017-12-25 06:00:00Z",
    "train_end_date": "2017-12-26 06:00:00Z",
}


def _m(name, n_tags=3, **over):
    cfg = {
        "type": "RandomDataset",
        "tag_list": [f"{name}-t{j}" for j in range(n_tags)],
        "resolution": "10min",
        **WINDOW,
    }
    cfg.update(over)
    return types.SimpleNamespace(name=name, dataset=cfg)


def _classic(machine):
    """The per-machine reference path the vectorized pass must match."""
    ds = GordoBaseDataset.from_dict(dict(machine.dataset))
    X, y = ds.get_data()
    return np.asarray(X, np.float32), ds.get_metadata()


class TestFingerprint:
    def test_identical_configs_equal(self):
        a = _m("a").dataset
        b = dict(_m("a").dataset)
        assert dataset_fingerprint(a) == dataset_fingerprint(b)

    @pytest.mark.parametrize(
        "override",
        [
            {"tag_list": ["a-t0", "a-t1"]},
            {"resolution": "5min"},
            {"row_filter": "`a-t0` > 0"},
            {"row_filter_buffer_size": 3},
            {"train_start_date": "2017-12-24 06:00:00Z"},
            {"train_end_date": "2017-12-27 06:00:00Z"},
            {"target_tag_list": ["a-t0"]},
            {"aggregation_methods": "max"},
            {"n_samples_threshold": 5},
            {"asset": "other"},
            {"some_future_knob": 1},  # unknown keys can only MISS
        ],
    )
    def test_any_differing_field_misses(self, override):
        base = _m("a").dataset
        other = dict(base)
        other.update(override)
        assert dataset_fingerprint(base) != dataset_fingerprint(other)

    def test_tag_spelling_normalizes(self):
        """str / dict / SensorTag spellings of the same tags must HIT —
        the fingerprint is over tag NAMES, not config syntax."""
        a = dict(_m("a").dataset)
        b = dict(a)
        b["tag_list"] = [{"name": t} for t in a["tag_list"]]
        assert dataset_fingerprint(a) == dataset_fingerprint(b)

    def test_batch_plane_uses_the_hoisted_fingerprint(self):
        """r18's backfill fetch dedup and the r24 ingest plane must share
        ONE fingerprint implementation (the hoist this PR performed)."""
        from gordo_tpu.batch.runner import _dataset_fingerprint

        assert _dataset_fingerprint is provider_fingerprint

    def test_provider_grain_ignores_window(self):
        """The fetch grain (backfill) shares frames across scoring
        windows; the output grain (build ingest) must not."""
        a = _m("a").dataset
        b = dict(a, train_end_date="2017-12-27 06:00:00Z")
        assert provider_fingerprint(a) == provider_fingerprint(b)
        assert dataset_fingerprint(a) != dataset_fingerprint(b)


class TestDedup:
    def test_twins_fetch_once_and_share_bytes(self):
        leader = _m("lead")
        twin = types.SimpleNamespace(name="twin", dataset=dict(leader.dataset))
        before = DEDUP_HITS_TOTAL.value()
        stats = {}
        out = load_chunk([leader, twin], stats=stats)
        Xl, yl, ml, _ = out["lead"]
        Xt, yt, mt, _ = out["twin"]
        assert Xl.tobytes() == Xt.tobytes()
        assert pickle.dumps(ml) == pickle.dumps(mt)
        assert stats["fetches"] == 1
        assert stats["dedup_hits"] == 1
        assert DEDUP_HITS_TOTAL.value() == before + 1

    def test_twin_metadata_is_isolated(self):
        """Dedup copies must not alias: the builder mutates metadata
        per machine downstream."""
        leader = _m("lead")
        twin = types.SimpleNamespace(name="twin", dataset=dict(leader.dataset))
        out = load_chunk([leader, twin])
        ml, mt = out["lead"][2], out["twin"][2]
        assert ml is not mt
        mt["tag_loading_metadata"]["poisoned"] = True
        assert "poisoned" not in ml["tag_loading_metadata"]

    def test_differing_window_fetches_twice(self):
        a = _m("a")
        b = types.SimpleNamespace(
            name="b",
            dataset=dict(a.dataset, train_end_date="2017-12-27 06:00:00Z"),
        )
        stats = {}
        out = load_chunk([a, b], stats=stats)
        assert stats["fetches"] == 2
        assert stats["dedup_hits"] == 0
        assert out["a"][0].shape != out["b"][0].shape

    def test_row_filter_routes_to_fallback(self):
        m = _m("filt", row_filter="`filt-t0` > -100")
        stats = {}
        out = load_chunk([m], stats=stats)
        assert stats["fallback"] == 1
        assert stats["vectorized"] == 0
        X, _, meta, _ = out[m.name]
        Xc, mc = _classic(m)
        assert X.tobytes() == Xc.tobytes()
        assert pickle.dumps(meta) == pickle.dumps(mc)


class TestVectorizedParity:
    def test_mixed_chunk_matches_per_machine_path(self):
        """The acceptance contract at the array level: a chunk mixing
        tag widths, a fingerprint twin, and a fallback machine produces
        byte-identical X and pickle-identical metadata vs get_data()."""
        machines = [_m("a"), _m("b"), _m("wide", n_tags=5)]
        machines.append(
            types.SimpleNamespace(name="twin-a", dataset=dict(machines[0].dataset))
        )
        machines.append(_m("filt", row_filter="`filt-t0` > -100"))
        out = load_chunk(machines)
        for m in machines:
            entry = out[m.name]
            assert not isinstance(entry, Exception), (m.name, entry)
            X, y, meta, secs = entry
            Xc, mc = _classic(m)
            assert X.tobytes() == Xc.tobytes(), m.name
            assert pickle.dumps(meta) == pickle.dumps(mc), m.name
            assert secs >= 0.0

    def test_y_is_x_for_untargeted_machines(self):
        """No target_tag_list → y shares X's buffer outright, so the
        dispatch plane stages ONE stacked array, not two."""
        out = load_chunk([_m("a"), _m("b")])
        for name in ("a", "b"):
            X, y, _, _ = out[name]
            assert y is X

    def test_bad_config_is_a_per_machine_value(self):
        """One broken machine must not poison the chunk."""
        good = _m("good")
        bad = types.SimpleNamespace(name="bad", dataset={"type": "NoSuch"})
        out = load_chunk([good, bad])
        assert isinstance(out["bad"], Exception)
        X, _, _, _ = out["good"]
        assert X.tobytes() == _classic(good)[0].tobytes()


class TestStackedHandoff:
    def test_capacity_buffer_is_adopted(self):
        machines = [_m(f"s{i}") for i in range(4)]
        out = load_chunk(machines, capacity=lambda m: m + 2)
        X0 = out["s0"][0]
        base = owned_stack_base(X0)
        assert base is not None
        assert base.shape[0] == 6  # 4 live + 2 padding slots
        assert stack_live_slots(base) == 4
        for i in range(4):
            assert np.shares_memory(out[f"s{i}"][0], base)

    def test_stack_machine_axis_is_a_view(self):
        from gordo_tpu.parallel.anomaly import _stack_machine_axis

        machines = [_m(f"s{i}") for i in range(4)]
        out = load_chunk(machines, capacity=lambda m: m)
        arrs = [out[f"s{i}"][0] for i in range(4)]
        stacked = _stack_machine_axis(arrs)
        base = owned_stack_base(arrs[0])
        assert np.shares_memory(stacked, base)
        assert np.array_equal(stacked, np.stack(arrs))

    def test_stack_machine_axis_copies_foreign_arrays(self):
        from gordo_tpu.parallel.anomaly import _stack_machine_axis

        arrs = [np.ones((5, 3), np.float32), np.zeros((5, 3), np.float32)]
        stacked = _stack_machine_axis(arrs)
        assert owned_stack_base(stacked) is None
        assert np.array_equal(stacked, np.stack(arrs))

    def test_pad_models_capacity_in_place(self):
        from gordo_tpu.parallel.anomaly import (
            _pad_models_capacity,
            _stack_machine_axis,
        )

        machines = [_m(f"s{i}") for i in range(3)]
        out = load_chunk(machines, capacity=lambda m: m + 1)
        arrs = [out[f"s{i}"][0] for i in range(3)]
        X = _stack_machine_axis(arrs)
        base = owned_stack_base(arrs[0])
        padded = _pad_models_capacity(X, 4)
        assert np.shares_memory(padded, base)
        assert padded.shape[0] == 4
        assert np.array_equal(padded[3], X[2])  # replicated last machine

    def test_pad_models_capacity_copies_foreign_arrays(self):
        from gordo_tpu.parallel.anomaly import _pad_models_capacity

        X = np.arange(24, dtype=np.float32).reshape(2, 4, 3)
        padded = _pad_models_capacity(X, 3)
        assert not np.shares_memory(padded, X)
        assert np.array_equal(padded[2], X[1])


class TestKillSwitch:
    def test_env_gate(self, monkeypatch):
        monkeypatch.delenv("GORDO_INGEST", raising=False)
        assert resolve_enabled() is True  # default on
        monkeypatch.setenv("GORDO_INGEST", "off")
        assert resolve_enabled() is False
        assert resolve_enabled(True) is True  # explicit arg beats env
        monkeypatch.setenv("GORDO_INGEST", "on")
        assert resolve_enabled(False) is False


PROJECT_YAML = """
machines:
  - name: cfg-a
    dataset:
      type: RandomDataset
      tags: [a-t0, a-t1]
  - name: cfg-b
    dataset:
      type: RandomDataset
      tags: [b-t0]
    model:
      gordo_tpu.ops.scalers.MinMaxScaler: {}
globals:
  dataset:
    resolution: 5min
"""


class TestConfigFastPath:
    def test_from_source_matches_legacy_path(self):
        from gordo_tpu.workflow.config import (
            NormalizedConfig,
            load_machine_config,
        )

        legacy = NormalizedConfig(load_machine_config(PROJECT_YAML), "p")
        fast = NormalizedConfig.from_source(PROJECT_YAML, "p")
        assert [m.to_dict() for m in legacy.machines] == [
            m.to_dict() for m in fast.machines
        ]
        assert legacy.config_globals == fast.config_globals

    def test_cache_hit_skips_the_parse(self, tmp_path, monkeypatch):
        import gordo_tpu.workflow.config as config_mod

        cold = config_mod.NormalizedConfig.from_source(
            PROJECT_YAML, "p", cache_dir=str(tmp_path)
        )
        assert list(tmp_path.glob("config-*.json"))

        def boom(_source):
            raise AssertionError("cache hit must not re-parse")

        monkeypatch.setattr(config_mod, "load_machine_config", boom)
        warm = config_mod.NormalizedConfig.from_source(
            PROJECT_YAML, "p", cache_dir=str(tmp_path)
        )
        assert [m.to_dict() for m in warm.machines] == [
            m.to_dict() for m in cold.machines
        ]
        assert warm.config_globals == cold.config_globals
        assert warm.project_name == "p"

    def test_project_name_is_part_of_the_key(self, tmp_path):
        from gordo_tpu.workflow.config import NormalizedConfig

        NormalizedConfig.from_source(PROJECT_YAML, "p1", cache_dir=str(tmp_path))
        NormalizedConfig.from_source(PROJECT_YAML, "p2", cache_dir=str(tmp_path))
        assert len(list(tmp_path.glob("config-*.json"))) == 2

    def test_corrupt_cache_entry_falls_back_cold(self, tmp_path):
        from gordo_tpu.workflow.config import NormalizedConfig

        NormalizedConfig.from_source(PROJECT_YAML, "p", cache_dir=str(tmp_path))
        (entry,) = tmp_path.glob("config-*.json")
        entry.write_text("{not json")
        cfg = NormalizedConfig.from_source(
            PROJECT_YAML, "p", cache_dir=str(tmp_path)
        )
        assert [m.name for m in cfg.machines] == ["cfg-a", "cfg-b"]

    def test_unjsonable_config_never_caches(self, tmp_path):
        """A YAML date parses to datetime.date — not JSON-representable,
        so the entry must simply not cache (correctness over speed)."""
        from gordo_tpu.workflow.config import NormalizedConfig

        text = PROJECT_YAML.replace(
            "resolution: 5min",
            "resolution: 5min\n  metadata:\n    dated: 2017-12-25",
        )
        cfg = NormalizedConfig.from_source(text, "p", cache_dir=str(tmp_path))
        assert not list(tmp_path.glob("config-*.json"))
        assert len(cfg.machines) == 2

    def test_duplicate_names_still_rejected(self):
        from gordo_tpu.workflow.config import NormalizedConfig

        dup = PROJECT_YAML.replace("cfg-b", "cfg-a")
        with pytest.raises(ValueError, match="Duplicate"):
            NormalizedConfig.from_source(dup, "p")


class TestIngestLintGate:
    @staticmethod
    def _lint(path):
        spec = importlib.util.spec_from_file_location(
            "gordo_lint",
            os.path.join(
                os.path.dirname(os.path.dirname(__file__)),
                "scripts",
                "lint.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.lint_file(path)

    def test_per_machine_pandas_banned_outside_fallback(self, tmp_path):
        bad = tmp_path / "gordo_tpu" / "ingest" / "plane.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "import pandas as pd\n"
            "def hot(df, ds):\n"
            "    a = df.resample('10min').mean()\n"
            "    b = pd.DataFrame({'x': [1]})\n"
            "    c = ds.get_data()\n"
            "    return pd.concat([a, b]), c\n"
            "def _load_fallback(dataset, align_lengths):\n"
            "    X, y = dataset.get_data()\n"
            "    return X.to_frame()\n"
        )
        msgs = [f[2] for f in self._lint(str(bad))]
        hits = [m for m in msgs if "ingest hot path" in m]
        assert len(hits) == 4  # resample, DataFrame, get_data, concat
        # _load_fallback's get_data/to_frame are sanctioned
        assert not any("to_frame" in m for m in hits)

    def test_shipping_plane_is_clean(self):
        plane_py = os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "gordo_tpu",
            "ingest",
            "plane.py",
        )
        msgs = [f[2] for f in self._lint(plane_py)]
        assert not any("ingest hot path" in m for m in msgs)


@pytest.mark.slow
class TestBuildParity:
    def test_ingest_build_byte_identical_to_classic(self, tmp_path):
        """The end-to-end acceptance contract: build_project with the
        ingest plane on produces byte-identical artifacts (definition
        bytes, metadata modulo volatile timings, model pickles modulo
        zeroed wall-clock) and registry keys vs the per-machine path."""
        import json

        from test_build_pipeline import _machines, _scrub_timings, _strip_meta

        from gordo_tpu.builder import build_project
        from gordo_tpu.workflow.config import Machine

        machines = _machines(6)
        machines.append(
            Machine.from_config(
                {"name": "twin-1", "dataset": dict(machines[1].dataset)}
            )
        )
        dirs = {}
        for label, ing in (("classic", False), ("ingest", True)):
            out = tmp_path / f"out-{label}"
            reg = tmp_path / f"reg-{label}"
            result = build_project(
                machines,
                str(out),
                model_register_dir=str(reg),
                max_bucket_size=4,
                artifact_format="v1",
                ingest=ing,
            )
            assert not result.failed, result.failed
            if ing:
                assert result.summary()["ingest"]["dedup_hits"] >= 1
            dirs[label] = (out, reg)
        c_out, c_reg = dirs["classic"]
        i_out, i_reg = dirs["ingest"]
        for m in machines:
            a, b = c_out / m.name, i_out / m.name
            assert (a / "definition.yaml").read_bytes() == (
                b / "definition.yaml"
            ).read_bytes(), m.name
            assert _strip_meta(
                json.loads((a / "metadata.json").read_text())
            ) == _strip_meta(
                json.loads((b / "metadata.json").read_text())
            ), m.name
            pa = pickle.loads((a / "model.pkl").read_bytes())
            pb = pickle.loads((b / "model.pkl").read_bytes())
            _scrub_timings(pa)
            _scrub_timings(pb)
            assert pickle.dumps(pa) == pickle.dumps(pb), m.name
        assert sorted(p.name for p in c_reg.iterdir()) == sorted(
            p.name for p in i_reg.iterdir()
        )
