"""Fleet engine tests: stacked vmapped training must reproduce the
single-machine path bit-for-bit (same RNG derivation, same padding), and
shard cleanly over the 8-virtual-device CPU mesh.

Reference test-strategy parity (SURVEY.md §5): "distributed" behavior is
asserted via single-host multi-device simulation, mirroring how the
reference asserts on generated Argo documents rather than live clusters.
"""

import numpy as np
import pytest

import jax

from gordo_tpu.models.estimator import AutoEncoder
from gordo_tpu.ops.scalers import MinMaxScaler
from gordo_tpu.parallel import (
    FleetDiffBuilder,
    fleet_apply,
    fleet_fit,
    fleet_mesh,
    stack_rows,
)
from gordo_tpu.parallel.anomaly import analyze_definition
from gordo_tpu.parallel.fleet import fit_data_parallel
from gordo_tpu.pipeline import Pipeline
from gordo_tpu.registry import lookup_factory
from gordo_tpu.serializer import from_definition
from gordo_tpu.train.fit import TrainConfig, fit as single_fit

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow


CFG = TrainConfig(epochs=3, batch_size=64, learning_rate=1e-3)


def _make_fleet_data(m=3, n=120, f=5, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((n, f)).astype(np.float32) for _ in range(m)]


def _hourglass(f):
    return lookup_factory("AutoEncoder", "feedforward_hourglass")(
        n_features=f, n_features_out=f
    )


class TestFleetFit:
    def test_matches_single_model_fits_exactly(self):
        Xs = _make_fleet_data()
        module = _hourglass(5)
        X, w, _ = stack_rows(Xs)
        res = fleet_fit(module, X, X, w, CFG, seeds=np.arange(3, dtype=np.uint32))

        per_model = res.unstack_params()
        for i, Xi in enumerate(Xs):
            params_i, hist_i = single_fit(
                module, Xi, Xi, CFG, rng=jax.random.PRNGKey(i)
            )
            for a, b in zip(
                jax.tree.leaves(per_model[i]), jax.tree.leaves(params_i)
            ):
                np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(res.history[i], hist_i, rtol=1e-5)

    def test_sharded_over_mesh_matches_unsharded(self):
        Xs = _make_fleet_data(m=5)  # deliberately not divisible by 8
        module = _hourglass(5)
        X, w, _ = stack_rows(Xs)
        seeds = np.arange(5, dtype=np.uint32)
        plain = fleet_fit(module, X, X, w, CFG, seeds=seeds)
        mesh = fleet_mesh()
        sharded = fleet_fit(module, X, X, w, CFG, seeds=seeds, mesh=mesh)
        assert sharded.n_models == 5
        for a, b in zip(
            jax.tree.leaves(plain.params), jax.tree.leaves(sharded.params)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b)[:5], rtol=1e-5, atol=1e-6)

    def test_ragged_lengths_are_masked(self):
        rng = np.random.default_rng(1)
        Xs = [
            rng.standard_normal((n, 4)).astype(np.float32) for n in (100, 80, 60)
        ]
        X, w, lengths = stack_rows(Xs)
        assert X.shape == (3, 100, 4)
        assert w.sum() == sum(lengths)
        module = _hourglass(4)
        res = fleet_fit(module, X, X, w, CFG)
        preds = fleet_apply(module, res.params, X)
        assert preds.shape == (3, 100, 4)
        assert np.isfinite(res.history).all()

    def test_data_parallel_single_model(self):
        rng = np.random.default_rng(2)
        X = rng.standard_normal((200, 6)).astype(np.float32)
        module = _hourglass(6)
        mesh = fleet_mesh(data_parallel=8)
        params, history = fit_data_parallel(module, X, X, CFG, mesh)
        assert np.isfinite(history).all()
        single_params, _ = single_fit(module, X, X, CFG)
        # same program, different sharding — results agree to float tolerance
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(single_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


DETECTOR_DEF = {
    "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.pipeline.Pipeline": {
                "steps": [
                    "gordo_tpu.ops.scalers.MinMaxScaler",
                    {
                        "gordo_tpu.models.estimator.AutoEncoder": {
                            "kind": "feedforward_hourglass",
                            "epochs": 3,
                            "batch_size": 64,
                        }
                    },
                ]
            }
        }
    }
}


class TestFleetDiffBuilder:
    def test_analyze_definition_accepts_canonical_config(self):
        model = from_definition(DETECTOR_DEF)
        spec = analyze_definition(model)
        assert spec is not None
        assert spec.train_cfg.epochs == 3
        assert isinstance(spec.signature, tuple)

    def test_analyze_definition_rejects_non_detector(self):
        assert analyze_definition(AutoEncoder()) is None
        assert analyze_definition(Pipeline([MinMaxScaler(), AutoEncoder()])) is None

    def test_fleet_build_matches_single_builds(self, sine_tags):
        m = 3
        rng = np.random.default_rng(7)
        Xs = [
            (sine_tags + 0.01 * rng.standard_normal(sine_tags.shape)).astype(
                np.float32
            )
            for _ in range(m)
        ]

        spec = analyze_definition(from_definition(DETECTOR_DEF))
        builder = FleetDiffBuilder(spec)
        detectors = builder.build(Xs)
        assert len(detectors) == m

        for i, Xi in enumerate(Xs):
            single = from_definition(DETECTOR_DEF)
            single.cross_validate(Xi)
            single.fit(Xi)

            fleet_det = detectors[i]
            # CV-fold statistics are EXACT: the fleet program materializes
            # each fold with the single path's own geometry and RNG (see
            # parallel/anomaly.py module docstring) — only float scheduling
            # noise remains.
            np.testing.assert_allclose(
                fleet_det.feature_thresholds_,
                single.feature_thresholds_,
                rtol=1e-4,
                atol=1e-6,
            )
            assert fleet_det.aggregate_threshold_ == pytest.approx(
                single.aggregate_threshold_, rel=1e-4
            )
            for name, stats in single.cv_metadata_["scores"].items():
                fleet_scores = fleet_det.cv_metadata_["scores"][name]
                np.testing.assert_allclose(
                    fleet_scores["folds"], stats["folds"], rtol=1e-3, atol=1e-5
                )
                assert fleet_scores["mean"] == pytest.approx(
                    stats["mean"], rel=1e-3, abs=1e-5
                )
            # The FINAL model is bit-identical: anomaly frames must agree.
            fa = fleet_det.anomaly(Xi)
            sa = single.anomaly(Xi)
            np.testing.assert_allclose(
                fa[("total-anomaly-score", "")].to_numpy(),
                sa[("total-anomaly-score", "")].to_numpy(),
                rtol=1e-4,
                atol=1e-5,
            )
            np.testing.assert_allclose(
                fa["model-output"].to_numpy(),
                sa["model-output"].to_numpy(),
                rtol=1e-4,
                atol=1e-5,
            )

    def test_fleet_build_on_mesh(self, sine_tags):
        spec = analyze_definition(from_definition(DETECTOR_DEF))
        mesh = fleet_mesh()
        detectors = FleetDiffBuilder(spec, mesh=mesh).build(
            [sine_tags, sine_tags * 1.1, sine_tags * 0.9]
        )
        assert len(detectors) == 3
        for det in detectors:
            assert np.isfinite(det.feature_thresholds_).all()
            assert det.aggregate_threshold_ > 0

    def test_fleet_build_lstm(self, sine_tags):
        definition = {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {
                                "gordo_tpu.models.estimator.LSTMAutoEncoder": {
                                    "kind": "lstm_hourglass",
                                    "lookback_window": 6,
                                    "epochs": 2,
                                    "batch_size": 64,
                                }
                            },
                        ]
                    }
                }
            }
        }
        X = sine_tags[:200]
        spec = analyze_definition(from_definition(definition))
        assert spec is not None
        detectors = FleetDiffBuilder(spec).build([X, X * 1.05])

        single = from_definition(definition)
        single.cross_validate(X)
        single.fit(X)
        np.testing.assert_allclose(
            detectors[0].feature_thresholds_,
            single.feature_thresholds_,
            rtol=1e-3,
            atol=1e-5,
        )
        assert detectors[0].aggregate_threshold_ == pytest.approx(
            single.aggregate_threshold_, rel=1e-3
        )
        # final model bit-identical (windowed path included)
        fa = detectors[0].anomaly(X)
        sa = single.anomaly(X)
        np.testing.assert_allclose(
            fa[("total-anomaly-score", "")].to_numpy(),
            sa[("total-anomaly-score", "")].to_numpy(),
            rtol=1e-3,
            atol=1e-4,
        )


def test_model_axis_pad_targets():
    """Machine-axis padding collapses counts onto log-many compiled
    shapes (pow2, then the mesh 'models'-axis multiple)."""
    from gordo_tpu.parallel.anomaly import _model_axis_pad

    assert [_model_axis_pad(m, None) for m in (1, 2, 3, 5, 272, 512)] == [
        1, 2, 4, 8, 512, 512,
    ]
    mesh = fleet_mesh()  # 8 virtual devices
    assert _model_axis_pad(3, mesh) == 8   # pow2 4, then mesh multiple 8
    assert _model_axis_pad(12, mesh) == 16


def test_pad_lengths_parity_on_already_aligned_data(sine_tags):
    """pad-up mode with machines ALREADY at the aligned length runs with
    all-ones masks — results must match the exact per-length program
    (same folds, same geometry, same RNG)."""
    Xs = [sine_tags[:400], (sine_tags[:400] * 1.1).astype(np.float32)]
    spec = analyze_definition(from_definition(DETECTOR_DEF))
    exact = FleetDiffBuilder(spec).build(Xs)
    padded = FleetDiffBuilder(spec, pad_lengths=100).build(Xs)

    for Xi, de, dp in zip(Xs, exact, padded):
        np.testing.assert_allclose(
            dp.feature_thresholds_, de.feature_thresholds_,
            rtol=1e-4, atol=1e-6,
        )
        assert dp.aggregate_threshold_ == pytest.approx(
            de.aggregate_threshold_, rel=1e-4
        )
        for name, stats in de.cv_metadata_["scores"].items():
            np.testing.assert_allclose(
                dp.cv_metadata_["scores"][name]["folds"], stats["folds"],
                rtol=1e-3, atol=1e-5,
            )
        np.testing.assert_allclose(
            dp.anomaly(Xi)[("total-anomaly-score", "")].to_numpy(),
            de.anomaly(Xi)[("total-anomaly-score", "")].to_numpy(),
            rtol=1e-4, atol=1e-5,
        )


def test_pad_lengths_ragged_one_program_zero_rows_dropped(
    sine_tags, monkeypatch
):
    """16 distinct row counts inside one pad boundary -> ONE masked
    program (not 16 exact ones), with every real row trained and sane
    finite thresholds for every machine."""
    from gordo_tpu.parallel import anomaly as anomaly_mod

    lengths = [400 - 6 * i for i in range(16)]       # 400..310, all -> 400
    Xs = [sine_tags[:L] for L in lengths]
    spec = analyze_definition(from_definition(DETECTOR_DEF))

    calls = []
    orig = FleetDiffBuilder._dispatch_group

    def counting(self, X, y, lens=None, warm=None):
        calls.append((X.shape, None if lens is None else tuple(lens)))
        return orig(self, X, y, lens=lens, warm=warm)

    monkeypatch.setattr(
        anomaly_mod.FleetDiffBuilder, "_dispatch_group", counting
    )

    detectors = FleetDiffBuilder(spec, pad_lengths=100).build(Xs)
    assert len(calls) == 1                            # O(1) compiles
    shape, lens = calls[0]
    assert shape == (16, 400, sine_tags.shape[1])
    assert sorted(lens) == sorted(lengths)            # zero rows dropped

    for Xi, det in zip(Xs, detectors):
        assert np.all(np.isfinite(det.feature_thresholds_))
        assert det.feature_thresholds_.min() > 0
        assert np.isfinite(det.aggregate_threshold_)
        scores = det.anomaly(Xi)
        assert len(scores) == len(Xi)                 # all rows score


def test_pad_lengths_too_short_machine_demotes_to_exact(sine_tags, caplog):
    """A machine whose real rows would miss an entire CV test block at the
    padded length must NOT get silently-zero thresholds — it builds through
    the exact per-length path instead (with a warning)."""
    import logging

    # 600-row pad boundary: TimeSeriesSplit(3) test blocks start at 150/
    # 300/450 — an 80-row machine would contribute no real test rows
    Xs = [sine_tags[:600], sine_tags[:80]]
    spec = analyze_definition(from_definition(DETECTOR_DEF))
    with caplog.at_level(logging.WARNING, logger="gordo_tpu.parallel.anomaly"):
        detectors = FleetDiffBuilder(spec, pad_lengths=600).build(Xs)
    assert any("exact per-length path" in r.message for r in caplog.records)

    # the short machine matches its single-machine build exactly
    single = from_definition(DETECTOR_DEF)
    single.cross_validate(Xs[1])
    single.fit(Xs[1])
    np.testing.assert_allclose(
        detectors[1].feature_thresholds_, single.feature_thresholds_,
        rtol=1e-4, atol=1e-6,
    )
    assert detectors[1].feature_thresholds_.min() > 0
    assert detectors[0].feature_thresholds_.min() > 0


def test_pad_lengths_shuffled_splitter_demotes_to_exact(sine_tags, caplog):
    """Pad-up exactness requires contiguous fold blocks; a shuffled
    splitter must demote the group to the exact path, not silently train
    on windows interleaved with padding."""
    import logging

    from sklearn.model_selection import KFold

    Xs = [sine_tags[:350], sine_tags[:400]]
    spec = analyze_definition(from_definition(DETECTOR_DEF))
    builder = FleetDiffBuilder(
        spec, cv=KFold(n_splits=3, shuffle=True, random_state=0),
        pad_lengths=100,
    )
    with caplog.at_level(logging.WARNING, logger="gordo_tpu.parallel.anomaly"):
        detectors = builder.build(Xs)
    assert any("non-contiguous" in r.message for r in caplog.records)
    for det in detectors:
        assert np.all(np.isfinite(det.feature_thresholds_))
        assert not getattr(det, "pad_built_", False)  # exact-path builds


def test_fleet_build_ragged_lengths_exact(sine_tags):
    """Machines of DIFFERENT lengths in one bucket: each length-group runs
    its own exact program, so every machine (not just the longest) matches
    its single-machine build."""
    Xs = [sine_tags[:400], sine_tags[:280], sine_tags[:400] * 1.1]
    spec = analyze_definition(from_definition(DETECTOR_DEF))
    detectors = FleetDiffBuilder(spec).build(Xs)

    for Xi, fleet_det in zip(Xs, detectors):
        single = from_definition(DETECTOR_DEF)
        single.cross_validate(Xi)
        single.fit(Xi)
        np.testing.assert_allclose(
            fleet_det.feature_thresholds_,
            single.feature_thresholds_,
            rtol=1e-4,
            atol=1e-6,
        )
        assert fleet_det.aggregate_threshold_ == pytest.approx(
            single.aggregate_threshold_, rel=1e-4
        )
        np.testing.assert_allclose(
            fleet_det.anomaly(Xi)[("total-anomaly-score", "")].to_numpy(),
            single.anomaly(Xi)[("total-anomaly-score", "")].to_numpy(),
            rtol=1e-4,
            atol=1e-5,
        )
