"""Compile plane: AOT program registry, warmup manifest round-trip,
warming→ready readiness, persistent-cache reuse across a process restart.

The acceptance-critical pins (ISSUE 5):

- serving results byte-identical with warmup on vs off (the AOT
  executable and the jit path are the same HLO);
- build → manifest → server pre-compile round-trip: what the builder
  records is what warmup compiles, and the first request after warmup
  dispatches a cache HIT, not a compile;
- ``/healthz`` reports ``warming`` under concurrent traffic and flips to
  ``ready`` exactly when the warmup future resolves;
- a forked process pointed at the same ``GORDO_COMPILE_CACHE_DIR``
  reuses the parent population's compiles (slow lane).
"""

import asyncio
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from gordo_tpu import compile as compile_plane
from gordo_tpu import telemetry
from gordo_tpu.builder import build_project
from gordo_tpu.compile import (
    load_warmup_manifest,
    warmup_collection,
    write_warmup_manifest,
)
from gordo_tpu.serve import ModelCollection, build_app
from gordo_tpu.workflow import NormalizedConfig

PROJECT = {
    "machines": [
        {
            "name": f"cp-machine-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tags": ["tag-1", "tag-2", "tag-3"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-27T06:00:00Z",
            },
        }
        for i in range(3)
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {
                                "gordo_tpu.models.estimator.AutoEncoder": {
                                    "kind": "feedforward_hourglass",
                                    "epochs": 2,
                                    "batch_size": 64,
                                }
                            },
                        ]
                    }
                }
            }
        }
    },
}


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("cp-artifacts")
    cfg = NormalizedConfig(PROJECT, "cpproj")
    result = build_project(cfg.machines, str(out))
    assert not result.failed
    return str(out)


# ---------------------------------------------------------------------------
# Program registry
# ---------------------------------------------------------------------------

def test_program_aot_matches_jit_bitwise():
    import jax.numpy as jnp

    def f(mode, stats, x):
        y = x * stats["a"] + stats["b"]
        return {"out": y if mode == "double" else -y}

    prog = compile_plane.Program("test.parity", f, static_argnames=("mode",))
    stats = {"a": jnp.full((4,), 1.5), "b": jnp.full((4,), -0.25)}
    x = np.random.default_rng(0).standard_normal((8, 4)).astype(np.float32)
    via_plane = prog("double", stats, x)
    via_jit = prog._jitted("double", stats, x)
    np.testing.assert_array_equal(
        np.asarray(via_plane["out"]), np.asarray(via_jit["out"])
    )


def test_program_warm_precompiles_and_call_hits():
    import jax
    import jax.numpy as jnp

    def g(x):
        return x + 1.0

    prog = compile_plane.Program("test.warm", g)
    sds = jax.ShapeDtypeStruct((5,), jnp.float32)
    first = prog.warm(sds)
    assert first > 0.0  # compiled now
    assert prog.warm(sds) == 0.0  # second warm is a no-op
    reg = telemetry.REGISTRY.snapshot()
    before = _counter(reg, "gordo_compile_cache_hits_total", "programs")
    out = prog(np.arange(5, dtype=np.float32))
    np.testing.assert_array_equal(
        np.asarray(out), np.arange(5, dtype=np.float32) + 1.0
    )
    after = _counter(
        telemetry.REGISTRY.snapshot(), "gordo_compile_cache_hits_total",
        "programs",
    )
    assert after == before + 1  # the real call hit the warmed executable


def _counter(snapshot, name, label_value):
    metric = snapshot["metrics"].get(name) or {}
    for key, value in metric.get("series", {}).items():
        if label_value in json.loads(key):
            return value
    return 0.0


def test_registry_lru_evicts_executables():
    import jax
    import jax.numpy as jnp

    reg = compile_plane.CompileRegistry(max_executables=2)

    def h(x):
        return x * 3.0

    prog = compile_plane.Program("test.evict", h, registry=reg)
    for n in (2, 3, 4):
        prog.warm(jax.ShapeDtypeStruct((n,), jnp.float32))
    assert reg.n_executables() == 2  # the first signature evicted


def test_cached_closure_shares_one_policy():
    calls = []

    def factory():
        calls.append(1)
        return object()

    a = compile_plane.cached_closure(("test.closure", 1), factory)
    b = compile_plane.cached_closure(("test.closure", 1), factory)
    assert a is b and len(calls) == 1


def test_plane_kill_switch_uses_plain_jit(monkeypatch):
    monkeypatch.setenv("GORDO_COMPILE_PLANE", "off")

    def f(x):
        return x - 2.0

    prog = compile_plane.Program("test.off", f)
    out = prog(np.arange(3, dtype=np.float32))
    np.testing.assert_array_equal(
        np.asarray(out), np.arange(3, dtype=np.float32) - 2.0
    )
    assert prog._registry._get_executable is not None  # nothing cached:
    # plain-jit dispatch leaves the AOT cache untouched for this call
    # (the registry may hold entries from other tests; assert via name)
    assert not any(
        key[0] == "test.off" for key in prog._registry._executables
    )


def test_closure_program_warm_precompiles_and_call_hits():
    """r23: the fleet-build closures get the Program warm/call contract —
    a warmed signature dispatches the AOT executable (cache HIT), and the
    result is bitwise the jitted closure's."""
    import jax
    import jax.numpy as jnp

    scale = 2.5  # the closed-over configuration

    def f(x):
        return x * scale

    prog = compile_plane.closure_program(f, name="test.closure_warm")
    sds = jax.ShapeDtypeStruct((6,), jnp.float32)
    assert prog.warm(sds) > 0.0   # compiled now
    assert prog.warm(sds) == 0.0  # idempotent
    before = _counter(
        telemetry.REGISTRY.snapshot(), "gordo_compile_cache_hits_total",
        "programs",
    )
    x = np.arange(6, dtype=np.float32)
    out = prog(x)
    np.testing.assert_array_equal(np.asarray(out), x * scale)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(prog._jitted(x))
    )
    after = _counter(
        telemetry.REGISTRY.snapshot(), "gordo_compile_cache_hits_total",
        "programs",
    )
    assert after == before + 1


def test_closure_program_cold_and_unwarmed_signatures_fall_through():
    """A never-warmed closure (the common cold build) and a warmed one
    called at a DIFFERENT signature both dispatch through plain jit —
    same numerics, nothing cached for the unseen shape."""
    import jax
    import jax.numpy as jnp

    def f(x):
        return x + 10.0

    cold = compile_plane.closure_program(f, name="test.closure_cold")
    assert not cold._exes
    x = np.arange(4, dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(cold(x)), x + 10.0)
    assert not cold._exes  # __call__ never populates the AOT dict

    warmed = compile_plane.closure_program(f, name="test.closure_other")
    warmed.warm(jax.ShapeDtypeStruct((4,), jnp.float32))
    y = np.arange(7, dtype=np.float32)  # signature never warmed
    np.testing.assert_array_equal(np.asarray(warmed(y)), y + 10.0)
    assert len(warmed._exes) == 1


def test_closure_program_kill_switch_uses_plain_jit(monkeypatch):
    monkeypatch.setenv("GORDO_COMPILE_PLANE", "off")

    def f(x):
        return x - 1.0

    prog = compile_plane.closure_program(f, name="test.closure_off")
    import jax
    import jax.numpy as jnp

    assert prog.warm(jax.ShapeDtypeStruct((3,), jnp.float32)) == 0.0
    assert not prog._exes  # plane off: nothing compiles ahead of time
    x = np.arange(3, dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(prog(x)), x - 1.0)


def test_fleet_builder_warm_precompiles_group_program():
    """FleetDiffBuilder.warm pre-compiles the bucket's program from shapes
    alone: the subsequent dispatch of a matching group is an AOT hit."""
    from gordo_tpu.parallel.anomaly import FleetDiffBuilder, analyze_definition
    from gordo_tpu.serializer import from_definition

    definition = {
        "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.pipeline.Pipeline": {
                    "steps": [
                        "gordo_tpu.ops.scalers.MinMaxScaler",
                        {
                            "gordo_tpu.models.estimator.AutoEncoder": {
                                "kind": "feedforward_hourglass",
                                "epochs": 1,
                                "batch_size": 64,
                            }
                        },
                    ]
                }
            }
        }
    }
    spec = analyze_definition(from_definition(definition))
    builder = FleetDiffBuilder(spec)
    dt = builder.warm(m=2, n_rows=220, n_features=3)
    assert dt > 0.0
    assert builder.warm(m=2, n_rows=220, n_features=3) == 0.0
    before = _counter(
        telemetry.REGISTRY.snapshot(), "gordo_compile_cache_hits_total",
        "programs",
    )
    rng = np.random.default_rng(3)
    Xs = [rng.standard_normal((220, 3)).astype(np.float32) for _ in range(2)]
    dets = builder.dispatch(Xs).collect()
    assert len(dets) == 2
    after = _counter(
        telemetry.REGISTRY.snapshot(), "gordo_compile_cache_hits_total",
        "programs",
    )
    assert after == before + 1  # the dispatch hit the warmed executable


# ---------------------------------------------------------------------------
# warmup manifest round-trip
# ---------------------------------------------------------------------------

def test_build_writes_warmup_manifest(model_dir):
    manifest = load_warmup_manifest(model_dir)
    assert manifest is not None
    machines = {
        name for entry in manifest["programs"] for name in entry["machines"]
    }
    assert machines == {f"cp-machine-{i}" for i in range(3)}
    entry = manifest["programs"][0]
    assert entry["n_features"] == 3 and entry["n_outputs"] == 3
    assert entry["signature"]
    assert manifest["row_buckets"] == [256, 2048]


def test_manifest_merge_keeps_disjoint_entries(tmp_path):
    out = str(tmp_path)
    write_warmup_manifest(
        out, [{"signature": "aaa", "machines": ["m1"], "n_machines": 1,
               "n_features": 2, "n_outputs": 2, "lookback": 1}]
    )
    # a later partial rebuild of a DIFFERENT machine merges, not clobbers
    write_warmup_manifest(
        out, [{"signature": "bbb", "machines": ["m2"], "n_machines": 1,
               "n_features": 2, "n_outputs": 2, "lookback": 1}]
    )
    # rebuilding m1 replaces its entry
    write_warmup_manifest(
        out, [{"signature": "ccc", "machines": ["m1"], "n_machines": 1,
               "n_features": 2, "n_outputs": 2, "lookback": 1}]
    )
    manifest = load_warmup_manifest(out)
    by_machine = {e["machines"][0]: e["signature"]
                  for e in manifest["programs"]}
    assert by_machine == {"m1": "ccc", "m2": "bbb"}
    # an empty (fully-cached) re-run leaves the manifest untouched
    assert write_warmup_manifest(out, []) is None
    assert load_warmup_manifest(out)["programs"] == manifest["programs"]


def test_manifest_carries_serving_dtype(tmp_path, monkeypatch):
    """v2 manifests record the build-time serving dtype: the env knob at
    write time wins, an explicit argument overrides it, and a v1
    manifest (no dtype field) reads back as float32."""
    out = str(tmp_path)
    entry = [{"signature": "sig", "machines": ["m1"], "n_machines": 1,
              "n_features": 2, "n_outputs": 2, "lookback": 1}]
    monkeypatch.setenv("GORDO_SERVE_DTYPE", "bf16")
    write_warmup_manifest(out, entry)
    manifest = load_warmup_manifest(out)
    assert manifest["dtype"] == "bfloat16"
    monkeypatch.delenv("GORDO_SERVE_DTYPE")
    # explicit argument beats the (now unset) env
    write_warmup_manifest(out, entry, serve_dtype="float32")
    assert load_warmup_manifest(out)["dtype"] == "float32"
    # a v1 manifest (pre-dtype) reads as float32
    import os as _os

    shard = _os.path.join(out, ".gordo-warmup",
                          "shard-000-of-001.json")
    doc = json.load(open(shard))
    doc.pop("dtype")
    doc["version"] = 1
    json.dump(doc, open(shard, "w"))
    assert load_warmup_manifest(out)["dtype"] == "float32"


def test_manifest_mixed_shard_dtypes_yield_none(tmp_path):
    """Shards disagreeing on dtype (a half-finished precision migration)
    must not let warmup guess — the manifest dtype reads as None and the
    serve plane falls back to its env resolution."""
    out = str(tmp_path)
    write_warmup_manifest(
        out, [{"signature": "a", "machines": ["m1"], "n_machines": 1,
               "n_features": 2, "n_outputs": 2, "lookback": 1}],
        shard=(0, 2), serve_dtype="float32",
    )
    write_warmup_manifest(
        out, [{"signature": "b", "machines": ["m2"], "n_machines": 1,
               "n_features": 2, "n_outputs": 2, "lookback": 1}],
        shard=(1, 2), serve_dtype="bfloat16",
    )
    assert load_warmup_manifest(out)["dtype"] is None


def test_bf16_manifest_warms_bf16_executables(model_dir, tmp_path, monkeypatch):
    """The dtype round-trip pin (ISSUE 7 satellite): a manifest written
    under bf16 must warm bf16 executables, not fp32 ones — and the
    collection built over it must DISPATCH bf16, so the warmed
    executables are the ones requests hit."""
    import shutil

    from gordo_tpu.compile.registry import REGISTRY

    # private copy: rewriting the shared module fixture's manifest would
    # leak bf16 into every other test using model_dir
    work = str(tmp_path / "bf16-artifacts")
    shutil.copytree(model_dir, work)
    manifest = load_warmup_manifest(work)
    monkeypatch.setenv("GORDO_SERVE_DTYPE", "bfloat16")
    write_warmup_manifest(
        work,
        [e for e in manifest["programs"]],
    )
    monkeypatch.delenv("GORDO_SERVE_DTYPE")
    assert load_warmup_manifest(work)["dtype"] == "bfloat16"

    # env UNSET: the manifest's dtype must drive both warmup and dispatch
    REGISTRY.clear()
    collection = ModelCollection.from_directory(work, project="cpproj")
    assert collection.serve_dtype == "bfloat16"
    stats = warmup_collection(collection)
    assert stats["errors"] == 0
    assert stats["dtype"] == "bfloat16"
    serve_keys = [
        key for key in REGISTRY._executables
        if str(key[0]).startswith("serve.")
    ]
    assert serve_keys, "warmup compiled no serving executables"
    for key in serve_keys:
        statics = dict(key[1])
        assert statics.get("dtype") == "bfloat16", key
    # and a real request hits a warmed executable, not a fresh compile
    reg = telemetry.REGISTRY.snapshot()
    before_miss = _counter(reg, "gordo_compile_cache_misses_total",
                           "programs")
    X = np.random.default_rng(3).standard_normal((256, 3)).astype(np.float32)
    collection.get("cp-machine-0").scorer.anomaly_arrays(X)
    after_miss = _counter(
        telemetry.REGISTRY.snapshot(),
        "gordo_compile_cache_misses_total", "programs",
    )
    assert after_miss == before_miss  # warmed, not compiled on request


def test_warmup_collection_precompiles_from_manifest(model_dir):
    collection = ModelCollection.from_directory(model_dir, project="cpproj")
    stats = warmup_collection(collection)
    assert stats["errors"] == 0
    assert stats["buckets"] == 1
    labels = {p["program"] for p in stats["programs"]}
    assert "serve.fleet/full" in labels
    assert "serve.fleet/subset" in labels
    assert "serve.score/anomaly" in labels
    # the streaming plane's incremental step warms alongside (rows=1 —
    # its dispatch shape is always one arriving row)
    assert "serve.stream_step" in labels
    # manifest row buckets drove the warm set
    rows = {p["rows"] for p in stats["programs"]}
    assert rows == {1, 256, 2048}


def test_serving_results_identical_warmup_on_vs_off(model_dir):
    """The acceptance parity pin: a warmed collection returns byte-for-
    byte what an unwarmed one does (same machines, same request)."""
    rng = np.random.default_rng(7)
    X = rng.standard_normal((300, 3)).astype(np.float32)

    warmed = ModelCollection.from_directory(model_dir, project="cpproj")
    assert warmup_collection(warmed)["errors"] == 0
    res_warm = warmed.fleet_scorer.score_all(
        {name: X for name in warmed.entries}
    )
    cold = ModelCollection.from_directory(model_dir, project="cpproj")
    res_cold = cold.fleet_scorer.score_all(
        {name: X for name in cold.entries}
    )
    assert set(res_warm) == set(res_cold)
    for name in res_warm:
        for key in res_warm[name]:
            np.testing.assert_array_equal(
                np.asarray(res_warm[name][key]),
                np.asarray(res_cold[name][key]),
                err_msg=f"{name}/{key} diverged between warmup on and off",
            )
    # per-machine route parity too
    e_warm = warmed.get(sorted(warmed.entries)[0])
    e_cold = cold.get(sorted(cold.entries)[0])
    a, b = e_warm.scorer.anomaly_arrays(X), e_cold.scorer.anomaly_arrays(X)
    for key in a:
        np.testing.assert_array_equal(np.asarray(a[key]), np.asarray(b[key]))


# ---------------------------------------------------------------------------
# warming → ready readiness under concurrent requests
# ---------------------------------------------------------------------------

def test_healthz_warming_to_ready_under_concurrent_requests(
    model_dir, monkeypatch
):
    """/healthz says ``warming`` while the warmup thread runs, requests
    issued DURING warming still succeed, and the state flips to ``ready``
    (with the compile plane's warming flag cleared) when it finishes."""
    from gordo_tpu.serve import server as server_mod

    release = threading.Event()
    started = threading.Event()

    def slow_warmup(collection, row_sizes=None):
        started.set()
        assert compile_plane.warming()  # the flag is up while we compile
        release.wait(timeout=30)
        return {"buckets": 1, "fallbacks": 0, "errors": 0, "programs": []}

    monkeypatch.setattr(server_mod, "warmup_scorers", slow_warmup)

    async def runner():
        collection = ModelCollection.from_directory(
            model_dir, project="cpproj"
        )
        client = TestClient(TestServer(build_app(collection, warmup=True)))
        await client.start_server()
        try:
            assert started.wait(timeout=10)
            # concurrent traffic during warming: state reports warming,
            # scoring requests still serve (they compile lazily)
            X = np.zeros((300, 3), np.float32).tolist()
            health, ready, score = await asyncio.gather(
                client.get("/healthz"),
                client.get("/gordo/v0/cpproj/ready"),
                client.post(
                    "/gordo/v0/cpproj/cp-machine-0/anomaly/prediction",
                    json={"X": X},
                ),
            )
            assert (await health.json())["state"] == "warming"
            assert ready.status == 503
            assert score.status == 200
            release.set()
            await _wait(client.app[server_mod.WARMUP_TASK_KEY])
            health2 = await client.get("/healthz")
            doc = await health2.json()
            assert doc["state"] == "ready"
            assert doc["warmup_errors"] == 0
            assert (await client.get("/gordo/v0/cpproj/ready")).status == 200
            assert not compile_plane.warming()
        finally:
            release.set()
            await client.close()

    async def _wait(fut):
        while not fut.done():
            await asyncio.sleep(0.01)

    asyncio.run(runner())


def test_coalescer_queues_while_warming(monkeypatch):
    """During warmup the coalescer coalesces unconditionally (queue
    behind the shared compile) instead of bypass-dispatching a cold
    compile per executor thread."""
    from gordo_tpu.serve.coalesce import CoalescingScorer

    co = CoalescingScorer(lambda: None, knee_batch=4)
    try:
        co.inflight = 1  # below min_concurrency: would normally bypass
        compile_plane.set_warming(True)
        try:
            assert co.should_coalesce() is True
        finally:
            compile_plane.set_warming(False)
        assert co.should_coalesce() is False  # back to the adaptive bypass
    finally:
        co.close()


# ---------------------------------------------------------------------------
# CLI gate
# ---------------------------------------------------------------------------

def test_gordo_warmup_dir_cli(model_dir):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import gordo

    res = CliRunner().invoke(gordo, ["warmup", "--dir", model_dir])
    assert res.exit_code == 0, res.output
    assert "serve.fleet/full" in res.output
    assert "error(s)" in res.output


def test_gordo_warmup_dir_cli_fails_on_compile_error(model_dir, monkeypatch):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import gordo

    def broken(collection, row_sizes=None, manifest=None):
        return {"buckets": 0, "fallbacks": 0, "errors": 2, "programs": [],
                "compile_seconds": 0.0}

    monkeypatch.setattr("gordo_tpu.compile.warmup_collection", broken)
    res = CliRunner().invoke(gordo, ["warmup", "--dir", model_dir])
    assert res.exit_code == 1


def test_gordo_warmup_requires_exactly_one_target():
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import gordo

    assert CliRunner().invoke(gordo, ["warmup"]).exit_code != 0
    assert CliRunner().invoke(
        gordo, ["warmup", "--dir", "x", "--url", "http://y"]
    ).exit_code != 0


# ---------------------------------------------------------------------------
# persistent-cache reuse across a forked-process restart (slow lane)
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, os, sys, time
import jax, jax.numpy as jnp
from gordo_tpu.utils.compile_cache import enable_persistent_compile_cache
from gordo_tpu import compile as compile_plane, telemetry

assert enable_persistent_compile_cache(), "cache must engage under force"

def f(x):
    return jnp.tanh(x @ x.T).sum()

prog = compile_plane.Program("test.persist", f)
t0 = time.perf_counter()
prog.warm(jax.ShapeDtypeStruct((64, 64), jnp.float32))
dt = time.perf_counter() - t0
hits = misses = 0
for line in telemetry.render().splitlines():
    if line.startswith('gordo_compile_cache_hits_total{cache="persistent"}'):
        hits = float(line.rsplit(" ", 1)[1])
    if line.startswith('gordo_compile_cache_misses_total{cache="persistent"}'):
        misses = float(line.rsplit(" ", 1)[1])
print(json.dumps({"compile_s": dt, "hits": hits, "misses": misses}))
"""


@pytest.mark.slow
def test_persistent_cache_reused_across_forked_restart(tmp_path):
    """Two fresh processes sharing GORDO_COMPILE_CACHE_DIR: the first
    populates the on-disk cache (a persistent miss), the restart loads
    the executable from disk (a persistent hit, attested by the
    compile-plane counters) — the forked-worker / server-restart reuse
    path of ISSUE 5, in miniature."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # force: CPU is excluded by default (AOT feature-mismatch hazard);
        # back-to-back children on one machine are the trusted case
        "GORDO_COMPILE_CACHE": "force",
        "GORDO_COMPILE_CACHE_DIR": str(tmp_path / "xla"),
        "GORDO_COMPILE_CACHE_MIN_SECONDS": "0",
    })

    def run():
        res = subprocess.run(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            timeout=180,
        )
        assert res.returncode == 0, res.stderr[-2000:]
        return json.loads(res.stdout.strip().splitlines()[-1])

    first = run()
    assert first["misses"] >= 1  # populated the disk cache
    restart = run()
    assert restart["hits"] >= 1, restart  # the restart loaded from disk
    assert os.listdir(str(tmp_path / "xla"))  # entries actually on disk
