"""ML-server integration tests (reference: Flask ``app.test_client()``
against a real artifact built once per session, SURVEY.md §5 "Server
integration"). Here: aiohttp TestClient driven through ``asyncio.run``."""

import asyncio

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from gordo_tpu import serializer
from gordo_tpu.builder import build_project
from gordo_tpu.serve import ModelCollection, build_app
from gordo_tpu.serve.scorer import CompiledScorer
from gordo_tpu.workflow import NormalizedConfig

PROJECT = {
    "machines": [
        {
            "name": "machine-a",
            "dataset": {
                "type": "RandomDataset",
                "tags": ["tag-1", "tag-2", "tag-3"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-27T06:00:00Z",
            },
        },
        {
            "name": "machine-b",
            "dataset": {
                "type": "RandomDataset",
                "tags": ["tag-1", "tag-2", "tag-3"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-27T06:00:00Z",
            },
        },
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {
                                "gordo_tpu.models.estimator.AutoEncoder": {
                                    "kind": "feedforward_hourglass",
                                    "epochs": 2,
                                    "batch_size": 64,
                                }
                            },
                        ]
                    }
                }
            }
        }
    },
}


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("artifacts")
    cfg = NormalizedConfig(PROJECT, "testproj")
    result = build_project(cfg.machines, str(out))
    assert not result.failed
    return str(out)


def _call(model_dir, fn):
    """Run coroutine ``fn(client)`` against a fresh test client."""

    async def runner():
        collection = ModelCollection.from_directory(model_dir, project="testproj")
        client = TestClient(TestServer(build_app(collection)))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()

    return asyncio.run(runner())


X_ROWS = [[0.1, 0.5, 0.9]] * 40


class TestServerRoutes:
    def test_project_index(self, model_dir):
        async def fn(client):
            resp = await client.get("/gordo/v0/testproj/")
            assert resp.status == 200
            return await resp.json()

        body = _call(model_dir, fn)
        assert body["machines"] == ["machine-a", "machine-b"]
        assert body["project-name"] == "testproj"

    def test_healthcheck_and_metadata(self, model_dir):
        async def fn(client):
            h = await client.get("/gordo/v0/testproj/machine-a/healthcheck")
            m = await client.get("/gordo/v0/testproj/machine-a/metadata")
            return h.status, await m.json()

        status, meta = _call(model_dir, fn)
        assert status == 200
        assert meta["metadata"]["name"] == "machine-a"
        assert meta["metadata"]["model"]["fleet_built"] is True

    def test_unknown_machine_404(self, model_dir):
        async def fn(client):
            resp = await client.get("/gordo/v0/testproj/nope/healthcheck")
            return resp.status

        assert _call(model_dir, fn) == 404

    def test_prediction_roundtrip(self, model_dir):
        async def fn(client):
            resp = await client.post(
                "/gordo/v0/testproj/machine-a/prediction", json={"X": X_ROWS}
            )
            return resp.status, await resp.json()

        status, body = _call(model_dir, fn)
        assert status == 200
        out = np.asarray(body["data"]["model-output"])
        assert out.shape == (40, 3)
        assert np.isfinite(out).all()
        assert body["time-seconds"] >= 0

    def test_prediction_record_payload(self, model_dir):
        records = [{"tag-1": 0.1, "tag-2": 0.5, "tag-3": 0.9}] * 10

        async def fn(client):
            resp = await client.post(
                "/gordo/v0/testproj/machine-a/prediction", json={"X": records}
            )
            return resp.status, await resp.json()

        status, body = _call(model_dir, fn)
        assert status == 200
        assert np.asarray(body["data"]["model-output"]).shape == (10, 3)

    def test_prediction_validation_errors(self, model_dir):
        async def fn(client):
            wrong_width = await client.post(
                "/gordo/v0/testproj/machine-a/prediction",
                json={"X": [[1.0, 2.0]]},
            )
            no_x = await client.post(
                "/gordo/v0/testproj/machine-a/prediction", json={"nope": 1}
            )
            return wrong_width.status, no_x.status

        assert _call(model_dir, fn) == (400, 400)

    def test_anomaly_prediction(self, model_dir):
        async def fn(client):
            resp = await client.post(
                "/gordo/v0/testproj/machine-a/anomaly/prediction",
                json={"X": X_ROWS},
            )
            return resp.status, await resp.json()

        status, body = _call(model_dir, fn)
        assert status == 200
        data = body["data"]
        assert np.asarray(data["tag-anomaly-scores"]).shape == (40, 3)
        assert len(data["total-anomaly-score"]) == 40
        assert data["total-anomaly-threshold"] > 0
        assert len(data["tag-anomaly-thresholds"]) == 3

    def test_download_model(self, model_dir):
        async def fn(client):
            resp = await client.get(
                "/gordo/v0/testproj/machine-a/download-model"
            )
            return resp.status, await resp.read()

        status, raw = _call(model_dir, fn)
        assert status == 200
        model = serializer.loads(raw)
        assert hasattr(model, "anomaly")


class TestCompiledScorer:
    def test_fused_matches_model_methods(self, model_dir):
        from gordo_tpu import artifacts

        _, refs = artifacts.discover(model_dir)
        model = next(r for r in refs if r.name == "machine-a").load_model()
        scorer = CompiledScorer(model)
        assert scorer.fused

        X = np.random.default_rng(3).standard_normal((50, 3)).astype(np.float32)
        np.testing.assert_allclose(
            scorer.predict(X), model.predict(X), rtol=1e-5, atol=1e-6
        )
        out = scorer.anomaly_arrays(X)
        frame = model.anomaly(X)
        np.testing.assert_allclose(
            out["total-anomaly-score"],
            frame[("total-anomaly-score", "")].to_numpy(),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_shape_buckets_reuse_compilation(self, model_dir):
        from gordo_tpu import artifacts

        _, refs = artifacts.discover(model_dir)
        model = next(r for r in refs if r.name == "machine-a").load_model()
        scorer = CompiledScorer(model)
        for n in (10, 40, 63, 64, 65, 200):
            out = scorer.predict(np.zeros((n, 3), np.float32))
            assert out.shape == (n, 3)


class TestScorerContractParity:
    """Fused path must match DiffBasedAnomalyDetector.anomaly semantics."""

    def _fitted_detector(self, sine_tags, window=None, cv=True):
        from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector
        from gordo_tpu.models.estimator import AutoEncoder
        from gordo_tpu.ops.scalers import MinMaxScaler
        from gordo_tpu.pipeline import Pipeline

        det = DiffBasedAnomalyDetector(
            base_estimator=Pipeline(
                [MinMaxScaler(), AutoEncoder(epochs=2, batch_size=64)]
            ),
            window=window,
        )
        if cv:
            det.cross_validate(sine_tags)
        det.fit(sine_tags)
        return det

    def test_window_smoothing_matches_model(self, sine_tags):
        det = self._fitted_detector(sine_tags, window=5)
        scorer = CompiledScorer(det)
        assert scorer.fused
        X = sine_tags[:80]
        out = scorer.anomaly_arrays(X)
        frame = det.anomaly(X)
        np.testing.assert_allclose(
            out["total-anomaly-score"],
            frame[("total-anomaly-score", "")].to_numpy(),
            rtol=1e-5,
            atol=1e-6,
        )
        np.testing.assert_allclose(
            out["tag-anomaly-scores"],
            frame["tag-anomaly-scores"].to_numpy(),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_blocked_rolling_median_equals_one_shot(self):
        from gordo_tpu.serve.scorer import (
            _rolling_median,
            _rolling_median_blocked,
        )
        import jax.numpy as jnp

        rng = np.random.default_rng(3)
        a = rng.standard_normal((101, 4)).astype(np.float32)
        a[rng.random((101, 4)) < 0.05] = np.nan  # NaNs must not diverge
        for window in (1, 5, 16):
            ref = np.asarray(_rolling_median(jnp.asarray(a), window))
            for block in (1, 7, 64, 101, 200):
                got = np.asarray(
                    _rolling_median_blocked(jnp.asarray(a), window, block)
                )
                np.testing.assert_allclose(
                    got, ref, rtol=1e-6, atol=1e-7, equal_nan=True,
                    err_msg=f"window={window} block={block}",
                )

    def test_over_bound_smoothing_stays_fused_and_exact(
        self, sine_tags, monkeypatch
    ):
        """Requests whose smoothing windows tensor exceeds the device
        bound must score through the blocked fused path (not the host
        pandas fallback) and still match the model exactly."""
        import gordo_tpu.serve.scorer as sc_mod

        det = self._fitted_detector(sine_tags, window=5)
        scorer = CompiledScorer(det)
        monkeypatch.setattr(sc_mod, "SMOOTH_ONE_SHOT_BOUND", 1)
        monkeypatch.setattr(sc_mod, "SMOOTH_BLOCK_TARGET", 60)
        host_calls = []
        orig_anomaly = det.anomaly
        monkeypatch.setattr(
            det, "anomaly",
            lambda *a, **k: host_calls.append(1) or orig_anomaly(*a, **k),
        )
        X = sine_tags[:80]
        out = scorer.anomaly_arrays(X)
        assert not host_calls, "fell back to the host path"
        frame = orig_anomaly(X)
        np.testing.assert_allclose(
            out["total-anomaly-score"],
            frame[("total-anomaly-score", "")].to_numpy(),
            rtol=1e-5, atol=1e-6,
        )

    def test_require_thresholds_raises_like_model(self, sine_tags):
        det = self._fitted_detector(sine_tags, cv=False)  # no thresholds
        scorer = CompiledScorer(det)
        assert scorer.fused
        with pytest.raises(AttributeError):
            det.anomaly(sine_tags[:32])
        with pytest.raises(AttributeError):
            scorer.anomaly_arrays(sine_tags[:32])


def test_non_numeric_payload_is_400(model_dir):
    """Strings in X are a client error (400), not an unhandled 500; JSON
    nulls coerce to NaN and propagate (reference-compatible looseness)."""

    async def fn(client):
        bad = await client.post(
            "/gordo/v0/testproj/machine-a/prediction",
            json={"X": [["a", "b", "c"]]},
        )
        nulls = await client.post(
            "/gordo/v0/testproj/machine-a/prediction",
            json={"X": [[1.0, None, 2.0]] * 4},
        )
        return bad.status, nulls.status

    assert _call(model_dir, fn) == (400, 200)


class TestTimeIndexParity:
    """Requests carrying per-row timestamps get start/end back (reference
    server-views behavior: time info in → time info out)."""

    INDEX = [f"2020-01-01T{h:02d}:00:00Z" for h in range(10)]
    ROWS = [[0.1, 0.5, 0.9]] * 10

    def test_anomaly_returns_start_end(self, model_dir):
        async def fn(client):
            resp = await client.post(
                "/gordo/v0/testproj/machine-a/anomaly/prediction",
                json={"X": self.ROWS, "index": self.INDEX},
            )
            return resp.status, await resp.json()

        status, body = _call(model_dir, fn)
        assert status == 200
        data = body["data"]
        assert len(data["start"]) == len(data["model-output"])
        assert data["start"][0].startswith("2020-01-01T00:00:00")
        # end = start + the index's 1h step
        assert data["end"][0].startswith("2020-01-01T01:00:00")

    def test_prediction_returns_start_end(self, model_dir):
        async def fn(client):
            resp = await client.post(
                "/gordo/v0/testproj/machine-a/prediction",
                json={"X": self.ROWS, "index": self.INDEX},
            )
            return await resp.json()

        data = _call(model_dir, fn)["data"]
        assert len(data["start"]) == 10 and len(data["end"]) == 10

    def test_without_index_no_time_columns(self, model_dir):
        async def fn(client):
            resp = await client.post(
                "/gordo/v0/testproj/machine-a/anomaly/prediction",
                json={"X": self.ROWS},
            )
            return await resp.json()

        data = _call(model_dir, fn)["data"]
        assert "start" not in data and "end" not in data

    def test_bad_index_length_is_400(self, model_dir):
        async def fn(client):
            resp = await client.post(
                "/gordo/v0/testproj/machine-a/anomaly/prediction",
                json={"X": self.ROWS, "index": self.INDEX[:3]},
            )
            return resp.status, await resp.json()

        status, body = _call(model_dir, fn)
        assert status == 400
        assert "index" in body["error"]

    def test_bulk_returns_per_machine_time(self, model_dir):
        async def fn(client):
            resp = await client.post(
                "/gordo/v0/testproj/_bulk/anomaly/prediction",
                json={
                    "X": {"machine-a": self.ROWS},
                    "index": {"machine-a": self.INDEX},
                },
            )
            return await resp.json()

        data = _call(model_dir, fn)["data"]["machine-a"]
        assert len(data["start"]) == len(data["model-output"])
        assert data["start"][0].startswith("2020-01-01T00:00:00")


class TestTimeColumns:
    """VERDICT r3 weak #5: end must come from per-row diffs (true row spans
    on irregular indices) with the artifact resolution as the 1-row
    fallback — not a median step."""

    def test_irregular_index_uses_per_row_diffs(self):
        import pandas as pd

        from gordo_tpu.serve.server import time_columns

        idx = pd.DatetimeIndex(
            [
                "2020-01-01T00:00:00Z",
                "2020-01-01T00:10:00Z",
                "2020-01-01T01:10:00Z",  # one-hour gap
                "2020-01-01T01:20:00Z",
            ]
        )
        cols = time_columns(idx, 4)
        assert cols["start"][0] == idx[0].isoformat()
        # each row ends exactly where the next begins
        assert cols["end"][:3] == cols["start"][1:]
        # last row extends by ITS preceding step (10min), not a median
        assert cols["end"][3] == (idx[3] + pd.Timedelta("10min")).isoformat()

    def test_offset_rows_consumed_at_front(self):
        import pandas as pd

        from gordo_tpu.serve.server import time_columns

        idx = pd.date_range("2020-01-01", periods=5, freq="10min", tz="UTC")
        cols = time_columns(idx, 3)  # lookback consumed the first 2 rows
        assert cols["start"][0] == idx[2].isoformat()
        assert cols["end"][-1] == (idx[4] + pd.Timedelta("10min")).isoformat()

    def test_single_row_falls_back_to_resolution(self):
        import pandas as pd

        from gordo_tpu.serve.server import time_columns

        idx = pd.DatetimeIndex(["2020-01-01T00:00:00Z"])
        cols = time_columns(idx, 1, resolution="10min")
        assert cols["end"][0] == (idx[0] + pd.Timedelta("10min")).isoformat()
        # no resolution metadata: end degrades to start, never crashes
        cols = time_columns(idx, 1)
        assert cols["end"][0] == idx[0].isoformat()


def test_rescan_reloads_equal_or_older_mtime(model_dir, tmp_path):
    """VERDICT r3 weak #4: an artifact replaced with an equal-or-OLDER
    mtime (cache copy, clock skew) must still reload — comparison is !=."""
    import os

    from gordo_tpu import artifacts
    from gordo_tpu.serve.server import ModelCollection

    # the (mtime, size) reload signal under test is the v1 per-machine-dir
    # one: export a v1 view of the (now pack-default) build output
    live_dir = str(tmp_path / "older-mtime")
    artifacts.unpack(model_dir, live_dir)
    collection = ModelCollection.from_directory(live_dir, project="testproj")
    name = sorted(collection.entries)[0]
    old_model = collection.get(name).model

    model_file = os.path.join(live_dir, name, "model.pkl")
    past = os.path.getmtime(model_file) - 3600
    os.utime(model_file, (past, past))
    changes = collection.rescan()
    assert changes["reloaded"] == [name]
    assert collection.get(name).model is not old_model


def test_msgpack_content_negotiation(model_dir):
    """Bulk fast path: a msgpack request body with Accept: x-msgpack gets a
    msgpack response whose arrays match the JSON route's values."""
    import numpy as np

    from gordo_tpu.serve import codec

    X = np.asarray(X_ROWS, np.float32)

    async def fn(client):
        json_resp = await client.post(
            "/gordo/v0/testproj/machine-a/anomaly/prediction",
            json={"X": X.tolist()},
        )
        json_body = await json_resp.json()
        mp_resp = await client.post(
            "/gordo/v0/testproj/_bulk/anomaly/prediction",
            data=codec.packb({"X": {"machine-a": X}}),
            headers={
                "Content-Type": codec.MSGPACK_CONTENT_TYPE,
                "Accept": codec.MSGPACK_CONTENT_TYPE,
            },
        )
        assert mp_resp.status == 200, await mp_resp.text()
        assert mp_resp.content_type == codec.MSGPACK_CONTENT_TYPE
        mp_body = codec.unpackb(await mp_resp.read())
        return json_body, mp_body

    json_body, mp_body = _call(model_dir, fn)
    mp = mp_body["data"]["machine-a"]
    assert isinstance(mp["model-output"], np.ndarray)
    np.testing.assert_allclose(
        mp["total-anomaly-score"],
        np.asarray(json_body["data"]["total-anomaly-score"]),
        rtol=1e-6, atol=1e-7,
    )
    # single-machine route also negotiates msgpack responses
    async def fn2(client):
        resp = await client.post(
            "/gordo/v0/testproj/machine-a/anomaly/prediction",
            json={"X": X.tolist()},
            headers={"Accept": codec.MSGPACK_CONTENT_TYPE},
        )
        assert resp.content_type == codec.MSGPACK_CONTENT_TYPE
        return codec.unpackb(await resp.read())

    single = _call(model_dir, fn2)
    assert isinstance(single["data"]["model-output"], np.ndarray)


def test_columnar_content_negotiation(model_dir):
    """The r19 bulk wire: Accept listing the GSB1 columnar type (with
    msgpack fallback, the client's header) gets a columnar response that
    decodes BITWISE identical to the msgpack response for the same
    request — arrays, scalar thresholds and per-machine time columns."""
    import pandas as pd

    from gordo_tpu.serve import codec

    rng = np.random.default_rng(13)
    X_a = rng.standard_normal((40, 3)).astype(np.float32)
    X_b = rng.standard_normal((25, 3)).astype(np.float32)
    index_a = [
        t.isoformat()
        for t in pd.date_range("2020-01-01", periods=40, freq="10min",
                               tz="UTC")
    ]
    payload = codec.packb(
        {"X": {"machine-a": X_a, "machine-b": X_b},
         "index": {"machine-a": index_a}}
    )

    async def fn(client):
        mp_resp = await client.post(
            "/gordo/v0/testproj/_bulk/anomaly/prediction",
            data=payload,
            headers={"Content-Type": codec.MSGPACK_CONTENT_TYPE,
                     "Accept": codec.MSGPACK_CONTENT_TYPE},
        )
        assert mp_resp.status == 200, await mp_resp.text()
        col_resp = await client.post(
            "/gordo/v0/testproj/_bulk/anomaly/prediction",
            data=payload,
            headers={
                "Content-Type": codec.MSGPACK_CONTENT_TYPE,
                "Accept": (
                    f"{codec.COLUMNAR_CONTENT_TYPE}, "
                    f"{codec.MSGPACK_CONTENT_TYPE}"
                ),
            },
        )
        assert col_resp.status == 200, await col_resp.text()
        assert col_resp.content_type == codec.COLUMNAR_CONTENT_TYPE
        # alien dtype params stay a 415 on the columnar type too
        bad = await client.post(
            "/gordo/v0/testproj/_bulk/anomaly/prediction",
            data=payload,
            headers={
                "Content-Type": codec.MSGPACK_CONTENT_TYPE,
                "Accept": f"{codec.COLUMNAR_CONTENT_TYPE};dtype=int128",
            },
        )
        assert bad.status == 415
        return (
            codec.unpackb(await mp_resp.read()),
            codec.decode_columnar(await col_resp.read()),
        )

    mp_body, col_body = _call(model_dir, fn)
    assert sorted(col_body["data"]) == sorted(mp_body["data"])
    for name, ref in mp_body["data"].items():
        got = col_body["data"][name]
        assert sorted(got) == sorted(ref), name
        for key, val in ref.items():
            if isinstance(val, np.ndarray):
                assert got[key].dtype == val.dtype, (name, key)
                assert got[key].tobytes() == val.tobytes(), (name, key)
            else:
                assert got[key] == val, (name, key)
    # time columns made it through the rest blob for the indexed machine
    a = col_body["data"]["machine-a"]
    assert len(a["start"]) == len(a["model-output"])
    assert a["start"][0].startswith("2020-01-01T00:00:00")
    assert "start" not in col_body["data"]["machine-b"]


def test_replay_bench_smoke(model_dir):
    """The replayed-stream HTTP benchmark harness drives a real server and
    reports coherent numbers for every mode/wire combination — and its
    in-run /metrics scrape (the tier-1 lane's Prometheus assertion) comes
    back valid under load."""
    from gordo_tpu.serve.replay import replay_bench

    collection = ModelCollection.from_directory(model_dir, project="testproj")
    for mode in ("single", "bulk"):
        for wire in ("json", "msgpack"):
            out = replay_bench(
                collection, mode=mode, wire=wire, n_rounds=2, rows=64,
                parallelism=4,
            )
            assert out["samples_per_sec"] > 0, out
            assert out["n_machines"] == 2
            # every replay doubles as a /metrics scrape assertion: the
            # instrumented server must expose a parseable exposition with
            # the per-route request histograms populated
            scrape = out["metrics_scrape"]
            assert scrape["status"] == 200, scrape
            assert scrape["families"] > 0
            assert scrape["has_request_histogram"], scrape


def test_metrics_endpoint_prometheus_exposition(model_dir):
    """GET /metrics returns valid Prometheus text: per-route/per-codec
    request histograms from the middleware, request counters by status,
    and — when the coalescer is on — its queue/policy gauges."""

    async def run():
        collection = ModelCollection.from_directory(
            model_dir, project="testproj"
        )
        client = TestClient(TestServer(
            build_app(collection, coalesce_window_ms=5.0,
                      coalesce_min_concurrency=1, coalesce_knee_batch=4)
        ))
        await client.start_server()
        try:
            resp = await client.post(
                "/gordo/v0/testproj/machine-a/anomaly/prediction",
                json={"X": X_ROWS},
            )
            assert resp.status == 200
            metrics_resp = await client.get("/metrics")
            return metrics_resp.status, await metrics_resp.text()
        finally:
            await client.close()

    status, text = asyncio.run(run())
    assert status == 200
    # exposition structure: HELP/TYPE headers then samples, by family
    assert "# TYPE gordo_server_request_seconds histogram" in text
    assert "# TYPE gordo_server_requests_total counter" in text
    # route label is the matched PATTERN ({machine} stays a placeholder:
    # cardinality bounded by the route table, not the fleet)
    route = "/gordo/v0/{project}/{machine}/anomaly/prediction"
    assert f'gordo_server_request_seconds_bucket{{route="{route}"' in text
    assert f'gordo_server_requests_total{{route="{route}",status="200"}}' in text
    # collection + coalescer point-in-time gauges refresh at scrape time
    assert "gordo_server_machines 2" in text
    assert "gordo_coalesce_batch_cap 4" in text
    assert "gordo_coalesce_standing_down 0" in text
    # every metric in the exposition obeys the catalog naming convention
    import re

    for line in text.splitlines():
        if line and not line.startswith("#"):
            name = line.split("{", 1)[0].split(" ", 1)[0]
            assert re.match(r"^gordo_[a-z_]+$", name), line


def test_replay_openloop_mode(model_dir):
    """Open-loop replay fires requests on a fixed arrival schedule and
    reports p50/p99 measured from the SCHEDULED start — the SLO-grade
    latency mode; the full protocol helper reports per-fraction points."""
    from gordo_tpu.serve.replay import openloop_bench, replay_bench

    collection = ModelCollection.from_directory(model_dir, project="testproj")
    out = replay_bench(
        collection, mode="single", wire="json", n_rounds=2, rows=64,
        arrival_rate_hz=40.0, openloop_duration_s=0.5,
    )
    assert out["open_loop"] and out["arrival_rate_hz"] == 40.0
    assert out["n_requests"] >= 20  # floor: enough samples for a p99
    assert out["latency_n"] == out["n_requests"]
    assert out["latency_p99_ms"] >= out["latency_p50_ms"] > 0

    proto = openloop_bench(
        collection, mode="single", wire="json", rows=64, sat_rounds=2,
        fractions=(0.5, 0.8), duration_s=0.5,
    )
    assert proto["saturation_requests_per_sec"] > 0
    assert sorted(proto["points"]) == ["0.5x", "0.8x"]
    for point in proto["points"].values():
        assert point["latency_p99_ms"] >= point["latency_p50_ms"] > 0
        assert point["latency_n"] >= 20


def test_coalesced_requests_match_direct_path(model_dir):
    """serve/coalesce.py: concurrent single-machine anomaly requests ride
    one stacked dispatch and must return the same scores as the
    per-machine executor path — including several concurrent requests for
    the SAME machine (round-splitting)."""
    import numpy as np

    rng = np.random.default_rng(9)
    payloads = [
        ("machine-a", rng.standard_normal((50 + i, 3)).astype(np.float32))
        for i in range(4)
    ] + [
        ("machine-b", rng.standard_normal((64, 3)).astype(np.float32))
        for _ in range(3)
    ]

    async def fire(client):
        async def one(name, X):
            resp = await client.post(
                f"/gordo/v0/testproj/{name}/anomaly/prediction",
                json={"X": X.tolist()},
            )
            assert resp.status == 200, await resp.text()
            return await resp.json()

        bodies = await asyncio.gather(
            *(one(name, X) for name, X in payloads)
        )
        idx = await client.get("/gordo/v0/testproj/")
        return bodies, (await idx.json())["coalescer"]

    async def run(coalesce_ms):
        collection = ModelCollection.from_directory(model_dir, project="testproj")
        # min_concurrency=1: force EVERY request through the coalescer so
        # the parity assertions below are deterministic (the adaptive
        # bypass has its own test)
        client = TestClient(TestServer(
            build_app(collection, coalesce_window_ms=coalesce_ms,
                      coalesce_min_concurrency=1, coalesce_knee_batch=8)
        ))
        await client.start_server()
        try:
            return await fire(client)
        finally:
            await client.close()

    direct, stats_off = asyncio.run(run(0.0))
    coalesced, stats_on = asyncio.run(run(5.0))
    assert stats_off == {"enabled": False}
    assert stats_on["enabled"] and stats_on["requests"] == len(payloads)
    for d, c in zip(direct, coalesced):
        np.testing.assert_allclose(
            np.asarray(c["data"]["total-anomaly-score"]),
            np.asarray(d["data"]["total-anomaly-score"]),
            rtol=1e-4, atol=1e-5,
        )
        assert c["data"]["total-anomaly-threshold"] == pytest.approx(
            d["data"]["total-anomaly-threshold"], rel=1e-5
        )


def test_coalescer_knee_cap_over_real_dispatches(model_dir):
    """An explicit knee cap bounds every stacked dispatch through the
    real server route: a burst wider than the cap splits into capped
    rounds instead of one mega-batch (stats must show it)."""
    import numpy as np

    rng = np.random.default_rng(7)
    X = rng.standard_normal((40, 3)).astype(np.float32).tolist()

    async def run():
        collection = ModelCollection.from_directory(
            model_dir, project="testproj"
        )
        client = TestClient(TestServer(
            build_app(collection, coalesce_window_ms=5.0,
                      coalesce_min_concurrency=1, coalesce_knee_batch=1)
        ))
        await client.start_server()
        try:
            async def one(name):
                resp = await client.post(
                    f"/gordo/v0/testproj/{name}/anomaly/prediction",
                    json={"X": X},
                )
                assert resp.status == 200, await resp.text()

            await asyncio.gather(
                *(one(n) for n in ["machine-a", "machine-b"] * 3)
            )
            idx = await client.get("/gordo/v0/testproj/")
            return (await idx.json())["coalescer"]
        finally:
            await client.close()

    st = asyncio.run(run())
    assert st["batch_cap"] == 1 and st["knee_batch"] == 1
    # every request rode its own capped dispatch
    assert st["dispatches"] == st["requests"] > 0
    assert st["mean_batch"] == 1.0


def test_coalescer_adaptive_bypass(model_dir):
    """Below ``coalesce_min_concurrency`` in-flight requests the route
    dispatches directly (no window wait, no coalescer dispatch); a
    concurrent burst still coalesces.  r4 verdict item 4: the coalescer
    must win or get out of the way."""
    import numpy as np

    rng = np.random.default_rng(5)
    X = rng.standard_normal((40, 3)).astype(np.float32).tolist()

    async def run():
        collection = ModelCollection.from_directory(
            model_dir, project="testproj"
        )
        client = TestClient(TestServer(
            build_app(collection, coalesce_window_ms=5.0,
                      coalesce_min_concurrency=2, coalesce_knee_batch=8)
        ))
        await client.start_server()
        try:
            async def one(name):
                resp = await client.post(
                    f"/gordo/v0/testproj/{name}/anomaly/prediction",
                    json={"X": X},
                )
                assert resp.status == 200, await resp.text()
                return await resp.json()

            # sequential: never ≥2 in flight → every request bypasses
            for _ in range(3):
                await one("machine-a")
            idx = await client.get("/gordo/v0/testproj/")
            seq = (await idx.json())["coalescer"]
            assert seq["bypassed_requests"] == 3
            assert seq["dispatches"] == 0 and seq["requests"] == 0

            # a concurrent burst overlaps → the later arrivals coalesce
            await asyncio.gather(
                *(one(n) for n in ["machine-a", "machine-b"] * 4)
            )
            idx = await client.get("/gordo/v0/testproj/")
            burst = (await idx.json())["coalescer"]
            assert burst["requests"] > 0 and burst["dispatches"] > 0
            assert burst["min_concurrency"] == 2
        finally:
            await client.close()

    asyncio.run(run())


def test_short_rows_are_400_on_both_paths(model_dir, tmp_path):
    """A request with fewer rows than the model's lookback window is a
    client error: 400 from the direct path AND the coalesced path (it
    previously sliced padded output with a negative bound -> garbage 200)."""
    import numpy as np

    from gordo_tpu import serializer
    from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector
    from gordo_tpu.models.estimator import LSTMAutoEncoder
    from gordo_tpu.ops.scalers import MinMaxScaler
    from gordo_tpu.pipeline import Pipeline

    rng = np.random.default_rng(1)
    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([
            MinMaxScaler(),
            LSTMAutoEncoder(lookback_window=10, epochs=1, batch_size=64),
        ]),
    )
    X_train = rng.standard_normal((150, 3)).astype(np.float32)
    det.cross_validate(X_train)
    det.fit(X_train)
    art_dir = tmp_path / "lstm-short" / "lstm-m"
    serializer.dump(det, str(art_dir), metadata={
        "dataset": {"tag_list": ["a", "b", "c"], "resolution": "10min"},
    })

    short = rng.standard_normal((4, 3)).astype(np.float32).tolist()

    async def run(coalesce_ms):
        collection = ModelCollection.from_directory(
            str(tmp_path / "lstm-short"), project="shortproj"
        )
        client = TestClient(TestServer(
            build_app(collection, coalesce_window_ms=coalesce_ms,
                      coalesce_min_concurrency=1, coalesce_knee_batch=8)
        ))
        await client.start_server()
        try:
            anom = await client.post(
                "/gordo/v0/shortproj/lstm-m/anomaly/prediction",
                json={"X": short},
            )
            pred = await client.post(
                "/gordo/v0/shortproj/lstm-m/prediction",
                json={"X": short},
            )
            return anom.status, await anom.json(), pred.status
        finally:
            await client.close()

    for coalesce_ms in (0.0, 5.0):
        status, body, pred_status = asyncio.run(run(coalesce_ms))
        assert status == 400, (coalesce_ms, body)
        assert "rows" in body["error"]
        assert pred_status == 400


def test_bulk_width_mismatch_isolated_per_machine(model_dir):
    """One machine's malformed width must error in ITS slot, not sink the
    stacked dispatch for the healthy machines riding the same request."""
    import numpy as np

    X_good = np.asarray(X_ROWS, np.float32)

    async def fn(client):
        resp = await client.post(
            "/gordo/v0/testproj/_bulk/anomaly/prediction",
            json={"X": {
                "machine-a": X_good.tolist(),
                "machine-b": X_good[:, :2].tolist(),  # wrong width
            }},
        )
        assert resp.status == 200, await resp.text()
        return await resp.json()

    body = _call(model_dir, fn)
    assert "model-output" in body["data"]["machine-a"]
    mb = body["data"]["machine-b"]
    assert "columns" in mb["error"]
    assert "client-error" not in mb  # transport metadata, not schema


def test_coalescer_routes_fallback_machines_off_worker(model_dir, tmp_path):
    """A non-fusable machine (host-path fallback, potentially slow) must
    not head-of-line-block coalesced requests for stacked machines — and
    both kinds still answer correctly through the same app."""
    import shutil

    import numpy as np

    from gordo_tpu import serializer
    from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector
    from gordo_tpu.models.estimator import AutoEncoder
    from gordo_tpu.ops.scalers import FunctionTransformer
    from gordo_tpu.ops.transformer_funcs import multiplier
    from gordo_tpu.pipeline import Pipeline

    live = tmp_path / "mixed"
    shutil.copytree(model_dir, live)
    rng = np.random.default_rng(3)
    X_train = rng.standard_normal((150, 3)).astype(np.float32)
    slow = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([
            FunctionTransformer(func=multiplier, kw_args={"factor": 1.0}),
            AutoEncoder(epochs=1, batch_size=64),
        ]),
    )
    slow.cross_validate(X_train)
    slow.fit(X_train)
    serializer.dump(slow, str(live / "machine-slow"), metadata={
        "dataset": {"tag_list": ["a", "b", "c"]},
    })

    async def main():
        collection = ModelCollection.from_directory(str(live), project="mx")
        fs = collection.fleet_scorer
        assert "machine-slow" in fs.fallbacks  # premise: truly non-fusable
        assert "machine-a" in fs.machine_bucket
        client = TestClient(TestServer(
            build_app(collection, coalesce_window_ms=5.0,
                      coalesce_min_concurrency=1, coalesce_knee_batch=8)
        ))
        await client.start_server()
        try:
            X = rng.standard_normal((40, 3)).astype(np.float32).tolist()

            async def one(name):
                resp = await client.post(
                    f"/gordo/v0/mx/{name}/anomaly/prediction",
                    json={"X": X},
                )
                assert resp.status == 200, (name, await resp.text())
                return await resp.json()

            bodies = await asyncio.gather(
                *(one(n) for n in
                  ["machine-a", "machine-slow", "machine-b", "machine-slow"])
            )
            return bodies
        finally:
            await client.close()

    bodies = asyncio.run(main())
    for body in bodies:
        assert len(body["data"]["total-anomaly-score"]) == 40


def test_warmup_scorers_compiles_and_app_serves(model_dir):
    """warmup_scorers precompiles every bucket without error, and an app
    built with warmup=True still serves normally (the warmup runs in a
    background executor task at startup)."""
    from gordo_tpu.serve.server import warmup_scorers

    collection = ModelCollection.from_directory(model_dir, project="testproj")
    stats = warmup_scorers(collection)
    assert stats["errors"] == 0
    assert stats["buckets"] == len(collection.fleet_scorer.buckets) >= 1

    async def runner():
        coll2 = ModelCollection.from_directory(model_dir, project="testproj")
        client = TestClient(TestServer(build_app(coll2, warmup=True)))
        await client.start_server()
        try:
            name = sorted(coll2.entries)[0]
            n_tags = len(coll2.get(name).tags)
            resp = await client.post(
                f"/gordo/v0/testproj/{name}/anomaly/prediction",
                json={"X": [[0.0] * n_tags] * 12},
            )
            assert resp.status == 200, await resp.text()
            from gordo_tpu.serve.server import WARMUP_TASK_KEY

            task = client.app.get(WARMUP_TASK_KEY)
            assert task is not None
            stats2 = await task  # warmup finishes without error
            assert stats2["errors"] == 0
            # readiness gate: 200 once warmup is done
            ready = await client.get("/gordo/v0/testproj/ready")
            assert ready.status == 200
        finally:
            await client.close()

    asyncio.run(runner())

    async def no_warmup_runner():
        coll3 = ModelCollection.from_directory(model_dir, project="testproj")
        client = TestClient(TestServer(build_app(coll3)))  # warmup off
        await client.start_server()
        try:
            ready = await client.get("/gordo/v0/testproj/ready")
            assert ready.status == 200  # no warmup configured -> ready
        finally:
            await client.close()

    asyncio.run(no_warmup_runner())


def test_warmup_failure_still_becomes_ready(model_dir, monkeypatch):
    """A warmup crash must resolve the warmup future with the ORIGINAL
    exception (not leak a NameError from the deleted except-bound name)
    and must not wedge /ready at 503 — warmup failure can't take down
    startup."""
    import gordo_tpu.serve.server as server_mod

    def boom(collection, row_sizes=None):
        raise RuntimeError("synthetic warmup failure")

    monkeypatch.setattr(server_mod, "warmup_scorers", boom)

    async def runner():
        coll = ModelCollection.from_directory(model_dir, project="testproj")
        client = TestClient(TestServer(build_app(coll, warmup=True)))
        await client.start_server()
        try:
            fut = client.app.get(server_mod.WARMUP_TASK_KEY)
            assert fut is not None
            with pytest.raises(RuntimeError, match="synthetic warmup"):
                await asyncio.wait_for(asyncio.shield(fut), timeout=30)
            # failed warmup is DONE -> pod enters rotation regardless
            ready = await client.get("/gordo/v0/testproj/ready")
            assert ready.status == 200
        finally:
            await client.close()

    asyncio.run(runner())


def test_warmup_scorers_empty_row_sizes(model_dir):
    """An explicit empty row_sizes list falls back to the defaults instead
    of IndexError-ing inside the warmup thread."""
    from gordo_tpu.serve.server import warmup_scorers

    collection = ModelCollection.from_directory(model_dir, project="testproj")
    stats = warmup_scorers(collection, row_sizes=[])
    assert stats["errors"] == 0


def test_over_bound_lookback_windows_fall_back_to_host(monkeypatch):
    """The model-input windows tensor (n, lookback, tags) has no blocked
    variant — requests past the device bound on that axis must score
    through the host path (and stay exact), not crash the fused compile."""
    import gordo_tpu.serve.scorer as sc_mod
    from lstm_detectors import fitted_lstm_detector

    rng = np.random.default_rng(7)
    det = fitted_lstm_detector(rng)  # shared shapes — see that module
    scorer = CompiledScorer(det)
    X = rng.standard_normal((60, 3)).astype(np.float32)
    fused = scorer.anomaly_arrays(X)

    monkeypatch.setattr(sc_mod, "SMOOTH_ONE_SHOT_BOUND", 1)
    host_calls = []
    orig_anomaly = det.anomaly
    monkeypatch.setattr(
        det, "anomaly",
        lambda *a, **k: host_calls.append(1) or orig_anomaly(*a, **k),
    )
    out = scorer.anomaly_arrays(X)
    assert host_calls, "over-bound lookback request did not use the host path"
    np.testing.assert_allclose(
        out["total-anomaly-score"], fused["total-anomaly-score"],
        rtol=1e-4, atol=1e-5,
    )
    # the /prediction surface is guarded too (same bound, host predict)
    fused_pred = None
    monkeypatch.setattr(sc_mod, "SMOOTH_ONE_SHOT_BOUND", 2 ** 27)
    fused_pred = scorer.predict(X)
    monkeypatch.setattr(sc_mod, "SMOOTH_ONE_SHOT_BOUND", 1)
    host_pred = scorer.predict(X)
    np.testing.assert_allclose(host_pred, fused_pred, rtol=1e-4, atol=1e-5)
