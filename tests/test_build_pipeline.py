"""Pipelined project builds (ISSUE 4): the loader-pool → device →
artifact-writer-pool drive loop must be byte-equivalent to the serial
path — same artifact bytes, same registry entries — and the writer pool
must fully drain before the resumable exit-75 path records its shard
state.  Slow lane, alongside tests/test_distributed.py (wired into the
CI test-full job, .github/workflows/ci.yml)."""

import json
import os
import pickle

import pytest

from gordo_tpu import telemetry
from gordo_tpu.builder import build_project
from gordo_tpu.builder import fleet_build as fb
from gordo_tpu.distributed.partition import ShardState, process_shard
from gordo_tpu.utils import disk_registry
from gordo_tpu.workflow.config import Machine

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow

#: metadata fields that legitimately differ between two builds of the
#: same config (wall-clock measurements) — same set the multihost dryrun
#: byte-identity check uses (scripts/multihost_dryrun.py)
VOLATILE_META = {
    "model_creation_date",
    "data_query_duration_sec",
    "cross_validation_duration_sec",
    "model_builder_duration_sec",
    "fit_samples_per_second",
    "fit_seconds",
    "fleet_seconds",
    "bucket_size",
}


def _machines(n, prefix="pipe", hours=24):
    return [
        Machine.from_config(
            {
                "name": f"{prefix}-{i}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": ["a", "b", "c"],
                    "train_start_date": "2017-12-25T06:00:00Z",
                    "train_end_date": "2017-12-26T06:00:00Z",
                },
            }
        )
        for i in range(n)
    ]


def _scrub_timings(obj, seen=None):
    """Zero wall-clock attributes through a pickled object graph (the
    multihost dryrun's technique): everything else must match to the bit."""
    if seen is None:
        seen = set()
    if id(obj) in seen:
        return
    seen.add(id(obj))
    if isinstance(obj, dict):
        for key, zero in (("fleet_seconds", 0.0), ("bucket_size", 0)):
            if key in obj:
                obj[key] = zero
        for v in obj.values():
            _scrub_timings(v, seen)
        return
    if isinstance(obj, (list, tuple)):
        for v in obj:
            _scrub_timings(v, seen)
        return
    d = getattr(obj, "__dict__", None)
    if d is None:
        return
    if "fit_seconds_" in d:
        d["fit_seconds_"] = 0.0
    for v in d.values():
        _scrub_timings(v, seen)


def _strip_meta(v):
    if isinstance(v, dict):
        return {k: _strip_meta(x) for k, x in v.items() if k not in VOLATILE_META}
    if isinstance(v, list):
        return [_strip_meta(x) for x in v]
    return v


class TestPipelineParity:
    def test_artifacts_and_registry_byte_identical_to_serial(self, tmp_path):
        """The acceptance contract: pipelined and serial drives of the
        same project produce byte-identical artifacts (model.pkl modulo
        zeroed wall-clock timings, definition.yaml byte-for-byte,
        metadata.json modulo timing fields) and the same registry keys."""
        machines = _machines(6)
        dirs = {}
        for label, pipe in (("serial", False), ("pipelined", True)):
            out = tmp_path / f"out-{label}"
            reg = tmp_path / f"reg-{label}"
            # v1 on purpose: this test's byte-identity contract is
            # defined on the per-machine-dir layout (v2 pack parity is
            # tests/test_artifacts.py::TestV1V2Parity's job)
            result = build_project(
                machines, str(out), model_register_dir=str(reg),
                max_bucket_size=2, pipeline=pipe, artifact_format="v1",
            )
            assert not result.failed
            assert sorted(result.artifacts) == sorted(m.name for m in machines)
            assert result.summary()["pipelined"] is pipe
            dirs[label] = (out, reg)

        s_out, s_reg = dirs["serial"]
        p_out, p_reg = dirs["pipelined"]
        for m in machines:
            a, b = s_out / m.name, p_out / m.name
            assert (a / "definition.yaml").read_bytes() == (
                b / "definition.yaml"
            ).read_bytes()
            with open(a / "model.pkl", "rb") as f:
                ma = pickle.load(f)
            with open(b / "model.pkl", "rb") as f:
                mb = pickle.load(f)
            _scrub_timings(ma)
            _scrub_timings(mb)
            assert pickle.dumps(ma) == pickle.dumps(mb), m.name
            meta_a = json.loads((a / "metadata.json").read_text())
            meta_b = json.loads((b / "metadata.json").read_text())
            assert _strip_meta(meta_a) == _strip_meta(meta_b), m.name
        # registry entries: same keys, each resolving to the machine dir
        keys_s = sorted(disk_registry.list_keys(str(s_reg)))
        keys_p = sorted(disk_registry.list_keys(str(p_reg)))
        assert keys_s == keys_p and len(keys_s) == len(machines)
        # no scratch residue
        assert not (p_out / ".gordo-tmp").exists()

    def test_pipelined_artifacts_cache_hit_a_serial_rerun(self, tmp_path):
        """Registry parity the way it matters: artifacts the PIPELINED
        path registered satisfy a SERIAL re-run's cache lookups."""
        machines = _machines(3, prefix="xcache")
        out, reg = str(tmp_path / "m"), str(tmp_path / "r")
        first = build_project(
            machines, out, model_register_dir=reg, pipeline=True,
        )
        assert sorted(first.fleet_built) == sorted(m.name for m in machines)
        rerun = build_project(
            machines, str(tmp_path / "m2"), model_register_dir=reg,
            pipeline=False,
        )
        assert sorted(rerun.cached) == sorted(m.name for m in machines)


class TestKillSwitch:
    def test_env_kill_switch_forces_serial(self, tmp_path, monkeypatch):
        monkeypatch.setenv("GORDO_BUILD_PIPELINE", "off")
        result = build_project(_machines(2, prefix="ks"), str(tmp_path / "m"))
        assert not result.failed
        assert result.summary()["pipelined"] is False

    def test_explicit_argument_beats_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("GORDO_BUILD_PIPELINE", "off")
        result = build_project(
            _machines(2, prefix="kse"), str(tmp_path / "m"), pipeline=True,
        )
        assert not result.failed
        assert result.summary()["pipelined"] is True

    def test_pipeline_telemetry_present_after_pipelined_build(self, tmp_path):
        build_project(
            _machines(2, prefix="tel"), str(tmp_path / "m"), pipeline=True,
        )
        scrape = telemetry.render()
        for name in (
            "gordo_build_pipeline_stage_seconds",
            "gordo_build_pipeline_stall_seconds",
            "gordo_build_pipeline_writer_queue_depth",
            "gordo_build_pipeline_chunks_total",
        ):
            assert name in scrape, name


class TestWriterDrainOnResumablePath:
    def test_queued_artifacts_land_before_shard_goes_resumable(
        self, tmp_path, monkeypatch
    ):
        """exit-75 contract: when a machine failure marks the shard
        resumable, every artifact the writer pool had queued is FULLY on
        disk, registered, and recorded in the shard state before the
        state transitions — a re-run must cache-hit them, and the state
        file must never reference a half-written artifact."""
        from gordo_tpu.dataset import datasets as ds_mod

        machines = _machines(6, prefix="drain")
        orig = ds_mod.RandomDataset.get_data
        calls = {"n": 0}

        def failing_get_data(self):
            calls["n"] += 1
            if calls["n"] == 5:  # one mid-stream load fails
                raise RuntimeError("synthetic data outage")
            return orig(self)

        monkeypatch.setattr(ds_mod.RandomDataset, "get_data", failing_get_data)
        out = str(tmp_path / "m")
        reg = str(tmp_path / "r")
        shard = process_shard(machines, 1, 0, output_dir=out)
        # v1: this test inspects per-machine dirs and the v1 writer
        # pool's drain semantics directly
        result = build_project(
            machines, out, model_register_dir=reg, max_bucket_size=2,
            data_workers=1, shard=shard, pipeline=True,
            artifact_format="v1",
        )
        assert len(result.failed) == 1
        ok_names = sorted(result.artifacts)
        assert len(ok_names) == 5

        state = ShardState.load(out, 0, 1)
        assert state.status == "resumable"
        # every completed machine was recorded AND is complete on disk
        assert sorted(state.completed) == ok_names
        for name in state.completed:
            art = os.path.join(out, name)
            assert os.path.exists(os.path.join(art, "model.pkl"))
            meta = json.loads(
                open(os.path.join(art, "metadata.json")).read()
            )
            assert meta["name"] == name
        # no half-written scratch artifacts survive the drain
        assert not os.path.exists(os.path.join(out, ".gordo-tmp"))
        # and the registered artifacts satisfy the resumed run's lookups
        monkeypatch.setattr(ds_mod.RandomDataset, "get_data", orig)
        shard2 = process_shard(machines, 1, 0, output_dir=out)
        rerun = build_project(
            machines, out, model_register_dir=reg, max_bucket_size=2,
            shard=shard2, pipeline=True, artifact_format="v1",
        )
        assert not rerun.failed
        assert sorted(rerun.cached) == ok_names
        assert ShardState.load(out, 0, 1).status == "done"

    def test_write_failure_fails_one_machine_loudly(self, tmp_path, monkeypatch):
        """A broken artifact write must fail that machine (recorded in
        result.failed) without sinking the drain or the other writes."""
        machines = _machines(4, prefix="wfail")
        orig = fb._write_artifact
        target = f"{machines[1].name}"

        def breaking_write(detector, metadata, dest, *args, **kwargs):
            if os.path.basename(dest) == target:
                raise OSError("disk full (synthetic)")
            return orig(detector, metadata, dest, *args, **kwargs)

        monkeypatch.setattr(fb, "_write_artifact", breaking_write)
        # v1: the synthetic failure targets the v1 per-machine writer
        # (_write_artifact); the pack writer's failure fallback is covered
        # by tests/test_artifacts.py
        result = build_project(
            machines, str(tmp_path / "m"), max_bucket_size=2, pipeline=True,
            artifact_format="v1",
        )
        assert list(result.failed) == [target]
        assert result.failed[target].startswith("write:")
        assert sorted(result.artifacts) == sorted(
            m.name for m in machines if m.name != target
        )
