"""Functional scaler tests — behavior parity with the sklearn transformers
the reference composes (values checked against analytic expectations)."""

import numpy as np
import pytest

from gordo_tpu.ops import scalers


@pytest.fixture
def X():
    rng = np.random.default_rng(7)
    return (rng.standard_normal((200, 5)) * np.array([1, 10, 0.1, 5, 2])
            + np.array([0, 100, -3, 4, 0.5])).astype(np.float32)


def test_minmax_range_and_inverse(X):
    sc = scalers.MinMaxScaler()
    Xt = sc.fit_transform(X)
    assert Xt.min() >= -1e-6 and Xt.max() <= 1 + 1e-6
    np.testing.assert_allclose(sc.inverse_transform(Xt), X, rtol=1e-4, atol=1e-4)


def test_minmax_custom_range(X):
    sc = scalers.MinMaxScaler(feature_range=(-1, 1))
    Xt = sc.fit_transform(X)
    np.testing.assert_allclose(Xt.min(axis=0), -1, atol=1e-5)
    np.testing.assert_allclose(Xt.max(axis=0), 1, atol=1e-5)


def test_standard_scaler(X):
    sc = scalers.StandardScaler()
    Xt = sc.fit_transform(X)
    np.testing.assert_allclose(Xt.mean(axis=0), 0, atol=1e-4)
    np.testing.assert_allclose(Xt.std(axis=0), 1, atol=1e-3)
    np.testing.assert_allclose(sc.inverse_transform(Xt), X, rtol=1e-3, atol=1e-3)


def test_robust_scaler(X):
    sc = scalers.RobustScaler()
    Xt = sc.fit_transform(X)
    np.testing.assert_allclose(np.median(Xt, axis=0), 0, atol=1e-4)
    np.testing.assert_allclose(sc.inverse_transform(Xt), X, rtol=1e-3, atol=1e-3)


def test_quantile_transformer_uniform(X):
    qt = scalers.QuantileTransformer(n_quantiles=50)
    Xt = qt.fit_transform(X)
    assert Xt.min() >= 0 and Xt.max() <= 1
    back = qt.inverse_transform(Xt)
    np.testing.assert_allclose(back, X, rtol=0.1, atol=0.5)


def test_simple_imputer_mean():
    X = np.array([[1.0, np.nan], [3.0, 4.0], [np.nan, 8.0]], dtype=np.float32)
    imp = scalers.SimpleImputer(strategy="mean")
    Xt = imp.fit_transform(X)
    assert not np.isnan(Xt).any()
    np.testing.assert_allclose(Xt[2, 0], 2.0, atol=1e-5)
    np.testing.assert_allclose(Xt[0, 1], 6.0, atol=1e-5)


def test_pca_roundtrip(X):
    pca = scalers.PCA()
    Xt = pca.fit_transform(X)
    np.testing.assert_allclose(pca.inverse_transform(Xt), X, rtol=1e-2, atol=1e-2)


def test_function_transformer_multiplier():
    from gordo_tpu.ops.transformer_funcs import multiplier

    ft = scalers.FunctionTransformer(func=multiplier, kw_args={"factor": 2.0})
    X = np.ones((3, 2), dtype=np.float32)
    np.testing.assert_allclose(ft.fit_transform(X), 2 * X)
    # definition round-trip stores dotted path
    params = ft.get_params()
    assert params["func"] == "gordo_tpu.ops.transformer_funcs.multiplier"


def test_scaler_nan_safety():
    X = np.array([[1.0, np.nan], [3.0, 4.0], [2.0, 8.0]], dtype=np.float32)
    sc = scalers.MinMaxScaler().fit(X)
    assert np.isfinite(sc.stats_["scale"]).all()
    assert np.isfinite(sc.stats_["offset"]).all()


def test_pure_apply_matches_stateful_transform(X):
    """The jit-fold contract: apply(stats, X) == transform(X) including
    non-default constructor options."""
    for sc in [
        scalers.MinMaxScaler(feature_range=(-2, 3)),
        scalers.StandardScaler(with_mean=False),
        scalers.RobustScaler(with_centering=False),
    ]:
        sc.fit(X)
        np.testing.assert_allclose(
            np.asarray(type(sc).apply(sc.stats_, X)), sc.transform(X),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(type(sc).invert(sc.stats_, sc.transform(X))), X,
            rtol=1e-3, atol=1e-3,
        )


def test_not_invertible_names_class():
    imp = scalers.SimpleImputer().fit(np.ones((3, 2), dtype=np.float32))
    out = imp.inverse_transform(np.ones((3, 2), dtype=np.float32))
    assert out.shape == (3, 2)  # imputer inverse is identity, not an error


def test_ignored_sklearn_kwargs_warn():
    """Unsupported sklearn-compat kwargs must warn, never silently change
    behaviour (VERDICT weak #6)."""
    import warnings

    from gordo_tpu.ops.scalers import (
        PCA,
        MinMaxScaler,
        QuantileTransformer,
        SimpleImputer,
    )

    for cls, kw in [
        (QuantileTransformer, {"subsample": 1000}),
        (PCA, {"whiten": True}),
        (SimpleImputer, {"add_indicator": True}),
        (MinMaxScaler, {"clip": True}),
    ]:
        with pytest.warns(UserWarning, match="ignoring unsupported"):
            cls(**kw)

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        MinMaxScaler()  # no extra kwargs -> no warning
