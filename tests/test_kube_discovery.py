"""KubeTargetDiscovery tests over a faked ``kubernetes`` client module —
the reference's watchman tests mocked the k8s client the same way
(SURVEY.md §5)."""

import sys
import types
from unittest import mock

import pytest


def _fake_kubernetes(services):
    """Build a fake `kubernetes` package exposing the surface kube.py uses."""
    module = types.ModuleType("kubernetes")

    class FakeCoreV1Api:
        last_call = {}

        def list_namespaced_service(self, namespace, label_selector=None):
            FakeCoreV1Api.last_call = {
                "namespace": namespace,
                "label_selector": label_selector,
            }
            items = []
            for name, port in services:
                svc = types.SimpleNamespace(
                    metadata=types.SimpleNamespace(name=name),
                    spec=types.SimpleNamespace(
                        ports=[types.SimpleNamespace(port=port)] if port else []
                    ),
                )
                items.append(svc)
            return types.SimpleNamespace(items=items)

    client = types.ModuleType("kubernetes.client")
    client.CoreV1Api = FakeCoreV1Api
    config = types.ModuleType("kubernetes.config")
    config.load_incluster_config = lambda: None
    config.load_kube_config = lambda: None
    module.client = client
    module.config = config
    return module, FakeCoreV1Api


def test_targets_from_services(monkeypatch):
    module, api = _fake_kubernetes([("gordo-server-0", 5555), ("gordo-server-1", 80)])
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    monkeypatch.setitem(sys.modules, "kubernetes.client", module.client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", module.config)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("prod-ns", project="proj-x", in_cluster=False)
    assert disc.targets() == [
        "http://gordo-server-0.prod-ns:5555",
        "http://gordo-server-1.prod-ns:80",
    ]
    assert api.last_call["namespace"] == "prod-ns"
    assert "gordo/project=proj-x" in api.last_call["label_selector"]


def test_portless_service_defaults_to_80(monkeypatch):
    module, _ = _fake_kubernetes([("bare-svc", None)])
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    monkeypatch.setitem(sys.modules, "kubernetes.client", module.client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", module.config)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("ns", in_cluster=False)
    assert disc.targets() == ["http://bare-svc.ns:80"]


def test_import_gated_without_package():
    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    with mock.patch.dict(sys.modules, {"kubernetes": None}):
        with pytest.raises(ImportError, match="kubernetes"):
            KubeTargetDiscovery("ns")


def test_watchman_merges_discovered_targets(monkeypatch):
    """A target_discovery object's URLs join the static target list."""
    import asyncio

    from gordo_tpu.watchman.server import Watchman

    class StubDiscovery:
        def targets(self):
            return ["http://svc-a.ns:5555", "http://static:1"]

    watchman = Watchman(
        "p", [], ["http://static:1"],
        target_discovery=StubDiscovery(), discover=False,
    )
    targets = asyncio.run(watchman._current_targets())
    assert targets == ["http://static:1", "http://svc-a.ns:5555"]
