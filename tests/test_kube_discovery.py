"""KubeTargetDiscovery tests over a faked ``kubernetes`` client module —
the reference's watchman tests mocked the k8s client the same way
(SURVEY.md §5)."""

import sys
import types
from unittest import mock

import pytest


def _svc(name, port):
    return types.SimpleNamespace(
        metadata=types.SimpleNamespace(name=name),
        spec=types.SimpleNamespace(
            ports=[types.SimpleNamespace(port=port)] if port else []
        ),
    )


def _fake_kubernetes(services, watch_events=None):
    """Build a fake `kubernetes` package exposing the surface kube.py uses
    (CoreV1Api list + watch.Watch event stream)."""
    module = types.ModuleType("kubernetes")

    class FakeCoreV1Api:
        last_call = {}

        def list_namespaced_service(self, namespace, label_selector=None):
            FakeCoreV1Api.last_call = {
                "namespace": namespace,
                "label_selector": label_selector,
            }
            return types.SimpleNamespace(
                items=[_svc(name, port) for name, port in services]
            )

    class FakeWatch:
        def __init__(self):
            self._stopped = False

        def stream(self, fn, namespace, label_selector=None,
                   timeout_seconds=None):
            for event in (watch_events or []):
                if self._stopped:
                    return
                yield event
            # keep the stream open until stop() so the thread idles
            # instead of hot-resyncing
            import time as _t
            while not self._stopped:
                _t.sleep(0.01)

        def stop(self):
            self._stopped = True

    client = types.ModuleType("kubernetes.client")
    client.CoreV1Api = FakeCoreV1Api
    config = types.ModuleType("kubernetes.config")
    config.load_incluster_config = lambda: None
    config.load_kube_config = lambda: None
    watch = types.ModuleType("kubernetes.watch")
    watch.Watch = FakeWatch
    module.client = client
    module.config = config
    module.watch = watch
    return module, FakeCoreV1Api


def test_targets_from_services(monkeypatch):
    module, api = _fake_kubernetes([("gordo-server-0", 5555), ("gordo-server-1", 80)])
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    monkeypatch.setitem(sys.modules, "kubernetes.client", module.client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", module.config)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("prod-ns", project="proj-x", in_cluster=False)
    assert disc.targets() == [
        "http://gordo-server-0.prod-ns:5555",
        "http://gordo-server-1.prod-ns:80",
    ]
    assert api.last_call["namespace"] == "prod-ns"
    assert "gordo/project=proj-x" in api.last_call["label_selector"]


def test_portless_service_defaults_to_80(monkeypatch):
    module, _ = _fake_kubernetes([("bare-svc", None)])
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    monkeypatch.setitem(sys.modules, "kubernetes.client", module.client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", module.config)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("ns", in_cluster=False)
    assert disc.targets() == ["http://bare-svc.ns:80"]


def test_import_gated_without_package():
    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    with mock.patch.dict(sys.modules, {"kubernetes": None}):
        with pytest.raises(ImportError, match="kubernetes"):
            KubeTargetDiscovery("ns")


def _install(monkeypatch, module):
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    monkeypatch.setitem(sys.modules, "kubernetes.client", module.client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", module.config)
    monkeypatch.setitem(sys.modules, "kubernetes.watch", module.watch)


def test_watch_stream_updates_targets_and_fires_on_change(monkeypatch):
    """Service ADDED/DELETED events mutate the live target cache without
    re-listing, and each change fires the on_change callback — fleet
    membership propagates at event latency, not poll cadence."""
    import time

    events = [
        {"type": "ADDED", "object": _svc("svc-new", 5555)},
        {"type": "DELETED", "object": _svc("svc-old", 5555)},
    ]
    module, _ = _fake_kubernetes([("svc-old", 5555)], watch_events=events)
    _install(monkeypatch, module)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("ns", in_cluster=False)
    changes = []
    disc.on_change = lambda: changes.append(disc.targets())
    disc.start_watch()
    try:
        deadline = time.time() + 5
        while time.time() < deadline:
            if disc.targets() == ["http://svc-new.ns:5555"]:
                break
            time.sleep(0.01)
        # seed list had svc-old; ADDED added svc-new; DELETED removed svc-old
        assert disc.targets() == ["http://svc-new.ns:5555"]
        assert len(changes) >= 2  # add + delete each notified
    finally:
        disc.stop_watch()
    # after stop the cache is dropped: targets() lists again (svc-old)
    assert disc.targets() == ["http://svc-old.ns:5555"]


def test_watchman_start_wires_watch_and_nudges_loop(monkeypatch):
    """Watchman.start() starts a watch-capable discovery and a change
    notification wakes the poll loop immediately (no poll_interval wait)."""
    import asyncio

    from gordo_tpu.watchman.server import Watchman

    class StubWatchDiscovery:
        def __init__(self):
            self.on_change = None
            self.watching = False
            self.stopped = False

        def start_watch(self):
            self.watching = True

        def stop_watch(self):
            self.stopped = True

        def targets(self):
            return []

    disc = StubWatchDiscovery()
    refreshes = []

    async def main():
        watchman = Watchman(
            "p", [], [], target_discovery=disc, discover=False,
            poll_interval=3600,  # only a nudge can trigger a 2nd refresh
        )

        async def fake_refresh():
            refreshes.append(asyncio.get_running_loop().time())
            return []

        watchman.refresh = fake_refresh
        watchman.start()
        assert disc.on_change is not None
        await asyncio.sleep(0.05)  # first cycle
        assert disc.watching
        n0 = len(refreshes)
        disc.on_change()  # simulate a watch event (thread-safe path)
        await asyncio.sleep(0.05)
        assert len(refreshes) > n0  # woke before the 1h poll interval
        await watchman.stop()
        assert disc.stopped

    asyncio.run(main())


def test_watchman_merges_discovered_targets(monkeypatch):
    """A target_discovery object's URLs join the static target list."""
    import asyncio

    from gordo_tpu.watchman.server import Watchman

    class StubDiscovery:
        def targets(self):
            return ["http://svc-a.ns:5555", "http://static:1"]

    watchman = Watchman(
        "p", [], ["http://static:1"],
        target_discovery=StubDiscovery(), discover=False,
    )
    targets = asyncio.run(watchman._current_targets())
    assert targets == ["http://static:1", "http://svc-a.ns:5555"]


# ---------------------------------------------------------------------------
# watch-thread edge cases (VERDICT weak #6): generation changes mid-event
# and stop() racing a pending apply
# ---------------------------------------------------------------------------

def _gated_kubernetes(services, first_events, late_events, gate):
    """Fake kubernetes whose FIRST Watch stream yields ``first_events``,
    then blocks on ``gate``, then yields ``late_events`` — so a test can
    stop/restart discovery while generation 1 is wedged mid-stream.
    Later Watch instances stream nothing and idle (like a quiet cluster).
    """
    import threading
    import types

    module = types.ModuleType("kubernetes")

    class FakeCoreV1Api:
        def list_namespaced_service(self, namespace, label_selector=None):
            return types.SimpleNamespace(
                items=[_svc(name, port) for name, port in services]
            )

    instances = []

    class FakeWatch:
        def __init__(self):
            self._stopped = False
            self.generation = len(instances)
            instances.append(self)

        def stream(self, fn, namespace, label_selector=None,
                   timeout_seconds=None):
            import time as _t
            if self.generation == 0:
                for event in first_events:
                    yield event
                gate.wait(timeout=10)  # wedged mid-stream
                for event in late_events:
                    if self._stopped:
                        return
                    yield event
            while not self._stopped:
                _t.sleep(0.01)

        def stop(self):
            self._stopped = True

    client = types.ModuleType("kubernetes.client")
    client.CoreV1Api = FakeCoreV1Api
    config = types.ModuleType("kubernetes.config")
    config.load_incluster_config = lambda: None
    config.load_kube_config = lambda: None
    watch = types.ModuleType("kubernetes.watch")
    watch.Watch = FakeWatch
    module.client = client
    module.config = config
    module.watch = watch
    return module


def test_abandoned_generation_event_cannot_poison_new_cache(monkeypatch):
    """Generation change mid-event: gen-1's stream wedges, stop_watch()'s
    join times out, a NEW generation starts and owns the cache — then
    gen-1 un-wedges and yields a late event.  The late apply must be
    discarded, not merged into gen-2's live cache."""
    import threading
    import time

    gate = threading.Event()
    late = [{"type": "ADDED", "object": _svc("svc-stale", 5555)}]
    module = _gated_kubernetes(
        [("svc-live", 5555)], first_events=[], late_events=late, gate=gate,
    )
    _install(monkeypatch, module)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("ns", in_cluster=False)
    disc.start_watch()
    # wait until gen-1 seeded its cache and entered the wedged stream
    deadline = time.time() + 5
    while time.time() < deadline:
        if disc.targets() == ["http://svc-live.ns:5555"]:
            break
        time.sleep(0.01)
    gen1_stop = disc._watch_stop
    # stop with the thread wedged: join(5) would block the test for 5s,
    # so shrink it by monkeypatching nothing — instead call stop in a
    # helper thread and wait for the flag
    stopper = threading.Thread(target=disc.stop_watch)
    stopper.start()
    deadline = time.time() + 6
    while not gen1_stop.is_set() and time.time() < deadline:
        time.sleep(0.01)
    assert gen1_stop.is_set()

    # new generation takes over and owns the cache
    disc.start_watch()
    deadline = time.time() + 5
    while time.time() < deadline:
        if disc.targets() == ["http://svc-live.ns:5555"]:
            break
        time.sleep(0.01)
    gen2_stop = disc._watch_stop
    assert gen2_stop is not gen1_stop

    # un-wedge gen-1: its late svc-stale event must be dropped
    gate.set()
    stopper.join(timeout=10)
    time.sleep(0.2)  # give the abandoned thread time to (mis)apply
    assert disc.targets() == ["http://svc-live.ns:5555"]
    disc.stop_watch()


def test_stop_racing_pending_apply_leaves_list_fallback(monkeypatch):
    """stop() racing a pending apply: the stream has an event in flight
    when stop_watch() runs.  After stop returns, targets() must be
    list-backed (cache dropped) and STAY list-backed — the straggler
    apply cannot resurrect a cache nobody owns."""
    import threading
    import time

    gate = threading.Event()
    late = [{"type": "ADDED", "object": _svc("svc-racer", 5555)}]
    module = _gated_kubernetes(
        [("svc-static", 5555)], first_events=[], late_events=late, gate=gate,
    )
    _install(monkeypatch, module)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("ns", in_cluster=False)
    disc.start_watch()
    deadline = time.time() + 5
    while time.time() < deadline:
        if disc.targets() == ["http://svc-static.ns:5555"]:
            break
        time.sleep(0.01)

    # stop while the stream is wedged with svc-racer still pending, then
    # release the event AFTER stop has returned
    stopper = threading.Thread(target=disc.stop_watch)
    stopper.start()
    time.sleep(0.1)
    gate.set()
    stopper.join(timeout=10)
    time.sleep(0.2)
    # cache must be gone and not resurrected by the raced apply...
    with disc._watch_lock:
        assert disc._watch_cache is None
    # ...and the poll path lists services directly
    assert disc.targets() == ["http://svc-static.ns:5555"]


def test_restart_after_stop_resyncs_fresh_state(monkeypatch):
    """A stopped-then-restarted discovery re-seeds from a full list
    (resync), so changes that happened while stopped are picked up."""
    import time

    services = [("svc-a", 5555)]
    module, _ = _fake_kubernetes(list(services))
    _install(monkeypatch, module)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("ns", in_cluster=False)
    disc.start_watch()
    deadline = time.time() + 5
    while time.time() < deadline:
        if disc.targets() == ["http://svc-a.ns:5555"]:
            break
        time.sleep(0.01)
    disc.stop_watch()

    # the cluster changed while we were not watching
    module.client.CoreV1Api = _fake_kubernetes(
        [("svc-a", 5555), ("svc-b", 80)]
    )[0].client.CoreV1Api
    disc._core = module.client.CoreV1Api()
    disc.start_watch()
    try:
        deadline = time.time() + 5
        while time.time() < deadline:
            if disc.targets() == [
                "http://svc-a.ns:5555", "http://svc-b.ns:80",
            ]:
                break
            time.sleep(0.01)
        assert disc.targets() == [
            "http://svc-a.ns:5555", "http://svc-b.ns:80",
        ]
    finally:
        disc.stop_watch()
