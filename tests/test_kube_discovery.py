"""KubeTargetDiscovery tests over a faked ``kubernetes`` client module —
the reference's watchman tests mocked the k8s client the same way
(SURVEY.md §5)."""

import sys
import types
from unittest import mock

import pytest


def _svc(name, port):
    return types.SimpleNamespace(
        metadata=types.SimpleNamespace(name=name),
        spec=types.SimpleNamespace(
            ports=[types.SimpleNamespace(port=port)] if port else []
        ),
    )


def _fake_kubernetes(services, watch_events=None):
    """Build a fake `kubernetes` package exposing the surface kube.py uses
    (CoreV1Api list + watch.Watch event stream)."""
    module = types.ModuleType("kubernetes")

    class FakeCoreV1Api:
        last_call = {}

        def list_namespaced_service(self, namespace, label_selector=None):
            FakeCoreV1Api.last_call = {
                "namespace": namespace,
                "label_selector": label_selector,
            }
            return types.SimpleNamespace(
                items=[_svc(name, port) for name, port in services]
            )

    class FakeWatch:
        def __init__(self):
            self._stopped = False

        def stream(self, fn, namespace, label_selector=None,
                   timeout_seconds=None):
            for event in (watch_events or []):
                if self._stopped:
                    return
                yield event
            # keep the stream open until stop() so the thread idles
            # instead of hot-resyncing
            import time as _t
            while not self._stopped:
                _t.sleep(0.01)

        def stop(self):
            self._stopped = True

    client = types.ModuleType("kubernetes.client")
    client.CoreV1Api = FakeCoreV1Api
    config = types.ModuleType("kubernetes.config")
    config.load_incluster_config = lambda: None
    config.load_kube_config = lambda: None
    watch = types.ModuleType("kubernetes.watch")
    watch.Watch = FakeWatch
    module.client = client
    module.config = config
    module.watch = watch
    return module, FakeCoreV1Api


def test_targets_from_services(monkeypatch):
    module, api = _fake_kubernetes([("gordo-server-0", 5555), ("gordo-server-1", 80)])
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    monkeypatch.setitem(sys.modules, "kubernetes.client", module.client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", module.config)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("prod-ns", project="proj-x", in_cluster=False)
    assert disc.targets() == [
        "http://gordo-server-0.prod-ns:5555",
        "http://gordo-server-1.prod-ns:80",
    ]
    assert api.last_call["namespace"] == "prod-ns"
    assert "gordo/project=proj-x" in api.last_call["label_selector"]


def test_portless_service_defaults_to_80(monkeypatch):
    module, _ = _fake_kubernetes([("bare-svc", None)])
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    monkeypatch.setitem(sys.modules, "kubernetes.client", module.client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", module.config)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("ns", in_cluster=False)
    assert disc.targets() == ["http://bare-svc.ns:80"]


def test_import_gated_without_package():
    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    with mock.patch.dict(sys.modules, {"kubernetes": None}):
        with pytest.raises(ImportError, match="kubernetes"):
            KubeTargetDiscovery("ns")


def _install(monkeypatch, module):
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    monkeypatch.setitem(sys.modules, "kubernetes.client", module.client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", module.config)
    monkeypatch.setitem(sys.modules, "kubernetes.watch", module.watch)


def test_watch_stream_updates_targets_and_fires_on_change(monkeypatch):
    """Service ADDED/DELETED events mutate the live target cache without
    re-listing, and each change fires the on_change callback — fleet
    membership propagates at event latency, not poll cadence."""
    import time

    events = [
        {"type": "ADDED", "object": _svc("svc-new", 5555)},
        {"type": "DELETED", "object": _svc("svc-old", 5555)},
    ]
    module, _ = _fake_kubernetes([("svc-old", 5555)], watch_events=events)
    _install(monkeypatch, module)

    from gordo_tpu.watchman.kube import KubeTargetDiscovery

    disc = KubeTargetDiscovery("ns", in_cluster=False)
    changes = []
    disc.on_change = lambda: changes.append(disc.targets())
    disc.start_watch()
    try:
        deadline = time.time() + 5
        while time.time() < deadline:
            if disc.targets() == ["http://svc-new.ns:5555"]:
                break
            time.sleep(0.01)
        # seed list had svc-old; ADDED added svc-new; DELETED removed svc-old
        assert disc.targets() == ["http://svc-new.ns:5555"]
        assert len(changes) >= 2  # add + delete each notified
    finally:
        disc.stop_watch()
    # after stop the cache is dropped: targets() lists again (svc-old)
    assert disc.targets() == ["http://svc-old.ns:5555"]


def test_watchman_start_wires_watch_and_nudges_loop(monkeypatch):
    """Watchman.start() starts a watch-capable discovery and a change
    notification wakes the poll loop immediately (no poll_interval wait)."""
    import asyncio

    from gordo_tpu.watchman.server import Watchman

    class StubWatchDiscovery:
        def __init__(self):
            self.on_change = None
            self.watching = False
            self.stopped = False

        def start_watch(self):
            self.watching = True

        def stop_watch(self):
            self.stopped = True

        def targets(self):
            return []

    disc = StubWatchDiscovery()
    refreshes = []

    async def main():
        watchman = Watchman(
            "p", [], [], target_discovery=disc, discover=False,
            poll_interval=3600,  # only a nudge can trigger a 2nd refresh
        )

        async def fake_refresh():
            refreshes.append(asyncio.get_running_loop().time())
            return []

        watchman.refresh = fake_refresh
        watchman.start()
        assert disc.on_change is not None
        await asyncio.sleep(0.05)  # first cycle
        assert disc.watching
        n0 = len(refreshes)
        disc.on_change()  # simulate a watch event (thread-safe path)
        await asyncio.sleep(0.05)
        assert len(refreshes) > n0  # woke before the 1h poll interval
        await watchman.stop()
        assert disc.stopped

    asyncio.run(main())


def test_watchman_merges_discovered_targets(monkeypatch):
    """A target_discovery object's URLs join the static target list."""
    import asyncio

    from gordo_tpu.watchman.server import Watchman

    class StubDiscovery:
        def targets(self):
            return ["http://svc-a.ns:5555", "http://static:1"]

    watchman = Watchman(
        "p", [], ["http://static:1"],
        target_discovery=StubDiscovery(), discover=False,
    )
    targets = asyncio.run(watchman._current_targets())
    assert targets == ["http://static:1", "http://svc-a.ns:5555"]
