"""CLI tests via click.testing.CliRunner (reference pattern: CLI tests
drive `build`/`workflow generate` with env vars, SURVEY.md §5)."""

import json
import os

import yaml
from click.testing import CliRunner

from gordo_tpu.cli.cli import gordo
import pytest

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow

DATA_CONFIG = {
    "type": "RandomDataset",
    "tags": ["cli-1", "cli-2"],
    "train_start_date": "2017-12-25T06:00:00Z",
    "train_end_date": "2017-12-26T06:00:00Z",
}

MODEL_CONFIG = {
    "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.pipeline.Pipeline": {
                "steps": [
                    "gordo_tpu.ops.scalers.MinMaxScaler",
                    {"gordo_tpu.models.estimator.AutoEncoder": {
                        "kind": "feedforward_hourglass",
                        "epochs": 1,
                        "batch_size": 64,
                    }},
                ]
            }
        }
    }
}

PROJECT_YAML = {
    "machines": [
        {"name": "cli-machine", "dataset": DATA_CONFIG},
    ],
    "globals": {"model": MODEL_CONFIG},
}


def test_build_with_env_vars(tmp_path):
    out = tmp_path / "models"
    runner = CliRunner()
    result = runner.invoke(
        gordo,
        ["build", str(out)],
        env={
            "MACHINE_NAME": "env-machine",
            "MODEL_CONFIG": json.dumps(MODEL_CONFIG),
            "DATA_CONFIG": json.dumps(DATA_CONFIG),
        },
    )
    assert result.exit_code == 0, result.output
    artifact = result.output.strip().splitlines()[-1]
    assert os.path.isdir(artifact)
    assert os.path.exists(os.path.join(artifact, "model.pkl"))


def test_build_print_cv_scores_and_cache(tmp_path):
    out = tmp_path / "models"
    reg = tmp_path / "register"
    runner = CliRunner()
    args = [
        "build", str(out),
        "--name", "cvm",
        "--model-config", json.dumps(MODEL_CONFIG),
        "--data-config", json.dumps(DATA_CONFIG),
        "--model-register-dir", str(reg),
        "--print-cv-scores",
    ]
    first = runner.invoke(gordo, args)
    assert first.exit_code == 0, first.output
    assert "explained_variance_score" in first.output
    # second run: cache hit, same artifact path
    second = runner.invoke(gordo, args)
    assert second.exit_code == 0
    assert first.output.strip().splitlines()[-1] == second.output.strip().splitlines()[-1]


def test_build_project_cli(tmp_path):
    cfg = tmp_path / "project.yaml"
    cfg.write_text(yaml.safe_dump(PROJECT_YAML))
    out = tmp_path / "models"
    runner = CliRunner()
    result = runner.invoke(
        gordo,
        ["build-project", "--machine-config", str(cfg),
         "--output-dir", str(out), "--project-name", "cliproj"],
    )
    assert result.exit_code == 0, result.output
    summary = json.loads(result.output.strip().splitlines()[-1])
    assert summary["n_machines"] == 1
    assert not summary["failed"]
    from gordo_tpu import artifacts
    assert "cli-machine" in artifacts.machines_on_disk(str(out))


def test_workflow_generate_and_unique_tags(tmp_path):
    cfg = tmp_path / "project.yaml"
    cfg.write_text(yaml.safe_dump(PROJECT_YAML))
    runner = CliRunner()

    gen = runner.invoke(
        gordo,
        ["workflow", "generate", "--machine-config", str(cfg),
         "--project-name", "wfproj"],
    )
    assert gen.exit_code == 0, gen.output
    docs = list(yaml.safe_load_all(gen.output))
    assert any(d["kind"] == "Job" for d in docs)

    tags = runner.invoke(
        gordo, ["workflow", "unique-tags", "--machine-config", str(cfg)]
    )
    assert tags.exit_code == 0
    assert tags.output.split() == ["cli-1", "cli-2"]

    plan = runner.invoke(
        gordo, ["workflow", "plan", "--machine-config", str(cfg)]
    )
    assert plan.exit_code == 0
    assert yaml.safe_load(plan.output)["n_buckets"] == 1

    argo = runner.invoke(
        gordo,
        ["workflow", "generate", "--machine-config", str(cfg),
         "--project-name", "wfproj", "--format", "argo"],
    )
    assert argo.exit_code == 0, argo.output
    argo_docs = list(yaml.safe_load_all(argo.output))
    kinds = [d["kind"] for d in argo_docs]
    assert "Workflow" in kinds and "Job" not in kinds
    assert "Deployment" in kinds  # serving manifests still emitted


def test_build_project_machines_filter(tmp_path):
    """--machines restricts the build to the named subset; unknown names
    error loudly instead of silently building nothing."""
    project = {
        "machines": [
            dict(PROJECT_YAML["machines"][0], name=f"flt-{i}")
            for i in range(3)
        ],
        "globals": PROJECT_YAML.get("globals", {}),
    }
    cfg = tmp_path / "project.yaml"
    cfg.write_text(yaml.safe_dump(project))
    out = tmp_path / "models"
    runner = CliRunner()
    result = runner.invoke(
        gordo,
        ["build-project", "--machine-config", str(cfg),
         "--output-dir", str(out), "--machines", "flt-0,flt-2"],
    )
    assert result.exit_code == 0, result.output
    summary = json.loads(result.output.strip().splitlines()[-1])
    assert summary["n_machines"] == 2
    from gordo_tpu import artifacts
    on_disk = artifacts.machines_on_disk(str(out))
    assert {"flt-0", "flt-2"} <= on_disk
    assert "flt-1" not in on_disk

    bad = runner.invoke(
        gordo,
        ["build-project", "--machine-config", str(cfg),
         "--output-dir", str(out), "--machines", "flt-0,nope"],
    )
    assert bad.exit_code != 0
    assert "nope" in bad.output


def test_help_lists_all_verbs():
    runner = CliRunner()
    result = runner.invoke(gordo, ["--help"])
    for verb in ("build", "build-project", "run-server", "run-watchman",
                 "client", "workflow"):
        assert verb in result.output


def test_telemetry_dump_merges_snapshot_dir(tmp_path):
    """`gordo telemetry dump --dir` merges the shard-local snapshots a
    (multi-host) build wrote and prints Prometheus text; the bare verb
    prints this process's registry."""
    from gordo_tpu import telemetry

    reg = telemetry.MetricsRegistry(enabled=True)
    reg.counter("gordo_cli_test_total", "x").inc(2)
    snap_dir = tmp_path / "models" / telemetry.SNAPSHOT_DIR
    reg.write_snapshot(str(snap_dir / "shard-000-of-002.json"))
    reg.write_snapshot(str(snap_dir / "shard-001-of-002.json"))

    runner = CliRunner()
    result = runner.invoke(
        gordo, ["telemetry", "dump", "--dir", str(tmp_path / "models")]
    )
    assert result.exit_code == 0, result.output
    assert "gordo_cli_test_total 4" in result.output  # 2 shards merged

    bare = runner.invoke(gordo, ["telemetry", "dump"])
    assert bare.exit_code == 0, bare.output
    assert "# TYPE gordo_events_total counter" in bare.output

    missing = runner.invoke(
        gordo, ["telemetry", "dump", "--dir", str(tmp_path / "empty")]
    )
    assert missing.exit_code != 0


def test_telemetry_dump_format_json(tmp_path):
    """Satellite: `--format json` prints the JSON snapshot document
    (merge-able), `--format prom` (the default) the text exposition, and
    a live-scrape + json combination is refused rather than guessed."""
    from gordo_tpu import telemetry

    reg = telemetry.MetricsRegistry(enabled=True)
    reg.counter("gordo_cli_fmt_total", "x").inc(3)
    snap_dir = tmp_path / "models" / telemetry.SNAPSHOT_DIR
    reg.write_snapshot(str(snap_dir / "shard-000-of-001.json"))

    runner = CliRunner()
    as_json = runner.invoke(
        gordo,
        ["telemetry", "dump", "--dir", str(tmp_path / "models"),
         "--format", "json"],
    )
    assert as_json.exit_code == 0, as_json.output
    doc = json.loads(as_json.output)
    assert doc["gordo_telemetry_snapshot"] == 1
    assert "gordo_cli_fmt_total" in doc["metrics"]

    bare_json = runner.invoke(gordo, ["telemetry", "dump", "--format", "json"])
    assert bare_json.exit_code == 0
    assert json.loads(bare_json.output)["gordo_telemetry_snapshot"] == 1

    refused = runner.invoke(
        gordo,
        ["telemetry", "dump", "--url", "http://localhost:1",
         "--format", "json"],
    )
    assert refused.exit_code != 0
    assert "not available with --url" in refused.output


def test_fleet_health_cli_reads_rollup_dir(tmp_path):
    """`gordo fleet-health --dir` merges the rollup JSONL files serving
    processes append and prints the status summary (or the full doc)."""
    import numpy as np

    from gordo_tpu import telemetry
    from gordo_tpu.telemetry import fleet_health as fh

    telemetry.FLEET_HEALTH.clear()
    try:
        rng = np.random.default_rng(0)
        base = fh.sketch_from_scores(
            rng.lognormal(0, 1, 4000), ts=0.0
        ).to_doc()
        telemetry.FLEET_HEALTH.set_baseline("cli-m-drift", base)
        telemetry.FLEET_HEALTH.set_baseline("cli-m-ok", base)
        telemetry.FLEET_HEALTH.record(
            "cli-m-drift", rng.lognormal(2.5, 1, 1000)
        )
        telemetry.FLEET_HEALTH.record("cli-m-ok", rng.lognormal(0, 1, 1000))
        fh.write_rollup(str(tmp_path), telemetry.FLEET_HEALTH.doc())
    finally:
        telemetry.FLEET_HEALTH.clear()

    runner = CliRunner()
    summary = runner.invoke(gordo, ["fleet-health", "--dir", str(tmp_path)])
    assert summary.exit_code == 0, summary.output
    doc = json.loads(summary.output)
    assert doc["machines"] == 2
    assert doc["by-status"]["drifting"] == 1
    assert doc["top-drift"][0]["machine"] == "cli-m-drift"

    full = runner.invoke(
        gordo, ["fleet-health", "--dir", str(tmp_path), "--full"]
    )
    assert full.exit_code == 0
    assert "cli-m-ok" in json.loads(full.output)["machines"]

    both = runner.invoke(
        gordo,
        ["fleet-health", "--dir", str(tmp_path), "--url", "http://x:1"],
    )
    assert both.exit_code != 0

    empty = runner.invoke(
        gordo, ["fleet-health", "--dir", str(tmp_path / "nope")]
    )
    assert empty.exit_code != 0
