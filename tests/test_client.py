"""Client integration tests against an in-process ML server.

Reference pattern (SURVEY.md §5): client tests run against real server
code with the RandomDataProvider-style synthetic backend — no external
services.
"""

import asyncio

import numpy as np
import pandas as pd
import pytest
from aiohttp import web

from gordo_tpu.builder import build_project
from gordo_tpu.client import Client, ForwardPredictionsToDisk, PredictionResult
from gordo_tpu.client.client import _frame_from_payload
from gordo_tpu.serve import ModelCollection, build_app
from gordo_tpu.workflow import NormalizedConfig

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow

PROJECT = {
    "machines": [
        {"name": "client-machine-a", "dataset": {
            "type": "RandomDataset",
            "tags": ["ct-1", "ct-2", "ct-3"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-27T06:00:00Z",
        }},
        {"name": "client-machine-b", "dataset": {
            "type": "RandomDataset",
            "tags": ["ct-4", "ct-5", "ct-6"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-27T06:00:00Z",
        }},
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {"gordo_tpu.models.estimator.AutoEncoder": {
                                "kind": "feedforward_hourglass",
                                "epochs": 2,
                                "batch_size": 64,
                            }},
                        ]
                    }
                }
            }
        }
    },
}


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("client-artifacts")
    result = build_project(NormalizedConfig(PROJECT, "cliproj").machines, str(out))
    assert not result.failed
    return str(out)


def _serve_and(model_dir, fn):
    """Start a real aiohttp server on an ephemeral port, run ``fn(port)``
    in a worker thread (the sync Client API), return its result."""

    async def runner():
        collection = ModelCollection.from_directory(model_dir, project="cliproj")
        app_runner = web.AppRunner(build_app(collection))
        await app_runner.setup()
        site = web.TCPSite(app_runner, "127.0.0.1", 0)
        await site.start()
        port = app_runner.addresses[0][1]
        try:
            return await asyncio.get_running_loop().run_in_executor(
                None, fn, port
            )
        finally:
            await app_runner.cleanup()

    return asyncio.run(runner())


class TestClient:
    def test_discovery_and_metadata(self, model_dir):
        def run(port):
            c = Client("cliproj", port=port)
            names = c.machine_names()
            meta = c.machine_metadata(names[0])
            return names, meta

        names, meta = _serve_and(model_dir, run)
        assert names == ["client-machine-a", "client-machine-b"]
        assert meta["name"] == "client-machine-a"
        assert [t["name"] for t in meta["dataset"]["tag_list"]] == [
            "ct-1", "ct-2", "ct-3",
        ]

    def test_predict_full_project(self, model_dir):
        def run(port):
            return Client("cliproj", port=port, batch_size=100).predict(
                "2017-12-27T06:00:00Z", "2017-12-28T06:00:00Z"
            )

        results = _serve_and(model_dir, run)
        assert len(results) == 2
        for res in results:
            assert isinstance(res, PredictionResult)
            assert res.ok, res.error_messages
            frame = res.predictions
            # 24h at 10min resolution, several 100-row chunks reassembled
            assert len(frame) == 145
            assert frame.index.is_monotonic_increasing
            total = frame[("total-anomaly-score", "")].to_numpy()
            assert np.isfinite(total).all()
            assert ("tag-anomaly-scores", "ct-1") in frame.columns or (
                "tag-anomaly-scores", "ct-4") in frame.columns
            assert ("total-anomaly-threshold", "") in frame.columns

    def test_predict_bulk_respects_samples_budget(self, model_dir,
                                                  monkeypatch):
        # 6 total columns across the fleet; 120 samples -> 20-row rounds
        # instead of batch_size=100 — a long range must still cover the
        # whole period, just over more (smaller) bulk bodies
        monkeypatch.setenv("GORDO_CLIENT_MAX_BULK_SAMPLES", "120")

        def run(port):
            return Client("cliproj", port=port, batch_size=100).predict(
                "2017-12-27T06:00:00Z", "2017-12-28T06:00:00Z"
            )

        results = _serve_and(model_dir, run)
        assert len(results) == 2
        for res in results:
            assert res.ok, res.error_messages
            frame = res.predictions
            assert len(frame) == 145
            assert frame.index.is_monotonic_increasing
            assert np.isfinite(
                frame[("total-anomaly-score", "")].to_numpy()
            ).all()

    def test_predict_forwards(self, model_dir, tmp_path):
        sink = tmp_path / "sink"

        def run(port):
            return Client(
                "cliproj",
                port=port,
                prediction_forwarder=ForwardPredictionsToDisk(str(sink)),
            ).predict(
                "2017-12-27T06:00:00Z",
                "2017-12-27T12:00:00Z",
                machine_names=["client-machine-a"],
            )

        results = _serve_and(model_dir, run)
        assert results[0].ok
        files = list((sink / "client-machine-a").iterdir())
        assert len(files) == 1
        stored = pd.read_csv(files[0]) if files[0].suffix == ".csv" else pd.read_parquet(files[0])
        assert len(stored) == len(results[0].predictions)

    def test_download_model(self, model_dir):
        def run(port):
            return Client("cliproj", port=port).download_model("client-machine-a")

        model = _serve_and(model_dir, run)
        assert hasattr(model, "anomaly")

    def test_unknown_machine_reports_error(self, model_dir):
        def run(port):
            return Client("cliproj", port=port).predict(
                "2017-12-27T06:00:00Z",
                "2017-12-27T12:00:00Z",
                machine_names=["nope"],
            )

        results = _serve_and(model_dir, run)
        assert not results[0].ok
        assert results[0].predictions is None


def test_frame_from_payload_shapes():
    data = {
        "model-output": np.ones((5, 2)).tolist(),
        "tag-anomaly-scores": np.ones((5, 2)).tolist(),
        "total-anomaly-score": np.ones(5).tolist(),
        "tag-anomaly-thresholds": [0.5, 0.7],
        "total-anomaly-threshold": 0.9,
    }
    idx = pd.date_range("2020-01-01", periods=7, freq="10min")
    frame = _frame_from_payload(data, ["a", "b"], idx)
    assert len(frame) == 5
    # aligned to the TAIL of the index (offset rows consumed at the front)
    assert frame.index[0] == idx[2]
    assert frame[("tag-anomaly-thresholds", "b")].iloc[0] == 0.7
    assert frame[("total-anomaly-threshold", "")].iloc[-1] == 0.9


def test_predict_bulk_matches_per_machine(model_dir):
    """use_bulk=True must return the same frames as the per-machine path."""

    def run(port):
        normal = Client("cliproj", port=port, batch_size=60).predict(
            "2017-12-27T06:00:00Z", "2017-12-27T18:00:00Z"
        )
        # default bulk wire format (msgpack) and the JSON fallback must
        # both match the per-machine path
        bulk = Client("cliproj", port=port, batch_size=60, use_bulk=True).predict(
            "2017-12-27T06:00:00Z", "2017-12-27T18:00:00Z"
        )
        bulk_json = Client(
            "cliproj", port=port, batch_size=60, use_bulk=True,
            use_msgpack=False,
        ).predict("2017-12-27T06:00:00Z", "2017-12-27T18:00:00Z")
        return normal, bulk, bulk_json

    normal, bulk, bulk_json = _serve_and(model_dir, run)
    assert [r.name for r in normal] == [r.name for r in bulk]
    for a, b in zip(normal, bulk_json):
        assert b.ok, b.error_messages
        np.testing.assert_allclose(
            a.predictions[("total-anomaly-score", "")].to_numpy(),
            b.predictions[("total-anomaly-score", "")].to_numpy(),
            rtol=1e-4, atol=1e-5,
        )
    for a, b in zip(normal, bulk):
        assert b.ok, b.error_messages
        assert len(a.predictions) == len(b.predictions)
        np.testing.assert_allclose(
            a.predictions[("total-anomaly-score", "")].to_numpy(),
            b.predictions[("total-anomaly-score", "")].to_numpy(),
            rtol=1e-4, atol=1e-5,
        )


def test_columnar_bulk_matches_msgpack_bitwise(model_dir):
    """The GSB1 columnar wire (the bulk default) must yield frames that
    are VALUE-IDENTICAL to the msgpack wire — same fp32 bits, since both
    ship the server's raw array bytes — and the lazy result must expose
    raw column access without ever building a DataFrame."""

    def run(port):
        columnar = Client(
            "cliproj", port=port, batch_size=60, use_bulk=True
        ).predict("2017-12-27T06:00:00Z", "2017-12-27T18:00:00Z")
        msgpack = Client(
            "cliproj", port=port, batch_size=60, use_bulk=True,
            use_columnar=False,
        ).predict("2017-12-27T06:00:00Z", "2017-12-27T18:00:00Z")
        return columnar, msgpack

    columnar, msgpack = _serve_and(model_dir, run)
    assert [r.name for r in columnar] == [r.name for r in msgpack]
    for col, mp in zip(columnar, msgpack):
        assert col.ok, col.error_messages
        # frame-free path: raw chunks and concatenated columns, no
        # DataFrame materialized yet
        lazy = col.raw
        assert lazy is not None and lazy._frame is None
        total = col.arrays("total-anomaly-score")
        scores = col.arrays("tag-anomaly-scores")
        threshold = col.arrays("total-anomaly-threshold")
        assert lazy._frame is None  # still no frame
        assert total.dtype == np.float32 and total.ndim == 1
        assert scores.ndim == 2 and len(scores) == len(total)
        assert isinstance(threshold, float)
        # bitwise identity against the msgpack wire
        np.testing.assert_array_equal(total, mp.arrays("total-anomaly-score"))
        assert scores.tobytes() == mp.arrays("tag-anomaly-scores").tobytes()
        assert threshold == mp.arrays("total-anomaly-threshold")
        # and the materialized frames agree too (exercises LazyFrame.frame)
        pd.testing.assert_frame_equal(col.predictions, mp.predictions)
        assert lazy._frame is not None  # .predictions cached the frame


def test_frame_from_payload_thresholds_when_rows_equal_tags():
    """Known keys dispatch by name: with n_rows == n_tags, a per-tag
    threshold vector must still become per-tag constant columns and a
    per-row series must stay a single ('key','') column."""
    data = {
        "model-output": np.ones((2, 2)).tolist(),
        "total-anomaly-score": [1.0, 2.0],
        "anomaly-confidence": [0.1, 0.2],
        "tag-anomaly-thresholds": [0.5, 0.7],
        "total-anomaly-threshold": 0.9,
    }
    idx = pd.date_range("2020-01-01", periods=2, freq="10min")
    frame = _frame_from_payload(data, ["a", "b"], idx)
    assert frame[("tag-anomaly-thresholds", "a")].tolist() == [0.5, 0.5]
    assert frame[("tag-anomaly-thresholds", "b")].tolist() == [0.7, 0.7]
    assert frame[("total-anomaly-score", "")].tolist() == [1.0, 2.0]
    assert frame[("anomaly-confidence", "")].tolist() == [0.1, 0.2]


def test_client_roundtrip_returns_server_time_columns(model_dir):
    """The frames a client assembles carry the SERVER's start index and an
    ('end','') column — clients no longer reattach time locally."""

    def run(port):
        return Client("cliproj", port=port, batch_size=50).predict(
            "2017-12-25T06:00:00Z", "2017-12-26T06:00:00Z",
            machine_names=["client-machine-a"],
        )

    results = _serve_and(model_dir, run)
    assert results[0].ok
    frame = results[0].predictions
    assert isinstance(frame.index, pd.DatetimeIndex)
    assert frame.index.name == "start"
    assert ("end", "") in frame.columns
    # end - start is the dataset resolution (10min for RandomDataset builds)
    deltas = (frame[("end", "")] - frame.index).unique()
    assert len(deltas) == 1


def test_fleet_generation_and_wait(model_dir):
    """ISSUE 11 satellite: clients surface each replica's active artifact
    generation and can await a generation fleet-wide."""

    def run(port):
        c = Client("cliproj", port=port)
        gens = c.fleet_generation()
        # already-satisfied wait returns immediately with the same map
        waited = c.wait_for_generation(max(gens.values()), timeout=10)
        try:
            c.wait_for_generation(max(gens.values()) + 1, timeout=1.0)
            timed_out = False
        except TimeoutError:
            timed_out = True
        return gens, waited, timed_out

    gens, waited, timed_out = _serve_and(model_dir, run)
    assert gens and all(g > 0 for g in gens.values())
    assert waited == gens
    assert timed_out, "an unreached generation must raise TimeoutError"
