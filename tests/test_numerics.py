"""Numerics CI (SURVEY.md §6.2): the reference has no sanitizers to port
(pure Python); the TPU-native substitute is jit-vs-eager equivalence and
NaN-debug-mode runs over the hot paths."""

import jax
import numpy as np
import pytest

import gordo_tpu.models.factories  # noqa: F401
from gordo_tpu.registry import lookup_factory
from gordo_tpu.train.fit import TrainConfig, fit

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow


@pytest.fixture()
def module(sine_tags):
    factory = lookup_factory("AutoEncoder", "feedforward_hourglass")
    return factory(n_features=sine_tags.shape[1],
                   n_features_out=sine_tags.shape[1])


def test_fit_jit_vs_eager_equivalence(module, sine_tags):
    cfg = TrainConfig(epochs=2, batch_size=128)
    jit_params, jit_hist = fit(module, sine_tags, sine_tags, cfg,
                               rng=jax.random.PRNGKey(3))
    with jax.disable_jit():
        eager_params, eager_hist = fit(module, sine_tags, sine_tags, cfg,
                                       rng=jax.random.PRNGKey(3))
    # float32 fusion/accumulation order differs between the compiled and
    # op-by-op programs; the check guards SEMANTIC divergence, not ulps
    np.testing.assert_allclose(jit_hist, eager_hist, rtol=1e-3, atol=1e-5)
    for a, b in zip(jax.tree.leaves(jit_params), jax.tree.leaves(eager_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-2, atol=5e-4
        )


def test_scoring_jit_vs_eager(module, sine_tags):
    from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector
    from gordo_tpu.models.estimator import AutoEncoder
    from gordo_tpu.ops.scalers import MinMaxScaler
    from gordo_tpu.pipeline import Pipeline

    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([MinMaxScaler(), AutoEncoder(epochs=1, batch_size=128)]),
        require_thresholds=False,
    )
    det.fit(sine_tags)
    jit_frame = det.anomaly(sine_tags[:50])
    with jax.disable_jit():
        eager_frame = det.anomaly(sine_tags[:50])
    np.testing.assert_allclose(
        jit_frame[("total-anomaly-score", "")].to_numpy(),
        eager_frame[("total-anomaly-score", "")].to_numpy(),
        rtol=1e-3, atol=1e-4,
    )


def test_fit_under_debug_nans(module, sine_tags):
    """The whole training program must stay finite under jax_debug_nans
    (any NaN raises immediately instead of poisoning params silently)."""
    jax.config.update("jax_debug_nans", True)
    try:
        params, hist = fit(
            module, sine_tags, sine_tags,
            TrainConfig(epochs=1, batch_size=128),
        )
        assert np.all(np.isfinite(hist))
    finally:
        jax.config.update("jax_debug_nans", False)
