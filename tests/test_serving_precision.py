"""Serving-precision plane + device-resident end-to-end scoring (ISSUE 7).

The acceptance-critical pins:

- the fused request path is decode → ONE device dispatch → encode, and
  the telemetry counters attest it per request;
- the fused epilogue (confidence on device) is BITWISE identical to the
  r11 host-side epilogue at fp32 (``GORDO_SERVE_FUSED=off``);
- ``GORDO_SERVE_DTYPE=bfloat16`` serving passes the fp32 parity gate
  with per-machine error bounds, across the per-machine, full-bucket,
  and subset-gather program variants (the full sweep incl. LSTM +
  smoothing lives in the slow lane);
- int8 is refused without the explicit opt-in;
- unknown wire dtypes are a 415 at the HTTP surface, both directions;
- the generated manifests stamp ``GORDO_SERVE_DTYPE`` on builder AND
  server pods;
- the request-path host-math lint gate rejects ``np.*`` compute in the
  serve dispatch scopes.
"""

import importlib.util
import os

import numpy as np
import pytest

from gordo_tpu import telemetry
from gordo_tpu.builder import build_project
from gordo_tpu.serve import precision
from gordo_tpu.serve.server import ModelCollection
from gordo_tpu.workflow import NormalizedConfig

_FF_MODEL = {
    "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.pipeline.Pipeline": {
                "steps": [
                    "gordo_tpu.ops.scalers.MinMaxScaler",
                    {
                        "gordo_tpu.models.estimator.AutoEncoder": {
                            "kind": "feedforward_hourglass",
                            "epochs": 2,
                            "batch_size": 64,
                        }
                    },
                ]
            }
        }
    }
}

_SMOOTH_MODEL = {
    "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
        "window": 4,  # exercises the fused rolling-median under bf16
        "base_estimator": _FF_MODEL[
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector"
        ]["base_estimator"],
    }
}

PROJECT = {
    "machines": [
        {
            "name": "prec-m-0",
            "dataset": {
                "type": "RandomDataset",
                "tags": ["t1", "t2", "t3"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-27T06:00:00Z",
            },
        },
        {
            "name": "prec-m-1",
            "dataset": {
                "type": "RandomDataset",
                "tags": ["t1", "t2", "t3"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-27T06:00:00Z",
            },
        },
        {
            "name": "prec-m-smooth",
            "dataset": {
                "type": "RandomDataset",
                "tags": ["t1", "t2", "t3"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-27T06:00:00Z",
            },
            "model": _SMOOTH_MODEL,
        },
    ],
    "globals": {"model": _FF_MODEL},
}

#: the per-machine fp32-vs-reduced parity bounds (max abs error as a
#: fraction of the machine's max |fp32| value — the methodology of
#: docs/perf.md "Serving precision").  Measured bf16 errors on the bench
#: model family sit under 1%; the bounds leave headroom for LSTM
#: accumulation without ever letting a broken cast (100% error) pass.
PARITY_BOUNDS = {
    "model-output": 0.03,
    "tag-anomaly-scores": 0.10,
    "total-anomaly-score": 0.10,
    "anomaly-confidence": 0.10,
}


def assert_parity(ref, reduced, bounds=PARITY_BOUNDS, label=""):
    for key, tol in bounds.items():
        if key not in ref:
            continue
        r = np.asarray(ref[key], np.float32)
        q = np.asarray(reduced[key], np.float32)
        assert r.shape == q.shape, (label, key)
        scale = max(float(np.max(np.abs(r))), 1e-6)
        err = float(np.max(np.abs(r - q))) / scale
        assert err <= tol, (
            f"{label}{key}: max-normalized error {err:.4%} > {tol:.2%}"
        )


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("prec-artifacts")
    cfg = NormalizedConfig(PROJECT, "precproj")
    result = build_project(cfg.machines, str(out))
    assert not result.failed
    return str(out)


def _counter_total(name: str) -> float:
    metric = telemetry.REGISTRY.snapshot()["metrics"].get(name) or {}
    return float(sum(metric.get("series", {}).values()))


def _X(rows=300, cols=3, seed=11):
    return np.random.default_rng(seed).standard_normal(
        (rows, cols)
    ).astype(np.float32)


# ---------------------------------------------------------------------------
# dtype resolution policy
# ---------------------------------------------------------------------------

def test_serve_dtype_resolution(monkeypatch):
    monkeypatch.delenv("GORDO_SERVE_DTYPE", raising=False)
    assert precision.serve_dtype() == "float32"
    assert precision.serve_dtype(default="bf16") == "bfloat16"
    monkeypatch.setenv("GORDO_SERVE_DTYPE", "fp32")
    # env beats the manifest default
    assert precision.serve_dtype(default="bfloat16") == "float32"
    monkeypatch.setenv("GORDO_SERVE_DTYPE", "bf16")
    assert precision.serve_dtype() == "bfloat16"
    monkeypatch.setenv("GORDO_SERVE_DTYPE", "float8")
    with pytest.raises(ValueError, match="unknown serving dtype"):
        precision.serve_dtype()


def test_int8_requires_explicit_opt_in(monkeypatch):
    monkeypatch.setenv("GORDO_SERVE_DTYPE", "int8")
    monkeypatch.delenv("GORDO_SERVE_INT8", raising=False)
    with pytest.raises(ValueError, match="opt-in"):
        precision.serve_dtype()
    monkeypatch.setenv("GORDO_SERVE_INT8", "1")
    assert precision.serve_dtype() == "int8"


# ---------------------------------------------------------------------------
# the fused single-dispatch path
# ---------------------------------------------------------------------------

def test_single_dispatch_and_transfer_per_request(model_dir):
    collection = ModelCollection.from_directory(model_dir, project="precproj")
    scorer = collection.get("prec-m-0").scorer
    X = _X()
    scorer.anomaly_arrays(X)  # compile outside the counted window
    d0 = _counter_total("gordo_serve_dispatches_total")
    t0 = _counter_total("gordo_serve_input_transfers_total")
    n = 5
    for _ in range(n):
        scorer.anomaly_arrays(X)
    assert _counter_total("gordo_serve_dispatches_total") - d0 == n
    assert _counter_total("gordo_serve_input_transfers_total") - t0 == n


def test_fused_equals_host_epilogue_fp32(model_dir, monkeypatch):
    """The r11 host-side epilogue (GORDO_SERVE_FUSED=off: concatenate/
    tile padding + host confidence divide) and the fused program must
    agree BITWISE at fp32 — same machines, same request."""
    collection = ModelCollection.from_directory(model_dir, project="precproj")
    X = _X()
    for name in ("prec-m-0", "prec-m-smooth"):
        scorer = collection.get(name).scorer
        fused = scorer.anomaly_arrays(X)
        monkeypatch.setenv("GORDO_SERVE_FUSED", "off")
        host = scorer.anomaly_arrays(X)
        monkeypatch.delenv("GORDO_SERVE_FUSED")
        assert set(fused) == set(host)
        for key in fused:
            np.testing.assert_array_equal(
                np.asarray(fused[key]), np.asarray(host[key]),
                err_msg=f"{name}/{key}",
            )


def test_concurrent_same_bucket_requests_do_not_corrupt(model_dir):
    """The pinned-pad-buffer aliasing regression: on the CPU backend a
    zero-copy ``jnp.asarray`` of the shared pad buffer would let request
    B's fill rewrite request A's live device array after the lock drops.
    Concurrent same-machine, same-bucket requests must score exactly
    what they score serially."""
    from concurrent.futures import ThreadPoolExecutor

    collection = ModelCollection.from_directory(model_dir, project="precproj")
    scorer = collection.get("prec-m-0").scorer
    payloads = [_X(rows=50 + i, seed=100 + i) for i in range(8)]
    expected = [
        np.asarray(scorer.anomaly_arrays(X)["total-anomaly-score"])
        for X in payloads
    ]
    with ThreadPoolExecutor(max_workers=8) as pool:
        for _ in range(5):
            results = list(
                pool.map(lambda X: scorer.anomaly_arrays(X), payloads)
            )
            for want, got in zip(expected, results):
                np.testing.assert_array_equal(
                    want, np.asarray(got["total-anomaly-score"])
                )


def test_pad_buffer_reused_across_same_shape_requests(model_dir):
    collection = ModelCollection.from_directory(model_dir, project="precproj")
    scorer = collection.get("prec-m-0").scorer
    scorer.anomaly_arrays(_X(rows=300))  # 300 pads up to the 512 bucket
    assert (512, 3) in scorer._pad_bufs
    buf = scorer._pad_bufs[(512, 3)]
    scorer.anomaly_arrays(_X(rows=280, seed=12))  # same bucket, same buffer
    assert scorer._pad_bufs[(512, 3)] is buf
    assert len(scorer._pad_bufs) <= scorer.MAX_PAD_BUFS


# ---------------------------------------------------------------------------
# reduced-precision parity (fast slice; the full sweep is slow-lane)
# ---------------------------------------------------------------------------

def test_bf16_parity_per_machine_and_bucket(model_dir, monkeypatch):
    """fp32 vs bf16 within the per-machine bounds, across the
    per-machine scorer AND the stacked bucket paths (full-bucket and
    1-machine subset gather) — including the smoothing machine."""
    X = _X(rows=400)
    ref_coll = ModelCollection.from_directory(model_dir, project="precproj")
    ref_fleet = ref_coll.fleet_scorer.score_all(
        {name: X for name in ref_coll.entries}
    )
    ref_sub = ref_coll.fleet_scorer.score_all({"prec-m-0": X})

    monkeypatch.setenv("GORDO_SERVE_DTYPE", "bfloat16")
    bf_coll = ModelCollection.from_directory(model_dir, project="precproj")
    assert bf_coll.serve_dtype == "bfloat16"
    bf_fleet = bf_coll.fleet_scorer.score_all(
        {name: X for name in bf_coll.entries}
    )
    bf_sub = bf_coll.fleet_scorer.score_all({"prec-m-0": X})
    for name in ref_coll.entries:
        ref_pm = ref_coll.get(name).scorer.anomaly_arrays(X)
        bf_pm = bf_coll.get(name).scorer.anomaly_arrays(X)
        assert_parity(ref_pm, bf_pm, label=f"per-machine {name}: ")
        assert_parity(
            ref_fleet[name], bf_fleet[name], label=f"bucket {name}: "
        )
    assert_parity(
        ref_sub["prec-m-0"], bf_sub["prec-m-0"], label="subset: "
    )
    # outputs stay f32 on the wire regardless of compute dtype
    assert np.asarray(
        bf_fleet["prec-m-0"]["total-anomaly-score"]
    ).dtype == np.float32


def test_int8_parity_behind_opt_in(model_dir, monkeypatch):
    X = _X(rows=300)
    ref = ModelCollection.from_directory(
        model_dir, project="precproj"
    ).get("prec-m-0").scorer.anomaly_arrays(X)
    monkeypatch.setenv("GORDO_SERVE_DTYPE", "int8")
    monkeypatch.setenv("GORDO_SERVE_INT8", "1")
    i8 = ModelCollection.from_directory(
        model_dir, project="precproj"
    ).get("prec-m-0").scorer.anomaly_arrays(X)
    # int8 fake-quant is coarser than bf16; bound it looser but finite
    assert_parity(
        ref, i8,
        bounds={k: 0.25 for k in PARITY_BOUNDS},
        label="int8: ",
    )


# ---------------------------------------------------------------------------
# HTTP surface: wire dtypes and 415s
# ---------------------------------------------------------------------------

def test_http_wire_dtype_and_415(model_dir):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from gordo_tpu.serve import codec
    from gordo_tpu.serve.server import build_app

    collection = ModelCollection.from_directory(model_dir, project="precproj")
    X = _X(rows=300)

    async def runner():
        client = TestClient(TestServer(build_app(collection)))
        await client.start_server()
        try:
            # bf16 on the wire, asked for via the Accept dtype param
            resp = await client.post(
                "/gordo/v0/precproj/_bulk/anomaly/prediction",
                data=codec.packb({"X": {"prec-m-0": X}}),
                headers={
                    "Content-Type": codec.MSGPACK_CONTENT_TYPE,
                    "Accept": codec.MSGPACK_CONTENT_TYPE + ";dtype=bfloat16",
                },
            )
            assert resp.status == 200
            doc = codec.unpackb(await resp.read())
            out = doc["data"]["prec-m-0"]["model-output"]
            assert out.dtype.name == "bfloat16"
            # unknown Accept dtype → 415, not 500
            resp = await client.post(
                "/gordo/v0/precproj/prec-m-0/anomaly/prediction",
                data=codec.packb({"X": X}),
                headers={
                    "Content-Type": codec.MSGPACK_CONTENT_TYPE,
                    "Accept": codec.MSGPACK_CONTENT_TYPE + ";dtype=int4",
                },
            )
            assert resp.status == 415
            # request body carrying an alien array dtype → 415 too
            resp = await client.post(
                "/gordo/v0/precproj/prec-m-0/anomaly/prediction",
                data=codec.packb({"X": X.astype(np.complex128)}),
                headers={"Content-Type": codec.MSGPACK_CONTENT_TYPE},
            )
            assert resp.status == 415
            # bf16 request BODIES score fine (clients may send reduced)
            import ml_dtypes

            resp = await client.post(
                "/gordo/v0/precproj/prec-m-0/anomaly/prediction",
                data=codec.packb({"X": X.astype(ml_dtypes.bfloat16)}),
                headers={"Content-Type": codec.MSGPACK_CONTENT_TYPE},
            )
            assert resp.status == 200
        finally:
            await client.close()

    asyncio.run(runner())


# ---------------------------------------------------------------------------
# generator stamping
# ---------------------------------------------------------------------------

def test_generator_stamps_serve_dtype():
    from gordo_tpu.workflow.generator import (
        generate_argo_workflow,
        generate_workflow,
    )

    cfg = NormalizedConfig(
        {"machines": PROJECT["machines"][:1], "globals": PROJECT["globals"]},
        "precproj",
    )
    docs = generate_workflow(cfg, serve_dtype="bf16")

    def envs_of(doc):
        tpl = doc["spec"]["template"]["spec"]["containers"][0]
        return {e["name"]: e.get("value") for e in tpl.get("env", [])}

    builder = next(d for d in docs if d["kind"] == "Job")
    server = next(
        d for d in docs
        if d["kind"] == "Deployment"
        and d["metadata"]["name"].startswith("gordo-server-")
    )
    assert envs_of(builder)["GORDO_SERVE_DTYPE"] == "bfloat16"
    assert envs_of(server)["GORDO_SERVE_DTYPE"] == "bfloat16"
    # unset → no stamp (the env default stays float32)
    docs_plain = generate_workflow(cfg)
    assert "GORDO_SERVE_DTYPE" not in envs_of(
        next(d for d in docs_plain if d["kind"] == "Job")
    )
    # a typo fails generation, not a pod
    with pytest.raises(ValueError):
        generate_workflow(cfg, serve_dtype="float8")
    # argo chunk tasks carry it too
    argo = generate_argo_workflow(cfg, serve_dtype="bf16")
    chunk = next(
        t for t in argo["spec"]["templates"] if t["name"] == "build-chunk"
    )
    env = {e["name"]: e["value"] for e in chunk["container"]["env"]}
    assert env["GORDO_SERVE_DTYPE"] == "bfloat16"


# ---------------------------------------------------------------------------
# the request-path host-math lint gate
# ---------------------------------------------------------------------------

class TestHostMathGate:
    @staticmethod
    def _lint(path):
        spec = importlib.util.spec_from_file_location(
            "gordo_lint", os.path.join(
                os.path.dirname(os.path.dirname(__file__)),
                "scripts", "lint.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.lint_file(path)

    def test_np_compute_in_dispatch_scope_rejected(self, tmp_path):
        bad = tmp_path / "gordo_tpu" / "serve" / "scorer.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "import numpy as np\n"
            "def _run(X):\n"
            "    X = np.concatenate([X, np.tile(X[-1:], (4, 1))])\n"
            "    return X\n"
            "def helper(X):\n"
            "    return np.concatenate([X, X])  # outside the gate\n"
        )
        msgs = [f[2] for f in self._lint(str(bad))]
        assert any("np.concatenate" in m and "_run" in m for m in msgs)
        assert any("np.tile" in m for m in msgs)
        assert not any("helper" in m for m in msgs)

    def test_serve_request_path_is_clean(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in (
            os.path.join("gordo_tpu", "serve", "scorer.py"),
            os.path.join("gordo_tpu", "serve", "fleet_scorer.py"),
        ):
            assert self._lint(os.path.join(repo, rel)) == [], rel


# ---------------------------------------------------------------------------
# the full parity sweep (slow lane; wired into CI test-full)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bf16_parity_full_suite(tmp_path, monkeypatch):
    """The fp32-vs-bf16 parity gate over the harder model family: an
    LSTM autoencoder (recurrent accumulation) plus the smoothing
    detector, at replay request sizes, across per-machine, full-bucket
    and subset dispatches — the suite a deployment must pass before
    flipping GORDO_SERVE_DTYPE=bfloat16 (docs/perf.md)."""
    lstm_model = {
        "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.pipeline.Pipeline": {
                    "steps": [
                        "gordo_tpu.ops.scalers.MinMaxScaler",
                        {
                            "gordo_tpu.models.estimator.LSTMAutoEncoder": {
                                "kind": "lstm_hourglass",
                                "lookback_window": 4,
                                "epochs": 2,
                                "batch_size": 64,
                            }
                        },
                    ]
                }
            }
        }
    }
    project = {
        "machines": [
            {
                "name": f"pf-lstm-{i}",
                "dataset": {
                    "type": "RandomDataset",
                    "tags": ["a", "b", "c", "d"],
                    "train_start_date": "2017-12-25T06:00:00Z",
                    "train_end_date": "2017-12-27T06:00:00Z",
                },
            }
            for i in range(2)
        ]
        + [
            {
                "name": "pf-smooth",
                "dataset": {
                    "type": "RandomDataset",
                    "tags": ["a", "b", "c", "d"],
                    "train_start_date": "2017-12-25T06:00:00Z",
                    "train_end_date": "2017-12-27T06:00:00Z",
                },
                "model": _SMOOTH_MODEL,
            }
        ],
        "globals": {"model": lstm_model},
    }
    out = str(tmp_path / "artifacts")
    cfg = NormalizedConfig(project, "pfproj")
    result = build_project(cfg.machines, out)
    assert not result.failed

    X = np.random.default_rng(5).standard_normal((2048, 4)).astype(
        np.float32
    )
    ref_coll = ModelCollection.from_directory(out, project="pfproj")
    ref_bulk = ref_coll.fleet_scorer.score_all(
        {name: X for name in ref_coll.entries}
    )
    ref_sub = ref_coll.fleet_scorer.score_all({"pf-lstm-0": X})

    monkeypatch.setenv("GORDO_SERVE_DTYPE", "bfloat16")
    bf_coll = ModelCollection.from_directory(out, project="pfproj")
    bf_bulk = bf_coll.fleet_scorer.score_all(
        {name: X for name in bf_coll.entries}
    )
    bf_sub = bf_coll.fleet_scorer.score_all({"pf-lstm-0": X})

    for name in ref_coll.entries:
        assert_parity(
            ref_coll.get(name).scorer.anomaly_arrays(X),
            bf_coll.get(name).scorer.anomaly_arrays(X),
            label=f"per-machine {name}: ",
        )
        assert_parity(
            ref_bulk[name], bf_bulk[name], label=f"bucket {name}: "
        )
    assert_parity(ref_sub["pf-lstm-0"], bf_sub["pf-lstm-0"],
                  label="subset: ")
