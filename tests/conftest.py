"""Test harness config.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding
(`("models", "data")` meshes, collectives) is exercised without TPU hardware
— the same simulation strategy the driver's `dryrun_multichip` uses.
"""

import os

# Must be set before jax backend initialization.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Deregister non-CPU PJRT plugins (e.g. the axon TPU tunnel) so backend
# discovery can't block on remote hardware during the test run.  Tests are
# hermetic CPU-only; TPU execution is covered by bench.py / the driver.
import jax._src.xla_bridge as _xb  # noqa: E402

# Only the tunnel-backed plugin is removed; the stock 'tpu' entry stays so
# platform names remain known to jax's lowering registries.
_xb._backend_factories.pop("axon", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _bound_xla_compile_state():
    """Clear jax's compiled-program caches after every test module.

    jax 0.9.0's XLA:CPU backend segfaults inside ``backend_compile_and_
    load`` when a fresh program compiles late in a long single-process
    run (~150+ tests of accumulated compile state; the same compile
    passes in isolation — reproduced repeatedly in this container, crash
    point moving with the suite's total compile pressure).  Dropping the
    caches per module bounds that state; modules that share program
    shapes pay one extra compile each, which is noise next to a crashed
    suite.  TPU is unaffected — this is purely a test-harness guard.
    """
    yield
    jax.clear_caches()


@pytest.fixture(scope="session")
def sine_tags():
    """Synthetic multi-tag sine matrix (the RandomDataProvider-style backbone
    of integration tests, per SURVEY.md §5)."""
    rng = np.random.default_rng(42)
    n, f, latents = 600, 6, 2
    t = np.arange(n)[:, None]
    phases = rng.uniform(0, 2 * np.pi, size=(1, latents))
    freqs = rng.uniform(0.01, 0.1, size=(1, latents))
    Z = np.sin(freqs * t + phases)  # shared latent signals
    mix = rng.uniform(-1, 1, size=(latents, f))
    X = Z @ mix + 0.05 * rng.standard_normal((n, f))
    return X.astype(np.float32)
