"""Project-scale fleet builds: YAML → bucketed fleet programs → per-machine
artifacts with cache parity (reference: builder tests against
RandomDataset + the provide_saved_model cache, SURVEY.md §5)."""

import numpy as np
import pytest
import yaml

from gordo_tpu import serializer
from gordo_tpu.builder import build_project
from gordo_tpu.parallel import fleet_mesh
from gordo_tpu.workflow import NormalizedConfig, load_machine_config


def _load_model(ref):
    """Load a model from a build-result artifact ref — a v2 pack ref
    (the library default now) or a v1 per-machine dir."""
    from gordo_tpu import artifacts

    if artifacts.is_pack_ref(ref):
        directory, name = artifacts.parse_ref(ref)
        return artifacts.PackStore(directory).load_model(name)
    return serializer.load(ref)


def _load_metadata(ref):
    from gordo_tpu import artifacts

    if artifacts.is_pack_ref(ref):
        directory, name = artifacts.parse_ref(ref)
        return artifacts.PackStore(directory).load_metadata(name)
    return serializer.load_metadata(ref)

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow


def _project_yaml(n_machines=3, epochs=2):
    machines = "\n".join(
        f"""
  - name: machine-{i}
    dataset:
      type: RandomDataset
      tags: [tag-a, tag-b, tag-c]
      train_start_date: "2017-12-25T06:00:00Z"
      train_end_date: "2017-12-27T06:00:00Z"
"""
        for i in range(n_machines)
    )
    return f"""
machines:{machines}
globals:
  model:
    gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector:
      base_estimator:
        gordo_tpu.pipeline.Pipeline:
          steps:
            - gordo_tpu.ops.scalers.MinMaxScaler
            - gordo_tpu.models.estimator.AutoEncoder:
                kind: feedforward_hourglass
                epochs: {epochs}
                batch_size: 64
"""


class TestBuildProject:
    def test_fleet_build_produces_per_machine_artifacts(self, tmp_path):
        cfg = NormalizedConfig(load_machine_config(_project_yaml()), "proj")
        out = tmp_path / "models"
        reg = tmp_path / "registry"
        result = build_project(
            cfg.machines,
            str(out),
            model_register_dir=str(reg),
            mesh=fleet_mesh(),
        )
        assert sorted(result.artifacts) == [
            "machine-0",
            "machine-1",
            "machine-2",
        ]
        assert result.fleet_built and not result.single_built
        assert not result.failed

        for name, path in result.artifacts.items():
            model = _load_model(path)
            meta = _load_metadata(path)
            assert meta["name"] == name
            assert meta["model"]["fleet_built"] is True
            assert "cross_validation" in meta["model"]
            assert meta["dataset"]["tag_list"]
            # the loaded artifact scores end-to-end
            X = np.random.default_rng(0).standard_normal((50, 3)).astype(
                np.float32
            )
            frame = model.anomaly(X)
            assert np.isfinite(
                frame[("total-anomaly-score", "")].to_numpy()
            ).all()

    def test_second_run_hits_cache(self, tmp_path):
        cfg = NormalizedConfig(load_machine_config(_project_yaml(2)), "proj")
        out, reg = str(tmp_path / "m"), str(tmp_path / "r")
        first = build_project(cfg.machines, out, model_register_dir=reg)
        assert len(first.fleet_built) == 2
        second = build_project(cfg.machines, out, model_register_dir=reg)
        assert sorted(second.cached) == ["machine-0", "machine-1"]
        assert not second.fleet_built
        assert second.artifacts == first.artifacts

    def test_config_change_rebuilds(self, tmp_path):
        out, reg = str(tmp_path / "m"), str(tmp_path / "r")
        cfg1 = NormalizedConfig(load_machine_config(_project_yaml(1, epochs=2)))
        build_project(cfg1.machines, out, model_register_dir=reg)
        cfg2 = NormalizedConfig(load_machine_config(_project_yaml(1, epochs=3)))
        result = build_project(cfg2.machines, out, model_register_dir=reg)
        assert result.fleet_built == ["machine-0"]

    def test_non_fleetable_model_falls_back_to_single(self, tmp_path):
        raw = load_machine_config(_project_yaml(1))
        # a bare pipeline (no anomaly detector) is not fleet-expressible
        raw["globals"]["model"] = yaml.safe_load(
            """
gordo_tpu.pipeline.Pipeline:
  steps:
    - gordo_tpu.ops.scalers.MinMaxScaler
    - gordo_tpu.models.estimator.AutoEncoder:
        kind: feedforward_hourglass
        epochs: 2
"""
        )
        cfg = NormalizedConfig(raw)
        result = build_project(cfg.machines, str(tmp_path / "m"))
        assert result.single_built == ["machine-0"]
        model = serializer.load(result.artifacts["machine-0"])
        X = np.random.default_rng(0).standard_normal((40, 3)).astype(np.float32)
        assert model.predict(X).shape == (40, 3)

    def test_mixed_feature_counts_bucket_separately(self, tmp_path):
        raw = load_machine_config(_project_yaml(2))
        raw["machines"][1]["dataset"]["tags"] = ["a", "b", "c", "d", "e"]
        cfg = NormalizedConfig(raw)
        result = build_project(cfg.machines, str(tmp_path / "m"))
        assert len(result.fleet_built) == 2
        assert not result.failed


class TestStreamingMemoryBound:
    def test_peak_loaded_bounded_to_two_chunks(self, tmp_path):
        """VERDICT r3 missing #5: the build must never hold more than the
        training chunk plus the prefetching chunk in host memory."""
        cfg = NormalizedConfig(
            yaml.safe_load(_project_yaml(n_machines=12)), "streamproj"
        )
        result = build_project(
            cfg.machines, str(tmp_path / "out"), max_bucket_size=2,
            data_workers=4,
        )
        assert not result.failed
        assert len(result.artifacts) == 12
        assert result.peak_loaded <= 4  # 2 chunks of 2
        assert result.summary()["peak_loaded_machines"] == result.peak_loaded

    def test_width_mismatch_reroutes_to_single_builder(self, tmp_path, monkeypatch):
        """A provider returning different widths than the config promised
        must not poison the stacked bucket — the machine builds single."""
        from gordo_tpu.dataset import datasets as ds_mod

        cfg = NormalizedConfig(
            yaml.safe_load(_project_yaml(n_machines=3)), "mismatchproj"
        )
        machines = cfg.machines
        orig = ds_mod.RandomDataset.get_data
        call_count = {"n": 0}

        def dropping_get_data(self):
            # the 2nd load in the stream (machine-1) silently loses a column
            X, y = orig(self)
            call_count["n"] += 1
            if call_count["n"] == 2:
                return X.iloc[:, :2], y.iloc[:, :2]
            return X, y

        monkeypatch.setattr(ds_mod.RandomDataset, "get_data", dropping_get_data)
        result = build_project(
            machines, str(tmp_path / "out"), max_bucket_size=8,
            data_workers=1,  # deterministic load order for the counter
        )
        # every machine still produced an artifact; the mismatched one went
        # through the single builder
        assert len(result.artifacts) == 3, result.failed
        assert len(result.single_built) == 1
        assert len(result.fleet_built) == 2


def test_2k_machine_build_stays_memory_bounded(tmp_path):
    """VERDICT r3 missing #5 scale proof: a 2000-machine project builds
    with at most two 128-machine chunks of arrays resident (~34 MB of
    float32 at these shapes — vs ~470 MB load-everything), and every
    machine still gets its artifact."""
    from gordo_tpu.workflow.config import Machine

    machines = [
        Machine.from_config(
            {
                "name": f"mem-{i:04d}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": [f"t-{i}-{j}" for j in range(3)],
                },
            }
        )
        for i in range(2000)
    ]
    result = build_project(
        machines, str(tmp_path / "out"), max_bucket_size=128, data_workers=8
    )
    assert not result.failed
    assert len(result.artifacts) == 2000
    assert result.peak_loaded <= 256


def test_build_project_over_mesh_end_to_end(tmp_path):
    """``build_project`` over the 8-virtual-device mesh, end-to-end: a
    RAGGED feedforward bucket (3 distinct row counts), an LSTM bucket, a
    cache re-run, and loadable artifacts that score.  Multi-chip evidence
    for the compile-heavy LSTM fleet path (r4 verdict item 3)."""
    from gordo_tpu.workflow.config import Machine
    from tests.lstm_detectors import BATCH, LOOKBACK, N_TAGS

    def ff_machine(i, hours):
        day = 25 + (6 + hours) // 24
        hh = (6 + hours) % 24
        return Machine.from_config({
            "name": f"mesh-ff-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tag_list": ["a", "b", "c"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": f"2017-12-{day}T{hh:02d}:10:00Z",
            },
        })

    def lstm_machine(i):
        return Machine.from_config({
            "name": f"mesh-lstm-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tag_list": [f"lt-{j}" for j in range(N_TAGS)],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-26T08:00:00Z",
            },
            "model": {
                "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                    "base_estimator": {
                        "gordo_tpu.pipeline.Pipeline": {
                            "steps": [
                                "gordo_tpu.ops.scalers.MinMaxScaler",
                                {
                                    "gordo_tpu.models.estimator"
                                    ".LSTMAutoEncoder": {
                                        "lookback_window": LOOKBACK,
                                        "epochs": 1,
                                        "batch_size": BATCH,
                                    }
                                },
                            ]
                        }
                    }
                }
            },
        })

    machines = [ff_machine(i, h) for i, h in enumerate((20, 21, 22))] + [
        lstm_machine(i) for i in range(2)
    ]
    mesh = fleet_mesh()
    assert mesh.devices.size == 8  # conftest pins 8 virtual CPU devices
    out, reg = tmp_path / "models", tmp_path / "registry"
    result = build_project(
        machines, str(out), model_register_dir=str(reg), mesh=mesh
    )
    assert not result.failed
    assert len(result.artifacts) == 5
    assert sorted(result.fleet_built) == sorted(m.name for m in machines)

    # artifacts load and score
    for name in ("mesh-ff-0", "mesh-lstm-0"):
        det = _load_model(result.artifacts[name])
        n_feat = 3
        X = np.random.default_rng(0).standard_normal((40, n_feat)).astype(
            np.float32
        )
        scores = det.anomaly(X)
        assert np.all(np.isfinite(det.feature_thresholds_))
        assert len(scores["total-anomaly-score"]) > 0

    # identical re-run over the same register: every machine a cache hit
    rerun = build_project(
        machines, str(tmp_path / "m2"), model_register_dir=str(reg),
        mesh=mesh,
    )
    assert not rerun.failed
    assert sorted(rerun.cached) == sorted(m.name for m in machines)


def test_align_lengths_collapses_ragged_row_counts(tmp_path, monkeypatch):
    """Ragged train windows compile one XLA program per DISTINCT row count
    (~14s each, measured); ``align_lengths`` truncates to a shared multiple
    (newest rows kept) so one program serves the whole bucket."""
    from gordo_tpu.builder import fleet_build as fb
    from gordo_tpu.workflow.config import Machine

    def machine(i, hours):
        day = 25 + (6 + hours) // 24
        hh = (6 + hours) % 24
        return Machine.from_config({
            "name": f"rag-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tag_list": ["a", "b", "c"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": f"2017-12-{day}T{hh:02d}:10:00Z",
            },
        })

    # 3 machines with 3 distinct row counts (10min resolution)
    machines = [machine(i, h) for i, h in enumerate((20, 21, 22))]

    seen_lengths = []
    # the drive loop enters the builder through the async dispatch seam
    orig_dispatch = fb.FleetDiffBuilder.dispatch

    def recording_dispatch(self, Xs, ys=None, **kwargs):
        seen_lengths.append(sorted({x.shape[0] for x in Xs}))
        return orig_dispatch(self, Xs, ys, **kwargs)

    monkeypatch.setattr(fb.FleetDiffBuilder, "dispatch", recording_dispatch)

    result = build_project(
        machines, str(tmp_path / "aligned"), align_lengths=60,
    )
    assert not result.failed
    assert len(result.fleet_built) == 3
    # all three truncated down to the shared multiple of 60 -> ONE length
    assert seen_lengths and all(len(s) == 1 for s in seen_lengths)
    assert seen_lengths[0][0] % 60 == 0

    seen_lengths.clear()
    result = build_project(machines, str(tmp_path / "ragged"))
    assert not result.failed
    # without alignment the ragged lengths all survive (exact parity mode)
    assert sorted(x for s in seen_lengths for x in s) == [122, 128, 134]


def test_pad_lengths_keeps_rows_and_collapses_programs(tmp_path, monkeypatch):
    """pad_lengths: ragged machines collapse into one padded group with NO
    rows dropped; artifacts record the mode; mutually exclusive with
    align_lengths; cache identity differs from an exact build."""
    from gordo_tpu.builder import fleet_build as fb
    from gordo_tpu.workflow.config import Machine

    def machine(i, hours):
        day = 25 + (6 + hours) // 24
        hh = (6 + hours) % 24
        return Machine.from_config({
            "name": f"pad-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tag_list": ["a", "b", "c"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": f"2017-12-{day}T{hh:02d}:10:00Z",
            },
        })

    machines = [machine(i, h) for i, h in enumerate((20, 21, 22))]

    with pytest.raises(ValueError, match="mutually exclusive"):
        build_project(
            machines, str(tmp_path / "x"), align_lengths=60, pad_lengths=60,
        )

    # pad=72: rows 122/128/134 all round up to 144, and every machine
    # still reaches the last CV test block (starts at row 108) — one group
    pad = 72

    seen = []
    # every group — padded or exact — launches through _dispatch_group
    orig = fb.FleetDiffBuilder._dispatch_group

    def recording(self, X, y, lens=None, warm=None):
        seen.append((X.shape[1], None if lens is None else list(lens)))
        return orig(self, X, y, lens=lens, warm=warm)

    monkeypatch.setattr(fb.FleetDiffBuilder, "_dispatch_group", recording)

    reg = tmp_path / "reg"
    result = build_project(
        machines, str(tmp_path / "padded"), model_register_dir=str(reg),
        pad_lengths=pad,
    )
    assert not result.failed and len(result.fleet_built) == 3
    # one padded group: rows 122/128/134 all pad up to 144
    assert len(seen) == 1 and seen[0][0] == 144
    assert sorted(seen[0][1]) == [122, 128, 134]

    meta = _load_metadata(result.artifacts["pad-0"])
    assert meta["model"]["pad_lengths"] == pad
    assert meta["model"]["rows_trained"] == 122

    # an exact re-run over the same register must MISS (different identity)
    seen.clear()
    rerun = build_project(
        machines, str(tmp_path / "exact"), model_register_dir=str(reg),
    )
    assert not rerun.failed and rerun.cached == []
    assert len(seen) == 3  # exact mode: one program per distinct length

    # identical padded re-run: every machine is a cache hit
    seen.clear()
    again = build_project(
        machines, str(tmp_path / "padded2"), model_register_dir=str(reg),
        pad_lengths=pad,
    )
    assert sorted(again.cached) == ["pad-0", "pad-1", "pad-2"]
    assert seen == []


def test_align_lengths_changes_cache_identity(tmp_path):
    """An artifact built with alignment must not satisfy an exact-parity
    build's cache lookup (and vice versa) — alignment changes what data
    trained, so it is part of the cache key."""
    from gordo_tpu.workflow.config import Machine

    machines = [Machine.from_config({
        "name": "ck-0",
        "dataset": {
            "type": "RandomDataset",
            "tag_list": ["a", "b", "c"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-26T03:10:00Z",
        },
    })]
    out, reg = str(tmp_path / "m"), str(tmp_path / "r")
    first = build_project(
        machines, out, model_register_dir=reg, align_lengths=60,
    )
    assert first.fleet_built == ["ck-0"]
    meta = _load_metadata(first.artifacts["ck-0"])
    assert meta["model"]["align_lengths"] == 60
    assert meta["model"]["rows_trained"] % 60 == 0

    # same register dir, no alignment: MISS (rebuild), not a stale hit
    second = build_project(machines, out, model_register_dir=reg)
    assert second.fleet_built == ["ck-0"] and not second.cached
    meta2 = _load_metadata(second.artifacts["ck-0"])
    assert "align_lengths" not in meta2["model"]

    # aligned again: the aligned registry entry points at the dir the
    # unaligned rerun overwrote; the artifact's cache_key stamp exposes
    # that -> miss and rebuild, never a silent wrong-artifact hit
    third = build_project(
        machines, out, model_register_dir=reg, align_lengths=60,
    )
    assert third.fleet_built == ["ck-0"] and not third.cached
    assert _load_metadata(
        third.artifacts["ck-0"]
    )["model"]["align_lengths"] == 60

    # an identical aligned rerun is now a genuine hit
    fourth = build_project(
        machines, out, model_register_dir=reg, align_lengths=60,
    )
    assert fourth.cached == ["ck-0"]


def test_estimate_ragged_compile_seconds_counts_filtered_machines():
    """Config-level bill: row_filter machines each count as a distinct
    length; same-window unfiltered machines share one."""
    from gordo_tpu.builder.fleet_build import estimate_ragged_compile_seconds
    from gordo_tpu.workflow.config import Machine
    from gordo_tpu.workflow.generator import COMPILE_SECONDS_PER_LENGTH

    def machine(i, row_filter=None):
        ds = {
            "type": "RandomDataset",
            "tag_list": ["a", "b", "c"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-26T06:00:00Z",
        }
        if row_filter:
            ds["row_filter"] = row_filter
        return Machine.from_config({"name": f"est-{i}", "dataset": ds})

    uniform = [machine(i) for i in range(5)]
    assert estimate_ragged_compile_seconds(uniform) == 0.0
    filtered = uniform + [
        machine(10 + i, row_filter=f"`a` > {i}") for i in range(4)
    ]
    # 1 shared window + 4 filtered = 5 distinct lengths, floor of 1
    assert estimate_ragged_compile_seconds(filtered) == pytest.approx(
        4 * COMPILE_SECONDS_PER_LENGTH
    )


class TestAutoPad:
    """VERDICT weak #4: raggedness is the production norm, so the builder
    selects pad_lengths itself when the predicted compile bill explodes."""

    @staticmethod
    def _ragged_machines(prefix="ap"):
        from gordo_tpu.workflow.config import Machine

        def machine(i, hours):
            day = 25 + (6 + hours) // 24
            hh = (6 + hours) % 24
            return Machine.from_config({
                "name": f"{prefix}-{i}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": ["a", "b", "c"],
                    "train_start_date": "2017-12-25T06:00:00Z",
                    "train_end_date": f"2017-12-{day}T{hh:02d}:10:00Z",
                },
            })

        # 3 distinct row counts (10min resolution): 122 / 128 / 134
        return [machine(i, h) for i, h in enumerate((20, 21, 22))]

    def test_auto_pad_triggers_over_budget_and_is_cache_stable(self, tmp_path):
        from gordo_tpu.builder.fleet_build import DEFAULT_AUTO_PAD_LENGTHS

        machines = self._ragged_machines()
        reg = str(tmp_path / "reg")
        result = build_project(
            machines, str(tmp_path / "m1"), model_register_dir=reg,
            auto_pad_budget_seconds=1.0,  # 3 distinct lengths >> 1s bill
        )
        assert not result.failed
        assert result.auto_pad == DEFAULT_AUTO_PAD_LENGTHS
        assert result.summary()["auto_pad_lengths"] == DEFAULT_AUTO_PAD_LENGTHS
        # the decision is deterministic, so a re-run computes the same
        # cache keys and hits every machine
        rerun = build_project(
            machines, str(tmp_path / "m2"), model_register_dir=reg,
            auto_pad_budget_seconds=1.0,
        )
        assert sorted(rerun.cached) == [m.name for m in machines]
        assert rerun.auto_pad == DEFAULT_AUTO_PAD_LENGTHS

    def test_no_auto_pad_override_keeps_exact_mode(self, tmp_path, monkeypatch):
        from gordo_tpu.builder import fleet_build as fb

        machines = self._ragged_machines(prefix="np")
        seen_lengths = []
        orig_dispatch = fb.FleetDiffBuilder.dispatch

        def recording_dispatch(self, Xs, ys=None, **kwargs):
            seen_lengths.append(sorted({x.shape[0] for x in Xs}))
            return orig_dispatch(self, Xs, ys, **kwargs)

        monkeypatch.setattr(fb.FleetDiffBuilder, "dispatch", recording_dispatch)
        result = build_project(
            machines, str(tmp_path / "m"), auto_pad=False,
            auto_pad_budget_seconds=1.0,
        )
        assert not result.failed
        assert result.auto_pad is None
        # exact-parity mode: all three ragged lengths survive
        assert sorted(x for s in seen_lengths for x in s) == [122, 128, 134]

    def test_under_budget_stays_exact(self, tmp_path):
        """The default budget is bigger than a 3-length project's bill —
        small ragged dev projects keep exact parity without flags."""
        machines = self._ragged_machines(prefix="ub")
        result = build_project(machines, str(tmp_path / "m"))
        assert not result.failed
        assert result.auto_pad is None

    def test_explicit_strategy_preempts_auto_pad(self, tmp_path):
        machines = self._ragged_machines(prefix="ex")
        result = build_project(
            machines, str(tmp_path / "m"), align_lengths=60,
            auto_pad_budget_seconds=1.0,
        )
        assert not result.failed
        assert result.auto_pad is None
        meta = _load_metadata(result.artifacts["ex-0"])
        assert meta["model"]["align_lengths"] == 60
