"""Factory registry + hourglass math tests (reference test strategy:
layer counts/dims vs config, registry lookups)."""

import jax
import jax.numpy as jnp
import pytest

from gordo_tpu.models.factories import (
    feedforward_hourglass,
    feedforward_model,
    feedforward_symmetric,
    hourglass_calc_dims,
    lstm_hourglass,
    lstm_model,
)
from gordo_tpu.registry import FACTORY_REGISTRY, lookup_factory


def test_hourglass_dims_taper():
    dims = hourglass_calc_dims(0.5, 3, 12)
    assert dims == [10, 8, 6]
    assert hourglass_calc_dims(0.0, 2, 4)[-1] == 1  # floor at 1
    assert hourglass_calc_dims(1.0, 3, 10) == [10, 10, 10]


def test_hourglass_dims_validation():
    with pytest.raises(ValueError):
        hourglass_calc_dims(1.5, 3, 10)
    with pytest.raises(ValueError):
        hourglass_calc_dims(0.5, 0, 10)


def test_registry_contains_all_factories():
    assert "feedforward_hourglass" in FACTORY_REGISTRY["AutoEncoder"]
    assert "feedforward_model" in FACTORY_REGISTRY["AutoEncoder"]
    assert "feedforward_symmetric" in FACTORY_REGISTRY["AutoEncoder"]
    assert "lstm_hourglass" in FACTORY_REGISTRY["LSTMAutoEncoder"]
    assert lookup_factory("AutoEncoder", "feedforward_hourglass") is feedforward_hourglass


def test_lookup_unknown_kind_raises_with_available():
    with pytest.raises(ValueError, match="feedforward_hourglass"):
        lookup_factory("AutoEncoder", "not_a_factory")


def test_feedforward_module_shapes():
    mod = feedforward_model(6, 6, encoding_dim=(8, 4), decoding_dim=(4, 8))
    params = mod.init(jax.random.PRNGKey(0), jnp.zeros((2, 6)))["params"]
    layer_names = sorted(params.keys())
    assert layer_names == ["dense_0", "dense_1", "dense_2", "dense_3", "out"]
    out = mod.apply({"params": params}, jnp.zeros((5, 6)))
    assert out.shape == (5, 6)
    assert out.dtype == jnp.float32


def test_feedforward_hourglass_layer_dims():
    mod = feedforward_hourglass(12, encoding_layers=3, compression_factor=0.5)
    params = mod.init(jax.random.PRNGKey(0), jnp.zeros((1, 12)))["params"]
    # encoder 10,8,6 then decoder 6,8,10 then out 12
    dims = [params[f"dense_{i}"]["kernel"].shape[1] for i in range(6)]
    assert dims == [10, 8, 6, 6, 8, 10]
    assert params["out"]["kernel"].shape == (10, 12)


def test_symmetric_rejects_empty_dims():
    with pytest.raises(ValueError):
        feedforward_symmetric(4, dims=())


def test_lstm_module_shapes():
    mod = lstm_model(5, 5, lookback_window=8, encoding_dim=(16,), decoding_dim=(16,))
    x = jnp.zeros((3, 8, 5))
    params = mod.init(jax.random.PRNGKey(0), x)["params"]
    out = mod.apply({"params": params}, x)
    assert out.shape == (3, 5)


def test_lstm_hourglass_builds():
    mod = lstm_hourglass(6, lookback_window=4, encoding_layers=2, compression_factor=0.5)
    x = jnp.zeros((2, 4, 6))
    params = mod.init(jax.random.PRNGKey(0), x)["params"]
    assert mod.apply({"params": params}, x).shape == (2, 6)


@pytest.mark.parametrize("dtype_name", ["float32", "bfloat16"])
def test_fused_lstm_matches_flax_cell(dtype_name):
    """The fused scan (input projection hoisted out of the recurrence) must
    stay interchangeable with ``nn.RNN(OptimizedLSTMCell)``: identical param
    tree, BIT-identical init (path-derived RNG), and outputs equal to fp
    rounding — old artifacts must keep loading and scoring the same."""
    import flax.linen as nn
    import numpy as np

    cd = jnp.float32 if dtype_name == "float32" else jnp.bfloat16
    dims, funcs, n_feat, lookback = (9, 7), ("tanh", "tanh"), 5, 6

    class FlaxReference(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.astype(cd)
            for i, d in enumerate(dims):
                x = nn.RNN(
                    nn.OptimizedLSTMCell(d, dtype=cd), name=f"lstm_{i}"
                )(x)
                x = jnp.tanh(x)
            return nn.Dense(n_feat, dtype=jnp.float32, name="out")(
                x[:, -1, :].astype(jnp.float32)
            )

    fused = lstm_model(
        n_feat, encoding_dim=dims[:1], decoding_dim=dims[1:],
        encoding_func=["tanh"], decoding_func=["tanh"],
        compute_dtype=dtype_name,
    )
    ref = FlaxReference()
    rng = jax.random.PRNGKey(3)
    x = jax.random.normal(jax.random.PRNGKey(4), (4, lookback, n_feat))
    p_ref = ref.init(rng, x)["params"]
    p_fused = fused.init(rng, x)["params"]
    assert jax.tree.structure(p_ref) == jax.tree.structure(p_fused)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_fused)):
        assert np.array_equal(a, b)  # bit-identical init

    y_ref = ref.apply({"params": p_ref}, x).astype(jnp.float32)
    y_fused = fused.apply({"params": p_fused}, x).astype(jnp.float32)
    tol = 1e-6 if dtype_name == "float32" else 2e-2
    np.testing.assert_allclose(y_ref, y_fused, atol=tol, rtol=tol)


def test_unknown_activation_raises():
    with pytest.raises(ValueError, match="Unknown activation"):
        mod = feedforward_model(4, encoding_dim=(4,), encoding_func=["nope"], decoding_dim=(4,))
        mod.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))


def test_compute_dtype_auto_resolves_by_backend():
    """"auto" is float32 on CPU (bf16 is emulated ~3x slower there) and
    bfloat16 only on TPU; explicit names always win."""
    from gordo_tpu.models.factories.feedforward import resolve_compute_dtype

    assert resolve_compute_dtype("auto") == jnp.dtype(
        jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    )
    assert resolve_compute_dtype("bfloat16") == jnp.dtype(jnp.bfloat16)
    assert resolve_compute_dtype("float32") == jnp.dtype(jnp.float32)


def test_mixed_precision_modules_keep_f32_params_and_outputs():
    """Explicit bfloat16 compute: params and outputs stay float32 (mixed
    precision — bf16 is the matmul dtype, not the state dtype)."""
    rng = jax.random.PRNGKey(1)
    ff = feedforward_model(
        6, encoding_dim=(8,), decoding_dim=(8,), compute_dtype="bfloat16"
    )
    x = jax.random.normal(rng, (4, 6))
    params = ff.init(rng, x)["params"]
    assert all(
        p.dtype == jnp.float32 for p in jax.tree.leaves(params)
    )
    out = ff.apply({"params": params}, x)
    assert out.dtype == jnp.float32 and bool(jnp.isfinite(out).all())

    lstm = lstm_model(
        5, lookback_window=4, encoding_dim=(8,), decoding_dim=(8,),
        compute_dtype="bfloat16",
    )
    xw = jax.random.normal(rng, (3, 4, 5))
    lparams = lstm.init(rng, xw)["params"]
    assert all(
        p.dtype == jnp.float32 for p in jax.tree.leaves(lparams)
    )
    lout = lstm.apply({"params": lparams}, xw)
    assert lout.dtype == jnp.float32 and bool(jnp.isfinite(lout).all())


def test_legacy_pickles_without_compute_dtype_stay_float32():
    """Artifacts pickled before the compute_dtype field existed unpickle
    WITHOUT the attribute and must fall back to the float32 class default
    — bf16 here would silently change the numerics those artifacts'
    anomaly thresholds were calibrated with."""
    from gordo_tpu.models.factories.feedforward import FeedForwardAutoEncoder
    from gordo_tpu.models.factories.lstm import LSTMAutoEncoderModule

    for mod in (
        feedforward_model(4, encoding_dim=(4,), decoding_dim=(4,)),
        lstm_model(4, lookback_window=2, encoding_dim=(4,), decoding_dim=(4,)),
    ):
        # simulate a pre-field pickle: the instance attribute is absent,
        # so lookup falls through to the class default
        object.__delattr__(mod, "compute_dtype")
        assert mod.compute_dtype == jnp.float32
    assert FeedForwardAutoEncoder.compute_dtype == jnp.float32
    assert LSTMAutoEncoderModule.compute_dtype == jnp.float32
