"""Drift-driven incremental refresh (ISSUE 13).

Fast lane: the pure pieces — hysteresis/cooldown selection, warm-param
resolution off a written pack, the shared rollup reader, the workflow
CronJob emission + refusals, the refresh-plane lint gate, and a
refresh_once cycle against stubbed health/build seams.

Slow lane (``TestRefreshAcceptance``): the end-to-end pin — build a
fleet, shift live inputs to a subset, let the refresh loop rebuild
exactly those machines warm, assert the generation flips, a live
serving collection delta-reloads only the touched pack, and the drift
signal returns to ok without any restart.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from gordo_tpu import artifacts, telemetry
from gordo_tpu.refresh import DriftSelector, RefreshConfig, refresh_once
from gordo_tpu.refresh import loop as refresh_loop
from gordo_tpu.telemetry import fleet_health as fh


def _doc(statuses):
    """A status-only health doc — enough for DriftSelector.observe."""
    return {
        "gordo-fleet-health": 1,
        "machines": {n: {"status": s} for n, s in statuses.items()},
    }


def _sketch_doc(shift, n=2000):
    rng = np.random.default_rng(42)
    return fh.sketch_from_scores(rng.lognormal(shift, 1, n), ts=0.0).to_doc()


def _health_doc(statuses):
    """A health doc with REAL score sketches behind each status —
    ``merge_health_docs`` (what ``read_rollups`` applies) recomputes
    drift/status from the sketches, so rollup-file tests need the
    distributions, not just labels."""
    baseline = _sketch_doc(0.0, n=4000)
    machines = {}
    for name, status in statuses.items():
        live = _sketch_doc(3.0 if status == "drifting" else 0.0)
        machines[name] = {"baseline": baseline, "live": live}
    return {"gordo-fleet-health": 1, "machines": machines}


# ---------------------------------------------------------------------------
# selection: hysteresis + cooldown
# ---------------------------------------------------------------------------

class TestDriftSelector:
    def test_hysteresis_requires_consecutive_observations(self):
        sel = DriftSelector(hysteresis=2, cooldown_seconds=0)
        assert sel.observe(_doc({"m-a": "drifting", "m-b": "ok"}), 0.0) == []
        assert sel.observe(_doc({"m-a": "drifting", "m-b": "ok"}), 1.0) == [
            "m-a"
        ]

    def test_non_drifting_observation_resets_the_streak(self):
        sel = DriftSelector(hysteresis=2, cooldown_seconds=0)
        sel.observe(_doc({"m-a": "drifting"}), 0.0)
        sel.observe(_doc({"m-a": "ok"}), 1.0)  # one quiet window resets
        assert sel.observe(_doc({"m-a": "drifting"}), 2.0) == []
        assert sel.observe(_doc({"m-a": "drifting"}), 3.0) == ["m-a"]

    def test_absent_machine_keeps_its_streak(self):
        """A silent shard is not evidence the drift cleared."""
        sel = DriftSelector(hysteresis=2, cooldown_seconds=0)
        sel.observe(_doc({"m-a": "drifting"}), 0.0)
        assert sel.observe(_doc({"m-b": "ok"}), 1.0) == []
        assert sel.observe(_doc({"m-a": "drifting"}), 2.0) == ["m-a"]

    def test_cooldown_suppresses_rebuilds_until_it_expires(self):
        sel = DriftSelector(hysteresis=1, cooldown_seconds=100)
        assert sel.observe(_doc({"m-a": "drifting"}), 0.0) == ["m-a"]
        sel.mark_rebuilt(["m-a"], 0.0)
        assert sel.observe(_doc({"m-a": "drifting"}), 50.0) == []
        assert sel.observe(_doc({"m-a": "drifting"}), 150.0) == ["m-a"]

    def test_state_round_trips_through_the_state_file(self, tmp_path):
        path = str(tmp_path / "state.json")
        sel = DriftSelector(hysteresis=3, cooldown_seconds=0)
        sel.observe(_doc({"m-a": "drifting"}), 0.0)
        sel.observe(_doc({"m-a": "drifting"}), 1.0)
        sel.save(path)
        # the next --once invocation resumes the streak at 2/3
        again = DriftSelector.load(path, hysteresis=3, cooldown_seconds=0)
        assert again.observe(_doc({"m-a": "drifting"}), 2.0) == ["m-a"]

    def test_corrupt_state_file_starts_fresh(self, tmp_path):
        path = tmp_path / "state.json"
        path.write_text("{torn")
        sel = DriftSelector.load(str(path), hysteresis=1, cooldown_seconds=0)
        assert sel.observe(_doc({"m-a": "drifting"}), 0.0) == ["m-a"]

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv(refresh_loop.ENV_HYSTERESIS, "5")
        monkeypatch.setenv(refresh_loop.ENV_COOLDOWN_SECONDS, "12.5")
        sel = DriftSelector()
        assert sel.hysteresis == 5
        assert sel.cooldown_seconds == 12.5


# ---------------------------------------------------------------------------
# warm-start material: previous-generation params off the pack store
# ---------------------------------------------------------------------------

class _FakeEstimator:
    def __init__(self, seed, with_history=True):
        rng = np.random.default_rng(seed)
        self.params_ = {
            "dense": {
                "w": rng.standard_normal((4, 3)).astype(np.float32),
                "b": rng.standard_normal(3).astype(np.float32),
            }
        }
        if with_history:
            self.history_ = np.asarray(
                [0.9, 0.5, 0.25 + seed], np.float32
            )


class _FakeDetector:
    def __init__(self, seed, with_history=True):
        self.base_estimator = _FakeEstimator(seed, with_history)


class TestWarmParamResolution:
    def test_resolves_params_and_previous_loss_from_the_pack(self, tmp_path):
        from gordo_tpu.builder.fleet_build import _resolve_warm_params

        names = ["wm-0", "wm-1"]
        artifacts.write_pack(
            str(tmp_path), names, [_FakeDetector(0), _FakeDetector(1)],
        )
        resolved = _resolve_warm_params(str(tmp_path), names + ["wm-miss"])
        assert sorted(resolved) == names  # unknown machine simply absent
        params, prev_loss = resolved["wm-1"]
        assert prev_loss == pytest.approx(1.25)
        np.testing.assert_array_equal(
            params["dense"]["w"],
            _FakeDetector(1).base_estimator.params_["dense"]["w"],
        )

    def test_no_store_resolves_empty(self, tmp_path):
        from gordo_tpu.builder.fleet_build import _resolve_warm_params

        assert _resolve_warm_params(str(tmp_path), ["wm-0"]) == {}

    def test_missing_history_resolves_none_loss(self, tmp_path):
        from gordo_tpu.builder.fleet_build import _resolve_warm_params

        artifacts.write_pack(
            str(tmp_path), ["wm-0"], [_FakeDetector(0, with_history=False)],
        )
        _, prev_loss = _resolve_warm_params(str(tmp_path), ["wm-0"])["wm-0"]
        assert prev_loss is None

    def test_warm_epoch_budget_and_env_override(self, monkeypatch):
        from gordo_tpu.builder.fleet_build import _warm_epochs
        from gordo_tpu.parallel.fleet import TrainConfig

        assert _warm_epochs(TrainConfig(epochs=8)) == 2  # 0.25 default
        monkeypatch.setenv("GORDO_REFRESH_EPOCH_FRACTION", "0.5")
        assert _warm_epochs(TrainConfig(epochs=8)) == 4
        monkeypatch.setenv("GORDO_REFRESH_EPOCH_FRACTION", "0.0")
        assert _warm_epochs(TrainConfig(epochs=8)) == 1  # never below 1

    def test_mismatched_leaf_signature_is_a_loud_error(self):
        from gordo_tpu.parallel.anomaly import _stack_warm_params

        good = {"w": np.zeros((4, 3), np.float32)}
        bad = {"w": np.zeros((4, 2), np.float32)}  # config changed
        with pytest.raises(ValueError, match="leaf signature"):
            _stack_warm_params([good, bad], 2)


# ---------------------------------------------------------------------------
# the shared rollup reader
# ---------------------------------------------------------------------------

class TestReadRollups:
    def test_empty_dir_reads_none(self, tmp_path):
        assert telemetry.read_rollups(str(tmp_path)) is None

    def test_reads_and_merges_rollups(self, tmp_path):
        d = str(tmp_path)
        fh.write_rollup(d, _health_doc({"rr-a": "drifting"}))
        doc = telemetry.read_rollups(d)
        assert doc["machines"]["rr-a"]["status"] == "drifting"


# ---------------------------------------------------------------------------
# one refresh cycle against stubbed seams
# ---------------------------------------------------------------------------

class _FakeBuildResult:
    def __init__(self, built, failed=None):
        self.fleet_built = list(built)
        self.single_built = []
        self.warm_started = list(built)
        self.warm_fallbacks = {}
        self.failed = dict(failed or {})
        self.generation = 7


class _Machine:
    def __init__(self, name):
        self.name = name


class TestRefreshOnce:
    @pytest.fixture
    def cfg(self, tmp_path):
        return RefreshConfig(
            machines=[_Machine("m-a"), _Machine("m-b")],
            output_dir=str(tmp_path),
            hysteresis=2,
            cooldown_seconds=0,
        )

    def test_no_health_is_a_noop_cycle(self, cfg):
        assert refresh_once(cfg)["outcome"] == "no-health"

    def test_streaks_accumulate_across_once_invocations(
        self, cfg, monkeypatch
    ):
        """The CronJob face: two separate ``--once`` processes — the
        state file carries the streak, the second cycle rebuilds, and
        only the drifted machine is handed to the builder."""
        import gordo_tpu.builder as builder_mod

        fh.write_rollup(
            cfg.output_dir, _health_doc({"m-a": "drifting", "m-b": "ok"})
        )
        calls = []

        def fake_build(machines, output_dir, **kwargs):
            calls.append(([m.name for m in machines], kwargs))
            return _FakeBuildResult([m.name for m in machines])

        monkeypatch.setattr(builder_mod, "build_project", fake_build)

        first = refresh_once(cfg)
        assert first["outcome"] == "idle"
        assert first["drifting"] == ["m-a"]
        assert not calls

        second = refresh_once(cfg)  # fresh selector — loads the state file
        assert second["outcome"] == "rebuilt"
        assert second["rebuilt"] == ["m-a"]
        assert second["generation"] == 7
        assert calls == [(["m-a"], {
            "model_register_dir": None, "warm_start": True,
        })]
        # cooldown: an immediately-following cycle stays idle
        cfg2 = RefreshConfig(
            machines=cfg.machines, output_dir=cfg.output_dir,
            hysteresis=2, cooldown_seconds=3600,
        )
        refresh_once(cfg2)
        third = refresh_once(cfg2)
        assert third["outcome"] == "idle" and len(calls) == 1

    def test_build_failure_reports_failed_outcome(self, cfg, monkeypatch):
        import gordo_tpu.builder as builder_mod

        cfg = RefreshConfig(
            machines=cfg.machines, output_dir=cfg.output_dir,
            hysteresis=1, cooldown_seconds=0,
        )
        fh.write_rollup(cfg.output_dir, _health_doc({"m-a": "drifting"}))
        monkeypatch.setattr(
            builder_mod, "build_project",
            lambda machines, output_dir, **kw: _FakeBuildResult(
                [], failed={"m-a": "boom"}
            ),
        )
        summary = refresh_once(cfg)
        assert summary["outcome"] == "failed"
        assert summary["failed"] == {"m-a": "boom"}

    def test_unknown_drifting_machine_is_reported_not_built(
        self, cfg, monkeypatch
    ):
        cfg = RefreshConfig(
            machines=[_Machine("m-a")], output_dir=cfg.output_dir,
            hysteresis=1, cooldown_seconds=0,
        )
        fh.write_rollup(cfg.output_dir,
                        _health_doc({"m-elsewhere": "drifting"}))
        summary = refresh_once(cfg)
        assert summary["outcome"] == "idle"
        assert summary["unknown"] == ["m-elsewhere"]


# ---------------------------------------------------------------------------
# CLI face
# ---------------------------------------------------------------------------

_PROJECT_YAML = """
machines:
  - name: cli-m-a
    dataset:
      type: RandomDataset
      tags: [t1, t2, t3]
      train_start_date: "2017-12-25T06:00:00Z"
      train_end_date: "2017-12-26T06:00:00Z"
"""


class TestRefreshCli:
    def test_once_with_no_health_exits_clean(self, tmp_path):
        from click.testing import CliRunner

        from gordo_tpu.cli.cli import gordo

        result = CliRunner().invoke(gordo, [
            "refresh", "--machine-config", _PROJECT_YAML,
            "--output-dir", str(tmp_path), "--once",
        ])
        assert result.exit_code == 0, result.output
        summary = json.loads(result.output.strip().splitlines()[-1])
        assert summary["outcome"] == "no-health"


# ---------------------------------------------------------------------------
# workflow CronJob emission
# ---------------------------------------------------------------------------

class TestRefreshCron:
    def _generate(self, schedule):
        from gordo_tpu.workflow import (
            NormalizedConfig,
            generate_workflow,
            load_machine_config,
        )

        config = NormalizedConfig(
            load_machine_config(_PROJECT_YAML), "cronproj"
        )
        return generate_workflow(config, refresh_cron=schedule)

    def test_cronjob_mirrors_the_builder_wiring(self):
        docs = self._generate("*/30 * * * *")
        jobs = [d for d in docs if d["kind"] == "CronJob"]
        assert len(jobs) == 1
        cj = jobs[0]
        assert cj["spec"]["schedule"] == "*/30 * * * *"
        assert cj["spec"]["concurrencyPolicy"] == "Forbid"
        pod = cj["spec"]["jobTemplate"]["spec"]["template"]["spec"]
        container = pod["containers"][0]
        assert container["command"] == ["gordo", "refresh"]
        assert "--once" in container["args"]
        volumes = {v["name"] for v in pod["volumes"]}
        assert {"models", "project-config", "compile-cache"} <= volumes
        env = {e["name"] for e in container["env"]}
        assert {"PROJECT_NAME", "GORDO_COMPILE_CACHE_DIR",
                "GORDO_REFRESH_HYSTERESIS"} <= env

    def test_malformed_schedule_is_refused(self):
        with pytest.raises(ValueError, match="5-field cron"):
            self._generate("hourly")
        with pytest.raises(ValueError, match=r"\[0-9\*/,-\]"):
            self._generate("* * * * mon")

    def test_builder_without_models_volume_is_refused(self):
        from gordo_tpu.workflow.generator import _refresh_cronjob

        stripped = {
            "spec": {"template": {"spec": {
                "containers": [{"name": "b", "env": []}],
                "volumes": [{"name": "project-config"}],
            }}}
        }
        with pytest.raises(ValueError, match="models"):
            _refresh_cronjob("p", "img", "0 * * * *", stripped)


# ---------------------------------------------------------------------------
# the plane-boundary lint gate
# ---------------------------------------------------------------------------

class TestRefreshLintGate:
    @staticmethod
    def _lint(path):
        spec = importlib.util.spec_from_file_location(
            "gordo_lint", os.path.join(
                os.path.dirname(os.path.dirname(__file__)),
                "scripts", "lint.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.lint_file(path)

    def test_server_internal_imports_rejected_in_refresh_plane(
        self, tmp_path
    ):
        bad = tmp_path / "gordo_tpu" / "refresh" / "thing.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "from gordo_tpu.serve.scorer import Scorer\n"
            "from gordo_tpu import watchman\n"
            "Scorer, watchman\n"
        )
        msgs = [f[2] for f in self._lint(str(bad))]
        assert sum("refresh plane" in m for m in msgs) == 2

    def test_refresh_plane_is_clean_under_the_gate(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in (
            os.path.join("gordo_tpu", "refresh", "loop.py"),
            os.path.join("gordo_tpu", "refresh", "__init__.py"),
        ):
            assert self._lint(os.path.join(repo, rel)) == [], rel


# ---------------------------------------------------------------------------
# end-to-end acceptance (slow lane — CI test-full job)
# ---------------------------------------------------------------------------

def _acceptance_yaml():
    machines = "\n".join(
        f"""
  - name: rf-{i}
    dataset:
      type: RandomDataset
      tags: [rf{i}-a, rf{i}-b, rf{i}-c]
      train_start_date: "2017-12-25T06:00:00Z"
      train_end_date: "2017-12-27T06:00:00Z"
"""
        for i in range(4)
    )
    return f"""
machines:{machines}
globals:
  model:
    gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector:
      base_estimator:
        gordo_tpu.pipeline.Pipeline:
          steps:
            - gordo_tpu.ops.scalers.MinMaxScaler
            - gordo_tpu.models.estimator.AutoEncoder:
                kind: feedforward_hourglass
                epochs: 4
                batch_size: 64
"""


@pytest.mark.slow
class TestRefreshAcceptance:
    """Build fleet → shift a subset's inputs → refresh rebuilds exactly
    those machines → generation flips → a live collection delta-reloads
    only the touched pack → drift returns to ok.  No restarts."""

    SHIFTED = "rf-1"

    def _machine_matrix(self, name):
        from gordo_tpu.dataset.base import GordoBaseDataset

        i = int(name.split("-")[1])
        ds = GordoBaseDataset.from_dict({
            "type": "RandomDataset",
            "tags": [f"rf{i}-a", f"rf{i}-b", f"rf{i}-c"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-27T06:00:00Z",
        })
        X, _ = ds.get_data()
        return np.asarray(X, np.float32)

    def test_drift_to_live_cycle(self, tmp_path, monkeypatch):
        from gordo_tpu.builder import build_project
        from gordo_tpu.dataset import datasets as ds_mod
        from gordo_tpu.serve.server import ModelCollection
        from gordo_tpu.workflow import NormalizedConfig, load_machine_config

        monkeypatch.setenv("GORDO_REFRESH_PARITY_FACTOR", "1e6")
        out = str(tmp_path / "models")
        cfg = NormalizedConfig(
            load_machine_config(_acceptance_yaml()), "refreshproj"
        )
        names = [m.name for m in cfg.machines]
        result = build_project(
            cfg.machines, out, max_bucket_size=2, artifact_format="v2",
        )
        assert not result.failed
        gen0 = artifacts.read_generation(out)
        assert gen0 >= 1

        # a live serving collection (adopts training baselines) sees
        # shifted traffic on ONE machine, in-range traffic on the rest
        reg = telemetry.FLEET_HEALTH
        reg.clear(names)
        coll = ModelCollection.from_directory(out, project="refreshproj")
        for name in names:
            X = self._machine_matrix(name)
            scale = 8.0 if name == self.SHIFTED else 1.0
            coll.get(name).scorer.anomaly_arrays(X * scale)
        doc = reg.doc(machines=names)
        statuses = {n: e["status"] for n, e in doc["machines"].items()}
        assert statuses[self.SHIFTED] == "drifting", statuses
        assert all(
            s == "ok" for n, s in statuses.items() if n != self.SHIFTED
        ), statuses
        fh.write_rollup(out, doc)

        # the refresh build must train the drifted machine on the NEW
        # (shifted) regime — shift that machine's dataset rows
        shifted_prefix = f"rf{self.SHIFTED.split('-')[1]}-"
        orig_get_data = ds_mod.RandomDataset.get_data

        def shifted_get_data(ds_self):
            X, y = orig_get_data(ds_self)
            tag0 = ds_self.tag_list[0]
            tag_name = getattr(tag0, "name", tag0)
            if str(tag_name).startswith(shifted_prefix):
                return X * 8.0, y * 8.0
            return X, y

        monkeypatch.setattr(
            ds_mod.RandomDataset, "get_data", shifted_get_data
        )

        # two health polls (hysteresis) → exactly the drifted machine
        # rebuilds warm; the suspended() guard keeps the refresh build's
        # own training scores out of the live window
        rcfg = RefreshConfig(
            machines=cfg.machines, output_dir=out,
            hysteresis=2, cooldown_seconds=0,
        )
        with reg.suspended():
            first = refresh_once(rcfg)
            assert first["outcome"] == "idle"
            assert first["drifting"] == [self.SHIFTED]
            second = refresh_once(rcfg)
        assert second["outcome"] == "rebuilt", second
        assert second["selected"] == [self.SHIFTED]
        assert second["rebuilt"] == [self.SHIFTED]
        assert second["warm_started"] == [self.SHIFTED], (
            "previous-generation params must warm-start the rebuild "
            f"(fallbacks: {second['warm_fallbacks']})"
        )
        gen1 = artifacts.read_generation(out)
        assert gen1 == second["generation"] == gen0 + 1

        # the live collection follows the flip with ONE whole-pack
        # transfer — only the touched machine reloads, no restart.
        # Materialize the stacked serving programs first so the reload's
        # device transfer is observable (lazy scorers defer it).
        with reg.suspended():
            _ = coll.fleet_scorer
        d0 = artifacts.device_put_count()
        changes = coll.maybe_delta_reload()
        assert changes["reloaded"] == [self.SHIFTED]
        assert artifacts.device_put_count() - d0 == 1
        assert coll.generation == gen1

        # warm attestation rides the artifact metadata
        store = artifacts.open_store(out)
        meta = store.load_metadata(self.SHIFTED)
        warm_meta = meta["model"]["warm_start"]
        assert warm_meta["warm"] is True
        assert warm_meta["epochs"] == 1  # ceil(4 * 0.25)

        # drift clears against the rebuilt baseline: fresh live window,
        # rebuilt model, same shifted regime → ok
        reg.clear([self.SHIFTED])
        reg.load_baselines({self.SHIFTED: meta})
        # get_data is monkeypatched for this machine by now, so the
        # matrix is already in the shifted regime — no extra scale
        X = self._machine_matrix(self.SHIFTED)
        coll.get(self.SHIFTED).scorer.anomaly_arrays(X)
        cleared = reg.doc(machines=[self.SHIFTED])
        entry = cleared["machines"][self.SHIFTED]
        assert entry["status"] == "ok", entry["drift"]

        # ... and the next refresh cycle goes back to idle
        fh.write_rollup(out, reg.doc(machines=names))
        with reg.suspended():
            after = refresh_once(rcfg)
        assert after["outcome"] == "idle"
        assert self.SHIFTED not in after["drifting"]
        reg.clear(names)


@pytest.mark.slow
class TestRefreshLongHorizonSoak:
    """ISSUE 14 satellite: the refresh plane under sustained drift — 20
    compressed drift→refresh→flip cycles against ONE live serving
    collection.  Pins the long-horizon invariants a single-cycle test
    can't: generations stay strictly monotone, the persisted selector
    state stays bounded (it must not accrete per-cycle entries), no
    machine is ever quarantined, and the live collection follows every
    flip by delta reload alone (no restart, no full rescan)."""

    CYCLES = 20

    def _soak_yaml(self):
        machines = "\n".join(
            f"""
  - name: soak-{i}
    dataset:
      type: RandomDataset
      tags: [soak{i}-a, soak{i}-b, soak{i}-c]
      train_start_date: "2017-12-25T06:00:00Z"
      train_end_date: "2017-12-26T06:00:00Z"
"""
            for i in range(2)
        )
        return f"""
machines:{machines}
globals:
  model:
    gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector:
      base_estimator:
        gordo_tpu.pipeline.Pipeline:
          steps:
            - gordo_tpu.ops.scalers.MinMaxScaler
            - gordo_tpu.models.estimator.AutoEncoder:
                kind: feedforward_hourglass
                epochs: 2
                batch_size: 64
"""

    def test_twenty_cycle_soak(self, tmp_path, monkeypatch):
        from gordo_tpu.builder import build_project
        from gordo_tpu.serve.server import ModelCollection
        from gordo_tpu.workflow import NormalizedConfig, load_machine_config

        # the soak drives rebuild mechanics, not loss quality — a huge
        # parity factor keeps every warm rebuild on the warm path
        monkeypatch.setenv("GORDO_REFRESH_PARITY_FACTOR", "1e6")
        out = str(tmp_path / "models")
        cfg = NormalizedConfig(
            load_machine_config(self._soak_yaml()), "soakproj"
        )
        names = [m.name for m in cfg.machines]
        result = build_project(cfg.machines, out, max_bucket_size=2)
        assert not result.failed
        generation = artifacts.read_generation(out)

        reg = telemetry.FLEET_HEALTH
        reg.clear(names)
        coll = ModelCollection.from_directory(out, project="soakproj")
        rcfg = RefreshConfig(
            machines=cfg.machines, output_dir=out,
            hysteresis=1, cooldown_seconds=0,
        )
        state_file = refresh_loop.state_path(out)
        state_size_early = None

        for cycle in range(self.CYCLES):
            target = names[cycle % len(names)]
            statuses = {
                n: ("drifting" if n == target else "ok") for n in names
            }
            fh.write_rollup(out, _health_doc(statuses))

            # the CronJob face: a fresh selector per cycle, streaks and
            # cooldowns riding state.json — the growth-bounded artifact
            with reg.suspended():
                summary = refresh_once(rcfg)
            assert summary["outcome"] == "rebuilt", (cycle, summary)
            assert summary["rebuilt"] == [target], (cycle, summary)
            assert not summary["failed"], (cycle, summary)

            # strictly monotone generations, one flip per cycle
            assert summary["generation"] == generation + 1, (cycle, summary)
            generation = summary["generation"]

            # the live collection follows by delta reload alone
            changes = coll.maybe_delta_reload()
            assert changes["reloaded"] == [target], (cycle, changes)
            assert changes["added"] == changes["removed"] == []
            assert coll.generation == generation
            assert coll.quarantined == {}, (cycle, coll.quarantined)

            if cycle == 1:
                state_size_early = os.path.getsize(state_file)

        # bounded state: one entry per fleet machine, not per cycle —
        # the file must not grow past its steady-state size (small slack
        # for float-digit jitter in last_rebuild timestamps)
        with open(state_file) as fh_state:
            state = json.load(fh_state)
        assert sorted(state["machines"]) == sorted(names)
        final_size = os.path.getsize(state_file)
        assert final_size <= state_size_early + 64, (
            state_size_early, final_size,
        )

        # the fleet survived 20 rebuild generations intact
        _, refs = artifacts.discover(out, quarantine=True)
        assert sorted(r.name for r in refs) == sorted(names)
        reg.clear(names)
