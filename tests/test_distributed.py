"""Multi-host runtime tests.

Fast lane: config/spec parsing, deterministic partitioning, shard-state
resumability, launcher env construction — pure host-side logic, no
``jax.distributed`` init (a second init in the shared test process would
poison every later test).  The REAL cross-process path — coordinator
bring-up, process-spanning mesh, barriers, worker death — runs in the
slow lane via ``scripts/multihost_dryrun.py``, which forks fresh
processes exactly like production does.
"""

import json
import os
import subprocess
import sys

import pytest

from gordo_tpu.distributed.launcher import pick_free_port, worker_env
from gordo_tpu.distributed.partition import (
    EXIT_SHARD_RESUMABLE,
    ShardState,
    max_processes,
    partition_machines,
    process_shard,
)
from gordo_tpu.distributed.runtime import (
    DistributedConfig,
    parse_multihost_spec,
)
from gordo_tpu.workflow.config import Machine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _machine(name, tags=("a", "b", "c"), model=None):
    cfg = {
        "name": name,
        "dataset": {"type": "RandomDataset", "tag_list": list(tags)},
    }
    if model:
        cfg["model"] = model
    return Machine.from_config(cfg)


# ---------------------------------------------------------------------------
# spec / env parsing
# ---------------------------------------------------------------------------

class TestSpecParsing:
    def test_cli_spec_roundtrip(self):
        cfg = parse_multihost_spec("10.0.0.2:8476,16,3")
        assert cfg.coordinator == "10.0.0.2:8476"
        assert cfg.num_processes == 16
        assert cfg.process_id == 3

    @pytest.mark.parametrize("bad", [
        "10.0.0.2:8476,16",        # missing pid
        "10.0.0.2,16,3",           # no port
        "10.0.0.2:8476,sixteen,3",  # non-integer N
        "10.0.0.2:8476,16,16",     # pid out of range
        "10.0.0.2:8476,0,0",       # zero processes
    ])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(ValueError):
            parse_multihost_spec(bad)

    def test_from_env_full(self):
        env = {
            "GORDO_COORDINATOR": "coord:1234",
            "GORDO_NUM_PROCESSES": "4",
            "GORDO_PROCESS_ID": "2",
            "GORDO_LOCAL_DEVICES": "2",
            "GORDO_BARRIER_TIMEOUT": "45",
        }
        cfg = DistributedConfig.from_env(env)
        assert cfg.coordinator == "coord:1234"
        assert cfg.num_processes == 4
        assert cfg.process_id == 2
        assert cfg.local_device_count == 2
        assert cfg.barrier_timeout == 45.0

    def test_from_env_absent_means_single_host(self):
        assert DistributedConfig.from_env({}) is None
        assert DistributedConfig.from_env({"GORDO_COORDINATOR": ""}) is None

    def test_from_env_partial_is_an_error(self):
        with pytest.raises(ValueError, match="GORDO"):
            DistributedConfig.from_env({"GORDO_COORDINATOR": "c:1"})


# ---------------------------------------------------------------------------
# partitioning
# ---------------------------------------------------------------------------

class TestPartition:
    def test_disjoint_and_exhaustive(self):
        machines = [_machine(f"m-{i:02d}") for i in range(11)]
        for n in (1, 2, 3, 5, 11):
            shards = partition_machines(machines, n)
            assert len(shards) == n
            names = sorted(m.name for s in shards for m in s)
            assert names == sorted(m.name for m in machines)

    def test_deterministic_and_order_independent(self):
        machines = [_machine(f"m-{i:02d}") for i in range(9)]
        ref = [
            [m.name for m in s] for s in partition_machines(machines, 3)
        ]
        shuffled = list(reversed(machines))
        again = [
            [m.name for m in s] for s in partition_machines(shuffled, 3)
        ]
        assert ref == again

    def test_balanced_within_one_machine(self):
        machines = [_machine(f"m-{i:02d}") for i in range(10)]
        sizes = [len(s) for s in partition_machines(machines, 3)]
        assert max(sizes) - min(sizes) <= 1

    def test_signatures_stay_grouped(self):
        """Same-signature machines slice contiguously — a shard never
        interleaves two signatures when it could hold one."""
        wide = [_machine(f"w-{i}", tags=("a", "b", "c", "d", "e"))
                for i in range(4)]
        narrow = [_machine(f"n-{i}") for i in range(4)]
        shards = partition_machines(narrow + wide, 2)
        for s in shards:
            prefixes = [m.name[0] for m in s]
            # each signature's members appear as one contiguous run
            for p in set(prefixes):
                first, last = prefixes.index(p), len(prefixes) - 1 - prefixes[::-1].index(p)
                assert all(x == p for x in prefixes[first:last + 1])

    def test_more_processes_than_machines_leaves_empty_shards(self):
        machines = [_machine("m-0"), _machine("m-1")]
        shards = partition_machines(machines, 4)
        assert sorted(len(s) for s in shards) == [0, 0, 1, 1]

    def test_max_processes_is_machine_count(self):
        machines = [_machine(f"m-{i}") for i in range(7)]
        assert max_processes(machines) == 7

    def test_process_shard_selects_own_slice(self, tmp_path):
        machines = [_machine(f"m-{i:02d}") for i in range(6)]
        all_names = []
        for pid in range(3):
            shard = process_shard(
                machines, 3, pid, output_dir=str(tmp_path)
            )
            assert shard.process_id == pid
            assert shard.state is not None
            all_names.extend(shard.names)
        assert sorted(all_names) == [m.name for m in machines]


# ---------------------------------------------------------------------------
# shard state (resumability)
# ---------------------------------------------------------------------------

class TestShardState:
    def test_roundtrip_and_progress(self, tmp_path):
        state = ShardState(str(tmp_path), 1, 2)
        state.start(["m-a", "m-b", "m-c"])
        state.record("m-a")
        loaded = ShardState.load(str(tmp_path), 1, 2)
        assert loaded.status == "running"
        assert loaded.completed == ["m-a"]
        assert loaded.machines == ["m-a", "m-b", "m-c"]
        state.finish()
        assert ShardState.load(str(tmp_path), 1, 2).status == "done"

    def test_resume_preserves_completed_for_same_shard(self, tmp_path):
        first = ShardState(str(tmp_path), 0, 2)
        first.start(["m-a", "m-b"])
        first.record("m-a")
        first.mark_resumable("peer died")
        # a re-run of the SAME shard keeps the history...
        second = ShardState(str(tmp_path), 0, 2)
        second.start(["m-b", "m-a"])  # order-insensitive
        assert second.completed == ["m-a"]
        # ...a different machine set resets it
        third = ShardState(str(tmp_path), 0, 2)
        third.start(["m-a", "m-z"])
        assert third.completed == []

    def test_load_missing_returns_none(self, tmp_path):
        assert ShardState.load(str(tmp_path), 0, 2) is None

    def test_resumable_exit_code_is_tempfail(self):
        assert EXIT_SHARD_RESUMABLE == 75  # BSD EX_TEMPFAIL: retry me


# ---------------------------------------------------------------------------
# launcher env
# ---------------------------------------------------------------------------

class TestLauncher:
    def test_pick_free_port_binds(self):
        port = pick_free_port()
        assert 1024 <= port <= 65535

    def test_worker_env_contract(self):
        env = worker_env(
            1, 4, "127.0.0.1:9999", local_devices=2, barrier_timeout=30,
        )
        assert env["GORDO_COORDINATOR"] == "127.0.0.1:9999"
        assert env["GORDO_NUM_PROCESSES"] == "4"
        assert env["GORDO_PROCESS_ID"] == "1"
        assert env["GORDO_LOCAL_DEVICES"] == "2"
        assert env["GORDO_BARRIER_TIMEOUT"] == "30"
        assert env["JAX_PLATFORMS"] == "cpu"
        assert "--xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]

    def test_worker_env_replaces_inherited_device_count(self):
        base = dict(os.environ)
        base["XLA_FLAGS"] = (
            "--xla_foo=1 --xla_force_host_platform_device_count=8"
        )
        env = worker_env(0, 2, "c:1", local_devices=3, base_env=base)
        flags = env["XLA_FLAGS"].split()
        assert "--xla_foo=1" in flags
        assert flags.count("--xla_force_host_platform_device_count=3") == 1
        assert "--xla_force_host_platform_device_count=8" not in flags


# ---------------------------------------------------------------------------
# sharded build_project (in-process, local mesh only — no jax.distributed)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_build_project_with_shard_builds_only_its_slice(tmp_path):
    from gordo_tpu.builder import build_project

    machines = [
        Machine.from_config({
            "name": f"sh-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tag_list": ["a", "b", "c"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-26T06:00:00Z",
            },
        })
        for i in range(4)
    ]
    out = str(tmp_path / "models")
    built = []
    for pid in range(2):
        shard = process_shard(machines, 2, pid, output_dir=out)
        result = build_project(machines, out, shard=shard)
        assert not result.failed
        assert sorted(result.artifacts) == sorted(shard.names)
        assert result.shard == (pid, 2)
        assert result.summary()["shard"]["process_id"] == pid
        state = ShardState.load(out, pid, 2)
        assert state.status == "done"
        assert sorted(state.completed) == sorted(shard.names)
        built.extend(result.artifacts)
    assert sorted(built) == [m.name for m in machines]


# ---------------------------------------------------------------------------
# the real multi-process path (slow lane): forked workers, real
# jax.distributed init, kill/resume — the CI form of the dryrun
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_multihost_dryrun_two_processes():
    """ISSUE acceptance: 2 forked processes pass on CPU — init succeeds,
    shards disjoint+exhaustive, artifacts byte-identical to single-host,
    and a killed worker leaves a resumable state a re-run completes."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "multihost_dryrun.py")],
        capture_output=True, text=True, timeout=570, cwd=REPO,
        env={
            k: v for k, v in os.environ.items()
            # the forked workers pin their own backends; drop the test
            # harness's 8-device flag so it can't leak in
            if k not in ("XLA_FLAGS",)
        },
    )
    assert proc.returncode == 0, (
        f"dryrun rc={proc.returncode}\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-2000:]}"
    )
    ok_line = [
        line for line in proc.stdout.splitlines() if line.startswith("OK ")
    ]
    assert ok_line, proc.stdout[-2000:]
    doc = json.loads(ok_line[0][3:])
    assert "multihost-init-2proc" in doc["phases"]
    assert "artifact-byte-identity" in doc["phases"]
    assert "resume-completed" in doc["phases"]
