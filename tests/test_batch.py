"""Backfill plane tests: archive format/durability, the deterministic
chunk plan, shard resolution, the runner's fp32 parity with the online
fused path, resumability, and the end-to-end wiring (CLI, workflow
Indexed Job, score_history, archive-seeded baselines).

Fast classes run in the tier-1 lane (pure host I/O, no model training);
the classes that build a real fleet or start a real server are marked
slow (CI test-full job).
"""

import asyncio
import importlib.util
import json
import os

import numpy as np
import pandas as pd
import pytest
from click.testing import CliRunner

from gordo_tpu import telemetry
from gordo_tpu.batch import (
    ArchiveError,
    ArchivePlanError,
    BackfillConfig,
    BackfillError,
    ScoreArchive,
    chunk_windows,
    resolve_shard,
    run_backfill,
)
from gordo_tpu.cli.cli import gordo


def _columns(rows, n_tags, t0_ns=0, step_ns=600_000_000_000, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "index-ns": t0_ns + step_ns * np.arange(rows, dtype=np.int64),
        "total-anomaly-score": rng.standard_normal(rows).astype(np.float32),
        "tag-anomaly-scores": rng.standard_normal(
            (rows, n_tags)
        ).astype(np.float32),
        "tags": [f"t-{j}" for j in range(n_tags)],
    }


def _create(root, **over):
    kw = dict(
        project="p", start="2020-01-01 00:00:00+00:00",
        end="2020-01-02 00:00:00+00:00", resolution="10min",
        chunk_rows=48, n_chunks=3, dtype="float32",
        machines=["m-a", "m-b"],
    )
    kw.update(over)
    return ScoreArchive.create(str(root), **kw)


class TestChunkWindows:
    def test_covers_half_open_range_exactly(self):
        windows = chunk_windows(
            "2020-01-01", "2020-01-02", "10min", 48
        )
        assert len(windows) == 3  # 144 rows / 48
        assert windows[0][0] == pd.Timestamp("2020-01-01", tz="UTC")
        assert windows[-1][1] == pd.Timestamp("2020-01-02", tz="UTC")
        for (_, a_end), (b_start, _) in zip(windows, windows[1:]):
            assert a_end == b_start

    def test_ragged_tail_window(self):
        windows = chunk_windows(
            "2020-01-01 00:00", "2020-01-01 01:30", "10min", 4
        )
        spans = [(t1 - t0) / pd.Timedelta("10min") for t0, t1 in windows]
        assert spans == [4, 4, 1]

    def test_deterministic_across_calls(self):
        a = chunk_windows("2020-03-01", "2020-04-01", "1min", 512)
        b = chunk_windows("2020-03-01", "2020-04-01", "1min", 512)
        assert a == b

    def test_tz_naive_is_utc(self):
        (t0, _), = chunk_windows(
            "2020-01-01", "2020-01-01 00:10", "10min", 100
        )
        assert t0 == pd.Timestamp("2020-01-01", tz="UTC")

    def test_bad_range_refused(self):
        with pytest.raises(ValueError, match="precede"):
            chunk_windows("2020-02-01", "2020-01-01", "10min", 48)


class TestResolveShard:
    def test_default_unsharded(self, monkeypatch):
        for var in ("GORDO_BACKFILL_SHARD", "GORDO_BACKFILL_SHARD_INDEX",
                    "GORDO_BACKFILL_NUM_SHARDS"):
            monkeypatch.delenv(var, raising=False)
        assert resolve_shard() == (0, 1)

    def test_explicit_spec(self):
        assert resolve_shard("2/5") == (2, 5)

    def test_env_spec(self, monkeypatch):
        monkeypatch.setenv("GORDO_BACKFILL_SHARD", "1/3")
        assert resolve_shard() == (1, 3)

    def test_indexed_job_env_pair(self, monkeypatch):
        monkeypatch.delenv("GORDO_BACKFILL_SHARD", raising=False)
        monkeypatch.setenv("GORDO_BACKFILL_SHARD_INDEX", "3")
        monkeypatch.setenv("GORDO_BACKFILL_NUM_SHARDS", "4")
        assert resolve_shard() == (3, 4)

    @pytest.mark.parametrize("bad", ["x/y", "3", "3/3", "-1/2", "1/0"])
    def test_malformed_specs_refused(self, bad):
        with pytest.raises(ValueError):
            resolve_shard(bad)


class TestScoreArchive:
    def test_round_trip_across_chunks(self, tmp_path):
        arch = _create(tmp_path)
        c0 = {"m-a": _columns(48, 3, seed=1),
              "m-b": _columns(48, 2, seed=2)}
        c1 = {"m-a": _columns(48, 3, t0_ns=48 * 600_000_000_000, seed=3)}
        arch.write_chunk(0, c0)
        arch.write_chunk(1, c1)

        rec = arch.read_machine("m-a")
        assert rec["tags"] == ["t-0", "t-1", "t-2"]
        assert rec["total-anomaly-score"].dtype == np.float32
        assert rec["tag-anomaly-scores"].shape == (96, 3)
        expect = np.concatenate([
            c0["m-a"]["total-anomaly-score"],
            c1["m-a"]["total-anomaly-score"],
        ])
        assert rec["total-anomaly-score"].tobytes() == expect.tobytes()
        # m-b only appears in chunk 0
        assert arch.read_machine("m-b")["tag-anomaly-scores"].shape == (48, 2)
        assert arch.read_machine("m-unknown") is None

    def test_read_clips_to_half_open_range(self, tmp_path):
        arch = _create(tmp_path)
        arch.write_chunk(0, {"m-a": _columns(48, 2)})
        step = 600_000_000_000
        rec = arch.read_machine(
            "m-a",
            start=pd.Timestamp(10 * step, unit="ns", tz="UTC"),
            end=pd.Timestamp(20 * step, unit="ns", tz="UTC"),
        )
        assert len(rec["index-ns"]) == 10
        assert rec["index-ns"][0] == 10 * step

    def test_completion_records_are_the_resume_ledger(self, tmp_path):
        arch = _create(tmp_path)
        arch.write_chunk(0, {"m-a": _columns(48, 2)})
        arch.write_chunk(2, {}, meta={"note": "empty window"})
        assert arch.completed_chunks(0) == {0, 2}
        assert arch.completed_chunks(1) == set()
        records = arch.chunk_records()
        assert records["0/0"]["segment"] is not None
        assert records["2/0"]["segment"] is None  # empty chunk, no file
        assert records["2/0"]["note"] == "empty window"

    def test_plan_mismatch_refused(self, tmp_path):
        _create(tmp_path)
        with pytest.raises(ArchivePlanError, match="chunk-rows"):
            _create(tmp_path, chunk_rows=64)

    def test_sibling_shard_merges_roster(self, tmp_path):
        _create(tmp_path, machines=["m-a"], shard=(0, 2))
        arch = _create(tmp_path, machines=["m-b"], shard=(1, 2))
        assert arch.machines() == ["m-a", "m-b"]
        assert set(arch.index()["shards"]) == {"0", "1"}

    def test_torn_archive_detected(self, tmp_path):
        arch = _create(tmp_path)
        fname = arch.write_chunk(0, {"m-a": _columns(8, 2)})
        os.unlink(os.path.join(arch.directory, fname))
        with pytest.raises(ArchiveError, match="torn"):
            arch.read_machine("m-a")

    def test_summary_counts(self, tmp_path):
        arch = _create(tmp_path)
        arch.write_chunk(0, {"m-a": _columns(48, 2)})
        arch.write_chunk(1, {})
        s = arch.summary()
        assert s["chunks-completed"] == 2
        assert s["segments"] == 1
        assert s["rows"] == 48
        assert s["plan"]["chunk-rows"] == 48


class TestBackfillTelemetry:
    def test_instruments_registered(self):
        text = telemetry.render()
        for metric in (
            "gordo_backfill_chunks_total",
            "gordo_backfill_rows_total",
            "gordo_backfill_samples_total",
            "gordo_backfill_samples_per_second",
            "gordo_backfill_device_transfers_total",
            "gordo_backfill_chunk_occupancy",
            "gordo_backfill_machines",
        ):
            assert metric in text, metric


class TestBackfillCli:
    def test_missing_fleet_exits_resumable(self, tmp_path):
        result = CliRunner().invoke(gordo, [
            "backfill", "--model-dir", str(tmp_path),
            "--start", "2020-01-01", "--end", "2020-01-02",
        ])
        # nothing to score is still EX_TEMPFAIL: the supervisor re-runs
        # once the artifacts exist (Indexed Jobs start before the PVC
        # has models during a first deploy)
        assert result.exit_code == 75


class TestWorkflowBackfillJob:
    CONFIG = {
        "machines": [
            {"name": f"wfb-{i}", "dataset": {
                "type": "RandomDataset",
                "tags": [f"wfb{i}-a", f"wfb{i}-b"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-26T06:00:00Z",
            }}
            for i in range(3)
        ]
    }

    def _generate(self, **kw):
        from gordo_tpu.workflow import NormalizedConfig, generate_workflow

        return generate_workflow(
            NormalizedConfig(self.CONFIG, "wfbproj"), **kw
        )

    def test_indexed_job_with_shard_env_pair(self):
        docs = self._generate(
            backfill=("2024-01-01", "2024-02-01"), backfill_shards=3
        )
        jobs = [d for d in docs if d.get("kind") == "Job"
                and "backfill" in d["metadata"]["name"]]
        assert len(jobs) == 1
        spec = jobs[0]["spec"]
        assert spec["completionMode"] == "Indexed"
        assert spec["completions"] == spec["parallelism"] == 3
        container = spec["template"]["spec"]["containers"][0]
        assert container["command"] == ["gordo", "backfill"]
        assert container["args"][:2] == ["--model-dir", "/models"]
        env = {e["name"]: e["value"] for e in container["env"]}
        assert env["GORDO_BACKFILL_SHARD_INDEX"] == "$(JOB_COMPLETION_INDEX)"
        assert env["GORDO_BACKFILL_NUM_SHARDS"] == "3"
        # the pod mirrors the builder's volumes: models PVC + config
        names = {v["name"]
                 for v in spec["template"]["spec"]["volumes"]}
        assert "models" in names

    def test_without_backfill_no_job(self):
        docs = self._generate()
        assert not any(
            "backfill" in d.get("metadata", {}).get("name", "")
            for d in docs
        )

    def test_shards_beyond_machines_refused(self):
        with pytest.raises(ValueError, match="atoms of the backfill"):
            self._generate(
                backfill=("2024-01-01", "2024-02-01"), backfill_shards=4
            )

    def test_malformed_range_refused(self):
        with pytest.raises(ValueError, match="does not parse"):
            self._generate(backfill=("not-a-time", "2024-02-01"))

    def test_inverted_range_refused(self):
        with pytest.raises(ValueError, match="must precede"):
            self._generate(backfill=("2024-02-01", "2024-01-01"))


class TestBatchLintGate:
    @staticmethod
    def _lint(path):
        spec = importlib.util.spec_from_file_location(
            "gordo_lint", os.path.join(
                os.path.dirname(os.path.dirname(__file__)),
                "scripts", "lint.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.lint_file(path)

    def test_http_imports_rejected_in_batch_plane(self, tmp_path):
        bad = tmp_path / "gordo_tpu" / "batch" / "thing.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "import aiohttp\n"
            "import urllib.request\n"
            "from gordo_tpu.serve import server\n"
            "from gordo_tpu.serve.server import ModelCollection\n"
            "from gordo_tpu import client\n"
            "from gordo_tpu.client.client import Client\n"
            "aiohttp, urllib, server, ModelCollection, client, Client\n"
        )
        msgs = [f[2] for f in self._lint(str(bad))]
        assert sum("backfill" in m for m in msgs) == 6

    def test_scorer_reuse_is_allowed(self, tmp_path):
        ok = tmp_path / "gordo_tpu" / "batch" / "fine.py"
        ok.parent.mkdir(parents=True)
        ok.write_text(
            "from gordo_tpu.serve.fleet_scorer import FleetScorer\n"
            "from gordo_tpu.serve import precision\n"
            "FleetScorer, precision\n"
        )
        msgs = [f[2] for f in self._lint(str(ok))]
        assert not any("backfill" in m for m in msgs)

    def test_batch_plane_is_clean_under_the_gate(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in ("archive.py", "compact.py", "runner.py", "__init__.py"):
            path = os.path.join(repo, "gordo_tpu", "batch", rel)
            assert self._lint(path) == [], rel


# ---------------------------------------------------------------------------
# end-to-end against a real built fleet (slow lane — CI test-full job)
# ---------------------------------------------------------------------------

PROJECT = {
    "machines": [
        {"name": f"bf-{i}", "dataset": {
            "type": "RandomDataset",
            "tags": [f"bf{i}-a", f"bf{i}-b", f"bf{i}-c"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-26T06:00:00Z",
        }}
        for i in range(3)
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {"gordo_tpu.models.estimator.AutoEncoder": {
                                "kind": "feedforward_hourglass",
                                "epochs": 1,
                                "batch_size": 64,
                            }},
                        ]
                    }
                }
            }
        }
    },
}

START = "2017-12-26 06:00:00+00:00"
END = "2017-12-27 06:00:00+00:00"  # 24h @ 10min = 144 rows
CHUNK_ROWS = 48


@pytest.fixture(scope="module")
def fleet_dir(tmp_path_factory):
    from gordo_tpu.builder import build_project
    from gordo_tpu.workflow import NormalizedConfig

    out = tmp_path_factory.mktemp("backfill-artifacts")
    result = build_project(
        NormalizedConfig(PROJECT, "bfproj").machines, str(out)
    )
    assert not result.failed
    return str(out)


def _backfill(fleet_dir, archive_dir, **over):
    kw = dict(
        model_dir=fleet_dir, start=START, end=END,
        archive_dir=archive_dir, project="bfproj",
        chunk_rows=CHUNK_ROWS,
    )
    kw.update(over)
    return run_backfill(BackfillConfig(**kw))


def _online_scores(fleet_dir, names=None):
    """The online fused path's scores over the backfill windows: the
    server's exact FleetScorer geometry fed the identical chunk slices
    the runner stages."""
    from gordo_tpu import artifacts
    from gordo_tpu.compile import load_warmup_manifest
    from gordo_tpu.dataset import dataset_from_metadata
    from gordo_tpu.serve import precision
    from gordo_tpu.serve.fleet_scorer import FleetScorer

    store, refs = artifacts.discover(fleet_dir, quarantine=True)
    refs = sorted(refs, key=lambda r: r.name)
    if names is not None:
        refs = [r for r in refs if r.name in set(names)]
    models = {r.name: r.load_model() for r in refs}
    metas = {r.name: (r.load_metadata() or {}) for r in refs}
    manifest_dtype = (load_warmup_manifest(fleet_dir) or {}).get("dtype")
    scorer = FleetScorer.from_models(
        models, pack_store=store,
        dtype=precision.serve_dtype(default=manifest_dtype),
    )
    frames = {}
    for name, meta in metas.items():
        X, _ = dataset_from_metadata(
            meta["dataset"], START, END
        ).get_data()
        frames[name] = X
    out = {name: {"total": [], "tags": []} for name in models}
    for t0, t1 in chunk_windows(START, END, "10min", CHUNK_ROWS):
        X_by = {}
        for name, X in frames.items():
            lo, hi = X.index.searchsorted(t0), X.index.searchsorted(t1)
            if hi > lo:
                X_by[name] = X.iloc[lo:hi].to_numpy(np.float32)
        if not X_by:
            continue
        with telemetry.FLEET_HEALTH.suspended():
            results = scorer.score_all(X_by)
        for name, res in results.items():
            if "error" in res:
                continue
            out[name]["total"].append(
                np.asarray(res["total-anomaly-score"], np.float32)
            )
            out[name]["tags"].append(
                np.asarray(res["tag-anomaly-scores"], np.float32)
            )
    return {
        name: {
            "total": np.concatenate(cols["total"]),
            "tags": np.concatenate(cols["tags"]),
        }
        for name, cols in out.items() if cols["total"]
    }


@pytest.mark.slow
class TestBackfillEndToEnd:
    def test_parity_with_online_fused_path(self, fleet_dir, tmp_path):
        summary = _backfill(fleet_dir, str(tmp_path / "arch"))
        assert summary["chunks"] == 3
        assert summary["chunks-ok"] == 3
        assert summary["remaining"] == 0
        assert summary["rows"] > 0
        assert summary["device-transfers"] >= 3  # >= one per chunk
        assert summary["samples-per-second"] > 0

        arch = ScoreArchive(str(tmp_path / "arch"))
        online = _online_scores(fleet_dir)
        assert set(arch.machines()) == set(online)
        for name, cols in online.items():
            rec = arch.read_machine(name)
            # the acceptance bar: archive bytes fp32-IDENTICAL to the
            # online fused path over the same windows (same dispatch
            # membership → same padded program geometry)
            assert rec["total-anomaly-score"].tobytes() == \
                cols["total"].tobytes(), name
            assert rec["tag-anomaly-scores"].tobytes() == \
                cols["tags"].tobytes(), name

    def test_kill_and_resume_is_byte_identical(self, fleet_dir, tmp_path):
        uninterrupted = str(tmp_path / "one-shot")
        interrupted = str(tmp_path / "resumed")
        _backfill(fleet_dir, uninterrupted)

        partial = _backfill(fleet_dir, interrupted, max_chunks=1)
        assert partial["chunks-ok"] == 1
        assert partial["remaining"] == 2
        resumed = _backfill(fleet_dir, interrupted)
        assert resumed["chunks-skipped"] == 1
        assert resumed["chunks-ok"] == 2
        assert resumed["remaining"] == 0

        a, b = ScoreArchive(uninterrupted), ScoreArchive(interrupted)
        assert a.machines() == b.machines()
        for name in a.machines():
            ra, rb = a.read_machine(name), b.read_machine(name)
            assert ra["index-ns"].tobytes() == rb["index-ns"].tobytes()
            assert ra["total-anomaly-score"].tobytes() == \
                rb["total-anomaly-score"].tobytes()
            assert ra["tag-anomaly-scores"].tobytes() == \
                rb["tag-anomaly-scores"].tobytes()

    def test_plan_drift_on_resume_refused(self, fleet_dir, tmp_path):
        archive_dir = str(tmp_path / "arch")
        _backfill(fleet_dir, archive_dir, max_chunks=1)
        with pytest.raises((ArchivePlanError, BackfillError)):
            _backfill(fleet_dir, archive_dir, chunk_rows=CHUNK_ROWS * 2)

    def test_sharded_runs_are_disjoint_and_merge(self, fleet_dir, tmp_path):
        archive_dir = str(tmp_path / "arch")
        s0 = _backfill(fleet_dir, archive_dir, shard="0/2")
        s1 = _backfill(fleet_dir, archive_dir, shard="1/2")
        assert s0["machines"] + s1["machines"] == 3
        arch = ScoreArchive(archive_dir)
        assert len(arch.machines()) == 3
        full = ScoreArchive(str(tmp_path / "full"))
        _backfill(fleet_dir, str(tmp_path / "full"))
        for name in arch.machines():
            merged = arch.read_machine(name)
            whole = full.read_machine(name)
            assert merged is not None and whole is not None
            # shard membership changes dispatch geometry, so scores are
            # shard-local — but coverage must match the unsharded run
            assert merged["index-ns"].tobytes() == \
                whole["index-ns"].tobytes()

    def test_machine_subset_and_unknown_machine(self, fleet_dir, tmp_path):
        summary = _backfill(
            fleet_dir, str(tmp_path / "sub"), machines=["bf-1"]
        )
        assert summary["machines"] == 1
        arch = ScoreArchive(str(tmp_path / "sub"))
        assert arch.machines() == ["bf-1"]
        with pytest.raises(BackfillError, match="not in the artifact"):
            _backfill(fleet_dir, str(tmp_path / "sub2"),
                      machines=["no-such-machine"])

    def test_score_history_reads_archive(self, fleet_dir, tmp_path):
        from gordo_tpu.client import Client

        archive_dir = str(tmp_path / "arch")
        _backfill(fleet_dir, archive_dir)
        frames = Client("bfproj").score_history(archive_dir=archive_dir)
        assert set(frames) == {"bf-0", "bf-1", "bf-2"}
        df = frames["bf-0"]
        assert df.index.tz is not None
        assert list(df.columns)[0] == "total-anomaly-score"
        assert [c for c in df.columns if c.startswith("tag-anomaly-")] == [
            "tag-anomaly-score-bf0-a",
            "tag-anomaly-score-bf0-b",
            "tag-anomaly-score-bf0-c",
        ]
        clipped = Client("bfproj").score_history(
            ["bf-0"], archive_dir=archive_dir,
            start="2017-12-26 12:00:00Z", end="2017-12-26 14:00:00Z",
        )
        assert len(clipped["bf-0"]) <= 12
        assert (clipped["bf-0"].index >= "2017-12-26 12:00:00Z").all()

    def test_baselines_from_archive(self, fleet_dir, tmp_path):
        archive_dir = str(tmp_path / "arch")
        _backfill(fleet_dir, archive_dir)
        docs = telemetry.baselines_from_archive(archive_dir)
        assert set(docs) == {"bf-0", "bf-1", "bf-2"}
        for doc in docs.values():
            assert doc.get("count", 0) > 0 or doc.get("counts")
        reg = telemetry.FLEET_HEALTH
        try:
            applied = telemetry.baselines_from_archive(
                archive_dir, machines=["bf-0"], apply=True
            )
            assert set(applied) == {"bf-0"}
        finally:
            reg.clear(["bf-0", "bf-1", "bf-2"])

    def test_cli_backfill_and_resume_exit_codes(self, fleet_dir, tmp_path):
        archive_dir = str(tmp_path / "arch")
        runner = CliRunner()
        bounded = runner.invoke(gordo, [
            "backfill", "--model-dir", fleet_dir,
            "--archive-dir", archive_dir, "--project-name", "bfproj",
            "--start", START, "--end", END,
            "--chunk-rows", str(CHUNK_ROWS), "--max-chunks", "1",
        ])
        # progress archived but range unfinished → EX_TEMPFAIL
        assert bounded.exit_code == 75, bounded.output
        summary = json.loads(bounded.output.strip().splitlines()[-1])
        assert summary["remaining"] == 2

        finished = runner.invoke(gordo, [
            "backfill", "--model-dir", fleet_dir,
            "--archive-dir", archive_dir, "--project-name", "bfproj",
            "--start", START, "--end", END,
            "--chunk-rows", str(CHUNK_ROWS),
        ])
        assert finished.exit_code == 0, finished.output
        summary = json.loads(finished.output.strip().splitlines()[-1])
        assert summary["chunks-skipped"] == 1
        assert summary["remaining"] == 0


@pytest.mark.slow
class TestArchiveHttpParity:
    """The archive path and the live HTTP bulk route must agree byte-for-
    byte: same windows, same dispatch membership, same fused programs —
    the backfill plane is the server's scorer without the server."""

    def test_bulk_route_matches_archive(self, fleet_dir, tmp_path):
        import aiohttp
        from aiohttp import web

        from gordo_tpu.dataset import dataset_from_metadata
        from gordo_tpu.serve import ModelCollection, build_app, codec

        archive_dir = str(tmp_path / "arch")
        _backfill(fleet_dir, archive_dir)
        arch = ScoreArchive(archive_dir)
        names = arch.machines()

        async def runner():
            collection = ModelCollection.from_directory(
                fleet_dir, project="bfproj"
            )
            frames = {}
            for name in names:
                meta = collection.get(name).metadata
                X, _ = dataset_from_metadata(
                    meta["dataset"], START, END
                ).get_data()
                frames[name] = X
            app_runner = web.AppRunner(build_app(collection))
            await app_runner.setup()
            site = web.TCPSite(app_runner, "127.0.0.1", 0)
            await site.start()
            port = app_runner.addresses[0][1]
            url = (f"http://127.0.0.1:{port}/gordo/v0/bfproj/"
                   f"_bulk/anomaly/prediction")
            per_machine = {n: {"total": [], "tags": []} for n in names}
            try:
                async with aiohttp.ClientSession() as session:
                    for t0, t1 in chunk_windows(
                        START, END, "10min", CHUNK_ROWS
                    ):
                        X_by = {}
                        for name, X in frames.items():
                            lo = X.index.searchsorted(t0)
                            hi = X.index.searchsorted(t1)
                            if hi > lo:
                                X_by[name] = X.iloc[lo:hi].to_numpy(
                                    np.float32
                                )
                        if not X_by:
                            continue
                        with telemetry.FLEET_HEALTH.suspended():
                            async with session.post(
                                url,
                                data=codec.packb({"X": X_by}),
                                headers={
                                    "Content-Type":
                                        codec.MSGPACK_CONTENT_TYPE,
                                    "Accept": codec.MSGPACK_CONTENT_TYPE,
                                },
                            ) as resp:
                                assert resp.status == 200
                                body = codec.unpackb(await resp.read())
                        for name, res in body["data"].items():
                            per_machine[name]["total"].append(
                                np.asarray(
                                    res["total-anomaly-score"],
                                    np.float32,
                                )
                            )
                            per_machine[name]["tags"].append(
                                np.asarray(
                                    res["tag-anomaly-scores"], np.float32
                                )
                            )
            finally:
                await app_runner.cleanup()
            return per_machine

        http_scores = asyncio.run(runner())
        telemetry.FLEET_HEALTH.clear(names)
        for name in names:
            rec = arch.read_machine(name)
            total = np.concatenate(http_scores[name]["total"])
            tags = np.concatenate(http_scores[name]["tags"])
            assert rec["total-anomaly-score"].tobytes() == \
                total.tobytes(), name
            assert rec["tag-anomaly-scores"].tobytes() == \
                tags.tobytes(), name
