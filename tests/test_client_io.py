"""Client transport-layer unit tests (fast lane): the bulk-round
samples budget.

ISSUE 14 satellite: a bulk round's payload spans EVERY machine, so
``batch_size`` alone bounds only the row axis — a long-time-range
request against a wide fleet used to pack one giant body through the
codec.  ``bulk_rows_budget`` shrinks the row slice so no round exceeds
``GORDO_CLIENT_MAX_BULK_SAMPLES`` total samples.
"""

import pytest

from gordo_tpu.client.io import (
    DEFAULT_MAX_BULK_SAMPLES,
    ENV_MAX_BULK_SAMPLES,
    bulk_rows_budget,
    max_bulk_samples,
)


class TestMaxBulkSamples:
    def test_default(self, monkeypatch):
        monkeypatch.delenv(ENV_MAX_BULK_SAMPLES, raising=False)
        assert max_bulk_samples() == DEFAULT_MAX_BULK_SAMPLES

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv(ENV_MAX_BULK_SAMPLES, "12345")
        assert max_bulk_samples() == 12345

    @pytest.mark.parametrize("bad", ["not-a-number", "-5", "0", ""])
    def test_invalid_env_falls_back(self, monkeypatch, bad):
        monkeypatch.setenv(ENV_MAX_BULK_SAMPLES, bad)
        assert max_bulk_samples() == DEFAULT_MAX_BULK_SAMPLES


class TestBulkRowsBudget:
    def test_narrow_fleet_keeps_batch_size(self, monkeypatch):
        monkeypatch.delenv(ENV_MAX_BULK_SAMPLES, raising=False)
        # 30 total columns: the default budget is far beyond
        # batch_size rows, so the row-axis contract stands
        assert bulk_rows_budget(30, 1000) == 1000

    def test_wide_fleet_shrinks_rows(self, monkeypatch):
        monkeypatch.setenv(ENV_MAX_BULK_SAMPLES, "10000")
        # 10k machines x 5 tags: 10000 // 50000 -> min 1 row per round
        assert bulk_rows_budget(50_000, 1000) == 1
        # 100 columns -> 100 rows per round
        assert bulk_rows_budget(100, 1000) == 100

    def test_progress_is_always_possible(self, monkeypatch):
        monkeypatch.setenv(ENV_MAX_BULK_SAMPLES, "1")
        assert bulk_rows_budget(10_000_000, 1000) == 1

    def test_zero_columns_degenerate(self):
        assert bulk_rows_budget(0, 250) == 250
        assert bulk_rows_budget(-3, 250) == 250

    def test_budget_never_exceeded(self, monkeypatch):
        monkeypatch.setenv(ENV_MAX_BULK_SAMPLES, "7777")
        for cols in (1, 3, 77, 1000, 7777, 20000):
            rows = bulk_rows_budget(cols, 10_000)
            assert rows * cols <= 7777 or rows == 1
