"""Mid-fit checkpoint/resume tests (SURVEY.md §6.4: an addition over the
reference, which only checkpoints at the artifact level)."""

import numpy as np
import pytest

import gordo_tpu.models.factories  # noqa: F401 — registers factories
from gordo_tpu.registry import lookup_factory
from gordo_tpu.train.checkpoint import fit_checkpointed, load_checkpoint
from gordo_tpu.train.fit import TrainConfig, fit

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow


@pytest.fixture()
def module(sine_tags):
    factory = lookup_factory("AutoEncoder", "feedforward_hourglass")
    return factory(n_features=sine_tags.shape[1],
                   n_features_out=sine_tags.shape[1])


CFG = TrainConfig(epochs=6, batch_size=128)


def _leaves_equal(a, b):
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_uninterrupted_checkpointed_fit_matches_plain_fit(
    module, sine_tags, tmp_path
):
    import jax

    plain_params, plain_hist = fit(
        module, sine_tags, sine_tags, CFG, rng=jax.random.PRNGKey(7)
    )
    ckpt_params, ckpt_hist = fit_checkpointed(
        module, sine_tags, sine_tags, CFG,
        ckpt_dir=str(tmp_path / "ck"),
        checkpoint_every=2,
        rng=jax.random.PRNGKey(7),
    )
    _leaves_equal(plain_params, ckpt_params)
    np.testing.assert_allclose(plain_hist, ckpt_hist, rtol=1e-6)


def test_resume_is_bit_identical(module, sine_tags, tmp_path):
    import jax

    full_dir = tmp_path / "full"
    full_params, _ = fit_checkpointed(
        module, sine_tags, sine_tags, CFG,
        ckpt_dir=str(full_dir), checkpoint_every=10,
        rng=jax.random.PRNGKey(7),
    )

    # interrupted run: only 2 epochs' worth of config, same seed/dir
    part_dir = str(tmp_path / "part")
    import dataclasses

    partial_cfg = dataclasses.replace(CFG, epochs=2)
    fit_checkpointed(
        module, sine_tags, sine_tags, partial_cfg,
        ckpt_dir=part_dir, checkpoint_every=2, rng=jax.random.PRNGKey(7),
    )
    assert load_checkpoint(part_dir) is not None

    # resume to the full 6 epochs
    resumed_params, resumed_hist = fit_checkpointed(
        module, sine_tags, sine_tags, CFG,
        ckpt_dir=part_dir, checkpoint_every=2, rng=jax.random.PRNGKey(7),
    )
    assert len(resumed_hist) == CFG.epochs
    _leaves_equal(full_params, resumed_params)


def test_checkpoint_files_written(module, sine_tags, tmp_path):
    ckpt = tmp_path / "files"
    fit_checkpointed(
        module, sine_tags, sine_tags,
        TrainConfig(epochs=2, batch_size=128),
        ckpt_dir=str(ckpt), checkpoint_every=1,
    )
    restored = load_checkpoint(str(ckpt))
    assert restored is not None
    assert restored[3] == 2  # epochs_done
    assert len(restored[2]) == 2  # history rides inside the checkpoint


def test_stale_checkpoint_not_reused(module, sine_tags, tmp_path):
    """A checkpoint from different data/config must be ignored, not
    silently returned (the CV-fold clone scenario)."""
    cfg = TrainConfig(epochs=2, batch_size=128)
    ckpt = str(tmp_path / "stale")
    fit_checkpointed(module, sine_tags, sine_tags, cfg, ckpt, 1)

    other = sine_tags[: len(sine_tags) // 2]
    params_other, hist = fit_checkpointed(module, other, other, cfg, ckpt, 1)
    assert len(hist) == cfg.epochs  # retrained, not skipped
    import jax

    fresh, _ = fit(module, other, other, cfg, rng=jax.random.PRNGKey(0))
    _leaves_equal(params_other, fresh)


def test_checkpoint_every_validation(module, sine_tags, tmp_path):
    with pytest.raises(ValueError):
        fit_checkpointed(
            module, sine_tags, sine_tags,
            TrainConfig(epochs=2, batch_size=128),
            ckpt_dir=str(tmp_path / "x"), checkpoint_every=0,
        )


def test_profiling_trace_noop_and_active(tmp_path, monkeypatch):
    import jax.numpy as jnp

    from gordo_tpu.utils import profiling

    # no env var → no-op
    monkeypatch.delenv(profiling.ENV_VAR, raising=False)
    with profiling.trace("noop"):
        pass

    monkeypatch.setenv(profiling.ENV_VAR, str(tmp_path))
    with profiling.trace("section"):
        jnp.ones(8).sum().block_until_ready()
    assert (tmp_path / "section").exists()




def test_estimator_checkpoint_dir_kwarg(sine_tags, tmp_path):
    from gordo_tpu.models.estimator import AutoEncoder

    est = AutoEncoder(
        epochs=3, batch_size=128,
        checkpoint_dir=str(tmp_path / "est-ck"), checkpoint_every=1,
    )
    est.fit(sine_tags)
    assert load_checkpoint(str(tmp_path / "est-ck")) is not None
    plain = AutoEncoder(epochs=3, batch_size=128).fit(sine_tags)
    _leaves_equal(est.params_, plain.params_)


def test_overtrained_checkpoint_discarded(module, sine_tags, tmp_path):
    """A checkpoint with more epochs done than the current budget must be
    discarded (the fingerprint excludes epochs, so it would otherwise match)
    and the fit retrained to exactly cfg.epochs."""
    import jax

    ckpt = str(tmp_path / "over")
    fit_checkpointed(
        module, sine_tags, sine_tags, CFG, ckpt, 2, rng=jax.random.PRNGKey(7)
    )  # 6 epochs done

    import dataclasses

    smaller = dataclasses.replace(CFG, epochs=4)
    params, hist = fit_checkpointed(
        module, sine_tags, sine_tags, smaller, ckpt, 2,
        rng=jax.random.PRNGKey(7),
    )
    assert len(hist) == smaller.epochs
    fresh, _ = fit(module, sine_tags, sine_tags, smaller,
                   rng=jax.random.PRNGKey(7))
    _leaves_equal(params, fresh)


def test_crash_between_renames_falls_back_to_old(module, sine_tags, tmp_path):
    """Simulate a crash after the previous payload was moved aside but
    before the new one landed: load_checkpoint must restore the .old
    payload instead of silently retraining from scratch."""
    import os

    ckpt = str(tmp_path / "crash")
    cfg = TrainConfig(epochs=2, batch_size=128)
    fit_checkpointed(module, sine_tags, sine_tags, cfg, ckpt, 1)

    final = os.path.join(ckpt, "ckpt")
    os.replace(final, final + ".old")  # the mid-save crash window
    restored = load_checkpoint(ckpt)
    assert restored is not None
    assert restored[3] == 2  # epochs_done from the moved-aside payload
