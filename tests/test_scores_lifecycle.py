"""Score-archive lifecycle (r20): compaction, retention, aggregation
pushdown.

Fast lane: the batch-plane surfaces — plan/compact byte-consistency,
aggregate correctness against a numpy reference and byte-identity
across compaction, gc retention semantics, the ls/stat inspection
documents, and the ``gordo scores`` CLI (pure host-side I/O, no model
build).  Slow lane (``TestScoresAggregateRoute``): the
``/scores/aggregate`` server route over a real built project — GSB1
columnar parity with the local aggregate, ``client.score_summary``
end-to-end, input validation, and the no-archive 404.
"""

import json
import os
import shutil

import numpy as np
import pandas as pd
import pytest
from click.testing import CliRunner

from gordo_tpu.batch import (
    AGGREGATE_STATS,
    ArchiveError,
    ScoreArchive,
    compact_scores,
    gc_scores,
    ls_scores,
    plan_compaction,
    stat_scores,
)
from gordo_tpu.cli.cli import gordo

MACHINES = ["m-a", "m-b"]
N_TAGS = 3
ROWS = 48  # x 10min = one 8h chunk
N_CHUNKS = 6  # 2 days -> 2 daily periods of 3 chunks
STEP_NS = 600_000_000_000
T0_NS = int(
    np.datetime64("2020-01-01").astype("datetime64[ns]").astype(np.int64)
)
SPAN_NS = ROWS * STEP_NS


def _build(root) -> ScoreArchive:
    arch = ScoreArchive.create(
        str(root), project="lc", start="2020-01-01", end="2020-01-03",
        resolution="10min", chunk_rows=ROWS, n_chunks=N_CHUNKS,
        dtype="float32", machines=MACHINES,
    )
    for c in range(N_CHUNKS):
        per = {}
        for i, m in enumerate(MACHINES):
            rng = np.random.default_rng(100 * c + i)
            per[m] = {
                "index-ns": (
                    T0_NS + c * SPAN_NS
                    + STEP_NS * np.arange(ROWS, dtype=np.int64)
                ),
                "total-anomaly-score": rng.random(ROWS, np.float32) * 3,
                "tag-anomaly-scores": rng.random((ROWS, N_TAGS), np.float32),
                "tags": [f"t{j}" for j in range(N_TAGS)],
            }
        arch.write_chunk(c, per)
    return arch


def _reads(arch):
    return {
        m: tuple(
            arch.read_machine(m)[k].tobytes()
            for k in ("index-ns", "total-anomaly-score",
                      "tag-anomaly-scores")
        )
        for m in MACHINES
    }


@pytest.fixture()
def archive(tmp_path):
    return _build(tmp_path)


class TestCompaction:
    def test_plan_names_closed_daily_partitions(self, archive):
        cp = plan_compaction(archive.directory.rsplit("/.gordo", 1)[0])
        keys = sorted(cp["eligible"])
        assert keys == ["20200101T000000", "20200102T000000"]
        for info in cp["eligible"].values():
            assert len(info["segments"]) == 3

    def test_reads_byte_identical_across_compaction(self, tmp_path):
        arch = _build(tmp_path)
        pre = _reads(arch)
        summary = compact_scores(str(tmp_path))
        assert summary["periods-compacted"] == 2
        assert summary["segments-merged"] == 6
        assert _reads(arch) == pre
        kinds = [s["kind"] for s in ls_scores(str(tmp_path))["segments"]]
        assert kinds == ["period", "period"]

    def test_aggregate_byte_identical_across_compaction(self, tmp_path):
        arch = _build(tmp_path)
        pre = arch.aggregate(stats=list(AGGREGATE_STATS), period="12h")
        compact_scores(str(tmp_path))
        post = arch.aggregate(stats=list(AGGREGATE_STATS), period="12h")
        assert pre["periods"] == post["periods"]
        for key in pre["stats"]:
            assert (
                pre["stats"][key].tobytes() == post["stats"][key].tobytes()
            ), key

    def test_second_run_is_a_no_op(self, tmp_path):
        _build(tmp_path)
        compact_scores(str(tmp_path))
        again = compact_scores(str(tmp_path))
        assert again["periods-compacted"] == 0
        assert again["segments-merged"] == 0

    def test_single_segment_partitions_are_not_churned(self, tmp_path):
        _build(tmp_path)
        # at an 8h partition every period holds exactly one segment;
        # rewriting those is churn, not compaction
        summary = compact_scores(str(tmp_path), period="8h")
        assert summary["periods-compacted"] == 0

    def test_dry_run_reports_without_writing(self, tmp_path):
        arch = _build(tmp_path)
        before = sorted(os.listdir(arch.directory))
        summary = compact_scores(str(tmp_path), dry_run=True)
        assert summary["dry-run"] is True
        assert sorted(summary["eligible"]) == [
            "20200101T000000", "20200102T000000"
        ]
        assert sorted(os.listdir(arch.directory)) == before


class TestAggregate:
    def test_matches_numpy_reference(self, archive):
        agg = archive.aggregate(period="12h", threshold=1.0)
        ns, tot = archive._machine_series(MACHINES[0])
        pid = ns // int(pd.Timedelta("12h").value)
        for j, p in enumerate(np.unique(pid)):
            rows = tot[pid == p]
            assert agg["stats"]["count"][0, j] == rows.size
            assert agg["stats"]["max"][0, j] == rows.max()
            assert abs(
                agg["stats"]["mean"][0, j]
                - rows.astype(np.float64).mean()
            ) < 1e-12
            assert agg["stats"]["exceed"][0, j] == int((rows > 1.0).sum())

    def test_percentiles_are_sketch_upper_bounds(self, archive):
        agg = archive.aggregate(period="12h", stats=["p50", "p99"])
        ns, tot = archive._machine_series(MACHINES[0])
        pid = ns // int(pd.Timedelta("12h").value)
        rows = tot[pid == pid.min()]
        for stat, q in (("p50", 0.5), ("p99", 0.99)):
            got = agg["stats"][stat][0, 0]
            exact = np.quantile(rows, q)
            # half-octave histogram: the reported value is the upper
            # edge of the bucket holding the exact percentile
            assert exact <= got <= exact * np.sqrt(2) * 1.01, (stat, got)

    def test_machine_subset_and_window(self, archive):
        agg = archive.aggregate(
            machines=["m-b"], start="2020-01-01", end="2020-01-02",
            period="12h",
        )
        assert agg["machines"] == ["m-b"]
        assert len(agg["periods"]) == 2
        assert agg["stats"]["count"].shape == (1, 2)
        assert (agg["stats"]["count"] == 3 * ROWS // 2).all()

    def test_unknown_machine_reads_empty(self, archive):
        agg = archive.aggregate(machines=["nope"], period="12h")
        assert (agg["stats"]["count"] == 0).all()
        assert np.isnan(agg["stats"]["mean"]).all()

    def test_bad_stat_and_period_refused(self, archive):
        with pytest.raises(ValueError, match="unknown aggregate stat"):
            archive.aggregate(stats=["p0"])
        with pytest.raises(ValueError, match="positive"):
            archive.aggregate(period="0h")


class TestRetention:
    NOW = pd.Timestamp("2020-01-05", tz="UTC").timestamp()

    def test_gc_prunes_aged_out_periods(self, tmp_path):
        arch = _build(tmp_path)
        compact_scores(str(tmp_path))
        g = gc_scores(str(tmp_path), keep_days=3, now=self.NOW)
        assert g["segments-deleted"] == 1
        assert g["periods-pruned"] == 1
        kept = arch.read_machine(MACHINES[0])
        assert kept["index-ns"].min() >= pd.Timestamp(
            "2020-01-02", tz="UTC"
        ).value
        # the completion ledger survives: a backfill resume must not
        # re-score (and resurrect) the retired window
        assert arch.completed_chunks(0) == set(range(N_CHUNKS))

    def test_gc_prunes_uncompacted_chunk_segments(self, tmp_path):
        arch = _build(tmp_path)
        g = gc_scores(str(tmp_path), keep_days=3, now=self.NOW)
        assert g["chunks-pruned"] == 3
        assert g["segments-deleted"] == 3
        assert stat_scores(str(tmp_path))["chunks-pruned"] == 3
        assert arch.read_machine(MACHINES[0])["index-ns"].size == 3 * ROWS

    def test_gc_refuses_keep_below_one_day(self, tmp_path):
        _build(tmp_path)
        with pytest.raises(ValueError, match="keep"):
            gc_scores(str(tmp_path), keep_days=0.5)

    def test_gc_noop_inside_retention_window(self, tmp_path):
        arch = _build(tmp_path)
        pre = _reads(arch)
        g = gc_scores(str(tmp_path), keep_days=365, now=self.NOW)
        assert g["segments-deleted"] == 0
        assert _reads(arch) == pre


class TestInspection:
    def test_ls_reports_kind_rows_bytes(self, tmp_path):
        arch = _build(tmp_path)
        listing = ls_scores(str(tmp_path))["segments"]
        assert len(listing) == N_CHUNKS
        assert {s["kind"] for s in listing} == {"chunk"}
        assert all(s["bytes"] > 0 for s in listing)
        compact_scores(str(tmp_path))
        listing = ls_scores(str(tmp_path))["segments"]
        assert [s["kind"] for s in listing] == ["period", "period"]
        assert all(
            s["rows"] == 3 * ROWS * len(MACHINES) for s in listing
        )
        assert arch.read_machine(MACHINES[0]) is not None

    def test_stat_tracks_lifecycle_state(self, tmp_path):
        _build(tmp_path)
        st = stat_scores(str(tmp_path))
        assert st["pending-compaction"] == 2
        assert st["by-kind"]["chunk"]["segments"] == N_CHUNKS
        compact_scores(str(tmp_path))
        st = stat_scores(str(tmp_path))
        assert st["pending-compaction"] == 0
        assert st["periods"] == ["20200101T000000", "20200102T000000"]
        assert st["by-kind"]["period"]["segments"] == 2

    def test_no_archive_refused(self, tmp_path):
        with pytest.raises(ArchiveError):
            ls_scores(str(tmp_path))


class TestScoresCli:
    def test_compact_stat_ls_gc_round_trip(self, tmp_path):
        _build(tmp_path)
        runner = CliRunner()
        root = str(tmp_path)

        r = runner.invoke(
            gordo, ["scores", "compact", "--dir", root, "--dry-run"]
        )
        assert r.exit_code == 0, r.output
        assert sorted(json.loads(r.output)["eligible"]) == [
            "20200101T000000", "20200102T000000"
        ]

        r = runner.invoke(gordo, ["scores", "compact", "--dir", root])
        assert r.exit_code == 0, r.output
        assert json.loads(r.output)["periods-compacted"] == 2

        r = runner.invoke(gordo, ["scores", "stat", "--dir", root])
        assert r.exit_code == 0, r.output
        assert json.loads(r.output)["pending-compaction"] == 0

        r = runner.invoke(gordo, ["scores", "ls", "--dir", root])
        assert r.exit_code == 0, r.output
        assert len(json.loads(r.output)["segments"]) == 2

        r = runner.invoke(
            gordo, ["scores", "gc", "--dir", root, "--keep", "0.5"]
        )
        assert r.exit_code != 0
        assert "keep" in r.output

    def test_missing_archive_is_a_clean_error(self, tmp_path):
        runner = CliRunner()
        for cmd in ("compact", "gc", "ls", "stat"):
            r = runner.invoke(
                gordo, ["scores", cmd, "--dir", str(tmp_path)]
            )
            assert r.exit_code != 0
            assert "no score archive" in r.output


# ---------------------------------------------------------------------------
# slow lane: the /scores/aggregate route over a real built project
# ---------------------------------------------------------------------------

PROJECT = {
    "machines": [{
        "name": "machine-a",
        "dataset": {
            "type": "RandomDataset",
            "tags": ["tag-1", "tag-2"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-27T06:00:00Z",
        },
    }],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {"gordo_tpu.models.estimator.AutoEncoder": {
                                "kind": "feedforward_hourglass",
                                "epochs": 1,
                                "batch_size": 64,
                            }},
                        ]
                    }
                }
            }
        }
    },
}


@pytest.fixture(scope="module")
def served_archive(tmp_path_factory):
    """A built 1-machine project whose model dir also holds a score
    archive — the layout ``run-server --model-dir`` discovers, with the
    archive riding along as the aggregate route's source."""
    from gordo_tpu.builder import build_project
    from gordo_tpu.workflow import NormalizedConfig

    out = str(tmp_path_factory.mktemp("scores-served"))
    result = build_project(NormalizedConfig(PROJECT, "testproj").machines, out)
    assert not result.failed
    arch = _build(out)
    return out, arch.aggregate(period="12h")


def _call(model_dir, fn):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from gordo_tpu.serve import ModelCollection, build_app

    async def runner():
        collection = ModelCollection.from_directory(
            model_dir, project="testproj"
        )
        client = TestClient(TestServer(build_app(collection)))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()

    return asyncio.run(runner())


@pytest.mark.slow
class TestScoresAggregateRoute:
    URL = "/gordo/v0/testproj/scores/aggregate"

    def test_columnar_parity_with_local_aggregate(self, served_archive):
        model_dir, local = served_archive
        from gordo_tpu.serve import codec

        async def fetch(client):
            resp = await client.get(
                f"{self.URL}?period=12h",
                headers={"Accept": "application/x-gordo-columnar"},
            )
            assert resp.status == 200, await resp.text()
            return codec.decode_columnar(await resp.read())

        doc = _call(model_dir, fetch)
        assert doc["machines"] == local["machines"]
        assert doc["periods"] == local["periods"]
        for mi, name in enumerate(local["machines"]):
            for stat in local["stats"]:
                got = np.asarray(doc["data"][name][stat])
                assert (
                    got.tobytes() == local["stats"][stat][mi].tobytes()
                ), (name, stat)

    def test_content_negotiation(self, served_archive):
        model_dir, _ = served_archive

        async def fetch(client):
            statuses = {}
            for accept in ("application/json", "application/x-msgpack",
                           "application/x-gordo-columnar"):
                resp = await client.get(
                    f"{self.URL}?period=12h",
                    headers={"Accept": accept},
                )
                statuses[accept] = resp.status
            return statuses

        assert set(_call(model_dir, fetch).values()) == {200}

    def test_bad_inputs_are_400(self, served_archive):
        model_dir, _ = served_archive

        async def fetch(client):
            out = []
            for query in ("?period=0d", "?stats=bogus", "?threshold=x"):
                resp = await client.get(self.URL + query)
                out.append(resp.status)
            return out

        assert _call(model_dir, fetch) == [400, 400, 400]

    def test_client_score_summary_end_to_end(self, served_archive):
        import asyncio

        from aiohttp import web as aioweb

        from gordo_tpu.client.client import Client
        from gordo_tpu.serve import ModelCollection, build_app

        model_dir, local = served_archive

        async def run():
            collection = ModelCollection.from_directory(
                model_dir, project="testproj"
            )
            runner = aioweb.AppRunner(build_app(collection))
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            try:
                client = Client(
                    project="testproj", host="127.0.0.1", port=port,
                    scheme="http",
                )
                return await client._with_session(
                    client.score_summary_async, ["m-a"], None, None,
                    ["count", "p99"], "12h", 1.0,
                )
            finally:
                await runner.cleanup()

        doc = asyncio.run(run())
        assert doc["machines"] == ["m-a"]
        got = np.asarray(doc["data"]["m-a"]["p99"])
        assert got.tobytes() == local["stats"]["p99"][0].tobytes()

    def test_404_without_archive(self, served_archive, tmp_path_factory):
        model_dir, _ = served_archive
        bare = str(tmp_path_factory.mktemp("scores-bare"))
        for entry in os.listdir(model_dir):
            if entry == ".gordo-scores":
                continue
            src = os.path.join(model_dir, entry)
            dst = os.path.join(bare, entry)
            if os.path.isdir(src):
                shutil.copytree(src, dst)
            else:
                shutil.copy2(src, dst)

        async def fetch(client):
            resp = await client.get(self.URL)
            return resp.status

        assert _call(bare, fetch) == 404
