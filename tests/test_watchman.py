"""Watchman tests — polling against a real in-process ML server (the
reference mocked kubernetes; we have no k8s layer to mock, the server
list is explicit config).

Deliberately UNMARKED slow (~17s): the fast CI lane keeps the watchman
discovery/eviction surface because it has no other smoke coverage there;
the heavier integration modules (fleet, client, cli, ...) carry the
``slow`` marker instead."""

import asyncio

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from gordo_tpu.builder import build_project
from gordo_tpu.serve import ModelCollection, build_app
from gordo_tpu.watchman import Watchman, build_watchman_app
from gordo_tpu.workflow import NormalizedConfig

PROJECT = {
    "machines": [
        {"name": "wm-machine", "dataset": {
            "type": "RandomDataset",
            "tags": ["w-1", "w-2"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-26T06:00:00Z",
        }},
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {"gordo_tpu.models.estimator.AutoEncoder": {
                                "kind": "feedforward_hourglass",
                                "epochs": 1,
                                "batch_size": 64,
                            }},
                        ]
                    }
                }
            }
        }
    },
}


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("wm-artifacts")
    result = build_project(NormalizedConfig(PROJECT, "wmproj").machines, str(out))
    assert not result.failed
    return str(out)


def test_watchman_aggregates_status(model_dir):
    async def main():
        # real ML server on an ephemeral port
        collection = ModelCollection.from_directory(model_dir, project="wmproj")
        runner = web.AppRunner(build_app(collection))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]

        watchman = Watchman(
            "wmproj",
            machines=["wm-machine", "missing-machine"],
            target_base_urls=[f"http://127.0.0.1:{port}"],
            poll_interval=3600,  # poll on demand only
        )
        client = TestClient(TestServer(build_watchman_app(watchman)))
        await client.start_server()
        try:
            resp = await client.get("/")
            assert resp.status == 200
            body = await resp.json()
        finally:
            await client.close()
            await runner.cleanup()
        return body

    body = asyncio.run(main())
    assert body["project-name"] == "wmproj"
    by_name = {e["target-name"]: e for e in body["endpoints"]}
    assert by_name["wm-machine"]["healthy"] is True
    assert (
        by_name["wm-machine"]["endpoint-metadata"]["metadata"]["name"]
        == "wm-machine"
    )
    assert by_name["missing-machine"]["healthy"] is False
    assert by_name["missing-machine"]["endpoint-metadata"] == {}


def test_watchman_healthcheck():
    async def main():
        watchman = Watchman("p", [], [], poll_interval=3600)
        client = TestClient(TestServer(build_watchman_app(watchman)))
        await client.start_server()
        try:
            resp = await client.get("/healthcheck")
            return resp.status
        finally:
            await client.close()

    assert asyncio.run(main()) == 200


def test_watchman_metrics_merges_targets_and_self(model_dir):
    """Watchman's /metrics is the fleet scrape surface: target servers'
    expositions merge under instance=<base_url> labels alongside
    watchman's own series as instance="watchman"."""
    from aiohttp import web

    from gordo_tpu.serve import ModelCollection, build_app

    async def main():
        collection = ModelCollection.from_directory(model_dir, project="wm")
        ml_runner = web.AppRunner(build_app(collection))
        await ml_runner.setup()
        site = web.TCPSite(ml_runner, "127.0.0.1", 0)
        await site.start()
        port = ml_runner.addresses[0][1]
        base = f"http://127.0.0.1:{port}"
        watchman = Watchman("wm", [], [base], poll_interval=3600)
        client = TestClient(TestServer(build_watchman_app(watchman)))
        await client.start_server()
        try:
            resp = await client.get("/metrics")
            text = await resp.text()
            return resp.status, resp.headers, text
        finally:
            await client.close()
            await ml_runner.cleanup()

    status, headers, text = asyncio.run(main())
    assert status == 200
    assert headers["X-Gordo-Scraped-Targets"] == "1"
    # the target's collection gauge arrives tagged with ITS base url
    assert 'gordo_server_machines{instance="http://127.0.0.1:' in text
    # watchman's own series ride the same document
    assert 'instance="watchman"' in text


def test_watchman_scrape_failures_are_counted_and_surfaced():
    """Satellite: a target that fails its /metrics scrape is no longer
    silent — it counts in gordo_watchman_scrape_failures_total under its
    instance label, and its last error rides the status doc's
    scrape-status block."""
    from gordo_tpu import telemetry

    dead = "http://127.0.0.1:1"  # connection refused

    async def main():
        watchman = Watchman("p", [], [dead], poll_interval=3600,
                            discover=False)
        client = TestClient(TestServer(build_watchman_app(watchman)))
        await client.start_server()
        try:
            await client.get("/metrics")  # first fan-out counts the failure
            # the second scrape's exposition includes the already-counted
            # failure series (watchman renders its own registry at
            # fan-out start)
            text = await (await client.get("/metrics")).text()
            status_doc = await (await client.get("/")).json()
            return text, status_doc
        finally:
            await client.close()

    text, status_doc = asyncio.run(main())
    counter = telemetry.REGISTRY.get("gordo_watchman_scrape_failures_total")
    assert counter.value(dead) >= 1
    # the failure series rides the merged exposition itself (as a
    # target=-labelled series under watchman's own instance label)
    assert "gordo_watchman_scrape_failures_total{target=" in text
    assert dead in status_doc["scrape-status"]
    assert status_doc["scrape-status"][dead]["last-error"]


def test_scrape_errors_clear_on_recovery(model_dir):
    """A target that failed once and then answers drops out of
    scrape-status (the dict reflects the LATEST fan-out, not history)."""
    from aiohttp import web

    from gordo_tpu.serve import ModelCollection, build_app

    async def main():
        collection = ModelCollection.from_directory(model_dir, project="wm")
        ml_runner = web.AppRunner(build_app(collection))
        await ml_runner.setup()
        site = web.TCPSite(ml_runner, "127.0.0.1", 0)
        await site.start()
        port = ml_runner.addresses[0][1]
        base = f"http://127.0.0.1:{port}"
        watchman = Watchman("wm", [], [base], poll_interval=3600,
                            discover=False)
        watchman.scrape_errors[base] = "ConnectionError: stale"
        client = TestClient(TestServer(build_watchman_app(watchman)))
        await client.start_server()
        try:
            await client.get("/metrics")
            status_doc = await (await client.get("/")).json()
            return status_doc
        finally:
            await client.close()
            await ml_runner.cleanup()

    status_doc = asyncio.run(main())
    assert status_doc["scrape-status"] == {}


def test_client_discovers_via_watchman(model_dir):
    """Reference behavior: the client gets its machine list from watchman
    and skips unhealthy endpoints."""
    from gordo_tpu.client import Client
    from gordo_tpu.watchman import Watchman, build_watchman_app

    async def main():
        collection = ModelCollection.from_directory(model_dir, project="wmproj")
        ml_runner = web.AppRunner(build_app(collection))
        await ml_runner.setup()
        ml_site = web.TCPSite(ml_runner, "127.0.0.1", 0)
        await ml_site.start()
        ml_port = ml_runner.addresses[0][1]

        watchman = Watchman(
            "wmproj",
            machines=["wm-machine", "ghost-machine"],
            target_base_urls=[f"http://127.0.0.1:{ml_port}"],
            poll_interval=3600,
        )
        wm_runner = web.AppRunner(build_watchman_app(watchman))
        await wm_runner.setup()
        wm_site = web.TCPSite(wm_runner, "127.0.0.1", 0)
        await wm_site.start()
        wm_port = wm_runner.addresses[0][1]

        try:
            client = Client(
                "wmproj", port=ml_port,
                watchman_url=f"http://127.0.0.1:{wm_port}",
            )
            import aiohttp
            async with aiohttp.ClientSession() as session:
                return await client.machine_names_async(session)
        finally:
            await wm_runner.cleanup()
            await ml_runner.cleanup()

    names = asyncio.run(main())
    assert names == ["wm-machine"]  # ghost skipped as unhealthy


def _build_extra_machine(model_dir, name):
    """Dump one more machine artifact into the project dir after startup."""
    project = {
        "machines": [{"name": name, "dataset": {
            "type": "RandomDataset",
            "tags": ["w-1", "w-2"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-26T06:00:00Z",
        }}],
        "globals": PROJECT["globals"],
    }
    # v1 on purpose: these tests delete the machine again via rmtree of
    # its per-machine dir (the mixed v1+v2 layout every reader handles)
    result = build_project(
        NormalizedConfig(project, "wmproj").machines, model_dir,
        artifact_format="v1",
    )
    assert not result.failed


def test_watchman_discovers_machines_added_mid_run(model_dir, tmp_path):
    """VERDICT weak #7: a machine appearing AFTER watchman start must be
    discovered (server project-index discovery) and served (collection
    rescan) without restarting either service."""
    import shutil

    live_dir = str(tmp_path / "live")
    shutil.copytree(model_dir, live_dir)

    async def main():
        collection = ModelCollection.from_directory(live_dir, project="wmproj")
        runner = web.AppRunner(build_app(collection))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]

        watchman = Watchman(
            "wmproj",
            machines=[],  # discovery-only: no static list at all
            target_base_urls=[f"http://127.0.0.1:{port}"],
            poll_interval=3600,
        )
        try:
            await watchman.refresh()
            first = sorted(watchman.statuses)

            # a new machine is built into the artifact dir mid-run
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None, _build_extra_machine, live_dir, "wm-late-machine"
            )
            changes = await loop.run_in_executor(None, collection.rescan)
            await watchman.refresh()
            second = sorted(watchman.statuses)
            healthy = {
                m: s.healthy for m, s in watchman.statuses.items()
            }
            return first, changes, second, healthy
        finally:
            await runner.cleanup()

    first, changes, second, healthy = asyncio.run(main())
    assert first == ["wm-machine"]  # discovered with zero config
    assert changes["added"] == ["wm-late-machine"]
    assert second == ["wm-late-machine", "wm-machine"]
    assert healthy["wm-late-machine"] is True


def test_collection_rescan_reloads_rebuilt_and_drops_removed(model_dir, tmp_path):
    import os
    import shutil
    import time as time_mod

    from gordo_tpu import artifacts

    # v1 per-machine-dir semantics under test (mtime reload, rmtree
    # removal): export a v1 view of the pack-default build output
    live_dir = str(tmp_path / "live2")
    artifacts.unpack(model_dir, live_dir)
    collection = ModelCollection.from_directory(live_dir, project="wmproj")
    old_model = collection.get("wm-machine").model

    # rebuild in place: newer mtime on the model file must reload the entry
    model_file = os.path.join(live_dir, "wm-machine", "model.pkl")
    os.utime(model_file, (time_mod.time() + 5, time_mod.time() + 5))
    changes = collection.rescan()
    assert changes["reloaded"] == ["wm-machine"]
    assert collection.get("wm-machine").model is not old_model

    # removal drops the entry
    shutil.rmtree(os.path.join(live_dir, "wm-machine"))
    _build_extra_machine(live_dir, "wm-survivor")
    changes = collection.rescan()
    assert changes["removed"] == ["wm-machine"]
    assert collection.get("wm-machine") is None
    assert collection.get("wm-survivor") is not None


def test_watchman_evicts_machines_gone_from_every_index(model_dir, tmp_path):
    """VERDICT r3 missing #6: a machine REMOVED from the project must stop
    being polled/reported after ``evict_after`` responding polls — but a
    cycle where no index was reachable must not count toward eviction, and
    statically configured machines are never evicted."""
    import shutil

    from gordo_tpu import artifacts

    live_dir = str(tmp_path / "evict")
    artifacts.unpack(model_dir, live_dir)  # v1 view: rmtree removes a machine
    _build_extra_machine(live_dir, "wm-doomed")

    async def main():
        collection = ModelCollection.from_directory(live_dir, project="wmproj")
        runner = web.AppRunner(build_app(collection))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        url = f"http://127.0.0.1:{port}"

        watchman = Watchman(
            "wmproj",
            machines=["wm-machine"],  # static: survives everything
            target_base_urls=[url],
            poll_interval=3600,
            evict_after=2,
        )
        try:
            await watchman.refresh()
            assert sorted(watchman.machines) == ["wm-doomed", "wm-machine"]

            # the machine's artifact is deleted and the server rescans
            shutil.rmtree(f"{live_dir}/wm-doomed")
            collection.rescan()

            # an unreachable cycle: no index responded -> no miss counted
            watchman.target_base_urls = ["http://127.0.0.1:1"]
            await watchman.refresh()
            assert "wm-doomed" in watchman.machines

            watchman.target_base_urls = [url]
            await watchman.refresh()  # miss 1
            assert "wm-doomed" in watchman.machines
            await watchman.refresh()  # miss 2 -> evicted
            assert "wm-doomed" not in watchman.machines
            assert "wm-doomed" not in watchman.statuses
            assert "wm-machine" in watchman.machines  # static survives
            body = watchman.to_json()
            assert all(
                e["target-name"] != "wm-doomed" for e in body["endpoints"]
            )
        finally:
            await runner.cleanup()

    asyncio.run(main())
