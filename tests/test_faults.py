"""Unit tests for the fault-injection plane (``gordo_tpu.faults``): spec
grammar, seeded determinism, firing controls (rate/times/after/match),
mode translation at the client I/O seam, and the off-by-default
zero-overhead contract.  The fleet-level chaos scenarios live in
``tests/chaos/`` (slow lane)."""

import errno
import time

import pytest

from gordo_tpu import faults


@pytest.fixture(autouse=True)
def _no_ambient_plane():
    """Tests must start and end with no installed plane."""
    faults.clear()
    yield
    faults.clear()


class TestSpecGrammar:
    def test_full_spec_parses(self):
        plane = faults.parse_spec(
            "seed=7;pack.open=eio:0.5;"
            "http.request=latency:1:ms=40,times=2,after=1,match=replica-3"
        )
        assert plane.seed == 7
        (rule,) = plane.rules["http.request"]
        assert rule.mode == "latency" and rule.rate == 1.0
        assert rule.ms == 40.0 and rule.times == 2 and rule.after == 1
        assert rule.match == "replica-3"
        (eio,) = plane.rules["pack.open"]
        assert eio.rate == 0.5

    @pytest.mark.parametrize("bad", [
        "pack.open",                 # no mode
        "pack.open=",                # empty mode
        "seed=x",                    # non-integer seed
        "pack.open=eio:nope",        # non-float rate
        "pack.open=eio:1.5",         # rate out of [0,1]
        "pack.open=eio:1:frob=3",    # unknown param
        "pack.open=eio:1:ms",        # param without value
    ])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(bad)

    def test_empty_clauses_ignored(self):
        plane = faults.parse_spec(";;seed=1;;")
        assert plane.seed == 1 and plane.rules == {}


class TestFiring:
    def test_off_is_a_noop(self):
        assert not faults.enabled()
        faults.check("pack.open", pack="p")  # no plane: returns silently

    def test_modes_translate(self):
        with faults.injected("pack.open=eio"):
            with pytest.raises(OSError) as exc:
                faults.check("pack.open")
            assert exc.value.errno == errno.EIO
        with faults.injected("artifact.write=enospc"):
            with pytest.raises(OSError) as exc:
                faults.check("artifact.write")
            assert exc.value.errno == errno.ENOSPC
        with faults.injected("pack.open=corrupt"):
            with pytest.raises(faults.InjectedFault) as exc:
                faults.check("pack.open", pack="p1")
            assert exc.value.mode == "corrupt" and "p1" in exc.value.detail

    def test_latency_delays_instead_of_raising(self):
        with faults.injected("http.request=latency:1:ms=30"):
            t0 = time.monotonic()
            faults.check("http.request")
            assert time.monotonic() - t0 >= 0.025

    def test_times_after_and_match(self):
        with faults.injected(
            "replica.scatter=dead:1:after=1,times=1,match=bad-host"
        ):
            # wrong context: the rule never even counts a call
            faults.check("replica.scatter", replica="http://good-host")
            # first matching call is skipped by after=1
            faults.check("replica.scatter", replica="http://bad-host")
            with pytest.raises(faults.InjectedFault):
                faults.check("replica.scatter", replica="http://bad-host")
            # times=1 exhausted
            faults.check("replica.scatter", replica="http://bad-host")

    def test_rate_zero_never_fires(self):
        with faults.injected("pack.open=eio:0"):
            for _ in range(50):
                faults.check("pack.open")

    def test_seeded_schedule_is_deterministic(self):
        def schedule(seed):
            fired = []
            with faults.injected(f"seed={seed};pack.open=eio:0.5"):
                for i in range(64):
                    try:
                        faults.check("pack.open", i=i)
                        fired.append(0)
                    except OSError:
                        fired.append(1)
            return fired

        a, b = schedule(7), schedule(7)
        assert a == b, "same seed, same call sequence, same faults"
        assert 0 < sum(a) < 64, "rate 0.5 fires some but not all"
        assert schedule(8) != a, "a different seed reshuffles the schedule"

    def test_stats_count_calls_and_fires(self):
        with faults.injected("seed=3;pack.read=corrupt:1:times=2") as plane:
            for _ in range(5):
                try:
                    faults.check("pack.read")
                except faults.InjectedFault:
                    pass
            assert plane.stats() == {
                "pack.read:corrupt": {"calls": 5, "fired": 2}
            }

    def test_injected_restores_previous_plane(self):
        outer = faults.configure("pack.open=eio")
        with faults.injected("pack.read=corrupt"):
            assert faults.plane() is not outer
        assert faults.plane() is outer

    def test_env_configures(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_FAULTS, "seed=2;pack.open=eio")
        plane = faults.configure()
        assert plane is not None and plane.seed == 2
        monkeypatch.delenv(faults.ENV_FAULTS)
        assert faults.configure() is None


class TestClientSeam:
    """The HTTP client seam translates InjectedFault into the transport
    errors the retry loop already classifies."""

    def test_blackhole_is_a_timeout(self):
        import asyncio

        from gordo_tpu.client.io import _check_http_fault

        with faults.injected("http.request=blackhole"):
            with pytest.raises(asyncio.TimeoutError):
                _check_http_fault("POST", "http://x/anomaly")

    def test_reset_is_a_connection_error(self):
        import aiohttp

        from gordo_tpu.client.io import _check_http_fault

        with faults.injected("http.request=reset"):
            with pytest.raises(aiohttp.ClientConnectionError):
                _check_http_fault("GET", "http://x/")

    def test_http_500_is_a_bad_response(self):
        from gordo_tpu.client.io import BadGordoResponse, _check_http_fault

        with faults.injected("http.request=http_500"):
            with pytest.raises(BadGordoResponse):
                _check_http_fault("GET", "http://x/")
