"""Workflow-generator tests — assert on the generated orchestration
documents, never a live cluster (reference test pattern, SURVEY.md §5)."""

import yaml

from gordo_tpu.workflow import (
    NormalizedConfig,
    build_plan,
    generate_workflow,
    unique_tags,
    workflow_to_yaml,
)

PROJECT = {
    "machines": [
        {"name": "gen-a", "dataset": {
            "type": "RandomDataset", "tags": ["t1", "t2"],
            "train_start_date": "2017-01-01T00:00:00Z",
            "train_end_date": "2017-01-02T00:00:00Z"}},
        {"name": "gen-b", "dataset": {
            "type": "RandomDataset", "tags": ["t2", "t3"],
            "train_start_date": "2017-01-01T00:00:00Z",
            "train_end_date": "2017-01-02T00:00:00Z"}},
        {"name": "gen-c", "dataset": {
            "type": "RandomDataset", "tags": ["t4", "t5", "t6"],
            "train_start_date": "2017-01-01T00:00:00Z",
            "train_end_date": "2017-01-02T00:00:00Z"}},
    ],
}


def _config():
    return NormalizedConfig(PROJECT, "genproj")


def test_unique_tags():
    assert unique_tags(_config().machines) == ["t1", "t2", "t3", "t4", "t5", "t6"]


def test_build_plan_buckets_by_signature():
    plan = build_plan(_config())
    assert plan["project-name"] == "genproj"
    assert plan["n_machines"] == 3
    # same default model: 2-tag machines bucket together, 3-tag separately
    assert plan["n_buckets"] == 2
    sizes = sorted(b["n_machines"] for b in plan["buckets"])
    assert sizes == [1, 2]
    two_tag = next(b for b in plan["buckets"] if b["n_machines"] == 2)
    assert sorted(two_tag["machines"]) == ["gen-a", "gen-b"]
    assert set(two_tag["cache_keys"]) == {"gen-a", "gen-b"}


def test_build_plan_reports_fetch_dedup_projection():
    """r24: `workflow plan` surfaces the ingest plane's fetch dedup —
    the operator sees the provider-fetch bill before building."""
    import copy

    project = copy.deepcopy(PROJECT)
    # twin of gen-a: identical dataset config, distinct name
    project["machines"].append(
        {"name": "gen-a-twin",
         "dataset": dict(project["machines"][0]["dataset"])}
    )
    plan = build_plan(NormalizedConfig(project, "genproj"))
    assert plan["ingest"] == {
        "distinct_dataset_fingerprints": 3,
        "dedup_hits": 1,
        "fetch_dedup_ratio": 0.25,
    }
    # no twins → no projected dedup
    assert build_plan(_config())["ingest"]["dedup_hits"] == 0


def test_build_plan_respects_max_bucket_size():
    plan = build_plan(_config(), max_bucket_size=1)
    assert plan["n_buckets"] == 3
    assert all(b["n_machines"] == 1 for b in plan["buckets"])


def _ragged_project(n_filtered=3, n_plain=2):
    """A bucket whose configs predict multiple distinct train lengths:
    row-filtered machines (each an unpredictable length) riding with
    uniform-window plain ones."""
    return {
        "machines": [
            {"name": f"rg-f-{i}", "dataset": {
                "type": "RandomDataset", "tags": ["t1", "t2"],
                "train_start_date": "2017-01-01T00:00:00Z",
                "train_end_date": "2017-01-02T00:00:00Z",
                "row_filter": f"`t1` > 0.{i}"}}
            for i in range(n_filtered)
        ] + [
            {"name": f"rg-p-{i}", "dataset": {
                "type": "RandomDataset", "tags": ["t1", "t2"],
                "train_start_date": "2017-01-01T00:00:00Z",
                "train_end_date": "2017-01-02T00:00:00Z"}}
            for i in range(n_plain)
        ],
    }


def test_build_plan_warns_on_predicted_ragged_compiles():
    """Neither align_lengths nor pad_lengths + length-diverse configs →
    the plan must carry the estimated compile bill (ADVICE r5 item 5,
    warning-only slice: explicit, not silent)."""
    plan = build_plan(NormalizedConfig(_ragged_project(), "rgproj"))
    warning = plan["ragged_compile_warning"]
    # 3 row-filtered (one predicted length each) + 1 shared plain window
    # = 4 predicted lengths in 1 bucket → 3 compiles beyond the floor
    assert warning["estimated_distinct_lengths"] == 4
    assert warning["estimated_extra_compiles"] == 3
    assert warning["estimated_extra_compile_seconds"] > 0
    assert "align_lengths" in warning["hint"]


def test_build_plan_warning_silenced_by_length_strategy():
    cfg = NormalizedConfig(_ragged_project(), "rgproj")
    aligned = build_plan(cfg, align_lengths=256)
    assert "ragged_compile_warning" not in aligned
    assert aligned["align_lengths"] == 256
    padded = build_plan(cfg, pad_lengths=128)
    assert "ragged_compile_warning" not in padded
    assert padded["pad_lengths"] == 128
    # pad_lengths is part of the planned cache identity: keys must differ
    # from an exact-mode plan's (they'd never match the registry entries
    # a padded build writes)
    exact = build_plan(cfg)
    bucket_p = padded["buckets"][0]["cache_keys"]
    bucket_e = exact["buckets"][0]["cache_keys"]
    assert all(bucket_p[m] != bucket_e[m] for m in bucket_p)


def test_build_plan_uniform_project_has_no_warning():
    plan = build_plan(_config())
    assert "ragged_compile_warning" not in plan


def test_generate_workflow_documents():
    docs = generate_workflow(_config())
    kinds = [d["kind"] for d in docs]
    assert kinds.count("Job") == 1              # ONE builder job, not 3 pods
    assert kinds.count("Deployment") == 2       # ml-server + watchman
    assert kinds.count("Service") == 2
    assert kinds.count("Mapping") == 4          # per-machine + stream routes
    assert kinds.count("ConfigMap") == 1        # embedded build plan

    job = next(d for d in docs if d["kind"] == "Job")
    container = job["spec"]["template"]["spec"]["containers"][0]
    assert container["command"] == ["gordo", "build-project"]
    assert "google.com/tpu" in container["resources"]["limits"]

    mappings = [d for d in docs if d["kind"] == "Mapping"]
    prefixes = {m["spec"]["prefix"] for m in mappings}
    assert "/gordo/v0/genproj/gen-a/" in prefixes

    plan_cm = next(d for d in docs if d["kind"] == "ConfigMap")
    embedded = yaml.safe_load(plan_cm["data"]["plan.yaml"])
    assert embedded["n_machines"] == 3


def test_generate_workflow_stream_route_is_sse_safe():
    """The streaming plane rides long-lived SSE connections: its Mapping
    must disable Ambassador's request timeout and stretch the idle
    timeout past the keepalive cadence, and the Services in front of the
    server/watchman must carry the LB connection-idle annotation."""
    docs = generate_workflow(_config())
    stream = next(
        d for d in docs
        if d["kind"] == "Mapping" and "stream" in d["metadata"]["name"]
    )
    assert stream["spec"]["prefix"] == "/gordo/v0/genproj/stream"
    assert stream["spec"]["timeout_ms"] == 0
    assert stream["spec"]["idle_timeout_ms"] == 86_400_000
    assert stream["spec"]["service"].startswith("gordo-ml-server")

    # per-machine mappings keep their request timeouts — only the
    # stream route is exempt
    for m in (d for d in docs if d["kind"] == "Mapping"):
        if m is not stream:
            assert "timeout_ms" not in m["spec"]

    for svc in (d for d in docs if d["kind"] == "Service"):
        annotations = svc["metadata"]["annotations"]
        key = (
            "service.beta.kubernetes.io/"
            "aws-load-balancer-connection-idle-timeout"
        )
        assert annotations[key] == "3600"


def test_generate_argo_workflow_dag_per_chunk():
    """The Argo shim: one Workflow doc, a DAG task per fleet chunk, each
    parameterized with its chunk's machine list and running the
    --machines-filtered build-project."""
    from gordo_tpu.workflow.generator import generate_argo_workflow

    wf = generate_argo_workflow(_config(), image="img:1", max_bucket_size=1)
    assert wf["apiVersion"] == "argoproj.io/v1alpha1"
    assert wf["kind"] == "Workflow"
    templates = {t["name"]: t for t in wf["spec"]["templates"]}
    tasks = templates["build"]["dag"]["tasks"]
    assert len(tasks) == 3  # max_bucket_size=1 -> one chunk per machine
    machine_params = sorted(
        t["arguments"]["parameters"][0]["value"] for t in tasks
    )
    assert machine_params == ["gen-a", "gen-b", "gen-c"]
    container = templates["build-chunk"]["container"]
    assert container["image"] == "img:1"
    assert container["command"] == ["gordo", "build-project"]
    assert "--machines" in container["args"]
    # chunk tasks are independent — Argo parallelizes them
    assert all("dependencies" not in t for t in tasks)

    # multi-machine chunks carry comma-joined names
    wf2 = generate_argo_workflow(_config(), max_bucket_size=512)
    tasks2 = {
        t["arguments"]["parameters"][0]["value"]
        for t in wf2["spec"]["templates"][0]["dag"]["tasks"]
    }
    assert "gen-a,gen-b" in tasks2


def test_workflow_yaml_roundtrip():
    docs = generate_workflow(_config())
    parsed = list(yaml.safe_load_all(workflow_to_yaml(docs)))
    assert len(parsed) == len(docs)
    assert parsed[0]["kind"] == "Job"


def test_server_deployment_args_and_warmup_default():
    """The ml-server Deployment warms up by default (pods must not serve
    cold-compile responses after a reschedule) and carries user-supplied
    extra run-server flags."""
    docs = generate_workflow(
        _config(), server_args=["--coalesce-ms", "2", "--model-parallel"]
    )
    dep = next(
        d for d in docs
        if d["kind"] == "Deployment"
        and d["metadata"]["name"].startswith("gordo-server-")
    )
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--warmup" in args
    i = args.index("--coalesce-ms")
    assert args[i: i + 3] == ["--coalesce-ms", "2", "--model-parallel"]


def test_generate_workflow_multihost_indexed_job():
    """--multihost N: the builder becomes an N-pod Indexed Job wired with
    the GORDO_* env contract and a headless Service giving pod 0 a stable
    coordinator DNS name."""
    docs = generate_workflow(_config(), multihost=2)
    job = next(d for d in docs if d["kind"] == "Job")
    assert job["spec"]["completionMode"] == "Indexed"
    assert job["spec"]["completions"] == 2
    assert job["spec"]["parallelism"] == 2
    pod = job["spec"]["template"]["spec"]
    assert pod["subdomain"] == "gordo-builder-genproj"
    env = {
        e["name"]: e["value"]
        for e in pod["containers"][0]["env"]
    }
    assert env["GORDO_NUM_PROCESSES"] == "2"
    assert env["GORDO_PROCESS_ID"] == "$(JOB_COMPLETION_INDEX)"
    assert env["GORDO_COORDINATOR"].startswith("gordo-builder-genproj-0.")
    # the headless service exists and has no cluster VIP
    headless = next(
        d for d in docs
        if d["kind"] == "Service"
        and d["metadata"]["name"] == "gordo-builder-genproj"
    )
    assert headless["spec"]["clusterIP"] == "None"


def test_generate_workflow_multihost_one_process_is_plain_job():
    docs = generate_workflow(_config(), multihost=1)
    job = next(d for d in docs if d["kind"] == "Job")
    assert "completionMode" not in job["spec"]


def test_generate_workflow_refuses_oversharded_multihost():
    """Bugfix (ISSUE 2 satellite): N beyond the machine-shard count is a
    config error with a clear message, not a manifest with idle
    barrier-holding pods."""
    import pytest

    with pytest.raises(ValueError, match="machine-shard count"):
        generate_workflow(_config(), multihost=4)  # only 3 machines
    with pytest.raises(ValueError, match="multihost"):
        generate_workflow(_config(), multihost=0)


def test_scrape_annotations_on_by_default():
    """Server and watchman pod templates carry the prometheus.io/*
    discovery annotations (their /metrics endpoints are the scrape
    surfaces) pointing at each component's own port."""
    docs = generate_workflow(_config())
    deployments = {
        d["metadata"]["name"]: d for d in docs if d["kind"] == "Deployment"
    }
    server_meta = deployments["gordo-server-genproj"]["spec"]["template"][
        "metadata"
    ]
    watchman_meta = deployments["gordo-watchman-genproj"]["spec"][
        "template"
    ]["metadata"]
    for meta, port in ((server_meta, "5555"), (watchman_meta, "5556")):
        ann = meta["annotations"]
        assert ann["prometheus.io/scrape"] == "true"
        assert ann["prometheus.io/port"] == port
        assert ann["prometheus.io/path"] == "/metrics"


def test_scrape_annotations_opt_out():
    docs = generate_workflow(_config(), scrape_annotations=False)
    for doc in docs:
        if doc["kind"] == "Deployment":
            meta = doc["spec"]["template"]["metadata"]
            assert "annotations" not in meta


def test_compile_cache_volume_on_builder_and_server():
    """Builder Job and server Deployment share one per-project compile
    cache: GORDO_COMPILE_CACHE_DIR points both at the same mounted PVC,
    so a rescheduled server loads executables the builder (or a previous
    server) already compiled (ISSUE 5 satellite)."""
    docs = generate_workflow(_config())
    job = next(d for d in docs if d["kind"] == "Job")
    dep = next(
        d for d in docs
        if d["kind"] == "Deployment"
        and d["metadata"]["name"].startswith("gordo-server-")
    )
    for doc in (job, dep):
        pod = doc["spec"]["template"]["spec"]
        container = pod["containers"][0]
        env = {e["name"]: e["value"] for e in container["env"]}
        assert env["GORDO_COMPILE_CACHE_DIR"] == "/compile-cache"
        mounts = {m["name"]: m for m in container["volumeMounts"]}
        assert mounts["compile-cache"]["mountPath"] == "/compile-cache"
        assert not mounts["compile-cache"].get("readOnly")
        volumes = {v["name"]: v for v in pod["volumes"]}
        assert volumes["compile-cache"]["persistentVolumeClaim"][
            "claimName"
        ] == "gordo-compile-cache-genproj"


def test_multihost_workers_share_the_compile_cache_path():
    """Every worker of a --multihost Indexed Job extends the builder
    template, so all N processes point at the SAME cache path and each
    fleet program compiles once per fleet, not once per process."""
    docs = generate_workflow(_config(), multihost=2)
    job = next(d for d in docs if d["kind"] == "Job")
    env = {
        e["name"]: e["value"]
        for e in job["spec"]["template"]["spec"]["containers"][0]["env"]
    }
    assert env["GORDO_COMPILE_CACHE_DIR"] == "/compile-cache"
    assert env["GORDO_NUM_PROCESSES"] == "2"
