"""bench.py CLI surface: ``--stage`` selection (the knob that lets an
operator — or scripts/tpu_first.sh on a freshly healed tunnel — run ONE
stage without paying for the rest) and ``--round`` persistence wiring.
Parsing only; the stages themselves run in the driver bench."""

import json

import pytest

import bench


def test_default_runs_every_stage_in_priority_order():
    assert bench.parse_stages([]) == [
        "build", "build_pipeline", "build_throughput", "build_ingest",
        "artifact_io", "hot_reload", "serving",
        "serving_precision", "serving_sharded", "serving_wire",
        "serving_openloop", "telemetry_overhead", "health_overhead",
        "cold_start", "multi_device", "refresh", "backfill",
        "scores_lifecycle", "streaming", "lstm",
    ]


def test_backfill_stage_selectable():
    assert bench.parse_stages(["--stage", "backfill"]) == ["backfill"]


def test_build_throughput_stage_selectable():
    assert bench.parse_stages(["--stage", "build_throughput"]) == [
        "build_throughput"
    ]


def test_build_ingest_stage_selectable():
    assert bench.parse_stages(["--stage", "build_ingest"]) == [
        "build_ingest"
    ]


def test_cold_start_stage_selectable():
    assert bench.parse_stages(["--stage", "cold_start"]) == ["cold_start"]


def test_refresh_stage_selectable():
    assert bench.parse_stages(["--stage", "refresh"]) == ["refresh"]


def test_serving_wire_stage_selectable():
    assert bench.parse_stages(["--stage", "serving_wire"]) == [
        "serving_wire"
    ]


def test_artifact_io_stage_selectable():
    assert bench.parse_stages(["--stage", "artifact_io"]) == ["artifact_io"]


def test_multi_device_stage_selectable():
    assert bench.parse_stages(["--stage", "multi_device"]) == [
        "multi_device"
    ]


def test_scores_lifecycle_stage_selectable():
    assert bench.parse_stages(["--stage", "scores_lifecycle"]) == [
        "scores_lifecycle"
    ]


def test_single_stage_selection():
    assert bench.parse_stages(["--stage", "serving_openloop"]) == [
        "serving_openloop"
    ]


def test_build_pipeline_stage_selectable():
    assert bench.parse_stages(["--stage", "build_pipeline"]) == [
        "build_pipeline"
    ]


def test_multi_stage_selection_is_canonically_ordered():
    # selection order must not reorder execution: build always precedes
    # lstm regardless of flag order
    assert bench.parse_stages(
        ["--stage", "lstm", "--stage", "build"]
    ) == ["build", "lstm"]


def test_unknown_stage_rejected():
    with pytest.raises(SystemExit):
        bench.parse_stages(["--stage", "nope"])


def test_round_flag_and_env(monkeypatch):
    monkeypatch.delenv("BENCH_ROUND", raising=False)
    assert bench.parse_cli([])[1] is None
    assert bench.parse_cli(["--round", "9"])[1] == 9
    monkeypatch.setenv("BENCH_ROUND", "7")
    assert bench.parse_cli([])[1] == 7
    # explicit flag beats the env
    assert bench.parse_cli(["--round", "9"])[1] == 9


def test_persist_round_atomic_write(tmp_path, monkeypatch):
    """The round artifact lands complete via tmp+rename, and a write
    failure is loud (nonzero exit code), not silent — the r6 round file
    was referenced from CHANGES.md but never actually committed."""
    monkeypatch.setattr(bench, "_REPO_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "_ROUND", 42)
    monkeypatch.setattr(bench, "_round_write_failed", False)
    doc = {"metric": "x", "value": 1.0}
    bench.persist_round(doc)
    path = tmp_path / "BENCH_r42.json"
    assert path.exists()
    assert json.loads(path.read_text()) == doc
    assert bench.exit_code() == 0
    # no stray tmp files
    assert [p.name for p in tmp_path.iterdir()] == ["BENCH_r42.json"]

    # unwritable target -> loud failure, nonzero exit
    monkeypatch.setattr(bench, "_REPO_DIR", str(tmp_path / "nope" / "deeper"))
    bench.persist_round(doc)
    assert bench.exit_code() == 1


def test_persist_round_noop_without_round(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_REPO_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "_ROUND", None)
    monkeypatch.setattr(bench, "_round_write_failed", False)
    bench.persist_round({"metric": "x"})
    assert list(tmp_path.iterdir()) == []
    assert bench.exit_code() == 0


@pytest.mark.slow
def test_cold_start_stage_smoke(monkeypatch):
    """The CI slow-lane cold_start smoke (ISSUE 5 satellite): one trial of
    the full stage — build, forked cold/warm children, cached restart —
    must produce the acceptance fields with the gates holding on CPU."""
    monkeypatch.setenv("BENCH_COLD_TRIALS", "1")
    out = {}
    bench.bench_cold_start(out)
    assert out["cold_start_warmed_5x_ok"] is True
    assert (
        out["cold_start_unwarmed_first_request_p99_ms"]
        >= 5.0 * out["cold_start_warmed_first_request_p99_ms"]
    )
    assert out["cold_start_cached_restart_ok"] is True
    assert out["cold_start_cache_hit_metrics"], (
        "persistent-cache hits must be attested in the child's exposition"
    )


@pytest.mark.slow
def test_serving_wire_stage_smoke(monkeypatch):
    """The CI slow-lane serving_wire smoke (ISSUE 15 satellite): a tiny
    fleet, one chunk per leg — the stage must produce both wire legs,
    the speedup ratio, and the fp32 value-identity attestation. The gate
    fields exist but are only ENFORCED at full scale (--round)."""
    monkeypatch.setenv("BENCH_WIRE_MACHINES", "8")
    monkeypatch.setenv("BENCH_WIRE_CHUNKS", "1")
    monkeypatch.setenv("BENCH_WIRE_MSGPACK_CHUNKS", "1")
    monkeypatch.setenv("BENCH_WIRE_ROWS", "256")
    monkeypatch.setenv("BENCH_WIRE_REPEATS", "1")
    out = {}
    bench.bench_serving_wire(out)
    assert out["serving_wire_columnar_samples_per_sec"] > 0
    assert out["serving_wire_msgpack_samples_per_sec"] > 0
    assert out["serving_wire_speedup_vs_msgpack"] == pytest.approx(
        out["serving_wire_columnar_samples_per_sec"]
        / out["serving_wire_msgpack_samples_per_sec"],
        rel=5e-3,
    )
    assert out["serving_wire_value_identity_ok"] is True
    assert "serving_wire_ge_3x_r18_ok" in out


@pytest.mark.slow
def test_multi_device_stage_smoke(monkeypatch):
    """The CI slow-lane multi_device smoke (r22 placement plane): forked
    children over a tiny {1,2} device sweep must report the per-count
    throughput curve, the speedup map, fp32 BYTE PARITY of the sharded
    fit + scoring vs the 1-device child, per-device placement attested
    via addressable_shards, exactly one sharded executable per bucket,
    and the honesty note when the host has fewer cores than forced
    devices. The >=1.6x-at-2 gate field exists but is only meaningful
    on real multi-core/multi-chip hosts."""
    monkeypatch.setenv("BENCH_MULTI_DEVICE_COUNTS", "1,2")
    monkeypatch.setenv("BENCH_MULTI_DEVICE_MACHINES", "8")
    monkeypatch.setenv("BENCH_MULTI_DEVICE_ROWS", "256")
    monkeypatch.setenv("BENCH_MULTI_DEVICE_ROUNDS", "2")
    out = {}
    bench.bench_multi_device(out)
    assert out["multi_device_counts"] == [1, 2]
    assert out["multi_device_samples_per_sec"]["1"] > 0
    assert out["multi_device_samples_per_sec"]["2"] > 0
    assert out["multi_device_speedup_at_2"] == pytest.approx(
        out["multi_device_samples_per_sec"]["2"]
        / out["multi_device_samples_per_sec"]["1"],
        rel=5e-3,
    )
    assert "multi_device_ge_1_6x_at_2_ok" in out
    # the r22 correctness gates
    assert out["multi_device_byte_parity"] == {"2": True}
    assert out["multi_device_byte_parity_ok"] is True
    assert out["multi_device_placement_ok"] is True
    att = out["multi_device_placement"]["2"]
    assert att["fit"]["n_shards"] == 2
    assert att["fit"]["device_ids"] == [0, 1]
    assert att["score"]["n_shards"] == 2
    assert att["one_executable_per_bucket_ok"] is True


@pytest.mark.slow
def test_scores_lifecycle_stage_smoke(monkeypatch, tmp_path):
    """The CI slow-lane scores_lifecycle smoke (ISSUE 16 tentpole): a
    tiny fleet-archive run of the full stage — build, scan, compact,
    aggregate byte-identity, server pushdown vs fetch-and-aggregate,
    gc — must produce every acceptance field with the CORRECTNESS
    attestations holding. The perf-ratio gates exist but are only
    ENFORCED at full scale (--round)."""
    monkeypatch.setenv("BENCH_SCORES_MACHINES", "8")
    monkeypatch.setenv("BENCH_SCORES_CHUNK_ROWS", "256")
    monkeypatch.setenv("BENCH_SCORES_CHUNKS", "4")
    monkeypatch.setenv("BENCH_SCORES_TAGS", "3")
    monkeypatch.setenv("BENCH_SCORES_DIR", str(tmp_path))
    out = {}
    bench.bench_scores_lifecycle(out)
    assert out["scores_machines"] == 8
    assert out["scores_compact_segments_merged"] >= 2
    assert out["scores_aggregate_bytes_identical_ok"] is True
    assert out["scores_pushdown_parity_ok"] is True
    assert out["scores_pushdown_speedup"] > 0
    assert "scores_compact_ge_half_scan_ok" in out
    assert "scores_pushdown_ge_10x_ok" in out
    assert out["scores_scan_mb_per_s"] > 0
    assert out["scores_compact_mb_per_s"] > 0
