"""bench.py stage selection (``--stage``): the CLI surface that lets an
operator (or scripts/tpu_first.sh on a freshly healed tunnel) run ONE
stage — e.g. serving_openloop — without paying for the rest.  Parsing
only; the stages themselves run in the driver bench."""

import pytest

import bench


def test_default_runs_every_stage_in_priority_order():
    assert bench.parse_stages([]) == [
        "build", "serving", "serving_openloop", "telemetry_overhead",
        "lstm",
    ]


def test_single_stage_selection():
    assert bench.parse_stages(["--stage", "serving_openloop"]) == [
        "serving_openloop"
    ]


def test_multi_stage_selection_is_canonically_ordered():
    # selection order must not reorder execution: build always precedes
    # lstm regardless of flag order
    assert bench.parse_stages(
        ["--stage", "lstm", "--stage", "build"]
    ) == ["build", "lstm"]


def test_unknown_stage_rejected():
    with pytest.raises(SystemExit):
        bench.parse_stages(["--stage", "nope"])
