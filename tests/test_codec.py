"""Serving codec tests: the native fastjson kernel and the msgpack wire
format must reproduce the stdlib-JSON response contract exactly (same
schema, value-identical floats after parsing)."""

import json

import numpy as np
import pytest

from gordo_tpu.serve import codec


def test_native_fastjson_is_available():
    """cc is in the image, so the native path must actually build — a
    silent fallback to stdlib json would quietly lose the serving rate."""
    from gordo_tpu._native import load_fastjson

    assert load_fastjson() is not None


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_array_roundtrip_exact(dtype):
    rng = np.random.default_rng(0)
    a = (
        rng.standard_normal((200, 7))
        * np.power(10.0, rng.integers(-30, 30, (200, 7)))
    ).astype(dtype)
    dec = np.asarray(json.loads(codec.dumps_bytes(a)), dtype)
    assert np.array_equal(dec, a)


def test_float32_edge_values_roundtrip():
    edge = np.array(
        [
            0.0, -0.0, 1.0, -1.0, 0.1, 1e-45, -1e-45,  # subnormal min
            3.4028235e38, -3.4028235e38,               # max finite
            1.1754944e-38,                             # min normal
            123456789.0, 1e9, 9.999999e8, 99999999.5,
            1e-4, 1e-5, 2.0 ** -126,
        ],
        np.float32,
    )
    dec = np.asarray(json.loads(codec.dumps_bytes(edge)), np.float32)
    assert np.array_equal(dec, edge)
    # negative-zero sign survives
    assert np.signbit(dec[1])


def test_random_bit_patterns_roundtrip():
    rng = np.random.default_rng(7)
    bits = rng.integers(0, 2 ** 32, 200_000, dtype=np.uint64).astype(np.uint32)
    a = bits.view(np.float32)
    a = a[np.isfinite(a)]
    dec = np.asarray(json.loads(codec.dumps_bytes(a)), np.float32)
    assert np.array_equal(dec, a)


def test_special_values_match_stdlib_text():
    s = codec.dumps_bytes(np.array([np.nan, np.inf, -np.inf], np.float32))
    assert s == b"[NaN,Infinity,-Infinity]"
    assert s == json.dumps([np.nan, np.inf, -np.inf]).replace(" ", "").encode()


def test_nested_response_shape():
    rng = np.random.default_rng(1)
    obj = {
        "data": {
            "model-output": rng.standard_normal((5, 3)).astype(np.float32),
            "total-anomaly-threshold": 1.25,
            "start": ["2020-01-01T00:00:00+00:00"],
            "errors": None,
            "n": np.int64(7),
        },
        "time-seconds": 0.125,
    }
    dec = json.loads(codec.dumps_bytes(obj))
    assert dec["data"]["total-anomaly-threshold"] == 1.25
    assert dec["data"]["start"] == ["2020-01-01T00:00:00+00:00"]
    assert dec["data"]["errors"] is None
    assert dec["data"]["n"] == 7
    assert len(dec["data"]["model-output"]) == 5


def test_empty_and_1d_arrays():
    assert json.loads(codec.dumps_bytes(np.zeros(0, np.float32))) == []
    assert json.loads(codec.dumps_bytes(np.zeros((0, 4), np.float32))) == []
    assert json.loads(codec.dumps_bytes(np.zeros((3, 0), np.float32))) == [
        [], [], [],
    ]
    assert json.loads(
        codec.dumps_bytes(np.arange(3, dtype=np.float32))
    ) == [0.0, 1.0, 2.0]


def test_non_contiguous_and_int_arrays():
    a = np.arange(24, dtype=np.float32).reshape(4, 6)[:, ::2]
    assert json.loads(codec.dumps_bytes(a)) == a.tolist()
    ints = np.arange(5)  # int64: stdlib fallback path
    assert json.loads(codec.dumps_bytes(ints)) == [0, 1, 2, 3, 4]


def test_msgpack_bf16_f16_roundtrip():
    """Reduced-precision wire support (ISSUE 7 satellite): bf16 rides the
    wire under an explicit name (its numpy ``dtype.str`` is an ambiguous
    ``<V2``), f16 under its standard spelling; both decode back to the
    exact same bits."""
    import ml_dtypes

    rng = np.random.default_rng(3)
    a32 = rng.standard_normal((50, 4)).astype(np.float32)
    for dt in (np.dtype(ml_dtypes.bfloat16), np.dtype(np.float16)):
        a = a32.astype(dt)
        dec = codec.unpackb(codec.packb({"x": a}))["x"]
        assert dec.dtype == dt
        assert np.array_equal(
            dec.astype(np.float32), a.astype(np.float32)
        )


def test_json_encodes_half_precision_arrays():
    """bf16/f16 arrays JSON-encode through the native f32 kernel (the
    widening is exact) instead of erroring or crawling through tolist."""
    import ml_dtypes

    a = np.array([0.5, -1.25, 3.0], np.float32)
    for dt in (ml_dtypes.bfloat16, np.float16):
        dec = json.loads(codec.dumps_bytes(a.astype(dt)))
        assert dec == [0.5, -1.25, 3.0]


def test_unknown_wire_dtype_rejected_on_decode():
    """An ``__nd__`` header naming a dtype outside the wire set raises
    UnsupportedWireDtype (the server's 415) instead of letting numpy
    throw a 500-shaped TypeError."""
    body = codec.packb({"x": np.zeros(3, np.complex128)})
    with pytest.raises(codec.UnsupportedWireDtype):
        codec.unpackb(body)
    # a corrupt/alien dtype string likewise
    import msgpack

    evil = msgpack.packb(
        {"__nd__": True, "dtype": "not-a-dtype", "shape": [1],
         "data": b"\x00\x00\x00\x00"},
        use_bin_type=True,
    )
    with pytest.raises(codec.UnsupportedWireDtype):
        codec.unpackb(evil)


def test_negotiate_wire_dtype_param():
    """``Accept: application/x-msgpack;dtype=bfloat16`` casts float array
    leaves to the asked-for wire precision; unknown names raise (→ 415);
    non-float leaves are untouched."""
    import ml_dtypes

    obj = {
        "data": {
            "model-output": np.arange(6, dtype=np.float32).reshape(2, 3),
            "n": np.arange(3),
        }
    }
    enc, ct = codec.negotiate("application/x-msgpack;dtype=bfloat16")
    assert ct == codec.MSGPACK_CONTENT_TYPE
    dec = codec.unpackb(enc(obj))
    assert dec["data"]["model-output"].dtype == np.dtype(ml_dtypes.bfloat16)
    assert dec["data"]["n"].dtype == np.int64  # ints don't quantize
    # plain negotiate stays f32 — the default wire is full precision
    enc2, _ = codec.negotiate(codec.MSGPACK_CONTENT_TYPE)
    assert codec.unpackb(enc2(obj))["data"]["model-output"].dtype == (
        np.float32
    )
    with pytest.raises(codec.UnsupportedWireDtype):
        codec.negotiate("application/x-msgpack;dtype=int4")
    # the dtype param composes with JSON too (values round to the wire
    # precision, text stays dtype-less JSON)
    enc3, ct3 = codec.negotiate("application/json;dtype=bfloat16")
    assert ct3 == "application/json"
    out = json.loads(enc3({"x": np.array([1.0 / 3.0], np.float32)}))
    assert abs(out["x"][0] - 1.0 / 3.0) < 2e-3  # bf16-rounded


def test_msgpack_roundtrip():
    rng = np.random.default_rng(2)
    obj = {
        "data": {
            "m-1": {
                "model-output": rng.standard_normal((10, 3)).astype(np.float32),
                "total-anomaly-score": rng.standard_normal(10),
                "total-anomaly-threshold": 0.5,
            },
            "m-2": {"error": "boom"},
        },
        "time-seconds": 0.5,
    }
    dec = codec.unpackb(codec.packb(obj))
    assert np.array_equal(
        dec["data"]["m-1"]["model-output"], obj["data"]["m-1"]["model-output"]
    )
    assert dec["data"]["m-1"]["model-output"].dtype == np.float32
    assert np.array_equal(
        dec["data"]["m-1"]["total-anomaly-score"],
        obj["data"]["m-1"]["total-anomaly-score"],
    )
    assert dec["data"]["m-2"] == {"error": "boom"}
    assert dec["time-seconds"] == 0.5
