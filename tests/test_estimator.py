"""Estimator contract tests (reference strategy: tiny epochs on small random
X; assert the sklearn contract and score behavior, not accuracy)."""

import numpy as np
import pytest

from gordo_tpu.models.estimator import AutoEncoder, LSTMAutoEncoder, LSTMForecast
from gordo_tpu.ops.metrics import (
    explained_variance_score,
    mean_absolute_error,
    mean_squared_error,
    r2_score,
)


# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow

def test_metrics_against_sklearn():
    import sklearn.metrics as skm

    rng = np.random.default_rng(3)
    y = rng.standard_normal((50, 4)).astype(np.float32)
    p = y + 0.1 * rng.standard_normal((50, 4)).astype(np.float32)
    np.testing.assert_allclose(
        float(explained_variance_score(y, p)),
        skm.explained_variance_score(y, p), atol=1e-5)
    np.testing.assert_allclose(float(r2_score(y, p)), skm.r2_score(y, p), atol=1e-5)
    np.testing.assert_allclose(
        float(mean_squared_error(y, p)), skm.mean_squared_error(y, p), atol=1e-6)
    np.testing.assert_allclose(
        float(mean_absolute_error(y, p)), skm.mean_absolute_error(y, p), atol=1e-6)


def test_autoencoder_fit_predict_score(sine_tags):
    model = AutoEncoder(kind="feedforward_hourglass", epochs=30, batch_size=128,
                        learning_rate=1e-2)
    model.fit(sine_tags)
    pred = model.predict(sine_tags)
    assert pred.shape == sine_tags.shape
    score = model.score(sine_tags)
    assert score > 0.5  # sine reconstruction should be decent after 30 epochs
    # loss decreased over training
    hist = model.history_
    assert hist[-1] < hist[0]


def test_autoencoder_metadata(sine_tags):
    model = AutoEncoder(epochs=2)
    model.fit(sine_tags)
    meta = model.get_metadata()
    assert meta["kind"] == "feedforward_hourglass"
    assert meta["num_params"] > 0
    assert len(meta["history"]["loss"]) == 2
    assert meta["fit_seconds"] > 0


def test_autoencoder_clone_unfitted(sine_tags):
    model = AutoEncoder(kind="feedforward_symmetric", dims=[8, 4], epochs=1)
    clone = model.clone()
    assert clone.kind == model.kind
    assert clone.params_ is None
    with pytest.raises(RuntimeError):
        clone.predict(sine_tags)


def test_deterministic_given_seed(sine_tags):
    a = AutoEncoder(epochs=3, seed=5).fit(sine_tags).predict(sine_tags)
    b = AutoEncoder(epochs=3, seed=5).fit(sine_tags).predict(sine_tags)
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_lstm_autoencoder_offset_and_shapes(sine_tags):
    L = 6
    model = LSTMAutoEncoder(
        kind="lstm_hourglass", lookback_window=L, epochs=2, batch_size=64,
        encoding_layers=1, compression_factor=0.5,
    )
    model.fit(sine_tags)
    pred = model.predict(sine_tags)
    assert model.offset == L - 1
    assert pred.shape == (sine_tags.shape[0] - L + 1, sine_tags.shape[1])


def test_lstm_forecast_offset_and_shapes(sine_tags):
    L = 6
    model = LSTMForecast(lookback_window=L, epochs=2, batch_size=64,
                         encoding_layers=1)
    model.fit(sine_tags)
    pred = model.predict(sine_tags)
    assert model.offset == L
    assert pred.shape == (sine_tags.shape[0] - L, sine_tags.shape[1])
    assert np.isfinite(model.score(sine_tags))


def test_explicit_targets_supported(sine_tags):
    y = sine_tags[:, :2]
    model = AutoEncoder(epochs=2)
    model.fit(sine_tags, y)
    assert model.predict(sine_tags).shape == y.shape
