"""Dataset layer tests (reference strategy: RandomDataProvider as the fake
backend; filter_rows/sensor_tag unit tests; file readers on tiny fixtures)."""

import numpy as np
import pandas as pd
import pytest

from gordo_tpu.dataset import (
    GordoBaseDataset,
    RandomDataset,
    SensorTag,
    TimeSeriesDataset,
    normalize_sensor_tags,
)
from gordo_tpu.dataset.datasets import InsufficientDataError
from gordo_tpu.dataset.data_provider.providers import (
    FileSystemTagProvider,
    RandomDataProvider,
)
from gordo_tpu.dataset.filter_rows import pandas_filter_rows


# -- sensor tags --------------------------------------------------------------
def test_normalize_sensor_tags_spellings():
    tags = normalize_sensor_tags(
        ["tag-a", ["tag-b", "asset-1"], {"name": "tag-c", "asset": "asset-2"},
         SensorTag("tag-d", "asset-3")],
        asset="default-asset",
    )
    assert tags[0] == SensorTag("tag-a", "default-asset")
    assert tags[1] == SensorTag("tag-b", "asset-1")
    assert tags[2] == SensorTag("tag-c", "asset-2")
    assert tags[3] == SensorTag("tag-d", "asset-3")


def test_normalize_bad_tag_raises():
    with pytest.raises(ValueError):
        normalize_sensor_tags([{"asset": "no-name"}])


# -- filter_rows --------------------------------------------------------------
def test_filter_rows_basic():
    df = pd.DataFrame({"A": [1, -1, 2, -2], "B": [10, 20, 30, 40]})
    out = pandas_filter_rows(df, "A > 0")
    assert list(out["A"]) == [1, 2]


def test_filter_rows_compound_and_backticks():
    df = pd.DataFrame({"TAG-A": [1, 5, 10], "TAG-B": [100, 50, 10]})
    out = pandas_filter_rows(df, "`TAG-A` > 2 & `TAG-B` < 60")
    assert len(out) == 2


def test_filter_rows_buffer():
    df = pd.DataFrame({"A": [1, 1, -1, 1, 1, 1]})
    out = pandas_filter_rows(df, "A > 0", buffer_size=1)
    # row 2 filtered, rows 1 and 3 buffered away too
    assert list(out.index) == [0, 4, 5]


def test_filter_rows_rejects_dangerous():
    df = pd.DataFrame({"A": [1]})
    with pytest.raises(ValueError):
        pandas_filter_rows(df, "A.__class__")
    with pytest.raises(ValueError):
        pandas_filter_rows(df, "@pd.eval('1')")
    with pytest.raises(ValueError):
        pandas_filter_rows(df, "exec('x')")


# -- providers ----------------------------------------------------------------
def test_random_provider_deterministic():
    p = RandomDataProvider(seed=1)
    start, end = pd.Timestamp("2020-01-01", tz="UTC"), pd.Timestamp("2020-01-05", tz="UTC")
    s1 = list(p.load_series(start, end, ["tag-a", "tag-b"]))
    s2 = list(p.load_series(start, end, ["tag-a", "tag-b"]))
    assert s1[0].name == "tag-a"
    pd.testing.assert_series_equal(s1[0], s2[0])
    # different tags differ
    assert not np.allclose(s1[0].to_numpy()[:10], s1[1].to_numpy()[:10])


def test_filesystem_provider_csv(tmp_path):
    asset_dir = tmp_path / "asset-1"
    asset_dir.mkdir()
    idx = pd.date_range("2020-01-01", periods=50, freq="1h", tz="UTC")
    for tag in ["t1", "t2"]:
        pd.DataFrame({"time": idx, "value": np.arange(50.0)}).to_csv(
            asset_dir / f"{tag}.csv", index=False, header=True
        )
    p = FileSystemTagProvider(str(tmp_path), asset="asset-1")
    assert p.can_handle_tag("t1")
    series = list(
        p.load_series(idx[0], idx[10], [["t1", "asset-1"], ["t2", "asset-1"]])
    )
    assert len(series) == 2 and len(series[0]) == 10
    with pytest.raises(FileNotFoundError):
        list(p.load_series(idx[0], idx[5], ["missing-tag"]))


def test_provider_roundtrip_via_dict():
    p = RandomDataProvider(min_size=50, max_size=60, seed=3)
    d = p.to_dict()
    p2 = RandomDataProvider.from_dict(d)
    assert isinstance(p2, RandomDataProvider)
    assert p2.min_size == 50 and p2.seed == 3


# -- datasets -----------------------------------------------------------------
def test_timeseries_dataset_assembles_matrix():
    ds = TimeSeriesDataset(
        train_start_date="2020-01-01T00:00:00Z",
        train_end_date="2020-01-10T00:00:00Z",
        tag_list=["tag-a", "tag-b", "tag-c"],
        data_provider=RandomDataProvider(min_size=500, max_size=600),
        resolution="1h",
    )
    X, y = ds.get_data()
    assert list(X.columns) == ["tag-a", "tag-b", "tag-c"]
    assert X.shape == y.shape and len(X) > 10
    assert not X.isna().any().any()
    meta = ds.get_metadata()
    assert meta["resolution"] == "1h"
    assert "summary_statistics" in meta
    assert meta["data_provider"]["type"].endswith("RandomDataProvider")


def test_timeseries_dataset_row_filter():
    ds = TimeSeriesDataset(
        train_start_date="2020-01-01T00:00:00Z",
        train_end_date="2020-01-10T00:00:00Z",
        tag_list=["tag-a"],
        data_provider=RandomDataProvider(min_size=500, max_size=600),
        resolution="1h",
        row_filter="`tag-a` > -100",  # passes everything
    )
    X, _ = ds.get_data()
    assert len(X) > 0
    assert ds.get_metadata()["filtered_periods"] == 0


def test_timeseries_dataset_target_tags():
    ds = TimeSeriesDataset(
        train_start_date="2020-01-01T00:00:00Z",
        train_end_date="2020-01-10T00:00:00Z",
        tag_list=["tag-a", "tag-b"],
        target_tag_list=["tag-b"],
        data_provider=RandomDataProvider(min_size=500, max_size=600),
        resolution="1h",
    )
    X, y = ds.get_data()
    assert list(X.columns) == ["tag-a", "tag-b"]
    assert list(y.columns) == ["tag-b"]


def test_timeseries_dataset_insufficient_data():
    ds = TimeSeriesDataset(
        train_start_date="2020-01-01T00:00:00Z",
        train_end_date="2020-01-02T00:00:00Z",
        tag_list=["tag-a"],
        data_provider=RandomDataProvider(min_size=5, max_size=8),
        resolution="1h",
        n_samples_threshold=1000,
    )
    with pytest.raises(InsufficientDataError):
        ds.get_data()


def test_dataset_date_validation():
    with pytest.raises(ValueError):
        TimeSeriesDataset(
            train_start_date="2020-01-02T00:00:00Z",
            train_end_date="2020-01-01T00:00:00Z",
            tag_list=["t"],
        )


def test_dataset_from_dict_dispatch():
    ds = GordoBaseDataset.from_dict(
        {
            "type": "RandomDataset",
            "train_start_date": "2020-01-01T00:00:00Z",
            "train_end_date": "2020-01-05T00:00:00Z",
            "tag_list": ["a", "b"],
        }
    )
    assert isinstance(ds, RandomDataset)
    X, y = ds.get_data()
    assert list(X.columns) == ["a", "b"]


class TestFastResampleParity:
    """The vectorized mean-resample must match pandas bin-for-bin."""

    def _series(self, n=500, seed=0, with_nans=True):
        import numpy as np
        import pandas as pd

        rng = np.random.default_rng(seed)
        # irregular timestamps over 2 days
        ts = np.sort(rng.integers(0, 2 * 24 * 3600, size=n)) * 10**9
        base = pd.Timestamp("2020-03-01T07:13:00Z").value
        idx = pd.DatetimeIndex((base + ts).astype("datetime64[ns]")).tz_localize("UTC")
        vals = rng.standard_normal(n)
        if with_nans:
            vals[rng.integers(0, n, size=20)] = np.nan
        return pd.Series(vals, index=idx, name="t")

    def test_matches_pandas_mean(self):
        import numpy as np

        from gordo_tpu.dataset.datasets import TimeSeriesDataset

        ds = TimeSeriesDataset(
            train_start_date="2020-03-01T00:00:00Z",
            train_end_date="2020-03-04T00:00:00Z",
            tag_list=["t"],
        )
        for resolution in ("10min", "1h", "37s"):
            ds.resolution = resolution
            s = self._series()
            fast = ds._resample_one(s)
            ref = s.resample(resolution).mean()
            # bin-for-bin identical, INCLUDING empty (NaN) bins
            assert np.array_equal(
                fast.index.as_unit("ns").asi8, ref.index.as_unit("ns").asi8
            )
            np.testing.assert_allclose(
                fast.to_numpy(), ref.to_numpy(), rtol=1e-12
            )

    def test_non_utc_tz_falls_back_to_pandas(self):
        import pandas as pd

        from gordo_tpu.dataset.datasets import TimeSeriesDataset

        ds = TimeSeriesDataset(
            train_start_date="2020-03-28T00:00:00Z",
            train_end_date="2020-03-31T00:00:00Z",
            tag_list=["t"],
        )
        # Oslo series over the 2020-03-29 DST transition
        s = self._series().tz_convert("Europe/Oslo")
        got = ds._resample_one(s)
        ref = s.resample("10min").mean()
        assert got.equals(ref)
        assert str(got.index.tz) == str(ref.index.tz)

    def test_unsorted_input(self):
        import numpy as np

        from gordo_tpu.dataset.datasets import TimeSeriesDataset

        ds = TimeSeriesDataset(
            train_start_date="2020-03-01T00:00:00Z",
            train_end_date="2020-03-04T00:00:00Z",
            tag_list=["t"],
        )
        s = self._series(with_nans=False)
        shuffled = s.sample(frac=1.0, random_state=1)
        fast = ds._resample_one(shuffled)
        ref = s.resample("10min").mean().dropna()
        np.testing.assert_allclose(
            fast.dropna().to_numpy(), ref.to_numpy(), rtol=1e-12
        )

    def test_non_mean_agg_falls_back(self):
        from gordo_tpu.dataset.datasets import TimeSeriesDataset

        ds = TimeSeriesDataset(
            train_start_date="2020-03-01T00:00:00Z",
            train_end_date="2020-03-04T00:00:00Z",
            tag_list=["t"],
            aggregation_methods="max",
        )
        s = self._series(with_nans=False)
        ref = s.resample("10min").agg("max")
        got = ds._resample_one(s)
        assert got.equals(ref)


def test_iroc_bundle_provider(tmp_path):
    import numpy as np
    import pandas as pd

    from gordo_tpu.dataset.data_provider.providers import IrocBundleProvider
    from gordo_tpu.dataset.datasets import TimeSeriesDataset

    # two bundle files, three tags interleaved (the IROC many-tags-per-CSV
    # layout), one headerless
    times = pd.date_range("2020-01-01", periods=200, freq="5min", tz="UTC")
    rows = []
    for i, t in enumerate(times):
        for tag in ("iroc-a", "iroc-b", "iroc-c"):
            rows.append((tag, t.isoformat(), float(i)))
    df = pd.DataFrame(rows, columns=["tag", "timestamp", "value"])
    df.iloc[:300].to_csv(tmp_path / "bundle1.csv", index=False)
    df.iloc[300:].to_csv(tmp_path / "bundle2.csv", index=False, header=False)

    provider = IrocBundleProvider(str(tmp_path))
    series = list(
        provider.load_series(times[0], times[-1] + pd.Timedelta("1min"),
                             ["iroc-a", "iroc-b"])
    )
    assert [s.name for s in series] == ["iroc-a", "iroc-b"]
    assert all(len(s) == 200 for s in series)
    np.testing.assert_allclose(series[0].to_numpy(), np.arange(200.0))

    # through the dataset layer (resample + join)
    ds = TimeSeriesDataset(
        train_start_date=str(times[0]),
        train_end_date=str(times[-1]),
        tag_list=["iroc-a", "iroc-b", "iroc-c"],
        data_provider=provider,
        resolution="10min",
    )
    X, y = ds.get_data()
    assert X.shape[1] == 3 and len(X) > 50

    import pytest

    with pytest.raises(KeyError):
        list(provider.load_series(times[0], times[-1], ["nope"]))


def test_iroc_tag_without_window_samples_yields_empty(tmp_path):
    import pandas as pd

    from gordo_tpu.dataset.data_provider.providers import IrocBundleProvider

    times = pd.date_range("2020-01-01", periods=10, freq="1h", tz="UTC")
    rows = [("present", t.isoformat(), 1.0) for t in times]
    rows += [("early", times[0].isoformat(), 2.0)]
    pd.DataFrame(rows, columns=["tag", "timestamp", "value"]).to_csv(
        tmp_path / "b.csv", index=False
    )
    provider = IrocBundleProvider(str(tmp_path))
    # window AFTER 'early' tag's only sample
    out = list(provider.load_series(times[2], times[-1], ["present", "early"]))
    assert len(out[0]) > 0
    assert len(out[1]) == 0  # empty series, not a KeyError


# -- edge cases: empty frames, NaN runs, duplicate stamps, tz handling --------
class TestFilterRowsEdgeCases:
    def test_empty_frame_passes_through(self):
        df = pd.DataFrame(columns=["a", "b"], dtype=float)
        out = pandas_filter_rows(df, "`a` > 0")
        assert out.empty
        assert list(out.columns) == ["a", "b"]

    def test_empty_frame_with_buffer(self):
        df = pd.DataFrame(columns=["a"], dtype=float)
        assert pandas_filter_rows(df, "`a` > 0", buffer_size=3).empty

    def test_nan_rows_are_filtered_not_kept(self):
        # NaN compares False under eval — a NaN run must drop, never
        # survive into training
        df = pd.DataFrame({"a": [1.0, np.nan, np.nan, 2.0, 3.0]})
        out = pandas_filter_rows(df, "`a` > 0")
        assert list(out["a"]) == [1.0, 2.0, 3.0]

    def test_buffer_widens_around_nan_runs(self):
        df = pd.DataFrame({"a": [1.0, 2.0, np.nan, 3.0, 4.0, 5.0]})
        out = pandas_filter_rows(df, "`a` > 0", buffer_size=1)
        # the NaN's positional neighbors (rows 1 and 3) drop with it
        assert list(out["a"]) == [1.0, 4.0, 5.0]

    def test_buffer_larger_than_frame_empties_it(self):
        df = pd.DataFrame({"a": [np.nan, 1.0, 2.0]})
        out = pandas_filter_rows(df, "`a` > 0", buffer_size=10)
        assert out.empty

    def test_all_rows_filtered_keeps_schema(self):
        df = pd.DataFrame({"a": [-1.0, -2.0], "b": [1.0, 2.0]})
        out = pandas_filter_rows(df, "`a` > 0")
        assert out.empty
        assert list(out.columns) == ["a", "b"]

    def test_duplicate_timestamps_filter_positionally(self):
        stamp = pd.Timestamp("2020-01-01", tz="UTC")
        idx = pd.DatetimeIndex([stamp, stamp, stamp + pd.Timedelta("10min")])
        df = pd.DataFrame({"a": [1.0, -1.0, 2.0]}, index=idx)
        out = pandas_filter_rows(df, "`a` > 0")
        # the two rows sharing a stamp filter independently
        assert list(out["a"]) == [1.0, 2.0]
        assert out.index[0] == stamp

    def test_duplicate_timestamps_with_buffer(self):
        stamp = pd.Timestamp("2020-01-01", tz="UTC")
        idx = pd.DatetimeIndex(
            [stamp, stamp, stamp + pd.Timedelta("10min"),
             stamp + pd.Timedelta("20min")]
        )
        df = pd.DataFrame({"a": [1.0, -1.0, 2.0, 3.0]}, index=idx)
        out = pandas_filter_rows(df, "`a` > 0", buffer_size=1)
        # widening is positional (rolling over rows), so the duplicate
        # stamp's good twin and the NEXT row drop, not every same-stamp
        # row by label
        assert list(out["a"]) == [3.0]

    def test_tz_naive_and_aware_indexes_both_work(self):
        naive = pd.DataFrame(
            {"a": [1.0, -1.0]},
            index=pd.date_range("2020-01-01", periods=2, freq="10min"),
        )
        aware = naive.tz_localize("UTC")
        assert list(pandas_filter_rows(naive, "`a` > 0")["a"]) == [1.0]
        out = pandas_filter_rows(aware, "`a` > 0", buffer_size=0)
        assert list(out["a"]) == [1.0]
        assert out.index.tz is not None

    def test_multiple_expressions_and_semantics(self):
        df = pd.DataFrame({"a": [1.0, 5.0, np.nan], "b": [1.0, -1.0, 1.0]})
        out = pandas_filter_rows(df, ["`a` > 0", "`b` > 0"])
        assert list(out["a"]) == [1.0]


class TestSensorTagEdgeCases:
    def test_empty_tag_list(self):
        assert normalize_sensor_tags([]) == []

    def test_asset_inherited_by_strings_and_short_lists(self):
        tags = normalize_sensor_tags(["t1", ["t2"], ("t3",)], asset="plant")
        assert [t.asset for t in tags] == ["plant"] * 3

    def test_sensor_tag_without_asset_adopts_default(self):
        bare = SensorTag("t1")
        (out,) = normalize_sensor_tags([bare], asset="plant")
        assert out == SensorTag("t1", "plant")

    def test_sensor_tag_with_asset_keeps_its_own(self):
        tagged = SensorTag("t1", "rig")
        (out,) = normalize_sensor_tags([tagged], asset="plant")
        assert out.asset == "rig"

    def test_dict_without_name_raises(self):
        from gordo_tpu.dataset.sensor_tag import SensorTagNormalizationError

        with pytest.raises(SensorTagNormalizationError, match="name"):
            normalize_sensor_tags([{"asset": "plant"}])

    def test_overlong_list_raises(self):
        from gordo_tpu.dataset.sensor_tag import SensorTagNormalizationError

        with pytest.raises(SensorTagNormalizationError, match="must be"):
            normalize_sensor_tags([["a", "b", "c"]])

    def test_unnormalizable_type_raises(self):
        from gordo_tpu.dataset.sensor_tag import SensorTagNormalizationError

        with pytest.raises(SensorTagNormalizationError):
            normalize_sensor_tags([42])

    def test_to_list_of_strings_round_trip(self):
        from gordo_tpu.dataset.sensor_tag import to_list_of_strings

        tags = normalize_sensor_tags(
            [{"name": "t1", "asset": "a"}, "t2", ["t3", "b"]]
        )
        assert to_list_of_strings(tags) == ["t1", "t2", "t3"]
