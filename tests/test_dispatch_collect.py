"""r23 dispatch/collect split: the async build plane must be
byte-equivalent to the serial one.

Pins, in order of blast radius:

- end-to-end: pipelined (dispatch k+1 before collect k) vs serial drives
  of the same project produce byte-identical artifacts and registry
  entries, across BOTH artifact layouts (v1 dirs, v2 packs), exact and
  pad-up grouping, cold and warm-start builds;
- builder-level: the collect side's LAZY/partial D2H fetch (device-side
  fold slicing, zero-copy view handout) returns exactly the values an
  eager ``to_host`` of the full result tree yields — ``cv_metadata_``,
  ``history_``, thresholds;
- the drive loop's dispatch window and the builder's dispatch family are
  lint-enforced D2H-free (scripts/lint.py gate, tested on synthesized
  sources).

Slow lane (CI test-full job), alongside tests/test_build_pipeline.py.
"""

import pickle

import numpy as np
import pytest

from gordo_tpu import artifacts
from gordo_tpu.builder import build_project
from gordo_tpu.parallel.anomaly import FleetDiffBuilder, analyze_definition
from gordo_tpu.serializer import from_definition
from gordo_tpu.utils import disk_registry
from gordo_tpu.utils.trees import to_host
from gordo_tpu.workflow.config import Machine

from tests.test_build_pipeline import _machines, _scrub_timings, _strip_meta

pytestmark = pytest.mark.slow

DETECTOR_DEF = {
    "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.pipeline.Pipeline": {
                "steps": [
                    "gordo_tpu.ops.scalers.MinMaxScaler",
                    {
                        "gordo_tpu.models.estimator.AutoEncoder": {
                            "kind": "feedforward_hourglass",
                            "epochs": 2,
                            "batch_size": 64,
                        }
                    },
                ]
            }
        }
    }
}


def _ragged_machines(n, prefix):
    """n machines whose train windows differ by an hour each — distinct
    row counts, so pad-up mode actually pads."""
    out = []
    for i in range(n):
        hours = 20 + i
        day = 25 + (6 + hours) // 24
        hh = (6 + hours) % 24
        out.append(Machine.from_config({
            "name": f"{prefix}-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tag_list": ["a", "b", "c"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": f"2017-12-{day}T{hh:02d}:10:00Z",
            },
        }))
    return out


def _assert_v1_parity(machines, a_out, b_out):
    for m in machines:
        a, b = a_out / m.name, b_out / m.name
        assert (a / "definition.yaml").read_bytes() == (
            b / "definition.yaml"
        ).read_bytes()
        with open(a / "model.pkl", "rb") as f:
            ma = pickle.load(f)
        with open(b / "model.pkl", "rb") as f:
            mb = pickle.load(f)
        _scrub_timings(ma)
        _scrub_timings(mb)
        assert pickle.dumps(ma) == pickle.dumps(mb), m.name
        import json

        meta_a = json.loads((a / "metadata.json").read_text())
        meta_b = json.loads((b / "metadata.json").read_text())
        assert _strip_meta(meta_a) == _strip_meta(meta_b), m.name


def _assert_v2_parity(machines, a_out, b_out):
    sa = artifacts.open_store(str(a_out))
    sb = artifacts.open_store(str(b_out))
    assert sorted(sa.names()) == sorted(sb.names())
    for m in machines:
        ma, mb = sa.load_model(m.name), sb.load_model(m.name)
        _scrub_timings(ma)
        _scrub_timings(mb)
        assert pickle.dumps(ma) == pickle.dumps(mb), m.name
        assert _strip_meta(sa.load_metadata(m.name)) == _strip_meta(
            sb.load_metadata(m.name)
        ), m.name


class TestAsyncSerialParity:
    """The acceptance contract: for every layout and grouping mode, the
    overlapped drive (dispatch chunk k+1 before collecting chunk k) and
    the serial drive produce the same bytes."""

    @pytest.mark.parametrize(
        "fmt,ragged",
        [("v1", False), ("v2", False), ("v1", True), ("v2", True)],
        ids=["v1-exact", "v2-exact", "v1-padded", "v2-padded"],
    )
    def test_cold_build_parity(self, tmp_path, fmt, ragged):
        if ragged:
            machines = _ragged_machines(4, prefix=f"dcp-{fmt}")
            kwargs = {"pad_lengths": 72}
        else:
            machines = _machines(4, prefix=f"dc-{fmt}")
            kwargs = {}
        dirs = {}
        for label, pipe in (("serial", False), ("async", True)):
            out = tmp_path / f"out-{label}"
            reg = tmp_path / f"reg-{label}"
            result = build_project(
                machines, str(out), model_register_dir=str(reg),
                max_bucket_size=2, pipeline=pipe, artifact_format=fmt,
                **kwargs,
            )
            assert not result.failed
            assert sorted(result.fleet_built) == sorted(
                m.name for m in machines
            )
            dirs[label] = (out, reg)
        a_out, a_reg = dirs["serial"]
        b_out, b_reg = dirs["async"]
        if fmt == "v1":
            _assert_v1_parity(machines, a_out, b_out)
        else:
            _assert_v2_parity(machines, a_out, b_out)
        assert sorted(disk_registry.list_keys(str(a_reg))) == sorted(
            disk_registry.list_keys(str(b_reg))
        )

    def test_warm_start_build_parity(self, tmp_path):
        """Warm-start rebuilds (v2 in-place delta writes) land the same
        bytes whether the drive loop overlaps or not — the warm path runs
        synchronously inside the dispatch window, and its ordering
        relative to cold chunks must not matter."""
        machines = _machines(4, prefix="dcw")
        stores = {}
        for label, pipe in (("serial", False), ("async", True)):
            out = tmp_path / f"out-{label}"
            cold = build_project(
                machines, str(out), max_bucket_size=2,
                artifact_format="v2", pipeline=False,
            )
            assert not cold.failed
            warm = build_project(
                machines, str(out), max_bucket_size=2,
                artifact_format="v2", pipeline=pipe, warm_start=True,
            )
            assert not warm.failed
            assert sorted(
                warm.warm_started + list(warm.warm_fallbacks)
            ) == sorted(m.name for m in machines)
            stores[label] = out
        _assert_v2_parity(machines, stores["serial"], stores["async"])

    def test_device_idle_seconds_reported(self, tmp_path):
        """The new occupancy instrument rides the build summary (and is
        sane: bounded by wall clock, non-negative)."""
        result = build_project(
            _machines(4, prefix="idle"), str(tmp_path / "m"),
            max_bucket_size=2, pipeline=True,
        )
        assert not result.failed
        idle = result.summary()["device_idle_seconds"]
        assert 0.0 <= idle <= result.seconds


class TestLazyFetchParity:
    """Regression pin for the collect side's partial fetch: slicing the
    scaler-stat fold axis on device and handing out zero-copy views must
    yield exactly what an eager full-tree ``to_host`` yields."""

    def test_collect_matches_eager_to_host(self):
        rng = np.random.default_rng(11)
        t = np.linspace(0, 20, 300, dtype=np.float32)
        base = np.stack([np.sin(t), np.cos(t), np.sin(2 * t)], axis=1)
        Xs = [
            (base + 0.01 * rng.standard_normal(base.shape)).astype(
                np.float32
            )
            for _ in range(3)
        ]
        spec = analyze_definition(from_definition(DETECTOR_DEF))
        builder = FleetDiffBuilder(spec)
        X = np.stack(Xs)
        g = builder._dispatch_group(X, X)

        # eager reference: the FULL device tree, fetched before collect
        # runs its partial reads (fetch is idempotent — same buffers)
        eager = to_host(g.out)
        dets = builder._collect_group(g)

        for i, det in enumerate(dets):
            np.testing.assert_array_equal(
                det.feature_thresholds_,
                eager["feature_thresholds"][i],
            )
            assert det.aggregate_threshold_ == float(
                eager["aggregate_threshold"][i]
            )
            est = det.base_estimator
            if hasattr(est, "steps"):
                est = est.steps[-1]
                if isinstance(est, tuple):
                    est = est[-1]
            np.testing.assert_array_equal(
                np.asarray(est.history_), eager["final_history"][i]
            )
            for name, stats in det.cv_metadata_["scores"].items():
                folds = eager["metrics"][name][i]
                assert stats["folds"] == [float(v) for v in folds]
                assert stats["mean"] == float(folds.mean())
                assert stats["std"] == float(folds.std())

    def test_collect_frees_device_tree_and_is_idempotent(self):
        rng = np.random.default_rng(12)
        Xs = [
            rng.standard_normal((250, 3)).astype(np.float32)
            for _ in range(2)
        ]
        spec = analyze_definition(from_definition(DETECTOR_DEF))
        pending = FleetDiffBuilder(spec).dispatch(Xs)
        dets = pending.collect()
        assert all(g.out is None for g in pending._groups)  # buffers freed
        assert pending.collect() is dets  # cached, no second fetch


class TestPrestackedBaselines:
    """The collect side's stacked host arrays double as the fleet-health
    baseline scorer's prestack (``PendingFleetBuild.prestacked`` →
    ``FleetScorer.from_models(prestacked_hint=...)``): the scorer adopts
    them whole instead of re-stacking per-machine views leaf by leaf.
    Sketch docs must be identical either way, and any fleet/hint mismatch
    must fall back to the generic stacking path, not mis-stack."""

    def _built(self, n=3, rows=240):
        rng = np.random.default_rng(21)
        names = [f"pre-{i}" for i in range(n)]
        Xs = [
            rng.standard_normal((rows, 3)).astype(np.float32)
            for _ in names
        ]
        spec = analyze_definition(from_definition(DETECTOR_DEF))
        pending = FleetDiffBuilder(spec).dispatch(Xs)
        dets = pending.collect()
        return names, Xs, dets, pending

    def test_hint_docs_match_stacking_path(self):
        from gordo_tpu.serve.fleet_scorer import FleetScorer
        from gordo_tpu.telemetry import fleet_health

        names, Xs, dets, pending = self._built()
        hint = pending.prestacked(names)
        assert hint is not None
        assert hint["names"] == names
        models = dict(zip(names, dets))
        X_by = dict(zip(names, Xs))
        with_hint = fleet_health.training_baselines(
            models, X_by, prestacked_hint=hint
        )
        plain = fleet_health.training_baselines(models, X_by)
        assert set(with_hint) == set(names)
        assert with_hint == plain

        # the hint must actually engage: the bucket's threshold rows are
        # the hint's own array, not a restacked copy
        scorer = FleetScorer.from_models(models, prestacked_hint=hint)
        assert (
            scorer.buckets[0].thresholds_np is hint["feature_thresholds"]
        )

    def test_hint_mismatch_falls_back(self):
        from gordo_tpu.telemetry import fleet_health

        names, Xs, dets, pending = self._built()
        hint = pending.prestacked(names)
        # a subset fleet (one machine's load failed upstream) no longer
        # matches the hinted names — stacking path, same docs, no error
        sub = dict(list(zip(names, dets))[:-1])
        X_by = dict(zip(names, Xs))
        docs = fleet_health.training_baselines(
            sub, X_by, prestacked_hint=hint
        )
        assert set(docs) == set(names[:-1])

    def test_prestacked_requires_collect(self):
        rng = np.random.default_rng(22)
        Xs = [
            rng.standard_normal((240, 3)).astype(np.float32)
            for _ in range(2)
        ]
        spec = analyze_definition(from_definition(DETECTOR_DEF))
        pending = FleetDiffBuilder(spec).dispatch(Xs)
        assert pending.prestacked(["a", "b"]) is None  # not collected yet
        pending.collect()  # leave no dangling device futures


class TestDispatchWindowLint:
    """The scripts/lint.py D2H gate covers the r23 dispatch window: a
    blocking fetch sneaking into the dispatch family is a lint error, on
    real sources and on synthesized regressions."""

    def _findings(self, basename, source, tmp_path):
        import ast
        import importlib.util
        import pathlib

        lint_path = (
            pathlib.Path(__file__).resolve().parents[1]
            / "scripts" / "lint.py"
        )
        lint_spec = importlib.util.spec_from_file_location("_lint", lint_path)
        lint = importlib.util.module_from_spec(lint_spec)
        lint_spec.loader.exec_module(lint)
        path = tmp_path / basename
        path.write_text(source)
        return lint._d2h_findings(str(path), ast.parse(source), set())

    def test_blocking_fetch_in_dispatch_scope_flagged(self, tmp_path):
        source = (
            "import numpy as np\n"
            "def dispatch(self, Xs):\n"
            "    return np.asarray(Xs[0])\n"
            "def _dispatch_group(self, X, y):\n"
            "    out = self._program(X, y)\n"
            "    return to_host(out)\n"
        )
        findings = self._findings("anomaly.py", source, tmp_path)
        assert len(findings) == 2
        assert "np.asarray" in findings[0][2]
        assert "to_host" in findings[1][2]

    def test_drive_loop_dispatch_scopes_flagged(self, tmp_path):
        source = (
            "def _dispatch_bucket(key, chunk, loaded):\n"
            "    loaded[0].block_until_ready()\n"
            "def _dispatch_chunk(spec, cv, ok, loaded):\n"
            "    import jax\n"
            "    jax.device_get(loaded)\n"
        )
        findings = self._findings("fleet_build.py", source, tmp_path)
        assert len(findings) == 2

    def test_collect_scopes_stay_unflagged(self, tmp_path):
        source = (
            "import numpy as np\n"
            "def _collect_group(self, g):\n"
            "    return to_host(g.out)\n"
            "def _finish_bucket(rec):\n"
            "    return np.asarray(rec.out)\n"
        )
        assert self._findings("anomaly.py", source, tmp_path) == []
        assert self._findings("fleet_build.py", source, tmp_path) == []

    def test_shipped_sources_pass_the_gate(self):
        import ast
        import importlib.util
        import pathlib

        root = pathlib.Path(__file__).resolve().parents[1]
        lint_path = root / "scripts" / "lint.py"
        lint_spec = importlib.util.spec_from_file_location("_lint", lint_path)
        lint = importlib.util.module_from_spec(lint_spec)
        lint_spec.loader.exec_module(lint)
        for rel in (
            "gordo_tpu/parallel/anomaly.py",
            "gordo_tpu/builder/fleet_build.py",
        ):
            src = (root / rel).read_text()
            noqa = {
                i + 1
                for i, line in enumerate(src.splitlines())
                if "# noqa" in line
            }
            assert lint._d2h_findings(
                str(root / rel), ast.parse(src), noqa
            ) == [], rel
