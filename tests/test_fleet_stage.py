"""The pipelined fleet-fit surface (ISSUE 4): ``fleet_stage`` (async H2D)
→ ``fleet_dispatch`` (donated buffers, async compute) → ``collect``
(lazy history fetch), plus the single-copy stacked padding and the
caller-params/seeds validation.  Fast lane: tiny module, two compiles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gordo_tpu.parallel import fleet_mesh
from gordo_tpu.parallel.fleet import (
    StagedFleetFit,
    _pad_models,
    _pad_stacked,
    fleet_dispatch,
    fleet_fit,
    fleet_init,
    fleet_keys,
    fleet_stage,
)
from gordo_tpu.registry import lookup_factory
from gordo_tpu.train.fit import TrainConfig, fit

M, N, F = 3, 40, 4
CFG = TrainConfig(epochs=2, batch_size=32)


@pytest.fixture(scope="module")
def module():
    return lookup_factory("AutoEncoder", "feedforward_hourglass")(
        n_features=F, n_features_out=F
    )


@pytest.fixture()
def data():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((M, N, F)).astype(np.float32)
    w = np.ones((M, N), np.float32)
    return X, w


class TestPadStacked:
    def test_matches_the_old_double_concatenate(self):
        rng = np.random.default_rng(1)
        X = rng.standard_normal((3, 10, 2)).astype(np.float32)
        for m_pad, n_total in ((3, 10), (4, 16), (3, 16), (8, 10)):
            old = X
            if n_total > 10:
                old = np.concatenate(
                    [old, np.zeros((3, n_total - 10, 2), np.float32)], axis=1
                )
            old = _pad_models(old, m_pad)
            assert np.array_equal(old, _pad_stacked(X, m_pad, n_total))

    def test_no_pad_returns_the_same_buffer(self):
        X = np.ones((2, 5, 3), np.float32)
        assert _pad_stacked(X, 2, 5) is X

    def test_weights_never_repeat_the_last_machine(self):
        w = np.ones((2, 5), np.float32)
        out = _pad_stacked(w, 4, 8, repeat_last=False)
        assert out[:2, :5].sum() == 10 and out.sum() == 10


class TestStageDispatchCollect:
    def test_matches_blocking_fleet_fit(self, module, data):
        X, w = data
        seeds = np.arange(M, dtype=np.uint32)
        blocking = fleet_fit(module, X, X, w, CFG, seeds=seeds)
        staged = fleet_stage(module, X, X, w, CFG, seeds=seeds)
        assert isinstance(staged, StagedFleetFit)
        res = fleet_dispatch(module, staged, CFG)
        # history is lazy: still a device array until first access
        assert not isinstance(res._history, np.ndarray)
        res.collect()
        assert isinstance(res._history, np.ndarray)
        assert res.history.shape == (M, CFG.epochs)
        assert np.array_equal(blocking.history, res.history)
        for a, b in zip(
            jax.tree.leaves(blocking.params), jax.tree.leaves(res.params)
        ):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_staged_batch_dispatches_exactly_once(self, module, data):
        X, w = data
        staged = fleet_stage(module, X, X, w, CFG)
        fleet_dispatch(module, staged, CFG).collect()
        with pytest.raises(RuntimeError, match="donated"):
            fleet_dispatch(module, staged, CFG)

    def test_history_property_slices_off_mesh_padding(self, module, data):
        X, w = data
        mesh = fleet_mesh()  # conftest pins 8 virtual devices; M=3 pads to 8
        res = fleet_fit(module, X, X, w, CFG, mesh=mesh)
        assert res.history.shape == (M, CFG.epochs)
        assert len(res.unstack_params()) == M


class TestCallerInputValidation:
    def test_params_leading_axis_must_match_padded_fleet(self, module, data):
        X, w = data
        mesh = fleet_mesh()
        init_keys, _ = fleet_keys(np.arange(M, dtype=np.uint32))
        params3 = fleet_init(module, init_keys, jnp.asarray(X[0, :1]))
        with pytest.raises(ValueError, match="leading model axis 8"):
            fleet_fit(module, X, X, w, CFG, mesh=mesh, params=params3)

    def test_correctly_padded_params_accepted_and_caller_copy_survives(
        self, module, data
    ):
        X, w = data
        mesh = fleet_mesh()
        init_keys, _ = fleet_keys(np.arange(8, dtype=np.uint32))
        params8 = fleet_init(module, init_keys, jnp.asarray(X[0, :1]))
        res = fleet_fit(module, X, X, w, CFG, mesh=mesh, params=params8)
        assert res.history.shape == (M, CFG.epochs)
        # dispatch donated a COPY: the caller's pytree is still usable
        for leaf in jax.tree.leaves(params8):
            np.asarray(leaf)

    def test_seeds_length_validated(self, module, data):
        X, w = data
        with pytest.raises(ValueError, match="one entry per machine"):
            fleet_fit(
                module, X, X, w, CFG, seeds=np.arange(5, dtype=np.uint32)
            )


class TestFitDonationSafety:
    def test_caller_arrays_and_params_survive_fit(self, module):
        """train.fit.fit donates into _fit_jit but must never delete a
        buffer the caller still holds — including the X-aliases-y case
        (AutoEncoder targets) and caller-supplied params."""
        rng = np.random.default_rng(2)
        Xj = jnp.asarray(rng.standard_normal((32, F)).astype(np.float32))
        params, hist = fit(module, Xj, Xj, CFG)
        float(Xj.sum())  # would raise if the buffer had been donated
        params2, hist2 = fit(module, Xj, Xj, CFG, params=params)
        np.asarray(jax.tree.leaves(params)[0])  # caller params intact
        assert hist.shape == hist2.shape == (CFG.epochs,)
