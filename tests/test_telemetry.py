"""Telemetry plane tests: metrics core (concurrent increments, histogram
bucket edges, golden Prometheus rendering), snapshots + merging, trace-id
propagation client → server → response header, span log, and the
``profiling.trace`` always-on recording satellite."""

import asyncio
import json
import os
import threading

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from gordo_tpu import telemetry
from gordo_tpu.telemetry import metrics as metrics_mod

GOLDEN_PATH = os.path.join(
    os.path.dirname(__file__), "data", "telemetry_golden.prom"
)


def _fresh() -> metrics_mod.MetricsRegistry:
    return metrics_mod.MetricsRegistry(enabled=True)


def _golden_registry() -> metrics_mod.MetricsRegistry:
    """Deterministic registry content behind the golden exposition file."""
    reg = _fresh()
    c = reg.counter(
        "gordo_golden_requests_total", "Requests by route and status",
        labels=("route", "status"),
    )
    c.inc(3, "/metrics", "200")
    c.inc(1, "/gordo/v0/{project}/", "404")
    c.inc(1, 'we"ird\\route', "200")  # label escaping exercised
    g = reg.gauge("gordo_golden_queue_depth", "Queue depth")
    g.set(4)
    # a gordo_machine_* family pins the fleet-health gauge rendering
    # (top-K per-machine series with the machine label)
    d = reg.gauge(
        "gordo_machine_drift", "Baseline-vs-live drift", labels=("machine",)
    )
    d.set(0.75, "m-001")
    d.set(0.5, "m-002")
    h = reg.histogram(
        "gordo_golden_request_seconds", "Latency", labels=("route",),
        buckets=(0.005, 0.05, 0.5),
    )
    h.observe(0.004, "/a")
    h.observe(0.05, "/a")  # exactly on a bound: le is inclusive
    h.observe(3.2, "/a")   # over the last bound: +Inf only
    # a gordo_stream_* family pins the streaming-plane catalog rendering
    # (per-event-type counter with the type label)
    s = reg.counter(
        "gordo_stream_events_pushed_total",
        "Events pushed to stream subscribers",
        labels=("type",),
    )
    s.inc(5, "verdict")
    s.inc(1, "threshold")
    # r22 placement-plane families pin the mesh catalog rendering: the
    # mesh-width gauge, the per-kind placement counter, and the
    # per-device transfer counter (labels mirror gordo_tpu/mesh/)
    reg.gauge(
        "gordo_mesh_devices",
        "Device count of the most recently constructed fleet mesh",
    ).set(4)
    p = reg.counter(
        "gordo_fleet_placements_total",
        "Fleet-stack device placements by kind (sharded mesh vs single "
        "device)",
        labels=("kind",),
    )
    p.inc(2, "sharded")
    p.inc(1, "single")
    t = reg.counter(
        "gordo_mesh_device_transfers_total",
        "Array leaves transferred to each device by the placement plane",
        labels=("device",),
    )
    t.inc(6, "0")
    t.inc(6, "1")
    return reg


class TestMetricsCore:
    def test_name_convention_enforced(self):
        reg = _fresh()
        for bad in ("requests_total", "gordo_BadCase", "gordo_", "gordo-x"):
            with pytest.raises(ValueError, match="catalog convention"):
                reg.counter(bad, "x")

    def test_get_or_create_and_kind_conflicts(self):
        reg = _fresh()
        c1 = reg.counter("gordo_x_total", "x")
        assert reg.counter("gordo_x_total", "x") is c1
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("gordo_x_total", "x")
        with pytest.raises(ValueError, match="already registered"):
            reg.counter("gordo_x_total", "x", labels=("other",))

    def test_concurrent_increments_are_exact(self):
        """The core thread-safety contract: N threads hammering the same
        counter + histogram lose no updates."""
        reg = _fresh()
        c = reg.counter("gordo_conc_total", "x", labels=("t",))
        h = reg.histogram("gordo_conc_seconds", "x")
        n, n_threads = 2000, 8

        def work(i):
            for _ in range(n):
                c.inc(1.0, str(i % 2))
                h.observe(0.01)

        threads = [
            threading.Thread(target=work, args=(i,))
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value("0") + c.value("1") == n * n_threads
        snap = h.snapshot_series()
        assert snap["count"] == n * n_threads
        assert snap["sum"] == pytest.approx(0.01 * n * n_threads)

    def test_histogram_bucket_edges_le_inclusive(self):
        """A value exactly on a bound lands in THAT bucket (Prometheus
        ``le`` semantics), and cumulative rendering reflects it."""
        reg = _fresh()
        h = reg.histogram("gordo_edges_seconds", "x", buckets=(0.1, 1.0))
        for v in (0.1, 1.0, 1.0001):
            h.observe(v)
        assert h.snapshot_series()["counts"] == [1, 1, 1]
        text = reg.render()
        assert 'gordo_edges_seconds_bucket{le="0.1"} 1' in text
        assert 'gordo_edges_seconds_bucket{le="1"} 2' in text
        assert 'gordo_edges_seconds_bucket{le="+Inf"} 3' in text
        assert "gordo_edges_seconds_count 3" in text

    def test_kill_switch_stops_recording(self):
        reg = _fresh()
        c = reg.counter("gordo_switch_total", "x")
        c.inc()
        reg.set_enabled(False)
        c.inc(100)
        reg.set_enabled(True)
        c.inc()
        assert c.value() == 2

    def test_rendering_matches_golden_file(self):
        with open(GOLDEN_PATH) as f:
            golden = f.read()
        assert _golden_registry().render() == golden


class TestSnapshots:
    def test_snapshot_render_roundtrip(self):
        reg = _golden_registry()
        assert telemetry.render_snapshot(reg.snapshot()) == reg.render()

    def test_merge_adds_counters_and_histograms(self):
        snap = _golden_registry().snapshot()
        merged = telemetry.merge_snapshots([snap, snap, snap])
        text = telemetry.render_snapshot(merged)
        assert 'gordo_golden_requests_total{route="/metrics",status="200"} 9' in text
        assert 'gordo_golden_request_seconds_count{route="/a"} 9' in text
        # gauges are last-write, not summed
        assert "gordo_golden_queue_depth 4" in text

    def test_merge_gauge_latest_snapshot_wins(self):
        old = _fresh()
        old.gauge("gordo_g_depth", "x").set(1)
        new = _fresh()
        new.gauge("gordo_g_depth", "x").set(7)
        snap_old, snap_new = old.snapshot(), new.snapshot()
        snap_old["time"], snap_new["time"] = 100.0, 200.0
        for order in ([snap_old, snap_new], [snap_new, snap_old]):
            text = telemetry.render_snapshot(telemetry.merge_snapshots(order))
            assert "gordo_g_depth 7" in text

    def test_write_and_load_snapshot_dir(self, tmp_path):
        reg = _golden_registry()
        d = str(tmp_path / "snaps")
        reg.write_snapshot(os.path.join(d, "shard-000-of-002.json"))
        reg.write_snapshot(os.path.join(d, "shard-001-of-002.json"))
        (tmp_path / "snaps" / "junk.json").write_text("{not json")
        snaps = telemetry.load_snapshot_dir(d)
        assert len(snaps) == 2
        text = telemetry.render_snapshot(telemetry.merge_snapshots(snaps))
        assert 'gordo_golden_requests_total{route="/metrics",status="200"} 6' in text

    def test_add_instance_label(self):
        text = _golden_registry().render()
        labeled = telemetry.add_instance_label(text, "http://a:5555")
        assert 'gordo_golden_queue_depth{instance="http://a:5555"} 4' in labeled
        assert (
            'gordo_golden_requests_total{route="/metrics",status="200",'
            'instance="http://a:5555"} 3' in labeled
        )
        # comments pass through untouched
        assert "# TYPE gordo_golden_queue_depth gauge" in labeled

    def test_merge_expositions_groups_families(self):
        """Merged multi-target output keeps each family's samples in ONE
        block under a single HELP/TYPE header (text-format requirement a
        naive concat violates)."""
        text = _golden_registry().render()
        merged = telemetry.merge_expositions([("a", text), ("b", text)])
        assert merged.count("# TYPE gordo_golden_queue_depth gauge") == 1
        lines = merged.splitlines()
        idx = [
            i for i, line in enumerate(lines)
            if line.startswith("gordo_golden_queue_depth{")
        ]
        assert len(idx) == 2 and idx[1] == idx[0] + 1  # contiguous block
        assert 'instance="a"' in lines[idx[0]]
        assert 'instance="b"' in lines[idx[1]]

    def test_scrape_metrics_merges_extra_pairs(self):
        from gordo_tpu.watchman.endpoints_status import scrape_metrics

        text = _golden_registry().render()
        merged, n = asyncio.run(
            scrape_metrics([], extra=[("watchman", text)])
        )
        assert n == 0
        assert 'gordo_golden_queue_depth{instance="watchman"} 4' in merged


class TestTracePropagation:
    """One trace id stitches client → HTTP header → server → response."""

    def _server_app(self):
        from gordo_tpu.serve.server import ModelCollection, build_app

        return build_app(ModelCollection({}, project="traceproj"))

    def test_server_echoes_and_mints_trace_ids(self):
        async def run():
            client = TestClient(TestServer(self._server_app()))
            await client.start_server()
            try:
                sent = await client.get(
                    "/gordo/v0/traceproj/",
                    headers={telemetry.TRACE_HEADER: "feedbeefcafe0123"},
                )
                unsent = await client.get("/gordo/v0/traceproj/")
                return (
                    sent.headers.get(telemetry.TRACE_HEADER),
                    unsent.headers.get(telemetry.TRACE_HEADER),
                )
            finally:
                await client.close()

        echoed, minted = asyncio.run(run())
        assert echoed == "feedbeefcafe0123"
        assert minted and len(minted) == 16 and minted != echoed

    def test_error_responses_carry_the_trace_id(self):
        async def run():
            client = TestClient(TestServer(self._server_app()))
            await client.start_server()
            try:
                resp = await client.get(
                    "/gordo/v0/traceproj/nope/healthcheck",
                    headers={telemetry.TRACE_HEADER: "abcdef0123456789"},
                )
                return resp.status, resp.headers.get(telemetry.TRACE_HEADER)
            finally:
                await client.close()

        status, tid = asyncio.run(run())
        assert status == 404 and tid == "abcdef0123456789"

    def test_client_io_sends_trace_header(self):
        """client/io.request_json injects the context's trace id into
        every outbound request (minting one when unbound)."""
        from gordo_tpu.client.io import post_json

        seen = {}

        async def handler(request: web.Request) -> web.Response:
            seen["trace"] = request.headers.get(telemetry.TRACE_HEADER)
            return web.json_response({"data": {}})

        async def run():
            app = web.Application()
            app.router.add_post("/score", handler)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = runner.addresses[0][1]
            import aiohttp

            telemetry.set_trace_id("0123456789abcdef")
            async with aiohttp.ClientSession() as session:
                await post_json(
                    session, f"http://127.0.0.1:{port}/score", {"X": []}
                )
            await runner.cleanup()

        asyncio.run(run())
        assert seen["trace"] == "0123456789abcdef"


class TestSpans:
    def test_span_log_jsonl(self, tmp_path, monkeypatch):
        log_path = str(tmp_path / "spans.jsonl")
        monkeypatch.setenv("GORDO_SPAN_LOG", log_path)
        telemetry.set_trace_id("1111222233334444")
        with telemetry.span("test.section", machine="m-1") as attrs:
            attrs["batch"] = 3
        with open(log_path) as f:
            doc = json.loads(f.readline())
        assert doc["span"] == "test.section"
        assert doc["trace"] == "1111222233334444"
        assert doc["machine"] == "m-1" and doc["batch"] == 3
        assert doc["seconds"] >= 0

    def test_span_feeds_histogram(self):
        h = telemetry.REGISTRY.get("gordo_span_seconds")
        before = h.snapshot_series("test.histo")["count"]
        with telemetry.span("test.histo"):
            pass
        assert h.snapshot_series("test.histo")["count"] == before + 1

    def test_ensure_trace_id_mints_once(self):
        telemetry.set_trace_id(None)
        tid = telemetry.ensure_trace_id()
        assert telemetry.ensure_trace_id() == tid == (
            telemetry.current_trace_id()
        )


def test_profiling_trace_records_without_profile_dir(monkeypatch):
    """Satellite: profiling.trace is no longer a pure no-op without
    GORDO_PROFILE_DIR — section wall time always reaches the registry,
    with the pre-'/' head as the bounded label."""
    monkeypatch.delenv("GORDO_PROFILE_DIR", raising=False)
    from gordo_tpu.utils import profiling

    h = profiling._SECTION_SECONDS
    before = h.snapshot_series("unit_test_section")["count"]
    with profiling.trace("unit_test_section/512"):
        pass
    assert h.snapshot_series("unit_test_section")["count"] == before + 1


def test_events_are_counted_and_single_line(caplog):
    import logging

    events = telemetry.REGISTRY.get("gordo_events_total")
    before = events.value("unit_test_event")
    test_logger = logging.getLogger("gordo_tpu.tests.events")
    with caplog.at_level(logging.WARNING, logger=test_logger.name):
        telemetry.log_event(
            test_logger, "unit_test_event", cooldown_s=0.5, streak=2
        )
    assert events.value("unit_test_event") == before + 1
    lines = [r.getMessage() for r in caplog.records]
    assert lines == ["EVENT unit_test_event cooldown_s=0.5 streak=2"]
