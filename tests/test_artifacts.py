"""Artifact format v2 (ISSUE 6): memory-mapped bucket packs.

Fast lane: the pack format itself — zero-copy round-trips, page
alignment, delta writes, corruption loudness, registry/manifest
satellites, and the new lint gates (all on synthetic objects, no
training).  Slow lane (``TestV1V2Parity``, CI test-full job): the
v1↔v2 parity suite — build the same project both ways and assert
scoring responses are byte-identical and the registry keys match, plus
conversion round-trips and the one-device_put-per-pack attestation.
"""

import importlib.util
import json
import os
import shutil

import numpy as np
import pytest

from gordo_tpu import artifacts
from gordo_tpu.utils import disk_registry


def _models(n, rng=None, width=3):
    rng = rng or np.random.default_rng(0)
    out = []
    for i in range(n):
        w = rng.standard_normal((8, width)).astype(np.float32)
        out.append(
            {
                "w": w,
                "w_again": w,  # duplicate reference — must restore shared
                "thr": rng.standard_normal(width).astype(np.float32),
                "scale": float(i),
                "note": f"machine {i}",
            }
        )
    return out


def _write(tmp_path, n=3, prefix="m"):
    names = [f"{prefix}-{i}" for i in range(n)]
    models = _models(n)
    metas = [{"name": nm, "cache_key": f"key-{i}"}
             for i, nm in enumerate(names)]
    pack_id = artifacts.write_pack(
        str(tmp_path), names, models, metas, definition="model: yes\n",
        cache_keys={nm: f"key-{i}" for i, nm in enumerate(names)},
    )
    return names, models, pack_id


class TestPackFormat:
    def test_roundtrip_is_zero_copy_and_value_exact(self, tmp_path):
        names, models, pack_id = _write(tmp_path)
        store = artifacts.open_store(str(tmp_path))
        assert store.names() == sorted(names)
        m1 = store.load_model("m-1")
        assert np.array_equal(m1["w"], models[1]["w"])
        assert np.array_equal(m1["thr"], models[1]["thr"])
        assert m1["scale"] == 1.0 and m1["note"] == "machine 1"
        # duplicate references restore as ONE shared view
        assert m1["w"] is m1["w_again"]
        # zero copy: the leaf is a view into the pack mmap, owning nothing
        assert not m1["w"].flags.owndata
        assert store.load_metadata("m-1")["cache_key"] == "key-1"
        assert store.definition("m-1") == "model: yes\n"

    def test_tensor_segments_are_page_aligned(self, tmp_path):
        _, _, pack_id = _write(tmp_path)
        store = artifacts.open_store(str(tmp_path))
        tensors = store.packs[pack_id]["tensors"]
        assert tensors, "stacked tensors recorded"
        for t in tensors:
            assert t["offset"] % 4096 == 0, t

    def test_stacked_tensors_match_slot_views(self, tmp_path):
        names, models, pack_id = _write(tmp_path)
        store = artifacts.open_store(str(tmp_path))
        m0 = store.load_model("m-0")
        loc = store.leaf_of(m0["w"])
        assert loc is not None and loc[0] == pack_id
        stacked = store.stacked(pack_id)[loc[1]]
        assert stacked.shape[0] == len(names)
        assert np.array_equal(stacked[0], models[0]["w"])
        assert np.array_equal(stacked[2], models[2]["w"])

    def test_leaf_signature_mismatch_refuses_pack(self, tmp_path):
        models = _models(2)
        models[1]["w"] = models[1]["w_again"] = np.zeros(
            (9, 3), np.float32
        )  # different shape
        with pytest.raises(artifacts.PackError, match="leaf signature"):
            artifacts.write_pack(str(tmp_path), ["a", "b"], models)

    def test_rewrite_supersedes_and_gcs_dead_packs(self, tmp_path):
        names, _, pack_id = _write(tmp_path)
        # rewrite the same machines as a new chunk grouping
        artifacts.write_pack(
            str(tmp_path), names, _models(3, np.random.default_rng(7)),
        )
        store = artifacts.open_store(str(tmp_path))
        # the superseded pack (same machine set -> same deterministic id
        # is replaced in place; a different grouping would be GC'd once
        # no machine rows point at it)
        for name in names:
            assert name in store
        live_packs = {store.location(n)[0] for n in names}
        packs_dir = artifacts.packs_dir(str(tmp_path))
        on_disk = {
            f for f in os.listdir(packs_dir) if f.endswith(".pack")
        }
        assert on_disk == {
            store.packs[p]["file"] for p in live_packs
        }, "no orphaned pack files survive a rewrite"


class TestDeltaWrite:
    def test_delta_rewrites_only_the_changed_slot(self, tmp_path):
        names, models, pack_id = _write(tmp_path)
        store = artifacts.open_store(str(tmp_path))
        before = {
            n: bytes(store.load_model(n)["w"].tobytes()) for n in names
        }
        new = dict(models[1])
        new["w"] = new["w_again"] = np.full((8, 3), 7.0, np.float32)
        new["scale"] = 99.0
        rewritten = artifacts.delta_write(
            str(tmp_path), {"m-1": new}, {"m-1": {"name": "m-1", "d": 1}}
        )
        assert rewritten == ["m-1"]
        store2 = artifacts.open_store(str(tmp_path))
        m1 = store2.load_model("m-1")
        assert np.all(m1["w"] == 7.0) and m1["scale"] == 99.0
        assert store2.load_metadata("m-1") == {"name": "m-1", "d": 1}
        for n in ("m-0", "m-2"):  # other slots byte-untouched
            assert store2.load_model(n)["w"].tobytes() == before[n]

    def test_delta_of_unknown_machine_is_loud(self, tmp_path):
        _write(tmp_path)
        with pytest.raises(artifacts.PackError, match="not in the pack"):
            artifacts.delta_write(str(tmp_path), {"nope": _models(1)[0]})

    def test_delta_structural_change_is_loud(self, tmp_path):
        _write(tmp_path)
        bad = _models(1)[0]
        bad["w"] = bad["w_again"] = np.zeros((2, 2), np.float32)
        with pytest.raises(artifacts.PackError, match="leaf signature"):
            artifacts.delta_write(str(tmp_path), {"m-0": bad})


class TestGenerations:
    """ISSUE 11: the versioned-generations layer over the pack index."""

    def test_stamp_publishes_pending_rows_once(self, tmp_path):
        names, _, _ = _write(tmp_path)
        # pack writes land pending — nothing published until the stamp
        assert artifacts.read_generation(str(tmp_path)) == 0
        assert artifacts.stamp_generation(str(tmp_path)) == 1
        # idempotent: a second stamp with nothing pending is a no-op
        assert artifacts.stamp_generation(str(tmp_path)) == 1
        store = artifacts.open_store(str(tmp_path))
        assert store.generation == 1
        assert all(int(store.machines[n]["gen"]) == 1 for n in names)

    def test_delta_write_stamps_its_own_flip(self, tmp_path):
        _, models, _ = _write(tmp_path)
        artifacts.stamp_generation(str(tmp_path))
        new = dict(models[1])
        new["w"] = new["w_again"] = np.full((8, 3), 5.0, np.float32)
        artifacts.delta_write(str(tmp_path), {"m-1": new})
        assert artifacts.read_generation(str(tmp_path)) == 2
        store = artifacts.open_store(str(tmp_path))
        assert int(store.machines["m-1"]["gen"]) == 2
        assert int(store.machines["m-0"]["gen"]) == 1

    def test_generation_sidecar_heals_on_stamp(self, tmp_path):
        _write(tmp_path)
        artifacts.stamp_generation(str(tmp_path))
        sidecar = os.path.join(
            artifacts.packs_dir(str(tmp_path)), artifacts.GENERATION_FILE
        )
        os.remove(sidecar)
        # reads fall back to the index document...
        assert artifacts.read_generation(str(tmp_path)) == 1
        # ...and a no-op stamp rewrites the sidecar
        assert artifacts.stamp_generation(str(tmp_path)) == 1
        assert os.path.exists(sidecar)

    def test_forced_stamp_flips_with_nothing_pending(self, tmp_path):
        """The operator heal path: pack bytes restored out-of-band leave
        no pending rows, so only a forced flip (`gordo artifacts flip`)
        can make serving replicas re-validate and drop a quarantine."""
        names, _, _ = _write(tmp_path)
        assert artifacts.stamp_generation(str(tmp_path)) == 1
        # nothing pending: plain stamp stays put, force republishes all
        assert artifacts.stamp_generation(str(tmp_path)) == 1
        assert artifacts.stamp_generation(str(tmp_path), force=True) == 2
        store = artifacts.open_store(str(tmp_path))
        assert store.generation == 2
        assert all(int(store.machines[n]["gen"]) == 2 for n in names)
        # every pack is revalidated downstream: the generation-gated
        # rescan reloads iff entry.gen < row.gen <= published
        assert "2" in store.generations

    def test_forced_stamp_on_empty_store_is_still_a_noop(self, tmp_path):
        assert artifacts.stamp_generation(str(tmp_path), force=True) == 0

    def test_gc_refuses_keep_below_one(self, tmp_path):
        _write(tmp_path)
        with pytest.raises(ValueError, match="live generation"):
            artifacts.gc_generations(str(tmp_path), 0)

    def test_gc_prunes_history_to_keep(self, tmp_path):
        _, models, _ = _write(tmp_path)
        artifacts.stamp_generation(str(tmp_path))
        for v in (5.0, 6.0, 7.0):
            new = dict(models[1])
            new["w"] = new["w_again"] = np.full((8, 3), v, np.float32)
            artifacts.delta_write(str(tmp_path), {"m-1": new})
        assert artifacts.read_generation(str(tmp_path)) == 4
        summary = artifacts.gc_generations(str(tmp_path), 2)
        assert summary["generation"] == 4
        assert summary["retained"] == [3, 4]
        store = artifacts.open_store(str(tmp_path))
        assert sorted(int(g) for g in store.generations) == [3, 4]


class TestGenerationGatedRescan:
    """ISSUE 11 satellite: the rescan reload signal is the published
    generation, never pack mtimes — ``delta_write`` mutates pack bytes
    in place, so mtime ticks while a write is still torn."""

    @staticmethod
    def _publish(tmp_path, generation, row_gens=None):
        pdir = artifacts.packs_dir(str(tmp_path))
        idx = os.path.join(pdir, "index.json")
        with open(idx) as fh:
            doc = json.load(fh)
        doc["generation"] = generation
        for name, g in (row_gens or {}).items():
            doc["machines"][name]["gen"] = g
        with open(idx, "w") as fh:
            json.dump(doc, fh)
        with open(
            os.path.join(pdir, artifacts.GENERATION_FILE), "w"
        ) as fh:
            fh.write(str(generation))

    def test_torn_write_defers_reload_until_flip(self, tmp_path):
        from gordo_tpu.serve.server import ModelCollection

        _, models, _ = _write(tmp_path)
        artifacts.stamp_generation(str(tmp_path))
        coll = ModelCollection.from_directory(str(tmp_path))
        assert coll.generation == 1
        unchanged = {"added": [], "reloaded": [], "removed": []}
        assert coll.rescan() == unchanged

        new = dict(models[1])
        new["w"] = new["w_again"] = np.full((8, 3), 9.0, np.float32)
        artifacts.delta_write(str(tmp_path), {"m-1": new})
        # reopen the torn window: bytes + row gen landed, flip did not
        self._publish(tmp_path, 1, {"m-1": 2})
        # mtime ticked and bytes changed — and the rescan must NOT act
        assert coll.maybe_delta_reload() == unchanged
        assert coll.rescan() == unchanged
        assert coll.entries["m-1"].generation == 1

        # land the flip: exactly the changed machine reloads
        self._publish(tmp_path, 2, {"m-1": 2})
        changes = coll.maybe_delta_reload()
        assert changes["reloaded"] == ["m-1"]
        assert coll.entries["m-1"].generation == 2
        assert coll.generation == 2
        # and the watch poll goes quiet again
        assert coll.maybe_delta_reload() == unchanged

    def test_generation_rollback_reloads_newer_entries(self, tmp_path):
        from gordo_tpu.serve.server import ModelCollection

        _, models, _ = _write(tmp_path)
        artifacts.stamp_generation(str(tmp_path))
        coll = ModelCollection.from_directory(str(tmp_path))
        new = dict(models[1])
        new["w"] = new["w_again"] = np.full((8, 3), 4.0, np.float32)
        artifacts.delta_write(str(tmp_path), {"m-1": new})
        assert coll.rescan()["reloaded"] == ["m-1"]
        assert coll.generation == 2
        # a restored backup can publish an OLDER id: entries newer than
        # the store must reload instead of pinning stale device state
        self._publish(tmp_path, 1, {"m-1": 1})
        assert coll.rescan()["reloaded"] == ["m-1"]
        assert coll.generation == 1
        assert coll.entries["m-1"].generation == 1


class TestCorruptionIsLoud:
    def test_truncated_pack_fails_open(self, tmp_path):
        _, _, pack_id = _write(tmp_path)
        store = artifacts.open_store(str(tmp_path))
        path = os.path.join(
            artifacts.packs_dir(str(tmp_path)), store.packs[pack_id]["file"]
        )
        with open(path, "r+b") as fh:
            fh.truncate(64)
        with pytest.raises(artifacts.PackCorruptError, match="truncated"):
            artifacts.open_store(str(tmp_path))

    def test_bad_index_offset_fails_open(self, tmp_path):
        _, _, pack_id = _write(tmp_path)
        index = os.path.join(
            artifacts.packs_dir(str(tmp_path)), "index.json"
        )
        doc = json.load(open(index))
        doc["packs"][pack_id]["tensors"][0]["offset"] = 10 ** 9
        json.dump(doc, open(index, "w"))
        with pytest.raises(artifacts.PackCorruptError, match="truncated"):
            artifacts.open_store(str(tmp_path))

    def test_bad_magic_fails_open(self, tmp_path):
        _, _, pack_id = _write(tmp_path)
        store = artifacts.open_store(str(tmp_path))
        path = os.path.join(
            artifacts.packs_dir(str(tmp_path)), store.packs[pack_id]["file"]
        )
        with open(path, "r+b") as fh:
            fh.write(b"XXXX")
        with pytest.raises(artifacts.PackCorruptError, match="magic"):
            artifacts.open_store(str(tmp_path))

    def test_server_load_of_corrupt_pack_is_loud(self, tmp_path):
        """The serving contract: a truncated pack must kill collection
        load, not silently shrink the fleet."""
        from gordo_tpu.serve.server import ModelCollection

        _, _, pack_id = _write(tmp_path)
        store = artifacts.open_store(str(tmp_path))
        path = os.path.join(
            artifacts.packs_dir(str(tmp_path)), store.packs[pack_id]["file"]
        )
        with open(path, "r+b") as fh:
            fh.truncate(64)
        with pytest.raises(artifacts.PackCorruptError):
            ModelCollection.from_directory(str(tmp_path))

    def test_truncated_meta_json_raises_pack_corrupt(self, tmp_path):
        """A torn ``<pack>.meta.json`` (crash mid-write of a pre-replace
        world, or disk damage) must surface as PackCorruptError at the
        metadata read, never as a silent empty-metadata default."""
        _, _, pack_id = _write(tmp_path)
        store = artifacts.open_store(str(tmp_path))
        meta = os.path.join(
            artifacts.packs_dir(str(tmp_path)),
            store.packs[pack_id]["meta_file"],
        )
        with open(meta, "w") as fh:
            fh.write('{"definition": "model: y')  # torn mid-document
        store = artifacts.open_store(str(tmp_path))  # tensors are fine
        with pytest.raises(
            artifacts.PackCorruptError, match="metadata unreadable"
        ):
            store.load_metadata("m-0")

    def test_skeleton_extent_past_eof_fails_open(self, tmp_path):
        """index.json addressing a skeleton segment past the pack's EOF
        is the same torn-index corruption as a bad tensor offset."""
        _, _, pack_id = _write(tmp_path)
        index = os.path.join(
            artifacts.packs_dir(str(tmp_path)), "index.json"
        )
        doc = json.load(open(index))
        doc["packs"][pack_id]["skeletons"][0] = [10 ** 9, 64]
        json.dump(doc, open(index, "w"))
        with pytest.raises(artifacts.PackCorruptError, match="truncated"):
            artifacts.open_store(str(tmp_path))


class TestCorruptionQuarantine:
    """The serving-side counterpart of TestCorruptionIsLoud: with
    ``quarantine=True`` a corrupt pack takes down only ITS machines —
    the rest of the store loads and serves."""

    def _two_packs_one_truncated(self, tmp_path):
        names_a, _, _ = _write(tmp_path, n=2, prefix="a")
        names_b, _, pack_b = _write(tmp_path, n=2, prefix="b")
        store = artifacts.open_store(str(tmp_path))
        path = os.path.join(
            artifacts.packs_dir(str(tmp_path)), store.packs[pack_b]["file"]
        )
        with open(path, "r+b") as fh:
            fh.truncate(64)
        return names_a, names_b, pack_b

    def test_quarantine_bounds_to_the_corrupt_pack(self, tmp_path):
        names_a, names_b, pack_b = self._two_packs_one_truncated(tmp_path)
        # strict mode (registry/CLI) stays loud
        with pytest.raises(artifacts.PackCorruptError, match="truncated"):
            artifacts.open_store(str(tmp_path))
        store = artifacts.open_store(str(tmp_path), quarantine=True)
        assert store.names() == sorted(names_a)
        assert sorted(store.quarantined_machines) == sorted(names_b)
        assert set(store.quarantined_packs) == {pack_b}
        # healthy machines load; quarantined ones raise with the cause
        assert store.load_model("a-0")["note"] == "machine 0"
        with pytest.raises(artifacts.PackCorruptError, match="quarantined"):
            store.load_model("b-0")

    def test_discover_excludes_quarantined_machines(self, tmp_path):
        names_a, names_b, _ = self._two_packs_one_truncated(tmp_path)
        store, refs = artifacts.discover(str(tmp_path), quarantine=True)
        assert sorted(r.name for r in refs) == sorted(names_a)
        assert sorted(store.quarantined_machines) == sorted(names_b)

    def test_collection_serves_around_quarantine(self, tmp_path):
        """The acceptance scenario's load half: one pack corrupted on
        disk -> the collection still builds, serves the unaffected
        machines, and reports exactly the injected machines."""
        from gordo_tpu.serve.server import ModelCollection

        names_a, names_b, _ = self._two_packs_one_truncated(tmp_path)
        coll = ModelCollection.from_directory(str(tmp_path))
        assert sorted(coll.entries) == sorted(names_a)
        assert sorted(coll.quarantined) == sorted(names_b)
        for name in names_b:
            assert "truncated" in coll.quarantined[name]["error"]
        # quarantined machines STAY in the fleet list: the positional
        # shard table must not shift underneath routing clients
        assert coll.fleet_machines == sorted(names_a + names_b)
        assert coll.last_error is not None

    def test_heal_on_rescan_when_pack_is_rewritten(self, tmp_path):
        """Delta-reload healing: a good generation flip over the broken
        machines clears their quarantine on the next rescan."""
        from gordo_tpu.serve.server import ModelCollection

        names_a, names_b, _ = self._two_packs_one_truncated(tmp_path)
        coll = ModelCollection.from_directory(str(tmp_path))
        assert sorted(coll.quarantined) == sorted(names_b)
        # a fresh build of the same machines writes a healthy pack and
        # repoints their index rows
        artifacts.write_pack(
            str(tmp_path), names_b, _models(2, np.random.default_rng(5)),
        )
        summary = coll.rescan()
        assert coll.quarantined == {}
        assert sorted(coll.entries) == sorted(names_a + names_b)
        assert sorted(summary["added"]) == sorted(names_b)


class TestFsck:
    def test_clean_store_is_ok(self, tmp_path):
        _write(tmp_path)
        report = artifacts.fsck(str(tmp_path))
        assert report["ok"] and report["findings"] == []
        assert report["packs_checked"] == 1 and report["machine_rows"] == 3

    def test_truncated_pack_is_a_finding_not_a_repair(self, tmp_path):
        _, _, pack_id = _write(tmp_path)
        store = artifacts.open_store(str(tmp_path))
        path = os.path.join(
            artifacts.packs_dir(str(tmp_path)), store.packs[pack_id]["file"]
        )
        with open(path, "r+b") as fh:
            fh.truncate(64)
        report = artifacts.fsck(str(tmp_path), repair=True)
        assert not report["ok"]
        assert any(f["kind"] == "pack" for f in report["findings"])
        assert os.path.exists(path), "fsck never deletes referenced files"

    def test_orphan_tmp_swept_on_repair(self, tmp_path):
        _write(tmp_path)
        pdir = artifacts.packs_dir(str(tmp_path))
        orphan = os.path.join(pdir, f"deadbeef.pack.tmp.{os.getpid()}")
        with open(orphan, "wb") as fh:
            fh.write(b"half-written")
        report = artifacts.fsck(str(tmp_path))
        assert not report["ok"]  # report-only: finding stands
        assert os.path.exists(orphan)
        report = artifacts.fsck(str(tmp_path), repair=True)
        assert report["ok"] and report["repaired"]
        assert not os.path.exists(orphan)

    def test_stale_generation_sidecar_repaired(self, tmp_path):
        _write(tmp_path)
        artifacts.stamp_generation(str(tmp_path))
        pdir = artifacts.packs_dir(str(tmp_path))
        sidecar = os.path.join(pdir, artifacts.GENERATION_FILE)
        with open(sidecar, "w") as fh:
            fh.write("0")  # crash left the sidecar a generation behind
        report = artifacts.fsck(str(tmp_path), repair=True)
        assert report["ok"]
        assert any(f["kind"] == "sidecar" for f in report["findings"])
        with open(sidecar) as fh:
            assert int(fh.read().strip()) == report["generation"]


class TestRefsAndRegistry:
    def test_pack_ref_parses(self, tmp_path):
        ref = artifacts.machine_ref(str(tmp_path), "m-0")
        assert artifacts.is_pack_ref(ref)
        directory, name = artifacts.parse_ref(ref)
        assert name == "m-0"
        assert directory.endswith(artifacts.PACKS_DIR)

    def test_resolve_cached_hit_and_misses(self, tmp_path):
        _write(tmp_path)
        ref = artifacts.machine_ref(str(tmp_path), "m-1")
        assert artifacts.resolve_cached(ref, "key-1") == ref
        # wrong key -> miss (slot was overwritten by a different build)
        assert artifacts.resolve_cached(ref, "other") is None
        # unknown machine -> miss
        missing = artifacts.machine_ref(str(tmp_path), "ghost")
        assert artifacts.resolve_cached(missing, "key-1") is None
        # vanished index -> miss, not a crash
        shutil.rmtree(artifacts.packs_dir(str(tmp_path)))
        assert artifacts.resolve_cached(ref, "key-1") is None

    def test_registry_write_key_fsyncs_parent_dir(self, tmp_path, monkeypatch):
        """ISSUE 6 satellite: the atomic rename alone is not durable —
        the parent directory must fsync after it, or a crash can keep
        the registry entry while its pack never landed."""
        synced = []
        real_fsync = os.fsync
        real_open = os.open

        opened = {}

        def tracking_open(path, flags, *a, **kw):
            fd = real_open(path, flags, *a, **kw)
            opened[fd] = path
            return fd

        def tracking_fsync(fd):
            synced.append(opened.get(fd, fd))
            return real_fsync(fd)

        monkeypatch.setattr(os, "open", tracking_open)
        monkeypatch.setattr(os, "fsync", tracking_fsync)
        reg = str(tmp_path / "reg")
        disk_registry.write_key(reg, "abc123", "value")
        assert disk_registry.get_value(reg, "abc123") == "value"
        assert reg in synced, "parent directory fsynced after the rename"


class TestManifestPruning:
    def test_stale_machines_prune_from_kept_rows(self, tmp_path):
        """ISSUE 6 satellite regression: a partial rebuild that shrinks
        a bucket must drop machines (and whole rows) no longer present,
        instead of union-merging stale (signature, bucket) rows forever.
        """
        from gordo_tpu.compile import load_warmup_manifest
        from gordo_tpu.compile.warmup import write_warmup_manifest

        out = str(tmp_path)
        write_warmup_manifest(out, [
            {"signature": "s1", "machines": ["a", "b"], "n_machines": 2,
             "n_features": 3, "n_outputs": 3, "lookback": 1},
            {"signature": "s2", "machines": ["c"], "n_machines": 1,
             "n_features": 3, "n_outputs": 3, "lookback": 1},
        ])
        # partial rebuild touching only "d": machine "b" vanished from
        # disk and every machine of row s2 is gone
        write_warmup_manifest(
            out,
            [{"signature": "s3", "machines": ["d"], "n_machines": 1,
              "n_features": 3, "n_outputs": 3, "lookback": 1}],
            live_machines={"a", "d"},
        )
        manifest = load_warmup_manifest(out)
        rows = {
            e["signature"]: e["machines"] for e in manifest["programs"]
        }
        assert rows == {"s1": ["a"], "s3": ["d"]}
        assert all(
            e["n_machines"] == len(e["machines"])
            for e in manifest["programs"]
        )

    def test_without_live_set_keeps_union_merge_behavior(self, tmp_path):
        from gordo_tpu.compile import load_warmup_manifest
        from gordo_tpu.compile.warmup import write_warmup_manifest

        out = str(tmp_path)
        write_warmup_manifest(out, [
            {"signature": "s1", "machines": ["a", "b"], "n_machines": 2},
        ])
        write_warmup_manifest(out, [
            {"signature": "s2", "machines": ["c"], "n_machines": 1},
        ])
        manifest = load_warmup_manifest(out)
        assert {e["signature"] for e in manifest["programs"]} == {"s1", "s2"}


class TestLintGates:
    @staticmethod
    def _lint(path):
        spec = importlib.util.spec_from_file_location(
            "gordo_lint", os.path.join(
                os.path.dirname(os.path.dirname(__file__)),
                "scripts", "lint.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.lint_file(path)

    def test_per_machine_path_construction_rejected(self, tmp_path):
        bad = tmp_path / "gordo_tpu" / "serve" / "thing.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "import os\np = os.path.join('d', 'model.pkl')\n"
        )
        findings = self._lint(str(bad))
        assert any("artifact path construction" in f[2] for f in findings)

    def test_artifacts_package_zero_copy_gate(self, tmp_path):
        bad = tmp_path / "gordo_tpu" / "artifacts" / "thing.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "import numpy as np\nimport jax\n"
            "def load(xs):\n    return np.stack(xs)\n"
            "def other(t):\n    return jax.device_put(t)\n"
            "def to_device(t):\n    return jax.device_put(t)\n"
        )
        msgs = [f[2] for f in self._lint(str(bad))]
        assert any("zero-copy" in m for m in msgs)
        assert any("device_put outside to_device" in m for m in msgs)
        assert sum("device_put outside" in m for m in msgs) == 1

    def test_repo_is_clean_under_the_new_gates(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in (
            os.path.join("gordo_tpu", "serve", "server.py"),
            os.path.join("gordo_tpu", "serve", "fleet_scorer.py"),
            os.path.join("gordo_tpu", "artifacts", "pack.py"),
            os.path.join("gordo_tpu", "artifacts", "__init__.py"),
        ):
            assert self._lint(os.path.join(repo, rel)) == [], rel


# ---------------------------------------------------------------------------
# v1 <-> v2 parity (slow lane — the CI test-full job)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestV1V2Parity:
    @pytest.fixture(scope="class")
    def built(self, tmp_path_factory):
        from gordo_tpu.builder import build_project
        from gordo_tpu.workflow.config import Machine

        base = tmp_path_factory.mktemp("parity")
        machines = [
            Machine.from_config({
                "name": f"pm-{i}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": ["a", "b", "c"],
                    "train_start_date": "2017-12-25T06:00:00Z",
                    "train_end_date": "2017-12-26T06:00:00Z",
                },
            })
            for i in range(5)
        ]
        dirs = {}
        for fmt in ("v1", "v2"):
            out = str(base / fmt)
            reg = str(base / f"reg-{fmt}")
            result = build_project(
                machines, out, model_register_dir=reg,
                max_bucket_size=2, artifact_format=fmt,
            )
            assert not result.failed
            assert result.summary()["artifact_format"] == fmt
            dirs[fmt] = (out, reg)
        return machines, dirs

    def test_registry_keys_identical(self, built):
        _, dirs = built
        k1 = disk_registry.list_keys(dirs["v1"][1])
        k2 = disk_registry.list_keys(dirs["v2"][1])
        assert k1 == k2 and len(k1) == 5

    def test_v2_writes_packs_not_machine_dirs(self, built):
        machines, dirs = built
        out2 = dirs["v2"][0]
        info = artifacts.store_info(out2)
        assert info["format"] == "v2-packs"
        assert info["packs"] == 3  # 5 machines at bucket 2
        assert info["dir_machines"] == 0
        for m in machines:
            assert not os.path.isdir(os.path.join(out2, m.name))

    def test_scoring_byte_identical_with_one_device_put_per_pack(self, built):
        from gordo_tpu.serve.server import ModelCollection

        _, dirs = built
        c1 = ModelCollection.from_directory(dirs["v1"][0])
        c2 = ModelCollection.from_directory(dirs["v2"][0])
        assert c2.pack_store is not None and c1.pack_store is None
        rng = np.random.default_rng(0)
        X = {
            n: rng.standard_normal((300, 3)).astype(np.float32)
            for n in c1.entries
        }
        d0 = artifacts.device_put_count()
        o2 = c2.fleet_scorer.score_all(X)
        dputs = artifacts.device_put_count() - d0
        # telemetry attestation: exactly ONE whole-pack transfer per pack
        assert dputs == len(c2.pack_store.packs) == 3
        o1 = c1.fleet_scorer.score_all(X)
        for n in o1:
            for k in o1[n]:
                assert (
                    np.asarray(o1[n][k]).tobytes()
                    == np.asarray(o2[n][k]).tobytes()
                ), (n, k)
        # per-machine route parity too
        s1 = c1.entries["pm-0"].scorer.anomaly_arrays(X["pm-0"])
        s2 = c2.entries["pm-0"].scorer.anomaly_arrays(X["pm-0"])
        for k in s1:
            assert (
                np.asarray(s1[k]).tobytes() == np.asarray(s2[k]).tobytes()
            ), k

    def test_v2_rerun_cache_hits_through_pack_refs(self, built, tmp_path):
        from gordo_tpu.builder import build_project

        machines, dirs = built
        out2, reg2 = dirs["v2"]
        rerun = build_project(
            machines, out2, model_register_dir=reg2,
            max_bucket_size=2, artifact_format="v2",
        )
        assert sorted(rerun.cached) == sorted(m.name for m in machines)
        assert all(
            artifacts.is_pack_ref(p) for p in rerun.artifacts.values()
        )

    def test_repack_then_unpack_round_trip(self, built, tmp_path):
        from gordo_tpu.serve.server import ModelCollection

        _, dirs = built
        src = str(tmp_path / "work")
        shutil.copytree(dirs["v1"][0], src)
        summary = artifacts.repack(src, max_bucket_size=2)
        assert summary["packs"] == 3 and not summary["kept_as_dirs"]
        rng = np.random.default_rng(0)
        c1 = ModelCollection.from_directory(dirs["v1"][0])
        X = {
            n: rng.standard_normal((300, 3)).astype(np.float32)
            for n in c1.entries
        }
        o1 = c1.fleet_scorer.score_all(X)
        o_packed = ModelCollection.from_directory(
            src
        ).fleet_scorer.score_all(X)
        dest = str(tmp_path / "export")
        artifacts.unpack(src, dest)
        o_unpacked = ModelCollection.from_directory(
            dest
        ).fleet_scorer.score_all(X)
        for n in o1:
            for k in o1[n]:
                want = np.asarray(o1[n][k]).tobytes()
                assert np.asarray(o_packed[n][k]).tobytes() == want
                assert np.asarray(o_unpacked[n][k]).tobytes() == want

    def test_rescan_reloads_after_delta_write(self, built):
        from gordo_tpu.serve.server import ModelCollection

        _, dirs = built
        out2 = dirs["v2"][0]
        coll = ModelCollection.from_directory(out2)
        name = "pm-0"
        entry = coll.entries[name]
        model = entry.model
        # steady state: rescan with nothing changed keeps entries AND the
        # mapped store object
        store_before = coll.pack_store
        assert coll.rescan() == {
            "added": [], "reloaded": [], "removed": [],
        }
        assert coll.pack_store is store_before
        import pickle

        rebuilt = pickle.loads(pickle.dumps(model))
        rebuilt.aggregate_threshold_ = 123.0
        artifacts.delta_write(out2, {name: rebuilt})
        changes = coll.rescan()
        assert name in changes["reloaded"]
        assert coll.entries[name].model.aggregate_threshold_ == 123.0

    def test_manifest_prunes_when_bucket_shrinks(self, built, tmp_path):
        from gordo_tpu.builder import build_project
        from gordo_tpu.compile import load_warmup_manifest
        from gordo_tpu.workflow.config import Machine

        machines, dirs = built
        out = str(tmp_path / "shrink")
        shutil.copytree(dirs["v2"][0], out)
        # machine pm-4 leaves the project: drop it from disk, then
        # partially rebuild one other machine with a changed config
        store = artifacts.open_store(out)
        doc = json.load(open(os.path.join(
            artifacts.packs_dir(out), "index.json"
        )))
        del doc["machines"]["pm-4"]
        json.dump(doc, open(os.path.join(
            artifacts.packs_dir(out), "index.json"
        ), "w"))
        changed = Machine.from_config({
            "name": "pm-0",
            "dataset": {
                "type": "RandomDataset",
                "tag_list": ["a", "b", "c"],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-26T12:00:00Z",
            },
        })
        result = build_project(
            [changed], out, max_bucket_size=2, artifact_format="v2",
        )
        assert not result.failed
        manifest = load_warmup_manifest(out)
        listed = {
            m for e in manifest["programs"] for m in e["machines"]
        }
        assert "pm-4" not in listed, "stale machine pruned from manifest"
        assert "pm-0" in listed
        del store  # silence unused warning; keeps mmap alive above


@pytest.mark.slow
class TestMixedLayout:
    def test_v2_build_with_single_fallback_serves_both(self, tmp_path):
        """Non-fleetable machines still write v1 dirs inside a v2 build;
        discovery and the collection serve the mixed layout."""
        import yaml

        from gordo_tpu.builder import build_project
        from gordo_tpu.serve.server import ModelCollection
        from gordo_tpu.workflow.config import Machine

        plain = yaml.safe_load("""
gordo_tpu.pipeline.Pipeline:
  steps:
    - gordo_tpu.ops.scalers.MinMaxScaler
    - gordo_tpu.models.estimator.AutoEncoder:
        kind: feedforward_hourglass
        epochs: 2
""")
        dataset = {
            "type": "RandomDataset",
            "tag_list": ["a", "b", "c"],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-26T06:00:00Z",
        }
        machines = [
            Machine.from_config({"name": "fleet-0", "dataset": dataset}),
            Machine.from_config({"name": "fleet-1", "dataset": dataset}),
            Machine.from_config(
                {"name": "plain-0", "dataset": dataset, "model": plain}
            ),
        ]
        out = str(tmp_path / "mixed")
        result = build_project(machines, out, artifact_format="v2")
        assert not result.failed
        assert sorted(result.fleet_built) == ["fleet-0", "fleet-1"]
        assert result.single_built == ["plain-0"]
        info = artifacts.store_info(out)
        assert info["packed_machines"] == 2 and info["dir_machines"] == 1
        coll = ModelCollection.from_directory(out)
        assert sorted(coll.entries) == ["fleet-0", "fleet-1", "plain-0"]
        X = np.random.default_rng(0).standard_normal(
            (40, 3)
        ).astype(np.float32)
        assert coll.entries["plain-0"].scorer.predict(X).shape == (40, 3)
        out_fleet = coll.fleet_scorer.score_all({"fleet-0": X})
        assert "total-anomaly-score" in out_fleet["fleet-0"]


@pytest.mark.slow
class TestHotReload:
    """ISSUE 11: zero-downtime delta hot reload of a serving collection.

    One built 5-machine v2 project (class-scoped, like TestV1V2Parity);
    the tests advance its generation with delta_writes and assert the
    serving collection follows in O(changed-machines): pack-granular
    device transfers, wholesale bucket reuse, byte-identity with a cold
    load, and per-machine generation consistency under concurrent
    scoring."""

    @pytest.fixture(scope="class")
    def served(self, tmp_path_factory):
        from gordo_tpu.builder import build_project
        from gordo_tpu.workflow.config import Machine

        out = str(tmp_path_factory.mktemp("hotreload") / "v2")
        machines = [
            Machine.from_config({
                "name": f"pm-{i}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": ["a", "b", "c"],
                    "train_start_date": "2017-12-25T06:00:00Z",
                    "train_end_date": "2017-12-26T06:00:00Z",
                },
            })
            for i in range(5)
        ]
        result = build_project(
            machines, out, max_bucket_size=2, artifact_format="v2",
        )
        assert not result.failed
        assert artifacts.read_generation(out) >= 1
        return out

    def test_delta_reload_is_pack_granular_and_byte_identical(self, served):
        import pickle

        from gordo_tpu.serve.server import ModelCollection

        coll = ModelCollection.from_directory(served)
        rng = np.random.default_rng(0)
        X = {
            n: rng.standard_normal((300, 3)).astype(np.float32)
            for n in coll.entries
        }
        before = coll.fleet_scorer.score_all(X)
        scorer_before = coll._fleet_scorer
        buckets_before = list(scorer_before.buckets)

        name = "pm-0"
        rebuilt = pickle.loads(pickle.dumps(coll.entries[name].model))
        rebuilt.aggregate_threshold_ = 123.0
        artifacts.delta_write(served, {name: rebuilt})
        d0 = artifacts.device_put_count()
        changes = coll.maybe_delta_reload()
        dputs = artifacts.device_put_count() - d0

        assert changes["reloaded"] == [name]
        assert coll.generation == artifacts.read_generation(served)
        assert coll.entries[name].model.aggregate_threshold_ == 123.0
        # O(changed): ONE whole-pack transfer for the one touched pack
        assert dputs == 1
        # the swapped-in scorer reuses every untouched bucket wholesale
        after_scorer = coll._fleet_scorer
        assert after_scorer is not None
        assert after_scorer is not scorer_before
        touched = scorer_before.machine_bucket[name][0]
        for i, (b_old, b_new) in enumerate(
            zip(buckets_before, after_scorer.buckets)
        ):
            assert (b_new is not b_old) == (i == touched), i

        # post-flip scoring is byte-identical to a cold load of the new
        # generation; unchanged machines byte-identical to before
        hot = coll.fleet_scorer.score_all(X)
        cold = ModelCollection.from_directory(
            served
        ).fleet_scorer.score_all(X)
        for n in hot:
            for k in hot[n]:
                assert (
                    np.asarray(hot[n][k]).tobytes()
                    == np.asarray(cold[n][k]).tobytes()
                ), (n, k)
                if n != name:
                    assert (
                        np.asarray(hot[n][k]).tobytes()
                        == np.asarray(before[n][k]).tobytes()
                    ), (n, k)
        assert (
            hot[name]["anomaly-confidence"].tobytes()
            != before[name]["anomaly-confidence"].tobytes()
        )

    def test_concurrent_scoring_during_flip_stays_consistent(self, served):
        import pickle
        import threading

        from gordo_tpu.serve.server import ModelCollection

        coll = ModelCollection.from_directory(served)
        rng = np.random.default_rng(1)
        X = {
            n: rng.standard_normal((200, 3)).astype(np.float32)
            for n in coll.entries
        }
        base = coll.fleet_scorer.score_all(X)
        name = "pm-1"
        rebuilt = pickle.loads(pickle.dumps(coll.entries[name].model))
        rebuilt.aggregate_threshold_ = 77.0

        errors, outputs = [], []
        stop = threading.Event()

        def loop():
            try:
                while not stop.is_set():
                    outputs.append(coll.fleet_scorer.score_all(X))
            except Exception as exc:  # pragma: no cover - the assertion
                errors.append(exc)

        th = threading.Thread(target=loop)
        th.start()
        try:
            artifacts.delta_write(served, {name: rebuilt})
            changes = coll.maybe_delta_reload()
            outputs_len_at_flip = len(outputs)
        finally:
            stop.set()
            th.join(timeout=60)

        assert not errors, errors
        assert changes["reloaded"] == [name]
        assert outputs, "scoring ran concurrently with the flip"

        cold = ModelCollection.from_directory(served)
        new = cold.fleet_scorer.score_all(X)
        keys = sorted(base[name])
        old_bytes = tuple(np.asarray(base[name][k]).tobytes() for k in keys)
        new_bytes = tuple(np.asarray(new[name][k]).tobytes() for k in keys)
        assert old_bytes != new_bytes
        for o in outputs:
            got = tuple(np.asarray(o[name][k]).tobytes() for k in keys)
            # every response is one generation or the other — never a
            # torn mix of old params with new thresholds
            assert got in (old_bytes, new_bytes)
            for n in o:
                if n == name:
                    continue
                for k in o[n]:
                    assert (
                        np.asarray(o[n][k]).tobytes()
                        == np.asarray(base[n][k]).tobytes()
                    ), (n, k)
        del outputs_len_at_flip
