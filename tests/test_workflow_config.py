"""Project-config normalization tests (reference:
``tests/gordo_components/workflow/`` — globals overlay, default model
injection, machine-name rules)."""

import pytest

from gordo_tpu.workflow import (
    DEFAULT_MODEL,
    Machine,
    NormalizedConfig,
    load_machine_config,
)

PROJECT_YAML = """
machines:
  - name: machine-one
    dataset:
      tags: [tag-1, tag-2]
      train_start_date: "2020-01-01T00:00:00Z"
      train_end_date: "2020-02-01T00:00:00Z"
  - name: machine-two
    dataset:
      tags: [tag-3, tag-4]
      train_start_date: "2020-01-01T00:00:00Z"
      train_end_date: "2020-02-01T00:00:00Z"
    model:
      gordo_tpu.models.estimator.AutoEncoder:
        kind: feedforward_symmetric
globals:
  dataset:
    resolution: 1h
  metadata:
    owner: team-a
"""


class TestNormalizedConfig:
    def test_globals_overlay_and_default_model(self):
        cfg = NormalizedConfig(load_machine_config(PROJECT_YAML), "proj")
        assert [m.name for m in cfg.machines] == ["machine-one", "machine-two"]
        m1, m2 = cfg.machines
        # globals merged into every machine's dataset / metadata
        assert m1.dataset["resolution"] == "1h"
        assert m1.metadata == {"owner": "team-a"}
        # default model injected when machine + globals define none
        assert m1.model == DEFAULT_MODEL
        # machine-level model wins
        assert "gordo_tpu.models.estimator.AutoEncoder" in m2.model

    def test_machine_overrides_beat_globals(self):
        raw = load_machine_config(PROJECT_YAML)
        raw["machines"][0]["dataset"]["resolution"] = "10min"
        cfg = NormalizedConfig(raw)
        assert cfg.machines[0].dataset["resolution"] == "10min"
        assert cfg.machines[1].dataset["resolution"] == "1h"

    @pytest.mark.parametrize(
        "bad", ["Machine", "has_underscore", "-leading", "trailing-", "a" * 64, ""]
    )
    def test_invalid_names_rejected(self, bad):
        with pytest.raises(ValueError, match="name"):
            Machine(name=bad, dataset={"tags": ["t"]})

    def test_duplicate_names_rejected(self):
        raw = load_machine_config(PROJECT_YAML)
        raw["machines"][1]["name"] = "machine-one"
        with pytest.raises(ValueError, match="Duplicate"):
            NormalizedConfig(raw)

    def test_missing_machines_key(self):
        with pytest.raises(ValueError, match="machines"):
            NormalizedConfig({"globals": {}})

    def test_machine_requires_dataset(self):
        with pytest.raises(ValueError, match="dataset"):
            Machine(name="ok-name", dataset={})
