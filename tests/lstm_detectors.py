"""Shared LSTM detector builder for tests.

ONE set of fit-program shapes, used by every test that fits an LSTM
detector in-process.  This is load-bearing beyond deduplication: a fresh
late-suite XLA CPU compile of a NEW LSTM fit shape segfaulted
reproducibly inside ``backend_compile_and_load`` (jax 0.9.0 CPU, ~200
tests of accumulated compile state; the same test alone passed).  Tests
that share these shapes hit the in-process jit cache after the first
fit, so changing the constants here changes every user together — the
coupling breaks loudly, not silently.
"""

import numpy as np

LOOKBACK = 6
ROWS = 160
N_TAGS = 3
BATCH = 64


def fitted_lstm_detector(rng: np.random.Generator, cv: bool = True):
    """Build + (optionally cross-validate) + fit one LSTM diff detector
    with the shared shapes."""
    from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector
    from gordo_tpu.models.estimator import LSTMAutoEncoder
    from gordo_tpu.ops.scalers import MinMaxScaler
    from gordo_tpu.pipeline import Pipeline

    X_train = rng.standard_normal((ROWS, N_TAGS)).astype(np.float32)
    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([
            MinMaxScaler(),
            LSTMAutoEncoder(
                lookback_window=LOOKBACK, epochs=1, batch_size=BATCH
            ),
        ]),
    )
    if cv:
        det.cross_validate(X_train)
    det.fit(X_train)
    return det
