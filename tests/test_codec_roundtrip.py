"""Property-style codec round-trip suite (ISSUE 15 satellite).

Random shapes — including empty, zero-width, F-order and big-endian
inputs — across every supported wire dtype must survive each codec
(GSB1 columnar, msgpack, JSON) VALUE-IDENTICAL, and alien dtypes must
fail the 415 contract (:class:`UnsupportedWireDtype`), never a 500.
The columnar cases also pin the r19 tentpole's parity claim: decoding
the GSB1 encoding of a stacked result is bitwise-equal to decoding the
msgpack encoding of its per-machine split.
"""

import json

import numpy as np
import pytest

from gordo_tpu.serve import codec

SHAPES = [(0,), (1,), (7,), (0, 4), (3, 0), (5, 3), (2, 3, 4), (64, 9)]
WIRE_DTYPES = [
    "float16", "float32", "float64", "bfloat16",
    "<i4", "<i8", "<u1", "|b1",
]


def _rand(rng, shape, name):
    dt = codec.wire_np_dtype(name)
    if dt.kind == "f" or dt.name == "bfloat16":
        return (rng.standard_normal(shape) * 10).astype(dt)
    if dt.kind == "b":
        return rng.integers(0, 2, shape).astype(bool)
    info = np.iinfo(dt)
    return rng.integers(info.min, min(info.max, 1 << 30), shape).astype(dt)


def _assert_value_identical(a, b, ctx):
    b = np.asarray(b)
    assert b.dtype == np.asarray(a).dtype, ctx
    assert np.asarray(a).tobytes() == b.tobytes(), ctx


class TestMsgpackRoundTrip:
    @pytest.mark.parametrize("name", WIRE_DTYPES)
    @pytest.mark.parametrize("shape", SHAPES)
    def test_exact(self, name, shape):
        rng = np.random.default_rng(hash((name, shape)) % 2**32)
        a = _rand(rng, shape, name)
        out = codec.unpackb(codec.packb({"x": a}))["x"]
        _assert_value_identical(a, out, (name, shape))

    def test_f_order_input(self):
        a = np.asfortranarray(
            np.arange(30, dtype=np.float32).reshape(5, 6)
        )
        assert not a.flags.c_contiguous
        out = codec.unpackb(codec.packb({"x": a}))["x"]
        _assert_value_identical(np.ascontiguousarray(a), out, "F-order")

    def test_big_endian_input_normalized(self):
        a = np.arange(20, dtype=">f8").reshape(4, 5)
        out = codec.unpackb(codec.packb({"x": a}))["x"]
        # the wire is little-endian by contract; values are identical
        assert out.dtype == np.dtype("<f8")
        np.testing.assert_array_equal(out, a.astype("<f8"))

    def test_memoryview_path_matches_tobytes(self):
        """Satellite 2: arrays above the memoryview threshold encode to
        the same wire bytes the tobytes() path produced."""
        rng = np.random.default_rng(0)
        big = rng.standard_normal((100, 17)).astype(np.float64)
        assert big.nbytes >= codec._MEMVIEW_MIN_NBYTES
        buf = codec._array_wire_buffer(big)
        assert isinstance(buf, memoryview)
        assert bytes(buf) == big.tobytes()
        small = big[:1, :3]
        assert isinstance(codec._array_wire_buffer(
            np.ascontiguousarray(small)), bytes)

    def test_alien_dtype_raises_415(self):
        with pytest.raises(codec.UnsupportedWireDtype):
            codec.unpackb(
                codec.packb(
                    {"__nd__": True, "dtype": "complex128", "shape": [1],
                     "data": b"\x00" * 16}
                )
            )


class TestJsonRoundTrip:
    @pytest.mark.parametrize("name", ["float32", "float64"])
    @pytest.mark.parametrize("shape", [(0,), (7,), (5, 3), (3, 0)])
    def test_exact(self, name, shape):
        rng = np.random.default_rng(hash((name, shape)) % 2**32)
        a = _rand(rng, shape, name)
        out = np.asarray(
            json.loads(codec.dumps_bytes({"x": a}))["x"], dtype=a.dtype
        )
        _assert_value_identical(a, out, (name, shape))


class TestColumnarRoundTrip:
    def _result(self, rng, dtype_name="float32"):
        dt = codec.wire_np_dtype(dtype_name)
        scores = _rand(rng, (4, 11, 3), dtype_name)
        total = _rand(rng, (4, 11), dtype_name)
        thr = _rand(rng, (4, 3), "float64")
        agg = _rand(rng, (4,), "float32")
        machines = {}
        for i, rows in enumerate((11, 7, 1, 0)):
            machines[f"m{i}"] = {
                "tag-anomaly-scores": (0, i, rows),
                "total-anomaly-score": (1, i, rows),
                "tag-anomaly-thresholds": (2, i, None),
                "total-anomaly-threshold": (3, i, None),
            }
        return codec.ColumnarResult(
            blocks=[scores.astype(dt), total.astype(dt), thr, agg],
            machines=machines,
            scalar_blocks={3},
            rest={
                "fellback": {
                    "model-output": _rand(rng, (6, 3), "float32"),
                    "total-anomaly-threshold": 1.25,
                },
                "broken": {"error": "no such machine"},
                "m1": {"start": ["2020-01-01T00:00:00Z"],
                       "end": ["2020-01-01T00:10:00Z"]},
            },
        )

    @pytest.mark.parametrize("dtype_name", ["float32", "float64", "bfloat16"])
    def test_columnar_equals_msgpack_of_split(self, dtype_name):
        """The tentpole parity pin: GSB1 decode == msgpack decode of the
        per-machine split, bitwise, including padded-slot extents,
        scalar thresholds, rest-blob machines and time-column merges."""
        rng = np.random.default_rng(5)
        col = self._result(rng, dtype_name)
        payload_split = {"data": col.split(), "time-seconds": 0.25}
        via_msgpack = codec.unpackb(codec.packb(payload_split))
        via_columnar = codec.decode_columnar(
            codec.encode_columnar({"data": col, "time-seconds": 0.25})
        )
        assert via_columnar["time-seconds"] == 0.25
        assert sorted(via_columnar["data"]) == sorted(via_msgpack["data"])
        for name, ref in via_msgpack["data"].items():
            got = via_columnar["data"][name]
            assert sorted(got) == sorted(ref), name
            for key, v in ref.items():
                w = got[key]
                if isinstance(v, np.ndarray):
                    _assert_value_identical(v, w, (name, key))
                else:
                    assert v == w and type(v) is type(w), (name, key)

    def test_views_are_zero_copy(self):
        rng = np.random.default_rng(6)
        body = codec.encode_columnar({"data": self._result(rng)})
        out = codec.decode_columnar(body)
        arr = out["data"]["m0"]["tag-anomaly-scores"]
        # np.frombuffer views are read-only windows into the body buffer
        assert not arr.flags.writeable
        assert not arr.flags.owndata

    def test_dtype_param_casts_blocks_not_scalars(self):
        rng = np.random.default_rng(7)
        col = self._result(rng)
        agg0 = float(np.asarray(col.blocks[3])[0])
        encode, ct = codec.negotiate(
            f"{codec.COLUMNAR_CONTENT_TYPE};dtype=bfloat16, "
            f"{codec.MSGPACK_CONTENT_TYPE}"
        )
        assert ct == codec.COLUMNAR_CONTENT_TYPE
        out = codec.decode_columnar(encode({"data": col}))
        assert out["data"]["m0"]["tag-anomaly-scores"].dtype.name == "bfloat16"
        assert out["data"]["m0"]["tag-anomaly-thresholds"].dtype.name == (
            "bfloat16"
        )
        # scalar threshold parity with msgpack: python float, uncast
        thr = out["data"]["m0"]["total-anomaly-threshold"]
        assert isinstance(thr, float) and thr == agg0

    def test_no_op_dtype_cast_elided(self):
        """Satellite 1: a float leaf already at the negotiated wire dtype
        is returned as-is — no astype copy."""
        import ml_dtypes

        a32 = np.ones((4, 4), np.float32)
        assert codec._cast_float_arrays(a32, np.dtype(np.float32)) is a32
        bf = np.ones((4, 4), ml_dtypes.bfloat16)
        assert codec._cast_float_arrays(bf, np.dtype(ml_dtypes.bfloat16)) is bf
        # ...and bf16 leaves DO cast when a different dtype is negotiated
        # (their dtype kind is 'V', which the old kind=='f' check missed)
        assert codec._cast_float_arrays(
            bf, np.dtype(np.float32)
        ).dtype == np.float32

    def test_degenerate_non_bulk_object(self):
        """Any response object survives the columnar encoder (zero-block
        body, msgpack rest): the ONE-negotiation-rule holds for every
        route, not just bulk."""
        obj = {"model": {"name": "x"}, "rows": [1, 2, 3],
               "arr": np.arange(5, dtype=np.int64)}
        out = codec.decode_columnar(codec.encode_columnar(obj))
        assert out["model"] == {"name": "x"} and out["rows"] == [1, 2, 3]
        _assert_value_identical(obj["arr"], out["arr"], "arr")

    def test_msgpack_and_json_fallbacks_split(self):
        """A ColumnarResult reaching the msgpack or JSON encoder (e.g. a
        probe without the columnar Accept) degrades to per-machine
        dicts, never a stringified object."""
        rng = np.random.default_rng(8)
        col = self._result(rng)
        mp = codec.unpackb(codec.packb({"data": col}))
        _assert_value_identical(
            np.asarray(col.blocks[0])[0][:11],
            mp["data"]["m0"]["tag-anomaly-scores"], "msgpack fallback",
        )
        js = json.loads(codec.dumps_bytes({"data": col}))
        assert len(js["data"]["m0"]["tag-anomaly-scores"]) == 11

    def test_empty_and_zero_width_blocks(self):
        col = codec.ColumnarResult(
            blocks=[np.zeros((2, 0, 4), np.float32),
                    np.zeros((2, 5, 0), np.float64)],
            machines={"a": {"x": (0, 0, 0), "y": (1, 0, 5)}},
        )
        out = codec.decode_columnar(codec.encode_columnar({"data": col}))
        assert out["data"]["a"]["x"].shape == (0, 4)
        assert out["data"]["a"]["y"].shape == (5, 0)

    def test_bad_magic_rejected(self):
        with pytest.raises(ValueError):
            codec.decode_columnar(b"NOPE" + b"\x00" * 16)

    def test_alien_block_dtype_raises_415(self):
        """A crafted header with an unsupported block dtype fails the
        415 contract (UnsupportedWireDtype), not a numpy crash."""
        body = codec.encode_columnar(
            {"data": codec.ColumnarResult(
                blocks=[np.zeros(4, np.float32)],
                machines={"a": {"x": (0, 0, None)}},
            )}
        )
        header_len = int.from_bytes(body[4:8], "little")
        header = json.loads(body[8:8 + header_len])
        header["blocks"][0]["dtype"] = "complex128"
        evil = json.dumps(header, separators=(",", ":")).encode()
        forged = (
            codec._COLUMNAR_MAGIC
            + len(evil).to_bytes(4, "little")
            + evil
            + body[8 + header_len:]
        )
        with pytest.raises(codec.UnsupportedWireDtype):
            codec.decode_columnar(forged)

    def test_negotiate_alien_dtype_param_raises(self):
        with pytest.raises(codec.UnsupportedWireDtype):
            codec.negotiate(f"{codec.COLUMNAR_CONTENT_TYPE};dtype=int128")


class TestNegotiatePrecedence:
    def test_columnar_wins_over_msgpack(self):
        _, ct = codec.negotiate(
            f"{codec.COLUMNAR_CONTENT_TYPE}, {codec.MSGPACK_CONTENT_TYPE}"
        )
        assert ct == codec.COLUMNAR_CONTENT_TYPE

    def test_msgpack_alone_untouched(self):
        _, ct = codec.negotiate(codec.MSGPACK_CONTENT_TYPE)
        assert ct == codec.MSGPACK_CONTENT_TYPE

    def test_json_fallback_untouched(self):
        _, ct = codec.negotiate("application/json")
        assert ct == "application/json"

    def test_wants_columnar(self):
        assert codec.wants_columnar(
            f"{codec.COLUMNAR_CONTENT_TYPE}, {codec.MSGPACK_CONTENT_TYPE}"
        )
        assert not codec.wants_columnar(codec.MSGPACK_CONTENT_TYPE)
        assert not codec.wants_columnar(None)
