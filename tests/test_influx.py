"""Influx provider/forwarder tests over a mocked ``influxdb`` client.

The reference covered these with a dockerized InfluxDB (SURVEY.md §5);
no docker in this image, so the client module is faked in ``sys.modules``
— exercising query construction, URI parsing, batching, and retry logic
without the real package.
"""

import sys
import types
from unittest import mock

import pandas as pd
import pytest


class FakeDataFrameClient:
    """Records constructor kwargs, queries, and written points."""

    instances: list = []

    def __init__(self, **kwargs):
        self.kwargs = kwargs
        self.queries = []
        self.written = []
        self.dropped = []
        self.created = []
        self.fail_writes = 0  # fail this many write_points calls
        FakeDataFrameClient.instances.append(self)

    def query(self, q):
        self.queries.append(q)
        idx = pd.date_range("2020-01-01", periods=4, freq="10min", tz="UTC")
        return {"sensors": pd.DataFrame({"Value": [1.0, 2.0, 3.0, 4.0]}, index=idx)}

    def write_points(self, frame, measurement=None, tags=None, batch_size=None):
        if self.fail_writes > 0:
            self.fail_writes -= 1
            raise ConnectionError("influx write failed")
        self.written.append(
            {"frame": frame, "measurement": measurement, "tags": tags,
             "batch_size": batch_size}
        )

    def drop_database(self, name):
        self.dropped.append(name)

    def create_database(self, name):
        self.created.append(name)


@pytest.fixture()
def fake_influx(monkeypatch):
    module = types.ModuleType("influxdb")
    module.DataFrameClient = FakeDataFrameClient
    FakeDataFrameClient.instances = []
    monkeypatch.setitem(sys.modules, "influxdb", module)
    return module


class TestInfluxDataProvider:
    def test_uri_parsing(self, fake_influx):
        from gordo_tpu.dataset.data_provider.providers import InfluxDataProvider

        InfluxDataProvider(uri="influxhost:8087/user/pass/sensordb")
        client = FakeDataFrameClient.instances[-1]
        assert client.kwargs == {
            "host": "influxhost",
            "port": 8087,
            "username": "user",
            "password": "pass",
            "database": "sensordb",
        }

    def test_uri_default_port_and_extra_kwargs(self, fake_influx):
        from gordo_tpu.dataset.data_provider.providers import InfluxDataProvider

        InfluxDataProvider(uri="h/u/p/db", ssl=True)
        assert FakeDataFrameClient.instances[-1].kwargs["port"] == 8086
        assert FakeDataFrameClient.instances[-1].kwargs["ssl"] is True

    def test_query_construction_and_series(self, fake_influx):
        from gordo_tpu.dataset.data_provider.providers import InfluxDataProvider

        provider = InfluxDataProvider(
            measurement="sensors", value_name="Value", uri="h:1/u/p/db"
        )
        series = list(
            provider.load_series(
                pd.Timestamp("2020-01-01", tz="UTC"),
                pd.Timestamp("2020-01-02", tz="UTC"),
                ["tag-a", "tag-b"],
            )
        )
        client = FakeDataFrameClient.instances[-1]
        assert len(client.queries) == 2
        q = client.queries[0]
        assert '"Value"' in q and '"sensors"' in q
        assert "2020-01-01" in q and "2020-01-02" in q
        assert "\"tag\" = 'tag-a'" in q
        assert [s.name for s in series] == ["tag-a", "tag-b"]
        assert len(series[0]) == 4

    def test_pickles_without_client(self, fake_influx):
        import pickle

        from gordo_tpu.dataset.data_provider.providers import InfluxDataProvider

        provider = InfluxDataProvider(uri="h:1/u/p/db")
        clone = pickle.loads(pickle.dumps(provider))
        assert clone._client is None

    def test_import_gated_without_package(self):
        from gordo_tpu.dataset.data_provider.providers import InfluxDataProvider

        with mock.patch.dict(sys.modules, {"influxdb": None}):
            with pytest.raises(ImportError, match="influxdb"):
                InfluxDataProvider(uri="h:1/u/p/db")


def _frame():
    idx = pd.date_range("2020-01-01", periods=3, freq="10min", tz="UTC")
    frame = pd.DataFrame(
        {
            ("model-output", "t1"): [1.0, 2.0, 3.0],
            ("model-output", "t2"): [1.0, 2.0, 3.0],
            ("total-anomaly-score", ""): [0.1, 0.2, 0.3],
        },
        index=idx,
    )
    frame.columns = pd.MultiIndex.from_tuples(frame.columns)
    return frame


class TestForwardPredictionsIntoInflux:
    def _make(self, fake_influx, **kwargs):
        from gordo_tpu.client.forwarders import ForwardPredictionsIntoInflux

        return ForwardPredictionsIntoInflux(
            destination_influx_uri="h:8086/user:pa:ss/preddb", **kwargs
        )

    def test_uri_parsing_allows_colon_in_password(self, fake_influx):
        self._make(fake_influx)
        client = FakeDataFrameClient.instances[-1]
        assert client.kwargs["username"] == "user"
        assert client.kwargs["password"] == "pa:ss"
        assert client.kwargs["database"] == "preddb"
        assert client.kwargs["port"] == 8086

    def test_bad_uri_rejected(self, fake_influx):
        from gordo_tpu.client.forwarders import ForwardPredictionsIntoInflux

        with pytest.raises(ValueError, match="destination_influx_uri"):
            ForwardPredictionsIntoInflux(destination_influx_uri="nonsense")

    def test_recreate_drops_and_creates(self, fake_influx):
        self._make(fake_influx, destination_influx_recreate=True)
        client = FakeDataFrameClient.instances[-1]
        assert client.dropped == ["preddb"] and client.created == ["preddb"]

    def test_forward_writes_one_measurement_per_top_level(self, fake_influx):
        fwd = self._make(fake_influx)
        fwd.forward(_frame(), "machine-a")
        client = FakeDataFrameClient.instances[-1]
        measurements = {w["measurement"] for w in client.written}
        assert measurements == {"model-output", "total-anomaly-score"}
        for w in client.written:
            assert w["tags"] == {"machine": "machine-a"}
            assert w["batch_size"] == 10_000
        total = next(
            w for w in client.written
            if w["measurement"] == "total-anomaly-score"
        )
        # empty second-level label becomes the measurement name
        assert list(total["frame"].columns) == ["total-anomaly-score"]

    def test_retry_then_success(self, fake_influx):
        fwd = self._make(fake_influx)
        client = FakeDataFrameClient.instances[-1]
        client.fail_writes = 2
        fwd.forward(_frame(), "machine-a")
        assert len(client.written) == 2  # both measurements landed

    def test_retries_exhausted_raises(self, fake_influx):
        fwd = self._make(fake_influx, n_retries=2)
        client = FakeDataFrameClient.instances[-1]
        client.fail_writes = 99
        with pytest.raises(ConnectionError):
            fwd.forward(_frame(), "machine-a")

    def test_api_key_header(self, fake_influx):
        self._make(fake_influx, destination_influx_api_key="secret-key")
        client = FakeDataFrameClient.instances[-1]
        assert client.kwargs["headers"] == {"Authorization": "secret-key"}


def test_query_escapes_quotes_in_tag_names(fake_influx):
    """VERDICT r3 weak #7: a tag name containing ``'`` must not break (or
    rewrite) the InfluxQL query — it is escaped into the string literal."""
    from gordo_tpu.dataset.data_provider.providers import InfluxDataProvider

    provider = InfluxDataProvider(
        measurement='se"ns', value_name="Value", uri="h:1/u/p/db"
    )
    list(
        provider.load_series(
            pd.Timestamp("2020-01-01", tz="UTC"),
            pd.Timestamp("2020-01-02", tz="UTC"),
            ["o'brien-tag"],
        )
    )
    q = FakeDataFrameClient.instances[-1].queries[0]
    assert "\"tag\" = 'o\\'brien-tag'" in q
    assert 'FROM "se\\"ns"' in q
