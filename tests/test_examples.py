"""Worked examples run green in the slow lane — docs that rot fail CI
(the reference kept its notebook walkthroughs executable the same way)."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ragged_fleet_example_runs():
    """examples/ragged_fleet.py: ragged plan warning → pad_lengths build →
    Argo emission → client bulk scoring, end to end."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "ragged_fleet.py")],
        capture_output=True, text=True, timeout=540, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-2000:]}"
    )
    assert "ragged_fleet example: OK" in proc.stdout
    assert "distinct lengths" in proc.stdout  # the plan's ragged warning
