"""Definition interpreter + artifact serialization tests.

Mirrors the reference's serializer test strategy (SURVEY.md §5): round-trip
idempotence and dump/load prediction equality.
"""

import numpy as np
import pytest

from gordo_tpu import serializer
from gordo_tpu.models.estimator import AutoEncoder
from gordo_tpu.ops.scalers import MinMaxScaler, StandardScaler
from gordo_tpu.pipeline import Pipeline
from gordo_tpu.serializer import from_definition, into_definition


REFERENCE_STYLE_DEFINITION = {
    "sklearn.pipeline.Pipeline": {
        "steps": [
            "sklearn.preprocessing.MinMaxScaler",
            {
                "gordo_components.model.models.KerasAutoEncoder": {
                    "kind": "feedforward_hourglass",
                    "epochs": 2,
                    "batch_size": 32,
                }
            },
        ]
    }
}


def test_reference_definition_builds_tpu_pipeline():
    pipe = from_definition(REFERENCE_STYLE_DEFINITION)
    assert isinstance(pipe, Pipeline)
    assert isinstance(pipe[0], MinMaxScaler)
    assert isinstance(pipe[-1], AutoEncoder)
    assert pipe[-1].kind == "feedforward_hourglass"


def test_string_definition_instantiates():
    obj = from_definition("sklearn.preprocessing.StandardScaler")
    assert isinstance(obj, StandardScaler)


def test_nested_kwargs_recursed():
    defn = {
        "gordo_tpu.pipeline.TransformedTargetRegressor": {
            "regressor": {
                "gordo_tpu.models.estimator.AutoEncoder": {"kind": "feedforward_model"}
            },
            "transformer": "gordo_tpu.ops.scalers.MinMaxScaler",
        }
    }
    obj = from_definition(defn)
    assert isinstance(obj.regressor, AutoEncoder)
    assert isinstance(obj.transformer, MinMaxScaler)


def test_into_definition_roundtrip_idempotent():
    pipe = from_definition(REFERENCE_STYLE_DEFINITION)
    defn1 = into_definition(pipe)
    pipe2 = from_definition(defn1)
    defn2 = into_definition(pipe2)
    assert defn1 == defn2


def test_named_steps_roundtrip():
    pipe = Pipeline([("scale", MinMaxScaler()), ("model", AutoEncoder())])
    defn = into_definition(pipe)
    pipe2 = from_definition(defn)
    assert list(pipe2.named_steps) == ["scale", "model"]
    assert isinstance(pipe2.named_steps["scale"], MinMaxScaler)


def test_disallowed_import_rejected():
    with pytest.raises(ValueError):
        from_definition("os.path.join")


def test_dump_load_prediction_equality(tmp_path, sine_tags):
    pipe = from_definition(REFERENCE_STYLE_DEFINITION)
    pipe.fit(sine_tags, sine_tags)
    pred1 = pipe.predict(sine_tags)

    out = serializer.dump(pipe, str(tmp_path / "model"), metadata={"name": "m1"})
    loaded = serializer.load(out)
    pred2 = loaded.predict(sine_tags)
    np.testing.assert_allclose(pred1, pred2, rtol=1e-5, atol=1e-5)

    meta = serializer.load_metadata(out)
    assert meta["name"] == "m1"


def test_dumps_loads_bytes(sine_tags):
    pipe = from_definition(REFERENCE_STYLE_DEFINITION)
    pipe.fit(sine_tags)
    blob = serializer.dumps(pipe)
    loaded = serializer.loads(blob)
    np.testing.assert_allclose(
        pipe.predict(sine_tags), loaded.predict(sine_tags), rtol=1e-5, atol=1e-5
    )
