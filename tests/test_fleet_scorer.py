"""Stacked multi-machine serving tests: the FleetScorer must match each
machine's own CompiledScorer/model output exactly."""

import asyncio

import numpy as np
import pytest

from gordo_tpu.builder import build_project
from gordo_tpu.serve import ModelCollection, build_app
from gordo_tpu.serve.fleet_scorer import FleetScorer
from gordo_tpu.serve.scorer import CompiledScorer
from gordo_tpu.workflow import NormalizedConfig

# heavy integration module: excluded from the fast CI lane
pytestmark = pytest.mark.slow

PROJECT = {
    "machines": [
        {"name": f"fs-machine-{i}", "dataset": {
            "type": "RandomDataset",
            "tags": [f"fs-{i}-{j}" for j in range(3)],
            "train_start_date": "2017-12-25T06:00:00Z",
            "train_end_date": "2017-12-26T06:00:00Z",
        }}
        for i in range(4)
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {"gordo_tpu.models.estimator.AutoEncoder": {
                                "kind": "feedforward_hourglass",
                                "epochs": 1,
                                "batch_size": 64,
                            }},
                        ]
                    }
                }
            }
        }
    },
}


@pytest.fixture(scope="module")
def models(tmp_path_factory):
    out = tmp_path_factory.mktemp("fs-artifacts")
    result = build_project(NormalizedConfig(PROJECT, "fsproj").machines, str(out))
    assert not result.failed
    # load through the artifact plane: the build now writes v2 packs by
    # default, so result.artifacts values are pack refs, not dirs
    from gordo_tpu import artifacts

    _, refs = artifacts.discover(str(out))
    return {r.name: r.load_model() for r in refs}, str(out)


class TestFleetScorer:
    def test_buckets_stack_homogeneous_machines(self, models):
        scorer = FleetScorer.from_models(models[0])
        assert scorer.n_stacked == 4
        assert len(scorer.buckets) == 1
        assert not scorer.fallbacks

    def test_matches_per_machine_scorer(self, models):
        scorer = FleetScorer.from_models(models[0])
        rng = np.random.default_rng(5)
        X_by = {
            name: rng.standard_normal((40 + 7 * i, 3)).astype(np.float32)
            for i, name in enumerate(sorted(models[0]))
        }
        bulk = scorer.score_all(X_by)
        for name, model in models[0].items():
            single = CompiledScorer(model).anomaly_arrays(X_by[name])
            for key in ("model-output", "tag-anomaly-scores",
                        "total-anomaly-score", "anomaly-confidence"):
                np.testing.assert_allclose(
                    bulk[name][key], single[key], rtol=1e-5, atol=1e-6,
                    err_msg=f"{name}/{key}",
                )
            assert bulk[name]["total-anomaly-threshold"] == pytest.approx(
                single["total-anomaly-threshold"]
            )

    def test_subset_of_machines(self, models):
        scorer = FleetScorer.from_models(models[0])
        names = sorted(models[0])[:2]
        X_by = {n: np.zeros((10, 3), np.float32) for n in names}
        out = scorer.score_all(X_by)
        assert sorted(out) == names
        assert out[names[0]]["model-output"].shape == (10, 3)

    def test_subset_dispatch_matches_per_machine(self, models):
        """Partial-bucket requests ride the gathered subset program (not a
        dummy-padded full-bucket dispatch); results must still match each
        machine's own scorer exactly, for any machine positions, with full
        and subset shapes alternating over the same bucket."""
        scorer = FleetScorer.from_models(models[0])
        rng = np.random.default_rng(9)
        names = sorted(models[0])
        full = {
            n: rng.standard_normal((24, 3)).astype(np.float32) for n in names
        }
        scorer.score_all(full)  # warm the full-bucket path first
        for subset_names in ([names[2]], [names[3], names[1]]):
            X_by = {
                n: rng.standard_normal((24, 3)).astype(np.float32)
                for n in subset_names
            }
            out = scorer.score_all(X_by)
            assert sorted(out) == sorted(subset_names)
            for n in subset_names:
                single = CompiledScorer(models[0][n]).anomaly_arrays(X_by[n])
                for key in ("model-output", "tag-anomaly-scores",
                            "total-anomaly-score", "anomaly-confidence"):
                    np.testing.assert_allclose(
                        out[n][key], single[key], rtol=1e-5, atol=1e-6,
                        err_msg=f"{n}/{key}",
                    )
                assert out[n]["total-anomaly-threshold"] == pytest.approx(
                    single["total-anomaly-threshold"]
                )
        # the subset PROGRAM must actually have run (not the dummy-padded
        # full-bucket path): subset-sized stacking buffers prove the route
        bucket = scorer.buckets[0]
        machine_dims = {shape[0] for shape in bucket._stack_bufs}
        assert {1, 2, len(bucket.names)} <= machine_dims
        # full-bucket calls still exact after subset calls reused buffers
        again = scorer.score_all(full)
        for n in names:
            single = CompiledScorer(models[0][n]).anomaly_arrays(full[n])
            np.testing.assert_allclose(
                again[n]["total-anomaly-score"],
                single["total-anomaly-score"], rtol=1e-5, atol=1e-6,
            )


def test_dispatch_all_assemble_matches_score_all(models):
    """The dispatch/assemble split (the coalescer's finish-pool contract)
    must produce byte-identical results to score_all — on another thread,
    for BOTH the gathered-subset and the full-bucket dispatch paths, and
    for mixed valid/invalid machine sets."""
    import threading

    scorer = FleetScorer.from_models(models[0])
    rng = np.random.default_rng(11)
    names = sorted(models[0])
    cases = {
        "subset": {names[0]: rng.standard_normal((40, 3)).astype(np.float32)},
        "full": {
            n: rng.standard_normal((40 + 3 * i, 3)).astype(np.float32)
            for i, n in enumerate(names)
        },
        "mixed": {
            names[0]: rng.standard_normal((40, 3)).astype(np.float32),
            names[1]: rng.standard_normal((40, 2)).astype(np.float32),  # bad width
        },
    }
    for label, X_by in cases.items():
        expected = scorer.score_all(X_by)
        pending = scorer.dispatch_all(X_by)
        box = {}

        def worker():
            box["out"] = pending.assemble()
            box["thread_ok"] = threading.current_thread().name == "asm"

        t = threading.Thread(target=worker, name="asm")
        t.start()
        t.join(timeout=30)
        assert box.get("thread_ok"), label
        out = box["out"]
        assert sorted(out) == sorted(expected), label
        for n in expected:
            for key, val in expected[n].items():
                if isinstance(val, np.ndarray):
                    np.testing.assert_array_equal(
                        out[n][key], val, err_msg=f"{label}/{n}/{key}"
                    )
                else:
                    assert out[n][key] == val, (label, n, key)
        # assemble is drain-once: a second call returns the same dict
        # without re-slicing
        assert pending.assemble() is out


def test_assemble_columnar_bitwise_matches_assemble(models):
    """The r19 columnar wire parity pin at the assembler: encoding the
    still-stacked ``assemble_columnar`` result through GSB1 and decoding
    it must be BITWISE identical to ``assemble`` — per machine, per key,
    dtype included — for subset, full-bucket and mixed valid/invalid
    dispatches.  One dispatch per bucket on both paths."""
    from gordo_tpu.serve import codec

    scorer = FleetScorer.from_models(models[0])
    rng = np.random.default_rng(21)
    names = sorted(models[0])
    cases = {
        "subset": {names[2]: rng.standard_normal((40, 3)).astype(np.float32)},
        "full": {
            n: rng.standard_normal((40 + 3 * i, 3)).astype(np.float32)
            for i, n in enumerate(names)
        },
        "mixed": {
            names[0]: rng.standard_normal((40, 3)).astype(np.float32),
            names[1]: rng.standard_normal((40, 2)).astype(np.float32),  # bad
        },
    }
    for label, X_by in cases.items():
        expected = scorer.score_all(X_by)
        pending = scorer.dispatch_all(X_by)
        n_dispatches = pending.n_device_dispatches
        col = pending.assemble_columnar()
        assert pending.n_device_dispatches == 0  # drained, like assemble
        decoded = codec.decode_columnar(
            codec.encode_columnar({"data": col})
        )["data"]
        # error machines must strip "client-error" exactly like the bulk
        # handler does on the msgpack path
        decoded = {
            n: {k: v for k, v in r.items() if k != "client-error"}
            for n, r in decoded.items()
        }
        expected_clean = {
            n: {k: v for k, v in r.items() if k != "client-error"}
            for n, r in expected.items()
        }
        assert sorted(decoded) == sorted(expected_clean), label
        for n in expected_clean:
            assert sorted(decoded[n]) == sorted(expected_clean[n]), (label, n)
            for key, val in expected_clean[n].items():
                got = decoded[n][key]
                if isinstance(val, np.ndarray):
                    assert got.dtype == val.dtype, (label, n, key)
                    assert got.tobytes() == val.tobytes(), (label, n, key)
                else:
                    assert got == val and type(got) is type(val), (
                        label, n, key,
                    )
        assert n_dispatches >= 1 or label == "mixed"


def test_estimate_knee_against_real_dispatch_paths(models):
    """The coalescer's knee sweep must run against the REAL fleet scorer —
    gathered-subset dispatches below the bucket size (1, 2) and the full
    stacked program at it (4) — and land on a valid pow2 cap."""
    from gordo_tpu.serve.coalesce import estimate_knee

    scorer = FleetScorer.from_models(models[0])
    est = estimate_knee(scorer, rows=32, max_batch=4)
    assert est["knee"] in (1, 2, 4)
    assert est["amortization"] > 0


def test_bulk_route(models):
    model_dir = models[1]

    async def main():
        from aiohttp.test_utils import TestClient, TestServer

        collection = ModelCollection.from_directory(model_dir, project="fsproj")
        client = TestClient(TestServer(build_app(collection)))
        await client.start_server()
        try:
            names = sorted(collection.entries)[:3]
            payload = {"X": {n: [[0.1, 0.2, 0.3]] * 12 for n in names}}
            resp = await client.post(
                "/gordo/v0/fsproj/_bulk/anomaly/prediction", json=payload
            )
            body = await resp.json()
            assert resp.status == 200, body
            assert sorted(body["data"]) == names
            for n in names:
                assert len(body["data"][n]["total-anomaly-score"]) == 12

            bad = await client.post(
                "/gordo/v0/fsproj/_bulk/anomaly/prediction",
                json={"X": {"nope": [[1, 2, 3]]}},
            )
            assert bad.status == 400
        finally:
            await client.close()

    asyncio.run(main())


def test_short_rows_rejected(models):
    """Requests with fewer rows than the model can consume must 400-style
    error, not silently return padded garbage."""
    from gordo_tpu.models.estimator import LSTMAutoEncoder
    from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector
    from gordo_tpu.ops.scalers import MinMaxScaler
    from gordo_tpu.pipeline import Pipeline

    rng = np.random.default_rng(0)
    X = rng.standard_normal((200, 3)).astype(np.float32)
    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([
            MinMaxScaler(),
            LSTMAutoEncoder(lookback_window=12, epochs=1, batch_size=64),
        ]),
        require_thresholds=False,
    )
    det.fit(X)
    scorer = FleetScorer.from_models({"lstm-m": det})
    assert scorer.n_stacked == 1
    out = scorer.score_all({"lstm-m": X[:4]})
    assert "error" in out["lstm-m"] and "lookback" in out["lstm-m"]["error"]


def test_unthresholded_require_thresholds_goes_to_fallback(models):
    from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector
    from gordo_tpu.models.estimator import AutoEncoder
    from gordo_tpu.ops.scalers import MinMaxScaler
    from gordo_tpu.pipeline import Pipeline

    rng = np.random.default_rng(0)
    X = rng.standard_normal((100, 3)).astype(np.float32)
    det = DiffBasedAnomalyDetector(
        base_estimator=Pipeline([MinMaxScaler(), AutoEncoder(epochs=1)]),
    )  # require_thresholds=True, no cross_validate
    det.fit(X)
    scorer = FleetScorer.from_models({"nothresh": det})
    assert scorer.n_stacked == 0 and "nothresh" in scorer.fallbacks
    out = scorer.score_all({"nothresh": X[:10]})
    assert "error" in out["nothresh"]  # per-machine error, not an exception


def test_repeated_calls_with_fresh_data_stay_exact(models):
    """Round-4 perf fix regression guard: the reused pinned stacking buffer
    and host-cached thresholds must not leak one call's data into the next
    — every call matches the per-machine scorer bit-for-bit."""
    scorer = FleetScorer.from_models(models[0])
    rng = np.random.default_rng(11)
    names = sorted(models[0])
    for call in range(3):
        X_by = {
            name: rng.standard_normal((32 + call, 3)).astype(np.float32)
            for name in names
        }
        bulk = scorer.score_all(X_by)
        for name in names:
            single = CompiledScorer(models[0][name]).anomaly_arrays(X_by[name])
            np.testing.assert_allclose(
                bulk[name]["total-anomaly-score"],
                single["total-anomaly-score"],
                rtol=1e-5, atol=1e-6, err_msg=f"call {call}, {name}",
            )
            # thresholds come from the host cache and are caller-owned copies
            thr = bulk[name]["tag-anomaly-thresholds"]
            assert isinstance(thr, np.ndarray)
            thr[:] = -1.0  # mutating a response must not poison the cache
    fresh = scorer.score_all(
        {names[0]: rng.standard_normal((32, 3)).astype(np.float32)}
    )
    assert (fresh[names[0]]["tag-anomaly-thresholds"] >= 0).all()


def test_lstm_machines_stack_and_match_per_machine_scorer():
    """BASELINE config 2's serving side: windowed LSTM detectors must
    stack into one vmapped program and match each machine's own
    CompiledScorer output exactly (windowing offset included)."""
    from lstm_detectors import LOOKBACK as L, fitted_lstm_detector

    rng = np.random.default_rng(4)
    dets = {f"lstm-{i}": fitted_lstm_detector(rng) for i in range(3)}

    scorer = FleetScorer.from_models(dets)
    assert scorer.n_stacked == 3 and len(scorer.buckets) == 1

    X_by = {
        name: rng.standard_normal((40 + 3 * i, 3)).astype(np.float32)
        for i, name in enumerate(sorted(dets))
    }
    bulk = scorer.score_all(X_by)
    for name, det in dets.items():
        single = CompiledScorer(det).anomaly_arrays(X_by[name])
        # windowing consumes lookback-1 rows at the front
        assert bulk[name]["model-output"].shape[0] == len(X_by[name]) - (L - 1)
        for key in ("model-output", "tag-anomaly-scores",
                    "total-anomaly-score", "anomaly-confidence"):
            np.testing.assert_allclose(
                bulk[name][key], single[key], rtol=1e-5, atol=1e-6,
                err_msg=f"{name}/{key}",
            )


def test_mesh_sharded_serving_matches_single_device(models):
    """Multi-chip stacked serving: with a ("models","data") mesh the
    bucket's machine axis is padded to a shard multiple, placed with a
    models-axis NamedSharding, and one dispatch spans every device —
    results must match the single-device scorer exactly."""
    import jax
    from gordo_tpu.parallel.mesh import MODEL_AXIS, fleet_mesh

    mesh = fleet_mesh(jax.devices())  # conftest: 8 virtual CPU devices
    assert mesh.shape[MODEL_AXIS] == 8
    sharded = FleetScorer.from_models(models[0], mesh=mesh)
    plain = FleetScorer.from_models(models[0])

    bucket = sharded.buckets[0]
    assert bucket.m_pad == 8  # 4 machines padded to the 8-way shard axis
    leaf = jax.tree.leaves(bucket.params)[0]
    assert leaf.shape[0] == 8
    assert MODEL_AXIS in str(leaf.sharding.spec)

    rng = np.random.default_rng(13)
    X_by = {
        name: rng.standard_normal((40 + 5 * i, 3)).astype(np.float32)
        for i, name in enumerate(sorted(models[0]))
    }
    out_s = sharded.score_all(X_by)
    out_p = plain.score_all(X_by)
    for name in X_by:
        for key in ("model-output", "tag-anomaly-scores",
                    "total-anomaly-score", "anomaly-confidence"):
            np.testing.assert_allclose(
                out_s[name][key], out_p[name][key], rtol=1e-5, atol=1e-6,
                err_msg=f"{name}/{key}",
            )
        assert out_s[name]["total-anomaly-threshold"] == pytest.approx(
            out_p[name]["total-anomaly-threshold"]
        )
    # subset requests (gather from sharded params) also stay exact
    one = sorted(models[0])[2]
    sub = sharded.score_all({one: X_by[one]})
    np.testing.assert_allclose(
        sub[one]["total-anomaly-score"],
        out_p[one]["total-anomaly-score"], rtol=1e-5, atol=1e-6,
    )


def test_smoothing_bound_chunks_machine_axis(monkeypatch):
    """When the smoothing windows tensor would exceed the device-memory
    bound at the full dispatch size, score_all must split the MACHINE axis
    into bound-respecting subset dispatches (not degrade to sequential
    per-machine scoring) and still match each machine's own scorer."""
    import gordo_tpu.serve.fleet_scorer as fs_mod
    from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector
    from gordo_tpu.models.estimator import AutoEncoder
    from gordo_tpu.ops.scalers import MinMaxScaler
    from gordo_tpu.pipeline import Pipeline

    rng = np.random.default_rng(6)
    window = 4
    dets = {}
    for i in range(4):
        X_train = rng.standard_normal((120, 3)).astype(np.float32)
        det = DiffBasedAnomalyDetector(
            base_estimator=Pipeline(
                [MinMaxScaler(), AutoEncoder(epochs=1, batch_size=64)]
            ),
            window=window,
        )
        det.cross_validate(X_train)
        det.fit(X_train)
        dets[f"sm-{i}"] = det

    scorer = FleetScorer.from_models(dets)
    assert scorer.n_stacked == 4 and len(scorer.buckets) == 1
    X_by = {
        n: rng.standard_normal((40, 3)).astype(np.float32) for n in dets
    }
    # rows pad to a bucket; allow exactly 2 machines' windows tensors per
    # dispatch -> the 4-machine request must split into 2 subset dispatches
    from gordo_tpu.serve.scorer import _bucket_rows
    per_machine = _bucket_rows(40) * window * 3
    monkeypatch.setattr(fs_mod, "SMOOTH_ELEMENT_BOUND", 2 * per_machine)
    out = scorer.score_all(X_by)
    bucket = scorer.buckets[0]
    machine_dims = {shape[0] for shape in bucket._stack_bufs}
    assert machine_dims == {2}, machine_dims  # chunked, never full-size
    for n, det in dets.items():
        single = CompiledScorer(det).anomaly_arrays(X_by[n])
        for key in ("model-output", "tag-anomaly-scores",
                    "total-anomaly-score", "anomaly-confidence"):
            np.testing.assert_allclose(
                out[n][key], single[key], rtol=1e-5, atol=1e-6,
                err_msg=f"{n}/{key}",
            )


def test_lookback_windows_bound_chunks_machine_axis(monkeypatch):
    """The machine-axis chunking bound must count the MODEL-INPUT windows
    tensor of lookback models, not just smoothing — a bulk dispatch whose
    stacked (m, n, lookback, tags) tensor would exceed the bound splits
    into subset chunks and stays exact."""
    import gordo_tpu.serve.fleet_scorer as fs_mod
    from gordo_tpu.serve.scorer import _bucket_rows
    from lstm_detectors import (
        LOOKBACK as L,
        N_TAGS,
        fitted_lstm_detector,
    )

    rng = np.random.default_rng(21)
    dets = {f"lb-{i}": fitted_lstm_detector(rng) for i in range(4)}

    scorer = FleetScorer.from_models(dets)
    assert scorer.n_stacked == 4
    X_by = {
        n: rng.standard_normal((40, N_TAGS)).astype(np.float32) for n in dets
    }
    per_machine = _bucket_rows(40) * L * N_TAGS  # win_factor = lookback
    monkeypatch.setattr(fs_mod, "SMOOTH_ELEMENT_BOUND", 2 * per_machine)
    out = scorer.score_all(X_by)
    dims = {s[0] for s in scorer.buckets[0]._stack_bufs}
    assert dims == {2}, dims  # chunked into 2-machine subset dispatches
    for n, det in dets.items():
        single = CompiledScorer(det).anomaly_arrays(X_by[n])
        np.testing.assert_allclose(
            out[n]["total-anomaly-score"], single["total-anomaly-score"],
            rtol=1e-5, atol=1e-6, err_msg=n,
        )


def test_width_mismatch_isolated_in_stacked_dispatch(models):
    """score_all itself (no HTTP-level validation in front of it — the
    coalescer path) must reject a wrong-width array in ITS machine's slot
    instead of corrupting or crashing the stacked dispatch."""
    scorer = FleetScorer.from_models(models[0])
    rng = np.random.default_rng(8)
    names = sorted(models[0])
    X_by = {n: rng.standard_normal((30, 3)).astype(np.float32) for n in names}
    X_by[names[0]] = rng.standard_normal((30, 5)).astype(np.float32)  # bad
    out = scorer.score_all(X_by)
    assert "columns" in out[names[0]]["error"]
    assert out[names[0]]["client-error"] is True
    for n in names[1:]:
        single = CompiledScorer(models[0][n]).anomaly_arrays(X_by[n])
        np.testing.assert_allclose(
            out[n]["total-anomaly-score"], single["total-anomaly-score"],
            rtol=1e-5, atol=1e-6,
        )
