"""Shared chaos-suite fixtures: one real 2-machine project build whose
machines land in SEPARATE packs (different tag counts → different
serving-chain signatures), so corrupting one pack must quarantine
exactly one machine."""

import pytest

from gordo_tpu import artifacts
from gordo_tpu.builder import build_project
from gordo_tpu.workflow import NormalizedConfig

PROJECT_NAME = "chaosproj"

_DATASET = {
    "type": "RandomDataset",
    "train_start_date": "2017-12-25T06:00:00Z",
    "train_end_date": "2017-12-27T06:00:00Z",
}

PROJECT = {
    "machines": [
        {"name": "chaos-a",
         "dataset": dict(_DATASET, tags=["cht-1", "cht-2", "cht-3"])},
        # 4 tags → different model signature → its own pack
        {"name": "chaos-b",
         "dataset": dict(_DATASET,
                         tags=["cht-4", "cht-5", "cht-6", "cht-7"])},
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {"gordo_tpu.models.estimator.AutoEncoder": {
                                "kind": "feedforward_hourglass",
                                "epochs": 2,
                                "batch_size": 64,
                            }},
                        ]
                    }
                }
            }
        }
    },
}


@pytest.fixture(scope="session")
def chaos_model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("chaos-artifacts")
    result = build_project(
        NormalizedConfig(PROJECT, PROJECT_NAME).machines, str(out)
    )
    assert not result.failed
    store = artifacts.open_store(str(out))
    assert store is not None and len(store.packs) == 2, (
        "chaos fixture needs the two machines in two distinct packs"
    )
    assert store.location("chaos-a")[0] != store.location("chaos-b")[0]
    return str(out)
