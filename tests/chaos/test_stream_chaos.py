"""Streaming-plane chaos: seeded ``stream.push`` / ``stream.ingest``
faults driven through a real server + the sync client iterator,
asserting the exactly-once contract — across mid-frame disconnects and
slow-consumer evictions, every published verdict reaches the consumer
exactly once (Last-Event-ID resume + the client's id > cursor guard),
and ingest retries never double-apply a row (the seam fires before any
state mutation).

Runs in the slow lane; CI replays it under the same fixed 3-seed
matrix as ``test_chaos.py``.
"""

import os
import threading
import time

import numpy as np
import pytest

from gordo_tpu import faults
from gordo_tpu.client import Client
from tests.chaos.conftest import PROJECT_NAME
from tests.chaos.test_chaos import _get_json, _serve_replicas

pytestmark = pytest.mark.slow

SEEDS = (
    [int(os.environ["GORDO_CHAOS_SEED"])]
    if os.environ.get("GORDO_CHAOS_SEED")
    else [7, 101, 9001]
)

N_ROWS = 30


@pytest.fixture(autouse=True)
def _no_ambient_plane():
    faults.clear()
    yield
    faults.clear()


def _rows(n, n_tags, seed):
    return (
        np.random.default_rng(seed)
        .uniform(0, 1, size=(n, n_tags))
        .tolist()
    )


def _published_verdicts(base, machine):
    """Ground truth from the long-poll surface (which bypasses the
    ``stream.push`` seam): ids of every verdict the hub published."""
    status, doc = _get_json(
        f"{base}/gordo/v0/{PROJECT_NAME}/stream"
        "?mode=poll&after=0&timeout=0"
    )
    assert status == 200 and not doc["replay-gap"]
    return [
        ev["id"] for ev in doc["events"]
        if ev["type"] == "verdict" and ev["data"]["machine"] == machine
    ]


def _consume_until_sentinel(client, out):
    """Collect chaos-a events until the chaos-b sentinel arrives.

    Yielded ids are strictly increasing (client cursor guard), and the
    sentinel is published after every chaos-a event — so once it shows
    up, anything the stream lost is lost for good and the comparison
    against the hub's ring is exact."""
    for ev in client.stream(machines=["chaos-a", "chaos-b"], after=0):
        if ev["data"]["machine"] == "chaos-b":
            return
        if ev["type"] == "verdict":
            out.append(ev["id"])


def _feed(base, seed, done):
    """Ingest N_ROWS for chaos-a one row at a time (paced, so most
    events hit the LIVE push path where the seam fires), then a
    chaos-b sentinel row."""
    feeder = Client(PROJECT_NAME, base_url=base)
    time.sleep(0.3)  # let the consumer attach first
    rows = _rows(N_ROWS, 3, seed)
    for row in rows:
        feeder.stream_ingest({"chaos-a": [row]})
        time.sleep(0.01)
    feeder.stream_ingest({"chaos-b": [_rows(1, 4, seed)[0]]})
    done.append(True)


class TestPushDisconnect:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_exactly_once_across_mid_frame_disconnects(
        self, chaos_model_dir, seed
    ):
        """``disconnect`` kills the transport after the id/event lines
        of a frame have hit the wire.  The client must discard the torn
        frame, reconnect with its cursor, and end up with every
        published verdict exactly once."""

        def fn(bases, colls):
            base = bases[0]
            collected, done = [], []
            with faults.injected(
                f"seed={seed};stream.push=disconnect:0.3:match=chaos-a"
            ) as plane:
                feeder = threading.Thread(
                    target=_feed, args=(base, seed, done)
                )
                feeder.start()
                consumer = Client(PROJECT_NAME, base_url=base)
                try:
                    _consume_until_sentinel(consumer, collected)
                finally:
                    feeder.join()
                fired = plane.stats()["stream.push:disconnect"]["fired"]
            published = _published_verdicts(base, "chaos-a")
            return collected, published, fired, done

        collected, published, fired, done = _serve_replicas(
            [chaos_model_dir], fn
        )
        assert done, "feeder did not finish"
        assert len(published) == N_ROWS
        # the contract: exactly the published set, no loss, no dup
        assert collected == published, (
            f"lost={set(published) - set(collected)} "
            f"dup_or_phantom={set(collected) - set(published)}"
        )
        assert fired >= 1, "seeded schedule never exercised the seam"


class TestSlowConsumerEviction:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_exactly_once_across_queue_overflow(
        self, chaos_model_dir, seed, monkeypatch
    ):
        """``slow_consumer`` stalls the SSE writer until its bounded
        queue (shrunk to 4 here) overflows and the hub marks it dead;
        the client reconnects and the ring replays what the dead
        subscriber missed."""
        monkeypatch.setenv("GORDO_STREAM_QUEUE", "4")

        def fn(bases, colls):
            base = bases[0]
            collected, done = [], []
            with faults.injected(
                f"seed={seed};"
                "stream.push=slow_consumer:1:times=1,match=chaos-a"
            ) as plane:
                feeder = threading.Thread(
                    target=_feed, args=(base, seed, done)
                )
                feeder.start()
                consumer = Client(PROJECT_NAME, base_url=base)
                try:
                    _consume_until_sentinel(consumer, collected)
                finally:
                    feeder.join()
                fired = plane.stats()[
                    "stream.push:slow_consumer"
                ]["fired"]
            published = _published_verdicts(base, "chaos-a")
            return collected, published, fired

        collected, published, fired = _serve_replicas(
            [chaos_model_dir], fn
        )
        assert len(published) == N_ROWS
        assert collected == published, (
            f"lost={set(published) - set(collected)} "
            f"dup_or_phantom={set(collected) - set(published)}"
        )
        assert fired == 1


class TestIngestRetrySafety:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_503_retries_never_double_apply(self, chaos_model_dir, seed):
        """The ``stream.ingest`` seam fires BEFORE any state mutation:
        a 503'd ingest applied nothing, so the client's automatic retry
        lands the row exactly once — N rows in, N verdicts out, ids
        with no holes in the per-machine sequence."""

        def fn(bases, colls):
            base = bases[0]
            client = Client(PROJECT_NAME, base_url=base)
            n = 10
            with faults.injected(
                f"seed={seed};stream.ingest=http_503:1:times=2"
            ) as plane:
                accepted = 0
                for row in _rows(n, 3, seed):
                    doc = client.stream_ingest({"chaos-a": [row]})
                    accepted += doc["accepted"]
                fired = plane.stats()["stream.ingest:http_503"]["fired"]
            published = _published_verdicts(base, "chaos-a")
            return n, accepted, fired, published

        n, accepted, fired, published = _serve_replicas(
            [chaos_model_dir], fn
        )
        assert accepted == n  # every row acked exactly once
        assert fired == 2  # the schedule actually 503'd two ingests
        assert len(published) == n  # ...and none of them half-applied
        # steps are per-machine sequential — a double-apply would show
        # as more events than rows, a loss as fewer
        assert len(set(published)) == n

    @pytest.mark.parametrize("seed", SEEDS)
    def test_reset_mid_ingest_is_retry_safe(self, chaos_model_dir, seed):
        """``reset`` tears the connection before the response; the
        client retries the POST.  Because the seam precedes mutation,
        the retried request is the FIRST application of the row."""

        def fn(bases, colls):
            base = bases[0]
            client = Client(PROJECT_NAME, base_url=base)
            n = 8
            with faults.injected(
                f"seed={seed};stream.ingest=reset:1:times=2"
            ):
                for row in _rows(n, 3, seed):
                    client.stream_ingest({"chaos-a": [row]})
            published = _published_verdicts(base, "chaos-a")
            return n, published

        n, published = _serve_replicas([chaos_model_dir], fn)
        assert len(published) == n
        assert len(set(published)) == n
