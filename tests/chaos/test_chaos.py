"""Fleet-level chaos scenarios: seeded fault schedules driven through
build → serve → reload → scatter-gather, asserting the degradation
contract — no torn responses, quarantine bounded to the injected
machines, byte-identical recovery, typed per-machine partial results
instead of raised exceptions.

Runs in the slow lane; CI replays it under a fixed 3-seed matrix
(``GORDO_CHAOS_SEED`` selects one seed per job, locally all three run).
"""

import asyncio
import json
import os
import shutil
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest
from aiohttp import web

from gordo_tpu import artifacts, faults
from gordo_tpu.client import Client
from gordo_tpu.client.client import _FAILOVER_TOTAL
from gordo_tpu.serve import ModelCollection, build_app
from tests.chaos.conftest import PROJECT_NAME

pytestmark = pytest.mark.slow

SEEDS = (
    [int(os.environ["GORDO_CHAOS_SEED"])]
    if os.environ.get("GORDO_CHAOS_SEED")
    else [7, 101, 9001]
)

START, END = "2017-12-27T06:00:00Z", "2017-12-27T12:00:00Z"


@pytest.fixture(autouse=True)
def _no_ambient_plane():
    faults.clear()
    yield
    faults.clear()


def _serve_replicas(model_dirs, fn):
    """Start one real aiohttp server per dir in ``model_dirs``, run
    ``fn(base_urls, collections)`` in a worker thread (the sync Client
    API), return its result."""

    async def runner():
        runners, bases, colls = [], [], []
        for d in model_dirs:
            coll = ModelCollection.from_directory(d, project=PROJECT_NAME)
            app_runner = web.AppRunner(build_app(coll))
            await app_runner.setup()
            site = web.TCPSite(app_runner, "127.0.0.1", 0)
            await site.start()
            port = app_runner.addresses[0][1]
            runners.append(app_runner)
            bases.append(f"http://127.0.0.1:{port}")
            colls.append(coll)
        try:
            return await asyncio.get_running_loop().run_in_executor(
                None, fn, bases, colls
            )
        finally:
            for app_runner in runners:
                await app_runner.cleanup()

    return asyncio.run(runner())


def _get_json(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as exc:
        return exc.code, json.load(exc)


class TestOverheadWhenOff:
    def test_disabled_seam_cost_is_negligible(self):
        """The ≤2% overhead gate for ``GORDO_FAULTS`` unset.  A request
        crosses a handful of seams and takes milliseconds; the disabled
        seam is one global load + an ``is None`` test, so even a very
        loose 5µs/call ceiling keeps seam cost under 2% of any request
        (5 seams × 5µs = 25µs ≪ 2% of a ~5ms request).  The ceiling is
        ~50× the measured cost, so runner jitter can't flake it, while a
        regression that makes the off path do real work (parse a spec,
        take a lock) still trips it."""
        import time

        assert not faults.enabled()
        n = 200_000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                faults.check("pack.read")
            best = min(best, time.perf_counter() - t0)
        assert best / n < 5e-6, f"disabled seam costs {best / n * 1e6:.2f}µs"


class TestReplicaDeath:
    def test_dead_replica_fails_over_and_completes(self, chaos_model_dir):
        """Acceptance: a replica dying mid-bulk-scoring → client.predict
        COMPLETES against the surviving replica and
        gordo_client_failover_total counts the recovery."""

        def run(bases, colls):
            before = _FAILOVER_TOTAL.value("recovered")
            # replica 0 is dead for every scatter sub-request aimed at it
            with faults.injected(
                f"replica.scatter=dead:1:match={bases[0]}"
            ):
                results = Client(
                    PROJECT_NAME, base_url=bases[1],
                    replica_urls=bases, use_bulk=True, batch_size=100,
                ).predict(START, END)
            return results, _FAILOVER_TOTAL.value("recovered") - before

        results, recovered = _serve_replicas([chaos_model_dir] * 2, run)
        assert len(results) == 2
        for res in results:
            assert res.ok, res.error_messages
            assert len(res.predictions) > 0
        assert recovered > 0, "failover must be visible in the counter"

    def test_whole_fleet_dead_returns_typed_partials(self, chaos_model_dir):
        """Every replica dead → predict still RETURNS, one typed error
        result per machine — never a raised exception, never a torn
        frame."""

        def run(bases, colls):
            before = _FAILOVER_TOTAL.value("exhausted")
            with faults.injected("replica.scatter=dead:1:match=127.0.0.1"):
                results = Client(
                    PROJECT_NAME, base_url=bases[0],
                    replica_urls=bases, use_bulk=True, batch_size=100,
                ).predict(START, END)
            return results, _FAILOVER_TOTAL.value("exhausted") - before

        results, exhausted = _serve_replicas([chaos_model_dir] * 2, run)
        assert sorted(r.name for r in results) == ["chaos-a", "chaos-b"]
        for res in results:
            assert not res.ok
            assert res.predictions is None
            assert res.error_messages
        assert exhausted > 0


class TestCorruptPackQuarantine:
    def _corrupt_pack_of(self, work, machine):
        store = artifacts.open_store(work)
        pack_id, _ = store.location(machine)
        path = os.path.join(
            artifacts.packs_dir(work), store.packs[pack_id]["file"]
        )
        with open(path, "r+b") as fh:
            fh.truncate(64)
        return path

    def test_quarantine_is_bounded_served_around_and_heals(
        self, chaos_model_dir, tmp_path
    ):
        """Acceptance: one pack corrupted on disk → the server STARTS,
        serves the unaffected machine byte-identically, reports exactly
        the injected machine quarantined, and a good generation flip
        heals it."""
        work = str(tmp_path / "degraded")
        shutil.copytree(chaos_model_dir, work)
        broken_path = self._corrupt_pack_of(work, "chaos-b")
        pristine_path = os.path.join(
            artifacts.packs_dir(chaos_model_dir),
            os.path.basename(broken_path),
        )

        # fsck sees the damage but never touches a referenced file
        report = artifacts.fsck(work, repair=True)
        assert not report["ok"]
        assert any(f["kind"] == "pack" for f in report["findings"])

        def run(bases, colls):
            base_ok, base_deg = bases
            out = {}
            c_ok = Client(PROJECT_NAME, base_url=base_ok)
            c_deg = Client(PROJECT_NAME, base_url=base_deg)

            # 1) the unaffected machine serves byte-identically
            r_ok = c_ok.predict(START, END, machine_names=["chaos-a"])[0]
            r_deg = c_deg.predict(START, END, machine_names=["chaos-a"])[0]
            assert r_ok.ok and r_deg.ok, (
                r_ok.error_messages, r_deg.error_messages
            )
            pd.testing.assert_frame_equal(
                r_ok.predictions, r_deg.predictions, check_exact=True
            )

            # 2) quarantine is bounded to exactly the injected machine
            status, doc = _get_json(f"{base_deg}/healthz")
            assert status == 200
            out["quarantined"] = doc["quarantined"]
            out["last_error"] = doc["last-error"]
            status, body = _get_json(
                f"{base_deg}/gordo/v0/{PROJECT_NAME}/chaos-b/metadata"
            )
            assert status == 503 and body["quarantined"]
            assert "truncated" in body["error"]
            status, body = _get_json(
                f"{base_deg}/gordo/v0/{PROJECT_NAME}/"
            )
            assert body["quarantined"] == ["chaos-b"]
            # served entries exclude the quarantined machine; it is
            # reported, not silently dropped
            assert body["machines"] == ["chaos-a"]

            # 3) deadline middleware: an exhausted budget 504s on arrival
            status, body = _get_json(
                f"{base_deg}/gordo/v0/{PROJECT_NAME}/chaos-a/metadata",
                headers={"X-Gordo-Deadline-Ms": "0"},
            )
            assert status == 504

            # 4) heal: restore the good pack bytes and FORCE a
            # generation flip (no build wrote pending rows, so a plain
            # stamp is a no-op — this is the `gordo artifacts flip`
            # path); the watch-triggered rescan clears the quarantine
            shutil.copy2(pristine_path, broken_path)
            assert artifacts.stamp_generation(work) == 1, "plain stamp is a no-op"
            assert artifacts.stamp_generation(work, force=True) == 2
            reloaded = colls[1].maybe_delta_reload()
            assert "chaos-b" in (
                reloaded["added"] + reloaded["reloaded"]
            )
            status, doc = _get_json(f"{base_deg}/healthz")
            assert doc.get("quarantined", []) == []
            status, _ = _get_json(
                f"{base_deg}/gordo/v0/{PROJECT_NAME}/chaos-b/metadata"
            )
            assert status == 200
            r_healed = c_deg.predict(
                START, END, machine_names=["chaos-b"]
            )[0]
            r_base = c_ok.predict(
                START, END, machine_names=["chaos-b"]
            )[0]
            assert r_healed.ok, r_healed.error_messages
            pd.testing.assert_frame_equal(
                r_healed.predictions, r_base.predictions, check_exact=True
            )
            return out

        out = _serve_replicas([chaos_model_dir, work], run)
        assert out["quarantined"] == ["chaos-b"]
        assert out["last_error"] and "truncated" in out["last_error"]["error"]


class TestSeededSchedules:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_transport_chaos_never_tears_a_response(
        self, chaos_model_dir, seed
    ):
        """Seeded connection resets on a quarter of client requests:
        retries + failover absorb them, every returned frame is whole."""

        def run(bases, colls):
            with faults.injected(f"seed={seed};http.request=reset:0.25"):
                results = Client(
                    PROJECT_NAME, base_url=bases[0],
                    replica_urls=bases, use_bulk=True,
                    batch_size=120, n_retries=6,
                ).predict(START, END)
            return results

        results = _serve_replicas([chaos_model_dir] * 2, run)
        assert len(results) == 2
        for res in results:
            assert res.ok, res.error_messages
            total = res.predictions[("total-anomaly-score", "")].to_numpy()
            assert np.isfinite(total).all(), "no torn/partial frame"

    @pytest.mark.parametrize("seed", SEEDS)
    def test_artifact_schedule_quarantine_and_recovery(self, tmp_path, seed):
        """Seeded write/open faults through repeated build rounds: the
        store never tears (every indexed machine is either loadable or
        quarantined with a cause), the same seed replays the same
        schedule, and clearing the faults recovers byte-identically."""

        def sequence(directory):
            rng = np.random.default_rng(0)
            written, failed = {}, []
            spec = (
                f"seed={seed};artifact.write=enospc:0.35;"
                "pack.open=eio:0.35"
            )
            with faults.injected(spec):
                for rnd in range(3):
                    for i in range(3):
                        name = f"s{rnd}-{i}"
                        model = {
                            "w": rng.standard_normal((4, 2)).astype(
                                np.float32
                            )
                        }
                        try:
                            artifacts.write_pack(
                                str(directory), [name], [model]
                            )
                            written[name] = model
                        except (OSError, artifacts.PackError):
                            failed.append(name)
                store = artifacts.open_store(
                    str(directory), quarantine=True
                )
                q_errors = dict(store.quarantined_machines)
                healthy = store.names()
            return written, failed, q_errors, healthy

        d1, d2 = tmp_path / "run1", tmp_path / "run2"
        d1.mkdir(), d2.mkdir()
        written, failed, q_errors, healthy = sequence(d1)
        assert written, "some writes must survive a 0.35 fault rate"

        # no torn store: every indexed machine is healthy XOR quarantined
        assert sorted(set(healthy) | set(q_errors)) == sorted(written)
        assert not set(healthy) & set(q_errors)
        for name, err in q_errors.items():
            assert "injected" in err.lower(), err

        # determinism: the same seed replays the same schedule
        w2, f2, q2, h2 = sequence(d2)
        assert (sorted(w2), f2, sorted(q2), h2) == (
            sorted(written), failed, sorted(q_errors), healthy
        )

        # recovery: faults off → fsck sweeps the write debris, the store
        # opens strict, and every surviving machine loads byte-identical
        report = artifacts.fsck(str(d1), repair=True)
        assert report["ok"], report["findings"]
        store = artifacts.open_store(str(d1))
        assert store.names() == sorted(written)
        for name, model in written.items():
            loaded = store.load_model(name)
            assert np.array_equal(loaded["w"], model["w"])


class TestRefreshChaos:
    """ISSUE 13 chaos case: an artifact-write fault injected mid-refresh
    must leave the store untorn — every indexed machine healthy XOR
    quarantined, the live generation untouched (servers keep serving the
    previous artifacts) — and the NEXT cycle, faults cleared, completes
    the rebuild and flips the generation."""

    @pytest.mark.parametrize("seed", SEEDS)
    def test_write_fault_mid_refresh_keeps_store_untorn(
        self, chaos_model_dir, tmp_path, seed
    ):
        from gordo_tpu.refresh import RefreshConfig, refresh_once
        from gordo_tpu.telemetry import fleet_health as fh
        from gordo_tpu.workflow import NormalizedConfig
        from tests.chaos.conftest import PROJECT

        work = str(tmp_path / "models")
        shutil.copytree(chaos_model_dir, work)
        machines = NormalizedConfig(PROJECT, PROJECT_NAME).machines
        gen0 = artifacts.read_generation(work)
        assert gen0 >= 1

        # a rollup doc with real sketches: chaos-a drifting, chaos-b ok
        rng = np.random.default_rng(seed)
        base = fh.sketch_from_scores(
            rng.lognormal(0, 1, 4000), ts=0.0
        ).to_doc()
        fh.write_rollup(work, {
            "gordo-fleet-health": 1,
            "machines": {
                "chaos-a": {"baseline": base, "live": fh.sketch_from_scores(
                    rng.lognormal(3, 1, 2000), ts=0.0).to_doc()},
                "chaos-b": {"baseline": base, "live": fh.sketch_from_scores(
                    rng.lognormal(0, 1, 2000), ts=0.0).to_doc()},
            },
        })
        cfg = RefreshConfig(
            machines=machines, output_dir=work,
            hysteresis=1, cooldown_seconds=0,
        )

        # cycle 1: every artifact write fails mid-refresh
        with faults.injected(f"seed={seed};artifact.write=enospc:1.0"):
            broken = refresh_once(cfg)
        assert broken["outcome"] == "failed"
        assert "chaos-a" in broken["failed"]

        # the store never tore: generation untouched, every indexed
        # machine healthy XOR quarantined, survivors loadable
        assert artifacts.read_generation(work) == gen0
        store = artifacts.open_store(work, quarantine=True)
        healthy = set(store.names())
        quarantined = set(store.quarantined_machines)
        assert healthy | quarantined == {"chaos-a", "chaos-b"}
        assert not healthy & quarantined
        for name in healthy:
            assert store.load_model(name) is not None

        # cycle 2, faults cleared: the drifted machine rebuilds and the
        # generation flips — the failed cycle cost nothing but time
        recovered = refresh_once(cfg)
        assert recovered["outcome"] == "rebuilt", recovered
        assert recovered["rebuilt"] == ["chaos-a"]
        assert artifacts.read_generation(work) == gen0 + 1
        store = artifacts.open_store(work)
        assert sorted(store.names()) == ["chaos-a", "chaos-b"]
