"""Kill-mid-compact chaos: the score-archive compaction's
write-new-then-flip discipline under the r16 fault plane.

``scores.compact=crash`` fires between a period file's tmp fsync and
its rename — the worst instant a real kill can land (bytes durable,
index still pointing at the chunk segments).  The contract under test:

- a period whose index flip COMPLETED is never lost — its file exists
  and every read that would touch it still answers;
- a period whose flip had not happened leaves the archive exactly as
  it was — reads byte-identical, no index damage;
- a resumed compaction converges to the same archive an uninterrupted
  run produces, byte for byte, file for file (the deterministic-merge
  guarantee that makes crash recovery a non-event).

Runs in the slow lane; CI replays it under the fixed 3-seed matrix
(``GORDO_CHAOS_SEED`` selects one seed per job, locally all three run).
"""

import filecmp
import glob
import os

import numpy as np
import pytest

from gordo_tpu import faults
from gordo_tpu.batch import ScoreArchive, compact_scores, stat_scores
from gordo_tpu.faults import InjectedFault

pytestmark = pytest.mark.slow

SEEDS = (
    [int(os.environ["GORDO_CHAOS_SEED"])]
    if os.environ.get("GORDO_CHAOS_SEED")
    else [7, 101, 9001]
)

MACHINES = ["cm-a", "cm-b", "cm-c"]
N_CHUNKS = 6  # 2 days of 8h chunks -> 2 daily periods of 3 chunks each
ROWS = 48
STEP_NS = 600_000_000_000  # 10min


def _build_archive(root) -> ScoreArchive:
    """A 2-day, 3-machine archive whose bytes are a pure function of the
    chunk index — so two builds (subject and control) are identical by
    construction and byte-level convergence is a meaningful assert."""
    arch = ScoreArchive.create(
        str(root), project="chaos", start="2020-01-01", end="2020-01-03",
        resolution="10min", chunk_rows=ROWS, n_chunks=N_CHUNKS,
        dtype="float32", machines=MACHINES,
    )
    t0 = int(
        np.datetime64("2020-01-01").astype("datetime64[ns]").astype(np.int64)
    )
    span = ROWS * STEP_NS
    for c in range(N_CHUNKS):
        rng = np.random.default_rng(c)
        arch.write_chunk(c, {
            m: {
                "index-ns": (
                    t0 + c * span
                    + STEP_NS * np.arange(ROWS, dtype=np.int64)
                ),
                "total-anomaly-score": rng.random(ROWS, dtype=np.float32),
                "tag-anomaly-scores": rng.random((ROWS, 2), dtype=np.float32),
                "tags": ["t0", "t1"],
            }
            for m in MACHINES
        })
    return arch


def _segment_files(arch: ScoreArchive):
    return sorted(
        os.path.basename(p)
        for p in glob.glob(os.path.join(arch.directory, "*.seg"))
    )


def _reads(arch: ScoreArchive):
    return {
        m: tuple(
            arch.read_machine(m)[k].tobytes()
            for k in ("index-ns", "total-anomaly-score",
                      "tag-anomaly-scores")
        )
        for m in MACHINES
    }


class TestKillMidCompact:
    @pytest.mark.parametrize("seed", SEEDS)
    @pytest.mark.parametrize("after", [0, 1])
    def test_completed_periods_survive_and_resume_converges(
        self, tmp_path, seed, after
    ):
        """Crash before the first flip (``after=0``: nothing committed)
        and between the two flips (``after=1``: one period committed).
        Either way: no completed period lost, reads byte-identical
        through the crash, and the resumed run converges to the
        uninterrupted control archive byte for byte."""
        control_root = str(tmp_path / "control")
        control = _build_archive(control_root)
        compact_scores(control_root)

        subject_root = str(tmp_path / "subject")
        arch = _build_archive(subject_root)
        pre = _reads(arch)

        spec = f"seed={seed};scores.compact=crash:1:after={after}"
        with faults.injected(spec):
            with pytest.raises(InjectedFault):
                compact_scores(subject_root)

        # exactly the periods flipped BEFORE the crash are committed,
        # and each committed period's segment file is durably present
        periods = (arch.index() or {}).get("periods") or {}
        assert len(periods) == after
        for rec in periods.values():
            assert os.path.exists(
                os.path.join(arch.directory, rec["segment"])
            ), rec["segment"]
        # every read is byte-identical through the crash
        assert _reads(arch) == pre

        # resume: the remaining periods compact, and the archive
        # converges to the uninterrupted control — same file set, same
        # bytes (deterministic merge), same reads
        summary = compact_scores(subject_root)
        assert summary["periods-compacted"] == 2 - after
        names = _segment_files(arch)
        assert names == _segment_files(control)
        for name in names:
            assert filecmp.cmp(
                os.path.join(arch.directory, name),
                os.path.join(control.directory, name),
                shallow=False,
            ), f"{name} diverged from the uninterrupted control"
        assert _reads(arch) == pre

    @pytest.mark.parametrize("seed", SEEDS)
    def test_crashed_attempt_leaves_no_index_damage(self, tmp_path, seed):
        """After a crash with nothing committed, the archive answers the
        full inspection surface (stat, aggregate) exactly as before —
        the crashed attempt is invisible to every reader."""
        root = str(tmp_path / "arch")
        arch = _build_archive(root)
        stat_pre = stat_scores(root)
        agg_pre = arch.aggregate(period="1d")

        with faults.injected(f"seed={seed};scores.compact=crash"):
            with pytest.raises(InjectedFault):
                compact_scores(root)

        stat_post = stat_scores(root)
        assert stat_post["periods-compacted"] == 0
        assert stat_post["pending-compaction"] == stat_pre[
            "pending-compaction"
        ]
        agg_post = arch.aggregate(period="1d")
        for key in agg_pre["stats"]:
            assert (
                agg_pre["stats"][key].tobytes()
                == agg_post["stats"][key].tobytes()
            ), key

    @pytest.mark.parametrize("seed", SEEDS)
    def test_repeated_crashes_then_resume(self, tmp_path, seed):
        """A compactor that dies on EVERY attempt makes no progress but
        corrupts nothing; the first clean run converges as if none of
        the crashes happened."""
        control_root = str(tmp_path / "control")
        control = _build_archive(control_root)
        compact_scores(control_root)

        root = str(tmp_path / "arch")
        arch = _build_archive(root)
        pre = _reads(arch)
        for _ in range(3):
            with faults.injected(f"seed={seed};scores.compact=crash"):
                with pytest.raises(InjectedFault):
                    compact_scores(root)
            assert _reads(arch) == pre

        summary = compact_scores(root)
        assert summary["periods-compacted"] == 2
        assert _segment_files(arch) == _segment_files(control)
        for name in _segment_files(arch):
            assert filecmp.cmp(
                os.path.join(arch.directory, name),
                os.path.join(control.directory, name),
                shallow=False,
            ), name
