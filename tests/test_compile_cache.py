"""Persistent-compile-cache gating: TPU/GPU-only by default.

XLA:CPU cached AOT executables embed the compiling process's detected
machine features; loading a mismatched entry segfaulted this container
(see utils/compile_cache.py module docstring).  These tests pin the gate:
no disk cache on the CPU backend unless forced.
"""

import pytest

from gordo_tpu.utils import compile_cache


@pytest.fixture(autouse=True)
def _reset_enabled(monkeypatch):
    monkeypatch.setattr(compile_cache, "_ENABLED", False)


def test_cpu_backend_skips_cache(monkeypatch, tmp_path):
    monkeypatch.delenv("GORDO_COMPILE_CACHE", raising=False)
    monkeypatch.setenv("GORDO_COMPILE_CACHE_DIR", str(tmp_path / "x"))
    # conftest pins the cpu backend for the whole suite
    assert compile_cache.enable_persistent_compile_cache() is False
    assert not (tmp_path / "x").exists()


def test_force_enables_on_cpu(monkeypatch, tmp_path):
    import jax

    monkeypatch.setenv("GORDO_COMPILE_CACHE", "force")
    monkeypatch.setenv("GORDO_COMPILE_CACHE_DIR", str(tmp_path / "y"))
    try:
        assert compile_cache.enable_persistent_compile_cache() is True
        assert (tmp_path / "y").exists()
    finally:
        # never leave a disk cache pointed at a tmp dir for later tests
        jax.config.update("jax_compilation_cache_dir", None)
        monkeypatch.setattr(compile_cache, "_ENABLED", False)


def test_opt_out(monkeypatch):
    monkeypatch.setenv("GORDO_COMPILE_CACHE", "0")
    assert compile_cache.enable_persistent_compile_cache() is False
