"""DataLakeProvider / NCS / IROC lake-reader tests over checked-in fixtures
(tests/data/lake) — the reference's own strategy of mocking the adls
filesystem object (SURVEY.md §5 data-provider bullet); here the mock is a
LocalFileSystem plus a call-recording wrapper to assert pruning behavior."""

import os

import pandas as pd
import pytest

from gordo_tpu.dataset.data_provider.lake import (
    IrocLakeReader,
    LocalFileSystem,
    NcsReader,
)
from gordo_tpu.dataset.data_provider.providers import DataLakeProvider
from gordo_tpu.dataset.sensor_tag import SensorTag

LAKE = os.path.join(os.path.dirname(__file__), "data", "lake")


class RecordingFS(LocalFileSystem):
    """LocalFileSystem that records every open() — the SDK mock."""

    def __init__(self, root):
        super().__init__(root)
        self.opened = []

    def open(self, path, mode="rb"):
        self.opened.append(path)
        return super().open(path, mode)


@pytest.fixture()
def fs():
    return RecordingFS(LAKE)


TAG1 = SensorTag("TAG-1", "asset-a")
TAG2 = SensorTag("TAG-2", "asset-a")
IROC1 = SensorTag("IROC-T1", "iroc-x")


class TestNcsReader:
    def test_reads_and_windows(self, fs):
        reader = NcsReader(fs, "")
        s = reader.read_tag(
            TAG1,
            pd.Timestamp("2017-02-01", tz="UTC"),
            pd.Timestamp("2017-03-01", tz="UTC"),
        )
        assert len(s) > 0
        assert s.index.min() >= pd.Timestamp("2017-02-01", tz="UTC")
        assert s.index.max() < pd.Timestamp("2017-03-01", tz="UTC")
        assert s.name == "TAG-1"

    def test_year_pruning_skips_out_of_window_files(self, fs):
        reader = NcsReader(fs, "")
        files = reader.files_in_window(
            TAG1,
            pd.Timestamp("2017-06-01", tz="UTC"),
            pd.Timestamp("2018-02-01", tz="UTC"),
        )
        years = sorted(os.path.basename(f) for f in files)
        assert years == ["TAG-1_2017.csv", "TAG-1_2018.csv"]
        # and reads only open the pruned set
        reader.read_tag(
            TAG1,
            pd.Timestamp("2017-06-01", tz="UTC"),
            pd.Timestamp("2018-02-01", tz="UTC"),
        )
        assert all("2016" not in p for p in fs.opened)

    def test_parquet_and_headerless_csv(self, fs):
        reader = NcsReader(fs, "")
        s = reader.read_tag(
            TAG2,
            pd.Timestamp("2017-01-01", tz="UTC"),
            pd.Timestamp("2018-07-01", tz="UTC"),
        )
        # spans the parquet 2017 part and the headerless csv 2018 part
        assert s.index.min().year == 2017
        assert s.index.max().year == 2018
        assert s.dtype == float

    def test_window_with_no_files_yields_empty_series(self, fs):
        reader = NcsReader(fs, "")
        s = reader.read_tag(
            TAG1,
            pd.Timestamp("2030-01-01", tz="UTC"),
            pd.Timestamp("2030-02-01", tz="UTC"),
        )
        assert len(s) == 0  # data gap, not a missing tag

    def test_missing_tag_raises(self, fs):
        reader = NcsReader(fs, "")
        with pytest.raises(FileNotFoundError, match="NOPE"):
            reader.read_tag(
                SensorTag("NOPE", "asset-a"),
                pd.Timestamp("2017-01-01", tz="UTC"),
                pd.Timestamp("2017-02-01", tz="UTC"),
            )

    def test_can_handle_tag(self, fs):
        reader = NcsReader(fs, "")
        assert reader.can_handle_tag(TAG1)
        assert not reader.can_handle_tag(SensorTag("TAG-1", None))
        assert not reader.can_handle_tag(SensorTag("NOPE", "asset-a"))


class TestIrocLakeReader:
    def test_reads_bundle_tag(self, fs):
        reader = IrocLakeReader(fs, "")
        s = reader.read_tag(
            IROC1,
            pd.Timestamp("2017-03-01", tz="UTC"),
            pd.Timestamp("2017-03-10", tz="UTC"),
        )
        assert len(s) > 0
        assert s.name == "IROC-T1"
        assert s.index.max() < pd.Timestamp("2017-03-10", tz="UTC")

    def test_unknown_tag_raises(self, fs):
        reader = IrocLakeReader(fs, "")
        with pytest.raises(KeyError):
            reader.read_tag(
                SensorTag("IROC-NOPE", "iroc-x"),
                pd.Timestamp("2017-03-01", tz="UTC"),
                pd.Timestamp("2017-03-10", tz="UTC"),
            )


class TestDataLakeProvider:
    def test_dispatches_ncs_and_iroc(self, fs):
        provider = DataLakeProvider(filesystem=fs, base_dir="")
        series = list(
            provider.load_series(
                pd.Timestamp("2017-03-01", tz="UTC"),
                pd.Timestamp("2017-03-20", tz="UTC"),
                [TAG1, IROC1],
            )
        )
        assert [s.name for s in series] == ["TAG-1", "IROC-T1"]
        assert all(len(s) > 0 for s in series)

    def test_can_handle_and_assetless_rejection(self, fs):
        provider = DataLakeProvider(filesystem=fs, base_dir="")
        assert provider.can_handle_tag(TAG1)
        assert provider.can_handle_tag(IROC1)
        assert not provider.can_handle_tag(SensorTag("TAG-1", None))
        with pytest.raises(ValueError, match="asset"):
            list(
                provider.load_series(
                    pd.Timestamp("2017-03-01", tz="UTC"),
                    pd.Timestamp("2017-03-20", tz="UTC"),
                    [SensorTag("TAG-1", None)],
                )
            )

    def test_dry_run_probes_without_reading(self, fs):
        provider = DataLakeProvider(filesystem=fs, base_dir="")
        list(
            provider.load_series(
                pd.Timestamp("2017-03-01", tz="UTC"),
                pd.Timestamp("2017-03-20", tz="UTC"),
                [TAG1],
                dry_run=True,
            ) or []
        )
        assert fs.opened == []  # existence checks only

    def test_unhandled_tag_errors_with_context(self, fs):
        provider = DataLakeProvider(filesystem=fs, base_dir="")
        with pytest.raises(ValueError, match="No lake reader"):
            list(
                provider.load_series(
                    pd.Timestamp("2017-03-01", tz="UTC"),
                    pd.Timestamp("2017-03-20", tz="UTC"),
                    [SensorTag("GHOST", "no-such-asset")],
                )
            )

    def test_roundtrips_through_params(self, fs):
        provider = DataLakeProvider(filesystem=fs, base_dir="", max_workers=2)
        params = provider.get_params()
        assert params["base_dir"] == ""
        import pickle

        clone = pickle.loads(pickle.dumps(provider))
        assert clone._fs is None  # handles never ride in pickles

    def test_adls_filesystem_import_gated(self):
        provider = DataLakeProvider(base_dir="")
        with pytest.raises(ImportError, match="azure-datalake-store"):
            provider.filesystem

    def test_dataset_integration(self, fs):
        """The dataset layer consumes the lake provider end-to-end."""
        from gordo_tpu.dataset.datasets import TimeSeriesDataset

        ds = TimeSeriesDataset(
            train_start_date="2017-02-01T00:00:00Z",
            train_end_date="2017-04-01T00:00:00Z",
            tag_list=[TAG1, TAG2],
            data_provider=DataLakeProvider(filesystem=fs, base_dir=""),
            resolution="1D",
        )
        X, y = ds.get_data()
        assert X.shape[0] > 0 and X.shape[1] == 2


def test_filesystem_string_spec_config_driven():
    """YAML configs wire mounted archives via 'local:<root>' (a
    TagFileSystem instance can't ride in a config dict)."""
    provider = DataLakeProvider(filesystem=f"local:{LAKE}", base_dir="")
    series = list(
        provider.load_series(
            pd.Timestamp("2017-03-01", tz="UTC"),
            pd.Timestamp("2017-03-20", tz="UTC"),
            [TAG1],
        )
    )
    assert len(series[0]) > 0
    # round-trips through the self-describing config
    clone = DataLakeProvider.from_dict(provider.to_dict())
    assert isinstance(clone, DataLakeProvider)
    with pytest.raises(ValueError, match="filesystem spec"):
        DataLakeProvider(filesystem="s3://nope")


def test_flat_layout_does_not_blend_prefix_tags(fs):
    """PUMP_A must not swallow PUMP_A_SPEED_2017.csv (underscore-extended
    tag names are common); matching is exact-name + strict suffix."""
    reader = NcsReader(fs, "")
    a = reader.read_tag(
        SensorTag("PUMP_A", "asset-flat"),
        pd.Timestamp("2017-01-01", tz="UTC"),
        pd.Timestamp("2017-04-01", tz="UTC"),
    )
    speed = reader.read_tag(
        SensorTag("PUMP_A_SPEED", "asset-flat"),
        pd.Timestamp("2017-01-01", tz="UTC"),
        pd.Timestamp("2017-04-01", tz="UTC"),
    )
    # the two tags were generated around means 1.0 and 100.0: any blending
    # would drag PUMP_A's mean far from 1
    assert abs(a.mean() - 1.0) < 2.0
    assert abs(speed.mean() - 100.0) < 2.0
    assert len(a) == len(speed)


def test_local_spec_provider_survives_pickle():
    import pickle

    provider = DataLakeProvider(filesystem=f"local:{LAKE}", base_dir="")
    clone = pickle.loads(pickle.dumps(provider))
    series = list(
        clone.load_series(
            pd.Timestamp("2017-03-01", tz="UTC"),
            pd.Timestamp("2017-03-20", tz="UTC"),
            [TAG1],
        )
    )
    assert len(series[0]) > 0  # re-wired to the SAME local archive


def test_injected_fs_pickle_raises_not_retargets(fs):
    import pickle

    provider = DataLakeProvider(filesystem=fs, base_dir="")
    clone = pickle.loads(pickle.dumps(provider))
    clone._fs = None  # simulate a filesystem that could not ride the pickle
    clone._had_injected_fs = True
    with pytest.raises(RuntimeError, match="did not survive pickling"):
        clone.filesystem


def test_iroc_bundles_fetched_once_per_asset(fs):
    reader = IrocLakeReader(fs, "")
    for tag in ("IROC-T1", "IROC-T2", "IROC-T1"):
        reader.read_tag(
            SensorTag(tag, "iroc-x"),
            pd.Timestamp("2017-03-01", tz="UTC"),
            pd.Timestamp("2017-03-10", tz="UTC"),
        )
    assert len(fs.opened) == 1  # one bundle file, downloaded exactly once


def test_stray_files_in_tag_dir_are_never_parsed(fs, tmp_path):
    """VERDICT r3 weak #6: a README/checksum dropped into a tag dir must be
    ignored, not parsed as sensor data via an ls() fallback."""
    import shutil

    root = tmp_path / "lake"
    tag_dir = root / "asset-a" / "TAG-1"
    tag_dir.mkdir(parents=True)
    shutil.copy(
        os.path.join(LAKE, "asset-a", "TAG-1", "TAG-1_2017.csv"),
        tag_dir / "TAG-1_2017.csv",
    )
    (tag_dir / "README.md").write_text("# not sensor data\n")
    (tag_dir / "TAG-1_2017.csv.sha256").write_text("deadbeef\n")
    rec = RecordingFS(str(root))
    reader = NcsReader(rec, "")
    series = reader.read_tag(
        TAG1,
        pd.Timestamp("2017-01-01", tz="UTC"),
        pd.Timestamp("2018-01-01", tz="UTC"),
    )
    assert len(series) > 0
    assert all("README" not in p and "sha256" not in p for p in rec.opened)

    # a tag dir holding ONLY strays = missing tag, not parsed garbage
    tag2_dir = root / "asset-a" / "TAG-2"
    tag2_dir.mkdir()
    (tag2_dir / "README.md").write_text("# stray\n")
    with pytest.raises(FileNotFoundError, match="TAG-2"):
        reader.read_tag(
            TAG2,
            pd.Timestamp("2017-01-01", tz="UTC"),
            pd.Timestamp("2018-01-01", tz="UTC"),
        )
