"""Per-signature fleet chunk-size defaults (``builder/fleet_build.py``):
recurrent signatures chunk at the LSTM sweep's knee, dense ones at the
r4 hardware-swept 512 — cheap spec-level tests, no training."""

from gordo_tpu.builder.fleet_build import (
    DEFAULT_MAX_BUCKET,
    DEFAULT_MAX_BUCKET_LSTM,
    default_bucket_size,
)
from gordo_tpu.parallel.anomaly import analyze_definition
from gordo_tpu import serializer


def _spec(estimator_cfg):
    model = serializer.from_definition({
        "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.pipeline.Pipeline": {
                    "steps": [
                        "gordo_tpu.ops.scalers.MinMaxScaler",
                        estimator_cfg,
                    ]
                }
            }
        }
    })
    spec = analyze_definition(model)
    assert spec is not None
    return spec


def test_dense_signature_gets_512():
    spec = _spec({
        "gordo_tpu.models.estimator.AutoEncoder": {
            "kind": "feedforward_hourglass", "epochs": 1,
        }
    })
    assert default_bucket_size(spec) == DEFAULT_MAX_BUCKET == 512


def test_lstm_signature_gets_the_swept_default():
    spec = _spec({
        "gordo_tpu.models.estimator.LSTMAutoEncoder": {
            "kind": "lstm_hourglass", "lookback_window": 12, "epochs": 1,
        }
    })
    assert default_bucket_size(spec) == DEFAULT_MAX_BUCKET_LSTM
    assert DEFAULT_MAX_BUCKET_LSTM < DEFAULT_MAX_BUCKET


def test_unknown_spec_degrades_to_dense_default():
    assert default_bucket_size(object()) == DEFAULT_MAX_BUCKET
