#!/usr/bin/env python
"""Simulated-multiprocess dryrun of the multi-host build path.

Forks N real worker processes (default 2), each with its own
``--xla_force_host_platform_device_count`` virtual-CPU backend, wired
into ONE ``jax.distributed`` job via the ``GORDO_*`` env contract — the
same mechanism as the driver's ``dryrun_multichip``, except the process
boundary (coordination service, heartbeats, barriers) is real.  Asserts:

1. cross-process init succeeds: every worker reports
   ``N x local_devices`` global devices and validates a sharded program
   over the process-spanning mesh;
2. the process shards are disjoint and exhaustive;
3. the merged registry + artifacts are byte-identical to a single-host
   build of the same project (model.pkl/definition.yaml byte-for-byte;
   metadata.json modulo build-timing fields);
4. killing one worker mid-build leaves a resumable per-shard state —
   survivors exit EXIT_SHARD_RESUMABLE — and a re-run completes the
   project with the survivor's machines all cache hits.

Run:  python scripts/multihost_dryrun.py [--processes 2]
      [--local-devices 2] [--skip-kill] [--keep]
Exit: 0 on success; 1 with a FAIL line otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the parent only orchestrates: no jax backend init here, so worker env
# construction can't inherit a poisoned backend
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from gordo_tpu.distributed.launcher import (  # noqa: E402
    pick_free_port,
    wait_all,
    worker_env,
)
from gordo_tpu.distributed.partition import (  # noqa: E402
    EXIT_SHARD_RESUMABLE,
    SHARD_STATE_DIR,
    ShardState,
)
from gordo_tpu.utils import disk_registry  # noqa: E402

#: metadata fields that legitimately differ between two builds of the
#: same config (wall-clock measurements); everything else must match
VOLATILE_META = {
    "model_creation_date",
    "data_query_duration_sec",
    "cross_validation_duration_sec",
    "model_builder_duration_sec",
    "fit_samples_per_second",
    "fit_seconds",
}

#: 8 machines over 2 processes → 4-machine shards, so every stacked
#: program (single-host: 8 lanes, shard: 4) keeps >= 2 lanes per virtual
#: device.  At 1 lane/device XLA:CPU specializes the program differently
#: and per-lane params drift by 1 ulp — a width artifact, not a
#: correctness bug, but the byte-identity assertion below is strict, so
#: the dryrun stays out of that regime (real shards are hundreds wide).
N_MACHINES = 8


def project_yaml(path: str) -> str:
    """A small homogeneous project: every machine fleet-buckets, builds in
    seconds on CPU, and exercises the cache/registry path."""
    doc = {
        "machines": [
            {
                "name": f"mh-{i}",
                "dataset": {
                    "type": "RandomDataset",
                    "tags": ["t-a", "t-b", "t-c"],
                    "train_start_date": "2017-12-25T06:00:00Z",
                    "train_end_date": "2017-12-26T06:00:00Z",
                },
            }
            for i in range(N_MACHINES)
        ],
        "globals": {
            "model": {
                "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                    "base_estimator": {
                        "gordo_tpu.pipeline.Pipeline": {
                            "steps": [
                                "gordo_tpu.ops.scalers.MinMaxScaler",
                                {
                                    "gordo_tpu.models.estimator.AutoEncoder": {
                                        "kind": "feedforward_hourglass",
                                        "epochs": 2,
                                        "batch_size": 64,
                                    }
                                },
                            ]
                        }
                    }
                }
            }
        },
    }
    import yaml

    with open(path, "w") as f:
        yaml.safe_dump(doc, f)
    return path


def build_argv(config_path, out_dir, reg_dir, extra=()):
    return [
        sys.executable, "-m", "gordo_tpu.cli.cli", "build-project",
        "--machine-config", config_path,
        "--project-name", "mhdry",
        "--output-dir", out_dir,
        "--model-register-dir", reg_dir,
        # the byte-identity contract this dryrun pins is defined at
        # per-machine granularity; v1 dirs make it directly comparable
        # (v2 pack chunking differs between a single-host and a sharded
        # build by construction — pack-level parity is the artifact
        # suite's job, tests/test_artifacts.py::TestV1V2Parity)
        "--artifact-format", "v1",
        *extra,
    ]


def launch(argv, n, local_devices, barrier_timeout, log_dir):
    coordinator = f"127.0.0.1:{pick_free_port()}"
    os.makedirs(log_dir, exist_ok=True)
    procs = []
    for pid in range(n):
        env = worker_env(
            pid, n, coordinator,
            local_devices=local_devices, barrier_timeout=barrier_timeout,
        )
        out = open(os.path.join(log_dir, f"worker-{pid}.log"), "wb")
        procs.append(subprocess.Popen(
            argv, env=env, stdout=out, stderr=subprocess.STDOUT, cwd=REPO,
        ))
    return procs


def last_json_line(log_path):
    doc = None
    try:
        with open(log_path, "rb") as f:
            for line in f.read().decode(errors="replace").splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        pass
    except OSError:
        pass
    return doc


def fail(msg, log_dir=None):
    print(f"FAIL: {msg}")
    if log_dir and os.path.isdir(log_dir):
        for name in sorted(os.listdir(log_dir)):
            path = os.path.join(log_dir, name)
            print(f"--- tail {name} ---")
            with open(path, "rb") as f:
                print(f.read().decode(errors="replace")[-3000:])
    sys.exit(1)


def _scrub_timings(obj, seen=None):
    """Zero wall-clock attributes (``fit_seconds_``, ``fleet_seconds``)
    and topology provenance (``bucket_size`` — the stacked-program width,
    which legitimately differs when a shard is smaller than the project)
    through the pickled object graph.  Everything else — params, scaler
    stats, thresholds, CV history — must match to the bit."""
    if seen is None:
        seen = set()
    if id(obj) in seen:
        return
    seen.add(id(obj))
    if isinstance(obj, dict):
        for key, zero in (("fleet_seconds", 0.0), ("bucket_size", 0)):
            if key in obj:
                obj[key] = zero
        for v in obj.values():
            _scrub_timings(v, seen)
        return
    if isinstance(obj, (list, tuple)):
        for v in obj:
            _scrub_timings(v, seen)
        return
    d = getattr(obj, "__dict__", None)
    if d is None:
        return
    if "fit_seconds_" in d:
        d["fit_seconds_"] = 0.0
    for v in d.values():
        _scrub_timings(v, seen)


def compare_artifacts(ref_dir, got_dir, names):
    """Byte-identity check: definition.yaml byte-for-byte; model.pkl
    byte-for-byte after zeroing wall-clock fit timings (every numeric
    array — params, scalers, thresholds, CV history — must match to the
    bit); metadata.json equal after dropping build-timing fields."""
    import pickle

    for name in names:
        a = os.path.join(ref_dir, name, "definition.yaml")
        b = os.path.join(got_dir, name, "definition.yaml")
        with open(a, "rb") as fa, open(b, "rb") as fb:
            if fa.read() != fb.read():
                return f"{name}/definition.yaml differs from single-host build"
        with open(os.path.join(ref_dir, name, "model.pkl"), "rb") as f:
            ma = pickle.load(f)
        with open(os.path.join(got_dir, name, "model.pkl"), "rb") as f:
            mb = pickle.load(f)
        _scrub_timings(ma)
        _scrub_timings(mb)
        if pickle.dumps(ma) != pickle.dumps(mb):
            return (
                f"{name}/model.pkl differs from single-host build beyond "
                "fit timings"
            )
        with open(os.path.join(ref_dir, name, "metadata.json")) as f:
            ma = json.load(f)
        with open(os.path.join(got_dir, name, "metadata.json")) as f:
            mb = json.load(f)

        drop = VOLATILE_META | {"fleet_seconds", "bucket_size"}

        def strip(v):
            if isinstance(v, dict):
                return {
                    k: strip(x) for k, x in v.items() if k not in drop
                }
            if isinstance(v, list):
                return [strip(x) for x in v]
            return v

        if strip(ma) != strip(mb):
            return f"{name}/metadata.json differs beyond timing fields"
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--processes", type=int, default=2)
    ap.add_argument("--local-devices", type=int, default=2)
    ap.add_argument("--barrier-timeout", type=float, default=30.0)
    ap.add_argument("--skip-kill", action="store_true",
                    help="Skip the worker-death/resume scenario.")
    ap.add_argument("--keep", action="store_true",
                    help="Keep the work dir for inspection.")
    args = ap.parse_args()
    n = args.processes

    work = tempfile.mkdtemp(prefix="gordo-mhdry-")
    print(f"workdir: {work}")
    t_start = time.time()
    ok = {"phases": []}
    try:
        config = project_yaml(os.path.join(work, "project.yaml"))

        # ---- phase 1: single-host reference build (same code path,
        # separate process so jax state can't leak into the workers)
        ref_out = os.path.join(work, "ref-models")
        ref_reg = os.path.join(work, "ref-registry")
        log_dir = os.path.join(work, "logs-ref")
        os.makedirs(log_dir, exist_ok=True)
        # same virtual-device count as each worker, but NO distributed init
        # (empty coordinator): the byte-identity comparison must only vary
        # the process topology, never the XLA backend shape
        ref_env = worker_env(0, 1, "unused:0", local_devices=args.local_devices)
        ref_env["GORDO_COORDINATOR"] = ""
        with open(os.path.join(log_dir, "single.log"), "wb") as out:
            rc = subprocess.call(
                build_argv(config, ref_out, ref_reg),
                env=ref_env, stdout=out, stderr=subprocess.STDOUT, cwd=REPO,
            )
        if rc != 0:
            fail(f"single-host reference build rc={rc}", log_dir)
        names = sorted(os.listdir(ref_out))
        names = [x for x in names if x.startswith("mh-")]
        if len(names) != N_MACHINES:
            fail(f"reference build produced {names}", log_dir)
        ok["phases"].append("single-host-reference")

        # ---- phase 2: N-process multihost build into a shared dir
        mh_out = os.path.join(work, "mh-models")
        mh_reg = os.path.join(work, "mh-registry")
        log_dir = os.path.join(work, "logs-mh")
        procs = launch(
            build_argv(config, mh_out, mh_reg), n,
            args.local_devices, args.barrier_timeout, log_dir,
        )
        codes = wait_all(procs, timeout=600)
        if codes != [0] * n:
            fail(f"multihost build exit codes {codes}", log_dir)

        # init evidence: every worker saw the full global device count
        shards = []
        for pid in range(n):
            doc = last_json_line(os.path.join(log_dir, f"worker-{pid}.log"))
            if not doc or "multihost" not in doc:
                fail(f"worker {pid} emitted no multihost summary", log_dir)
            mh = doc["multihost"]
            expect = n * args.local_devices
            if mh["global_devices"] != expect:
                fail(
                    f"worker {pid} saw {mh['global_devices']} global "
                    f"devices, expected {expect}", log_dir,
                )
            state = ShardState.load(mh_out, pid, n)
            if state is None or state.status != "done":
                fail(f"worker {pid} shard state missing/not done", log_dir)
            shards.append(state.machines)
        flat = sorted(x for s in shards for x in s)
        if flat != sorted(names):
            fail(
                f"shards not disjoint+exhaustive: {shards} vs {names}",
                log_dir,
            )
        ok["phases"].append(f"multihost-init-{n}proc")
        ok["shards"] = shards

        # artifacts + merged registry byte-identical to single-host
        err = compare_artifacts(ref_out, mh_out, names)
        if err:
            fail(err, log_dir)
        if disk_registry.list_keys(mh_reg) != disk_registry.list_keys(ref_reg):
            fail(
                f"merged registry keys differ: {disk_registry.list_keys(mh_reg)} "
                f"vs {disk_registry.list_keys(ref_reg)}", log_dir,
            )
        ok["phases"].append("artifact-byte-identity")

        # ---- phase 3: kill one worker mid-build; survivor exits
        # resumable; a re-run completes from cache + the dead remainder
        if not args.skip_kill:
            k_out = os.path.join(work, "kill-models")
            k_reg = os.path.join(work, "kill-registry")
            log_dir = os.path.join(work, "logs-kill")
            procs = launch(
                build_argv(config, k_out, k_reg), n,
                args.local_devices, args.barrier_timeout, log_dir,
            )
            victim = procs[-1]
            victim_state = os.path.join(
                k_out, SHARD_STATE_DIR,
                f"shard-{n - 1:03d}-of-{n:03d}.json",
            )
            # kill as soon as the victim has STARTED its shard (state file
            # exists) — before it can finish everything
            deadline = time.time() + 120
            while time.time() < deadline:
                if os.path.exists(victim_state):
                    break
                if victim.poll() is not None:
                    fail("victim exited before starting its shard", log_dir)
                time.sleep(0.02)
            else:
                fail("victim never wrote its shard state", log_dir)
            victim.send_signal(signal.SIGKILL)
            codes = wait_all(procs, timeout=600)
            if codes[-1] != -signal.SIGKILL:
                fail(f"victim exit code {codes[-1]} != SIGKILL", log_dir)
            for pid, code in enumerate(codes[:-1]):
                if code != EXIT_SHARD_RESUMABLE:
                    fail(
                        f"survivor {pid} exited {code}, expected "
                        f"EXIT_SHARD_RESUMABLE={EXIT_SHARD_RESUMABLE}",
                        log_dir,
                    )
            dead = ShardState.load(k_out, n - 1, n)
            if dead is None or dead.status == "done":
                fail("dead shard state missing or claims done", log_dir)
            remaining = sorted(set(dead.machines) - set(dead.completed))
            ok["phases"].append(
                f"kill-detected (dead shard had {len(remaining)} "
                "machine(s) left)"
            )

            # re-run the SAME spec: fresh coordinator, same dirs — every
            # already-built machine must cache-hit, the remainder builds
            log_dir2 = os.path.join(work, "logs-resume")
            procs = launch(
                build_argv(config, k_out, k_reg), n,
                args.local_devices, args.barrier_timeout, log_dir2,
            )
            codes = wait_all(procs, timeout=600)
            if codes != [0] * n:
                fail(f"resume run exit codes {codes}", log_dir2)
            built = sorted(
                x for x in os.listdir(k_out) if x.startswith("mh-")
            )
            if built != sorted(names):
                fail(f"resume run left artifacts incomplete: {built}", log_dir2)
            for pid in range(n):
                state = ShardState.load(k_out, pid, n)
                if state is None or state.status != "done":
                    fail(f"resumed shard {pid} not done", log_dir2)
            # survivors' machines must have been cache hits on the re-run
            for pid in range(n - 1):
                doc = last_json_line(
                    os.path.join(log_dir2, f"worker-{pid}.log")
                )
                if doc and doc.get("fleet_built", 0) + doc.get(
                    "single_built", 0
                ) > 0 and doc.get("cached", 0) == 0:
                    fail(
                        f"survivor {pid} rebuilt instead of cache-hitting",
                        log_dir2,
                    )
            err = compare_artifacts(ref_out, k_out, names)
            if err:
                fail(f"post-resume {err}", log_dir2)
            ok["phases"].append("resume-completed")

        ok["seconds"] = round(time.time() - t_start, 1)
        print("OK " + json.dumps(ok))
    finally:
        if args.keep:
            print(f"kept workdir: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
