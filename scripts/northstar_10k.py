"""North-star scale proof: a 10,000-machine project, end to end.

BASELINE.md's north star is "10k per-tag models in under an hour on a
v5e-64".  This script drives the full production path at that machine
count on whatever backend is available (CPU jax for the scale proof —
the memory-bounded streaming pipeline is identical):

  project YAML (10k machines) → NormalizedConfig → workflow build_plan
  → build_project (bucketed, streaming, 2-chunk memory bound) → artifact

and writes a JSON artifact (``northstar_10k.json``) recording the plan
shape, wall time, build rate, and the peak number of machines whose
arrays were resident at once (must stay ≤ 2 × max_bucket_size).

Run detached (the full run exceeds interactive timeouts)::

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        nohup python scripts/northstar_10k.py > /tmp/northstar.log 2>&1 &
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

N_MACHINES = int(os.environ.get("NORTHSTAR_MACHINES", "10000"))
N_TAGS = int(os.environ.get("NORTHSTAR_TAGS", "10"))
BUCKET = int(os.environ.get("NORTHSTAR_BUCKET", "512"))
OUT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "northstar_10k.json"
)


def project_yaml(n: int) -> str:
    machines = "\n".join(
        f"  - name: ns-{i:05d}\n"
        f"    dataset:\n"
        f"      type: RandomDataset\n"
        f"      tags: [{', '.join(f'ns-{i:05d}-t{j}' for j in range(N_TAGS))}]\n"
        for i in range(n)
    )
    # tiny epochs: the scale proof is about the pipeline (bucketing,
    # streaming, memory bound, artifact IO), not FLOPs
    return (
        "machines:\n" + machines + """
globals:
  model:
    gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector:
      base_estimator:
        gordo_tpu.pipeline.Pipeline:
          steps:
            - gordo_tpu.ops.scalers.MinMaxScaler
            - gordo_tpu.models.estimator.AutoEncoder:
                kind: feedforward_hourglass
                epochs: 3
                batch_size: 64
"""
    )


def measure_config(text: str):
    """Same-session interleaved config measurement (r24 protocol).

    The r23 artifact's ``config_seconds`` was measured on different
    hardware-sharing conditions than any re-run, so the r24 fast-path
    gate (≤ 0.5×) compares against a BASELINE RE-MEASURED IN THIS RUN:
    the legacy path (pure-Python SafeLoader + eager normalization) and
    the fast path (:meth:`NormalizedConfig.from_source`: C loader,
    Counter dup-check, merge fast paths) alternate for two rounds and
    the per-path best stands.  A third number records the content-hash
    cache warm hit (parse + normalization both skipped).
    """
    import yaml

    from gordo_tpu.workflow.config import NormalizedConfig

    def legacy() -> float:
        t0 = time.time()
        cfg = yaml.load(text, Loader=yaml.SafeLoader)
        NormalizedConfig(cfg, "northstar")
        return time.time() - t0

    best = {"legacy": None, "fast": None}
    config = None
    for _ in range(2):
        dt = legacy()
        if best["legacy"] is None or dt < best["legacy"]:
            best["legacy"] = dt
        t0 = time.time()
        config = NormalizedConfig.from_source(text, "northstar")
        dt = time.time() - t0
        if best["fast"] is None or dt < best["fast"]:
            best["fast"] = dt
        print(
            f"config round: legacy {best['legacy']:.1f}s "
            f"fast {best['fast']:.1f}s", flush=True,
        )

    cache_dir = tempfile.mkdtemp(prefix="northstar-cfgcache-")
    try:
        NormalizedConfig.from_source(text, "northstar", cache_dir=cache_dir)
        t0 = time.time()
        config = NormalizedConfig.from_source(
            text, "northstar", cache_dir=cache_dir
        )
        t_warm = time.time() - t0
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return config, best["fast"], best["legacy"], t_warm


def main() -> int:
    from gordo_tpu.builder.fleet_build import build_project
    from gordo_tpu.workflow.generator import build_plan

    t_all = time.time()
    print(f"generating {N_MACHINES}-machine project yaml...", flush=True)
    text = project_yaml(N_MACHINES)
    config, t_config, t_config_base, t_config_warm = measure_config(text)
    print(
        f"config fast path {t_config:.1f}s vs legacy {t_config_base:.1f}s "
        f"(cache-warm {t_config_warm:.2f}s)", flush=True,
    )

    t0 = time.time()
    plan = build_plan(config, max_bucket_size=BUCKET)
    t_plan = time.time() - t0
    print(
        f"plan: {plan['n_machines']} machines in {plan['n_buckets']} "
        f"chunks ({t_plan:.1f}s)", flush=True,
    )

    out_dir = tempfile.mkdtemp(prefix="northstar-")
    try:
        t0 = time.time()
        result = build_project(
            config.machines, out_dir, max_bucket_size=BUCKET
        )
        t_build = time.time() - t0
        rate = len(result.artifacts) / t_build * 3600.0
        doc = {
            "n_machines": N_MACHINES,
            "n_tags": N_TAGS,
            "max_bucket_size": BUCKET,
            "plan_chunks": plan["n_buckets"],
            "config_seconds": round(t_config, 1),
            "config_seconds_baseline": round(t_config_base, 1),
            "config_ratio": round(t_config / t_config_base, 3),
            "config_cache_warm_seconds": round(t_config_warm, 2),
            "plan_seconds": round(t_plan, 1),
            "build_seconds": round(t_build, 1),
            "built_ok": len(result.artifacts),
            "fleet_built": len(result.fleet_built),
            "failed": len(result.failed),
            "models_per_hour": round(rate),
            "peak_loaded": result.peak_loaded,
            "peak_loaded_bound": 2 * BUCKET,
            "memory_bound_held": result.peak_loaded <= 2 * BUCKET,
            "loader_workers": result.loader_workers,
            "ingest": result.ingest,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
            "total_seconds": round(time.time() - t_all, 1),
        }
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)

    with open(os.path.abspath(OUT_PATH), "w") as fh:
        json.dump(doc, fh, indent=1)
    print(json.dumps(doc), flush=True)
    ok = (
        doc["failed"] == 0
        and doc["built_ok"] == N_MACHINES
        and doc["memory_bound_held"]
        and doc["config_ratio"] <= 0.5
    )
    print("NORTHSTAR", "OK" if ok else "FAILED", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
