#!/usr/bin/env python
"""Self-contained stdlib linter — the ``make lint`` backend.

This image ships no flake8/ruff/pyflakes and has no network, so the local
lint gate is built on ``ast``: syntax errors, unused imports, wildcard
imports, duplicate function/class definitions in a scope, mutable default
arguments, ``except:`` bare clauses, and telemetry metric names violating
the ``gordo_[a-z_]+`` catalog convention (any literal first argument to a
``counter``/``gauge``/``histogram`` registration call — the same pattern
``telemetry.metrics`` enforces at runtime, caught here before anything
runs).  CI additionally runs flake8 (installable on GitHub runners — see
.github/workflows/ci.yml); this script is the everywhere-runnable subset.

Usage: python scripts/lint.py PATH [PATH ...]   (exit 1 on findings)
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Iterator, List, Tuple

Finding = Tuple[str, int, str]

#: must match gordo_tpu.telemetry.metrics.NAME_RE (kept literal here so
#: the linter stays import-free and runs on any checkout)
METRIC_NAME_RE = re.compile(r"^gordo_[a-z_]+$")
#: registration entrypoints whose first literal argument is a metric name
METRIC_FACTORIES = {"counter", "gauge", "histogram"}

#: latency-critical drive loops and dispatch windows, by file basename →
#: function names: the build-pipeline drive loop, the coalescer's drain
#: thread, and (r23) the fleet-build DISPATCH window — everything between
#: launching chunk k+1's program and collecting chunk k.  A blocking
#: device→host transfer there stalls EVERY stage behind it (the drain
#: thread can't gather the next batch; the drive loop can't stage the
#: next chunk; a fetch inside dispatch serializes the overlap the
#: dispatch/collect split exists to create), so direct D2H calls are
#: design bugs in these scopes — results must flow through the collect
#: side (``PendingFleetBuild.collect`` / ``_finish_bucket``) or the
#: writer/finish pools instead.  ``# noqa`` opts a line out, as
#: elsewhere.
D2H_FORBIDDEN_SCOPES = {
    "fleet_build.py": {"_drive_pipeline", "_dispatch_bucket",
                       "_dispatch_chunk"},
    "coalesce.py": {"_run", "_drain"},
    "anomaly.py": {"dispatch", "_dispatch_group",
                   "_dispatch_exact_length_groups", "_dispatch_padded"},
}
#: attribute calls that force a blocking device→host transfer
D2H_BLOCKING_ATTRS = {"device_get", "block_until_ready"}
#: bare-name calls that do the same (gordo_tpu.utils.trees.to_host)
D2H_BLOCKING_NAMES = {"to_host"}
#: modules whose ``.asarray(...)`` materializes a jax array on host
D2H_ASARRAY_MODULES = {"np", "numpy"}

#: request-path host-math gate (serve/): between decode and dispatch a
#: request's data must not be computed on with host numpy — padding,
#: scaling, windowing, thresholds and confidence all live INSIDE the
#: fused device programs now, and host np compute creeping back in is
#: exactly the regression this PR removed (r11: concatenate/tile padding
#: and a host confidence divide per request).  Scoped to the dispatch/
#: epilogue functions; ``np.asarray`` wraps, buffer fills, and the
#: explicitly-named legacy kill-switch helpers are the decode side and
#: stay allowed.  ``# noqa`` opts a line out, as elsewhere.
HOST_MATH_FORBIDDEN_SCOPES = {
    "scorer.py": {"_run", "predict", "anomaly_arrays"},
    "fleet_scorer.py": {"score", "score_subset", "assemble",
                        "assemble_columnar"},
}
HOST_MATH_MODULES = {"np", "numpy"}
HOST_MATH_CALLS = {
    "concatenate", "tile", "stack", "vstack", "hstack", "repeat", "pad",
    "maximum", "minimum", "clip", "where", "abs", "divide", "multiply",
    "add", "subtract", "median", "percentile", "mean", "sum", "matmul",
    "dot", "einsum",
}
SERVE_DIR = os.path.join("gordo_tpu", "serve")

#: bulk-wire hot-loop contract (r19): the bulk encode/decode paths move
#: stacked blocks and (machine → extent) maps — building a per-machine
#: pandas frame inside them reintroduces the ~35x frame-materialization
#: wall BENCH_r18 measured (264k samples/s against a 9.4M/s wire floor).
#: Frames belong behind the client's LazyFrame (first-access
#: materialization), never inside the bulk request/response loops.
#: ``# noqa`` opts a line out, as elsewhere.
BULK_FRAME_FORBIDDEN_SCOPES = {
    "server.py": {"bulk_anomaly_prediction"},
    "codec.py": {"encode_columnar", "decode_columnar"},
    "fleet_scorer.py": {"assemble", "assemble_columnar"},
    "client.py": {"_predict_bulk"},
}
BULK_FRAME_MODULES = {"pd", "pandas"}
BULK_FRAME_CALLS = {"DataFrame", "concat"}
#: bare-name calls that materialize a frame (the client's own builder)
BULK_FRAME_NAMES = {"DataFrame", "_frame_from_payload"}
BULK_FRAME_DIRS = (
    os.path.join("gordo_tpu", "serve"),
    os.path.join("gordo_tpu", "client"),
)

#: the ONE module family allowed to touch jax.jit directly: the compile
#: plane (gordo_tpu/compile/) owns every jitted program in the stack —
#: register through compile.program (AOT serving path) or compile.jit
#: (passthrough) instead.  Tests are allowlisted (they jit ad-hoc probe
#: functions); ``# noqa`` opts a line out, as elsewhere.
JIT_ALLOWED_DIR = os.path.join("gordo_tpu", "compile")

#: per-machine artifact path construction is owned by the artifact plane:
#: only gordo_tpu/artifacts/ (both formats behind one API), the
#: serializer (which defines the v1 layout) and the builder (the v1
#: write path) may reference the per-machine artifact file names.  Any
#: other product code joining "<dir>/<machine>/model.pkl" bypasses the
#: v2 pack index and silently grows a third layout.
ARTIFACT_PATH_ALLOWED_DIRS = (
    os.path.join("gordo_tpu", "artifacts"),
    os.path.join("gordo_tpu", "serializer"),
    os.path.join("gordo_tpu", "builder"),
)
ARTIFACT_FILE_LITERALS = {"model.pkl", "metadata.json", "definition.yaml"}
ARTIFACT_FILE_ATTRS = {"MODEL_FILE", "METADATA_FILE", "DEFINITION_FILE"}

#: gordo_tpu/artifacts/ load-path contract: packs load ZERO-COPY (memmap
#: views — no host stack/concat copies) and ship to the device through
#: exactly one call site, the function named ``to_device`` (the counted
#: transfer behind the "one device_put per pack" acceptance gate).
ARTIFACTS_DIR = os.path.join("gordo_tpu", "artifacts")
ARTIFACTS_COPY_CALLS = {"stack", "concatenate", "vstack", "hstack"}
ARTIFACTS_DEVICE_PUT_FN = "to_device"

#: placement single-owner contract (r22): device meshes and shardings are
#: owned by gordo_tpu/mesh/ — raw ``jax.device_put`` and any
#: ``jax.sharding.*`` construction/import outside the placement plane
#: (and the artifact plane's ``to_device``, policed separately above)
#: bypasses the counted ``place()`` seam and the mesh the compile plane
#: keys executables on.  Tests are allowlisted (they probe placement
#: directly); ``# noqa`` opts a line out, as elsewhere.
MESH_DIR = os.path.join("gordo_tpu", "mesh")

#: serve-path shard contract: the machine→replica partition has exactly
#: ONE implementation (gordo_tpu/serve/shard.py, wrapping the builder's
#: partition_machines).  Server, client, watchman and the workflow
#: generator all compute it locally, so a second implementation that
#: drifts by one machine silently misroutes that machine forever —
#: reject direct partition_machines use AND ad-hoc shard arithmetic
#: (``... % n_shards``, ``hash(name) % ...``) anywhere on the serve path
#: outside the one module.
SHARD_FN_MODULE = os.path.join("gordo_tpu", "serve", "shard.py")
SHARD_PATH_DIRS = (
    os.path.join("gordo_tpu", "serve"),
    os.path.join("gordo_tpu", "client"),
    os.path.join("gordo_tpu", "watchman"),
    os.path.join("gordo_tpu", "workflow"),
)

#: degraded-mode contract on the serving/artifact planes: a swallowed
#: exception (``except Exception: pass``) there turns a fault into a torn
#: response or a silently-missing machine.  Every failure must either be
#: quarantined (recorded with detail), converted to a typed per-machine
#: error, or re-raised — never dropped.  ``# noqa`` opts a line out.
SWALLOW_FORBIDDEN_DIRS = (
    os.path.join("gordo_tpu", "serve"),
    os.path.join("gordo_tpu", "artifacts"),
)

#: fault-injection overhead contract: ``GORDO_FAULTS`` unset must cost
#: nothing on the latency-critical drive loops, so the injection seams
#: (``faults.check`` / ``faults.plane`` / ``faults.enabled``) may not
#: appear inside these function bodies at all — seams live at the I/O
#: edges (open/read/write/request), never per-batch.
FAULTS_FORBIDDEN_SCOPES = {
    "fleet_build.py": {"_drive_pipeline"},
    "coalesce.py": {"_run", "_drain"},
}

#: refresh-plane boundary contract: gordo_tpu/refresh/ talks to serving
#: ONLY over its file and HTTP interfaces (fleet-health rollup files /
#: the /fleet-health endpoint, the client's generation handshake) —
#: importing server or watchman internals would couple the rebuild loop
#: to in-process scorer state and quietly break the "any health surface,
#: any server" deployment shape.
REFRESH_DIR = os.path.join("gordo_tpu", "refresh")
REFRESH_FORBIDDEN_IMPORT_PREFIXES = (
    "gordo_tpu.serve",
    "gordo_tpu.watchman",
)

#: backfill-plane boundary contract: gordo_tpu/batch/ is the OFFLINE
#: path — models from the artifact plane, data from dataset providers,
#: scores into the archive.  It reuses the serving stack's scorer and
#: compile plane (gordo_tpu.serve.fleet_scorer / precision are fine),
#: but the HTTP tier must never leak in: no serve.server, no client, no
#: watchman, no HTTP library.  A backfill that talks HTTP has silently
#: become a load generator against production replicas.
BATCH_DIR = os.path.join("gordo_tpu", "batch")
BATCH_FORBIDDEN_IMPORT_PREFIXES = (
    "gordo_tpu.serve.server",
    "gordo_tpu.client",
    "gordo_tpu.watchman",
    "aiohttp",
    "requests",
    "httpx",
    "urllib",
    "http",
)


def _jit_allowed(path: str) -> bool:
    norm = os.path.normpath(path)
    parts = norm.split(os.sep)
    if "tests" in parts or os.path.basename(norm).startswith("test_"):
        return True
    return JIT_ALLOWED_DIR in norm


def _jit_findings(path: str, tree: ast.AST, noqa_lines: set) -> List[Finding]:
    """Flag ``jax.jit`` references (decorator, call, or partial argument)
    outside the compile plane: on-first-call jit tracing is exactly the
    cold-start ambush the compile plane exists to schedule away, and a
    program it doesn't know about can't be warmed, counted, or evicted."""
    if _jit_allowed(path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
            and node.lineno not in noqa_lines
        ):
            findings.append(
                (path, node.lineno,
                 "bare jax.jit outside gordo_tpu/compile/ — register the "
                 "program with the compile plane (compile.program for the "
                 "AOT serving path, compile.jit as a passthrough)")
            )
    return findings


def _refresh_import_findings(
    path: str, tree: ast.AST, noqa_lines: set
) -> List[Finding]:
    """Flag server/watchman-internal imports inside gordo_tpu/refresh/:
    the refresh loop's plane boundary is files and HTTP only (rollup
    files, /fleet-health, the client generation handshake)."""
    norm = os.path.normpath(path)
    if REFRESH_DIR not in norm:
        return []
    findings: List[Finding] = []

    def _bad(module: str) -> bool:
        return any(
            module == p or module.startswith(p + ".")
            for p in REFRESH_FORBIDDEN_IMPORT_PREFIXES
        )

    for node in ast.walk(tree):
        bad = None
        if isinstance(node, ast.Import):
            for alias in node.names:
                if _bad(alias.name):
                    bad = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            if _bad(node.module):
                bad = node.module
            elif node.module == "gordo_tpu":
                hits = [
                    a.name for a in node.names
                    if a.name in ("serve", "watchman")
                ]
                if hits:
                    bad = f"gordo_tpu.{hits[0]}"
        if bad and getattr(node, "lineno", 0) not in noqa_lines:
            findings.append(
                (path, node.lineno,
                 f"import of {bad} inside gordo_tpu/refresh/ — the "
                 "refresh plane talks to serving ONLY over its file and "
                 "HTTP interfaces (telemetry.read_rollups, /fleet-health, "
                 "client.wait_for_generation), never server internals")
            )
    return findings


#: the build-ingest hot path (gordo_tpu/ingest/plane.py) must stay
#: columnar numpy: per-machine pandas assembly verbs are banned outside
#: the ONE sanctioned escape hatch, ``_load_fallback`` (row filters,
#: custom aggregation, subclassed datasets).  ``pd.tseries...to_offset``
#: and type references stay legal — the ban is on per-machine FRAME
#: construction and resampling, the r24 512-sequential-passes wall.
INGEST_PLANE_FILE = os.path.join("gordo_tpu", "ingest", "plane.py")
INGEST_SANCTIONED_SCOPES = {"_load_fallback"}
INGEST_BANNED_ATTR_CALLS = {
    "resample", "to_frame", "iterrows", "get_data",
}
INGEST_BANNED_PD_CALLS = {"DataFrame", "Series", "concat"}


def _ingest_findings(
    path: str, tree: ast.AST, noqa_lines: set
) -> List[Finding]:
    """Flag per-machine pandas assembly in the ingest hot path: every
    machine routed through :func:`load_chunk`'s vectorized pass must be
    assembled by the shared columnar kernels; a stray ``.resample()`` /
    ``pd.DataFrame`` / ``.get_data()`` reintroduces the per-machine wall
    the plane exists to remove.  ``_load_fallback`` is the sanctioned
    per-machine path; ``# noqa`` opts a line out, as elsewhere."""
    norm = os.path.normpath(path)
    if not norm.endswith(INGEST_PLANE_FILE):
        return []
    sanctioned = [
        (node.lineno, getattr(node, "end_lineno", node.lineno))
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name in INGEST_SANCTIONED_SCOPES
    ]
    findings: List[Finding] = []
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        func = call.func
        bad = None
        if isinstance(func, ast.Attribute):
            if (
                func.attr in INGEST_BANNED_PD_CALLS
                and isinstance(func.value, ast.Name)
                and func.value.id in ("pd", "pandas")
            ):
                bad = f"{func.value.id}.{func.attr}"
            elif func.attr in INGEST_BANNED_ATTR_CALLS:
                bad = f".{func.attr}"
        if not bad or call.lineno in noqa_lines:
            continue
        if any(a <= call.lineno <= b for a, b in sanctioned):
            continue
        findings.append(
            (path, call.lineno,
             f"per-machine pandas assembly {bad}() in the ingest hot "
             "path — machines assemble through the columnar vectorized "
             "pass; the only sanctioned per-machine route is "
             "_load_fallback")
        )
    return findings


def _batch_import_findings(
    path: str, tree: ast.AST, noqa_lines: set
) -> List[Finding]:
    """Flag HTTP-tier imports inside gordo_tpu/batch/: the backfill
    plane scores offline through the artifact/dataset/compile planes —
    serve.server, the client, watchman, and HTTP libraries are all on
    the wrong side of its boundary."""
    norm = os.path.normpath(path)
    if BATCH_DIR not in norm:
        return []
    findings: List[Finding] = []

    def _bad(module: str) -> bool:
        return any(
            module == p or module.startswith(p + ".")
            for p in BATCH_FORBIDDEN_IMPORT_PREFIXES
        )

    for node in ast.walk(tree):
        bad = None
        if isinstance(node, ast.Import):
            for alias in node.names:
                if _bad(alias.name):
                    bad = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            if _bad(node.module):
                bad = node.module
            elif node.module == "gordo_tpu.serve":
                hits = [a.name for a in node.names if a.name == "server"]
                if hits:
                    bad = "gordo_tpu.serve.server"
            elif node.module == "gordo_tpu":
                hits = [
                    a.name for a in node.names
                    if a.name in ("client", "watchman")
                ]
                if hits:
                    bad = f"gordo_tpu.{hits[0]}"
        if bad and getattr(node, "lineno", 0) not in noqa_lines:
            findings.append(
                (path, node.lineno,
                 f"import of {bad} inside gordo_tpu/batch/ — the backfill "
                 "plane is offline by contract: models via "
                 "artifacts.discover, data via dataset providers, scores "
                 "into the archive; never serve.server, the HTTP client, "
                 "or an HTTP library")
            )
    return findings


def _artifact_path_findings(
    path: str, tree: ast.AST, noqa_lines: set
) -> List[Finding]:
    """Flag per-machine artifact file references (``"model.pkl"`` /
    ``serializer.MODEL_FILE`` and friends) in product code outside the
    artifact plane's allowlisted owners."""
    norm = os.path.normpath(path)
    parts = norm.split(os.sep)
    if "tests" in parts or os.path.basename(norm).startswith("test_"):
        return []
    if os.path.join("gordo_tpu", "") not in norm + os.sep:
        return []  # scripts/bench/examples are operator tooling
    if any(d in norm for d in ARTIFACT_PATH_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        bad = None
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value in ARTIFACT_FILE_LITERALS
        ):
            bad = repr(node.value)
        elif (
            isinstance(node, ast.Attribute)
            and node.attr in ARTIFACT_FILE_ATTRS
        ):
            bad = f"serializer.{node.attr}"
        if bad and getattr(node, "lineno", 0) not in noqa_lines:
            findings.append(
                (path, node.lineno,
                 f"per-machine artifact path construction ({bad}) outside "
                 "gordo_tpu/artifacts/ — go through the artifact plane "
                 "(artifacts.discover / ArtifactRef / write_pack)")
            )
    return findings


def _artifacts_pack_findings(
    path: str, tree: ast.AST, noqa_lines: set
) -> List[Finding]:
    """Enforce the pack load contract inside gordo_tpu/artifacts/: no
    host copy calls (stack/concatenate — loads must stay memmap views)
    and ``device_put`` only inside ``to_device`` (the one counted
    whole-pack transfer)."""
    norm = os.path.normpath(path)
    if ARTIFACTS_DIR not in norm:
        return []
    findings: List[Finding] = []
    # map every node to its enclosing function name
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in ast.walk(node):
                child._lint_fn = getattr(  # type: ignore[attr-defined]
                    child, "_lint_fn", node.name
                )
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if (
            func.attr in ARTIFACTS_COPY_CALLS
            and node.lineno not in noqa_lines
        ):
            findings.append(
                (path, node.lineno,
                 f"host copy call .{func.attr}() inside gordo_tpu/artifacts/"
                 " — pack loads are zero-copy memmap views by contract")
            )
        if func.attr == "device_put" and node.lineno not in noqa_lines:
            fn = getattr(node, "_lint_fn", None)
            if fn != ARTIFACTS_DEVICE_PUT_FN:
                findings.append(
                    (path, node.lineno,
                     "device_put outside to_device() in gordo_tpu/artifacts/"
                     " — the one counted whole-pack transfer is the only "
                     "allowed call site")
                )
    return findings


def _mesh_findings(path: str, tree: ast.AST, noqa_lines: set) -> List[Finding]:
    """Flag raw ``jax.device_put`` calls and ``jax.sharding`` imports /
    attribute chains outside the placement plane (``gordo_tpu/mesh/``):
    device placement has ONE owner — go through ``gordo_tpu.mesh.place``
    for transfers and ``model_sharding``/``PlacementSpec`` (or the
    re-exported ``Mesh``/``NamedSharding`` types) for shardings.  The
    artifact plane's ``to_device`` is the other transfer seam and is
    policed by ``_artifacts_pack_findings``."""
    norm = os.path.normpath(path)
    parts = norm.split(os.sep)
    if "tests" in parts or os.path.basename(norm).startswith("test_"):
        return []
    if MESH_DIR in norm:
        return []
    in_artifacts = ARTIFACTS_DIR in norm
    findings: List[Finding] = []
    for node in ast.walk(tree):
        bad = None
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "device_put"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
            and not in_artifacts  # to_device scoping handled separately
        ):
            bad = (
                "raw jax.device_put outside gordo_tpu/mesh/ — route the "
                "transfer through gordo_tpu.mesh.place (counted, "
                "sharding-aware) or artifacts.to_device (pack loads)"
            )
        elif isinstance(node, ast.Import) and any(
            a.name == "jax.sharding" or a.name.startswith("jax.sharding.")
            for a in node.names
        ):
            bad = (
                "import of jax.sharding outside gordo_tpu/mesh/ — the "
                "placement plane owns mesh/sharding construction; import "
                "Mesh/NamedSharding/model_sharding from gordo_tpu.mesh"
            )
        elif isinstance(node, ast.ImportFrom) and node.module and (
            node.module == "jax.sharding"
            or node.module.startswith("jax.sharding.")
        ):
            bad = (
                "import from jax.sharding outside gordo_tpu/mesh/ — the "
                "placement plane owns mesh/sharding construction; import "
                "Mesh/NamedSharding/model_sharding from gordo_tpu.mesh"
            )
        elif (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "sharding"
            and isinstance(node.value.value, ast.Name)
            and node.value.value.id == "jax"
        ):
            bad = (
                f"jax.sharding.{node.attr} outside gordo_tpu/mesh/ — the "
                "placement plane owns mesh/sharding construction; use the "
                "gordo_tpu.mesh re-exports"
            )
        if bad and getattr(node, "lineno", 0) not in noqa_lines:
            findings.append((path, node.lineno, bad))
    return findings


def _shard_findings(path: str, tree: ast.AST, noqa_lines: set) -> List[Finding]:
    """Flag serve-path shard computation outside the one shared shard
    function (``gordo_tpu/serve/shard.py``): direct
    ``partition_machines`` imports/references, and modulo arithmetic
    involving shard-named operands or ``hash(...)`` (the classic ad-hoc
    consistent-hash shortcut that silently disagrees with the real
    partition)."""
    norm = os.path.normpath(path)
    parts = norm.split(os.sep)
    if "tests" in parts or os.path.basename(norm).startswith("test_"):
        return []
    if norm.endswith(SHARD_FN_MODULE):
        return []
    if not any(d in norm for d in SHARD_PATH_DIRS):
        return []
    findings: List[Finding] = []

    def _mentions_shard_or_hash(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and "shard" in sub.id.lower():
                return True
            if isinstance(sub, ast.Attribute) and "shard" in sub.attr.lower():
                return True
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "hash"
            ):
                return True
        return False

    for node in ast.walk(tree):
        bad = None
        if isinstance(node, ast.ImportFrom) and any(
            a.name == "partition_machines" for a in node.names
        ):
            bad = "partition_machines import"
        elif (
            isinstance(node, ast.Name)
            and node.id == "partition_machines"
        ) or (
            isinstance(node, ast.Attribute)
            and node.attr == "partition_machines"
        ):
            bad = "partition_machines reference"
        elif (
            isinstance(node, ast.BinOp)
            and isinstance(node.op, ast.Mod)
            and not isinstance(node.left, ast.Constant)  # "%s" formatting
            and _mentions_shard_or_hash(node)
        ):
            bad = "ad-hoc shard arithmetic (modulo)"
        if bad and getattr(node, "lineno", 0) not in noqa_lines:
            findings.append(
                (path, node.lineno,
                 f"{bad} on the serve path — the machine→replica "
                 "partition has ONE implementation: go through "
                 "gordo_tpu.serve.shard (shard_map/shard_of/owned_names)")
            )
    return findings


def _swallow_findings(
    path: str, tree: ast.AST, noqa_lines: set
) -> List[Finding]:
    """Flag ``except Exception: pass`` (and the bare/``BaseException``
    forms) inside the serve and artifact planes — see
    ``SWALLOW_FORBIDDEN_DIRS``."""
    norm = os.path.normpath(path)
    parts = norm.split(os.sep)
    if "tests" in parts or os.path.basename(norm).startswith("test_"):
        return []
    if not any(d in norm for d in SWALLOW_FORBIDDEN_DIRS):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.body and not all(isinstance(s, ast.Pass) for s in node.body):
            continue
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException")
        )
        if broad and node.lineno not in noqa_lines:
            findings.append(
                (path, node.lineno,
                 "swallowed exception (except Exception: pass) on the "
                 "serve/artifact plane — quarantine it, convert it to a "
                 "typed per-machine error, or re-raise")
            )
    return findings


def _faults_findings(
    path: str, tree: ast.AST, noqa_lines: set
) -> List[Finding]:
    """Flag fault-injection seam calls (``faults.check`` etc.) inside the
    latency-critical scopes of ``FAULTS_FORBIDDEN_SCOPES`` — the chaos
    plane's zero-overhead-when-unset guarantee holds because seams sit at
    I/O edges, never in per-batch loop bodies."""
    scopes = FAULTS_FORBIDDEN_SCOPES.get(os.path.basename(path))
    if not scopes:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in scopes:
            continue
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "faults"
                and sub.lineno not in noqa_lines
            ):
                findings.append(
                    (path, sub.lineno,
                     f"faults.{sub.attr} inside {node.name}() — injection "
                     "seams are banned from hot loop bodies (the "
                     "zero-overhead-when-unset contract); put the seam at "
                     "the I/O edge instead")
                )
    return findings


def iter_py_files(paths: List[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path) and path.endswith(".py"):
            yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = [
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".pytest_cache")
                ]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


class _ImportTracker(ast.NodeVisitor):
    """Collect imported names and every name usage in a module."""

    def __init__(self):
        self.imports: List[Tuple[str, int]] = []  # (bound name, lineno)
        self.used: set = set()

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.imports.append((name, node.lineno))

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # compiler directives, used by definition
        for alias in node.names:
            if alias.name == "*":
                continue  # flagged separately
            name = alias.asname or alias.name
            self.imports.append((name, node.lineno))

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)


def _d2h_findings(path: str, tree: ast.AST, noqa_lines: set) -> List[Finding]:
    """Flag blocking device→host calls inside the pipeline drive loop,
    the coalescer drain thread, and the fleet-build dispatch window (see
    ``D2H_FORBIDDEN_SCOPES``): direct ``jax.device_get`` /
    ``.block_until_ready()`` / ``np.asarray`` (which materializes a jax
    array on host) / ``to_host`` calls in those function bodies."""
    scopes = D2H_FORBIDDEN_SCOPES.get(os.path.basename(path))
    if not scopes:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in scopes:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            func = call.func
            bad = None
            if isinstance(func, ast.Attribute):
                if func.attr in D2H_BLOCKING_ATTRS:
                    bad = func.attr
                elif (
                    func.attr == "asarray"
                    and isinstance(func.value, ast.Name)
                    and func.value.id in D2H_ASARRAY_MODULES
                ):
                    bad = f"{func.value.id}.asarray"
            elif isinstance(func, ast.Name) and func.id in D2H_BLOCKING_NAMES:
                bad = func.id
            if bad and call.lineno not in noqa_lines:
                findings.append(
                    (path, call.lineno,
                     f"blocking D2H call {bad}() inside {node.name}() — "
                     "this scope is a drive loop/drain thread/dispatch "
                     "window; route results through the collect side or "
                     "the writer/finish pool")
                )
    return findings


def _host_math_findings(
    path: str, tree: ast.AST, noqa_lines: set
) -> List[Finding]:
    """Flag host numpy COMPUTE calls (``np.concatenate``/``np.tile``/
    arithmetic reductions — see ``HOST_MATH_CALLS``) inside the serve
    plane's request-path scopes (``HOST_MATH_FORBIDDEN_SCOPES``): that
    work belongs inside the fused device program, where it is one
    dispatch instead of a per-request host bill."""
    norm = os.path.normpath(path)
    if SERVE_DIR not in norm:
        return []
    scopes = HOST_MATH_FORBIDDEN_SCOPES.get(os.path.basename(norm))
    if not scopes:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in scopes:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            func = call.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in HOST_MATH_CALLS
                and isinstance(func.value, ast.Name)
                and func.value.id in HOST_MATH_MODULES
                and call.lineno not in noqa_lines
            ):
                findings.append(
                    (path, call.lineno,
                     f"host numpy compute {func.value.id}.{func.attr}() "
                     f"inside {node.name}() — the serve request path is "
                     "decode -> one device dispatch -> encode; fuse this "
                     "into the compiled program (serve/scorer.py)")
                )
    return findings


def _bulk_frame_findings(
    path: str, tree: ast.AST, noqa_lines: set
) -> List[Finding]:
    """Flag per-machine pandas frame construction (``pd.DataFrame`` /
    ``pd.concat`` / ``_frame_from_payload``) inside the bulk wire hot
    loops (``BULK_FRAME_FORBIDDEN_SCOPES``): the server bulk handler,
    the GSB1 encode/decode pair, the stacked assemblers and the
    client's bulk reassembly all move raw blocks — frame building is
    the r18 35x wall and lives behind the LazyFrame's first-access
    materialization instead."""
    norm = os.path.normpath(path)
    if not any(d in norm for d in BULK_FRAME_DIRS):
        return []
    scopes = BULK_FRAME_FORBIDDEN_SCOPES.get(os.path.basename(norm))
    if not scopes:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in scopes:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            func = call.func
            bad = None
            if (
                isinstance(func, ast.Attribute)
                and func.attr in BULK_FRAME_CALLS
                and isinstance(func.value, ast.Name)
                and func.value.id in BULK_FRAME_MODULES
            ):
                bad = f"{func.value.id}.{func.attr}"
            elif isinstance(func, ast.Name) and func.id in BULK_FRAME_NAMES:
                bad = func.id
            if bad and call.lineno not in noqa_lines:
                findings.append(
                    (path, call.lineno,
                     f"per-machine frame construction {bad}() inside "
                     f"{node.name}() — the bulk wire hot loop ships raw "
                     "blocks; materialize frames behind LazyFrame.frame "
                     "(first access), never per chunk in the loop")
                )
    return findings


def lint_file(path: str) -> List[Finding]:
    findings: List[Finding] = []
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [(path, exc.lineno or 0, f"syntax error: {exc.msg}")]

    # module docstring-level "# noqa" opt-outs per line
    noqa_lines = {
        i + 1
        for i, line in enumerate(source.splitlines())
        if "# noqa" in line
    }

    tracker = _ImportTracker()
    tracker.visit(tree)
    # names listed in __all__ count as used (re-export surface); other
    # string literals do NOT — a dict key or log message that happens to
    # match an import name must not suppress an unused-import finding
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    tracker.used.add(elt.value)
    is_package_init = os.path.basename(path) == "__init__.py"
    if not is_package_init:  # __init__ re-export surface is exempt
        for name, lineno in tracker.imports:
            if name not in tracker.used and lineno not in noqa_lines:
                findings.append((path, lineno, f"unused import: {name}"))

    findings.extend(_d2h_findings(path, tree, noqa_lines))
    findings.extend(_faults_findings(path, tree, noqa_lines))
    findings.extend(_swallow_findings(path, tree, noqa_lines))
    findings.extend(_host_math_findings(path, tree, noqa_lines))
    findings.extend(_bulk_frame_findings(path, tree, noqa_lines))
    findings.extend(_shard_findings(path, tree, noqa_lines))
    findings.extend(_jit_findings(path, tree, noqa_lines))
    findings.extend(_mesh_findings(path, tree, noqa_lines))
    findings.extend(_artifact_path_findings(path, tree, noqa_lines))
    findings.extend(_artifacts_pack_findings(path, tree, noqa_lines))
    findings.extend(_refresh_import_findings(path, tree, noqa_lines))
    findings.extend(_batch_import_findings(path, tree, noqa_lines))
    findings.extend(_ingest_findings(path, tree, noqa_lines))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            func = node.func
            fname = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name)
                else None
            )
            if (
                fname in METRIC_FACTORIES
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and not METRIC_NAME_RE.match(node.args[0].value)
                and node.lineno not in noqa_lines
            ):
                findings.append(
                    (path, node.lineno,
                     f"metric name {node.args[0].value!r} violates the "
                     f"catalog convention {METRIC_NAME_RE.pattern}")
                )
        if isinstance(node, ast.ImportFrom) and any(
            a.name == "*" for a in node.names
        ):
            findings.append((path, node.lineno, "wildcard import"))
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if node.lineno not in noqa_lines:
                findings.append((path, node.lineno, "bare except:"))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in node.args.defaults + node.args.kw_defaults:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    findings.append(
                        (path, node.lineno,
                         f"mutable default argument in {node.name}()")
                    )
        if isinstance(node, (ast.Module, ast.ClassDef)):
            seen = {}
            body = node.body
            for child in body:
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    if child.name in seen and not any(
                        isinstance(d, ast.Name)
                        and d.id in ("property", "overload")
                        or isinstance(d, ast.Attribute)
                        for d in child.decorator_list
                    ):
                        findings.append(
                            (path, child.lineno,
                             f"duplicate definition of {child.name} "
                             f"(first at line {seen[child.name]})")
                        )
                    seen.setdefault(child.name, child.lineno)
    return findings


def main(argv: List[str]) -> int:
    paths = argv or ["gordo_tpu", "tests", "bench.py", "__graft_entry__.py"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"lint: path(s) do not exist: {missing}", file=sys.stderr)
        return 2
    all_findings: List[Finding] = []
    n_files = 0
    for path in iter_py_files(paths):
        n_files += 1
        all_findings.extend(lint_file(path))
    for path, lineno, msg in all_findings:
        print(f"{path}:{lineno}: {msg}")
    print(
        f"lint: {n_files} files, {len(all_findings)} finding(s)",
        file=sys.stderr,
    )
    if n_files == 0:
        print("lint: no files found — refusing to pass vacuously",
              file=sys.stderr)
        return 2
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
