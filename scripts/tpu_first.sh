#!/bin/bash
# Run the pending TPU measurements, FIRST THING on a healthy tunnel.
# (docs/perf.md "Pending TPU re-measurements" — the r4 wedge queue.)
#
# Discipline (see .claude/skills/verify/SKILL.md): one TPU process at a
# time, never timeout-kill a TPU client, keep the machine idle while a
# bench runs, each step sized well under 10 minutes.
set -u
cd "$(dirname "$0")/.."

echo "== probe =="
timeout 75 python -c "import jax; print(jax.devices())" || {
  echo "tunnel not healthy (rc=$?) — aborting before anything can wedge"
  exit 1
}

echo "== 1/3 full bench (persists per-stage to BENCH_partial_tpu.json) =="
python bench.py | tee /tmp/bench_tpu.json

echo "== 2/3 bf16-vs-fp32 LSTM sweep =="
python scripts/sweep_constants.py lstmdtype 32

echo "== 3/3 record =="
git add BENCH_partial_tpu.json 2>/dev/null
echo "Done. Update docs/perf.md headline tables from the output above,"
echo "then commit (git add BENCH_partial_tpu.json docs/perf.md)."
