#!/usr/bin/env python
"""Hardware sweeps for device-side tuning constants and perf scenarios
(results recorded in docs/perf.md).  Each sweep is sized to finish well
inside a 10-minute window (TPU-tunnel processes must not be
timeout-killed — a killed client can wedge the relay):

- ``minbucket``: fused-scorer latency vs padded row-bucket size
  (→ ``serve/scorer.py::MIN_BUCKET``)
- ``bucket``: fleet-build rate vs ``max_bucket_size``
  (→ ``builder/fleet_build.py::DEFAULT_MAX_BUCKET``)
- ``smooth``: stacked smoothing-window scoring vs the windows-tensor size
  (→ ``serve/fleet_scorer.py::SMOOTH_ELEMENT_BOUND``)
- ``multibucket``: mixed-tag-width project vs a uniform one (per-bucket
  compile/dispatch overhead)
- ``sustained``: one 4096-machine memory-bounded project build
- ``lstmdtype``: LSTM fleet build rate, bfloat16 vs float32 compute
- ``lstmbucket``: LSTM fleet build rate vs machines-per-bucket, 64→512
  (→ ``builder/fleet_build.py::DEFAULT_MAX_BUCKET_LSTM``)

Usage: python scripts/sweep_constants.py
           {minbucket|bucket|smooth|multibucket|sustained|lstmdtype|lstmbucket} [n]
(``n`` — machine count — applies to bucket/sustained/lstmdtype only.)
"""

from __future__ import annotations

import shutil
import sys
import tempfile
import time

import numpy as np


def build_one(n_tags: int = 10, window: int = 0):
    from gordo_tpu.builder.build_model import build_model
    from gordo_tpu.workflow.config import Machine

    mc = {
        "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
            **({"window": window} if window else {}),
            "base_estimator": {
                "gordo_tpu.pipeline.Pipeline": {
                    "steps": [
                        "gordo_tpu.ops.scalers.MinMaxScaler",
                        {
                            "gordo_tpu.models.estimator.AutoEncoder": {
                                "kind": "feedforward_hourglass",
                                "epochs": 10,
                                "batch_size": 64,
                            }
                        },
                    ]
                }
            },
        }
    }
    m = Machine.from_config(
        {
            "name": "sweep-m",
            "dataset": {
                "type": "RandomDataset",
                "tag_list": [f"t-{j}" for j in range(n_tags)],
            },
            "model": mc,
        }
    )
    model, _ = build_model(m.name, m.model, m.dataset, {}, m.evaluation)
    return model


def sweep_minbucket() -> None:
    """Latency vs padded bucket rows: if flat up to 256+, MIN_BUCKET can
    rise to cut jit-cache entries; if it climbs, small buckets pay off."""
    from gordo_tpu.serve.scorer import CompiledScorer

    sc = CompiledScorer(build_one())
    rng = np.random.default_rng(0)
    for rows in (32, 64, 128, 256, 512, 1024, 2048):
        X = rng.standard_normal((rows, 10)).astype(np.float32)
        sc.anomaly_arrays(X)  # compile this bucket
        t0 = time.perf_counter()
        for _ in range(30):
            sc.anomaly_arrays(X)
        dt = (time.perf_counter() - t0) / 30
        print(
            f"rows={rows:5d}: {dt * 1000:6.2f} ms/call "
            f"({rows * 10 / dt / 1e3:,.0f}k samples/s)",
            flush=True,
        )


def sweep_bucket(n_machines: int = 512) -> None:
    from gordo_tpu.builder.fleet_build import build_project
    from gordo_tpu.workflow.config import Machine

    machines = [
        Machine.from_config(
            {
                "name": f"swp-{i:04d}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": [f"t-{i}-{j}" for j in range(10)],
                },
            }
        )
        for i in range(n_machines)
    ]
    for bucket in (128, 256, 512):
        _timed_build(
            machines, f"max_bucket={bucket:5d}", max_bucket_size=bucket
        )


def _timed_build(machines, label: str, **build_kwargs) -> None:
    """Cold + warm timed ``build_project`` runs; prints one result line —
    the ONE measurement harness every build-rate sweep shares."""
    from gordo_tpu.builder.fleet_build import build_project

    rates = []
    for _run in range(2):
        out = tempfile.mkdtemp()
        t0 = time.perf_counter()
        res = build_project(machines, out, **build_kwargs)
        dt = time.perf_counter() - t0
        shutil.rmtree(out, ignore_errors=True)
        assert not res.failed, list(res.failed.items())[:2]
        rates.append(len(res.artifacts) / dt * 3600)
    print(
        f"{label}: warm {rates[-1]:,.0f} models/h (cold {rates[0]:,.0f})",
        flush=True,
    )


def _machines(n: int, n_tags: int = 10, prefix: str = "swp"):
    from gordo_tpu.workflow.config import Machine

    return [
        Machine.from_config(
            {
                "name": f"{prefix}-{i:04d}",
                "dataset": {
                    "type": "RandomDataset",
                    "tag_list": [f"t-{i}-{j}" for j in range(n_tags)],
                },
            }
        )
        for i in range(n)
    ]


def sweep_multibucket() -> None:
    """Bench-diversity scenario: a project whose machines split across 4
    tag widths (4 buckets, 4 programs) vs a uniform project of the same
    size — measures the per-bucket compile+dispatch overhead."""
    from gordo_tpu.builder.fleet_build import build_project
    import shutil as sh
    import tempfile as tf

    uniform = _machines(512, 10, "uni")
    mixed = (
        _machines(128, 8, "w8") + _machines(128, 12, "w12")
        + _machines(128, 16, "w16") + _machines(128, 24, "w24")
    )
    for label, machines in (("uniform-1-bucket", uniform),
                            ("mixed-4-buckets", mixed)):
        _timed_build(machines, label)


def sweep_sustained(n: int = 4096) -> None:
    """Bench-diversity scenario: one sustained 4096-machine project build
    (8 chunks of 512) — the memory-bounded stream at scale, warm rate."""
    from gordo_tpu.builder.fleet_build import build_project
    import shutil as sh
    import tempfile as tf

    machines = _machines(n, 10, "sus")
    for run in range(2):
        out = tf.mkdtemp()
        t0 = time.perf_counter()
        res = build_project(machines, out)
        dt = time.perf_counter() - t0
        sh.rmtree(out, ignore_errors=True)
        assert not res.failed, list(res.failed.items())[:2]
        print(f"run {run}: {len(res.artifacts)} machines in {dt:.1f}s "
              f"({len(res.artifacts) / dt * 3600:,.0f} models/h, "
              f"peak_loaded={res.peak_loaded})", flush=True)


def sweep_lstmdtype(n_machines: int = 32) -> None:
    """The r4 pending measurement (docs/perf.md): LSTM fleet build rate
    with bfloat16 vs float32 recurrent compute.  The LSTM scenario is the
    only FLOP-heavy path, so the MXU-native dtype should move it; run on a
    healthy TPU (each dtype compiles its own program — cold run first,
    warm run is the number)."""
    from gordo_tpu.builder.fleet_build import build_project
    from gordo_tpu.workflow.config import Machine

    for dtype in ("bfloat16", "float32"):
        machines = [
            Machine.from_config(
                {
                    "name": f"dt-{dtype[:4]}-{i:03d}",
                    "dataset": {
                        "type": "RandomDataset",
                        "tag_list": [f"t-{i}-{j}" for j in range(50)],
                    },
                    "model": {
                        "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                            "base_estimator": {
                                "gordo_tpu.pipeline.Pipeline": {
                                    "steps": [
                                        "gordo_tpu.ops.scalers.MinMaxScaler",
                                        {
                                            "gordo_tpu.models.estimator"
                                            ".LSTMAutoEncoder": {
                                                "kind": "lstm_hourglass",
                                                "lookback_window": 12,
                                                "epochs": 10,
                                                "batch_size": 64,
                                                "compute_dtype": dtype,
                                            }
                                        },
                                    ]
                                }
                            }
                        }
                    },
                }
            )
            for i in range(n_machines)
        ]
        _timed_build(machines, f"compute_dtype={dtype}")


def sweep_lstmbucket(n_unused: int = 0, epochs: int = 2) -> None:
    """Machines-per-bucket sweep for the LSTM fleet CV+fit program
    (→ ``builder/fleet_build.py::DEFAULT_MAX_BUCKET_LSTM``).

    Per bucket size b in 64→512: build exactly b machines as ONE chunk
    (``max_bucket_size=b``) — a big project's steady-state rate IS its
    per-chunk rate, since chunks run sequentially — cold then warm, so
    the table carries both the per-size compile cost and the amortized
    rate.  ``epochs=2`` (vs the bench's 10) keeps the 512-point tractable
    on CPU; dispatch-amortization differences between bucket sizes only
    get MORE visible with less compute per machine, so the knee the sweep
    finds is conservative.  Peak host/device memory scales with b via the
    stacked (b, rows, 50) arrays and the windows tensors — the smoothing
    bound (`docs/perf.md`) is the other half of the decision."""
    from gordo_tpu.workflow.config import Machine

    for b in (64, 128, 256, 512):
        machines = [
            Machine.from_config(
                {
                    "name": f"lb-{b}-{i:03d}",
                    "dataset": {
                        "type": "RandomDataset",
                        "tag_list": [f"t-{i}-{j}" for j in range(50)],
                    },
                    "model": {
                        "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                            "base_estimator": {
                                "gordo_tpu.pipeline.Pipeline": {
                                    "steps": [
                                        "gordo_tpu.ops.scalers.MinMaxScaler",
                                        {
                                            "gordo_tpu.models.estimator"
                                            ".LSTMAutoEncoder": {
                                                "kind": "lstm_hourglass",
                                                "lookback_window": 12,
                                                "epochs": epochs,
                                                "batch_size": 64,
                                            }
                                        },
                                    ]
                                }
                            }
                        }
                    },
                }
            )
            for i in range(b)
        ]
        _timed_build(machines, f"lstm_bucket={b:4d}", max_bucket_size=b)


def sweep_smooth() -> None:
    """Probe the smoothing windows-tensor guard: disable it and drive
    stacked scoring at sizes spanning the current 2^27-element bound."""
    import gordo_tpu.serve.fleet_scorer as fs_mod
    from gordo_tpu.serve.fleet_scorer import FleetScorer

    model = build_one(window=144)
    rng = np.random.default_rng(0)
    fs_mod.SMOOTH_ELEMENT_BOUND = 2 ** 40  # hardware probe: guard off
    for m_count, rows in ((32, 2048), (64, 2048), (64, 4096)):
        elems = m_count * rows * 144 * 10
        fleet = FleetScorer.from_models(
            {f"m-{i}": model for i in range(m_count)}
        )
        X_by = {
            f"m-{i}": rng.standard_normal((rows, 10)).astype(np.float32)
            for i in range(m_count)
        }
        try:
            fleet.score_all(X_by)  # compile
            t0 = time.perf_counter()
            for _ in range(3):
                fleet.score_all(X_by)
            dt = (time.perf_counter() - t0) / 3
            print(
                f"M={m_count} rows={rows} window=144 "
                f"elems=2^{np.log2(elems):.1f}: OK {dt * 1000:,.0f} ms/call "
                f"({m_count * rows * 10 / dt / 1e6:.2f}M samples/s)",
                flush=True,
            )
        except Exception as exc:
            print(
                f"M={m_count} rows={rows} elems=2^{np.log2(elems):.1f}: "
                f"FAILED {type(exc).__name__}: {str(exc)[:160]}",
                flush=True,
            )


if __name__ == "__main__":
    sweeps = {
        "minbucket": sweep_minbucket,
        "bucket": sweep_bucket,
        "smooth": sweep_smooth,
        "multibucket": sweep_multibucket,
        "sustained": sweep_sustained,
        "lstmdtype": sweep_lstmdtype,
        "lstmbucket": sweep_lstmbucket,
    }
    which = sys.argv[1] if len(sys.argv) > 1 else ""
    if which not in sweeps:
        print(
            f"usage: {sys.argv[0]} {{{'|'.join(sweeps)}}} [n]",
            file=sys.stderr,
        )
        sys.exit(2)
    sized = {"bucket", "sustained", "lstmdtype"}
    if len(sys.argv) > 2:
        if which not in sized:
            print(
                f"sweep {which!r} takes no size argument "
                f"(sized sweeps: {sorted(sized)})",
                file=sys.stderr,
            )
            sys.exit(2)
        sweeps[which](int(sys.argv[2]))
    else:
        sweeps[which]()
