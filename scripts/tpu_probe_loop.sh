#!/bin/bash
# Probe the axon TPU tunnel every ~4 minutes; log results. Stop when healthy.
# Usage: nohup bash scripts/tpu_probe_loop.sh >/tmp/tpu_probe.log 2>&1 &
while true; do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 70 python -c "import jax; print(jax.devices())" 2>&1)
  rc=$?
  echo "[$ts] rc=$rc $(echo "$out" | tail -1)"
  if [ $rc -eq 0 ] && echo "$out" | grep -q "TpuDevice"; then
    echo "[$ts] TUNNEL HEALTHY"
    break
  fi
  sleep 240
done
