#!/bin/bash
# Probe the axon TPU tunnel every ~4 minutes; log every probe BOTH to
# stdout and to TUNNEL_LOG.md at the repo root, so a tunnel that stays
# wedged for a whole round is itself driver-attested (VERDICT r5 "Next
# round" item 1: if the tunnel stays dead, the wedge must be evidence,
# not an excuse).  Stop when healthy.
# Usage: nohup bash scripts/tpu_probe_loop.sh >/tmp/tpu_probe.log 2>&1 &
cd "$(dirname "$0")/.."
LOG=TUNNEL_LOG.md
if [ ! -f "$LOG" ]; then
  {
    echo "# TPU tunnel probe log"
    echo
    echo "One row per probe of the axon TPU tunnel, appended by"
    echo '`scripts/tpu_probe_loop.sh` (the probe is `timeout 70 python -c'
    echo '"import jax; print(jax.devices())"`).  rc=0 with TpuDevice ='
    echo "healthy; rc=124 = backend init blocked for 70s (the wedged-tunnel"
    echo "signature); anything else = init error (see result column)."
    echo
    echo "| timestamp (UTC) | rc | result |"
    echo "|---|---|---|"
  } > "$LOG"
fi
while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  out=$(timeout 70 python -c "import jax; print(jax.devices())" 2>&1)
  rc=$?
  # last line, pipe-safe, bounded — enough to distinguish wedge vs error
  last=$(echo "$out" | tail -1 | tr -d '|' | cut -c1-120)
  [ $rc -eq 124 ] && [ -z "$last" ] && last="(timeout: init blocked 70s)"
  echo "| $ts | $rc | $last |" >> "$LOG"
  echo "[$ts] rc=$rc $last"
  if [ $rc -eq 0 ] && echo "$out" | grep -q "TpuDevice"; then
    echo "| $ts | 0 | TUNNEL HEALTHY — run scripts/tpu_first.sh NOW |" >> "$LOG"
    echo "[$ts] TUNNEL HEALTHY"
    break
  fi
  sleep 240
done
