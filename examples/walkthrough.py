"""End-to-end walkthrough (reference: the docs/examples notebook loop).

Build a small project from config with synthetic data, inspect metadata,
score anomalies locally, serve over HTTP, and bulk-score with the client.

Run:  python examples/walkthrough.py
"""

import asyncio
import tempfile

import numpy as np

from gordo_tpu.builder.fleet_build import build_project
from gordo_tpu.workflow import NormalizedConfig

PROJECT = {
    "machines": [
        {
            "name": f"demo-machine-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tags": [f"demo-{i}-tag-{j}" for j in range(4)],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": "2017-12-28T06:00:00Z",
            },
        }
        for i in range(3)
    ],
    # no "model": machines get the default
    # DiffBasedAnomalyDetector(Pipeline[MinMaxScaler, hourglass AE])
}


def main():
    out_dir = tempfile.mkdtemp(prefix="gordo-demo-")
    config = NormalizedConfig(PROJECT, "demo")

    # 1. Fleet build: 3 homogeneous machines -> ONE stacked XLA program
    result = build_project(config.machines, out_dir)
    print("built:", result.summary())

    # 2. Artifact + metadata — via the artifact plane: the build writes
    # format v2 by default (one memory-mapped pack per fleet chunk), and
    # `artifacts.discover` is the one loading API over both formats
    from gordo_tpu import artifacts

    _, refs = artifacts.discover(out_dir)
    ref = next(r for r in refs if r.name == "demo-machine-0")
    meta = ref.load_metadata()
    print("rows:", meta["dataset"]["rows_after_filter"],
          "| cv scores:", {k: round(v["mean"], 4) if isinstance(v, dict) else v
                           for k, v in list(meta["model"]["cross_validation"]["scores"].items())[:1]})

    # 3. Local anomaly scoring
    model = ref.load_model()
    X = np.random.default_rng(0).standard_normal((64, 4)).astype(np.float32)
    frame = model.anomaly(X)
    print("anomaly frame columns:", sorted({c[0] for c in frame.columns}))
    print("mean total score:", float(frame[("total-anomaly-score", "")].mean()))

    # 4. Serve + client round trip (in-process)
    from aiohttp import web

    from gordo_tpu.client import Client
    from gordo_tpu.serve import ModelCollection, build_app

    async def serve_and_score():
        runner = web.AppRunner(
            build_app(ModelCollection.from_directory(out_dir, project="demo"))
        )
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        try:
            client = Client("demo", port=port)
            results = await client.predict_async(
                "2017-12-28T06:00:00Z", "2017-12-29T06:00:00Z"
            )
            for res in results:
                rows = 0 if res.predictions is None else len(res.predictions)
                print(res.name, "->", rows, "scored rows",
                      "(ok)" if res.ok else res.error_messages)

            # 5. Watchman: the fleet-health poller that fronts a project —
            # point it at the server, poll once, read the status document
            from gordo_tpu.watchman import Watchman, build_watchman_app

            watchman = Watchman(
                project="demo",
                machines=sorted(
                    m["name"] for m in PROJECT["machines"]
                ),
                target_base_urls=[f"http://127.0.0.1:{port}"],
                poll_interval=3600,  # we poll by hand below
            )
            wm_runner = web.AppRunner(build_watchman_app(watchman))
            await wm_runner.setup()
            wm_site = web.TCPSite(wm_runner, "127.0.0.1", 0)
            await wm_site.start()
            try:
                await watchman.refresh()
                doc = watchman.to_json()
                healthy = sum(
                    1 for e in doc["endpoints"] if e["healthy"]
                )
                print(f"watchman: {healthy}/{len(doc['endpoints'])} "
                      "endpoints healthy")
            finally:
                await wm_runner.cleanup()
        finally:
            await runner.cleanup()

    asyncio.run(serve_and_score())


if __name__ == "__main__":
    main()
