"""Worked example: a RAGGED project end to end.

Raggedness — machines whose train windows/filters produce different row
counts — is the production norm, and exact-parity fleet builds pay one
XLA compile per distinct row count.  This example walks the intended
workflow:

1. plan the project and read the predicted ragged compile bill;
2. build with ``pad_lengths`` (zero data loss) so the ragged bucket
   collapses onto one padded program per aligned length;
3. emit the Argo Workflow document a cluster would run;
4. serve the artifacts and bulk-score every machine through the client's
   stacked bulk route.

Run:  python examples/ragged_fleet.py
(CI runs this in the slow lane — tests/test_examples.py.)
"""

import asyncio
import tempfile

import numpy as np
import yaml

from gordo_tpu.builder.fleet_build import build_project
from gordo_tpu.workflow import NormalizedConfig, build_plan
from gordo_tpu.workflow.generator import generate_argo_workflow

# four machines sharing one model signature but with three DISTINCT train
# lengths (staggered end dates at 10-minute resolution): a ragged bucket
PROJECT = {
    "machines": [
        {
            "name": f"ragged-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tags": [f"rag-tag-{j}" for j in range(3)],
                "train_start_date": "2017-12-25T06:00:00Z",
                "train_end_date": end,
            },
        }
        for i, end in enumerate([
            "2017-12-26T02:10:00Z",   # 122 rows
            "2017-12-26T03:10:00Z",   # 128 rows
            "2017-12-26T04:10:00Z",   # 134 rows
            "2017-12-26T04:10:00Z",   # 134 rows (shares a length)
        ])
    ],
    "globals": {
        "model": {
            "gordo_tpu.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.pipeline.Pipeline": {
                        "steps": [
                            "gordo_tpu.ops.scalers.MinMaxScaler",
                            {
                                "gordo_tpu.models.estimator.AutoEncoder": {
                                    "kind": "feedforward_hourglass",
                                    "epochs": 2,
                                    "batch_size": 64,
                                }
                            },
                        ]
                    }
                }
            }
        }
    },
}

#: rows 122/128/134 all pad UP to 144 — one program instead of three —
#: and every machine still reaches the last CV test block (see
#: docs/fleet.md "pad_lengths")
PAD = 72


def main():
    out_dir = tempfile.mkdtemp(prefix="gordo-ragged-")
    config = NormalizedConfig(PROJECT, "ragged-demo")

    # 1. Plan first: the dry run is where the ragged bill should surface
    plan = build_plan(config)
    warning = plan.get("ragged_compile_warning")
    assert warning, "a ragged project must carry the compile-bill warning"
    print(
        f"plan: {plan['n_machines']} machines, {plan['n_buckets']} "
        f"bucket(s); predicted ~{warning['estimated_distinct_lengths']} "
        f"distinct lengths ≈ {warning['estimated_extra_compile_seconds']}s "
        "of extra compiles in exact mode"
    )

    # 2. Build with pad_lengths: zero rows dropped, ragged lengths
    # collapse onto one padded program (build_project would also
    # auto-select padding past its compile budget — see --no-auto-pad)
    result = build_project(config.machines, out_dir, pad_lengths=PAD)
    assert not result.failed, result.failed
    print("built:", result.summary())
    # via the artifact plane: v2 packs are the build default now
    from gordo_tpu import artifacts

    _, refs = artifacts.discover(out_dir)
    meta = next(r for r in refs if r.name == "ragged-0").load_metadata()
    print(
        "ragged-0 artifact: pad_lengths =", meta["model"].get("pad_lengths"),
        "| rows_trained =", meta["model"].get("rows_trained"),
    )

    # 3. The Argo document a cluster would run (one DAG task per fleet
    # chunk; gordo workflow generate --format argo renders the same)
    argo = generate_argo_workflow(config)
    tasks = argo["spec"]["templates"][0]["dag"]["tasks"]
    print(
        f"argo workflow: {len(tasks)} build task(s); first runs:",
        " ".join(argo["spec"]["templates"][1]["container"]["args"][:4]),
    )
    print("---- argo yaml (head) ----")
    print("\n".join(yaml.safe_dump(argo, sort_keys=False).splitlines()[:8]))

    # 4. Serve + client BULK scoring: one stacked dispatch per chunk
    # across all machines, not one HTTP round-trip per machine
    from aiohttp import web

    from gordo_tpu.client import Client
    from gordo_tpu.serve import ModelCollection, build_app

    async def serve_and_bulk_score():
        runner = web.AppRunner(
            build_app(
                ModelCollection.from_directory(out_dir, project="ragged-demo")
            )
        )
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        try:
            client = Client("ragged-demo", port=port, use_bulk=True)
            results = await client.predict_async(
                "2017-12-28T06:00:00Z", "2017-12-29T06:00:00Z"
            )
            for res in results:
                rows = 0 if res.predictions is None else len(res.predictions)
                scores = (
                    res.predictions[("total-anomaly-score", "")]
                    if res.predictions is not None else None
                )
                print(
                    res.name, "->", rows, "rows, mean total score",
                    None if scores is None else round(float(np.mean(scores)), 4),
                    "(ok)" if res.ok else res.error_messages,
                )
            assert all(r.ok for r in results)
        finally:
            await runner.cleanup()

    asyncio.run(serve_and_bulk_score())
    print("ragged_fleet example: OK")


if __name__ == "__main__":
    main()
