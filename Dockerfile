# gordo-tpu — one image, four runtime roles (reference shipped one image
# per role: ModelBuilder / ModelServer / Watchman / Client; the roles here
# share a wheel and differ only in entrypoint, selected by the k8s
# manifests `gordo workflow generate` emits).
#
# Base note: for real TPU pods use a JAX TPU base image (e.g.
# a python image + `jax[tpu]` from the libtpu releases); CI can build on
# plain python for CPU-only tests.
ARG BASE_IMAGE=python:3.12-slim
FROM ${BASE_IMAGE}

WORKDIR /opt/gordo-tpu

COPY pyproject.toml README.md ./
COPY gordo_tpu ./gordo_tpu
RUN pip install --no-cache-dir .

# role entrypoints (override command per role):
#   model-builder: gordo build-project --machine-config /config/project.yaml ...
#   ml-server:     gordo run-server --model-dir /models ...
#   watchman:      gordo run-watchman --machine-config /config/project.yaml ...
#   client:        gordo client predict <start> <end> ...
ENTRYPOINT ["gordo"]
CMD ["--help"]
