# gordo-tpu developer targets (reference parity: the Makefile drives
# tests/lint/images).

PYTHON ?= python
IMAGE  ?= gordo-tpu
TAG    ?= latest

.PHONY: test test-fast lint bench install image docs clean

install:
	$(PYTHON) -m pip install -e . --no-build-isolation --no-deps

test:
	$(PYTHON) -m pytest tests/ -q

test-fast:
	$(PYTHON) -m pytest tests/ -q -x -k "not fleet_build and not client and not watchman"

bench:
	$(PYTHON) bench.py

image:
	docker build -t $(IMAGE):$(TAG) .

docs:
	@ls docs/*.md

clean:
	rm -rf build dist *.egg-info .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
