# gordo-tpu developer targets (reference parity: the Makefile drives
# tests/lint/images).

PYTHON ?= python
IMAGE  ?= gordo-tpu
TAG    ?= latest

.PHONY: test test-fast test-slow lint bench install image docs clean

install:
	$(PYTHON) -m pip install -e . --no-build-isolation --no-deps

test:
	$(PYTHON) -m pytest tests/ -q

# marker-gated fast lane (CI's per-push gate; measured ~3 min)
test-fast:
	$(PYTHON) -m pytest tests/ -q -m "not slow"

test-slow:
	$(PYTHON) -m pytest tests/ -q -m slow

# stdlib AST linter (no flake8 in this image; CI also runs flake8)
lint:
	$(PYTHON) scripts/lint.py

bench:
	$(PYTHON) bench.py

image:
	docker build -t $(IMAGE):$(TAG) .

docs:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu $(PYTHON) scripts/gen_api_docs.py
	@ls docs/*.md

clean:
	rm -rf build dist *.egg-info .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
